"""Rule registry, findings, and suppression handling for burstlint.

A rule is a named check registered with @rule; running the analysis invokes
every registered (non-disabled) checker and collects Findings.  Findings
carry file:line so they are clickable in editors and greppable in CI logs.

Suppression: a source line carrying `# burstlint: disable=RULE[,RULE2]`
suppresses those rules' findings for that line (AST rules only — jaxpr
findings are anchored to entry-point definitions, disable those via
--disable on the CLI or the `disable` argument of run_analysis).
"""

import json
import re
from dataclasses import dataclass, field, asdict
from typing import Callable, Dict, List, Optional

_SUPPRESS_RE = re.compile(r"#\s*burstlint:\s*disable=([\w,\-]+)")


@dataclass
class Finding:
    rule: str
    message: str
    file: str = "<trace>"
    line: int = 0

    def format(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Rule:
    name: str
    kind: str  # "ast" | "jaxpr"
    doc: str
    checker: Optional[Callable] = None  # astlint: per-tree; jaxpr: global


RULES: Dict[str, Rule] = {}


def rule(name: str, kind: str, doc: str):
    """Register a rule.  AST checkers get (tree, src_lines, path, ctx) and
    yield Findings; jaxpr checkers are invoked by their family driver."""

    def deco(fn):
        RULES[name] = Rule(name=name, kind=kind, doc=doc, checker=fn)
        return fn

    return deco


def suppressed_rules(src_line: str) -> List[str]:
    m = _SUPPRESS_RE.search(src_line)
    if not m:
        return []
    return [r.strip() for r in m.group(1).split(",") if r.strip()]


def filter_suppressed(findings: List[Finding], src_lines: List[str]):
    """Drop findings whose anchoring source line disables their rule."""
    out = []
    for f in findings:
        if 1 <= f.line <= len(src_lines):
            if f.rule in suppressed_rules(src_lines[f.line - 1]):
                continue
        out.append(f)
    return out


def run_analysis(root=None, *, disable=(), ast_only=False,
                 paths=None) -> List[Finding]:
    """Run every registered rule; returns the surviving findings.

    root: package directory to lint (default: this package).  ast_only
    skips the jaxpr tracing family (used by fast editor hooks); `paths`
    overrides the AST lint file set."""
    import os

    from . import astlint

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings: List[Finding] = []
    findings += astlint.lint_paths(paths or astlint.default_paths(root))
    if not ast_only:
        from . import ringcheck, numerics, obscheck, poolcheck, servecheck

        findings += ringcheck.check_all()
        findings += numerics.check_all()
        findings += obscheck.check_all()
        findings += servecheck.check_all()
        findings += poolcheck.check_all()
    return [f for f in findings if f.rule not in set(disable)]


def render(findings: List[Finding], as_json: bool) -> str:
    if as_json:
        return json.dumps(
            {
                "rules_registered": sorted(RULES),
                "n_findings": len(findings),
                "findings": [asdict(f) for f in findings],
            },
            indent=1,
        )
    if not findings:
        return (f"burstlint: clean "
                f"({len(RULES)} rules: {', '.join(sorted(RULES))})")
    lines = [f.format() for f in findings]
    lines.append(f"burstlint: {len(findings)} finding(s)")
    return "\n".join(lines)
