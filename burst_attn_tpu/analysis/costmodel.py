"""burstcost: static resource plans + analytic roofline for the ring kernels.

Every knob in ops/tuning.py is hand-entered, and until this module the only
proof that a (generation, topology, wire-dtype, pass) config actually fits
its VMEM budget was an on-device Mosaic allocation failure.  Following the
IO-aware analyses of FlashAttention (arXiv 2205.14135) and the CUTLASS case
study (arXiv 2312.11918), the tile shapes and traffic are all statically
derivable, so this module computes — with no device in hand —

  * a VMEM/slot/semaphore PLAN per fused fwd, fused bwd and ragged-paged
    config, mirroring (a) the dispatch gates' admission formulas
    (ops/fused_ring.supported, ops/ragged_paged.ragged_supported) and
    (b) the kernels' full scratch_shapes inventories, so burstlint can
    prove at lint time that any shard a gate ADMITS also COMPILES
    (full plan <= the Mosaic VMEM_LIMIT) across the whole tuning-table x
    {uni, bidi, double} x {fp32, int8, fp8} x {fwd, bwd} matrix;

  * an analytic ROOFLINE cost model: FLOPs from the masks.spec_pair_count
    closed forms (elided rounds contribute exactly zero — the identity the
    cost-model-consistent rule pins against the devstats pair algebra),
    ICI bytes from schedule.wire_round_bytes times the compiled program's
    send census, HBM bytes from the block plans — exported as a machine-
    readable table (python -m burst_attn_tpu.analysis --cost-json) that
    the autotuner (ROADMAP item 1) consumes to prune infeasible/dominated
    configs and fleet/sim.py consumes as its replica cost function.

Cross-validation story (analysis/costcheck.py runs all three at lint time):
against the devstats pair/flop counters (closed form == per-round sum over
the compiled program), against the burst.wire_bytes counter formula
(stream_bytes == schedule.wire_round_bytes, the single derivation), and
against measured results/ring_overlap.jsonl floors where TPU rows exist
(the benchmark records t_comm_pred_s/t_compute_pred_s per row via
predict_floors, so every future TPU window calibrates HW for free).

Everything here is host-side integer/float arithmetic over compiled
RingPrograms — no tracing, no devices; safe in the burstlint gate.
"""

from typing import Dict, List, NamedTuple, Optional, Tuple

from ..ops import tuning
from ..ops.masks import _host_round_pairs, live_round_prefix
from ..ops.pallas_flash import VMEM_LIMIT, _pick_block
from ..ops.ragged_paged import _block_rows
from ..parallel import schedule as sched

# ---------------------------------------------------------------------------
# hardware roofline constants

# Per-generation spec-sheet rates.  peak_flops is dense bf16 (MUST match
# benchmarks/train_smoke.PEAK_BF16 — pinned by tests/test_costmodel.py and
# the cost-model-consistent rule); hbm_bw is the published HBM bandwidth;
# ici_bw is the usable ONE-DIRECTION bandwidth of a single ring link —
# spec-sheet derived and calibration-pending until ring_overlap.jsonl
# carries TPU rows (pred_ratio on every row tracks the correction factor).
class HwSpec(NamedTuple):
    peak_flops: float  # dense bf16 FLOPs/s per chip
    hbm_bw: float      # HBM bytes/s per chip
    ici_bw: float      # one-direction ring-link bytes/s


HW: Dict[str, HwSpec] = {
    "v5e": HwSpec(197e12, 819e9, 45e9),
    "v5p": HwSpec(459e12, 2765e9, 90e9),
    "v4": HwSpec(275e12, 1228e9, 45e9),
    "v6": HwSpec(918e12, 1638e9, 90e9),
    # the default row tunes like a v5e (tuning._DEFAULT mirrors the v5e
    # measurements), so it prices like one
    "default": HwSpec(197e12, 819e9, 45e9),
}

# Semaphore tripwires: Mosaic semaphores are cheap SMEM words, but a
# schedule whose semaphore census grows past these bounds has almost
# certainly gained an unintended per-slot or per-bank array — the plan
# counts them so a regression is a lint finding, not an on-device surprise.
SEM_DMA_BUDGET = 128
SEM_REGULAR_BUDGET = 64

# canonical 8-device benchmark shape class (bench.py headline: seq=65536 on
# an 8-ring, 32 heads, d=128 -> per-shard s=8192); the cost table prices
# every config at this shape AND at the largest shard its gate admits
DEFAULT_SHAPE = dict(b=1, n=32, n_kv=32, s=8192, d=128)
DEFAULT_WORLD = 8
PASSES = ("fwd", "bwd")


def _hw(generation: str) -> HwSpec:
    if generation not in HW:
        raise KeyError(f"no HwSpec for generation {generation!r}")
    return HW[generation]


def _factor(world: int) -> Tuple[int, int]:
    """(n_inter, n_intra) the double ring factors a flat world into —
    benchmarks/ring_overlap.py's factorization (smallest n_inter >= 2)."""
    n_i = 2
    while world % n_i or (world // n_i) < 2:
        n_i += 1
        if n_i > world // 2:
            raise ValueError(f"world {world} has no double-ring factoring")
    return n_i, world // n_i


def compile_program(pass_: str, topology: str, world: int,
                    rf: tuning.ResolvedFused,
                    r_live: Optional[int] = None) -> sched.RingProgram:
    """The RingProgram the fused dispatch would run for this config —
    same compiler entry, same slot/wire plumbing (ops/fused_ring._compile_for
    without a cfg object)."""
    n_inter, n_intra = (1, world) if topology != "double" else _factor(world)
    if pass_ == "fwd":
        return sched.compile_fwd(topology, n_intra, n_inter,
                                 slots=rf.kv_slots, slots1=rf.ccw_slots,
                                 r_live=r_live, wire=rf.wire_dtype)
    return sched.compile_bwd(topology, n_intra, n_inter,
                             slots=rf.bwd_slots, slots1=rf.bwd_ccw_slots,
                             dq_slots=rf.bwd_slots, r_live=r_live,
                             wire=rf.wire_dtype)


# ---------------------------------------------------------------------------
# VMEM / slot / semaphore plans (the kernel inventories, priced statically)


class ResourcePlan(NamedTuple):
    """Static resource footprint of one fused kernel launch.

    gate_bytes  the dispatch gate's admission formula (supported()'s plan)
    vmem_bytes  the full VMEM-space scratch + block-window inventory the
                kernel's pallas_call declares (mirrors scratch_shapes)
    slot_bytes  the ANY-space rotating slot banks (HBM-resident payload +
                scale + accumulator staging)
    sem_dma / sem_regular  semaphore census of the launch
    """

    gate_bytes: int
    vmem_bytes: int
    slot_bytes: int
    sem_dma: int
    sem_regular: int


def fwd_gate_bytes(rf: tuning.ResolvedFused, *, b: int, n: int, s: int,
                   d: int) -> int:
    """ops/fused_ring.supported's forward VMEM plan, re-derived: resident
    k+v chunk (wire itemsize), packed m/l stats, acc staging."""
    bq = _pick_block(s, rf.block_q)
    return 2 * s * d * rf.wire_itemsize + 2 * b * n * s * 4 + 3 * bq * d * 4


def bwd_gate_bytes(rf: tuning.ResolvedFused, *, s: int, d: int) -> int:
    """ops/fused_ring.supported's backward VMEM plan, re-derived: resident
    k+v chunk, fp32 dk/dv accumulators, per-step bundle + dq tiles."""
    bqb = _pick_block(s, rf.block_q_bwd)
    return (2 * s * d * 4 + 2 * s * d * 4
            + 3 * bqb * d * rf.wire_itemsize + 4 * bqb * d * 4)


def fwd_plan(rf: tuning.ResolvedFused, program: sched.RingProgram, *,
             b: int, n: int, n_kv: int, s: int, d: int,
             itemsize: int = 4) -> ResourcePlan:
    """Full static plan of one fused forward launch (ops/fused_ring.py's
    scratch_shapes + block windows, priced in bytes)."""
    wi = rf.wire_itemsize
    bq = _pick_block(s, rf.block_q)
    quant = program.wire is not None
    # ANY space: per-bank payload slot banks (+ fp32 scale sub-banks when
    # quantized) and the accbuf carry
    slot = 0
    for bank_slots in program.slots:
        slot += bank_slots * 2 * b * n_kv * s * d * wi
        if quant:
            slot += bank_slots * 2 * b * n_kv * 4
    slot += b * n * s * d * 4                      # accbuf (fp32 carry)
    # VMEM space: resident chunk, packed stats, acc tiles, block windows
    vmem = (2 * s * d * wi                         # kchunk + vchunk
            + (8 if quant else 0)                  # ksc_t + vsc_t
            + 2 * b * n * s * 4                    # mstat + lstat
            + 2 * bq * d * 4                       # acc_in + acc_scr
            + 2 * bq * 4                           # m_sw + l_sw
            + 2 * bq * d * itemsize                # q block window + o block
            + b * n * s * 4)                       # lse output block
    sem_dma = ((2 if not quant else 4) * len(program.copy_in)
               + (2 if not quant else 4)           # chunk_sem
               + 2)                                # acc_sem
    sem_reg = 0
    for bank_slots in program.slots:
        sem_dma += 4 * bank_slots                  # k/v send+recv per slot
        sem_reg += bank_slots                      # free credits
    gate = fwd_gate_bytes(rf, b=b, n=n, s=s, d=d)
    return ResourcePlan(gate, vmem, slot, sem_dma, sem_reg)


def bwd_plan(rf: tuning.ResolvedFused, program: sched.RingProgram, *,
             b: int, n: int, n_kv: int, s: int, d: int,
             itemsize: int = 4, opt_comm: bool = True) -> ResourcePlan:
    """Full static plan of one fused backward launch (ops/fused_ring_bwd.py's
    scratch_shapes + block windows, priced in bytes).  The dq-bank home
    slot is priced for EVERY dq ring bank (the kernel allocates it only on
    banks that receive a home stream) — a deliberate upper bound: a budget
    proof may over-count, never under-count."""
    wi = rf.wire_itemsize
    bqb = _pick_block(s, rf.block_q_bwd)
    quant = program.wire is not None
    dq_item = 1 if quant else 4
    first_elems = b * n * s if opt_comm else b * n * s * d
    slot = 0
    for bank_slots in program.slots:
        slot += bank_slots * (first_elems * wi       # firstbuf (delta | o)
                              + 2 * b * n * s * d * wi  # dobuf + qbuf
                              + b * n * s * 4)          # lsebuf (fp32)
        if quant:
            slot += bank_slots * 3 * b * n * 4         # f/do/q scale banks
    dq_ring_banks = (program.n_dq_banks if program.topology != "double"
                     else 1)
    for bank in range(dq_ring_banks):
        sl = program.dq_slots[bank] + 1                # +1 home slot bound
        slot += sl * b * n * s * d * dq_item
        if quant:
            slot += sl * b * n * 4                     # dqscbuf
    has_dqi = program.topology == "double"
    if has_dqi:
        slot += program.dq_slots[1] * b * n * s * d * dq_item
        if quant:
            slot += program.dq_slots[1] * b * n * 4    # dqiscbuf
    vmem = (2 * s * d * 4                              # kchunk + vchunk
            + 2 * s * d * 4                            # dk_acc + dv_acc
            + 2 * bqb * d * wi                         # q_t + do_t
            + (bqb * wi if opt_comm else bqb * d * wi)  # first_t
            + bqb * 4                                  # lse_t
            + 2 * bqb * d * dq_item                    # dq_arr + dqi_arr
            + bqb * d * 4                              # dq_scr
            + 2 * s * d * 4)                           # dk + dv out windows
    if quant:
        vmem += 5 * 4 + bqb * d * dq_item + 4          # scale tiles + dq_q
    sem_dma = (8                                       # cp_sem bound
               + 2 + 4                                 # chunk_sem + kvio_sem
               + (7 if quant else 4)                   # tile_sem
               + (6 if quant else 3))                  # dqio_sem
    sem_reg = 0
    for bank_slots in program.slots:
        sem_dma += 2 * bank_slots                      # psend + precv
        sem_reg += bank_slots                          # free_pay
    for bank in range(dq_ring_banks):
        sl = program.dq_slots[bank] + 1
        sem_dma += 2 * sl                              # dqsend + dqrecv
        sem_reg += sl                                  # free_dq
        sem_dma += 2                                   # home_sems
    if has_dqi:
        sem_dma += 2 * program.dq_slots[1]
        sem_reg += program.dq_slots[1]
    gate = bwd_gate_bytes(rf, s=s, d=d)
    return ResourcePlan(gate, vmem, slot, sem_dma, sem_reg)


def plan(pass_: str, rf: tuning.ResolvedFused, program: sched.RingProgram,
         *, b: int, n: int, n_kv: int, s: int, d: int, itemsize: int = 4,
         opt_comm: bool = True) -> ResourcePlan:
    if pass_ == "fwd":
        return fwd_plan(rf, program, b=b, n=n, n_kv=n_kv, s=s, d=d,
                        itemsize=itemsize)
    if pass_ != "bwd":
        raise ValueError(f"pass_ must be 'fwd' or 'bwd', got {pass_!r}")
    return bwd_plan(rf, program, b=b, n=n, n_kv=n_kv, s=s, d=d,
                    itemsize=itemsize, opt_comm=opt_comm)


def max_admitted_shard(pass_: str, rf: tuning.ResolvedFused, *, b: int,
                       n: int, d: int, cap: int = 1 << 22) -> int:
    """Largest power-of-two per-shard s the dispatch gate admits against
    this generation's fused_vmem_budget — the shard the budget-soundness
    theorem must prove compiles (kernel-vmem-budget checks the FULL plan
    at this s stays under the Mosaic VMEM_LIMIT)."""
    s, best = 256, 0
    while s <= cap:
        gate = (fwd_gate_bytes(rf, b=b, n=n, s=s, d=d) if pass_ == "fwd"
                else bwd_gate_bytes(rf, s=s, d=d))
        if gate > rf.vmem_budget:
            break
        best = s
        s *= 2
    return best


def ragged_plan_bytes(*, d_head: int, page: int, group: int,
                      quantized: bool, block_q: int = 8) -> int:
    """ops/ragged_paged.ragged_supported's VMEM plan, re-derived: q/o/acc
    tiles, score + m/l columns, double-buffered k/v pages."""
    rows = _block_rows(max(1, block_q), group)
    kv_bytes = 1 if quantized else 2
    return (rows * d_head * 4 * 3
            + rows * (page + 2) * 4
            + 4 * page * d_head * kv_bytes)


# the serving shapes the ragged plan is proven for: every combination the
# engine's defaults can dispatch (pages are 128-lane multiples; group 8 is
# the GQA headline, 1 the MHA floor; quantized covers the int8 KV cache)
RAGGED_MATRIX = tuple(
    dict(d_head=d_head, page=page, group=group, quantized=quantized)
    for d_head in (128, 256)
    for page in (128, 256, 512)
    for group in (1, 8)
    for quantized in (False, True))


# pool storage dtypes the paged KV cache can hold natively (ISSUE 17),
# with their per-element byte cost; the 1 B/elem dtypes additionally
# stream one fp32 scale per (token, kv head) for each of K and V
POOL_DTYPES = {"fp32": 4, "int8": 1, "fp8": 1}


def ragged_hbm_bytes(*, d_head: int, n_kv: int, kv_len: int,
                     pool_dtype: str) -> int:
    """Analytic HBM bytes ONE decode step's attention must stream per
    sequence: the full resident K+V at the pool's storage width, plus —
    on natively quantized pools — the fp32 per-token scale columns.
    Decode is bandwidth-bound (the q tile is one token), so this ratio
    IS the analytic decode-throughput win of a quantized pool."""
    eb = POOL_DTYPES[pool_dtype]
    total = 2 * n_kv * kv_len * d_head * eb         # K + V pages
    if eb == 1:
        total += 2 * n_kv * kv_len * 4              # fp32 scale sidecars
    return total


# ---------------------------------------------------------------------------
# FLOPs: closed forms over the global mask, and the devstats per-round sum


def pass_pairs(layout: str, s: int, world: int, *, causal: bool,
               window: Optional[int] = None) -> int:
    """Closed-form attending (row, col) pair count of ONE full ring pass,
    per (batch, head): the ring visits every (q chunk, kv chunk) pair
    exactly once across all devices and rounds, so the total is the GLOBAL
    S x S mask's pair count (S = world * s) — independent of layout and of
    dead-round elision (elided rounds attend zero pairs by construction).
    This is the identity the cost-model-consistent rule pins against the
    devstats algebra (devstats_pass_pairs)."""
    del layout  # layouts permute token placement, not the global mask
    S = world * s
    if not causal:
        return S * S
    if window is None:
        return S * (S + 1) // 2
    w = min(window, S)
    # rows 0..w-1 attend i+1 cols (triangular head); the rest attend w
    return w * (w + 1) // 2 + (S - w) * w


def devstats_pass_pairs(program: sched.RingProgram, layout: str, s: int, *,
                        causal: bool, window: Optional[int] = None,
                        pair_fn=None) -> int:
    """The devstats pair algebra: sum of the per-round occupancy closed
    form (masks.spec_pair_count's host twin) over every device and every
    EXECUTED round of the compiled program — exactly what the
    devstats.flops counter integrates at 4*d FLOPs/pair.  `pair_fn` is a
    mutation seam for the lint tests (defaults to the production twin)."""
    fn = _host_round_pairs if pair_fn is None else pair_fn
    total = 0
    for dev in range(program.world):
        inter, intra = divmod(dev, program.n_intra)
        for r in range(program.n_rounds):
            kv_part = sched.partition_for_round(program, r, inter, intra)
            total += fn(layout, dev, kv_part, s, causal, window)
    return total


def pass_flops(pass_: str, layout: str, *, b: int, n: int, s: int, d: int,
               world: int, causal: bool,
               window: Optional[int] = None) -> float:
    """Analytic MXU FLOPs of one pass across the WHOLE ring: 4*d per
    attending pair forward (qk^T + pv, matching obs/devstats.py's
    flops = attn_pairs * 4 * head_dim), 2.5x that backward (the 5-matmul
    recompute factor benchmarks/benchmark.flops uses)."""
    pairs = pass_pairs(layout, s, world, causal=causal, window=window)
    fwd = 4.0 * d * pairs * b * n
    return fwd if pass_ == "fwd" else 2.5 * fwd


# ---------------------------------------------------------------------------
# ICI bytes: an independent re-derivation of the wire formula, plus the
# compiled program's send census


def stream_bytes(pass_: str, wire: Optional[str], *, b: int, n: int,
                 n_kv: int, s: int, d: int, opt_comm: bool = True,
                 itemsize: int = 4) -> Dict[str, int]:
    """Per-round per-device ring bytes by stream, re-derived here from the
    payload shapes and quantization rules — deliberately NOT a call into
    schedule.wire_round_bytes, so the cost-model-consistent rule can pin
    the two derivations equal and catch either one drifting.

    fwd "kv": the k and v chunks (1 B/elem quantized, else the dense
    itemsize) plus one fp32 scale per (batch, kv head) per operand.
    bwd "bundle": delta (opt_comm) | o, do, q at wire width; lse exempt
    (fp32); three per-(batch, head) scales when quantized.
    bwd "dq": the streamed partial (fp32 dense / 1 B quantized) plus its
    refreshed per-(batch, head) scale."""
    wi = itemsize if wire is None else 1
    scale = 0 if wire is None else 4
    if pass_ == "fwd":
        return {"kv": 2 * (b * n_kv * s * d * wi + b * n_kv * scale)}
    if pass_ != "bwd":
        raise ValueError(f"pass_ must be 'fwd' or 'bwd', got {pass_!r}")
    first = b * n * s * (4 if wire is None else 1) if opt_comm \
        else b * n * s * d * wi
    bundle = first + 2 * b * n * s * d * wi + b * n * s * 4 \
        + 3 * b * n * scale
    dq = b * n * s * d * (4 if wire is None else 1) + b * n * scale
    return {"bundle": bundle, "dq": dq}


def send_census(program: sched.RingProgram) -> Dict[str, int]:
    """Per-device send counts of one compiled pass, straight off the op
    table: payload sends per channel, dq hops (ring + boundary + home /
    final — the add-and-forward stream the comm floor times)."""
    rows = program.rows
    n0 = sum(rows["send0"][r] for r in range(program.n_rounds))
    n1 = sum(rows["send1"][r] for r in range(program.n_rounds))
    out = {"send0": int(n0), "send1": int(n1), "dq": 0}
    if program.kind == "bwd":
        out["dq"] = sum(1 for r in range(program.n_rounds)
                        if rows["dq_send"][r] != sched.DQ_NONE)
    return out


def pass_ici_bytes(pass_: str, program: sched.RingProgram, *, b: int,
                   n: int, n_kv: int, s: int, d: int, opt_comm: bool = True,
                   itemsize: int = 4) -> int:
    """Total per-device ICI bytes of one pass: the per-round stream bytes
    times the compiled program's send census."""
    per = stream_bytes(pass_, program.wire, b=b, n=n, n_kv=n_kv, s=s, d=d,
                       opt_comm=opt_comm, itemsize=itemsize)
    c = send_census(program)
    if pass_ == "fwd":
        return (c["send0"] + c["send1"]) * per["kv"]
    return (c["send0"] + c["send1"]) * per["bundle"] + c["dq"] * per["dq"]


def pass_hbm_bytes(pass_: str, program: sched.RingProgram, *, b: int,
                   n: int, n_kv: int, s: int, d: int, opt_comm: bool = True,
                   itemsize: int = 4) -> int:
    """Per-device HBM traffic of one pass — the block-plan derivation.

    fwd: per executed round, the q sweep re-reads q and round-trips the
    fp32 accbuf carry, and the consumed chunk copies slot -> VMEM; one
    final o + lse writeback.  bwd: per round, the bundle copies slot ->
    tiles and the active dq partial round-trips; the resident k/v reads
    once and dk/dv write once."""
    R = program.n_rounds
    wi = itemsize if program.wire is None else 1
    if pass_ == "fwd":
        per_round = (b * n * s * d * itemsize        # q re-read
                     + 2 * b * n * s * d * 4         # accbuf round trip
                     + 2 * s * d * wi)               # chunk slot -> VMEM
        final = b * n * s * d * itemsize + b * n * s * 4   # o + lse
        return R * per_round + final + 2 * s * d * itemsize  # kv copy-in
    first = b * n * s * (4 if program.wire is None else 1) if opt_comm \
        else b * n * s * d * wi
    per_round = (first + 2 * b * n * s * d * wi + b * n * s * 4  # bundle
                 + 2 * b * n * s * d * 4)            # dq round trip
    return R * per_round + 2 * s * d * itemsize + 2 * s * d * 4


# ---------------------------------------------------------------------------
# roofline floors


class CostEstimate(NamedTuple):
    flops: float        # whole-ring pass FLOPs (all devices)
    hbm_bytes: int      # per-device HBM traffic
    ici_bytes: int      # per-device ICI traffic
    t_compute_s: float  # per-device compute floor: max(MXU, HBM) time
    t_comm_s: float     # per-device serialized-hop comm floor


def _comm_floor_s(pass_: str, program: sched.RingProgram, hw: HwSpec, *,
                  b: int, n: int, n_kv: int, s: int, d: int,
                  opt_comm: bool = True, itemsize: int = 4) -> float:
    """Serialized-hop comm floor: the critical chain of sends a device
    must wait out, per topology.  uni serializes every send down one link;
    bidi runs its two directions concurrently (the longer chain bounds);
    the double ring's inter hop is prefetched a full intra cycle early, so
    only the intra chain bounds.  The bwd dq stream shares the bundle's
    links one hop behind, so its hops add to the same chain (halved across
    the two directions of a bidi ring)."""
    per = stream_bytes(pass_, program.wire, b=b, n=n, n_kv=n_kv, s=s, d=d,
                       opt_comm=opt_comm, itemsize=itemsize)
    c = send_census(program)
    if program.topology == "bidi":
        chain = max(c["send0"], c["send1"])
        dq_hops = -(-c["dq"] // 2)
    elif program.topology == "double":
        chain = c["send0"]      # inter sends (send1) hide behind the cycle
        dq_hops = c["dq"]
    else:
        chain = c["send0"] + c["send1"]
        dq_hops = c["dq"]
    if pass_ == "fwd":
        return chain * per["kv"] / hw.ici_bw
    return (chain * per["bundle"] + dq_hops * per["dq"]) / hw.ici_bw


def roofline(pass_: str, generation: str, program: sched.RingProgram, *,
             layout: str, b: int, n: int, n_kv: int, s: int, d: int,
             causal: bool, window: Optional[int] = None,
             opt_comm: bool = True, itemsize: int = 4) -> CostEstimate:
    hw = _hw(generation)
    fl = pass_flops(pass_, layout, b=b, n=n, s=s, d=d,
                    world=program.world, causal=causal, window=window)
    hbm = pass_hbm_bytes(pass_, program, b=b, n=n, n_kv=n_kv, s=s, d=d,
                         opt_comm=opt_comm, itemsize=itemsize)
    ici = pass_ici_bytes(pass_, program, b=b, n=n, n_kv=n_kv, s=s, d=d,
                         opt_comm=opt_comm, itemsize=itemsize)
    t_mxu = fl / program.world / hw.peak_flops
    t_hbm = hbm / hw.hbm_bw
    t_comm = _comm_floor_s(pass_, program, hw, b=b, n=n, n_kv=n_kv, s=s,
                           d=d, opt_comm=opt_comm, itemsize=itemsize)
    return CostEstimate(fl, hbm, ici, max(t_mxu, t_hbm), t_comm)


def predict_floors(pass_: str, *, b: int, n: int, n_kv: int, s: int, d: int,
                   world: int, topology: str = "uni",
                   generation: Optional[str] = None,
                   wire: Optional[str] = None, layout: str = "zigzag",
                   causal: bool = True, window: Optional[int] = None,
                   opt_comm: bool = True,
                   itemsize: int = 4) -> Tuple[float, float]:
    """(t_comm_pred_s, t_compute_pred_s) — the static model's floors for
    one measured ring config, what benchmarks/ring_overlap.py records
    beside its measured floors so TPU rows calibrate HW for free.
    generation=None resolves the running device's generation and falls
    back to "v5e" off-TPU (the repo's measured hardware)."""
    if generation is None:
        generation = tuning.canonical_kind() or "v5e"
    r_live = None
    if window is not None and layout == "contig" and causal:
        rl = live_round_prefix(layout, s, world, causal=True, window=window)
        r_live = rl if rl < world else None
    rf = tuning.resolve_fused(table=tuning.generation_row(
        generation if generation in tuning.generations() else "default"),
        wire_dtype=wire)
    program = compile_program(pass_, topology, world, rf, r_live=r_live)
    est = roofline(pass_, generation if generation in HW else "default",
                   program, layout=layout, b=b, n=n, n_kv=n_kv, s=s, d=d,
                   causal=causal, window=window, opt_comm=opt_comm,
                   itemsize=itemsize)
    return est.t_comm_s, est.t_compute_s


def predict_metric(metric: str) -> Optional[float]:
    """Analytic roofline expectation for a bench.py headline metric string
    ("... TFLOPs/s/chip @ seq=65536 causal bf16"), or None when the metric
    is not a TFLOPs-style headline.  Assumes the canonical bench shape
    (world=8, 32 heads, d=128 — bench.py's defaults) and prices on v5e;
    scripts/check_regression.py surfaces it as the `predicted` verdict
    field so a stale cached number sits beside its analytic ceiling."""
    import re

    if "TFLOPs/s" not in metric:
        return None
    m = re.search(r"seq=(\d+)", metric)
    if not m:
        return None
    seq = int(m.group(1))
    world = DEFAULT_WORLD
    n = d = None
    n = DEFAULT_SHAPE["n"]
    d = DEFAULT_SHAPE["d"]
    s = max(1, seq // world)
    causal = "causal" in metric
    itemsize = 2 if "bf16" in metric else 4
    passes = PASSES if "fwd+bwd" in metric else ("fwd",)
    t = 0.0
    total_flops = 0.0
    for p in passes:
        tc, tx = predict_floors(p, b=1, n=n, n_kv=n, s=s, d=d, world=world,
                                generation="v5e", causal=causal,
                                itemsize=itemsize)
        t += max(tc, tx)
        total_flops += pass_flops(p, "zigzag", b=1, n=n, s=s, d=d,
                                  world=world, causal=causal)
    if t <= 0:
        return None
    return round(total_flops / world / t / 1e12, 2)


# ---------------------------------------------------------------------------
# the exported cost table (--cost-json): autotuner pruning + fleet/sim.py


def cost_table(world: int = DEFAULT_WORLD,
               shape: Optional[dict] = None) -> dict:
    """The full tuning-table x topology x wire-dtype x pass matrix, one
    machine-readable row per config: resolved knobs, static resource plan
    (at the canonical shape AND the largest gate-admitted shard), roofline
    estimates, and a `fits` verdict the autotuner prunes on.  Plus the
    ragged-paged serving plans and the per-pool-dtype decode HBM pricing
    (`ragged_hbm`, new in v2).  Schema "burstcost-v2" is pinned by
    tests/test_analysis.py."""
    shp = dict(DEFAULT_SHAPE if shape is None else shape)
    b, n, n_kv, s, d = (shp[k] for k in ("b", "n", "n_kv", "s", "d"))
    rows: List[dict] = []
    for gen in tuning.generations():
        table = tuning.generation_row(gen)
        for wire in sched.WIRE_DTYPES:
            rf = tuning.resolve_fused(table=table, wire_dtype=wire)
            for topo in sched.TOPOLOGIES:
                for pass_ in PASSES:
                    program = compile_program(pass_, topo, world, rf)
                    pl = plan(pass_, rf, program, b=b, n=n, n_kv=n_kv,
                              s=s, d=d)
                    s_max = max_admitted_shard(pass_, rf, b=b, n=n, d=d)
                    prog_max = program
                    pl_max = plan(pass_, rf, prog_max, b=b, n=n, n_kv=n_kv,
                                  s=s_max, d=d)
                    est = roofline(pass_, gen, program, layout="zigzag",
                                   b=b, n=n, n_kv=n_kv, s=s, d=d,
                                   causal=True)
                    rows.append({
                        "generation": gen, "topology": topo,
                        "wire": wire, "pass": pass_,
                        "block_q": rf.block_q if pass_ == "fwd"
                        else rf.block_q_bwd,
                        "block_kv": rf.block_kv if pass_ == "fwd"
                        else rf.block_kv_bwd,
                        "slots": list(program.slots),
                        "n_rounds": program.n_rounds,
                        "gate_bytes": pl.gate_bytes,
                        "vmem_bytes": pl.vmem_bytes,
                        "slot_bytes": pl.slot_bytes,
                        "sem_dma": pl.sem_dma,
                        "sem_regular": pl.sem_regular,
                        "budget": rf.vmem_budget,
                        "vmem_limit": VMEM_LIMIT,
                        "max_shard_seq": s_max,
                        "vmem_bytes_at_max": pl_max.vmem_bytes,
                        "fits": bool(pl.gate_bytes <= rf.vmem_budget
                                     and pl.vmem_bytes <= VMEM_LIMIT
                                     and pl_max.vmem_bytes <= VMEM_LIMIT),
                        "flops": est.flops,
                        "hbm_bytes": est.hbm_bytes,
                        "ici_bytes": est.ici_bytes,
                        "t_compute_s": est.t_compute_s,
                        "t_comm_s": est.t_comm_s,
                    })
    ragged = []
    for cfgr in RAGGED_MATRIX:
        pb = ragged_plan_bytes(**cfgr)
        ragged.append({**cfgr, "plan_bytes": pb, "vmem_limit": VMEM_LIMIT,
                       "fits": bool(pb <= VMEM_LIMIT)})
    # per-pool-dtype decode bandwidth: what one decode step streams at
    # the canonical shape's resident length, and the analytic win a
    # 1 B/elem pool buys over fp32 (scale sidecars included)
    ragged_hbm = []
    for d_head in (128, 256):
        base = ragged_hbm_bytes(d_head=d_head, n_kv=n_kv, kv_len=s,
                                pool_dtype="fp32")
        for pool_dtype, eb in sorted(POOL_DTYPES.items()):
            hb = ragged_hbm_bytes(d_head=d_head, n_kv=n_kv, kv_len=s,
                                  pool_dtype=pool_dtype)
            ragged_hbm.append({
                "d_head": d_head, "n_kv": n_kv, "kv_len": s,
                "pool_dtype": pool_dtype, "kv_elem_bytes": eb,
                "hbm_bytes": hb,
                "win_vs_fp32": base / hb,
            })
    return {
        "schema": "burstcost-v2",
        "world": world,
        "shape": shp,
        "hw": {g: {"peak_flops": h.peak_flops, "hbm_bw": h.hbm_bw,
                   "ici_bw": h.ici_bw} for g, h in sorted(HW.items())},
        "n_rows": len(rows),
        "rows": rows,
        "ragged": ragged,
        "ragged_hbm": ragged_hbm,
    }
