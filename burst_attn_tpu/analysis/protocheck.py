"""proto-*: the serving protocols, model-checked (burstlint).

Four rules back onto burstcheck (analysis/modelcheck.py), which BFS-
explores every interleaving of the protocol models — crash injected at
every step — and proves the safety invariants the fleet's docs promise:

  proto-transfer-atomic   every KV transfer lands exactly once; a kill
                          or abort at ANY step leaves the receiver pool
                          without a single leaked page.  Checked twice:
                          full-precision, and quantized — where every
                          kv_page frame is a (page, scale) PAIR that
                          must stage/land/abort as one unit
  proto-journal-durable   no token reaches a caller before its journal
                          record is fsynced (delivered ⟹ durable), so a
                          crash never un-happens delivered output
  proto-pool-conserved    free-list/refcount conservation and the CoW
                          write barrier hold under every interleaving
                          of admit/share/append/retire/evict
  proto-no-deadlock       bounded liveness: until the protocol run
                          resolves, some non-fault transition is always
                          enabled (a credit/ack circular wait is the
                          canonical violation)

The models transition through the SAME pure machines production runs
(`burst_attn_tpu.protocols.*` — see each class's delegation), so these
rules watch real code, not a spec that can drift.  Findings carry the
minimal counterexample trace; anchors point at the production method
that executes the violated machine.

The gate runs shallow-bound canaries (exhaustive for these model
sizes — `truncated` stays False); tests/test_modelcheck.py re-runs the
sweep at deep bounds and larger models under @slow.  Mutation coverage
(tests/test_analysis.py): dropped fsync, eager-staging page leak,
skipped commit preconditions, no-op CoW, and a per-page credit window
against the commit-time-only ack each fire exactly one rule.
"""

from typing import List

from .core import Finding, rule
from . import modelcheck as mc

rule("proto-transfer-atomic", "model",
     "every KV transfer lands exactly once with zero receiver-pool page "
     "leaks, under all interleavings with kill at every step")(None)
rule("proto-journal-durable", "model",
     "delivered ⟹ durable: no token reaches a caller before its journal "
     "record is fsynced, under all interleavings incl. crash")(None)
rule("proto-pool-conserved", "model",
     "pool free-list/refcount conservation + the CoW write barrier hold "
     "under every admit/share/append/retire/evict interleaving")(None)
rule("proto-no-deadlock", "model",
     "bounded liveness: some non-fault transition stays enabled until "
     "the protocol run resolves (no credit/ack circular wait)")(None)

# which rule owns a model's SAFETY violations (deadlocks all map to
# proto-no-deadlock regardless of model)
_SAFETY_RULE = {
    "transfer": "proto-transfer-atomic",
    "journal": "proto-journal-durable",
    "pool": "proto-pool-conserved",
}

# gate bounds: exhaustive for these model sizes (the clean runs finish
# far below them with truncated=False — see docs/analysis.md for how to
# size bounds when growing a model)
_GATE = (
    (mc.transfer_model, {}, 40, 100_000),
    # the quantized-pool variant: kv_page frames carry (page, scale)
    # pairs; exactly-once landing must hold PER PAIR (a split sidecar —
    # SCALE_PAIRED mutated off — fires proto-transfer-atomic)
    (mc.transfer_model, {"quantized": True}, 40, 100_000),
    (mc.journal_model, {}, 24, 50_000),
    (mc.pool_model, {}, 20, 50_000),
)


def _anchor(model_name: str):
    """Anchor findings at the production code that EXECUTES the violated
    machine, so the finding is clickable where the fix goes."""
    import inspect

    try:
        if model_name == "transfer":
            from ..fleet import kvplane
            fn = kvplane.KvReceiver.commit
        elif model_name == "journal":
            from ..serving import checkpoint
            fn = checkpoint.TokenJournal.delivered
        else:
            from ..models import paged_decode
            fn = paged_decode.PagePool._step
        return inspect.getsourcefile(fn), inspect.getsourcelines(fn)[1]
    except (OSError, TypeError, ImportError):
        return "<trace>", 0


def run_models(specs=_GATE) -> List[mc.CheckResult]:
    return [mc.check(mk(**kw), max_depth=depth, max_states=states)
            for mk, kw, depth, states in specs]


def check_all() -> List[Finding]:
    findings: List[Finding] = []
    for res in run_models():
        if res.ok:
            if res.truncated:
                # a truncated CLEAN run proved nothing exhaustively;
                # surface it as the model's safety rule rather than
                # passing silently
                f, ln = _anchor(res.model)
                findings.append(Finding(
                    rule=_SAFETY_RULE[res.model],
                    message=(f"model '{res.model}' hit its search bound "
                             f"({res.states} states) before exhausting "
                             f"interleavings — raise the gate bound"),
                    file=f, line=ln))
            continue
        v = res.violation
        name = ("proto-no-deadlock" if v.kind == "deadlock"
                else _SAFETY_RULE[res.model])
        f, ln = _anchor(res.model)
        findings.append(Finding(
            rule=name,
            message=(f"model '{res.model}' ({res.states} states "
                     f"explored): {mc.format_trace(v)}"),
            file=f, line=ln))
    return findings
