"""Generic jaxpr walking for the burstlint verifiers.

Extracts the ordered stream of collective events (ppermute / all_to_all /
psum) from a traced program, unrolling scan bodies by their static trip
count and flattening nested call primitives (pjit, custom_vjp, shard_map,
pallas_call, cond branches).  Pure host-side: operates on the jaxpr data
structure only, never executes the program.
"""

from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple


@dataclass
class CollEvent:
    prim: str                 # "ppermute" | "all_to_all" | "psum"
    axis: str
    hops: Optional[int]       # uniform rotation offset; None if not a rotation
    dtype: str
    rank: int
    perm: Optional[Tuple] = None
    in_cond: bool = False     # event sits under a lax.cond branch
    in_while: bool = False    # event sits under a while loop (trip unknown)


def _axis_str(axis_name) -> str:
    if isinstance(axis_name, (tuple, list)):
        return str(axis_name[0]) if len(axis_name) == 1 else ",".join(
            str(a) for a in axis_name)
    return str(axis_name)


def rotation_offset(perm) -> Optional[int]:
    """Uniform rotation offset of a ppermute perm, or None when the perm is
    not a bijective constant-offset rotation over 0..n-1."""
    n = len(perm)
    srcs = sorted(p[0] for p in perm)
    dsts = sorted(p[1] for p in perm)
    if srcs != list(range(n)) or dsts != list(range(n)):
        return None
    offs = {(d - s) % n for s, d in perm}
    if len(offs) != 1:
        return None
    return offs.pop()


def _subjaxprs(params):
    """(key, jaxpr) pairs for every jaxpr-valued param of an equation."""
    for k, v in params.items():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            if hasattr(item, "eqns"):
                yield k, item
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield k, item.jaxpr


def collect_collectives(jaxpr, *, _in_cond=False, _in_while=False
                        ) -> List[CollEvent]:
    """Flat, issue-ordered collective event stream of `jaxpr` (a Jaxpr or
    ClosedJaxpr).  Scan bodies repeat `length` times; both cond branches
    are walked in order (flagged in_cond) — the ring code keeps collectives
    outside conds, and the checkers treat any conditional collective as a
    finding in its own right."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    events: List[CollEvent] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "ppermute":
            perm = tuple(tuple(p) for p in eqn.params["perm"])
            aval = eqn.outvars[0].aval
            events.append(CollEvent(
                prim="ppermute",
                axis=_axis_str(eqn.params["axis_name"]),
                hops=rotation_offset(perm),
                dtype=str(aval.dtype), rank=len(aval.shape), perm=perm,
                in_cond=_in_cond, in_while=_in_while))
        elif name in ("all_to_all", "psum"):
            aval = eqn.outvars[0].aval
            events.append(CollEvent(
                prim=name, axis=_axis_str(eqn.params.get("axis_name")),
                hops=None, dtype=str(aval.dtype), rank=len(aval.shape),
                in_cond=_in_cond, in_while=_in_while))
        elif name == "scan":
            body = collect_collectives(
                eqn.params["jaxpr"], _in_cond=_in_cond, _in_while=_in_while)
            events.extend(body * int(eqn.params["length"]))
        elif name == "cond":
            for br in eqn.params["branches"]:
                events.extend(collect_collectives(
                    br, _in_cond=True, _in_while=_in_while))
        elif name == "while":
            for key in ("cond_jaxpr", "body_jaxpr"):
                events.extend(collect_collectives(
                    eqn.params[key], _in_cond=_in_cond, _in_while=True))
        else:
            for _k, sub in _subjaxprs(eqn.params):
                events.extend(collect_collectives(
                    sub, _in_cond=_in_cond, _in_while=_in_while))
    return events


def iter_eqns(jaxpr):
    """Yield every equation of `jaxpr` and its nested subjaxprs (scan
    bodies once, both cond branches) — for the numerics walkers, where
    multiplicity doesn't matter."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for _k, sub in _subjaxprs(eqn.params):
            yield from iter_eqns(sub)
