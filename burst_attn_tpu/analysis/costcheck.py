"""cost-*: the static resource & roofline verifier (burstlint family 4).

Three rules back onto burstcost (analysis/costmodel.py), which prices
every fused-kernel config with no device in hand:

  kernel-vmem-budget     every tuning-table row x {uni, bidi, double} x
                         {fp32, int8, fp8} x {fwd, bwd} config fits: the
                         dispatch gate's plan stays within the row's
                         fused_vmem_budget at the canonical shape, the
                         FULL kernel scratch inventory stays within the
                         Mosaic VMEM_LIMIT at the LARGEST shard the gate
                         admits (admitted ⟹ compiles), the semaphore
                         census stays under its tripwires, and every
                         ragged-paged serving shape fits its plan
  cost-model-consistent  the roofline's inputs agree with production
                         counters: closed-form pass pairs == the devstats
                         per-round pair algebra summed over the compiled
                         program (exactly, including elided rounds), and
                         the model's independent stream-bytes derivation
                         == schedule.wire_round_bytes (the single source
                         the burst.wire_bytes counter integrates) over
                         pass x wire x opt_comm x itemsize; measured TPU
                         rows in results/ring_overlap.jsonl must sit
                         within the calibration band of the model's floor
  tuning-table-sound     the raw tables obey the invariants dispatch
                         assumes: bwd blocks never resolve larger than
                         fwd, cliff clamps are monotone and in-budget,
                         slots >= 2, wire dtypes legal, budgets within
                         the Mosaic limit, blocks lane-aligned, aliases
                         resolve, and later generations never shrink the
                         v5e-measured cliff areas

The checks compute through the SAME resolution algebra production
dispatch runs (tuning.resolve_fused(table=row), sched.compile_fwd/bwd,
ops gates' formulas re-derived) so they watch real code, not a spec that
can drift.  The gate prices the full 90-config matrix — pure host
arithmetic, well under a second.  tests/test_costmodel.py re-runs deep
per-generation shape sweeps under @slow with a fast canary.  Mutation
coverage (tests/test_analysis.py): an inflated slot plan with an
unchanged budget, a window-blind pair function, and a fwd<bwd table
inversion each fire exactly one rule.
"""

import json
import os
from typing import List, Optional

from .core import Finding, rule
from . import costmodel as cm
from ..ops import tuning
from ..parallel import schedule as sched

rule("kernel-vmem-budget", "cost",
     "every tuning-table x topology x wire-dtype x pass config fits its "
     "generation's VMEM budget, and the full kernel scratch inventory "
     "fits the Mosaic limit at the largest gate-admitted shard")(None)
rule("cost-model-consistent", "cost",
     "roofline FLOPs == devstats pair algebra over compiled programs "
     "(incl. elided rounds); model stream bytes == wire_round_bytes (the "
     "burst.wire_bytes formula); measured TPU floors within band")(None)
rule("tuning-table-sound", "cost",
     "tuning tables obey dispatch's invariants: bwd blocks <= fwd, "
     "cliff clamps monotone + in-budget, slots/wire/alignment legal, "
     "budgets within the Mosaic limit, aliases resolve")(None)

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_OVERLAP_JSONL = os.path.join(_ROOT, "results", "ring_overlap.jsonl")

# calibration band for measured TPU floors: the model's t_comm floor is a
# spec-sheet lower bound, so a measured comm-only time should not beat it
# by more than 2x (model overestimates -> autotuner mispruning) nor exceed
# it by more than 20x (model wildly optimistic -> floors meaningless)
_CALIB_FAST, _CALIB_SLOW = 0.5, 20.0

# the small consistency mesh: exact identities are shape-independent, so
# the gate proves them at a cheap shard size on the canonical 8-ring
_CONSIST_S, _CONSIST_WORLD = 512, 8


def _anchor(which: str):
    """Anchor findings at the production code whose numbers the model
    mirrors — where a fix (or a model update) goes."""
    import inspect

    try:
        if which == "gate":
            from ..ops import fused_ring
            fn = fused_ring.supported
        elif which == "ragged":
            from ..ops import ragged_paged
            fn = ragged_paged.ragged_supported
        elif which == "wire":
            fn = sched.wire_round_bytes
        elif which == "pairs":
            from ..ops import masks
            fn = masks.spec_pair_count
        else:  # "table"
            fn = tuning.block_defaults
        return inspect.getsourcefile(fn), inspect.getsourcelines(fn)[1]
    except (OSError, TypeError, ImportError):
        return "<trace>", 0


# ---------------------------------------------------------------------------
# kernel-vmem-budget


def check_vmem_budget(table=None, world: int = cm.DEFAULT_WORLD,
                      shape: Optional[dict] = None) -> List[Finding]:
    """Prove the full config matrix within budget.  `table` narrows the
    sweep to one injected BlockTable row (the mutation seam — an inflated
    row with an unchanged budget must fire); default sweeps every
    generation."""
    findings: List[Finding] = []
    shp = dict(cm.DEFAULT_SHAPE if shape is None else shape)
    b, n, n_kv, s, d = (shp[k] for k in ("b", "n", "n_kv", "s", "d"))
    gens = ((("<injected>", table),) if table is not None
            else tuple((g, tuning.generation_row(g))
                       for g in tuning.generations()))
    gate_f, gate_ln = _anchor("gate")
    for gen, row in gens:
        for wire in sched.WIRE_DTYPES:
            rf = tuning.resolve_fused(table=row, wire_dtype=wire)
            for topo in sched.TOPOLOGIES:
                for pass_ in cm.PASSES:
                    program = cm.compile_program(pass_, topo, world, rf)
                    pl = cm.plan(pass_, rf, program, b=b, n=n, n_kv=n_kv,
                                 s=s, d=d)
                    ctx = (f"{gen}/{topo}/{wire or 'fp32'}/{pass_}")
                    if pl.gate_bytes > rf.vmem_budget:
                        findings.append(Finding(
                            rule="kernel-vmem-budget",
                            message=(f"{ctx}: gate plan {pl.gate_bytes} B "
                                     f"exceeds fused_vmem_budget "
                                     f"{rf.vmem_budget} B at the canonical "
                                     f"shape (s={s}) — the dispatch gate "
                                     "would reject its own generation"),
                            file=gate_f, line=gate_ln))
                    if pl.vmem_bytes > cm.VMEM_LIMIT:
                        findings.append(Finding(
                            rule="kernel-vmem-budget",
                            message=(f"{ctx}: full scratch inventory "
                                     f"{pl.vmem_bytes} B exceeds the Mosaic "
                                     f"VMEM_LIMIT {cm.VMEM_LIMIT} B at the "
                                     f"canonical shape (s={s})"),
                            file=gate_f, line=gate_ln))
                    s_max = cm.max_admitted_shard(pass_, rf, b=b, n=n, d=d)
                    if s_max:
                        pl_max = cm.plan(pass_, rf, program, b=b, n=n,
                                         n_kv=n_kv, s=s_max, d=d)
                        if pl_max.vmem_bytes > cm.VMEM_LIMIT:
                            findings.append(Finding(
                                rule="kernel-vmem-budget",
                                message=(f"{ctx}: the gate ADMITS shard "
                                         f"s={s_max} but the full scratch "
                                         f"inventory there is "
                                         f"{pl_max.vmem_bytes} B > "
                                         f"VMEM_LIMIT {cm.VMEM_LIMIT} B — "
                                         "admitted config would fail to "
                                         "compile; tighten the budget or "
                                         "the gate formula"),
                                file=gate_f, line=gate_ln))
                    if (pl.sem_dma > cm.SEM_DMA_BUDGET
                            or pl.sem_regular > cm.SEM_REGULAR_BUDGET):
                        findings.append(Finding(
                            rule="kernel-vmem-budget",
                            message=(f"{ctx}: semaphore census "
                                     f"(dma={pl.sem_dma}, "
                                     f"regular={pl.sem_regular}) exceeds "
                                     f"the tripwire "
                                     f"({cm.SEM_DMA_BUDGET}/"
                                     f"{cm.SEM_REGULAR_BUDGET}) — an "
                                     "unintended per-slot array grew the "
                                     "schedule"),
                            file=gate_f, line=gate_ln))
    rag_f, rag_ln = _anchor("ragged")
    if table is None:
        for cfgr in cm.RAGGED_MATRIX:
            pb = cm.ragged_plan_bytes(**cfgr)
            if pb > cm.VMEM_LIMIT:
                findings.append(Finding(
                    rule="kernel-vmem-budget",
                    message=(f"ragged {cfgr}: plan {pb} B exceeds "
                             f"VMEM_LIMIT {cm.VMEM_LIMIT} B"),
                    file=rag_f, line=rag_ln))
    return findings


# ---------------------------------------------------------------------------
# cost-model-consistent


def check_cost_consistency(pair_fn=None,
                           overlap_path: str = _OVERLAP_JSONL
                           ) -> List[Finding]:
    """Pin the roofline's inputs to production counters.  `pair_fn`
    substitutes the devstats per-round pair twin (the mutation seam — a
    window-blind variant must fire); default is the production twin."""
    findings: List[Finding] = []
    s, world = _CONSIST_S, _CONSIST_WORLD
    pairs_f, pairs_ln = _anchor("pairs")
    rf = tuning.resolve_fused(table=tuning.generation_row("default"))
    cases = [(layout, topo, True, None)
             for layout in ("zigzag", "striped", "contig")
             for topo in sched.TOPOLOGIES]
    cases += [("zigzag", "uni", False, None),    # non-causal
              ("contig", "uni", True, 3 * s // 2)]  # windowed -> elision
    for layout, topo, causal, window in cases:
        r_live = None
        if window is not None:
            from ..ops.masks import live_round_prefix
            rl = live_round_prefix(layout, s, world, causal=causal,
                                   window=window)
            r_live = rl if rl < world else None
        program = cm.compile_program("fwd", topo, world, rf, r_live=r_live)
        closed = cm.pass_pairs(layout, s, world, causal=causal,
                               window=window)
        summed = cm.devstats_pass_pairs(program, layout, s, causal=causal,
                                        window=window, pair_fn=pair_fn)
        if closed != summed:
            findings.append(Finding(
                rule="cost-model-consistent",
                message=(f"pair algebra split: closed form says {closed} "
                         f"attending pairs for {layout}/{topo} "
                         f"(causal={causal}, window={window}, s={s}, "
                         f"world={world}) but the devstats per-round sum "
                         f"over the compiled program says {summed} — the "
                         "roofline's FLOPs no longer match what the "
                         "devstats counters will integrate"),
                file=pairs_f, line=pairs_ln))
    wire_f, wire_ln = _anchor("wire")
    for pass_ in cm.PASSES:
        for wire in sched.WIRE_DTYPES:
            for opt_comm in (True, False):
                for itemsize in (4, 2):
                    kw = dict(b=2, n=16, n_kv=4, s=s, d=128,
                              opt_comm=opt_comm, itemsize=itemsize)
                    ours = cm.stream_bytes(pass_, wire, **kw)
                    theirs = sched.wire_round_bytes(pass_, wire, **kw)
                    if ours != theirs:
                        findings.append(Finding(
                            rule="cost-model-consistent",
                            message=(f"stream-bytes split for {pass_}/"
                                     f"{wire or 'fp32'}/opt_comm={opt_comm}"
                                     f"/itemsize={itemsize}: model says "
                                     f"{ours}, wire_round_bytes (the "
                                     f"burst.wire_bytes formula) says "
                                     f"{theirs}"),
                            file=wire_f, line=wire_ln))
    findings.extend(_check_measured_floors(overlap_path))
    return findings


def _check_measured_floors(path: str) -> List[Finding]:
    """Calibrate against measured TPU rows of results/ring_overlap.jsonl:
    a comm-only measurement outside [0.5x, 20x] of the model's floor means
    the HW table (or the hop model) is wrong enough to misprune.  CPU
    smoke rows are skipped — interpret-mode timing calibrates nothing."""
    findings: List[Finding] = []
    if not os.path.exists(path):
        return findings
    wire_f, wire_ln = _anchor("wire")
    with open(path, encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                row = json.loads(raw)
            except ValueError:
                continue
            if row.get("backend") != "tpu":
                continue
            meas = row.get("t_comm_only_s")
            if not meas or row.get("pass") not in cm.PASSES:
                continue
            try:
                t_comm, _ = cm.predict_floors(
                    row["pass"], b=1, n=int(row["heads"]),
                    n_kv=int(row["heads"]),
                    s=int(row["seq"]) // int(row["world"]),
                    d=int(row["dim"]), world=int(row["world"]),
                    topology=row.get("topology", "uni"),
                    generation="v5e", wire=row.get("wire_dtype"),
                    layout=row.get("layout", "zigzag"),
                    causal=bool(row.get("causal", True)),
                    window=row.get("window"))
            except (KeyError, TypeError, ValueError):
                continue
            if t_comm <= 0:
                continue
            ratio = float(meas) / t_comm
            if not (_CALIB_FAST <= ratio <= _CALIB_SLOW):
                findings.append(Finding(
                    rule="cost-model-consistent",
                    message=(f"ring_overlap.jsonl:{lineno}: measured TPU "
                             f"comm-only {meas:.6f}s is {ratio:.2f}x the "
                             f"model floor {t_comm:.6f}s (band "
                             f"[{_CALIB_FAST}, {_CALIB_SLOW}]) for "
                             f"{row.get('pass')}/{row.get('topology')} "
                             f"seq={row.get('seq')} — recalibrate "
                             "costmodel.HW"),
                    file=wire_f, line=wire_ln))
    return findings


# ---------------------------------------------------------------------------
# tuning-table-sound


def check_tuning_sound(table=None) -> List[Finding]:
    """The invariants dispatch assumes of every table row.  `table`
    narrows to one injected row (the mutation seam — a fwd<bwd inversion
    must fire); default sweeps the real tables."""
    findings: List[Finding] = []
    tab_f, tab_ln = _anchor("table")

    def bad(msg):
        findings.append(Finding(rule="tuning-table-sound", message=msg,
                                file=tab_f, line=tab_ln))

    gens = ((("<injected>", table),) if table is not None
            else tuple((g, tuning.generation_row(g))
                       for g in tuning.generations()))
    for gen, row in gens:
        # raw bwd fields never exceed their fwd partner: resolve_fused
        # min()-clamps bwd blocks against fwd, so an inverted RAW entry is
        # silently ignored dead weight — exactly the drift this rule exists
        # to catch before a tuner trusts the number
        for bwd_field, fwd_field in (
                ("fused_block_q_bwd", "fused_block_q"),
                ("fused_block_kv_bwd", "fused_block_kv"),
                ("bwd_cliff_area", "fwd_cliff_area")):
            bv, fv = getattr(row, bwd_field), getattr(row, fwd_field)
            if bv > fv:
                bad(f"{gen}: {bwd_field}={bv} > {fwd_field}={fv} — the "
                    "bwd pass tiles a strict subset of fwd VMEM; an "
                    "inverted raw entry is dead weight resolve_fused "
                    "silently clamps away")
        # resolved view agrees (resolve through the SAME algebra dispatch
        # runs): bwd blocks never resolve larger than fwd
        for wire in sched.WIRE_DTYPES:
            rf = tuning.resolve_fused(table=row, wire_dtype=wire)
            if rf.block_q_bwd > rf.block_q or rf.block_kv_bwd > rf.block_kv:
                bad(f"{gen}/{wire or 'fp32'}: resolved bwd blocks "
                    f"({rf.block_q_bwd},{rf.block_kv_bwd}) exceed fwd "
                    f"({rf.block_q},{rf.block_kv})")
            for slot_field in ("kv_slots", "ccw_slots", "bwd_slots",
                               "bwd_ccw_slots"):
                if getattr(rf, slot_field) < 2:
                    bad(f"{gen}/{wire or 'fp32'}: {slot_field}="
                        f"{getattr(rf, slot_field)} < 2 — the ring "
                        "needs a landing slot while one is in flight")
        if row.fused_wire_dtype not in sched.WIRE_DTYPES:
            bad(f"{gen}: fused_wire_dtype={row.fused_wire_dtype!r} not in "
                f"{sched.WIRE_DTYPES}")
        if row.fused_vmem_budget > cm.VMEM_LIMIT:
            bad(f"{gen}: fused_vmem_budget={row.fused_vmem_budget} exceeds "
                f"the Mosaic VMEM_LIMIT {cm.VMEM_LIMIT} — the gate would "
                "admit configs Mosaic rejects")
        for field in ("fwd_block_q", "fwd_block_kv", "fwd_block_kv_compute",
                      "bwd_block_q", "bwd_block_kv", "fused_block_q",
                      "fused_block_kv", "fused_block_q_bwd",
                      "fused_block_kv_bwd"):
            v = getattr(row, field)
            if v <= 0 or v % 128:
                bad(f"{gen}: {field}={v} is not a positive multiple of "
                    "the 128-lane tile")
        # cliff clamp monotone: never grows kv, never exceeds the area
        # (above the 128-lane floor), and larger areas never shrink the
        # result.  Probed at areas below AND above the blocks' product so
        # the clamping branch itself is exercised; the tuning logger is
        # muted around the probes (the warning is for real dispatches).
        bq, bkv = row.fused_block_q_bwd, row.fused_block_kv_bwd
        prev = 0
        if tuning._cliff_ok():
            continue  # BURST_ALLOW_CLIFF=1: the clamp is deliberately off
        was_disabled = tuning.logger.disabled
        tuning.logger.disabled = True
        try:
            for area in sorted({bq * 128, bq * bkv // 2, bq * bkv,
                                row.bwd_cliff_area}):
                _, kv = tuning._clamp_cliff(bq, bkv, area, "cost-lint")
                if kv > bkv:
                    bad(f"{gen}: _clamp_cliff grew block_kv {kv} past the "
                        f"table's {bkv} at area {area}")
                if bq * kv > max(area, 128 * bq):
                    bad(f"{gen}: _clamp_cliff result {kv} violates area "
                        f"{area} (and is not the 128-lane floor)")
                if kv < prev:
                    bad(f"{gen}: _clamp_cliff not monotone in area: {kv} "
                        f"at area {area} < {prev} at the smaller area")
                prev = kv
        finally:
            tuning.logger.disabled = was_disabled
    if table is None:
        for alias, target in tuning._KIND_ALIASES:
            if target not in tuning._TABLE:
                bad(f"alias {alias!r} -> {target!r} resolves outside the "
                    "tuning table")
        v5e = tuning.generation_row("v5e")
        for gen in ("v5p", "v4"):
            row = tuning.generation_row(gen)
            if (row.fwd_cliff_area < v5e.fwd_cliff_area
                    or row.bwd_cliff_area < v5e.bwd_cliff_area):
                bad(f"{gen}: cliff areas shrink below the v5e-measured "
                    "floor — bigger-VMEM generations never clamp harder")
    return findings


def check_all() -> List[Finding]:
    findings = check_vmem_budget()
    findings += check_cost_consistency()
    findings += check_tuning_sound()
    return findings
