"""Numerics verifiers (burstlint family 1, rules fp32-accum / lse-fp32).

Walks the jaxprs of the attention tile oracle (ops/tile.py) and the Pallas
flash kernels (ops/pallas_flash.py, traced through the pallas_call
equation's inner jaxpr — no TPU needed) on bf16 inputs and asserts the
FlashAttention numerics contract (arXiv 2205.14135; PAPER.md):

  fp32-accum  every dot_general with a low-precision (bf16/f16) operand
              accumulates in float32 (preferred_element_type) — an MXU dot
              that keeps a bf16 accumulator loses ~8 bits of mantissa per
              long-sequence softmax reduction.
  lse-fp32    the running-max / log-sum-exp statistics ([B, N, S] rank-3
              float32 tensors in every shard-level trace) are never
              downcast below fp32 mid-ring; only the final rank-4 output
              may cast back to the activation dtype.
"""

import inspect
from typing import List

from .core import Finding, rule
from .jaxpr_tools import iter_eqns

rule("fp32-accum", "jaxpr",
     "dot_general on bf16/f16 operands must accumulate in float32")(None)
rule("lse-fp32", "jaxpr",
     "rank-3 softmax stats (m/lse/delta) must never downcast below fp32")(None)

_LOW = ("bfloat16", "float16")


def _anchor(fn):
    try:
        return inspect.getsourcefile(fn), inspect.getsourcelines(fn)[1]
    except (OSError, TypeError):
        return "<trace>", 0


def check_trace(closed_jaxpr, *, where: str, anchor,
                stats_rank: int = 3) -> List[Finding]:
    """Run both numerics rules over one traced program."""
    findings: List[Finding] = []
    path, line = anchor
    for eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name == "dot_general":
            in_dtypes = {str(v.aval.dtype) for v in eqn.invars
                         if hasattr(v.aval, "dtype")}
            out_dtype = str(eqn.outvars[0].aval.dtype)
            if in_dtypes & set(_LOW) and out_dtype != "float32":
                findings.append(Finding(
                    rule="fp32-accum", file=path, line=line,
                    message=f"{where}: dot_general({'/'.join(sorted(in_dtypes))})"
                            f" accumulates in {out_dtype}, not float32 — "
                            "pass preferred_element_type=jnp.float32"))
        elif name == "convert_element_type":
            out = eqn.outvars[0].aval
            src = eqn.invars[0].aval
            if (str(getattr(src, "dtype", "")) == "float32"
                    and str(out.dtype) in _LOW
                    and len(getattr(src, "shape", ())) == stats_rank):
                findings.append(Finding(
                    rule="lse-fp32", file=path, line=line,
                    message=f"{where}: rank-{stats_rank} float32 stat tensor "
                            f"{tuple(src.shape)} downcast to {out.dtype} — "
                            "m/lse/delta must stay fp32 across ring rounds"))
    return findings


# ---------------------------------------------------------------------------
# wire-precision scale handling (reported under fused-ring-fused)

_QUANT = ("int8", "float8_e4m3fn", "float8_e5m2")
# prims a still-unscaled dequantized value may flow through: linear in the
# value, so the deferred per-block scale can still be applied after them
# (the fused fwd multiplies AFTER the QK/PV dot — distributivity)
_WIRE_PASS = {
    "convert_element_type", "reshape", "transpose", "broadcast_in_dim",
    "squeeze", "expand_dims", "slice", "dynamic_slice", "rev", "copy",
    "neg", "dot_general", "concatenate", "gather",
}


def _tainted_in(eqn, tainted):
    return any((v in tainted) for v in eqn.invars if not hasattr(v, "val"))


def _map_sub_taint(eqn, tainted):
    """(subjaxprs, per-sub tainted-invar sets) for control-flow prims whose
    operand->body mapping is positional; everything else recurses fresh."""
    name = eqn.primitive.name
    outs = []
    if name == "cond":
        ops = eqn.invars[1:]
        for br in eqn.params["branches"]:
            jx = br.jaxpr if hasattr(br, "jaxpr") else br
            sub_t = {sv for v, sv in zip(ops, jx.invars)
                     if not hasattr(v, "val") and v in tainted}
            outs.append((jx, sub_t))
        return outs
    for key in ("jaxpr", "call_jaxpr"):
        sub = eqn.params.get(key)
        if sub is None:
            continue
        jx = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        if not hasattr(jx, "eqns"):
            continue
        if len(jx.invars) == len(eqn.invars):
            sub_t = {sv for v, sv in zip(eqn.invars, jx.invars)
                     if not hasattr(v, "val") and v in tainted}
        else:
            sub_t = set()
        outs.append((jx, sub_t))
    return outs


def _walk_wire(jaxpr, tainted, findings, where, path, line, seen):
    """Taint pass for the wire-rescale proof: a convert FROM a quantized
    dtype seeds taint; a `mul` clears it (the in-tile rescale); linear
    pass-through prims propagate it; anything else consuming a tainted
    value — an add into an accumulator, an exp2, a reduction — means a
    quantized payload reached accumulation without its scale."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            qin = {str(v.aval.dtype) for v in eqn.invars
                   if hasattr(v.aval, "dtype")} & set(_QUANT)
            if qin:
                findings.append(Finding(
                    rule="fused-ring-fused", file=path, line=line,
                    message=f"{where}: dot_general consumes a raw "
                            f"{'/'.join(sorted(qin))} operand — quantized "
                            "payloads must convert to f32 (and rescale) "
                            "around the MXU, never feed it directly"))
        tin = _tainted_in(eqn, tainted)
        if name == "convert_element_type":
            src = str(getattr(eqn.invars[0].aval, "dtype", ""))
            dst = str(eqn.outvars[0].aval.dtype)
            if src in _QUANT and dst.startswith(("float", "bfloat")):
                tainted.add(eqn.outvars[0])
                continue
            if tin:
                tainted.add(eqn.outvars[0])
            continue
        if name == "mul":
            continue  # the rescale: taint (if any) is discharged here
        subs = _map_sub_taint(eqn, tainted)
        if subs:
            for jx, sub_t in subs:
                key = id(jx)
                if key in seen and not sub_t:
                    continue
                seen.add(key)
                sub_out = _walk_wire(jx, sub_t, findings, where, path, line,
                                     seen)
                if hasattr(jx, "outvars") and len(jx.outvars) == \
                        len(eqn.outvars):
                    for ov, sov in zip(eqn.outvars, jx.outvars):
                        if not hasattr(sov, "val") and sov in sub_out:
                            tainted.add(ov)
            continue
        if not tin:
            continue
        if name in _WIRE_PASS:
            for ov in eqn.outvars:
                tainted.add(ov)
            continue
        findings.append(Finding(
            rule="fused-ring-fused", file=path, line=line,
            message=f"{where}: dequantized wire payload reaches `{name}` "
                    "without an in-tile rescale — every quantized send "
                    "needs a matching scale multiply before accumulation"))
    return tainted


def check_wire_trace(closed_jaxpr, *, where: str, anchor) -> List[Finding]:
    """Scale-handling proof over one traced fused program (fused-ring-fused
    family): every int8/fp8 -> float conversion must meet a `mul` (its
    per-block scale) before the value is accumulated or leaves the trace,
    and no dot_general may consume a quantized dtype directly.  Vacuous on
    dense traces (no quantized converts), so it runs unconditionally."""
    findings: List[Finding] = []
    path, line = anchor
    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") \
        else closed_jaxpr
    out_taint = _walk_wire(jaxpr, set(), findings, where, path, line, set())
    escaped = [v for v in jaxpr.outvars
               if not hasattr(v, "val") and v in out_taint]
    if escaped:
        findings.append(Finding(
            rule="fused-ring-fused", file=path, line=line,
            message=f"{where}: {len(escaped)} output(s) carry a dequantized "
                    "payload that never met its scale multiply"))
    return findings


def check_all() -> List[Finding]:
    import jax
    import jax.numpy as jnp

    from ..ops import tile
    from ..ops.masks import round_spec

    findings: List[Finding] = []
    b, n, s, d = 1, 2, 128, 64
    S = jax.ShapeDtypeStruct
    q4 = S((b, n, s, d), jnp.bfloat16)
    f3 = S((b, n, s), jnp.float32)
    f4 = S((b, n, s, d), jnp.float32)
    spec = round_spec(jnp.int32(0), jnp.int32(0), s, s, True, "contig")
    scale = d ** -0.5

    # ---- jnp tile oracle ----
    jx = jax.make_jaxpr(
        lambda q, k, v, m, lse, acc: tile.tile_fwd(
            q, k, v, m, lse, acc, scale, spec))(q4, q4, q4, f3, f3, f4)
    findings += check_trace(jx, where="tile_fwd",
                            anchor=_anchor(tile.tile_fwd))
    jx = jax.make_jaxpr(
        lambda do, q, k, v, delta, lse: tile.tile_bwd(
            do, q, k, v, delta, lse, scale, spec))(q4, q4, q4, q4, f3, f3)
    findings += check_trace(jx, where="tile_bwd",
                            anchor=_anchor(tile.tile_bwd))

    # ---- pallas flash kernels (inner jaxpr of the pallas_call eqn) ----
    try:
        from ..ops import pallas_flash
    except ImportError:
        return findings  # no pallas on this backend: the tile rules stand
    jx = jax.make_jaxpr(
        lambda q, k, v: pallas_flash.flash_fwd(
            q, k, v, None, None, None, scale, spec,
            block_q=64, block_kv=64))(q4, q4, q4)
    findings += check_trace(jx, where="flash_fwd kernel",
                            anchor=_anchor(pallas_flash.flash_fwd))
    jx = jax.make_jaxpr(
        lambda do, q, k, v, delta, lse: pallas_flash.flash_bwd(
            do, q, k, v, delta, lse, scale, spec,
            block_q=64, block_kv=64))(q4, q4, q4, q4, f3, f3)
    findings += check_trace(jx, where="flash_bwd kernel",
                            anchor=_anchor(pallas_flash.flash_bwd))
    return findings
