"""ragged-serve-safe: the serving kernel's static contract (burstlint).

The one-launch ragged kernel (ops/ragged_paged.py) is the serving hot
path: every engine tick is one launch, and the engine jit-compiles it
with TRACED per-slot token counts (q_lens) so admission, retirement, and
chunking never retrace.  This rule proves, from the traced jaxpr alone:

  jit-safety     the kernel wrapper traces abstractly at both engine
                 launch widths (decode qt=1, prefill chunk) with every
                 runtime input a tracer — any host concretization of
                 q_lens/kv_lens/page_table (an `int()` on a tracer, a
                 shape depending on a value) fails the trace and is a
                 finding, not a serving-time crash.
  callback-free  zero host-callback primitives inside the launch (the
                 obs-jit-safe contract, extended to serving: a callback
                 here is a device<->host round trip per engine tick).
  remote-DMA=0   a census of cross-chip DMA starts in the kernel body
                 must be ZERO.  This kernel serves the single-host pool;
                 cross-device traffic belongs to the ring subsystem
                 (parallel/fused_ring.py) and the sequence-parallel
                 decode path (models/dist_decode.py) — a remote
                 `dma_start` appearing in THIS kernel means pool state
                 leaked into a collective.
  fp32-accum     every low-precision dot in the launch accumulates in
                 float32 (numerics family, same walker) — the online
                 softmax keeps the FlashAttention numerics contract in
                 serving too.

All checks are host-side jaxpr walks over `jax.make_jaxpr` traces — no
TPU, no execution — so they run in the tier-1 burstlint gate.
"""

import inspect
from typing import List

from .core import Finding, rule

rule("ragged-serve-safe", "jaxpr",
     "ragged serving kernel traces under jit with traced q_lens, carries "
     "zero host callbacks and zero remote DMA starts")(None)


def _anchor(fn):
    try:
        return inspect.getsourcefile(fn), inspect.getsourcelines(fn)[1]
    except (OSError, TypeError):
        return "<trace>", 0


def check_trace(closed_jaxpr, *, where: str, anchor) -> List[Finding]:
    """The three jaxpr-walk halves over one traced ragged launch."""
    from . import numerics, obscheck
    from .ringcheck import _remote_dma_starts

    findings = obscheck.check_trace(closed_jaxpr, where=where, anchor=anchor,
                                    rule_name="ragged-serve-safe")
    remote = _remote_dma_starts(closed_jaxpr)
    if remote:
        path, line = anchor
        findings.append(Finding(
            rule="ragged-serve-safe", file=path, line=line,
            message=f"{where}: {len(remote)} remote DMA start(s) in the "
                    "single-host serving kernel — cross-chip traffic "
                    "belongs to the ring/dist_decode paths, never this "
                    "launch (census must be zero)"))
    findings += numerics.check_trace(closed_jaxpr, where=where, anchor=anchor)
    return findings


def check_all() -> List[Finding]:
    """Trace the ragged launch at the engine's widths (decode 1, chunk 8;
    fp32 pool and int8+bf16 GQA pool) and walk every contract."""
    import jax
    import jax.numpy as jnp

    from ..ops import ragged_paged

    anchor = _anchor(ragged_paged.ragged_paged_attention)
    findings: List[Finding] = []
    S = jax.ShapeDtypeStruct
    slots, width, page, d = 4, 8, 128, 64

    cases = [
        # (label, n_q, n_kv, qt, dtype, quantized)
        ("decode fp32", 4, 4, 1, jnp.float32, False),
        ("chunk fp32", 4, 4, 8, jnp.float32, False),
        ("chunk bf16 GQA int8", 8, 2, 8, jnp.bfloat16, True),
    ]
    for label, n_q, n_kv, qt, dt, quant in cases:
        q = S((slots, n_q, qt, d), dt)
        kp = S((16, n_kv, page, d), jnp.int8 if quant else dt)
        table = S((slots, width), jnp.int32)
        lens = S((slots,), jnp.int32)
        sc = S((16, n_kv, page), jnp.float32) if quant else None

        def launch(q, kp, vp, table, q_lens, kv_lens, ks=None, vs=None):
            return ragged_paged.ragged_paged_attention(
                q, kp, vp, table, q_lens, kv_lens,
                k_scales=ks, v_scales=vs, interpret=True)

        try:
            if quant:
                jx = jax.make_jaxpr(launch)(q, kp, kp, table, lens, lens,
                                            sc, sc)
            else:
                jx = jax.make_jaxpr(launch)(q, kp, kp, table, lens, lens)
        except Exception as e:  # noqa: BLE001 — the failure IS the finding
            path, line = anchor
            findings.append(Finding(
                rule="ragged-serve-safe", file=path, line=line,
                message=f"ragged launch ({label}): abstract trace with "
                        f"traced q_lens/kv_lens failed — the kernel is not "
                        f"jit-safe for the serving engine "
                        f"({type(e).__name__}: {e})"))
            continue
        findings += check_trace(jx, where=f"ragged launch ({label})",
                                anchor=anchor)
    return findings
