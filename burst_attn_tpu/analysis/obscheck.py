"""obs-jit-safe, jaxpr half (burstlint family 1).

The AST half (astlint._check_obs_jit_safe) proves no obs BINDING is called
from a statically jit-marked function; this half closes the dynamic gap —
instrumentation smuggled into a compiled program through any indirection
(a helper module, `jax.debug.callback(REGISTRY.inc)`, a pure_callback
wrapper) shows up in the traced jaxpr as a host-callback primitive no
matter how it was spelled.  The hot attention programs are traced
abstractly (same harness as ringcheck) and must contain ZERO callback
primitives: the ring's value is overlap, and a host callback inside the
ring is a synchronous device<->host round trip per step, exactly the
regression this subsystem exists to catch.

Flagged primitives: anything whose name contains "callback"
(pure_callback / io_callback / debug_callback across jax versions) plus
the legacy host_callback "outside_call".
"""

import inspect
from typing import List

from .core import Finding
from .jaxpr_tools import iter_eqns

_LEGACY_CALLBACK_PRIMS = ("outside_call",)


def _is_callback_prim(name: str) -> bool:
    return "callback" in name or name in _LEGACY_CALLBACK_PRIMS


def _anchor(fn):
    try:
        return inspect.getsourcefile(fn), inspect.getsourcelines(fn)[1]
    except (OSError, TypeError):
        return "<trace>", 0


def check_trace(closed_jaxpr, *, where: str, anchor) -> List[Finding]:
    """Flag every host-callback primitive in one traced program."""
    findings: List[Finding] = []
    path, line = anchor
    for eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if _is_callback_prim(name):
            findings.append(Finding(
                rule="obs-jit-safe", file=path, line=line,
                message=f"{where}: host-callback primitive `{name}` inside "
                        "the traced program — a synchronous device<->host "
                        "round trip per executed step; obs instrumentation "
                        "must stay at the host dispatch boundary"))
    return findings


def check_all() -> List[Finding]:
    """Trace the burst forward AND backward shard programs on a simulated
    flat ring and prove both are callback-free.  (The scan/fused dispatch,
    tile kernels, and case-split branches are all inside these traces;
    ringcheck's topology matrix covers scheduling, this covers purity.)"""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..parallel import burst
    from ..utils.compat import shard_map

    findings: List[Finding] = []
    devs = jax.devices()
    world = 4
    if len(devs) < world:
        raise RuntimeError(
            f"analysis needs {world} simulated devices "
            f"(XLA_FLAGS=--xla_force_host_platform_device_count=8); "
            f"have {len(devs)}")
    mesh = Mesh(np.asarray(devs[:world]), ("sp",))
    cfg = burst.BurstConfig(causal=True, layout="zigzag", intra_axis="sp",
                            backend="jnp")
    b, n, d, s_local = 1, 2, 8, 16
    S = jax.ShapeDtypeStruct
    q = S((b, n, s_local * world, d), jnp.bfloat16)
    lse = S((b, n, s_local * world), jnp.float32)
    spec4 = P(None, None, "sp", None)
    spec3 = P(None, None, "sp")

    fwd = shard_map(lambda q, k, v: burst._fwd_impl(q, k, v, cfg),
                    mesh=mesh, in_specs=(spec4,) * 3,
                    out_specs=(spec4, spec3), check_vma=False)
    findings += check_trace(jax.make_jaxpr(fwd)(q, q, q),
                            where="burst fwd", anchor=_anchor(burst._fwd_impl))
    bwd = shard_map(
        lambda q, k, v, o, lse, do: burst._bwd_impl(cfg, q, k, v, o, lse, do),
        mesh=mesh, in_specs=(spec4,) * 4 + (spec3, spec4),
        out_specs=(spec4,) * 3, check_vma=False)
    findings += check_trace(jax.make_jaxpr(bwd)(q, q, q, q, lse, q),
                            where="burst bwd", anchor=_anchor(burst._bwd_impl))
    return findings
