"""obs-jit-safe + devstats-pure, jaxpr halves (burstlint family 1).

The AST half (astlint._check_obs_jit_safe) proves no obs BINDING is called
from a statically jit-marked function; this half closes the dynamic gap —
instrumentation smuggled into a compiled program through any indirection
(a helper module, `jax.debug.callback(REGISTRY.inc)`, a pure_callback
wrapper) shows up in the traced jaxpr as a host-callback primitive no
matter how it was spelled.  The hot attention programs are traced
abstractly (same harness as ringcheck) and must contain ZERO callback
primitives: the ring's value is overlap, and a host callback inside the
ring is a synchronous device<->host round trip per step, exactly the
regression this subsystem exists to catch.

`devstats-pure` extends the same proof to the device-side telemetry path
(obs/devstats.py — the one obs module the AST rule deliberately EXEMPTS
from the jit ban):

  1. the stats-enabled ring forward AND backward
     (`burst_attn_shard(..., collect_stats=True)` through
     `jax.value_and_grad`) trace to jaxprs with zero host-callback
     primitives — collecting telemetry in-graph must never smuggle a
     host hop into the ring;
  2. the stats-OFF trace is BIT-IDENTICAL (string-equal jaxpr) to the
     plain pre-devstats entry point (`_burst_attn_shard_plain`) — turning
     the feature off must cost nothing, byte for byte.

Flagged primitives: anything whose name contains "callback"
(pure_callback / io_callback / debug_callback across jax versions) plus
the legacy host_callback "outside_call".
"""

import inspect
from typing import List

from .core import Finding, rule
from .jaxpr_tools import iter_eqns

rule("devstats-pure", "jaxpr",
     "stats-enabled ring fwd/bwd carry zero host-callback primitives; "
     "stats-off trace bit-identical to the plain ring")(None)

rule("ckpt-jit-safe", "jaxpr",
     "traced serve-step programs (ragged_model_step / paged_decode_step) "
     "carry zero host-callback primitives — checkpoint/journal writes "
     "stay at the host dispatch boundary")(None)

rule("pipe-fused-pure", "jaxpr",
     "the fused multi-step decode scan (pipelined engine) traces with zero "
     "host-callback primitives and zero remote-DMA/collective primitives — "
     "K device steps per host dispatch, no hidden host or wire hops")(None)

rule("pipe-tick-identity", "jaxpr",
     "the K=1 pipelined tick traces string-identical to the synchronous "
     "engine tick (model step + sample) — pipelining moves WHEN readback "
     "happens, never WHAT is computed")(None)

_LEGACY_CALLBACK_PRIMS = ("outside_call",)


def _is_callback_prim(name: str) -> bool:
    return "callback" in name or name in _LEGACY_CALLBACK_PRIMS


def _anchor(fn):
    try:
        return inspect.getsourcefile(fn), inspect.getsourcelines(fn)[1]
    except (OSError, TypeError):
        return "<trace>", 0


def check_trace(closed_jaxpr, *, where: str, anchor,
                rule_name: str = "obs-jit-safe") -> List[Finding]:
    """Flag every host-callback primitive in one traced program."""
    findings: List[Finding] = []
    path, line = anchor
    for eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if _is_callback_prim(name):
            findings.append(Finding(
                rule=rule_name, file=path, line=line,
                message=f"{where}: host-callback primitive `{name}` inside "
                        "the traced program — a synchronous device<->host "
                        "round trip per executed step; obs instrumentation "
                        "must stay at the host dispatch boundary"))
    return findings


# Substrings that mark a cross-device primitive: collectives (ppermute /
# psum / all_gather / all_to_all / pbroadcast), plus anything spelled as
# an explicit remote copy or DMA across jax versions.  A single-host
# decode scan must bind none of them — the fused launch's whole point is
# K steps with zero host AND zero wire traffic per dispatch.
_REMOTE_PRIM_MARKERS = ("ppermute", "psum", "pmax", "pmin", "pbroadcast",
                        "all_gather", "all_to_all", "collective",
                        "remote", "dma")


def check_remote_free(closed_jaxpr, *, where: str, anchor,
                      rule_name: str = "pipe-fused-pure") -> List[Finding]:
    """Flag every remote-DMA/collective primitive in one traced program."""
    findings: List[Finding] = []
    path, line = anchor
    for eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if any(m in name for m in _REMOTE_PRIM_MARKERS):
            findings.append(Finding(
                rule=rule_name, file=path, line=line,
                message=f"{where}: remote/collective primitive `{name}` "
                        "inside the traced decode program — the fused "
                        "launch must be a purely local device program "
                        "(no wire traffic hidden inside the scan)"))
    return findings


_ADDR_RE = None


def _canon_jaxpr(closed_jaxpr) -> str:
    """Jaxpr pretty-print with run-dependent noise removed: custom_vjp
    params embed live function objects whose reprs carry heap addresses
    (`0x7f...`), which differ between two traces of the SAME program."""
    global _ADDR_RE
    if _ADDR_RE is None:
        import re

        _ADDR_RE = re.compile(r"0x[0-9a-f]+")
    return _ADDR_RE.sub("0x", str(closed_jaxpr))


def check_off_identity(jaxpr_off, jaxpr_plain, *, anchor) -> List[Finding]:
    """devstats-pure half 2: the collect_stats=False trace must be
    STRING-IDENTICAL (modulo heap addresses) to the plain (pre-devstats)
    ring program — the only acceptable cost of the telemetry feature when
    it is off is zero."""
    path, line = anchor
    if _canon_jaxpr(jaxpr_off) == _canon_jaxpr(jaxpr_plain):
        return []
    return [Finding(
        rule="devstats-pure", file=path, line=line,
        message="collect_stats=False ring trace diverged from the plain "
                "ring program — devstats machinery is leaking into the "
                "stats-off path (it must be bit-identical to a build "
                "without devstats)")]


def check_all() -> List[Finding]:
    """Trace the burst forward AND backward shard programs on a simulated
    flat ring and prove both are callback-free.  (The scan/fused dispatch,
    tile kernels, and case-split branches are all inside these traces;
    ringcheck's topology matrix covers scheduling, this covers purity.)"""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..parallel import burst
    from ..utils.compat import shard_map

    findings: List[Finding] = []
    devs = jax.devices()
    world = 4
    if len(devs) < world:
        raise RuntimeError(
            f"analysis needs {world} simulated devices "
            f"(XLA_FLAGS=--xla_force_host_platform_device_count=8); "
            f"have {len(devs)}")
    mesh = Mesh(np.asarray(devs[:world]), ("sp",))
    cfg = burst.BurstConfig(causal=True, layout="zigzag", intra_axis="sp",
                            backend="jnp")
    b, n, d, s_local = 1, 2, 8, 16
    S = jax.ShapeDtypeStruct
    q = S((b, n, s_local * world, d), jnp.bfloat16)
    lse = S((b, n, s_local * world), jnp.float32)
    spec4 = P(None, None, "sp", None)
    spec3 = P(None, None, "sp")

    fwd = shard_map(lambda q, k, v: burst._fwd_impl(q, k, v, cfg),
                    mesh=mesh, in_specs=(spec4,) * 3,
                    out_specs=(spec4, spec3), check_vma=False)
    findings += check_trace(jax.make_jaxpr(fwd)(q, q, q),
                            where="burst fwd", anchor=_anchor(burst._fwd_impl))
    bwd = shard_map(
        lambda q, k, v, o, lse, do: burst._bwd_impl(cfg, q, k, v, o, lse, do),
        mesh=mesh, in_specs=(spec4,) * 4 + (spec3, spec4),
        out_specs=(spec4,) * 3, check_vma=False)
    findings += check_trace(jax.make_jaxpr(bwd)(q, q, q, q, lse, q),
                            where="burst bwd", anchor=_anchor(burst._bwd_impl))

    # ---- devstats-pure: the telemetry path keeps both promises ----
    anchor_dev = _anchor(burst.burst_attn_shard)

    def stats_fwdbwd(q, k, v):
        # value_and_grad THROUGH the stats entry: fwd + bwd + every stats
        # equation land in one jaxpr; summing the stats leaves into the
        # output keeps them from being dead-code-eliminated
        def loss(q, k, v):
            o, st = burst.burst_attn_shard(q, k, v, cfg, collect_stats=True)
            return jnp.sum(o.astype(jnp.float32)), st

        (l, st), grads = jax.value_and_grad(loss, (0, 1, 2),
                                            has_aux=True)(q, k, v)
        st_sum = sum(jnp.sum(x.astype(jnp.float32))
                     for x in jax.tree.leaves(st))
        return l + st_sum, grads

    stats_prog = shard_map(stats_fwdbwd, mesh=mesh, in_specs=(spec4,) * 3,
                           out_specs=(P(), (spec4,) * 3), check_vma=False)
    findings += check_trace(jax.make_jaxpr(stats_prog)(q, q, q),
                            where="burst fwd+bwd (collect_stats=True)",
                            anchor=anchor_dev, rule_name="devstats-pure")

    off = shard_map(
        lambda q, k, v: burst.burst_attn_shard(q, k, v, cfg,
                                               collect_stats=False),
        mesh=mesh, in_specs=(spec4,) * 3, out_specs=spec4, check_vma=False)
    plain = shard_map(
        lambda q, k, v: burst._burst_attn_shard_plain(q, k, v, cfg),
        mesh=mesh, in_specs=(spec4,) * 3, out_specs=spec4, check_vma=False)
    findings += check_off_identity(jax.make_jaxpr(off)(q, q, q),
                                   jax.make_jaxpr(plain)(q, q, q),
                                   anchor=anchor_dev)

    # ---- ckpt-jit-safe: the serve-step programs the checkpoint layer
    # wraps.  Journal appends / snapshot saves live in the engines' host
    # loops; this proves none of them leaked INTO the traced step — a
    # journal hook spelled as `jax.debug.callback(journal.tokens, ...)`
    # would surface here as a callback primitive regardless of module.
    from ..models.paged_decode import init_paged_state, paged_decode_step
    from ..models.transformer import ModelConfig, init_params
    from ..serving import model as serving_model

    cfg_s = ModelConfig(vocab=97, d_model=16, n_layers=1, n_heads=2,
                        n_kv_heads=1, d_head=8, d_ff=32, attn_backend="jnp",
                        remat=False, dtype=jnp.float32, batch_axis=None,
                        head_axis=None)
    params = init_params(jax.random.PRNGKey(0), cfg_s)
    state, _pool = init_paged_state(cfg_s, slots=2, n_pages=4, page=128,
                                    max_pages_per_seq=2)
    toks2 = jnp.zeros((2, 8), jnp.int32)
    qlens = jnp.ones((2,), jnp.int32)
    for attn in ("dense", "ragged"):
        findings += check_trace(
            jax.make_jaxpr(
                lambda p, t, ql, st: serving_model.ragged_model_step(
                    p, t, ql, st, cfg_s, attn=attn)
            )(params, toks2, qlens, state),
            where=f"ragged_model_step (attn={attn})",
            anchor=_anchor(serving_model.ragged_model_step),
            rule_name="ckpt-jit-safe")
    findings += check_trace(
        jax.make_jaxpr(
            lambda p, t, st: paged_decode_step(p, t, st, cfg_s)
        )(params, jnp.zeros((2,), jnp.int32), state),
        where="paged_decode_step", anchor=_anchor(paged_decode_step),
        rule_name="ckpt-jit-safe")

    # ---- pipe-fused-pure: the pipelined engine's fused multi-step scan.
    # K decode steps execute per host dispatch; a callback primitive in
    # the scan body would fire K times per launch, and a collective/DMA
    # would put wire traffic inside what must be a purely local program.
    rng = jax.random.PRNGKey(0)
    first = jnp.zeros((2,), jnp.int32)
    anchor_ms = _anchor(serving_model.multi_step_decode)
    for attn in ("dense", "ragged"):
        jx = jax.make_jaxpr(
            lambda p, t, ql, st, r, attn=attn: serving_model.multi_step_decode(
                p, t, ql, st, r, cfg_s, k=4, attn=attn)
        )(params, first, qlens, state, rng)
        where = f"multi_step_decode (k=4, attn={attn})"
        findings += check_trace(jx, where=where, anchor=anchor_ms,
                                rule_name="pipe-fused-pure")
        findings += check_remote_free(jx, where=where, anchor=anchor_ms)

    # ---- pipe-tick-identity: the K=1 pipelined launch is the SAME
    # program as the synchronous engine's tick (model step + greedy
    # sample), proven at the jaxpr-string level — the token-exactness
    # argument for the pipelined engine rests on this identity.
    def _sync_tick(p, t, ql, st, key):
        logits, st2 = serving_model.ragged_model_step(p, t, ql, st, cfg_s,
                                                      attn="ragged")
        choice = serving_model.sample_logits(logits, key, temperature=0.0,
                                             top_k=None, top_p=None,
                                             nan_sentinel=True)
        return choice, st2

    toks1 = jnp.zeros((2, 1), jnp.int32)
    jx_pipe = jax.make_jaxpr(
        lambda p, t, ql, st, key: serving_model.pipelined_tick(
            p, t, ql, st, key, cfg_s, attn="ragged")
    )(params, toks1, qlens, state, rng)
    jx_sync = jax.make_jaxpr(_sync_tick)(params, toks1, qlens, state, rng)
    if _canon_jaxpr(jx_pipe) != _canon_jaxpr(jx_sync):
        path, line = _anchor(serving_model.pipelined_tick)
        findings.append(Finding(
            rule="pipe-tick-identity", file=path, line=line,
            message="K=1 pipelined tick trace diverged from the synchronous "
                    "engine tick — the pipelined engine is no longer "
                    "launching the same compiled program, so its "
                    "token-exactness guarantee is void"))
    return findings
