"""pagepool-cow-safe: the copy-on-write write barrier, proven live
(burstlint).

Prefix caching (ISSUE 13) makes pool pages SHARED: several slots' table
rows — and the prefix-cache index — can reference one physical page.
The single safety contract is that no jitted launch ever scatters K/V
into a page the host-side allocator holds at refcount > 1: every launch
must run behind the CoW barrier (serving/engine._cow_barrier ->
serving/model.cow_pages), so the scatter indices the device sees always
come from the POST-CoW page table.  A violation is silent cross-request
corruption — the other sequence sharing the page reads poisoned K/V and
decodes garbage with no error anywhere.

A jaxpr walk cannot see this (the scatter is correct code; what matters
is the HOST allocator state the indices were derived from), so this rule
drives a real tiny prefix-cache engine through the sharing-heavy
schedule — concurrent partial-prefix hits, plus the exact-template
FULL-prompt hit whose last-token re-absorption targets the final shared
page (the one organic refcount>1 write) — and checks, immediately before
EVERY launch, that each table column the launch's token counts will
scatter into (lengths//page .. (lengths+q_len-1)//page) is held at
refcount <= 1.  Afterwards it proves the pool ALGEBRA drains: with every
request retired and the cache fully evicted, the free list must hold
every usable page and every refcount must be zero — a refcount leak in
release (pages held forever) or a double-free (free-list duplicates)
both surface here.

Mutation coverage (tests/test_analysis.py): no-op'ing cow_pages fires
the scatter check; a release that forgets to free fires the drain check.
"""

from typing import List

import numpy as np

from .core import Finding, rule

rule("pagepool-cow-safe", "jaxpr",
     "no jitted launch scatters K/V into a page held at refcount>1 "
     "(post-CoW table only), and the shared pool drains to empty after "
     "retire + full eviction")(None)

_RULE = "pagepool-cow-safe"


def _anchor():
    import inspect

    from ..serving import engine as eng_mod

    try:
        fn = eng_mod.RaggedServeEngine._cow_barrier
        return inspect.getsourcefile(fn), inspect.getsourcelines(fn)[1]
    except (OSError, TypeError):
        return "<trace>", 0


def check_all() -> List[Finding]:
    """Drive the shared-prefix schedule on a tiny engine; every launch is
    precondition-checked against the live allocator."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import ModelConfig, init_params
    from ..serving import engine as eng_mod

    path, line = _anchor()
    findings: List[Finding] = []
    cfg = ModelConfig(vocab=61, d_model=32, n_layers=1, n_heads=2,
                      n_kv_heads=1, d_head=16, d_ff=64, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    page = 128
    rng = np.random.default_rng(0x90001)
    tmpl = rng.integers(1, 61, size=page)  # exactly one cacheable page
    prompts = [np.concatenate([tmpl, rng.integers(1, 61, size=7)]),
               np.concatenate([tmpl, rng.integers(1, 61, size=11)]),
               tmpl.copy()]  # FULL-prompt hit: the CoW boundary write

    violations: List[str] = []
    holder = {}
    real_step = eng_mod.ragged_model_step

    def checked_step(params, toks, q_lens, state, cfg, **kw):
        # the precondition: at launch time, every column this launch's
        # token counts will scatter into must be private (refcount <= 1)
        pool = holder["pool"]
        ql = np.asarray(q_lens)
        lens = np.asarray(state.lengths)
        table = np.asarray(state.page_table)
        pg = state.k_pages[0].shape[2]
        for slot in range(len(ql)):
            n = int(ql[slot])
            if n <= 0:
                continue
            first = int(lens[slot]) // pg
            last = (int(lens[slot]) + n - 1) // pg
            for col in range(first, min(last, table.shape[1] - 1) + 1):
                pid = int(table[slot, col])
                if pid and pool.refcount(pid) > 1:
                    violations.append(
                        f"slot {slot} col {col}: launch scatters "
                        f"{n} token(s) into page {pid} at refcount "
                        f"{pool.refcount(pid)}")
        return real_step(params, toks, q_lens, state, cfg, **kw)

    eng_mod.ragged_model_step = checked_step
    try:
        engine = eng_mod.RaggedServeEngine(
            params, cfg, slots=2, n_pages=12, page=page,
            max_pages_per_seq=4, prefix_cache=True, chunk=page)
        holder["pool"] = engine.pool
        # wave 1 registers the template; wave 2 admits concurrent hits
        # including the full-prompt hit whose re-absorbed last token is
        # the one organic write into a shared page
        for wave in range(2):
            for p in prompts:
                engine.submit(p, 3)
            engine.run()
    except Exception as e:  # noqa: BLE001 — the failure IS the finding
        findings.append(Finding(
            rule=_RULE, file=path, line=line,
            message="shared-prefix engine schedule crashed before the "
                    f"write-barrier check completed ({type(e).__name__}: "
                    f"{e})"))
        return findings
    finally:
        eng_mod.ragged_model_step = real_step

    if violations:
        findings.append(Finding(
            rule=_RULE, file=path, line=line,
            message=f"{len(violations)} jitted launch(es) scattered K/V "
                    "into a shared page (refcount > 1) — the CoW barrier "
                    "did not privatize the scatter targets: "
                    + "; ".join(violations[:3])))

    # pool-algebra drain: everything retired, cache fully evicted — the
    # free list must be whole and every refcount zero
    engine.cache.evict(engine.pool.n_pages)
    pool = engine.pool
    usable = pool.n_pages - 1
    free = [int(p) for p in pool._free]
    if len(free) != len(set(free)):
        findings.append(Finding(
            rule=_RULE, file=path, line=line,
            message="double-free: the pool free list holds duplicate "
                    f"page ids after drain ({free})"))
    if pool.available != usable or any(r != 0 for r in pool._refs[1:]):
        held = [i for i in range(1, pool.n_pages) if pool._refs[i] > 0]
        findings.append(Finding(
            rule=_RULE, file=path, line=line,
            message="refcount leak: after retiring every request and "
                    f"evicting the whole cache, {usable - pool.available} "
                    f"page(s) never returned to the free list "
                    f"(still-referenced pages: {held})"))
    return findings
