"""pagepool-cow-safe: the copy-on-write write barrier, proven live
(burstlint).

Prefix caching (ISSUE 13) makes pool pages SHARED: several slots' table
rows — and the prefix-cache index — can reference one physical page.
The single safety contract is that no jitted launch ever scatters K/V
into a page the host-side allocator holds at refcount > 1: every launch
must run behind the CoW barrier (serving/engine._cow_barrier ->
serving/model.cow_pages), so the scatter indices the device sees always
come from the POST-CoW page table.  A violation is silent cross-request
corruption — the other sequence sharing the page reads poisoned K/V and
decodes garbage with no error anywhere.

A jaxpr walk cannot see this (the scatter is correct code; what matters
is the HOST allocator state the indices were derived from), so this rule
drives a real tiny prefix-cache engine through the sharing-heavy
schedule — concurrent partial-prefix hits, plus the exact-template
FULL-prompt hit whose last-token re-absorption targets the final shared
page (the one organic refcount>1 write) — and checks, immediately before
EVERY launch, that each table column the launch's token counts will
scatter into (lengths//page .. (lengths+q_len-1)//page) is held at
refcount <= 1.  Afterwards it proves the pool ALGEBRA drains: with every
request retired and the cache fully evicted, the free list must hold
every usable page and every refcount must be zero — a refcount leak in
release (pages held forever) or a double-free (free-list duplicates)
both surface here.

`pool-quant-safe` proves the natively quantized pool (ISSUE 17) keeps
its (page, scale) pairs atomic under the same sharing-heavy schedule,
driven on an fp8-storage engine.  A 1 B/elem pool is only meaningful
WITH its per-token fp32 scale column — a page that lands without its
scale (or a CoW copy that privatizes the page column but not the scale
column) dequantizes to garbage up to the quant range (448x off for
fp8-e4m3) with no error anywhere.  Three checks:

  - every launch on the quantized pool must carry scale banks whose
    shape mirrors the page banks (pair residency);
  - every `_copy_pages_jit` CoW copy must carry all FOUR banks — K/V
    pages and K/V scales — byte-exactly from source to private page
    (the copy seam is wrapped; zero copies observed is itself a
    finding, so the check cannot degenerate silently);
  - after every launch, each K/V column the launch wrote must
    dequantize (stored q * stored scale) back to the independently
    recomputed true projection rows within fp8 quantization tolerance
    — a scatter that lands the page bytes without updating the scale
    column leaves a self-consistent-LOOKING pair that is numerically
    wrong, and only ground truth can see it.

Mutation coverage (tests/test_analysis.py): no-op'ing cow_pages fires
the scatter check; a release that forgets to free fires the drain
check; a _copy_pages_jit that copies pages but not scales, and a step
that reverts freshly scattered scale columns, each fire
pool-quant-safe.
"""

from typing import List

import numpy as np

from .core import Finding, rule

rule("pagepool-cow-safe", "jaxpr",
     "no jitted launch scatters K/V into a page held at refcount>1 "
     "(post-CoW table only), and the shared pool drains to empty after "
     "retire + full eviction")(None)
rule("pool-quant-safe", "jaxpr",
     "on a natively quantized (fp8) pool, every scatter and every CoW "
     "copy lands the page and its scale column as one unit — written "
     "columns dequantize to the true projections, privatized pages "
     "carry their scales")(None)

_RULE = "pagepool-cow-safe"
_QRULE = "pool-quant-safe"


def _anchor():
    import inspect

    from ..serving import engine as eng_mod

    try:
        fn = eng_mod.RaggedServeEngine._cow_barrier
        return inspect.getsourcefile(fn), inspect.getsourcelines(fn)[1]
    except (OSError, TypeError):
        return "<trace>", 0


def _quant_anchor():
    import inspect

    from ..serving import model as serve_model

    try:
        fn = serve_model.cow_pages
        return inspect.getsourcefile(fn), inspect.getsourcelines(fn)[1]
    except (OSError, TypeError):
        return "<trace>", 0


def _check_cow() -> List[Finding]:
    """Drive the shared-prefix schedule on a tiny engine; every launch is
    precondition-checked against the live allocator."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import ModelConfig, init_params
    from ..serving import engine as eng_mod

    path, line = _anchor()
    findings: List[Finding] = []
    cfg = ModelConfig(vocab=61, d_model=32, n_layers=1, n_heads=2,
                      n_kv_heads=1, d_head=16, d_ff=64, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    page = 128
    rng = np.random.default_rng(0x90001)
    tmpl = rng.integers(1, 61, size=page)  # exactly one cacheable page
    prompts = [np.concatenate([tmpl, rng.integers(1, 61, size=7)]),
               np.concatenate([tmpl, rng.integers(1, 61, size=11)]),
               tmpl.copy()]  # FULL-prompt hit: the CoW boundary write

    violations: List[str] = []
    holder = {}
    real_step = eng_mod.ragged_model_step

    def checked_step(params, toks, q_lens, state, cfg, **kw):
        # the precondition: at launch time, every column this launch's
        # token counts will scatter into must be private (refcount <= 1)
        pool = holder["pool"]
        ql = np.asarray(q_lens)
        lens = np.asarray(state.lengths)
        table = np.asarray(state.page_table)
        pg = state.k_pages[0].shape[2]
        for slot in range(len(ql)):
            n = int(ql[slot])
            if n <= 0:
                continue
            first = int(lens[slot]) // pg
            last = (int(lens[slot]) + n - 1) // pg
            for col in range(first, min(last, table.shape[1] - 1) + 1):
                pid = int(table[slot, col])
                if pid and pool.refcount(pid) > 1:
                    violations.append(
                        f"slot {slot} col {col}: launch scatters "
                        f"{n} token(s) into page {pid} at refcount "
                        f"{pool.refcount(pid)}")
        return real_step(params, toks, q_lens, state, cfg, **kw)

    eng_mod.ragged_model_step = checked_step
    try:
        engine = eng_mod.RaggedServeEngine(
            params, cfg, slots=2, n_pages=12, page=page,
            max_pages_per_seq=4, prefix_cache=True, chunk=page)
        holder["pool"] = engine.pool
        # wave 1 registers the template; wave 2 admits concurrent hits
        # including the full-prompt hit whose re-absorbed last token is
        # the one organic write into a shared page
        for wave in range(2):
            for p in prompts:
                engine.submit(p, 3)
            engine.run()
    except Exception as e:  # noqa: BLE001 — the failure IS the finding
        findings.append(Finding(
            rule=_RULE, file=path, line=line,
            message="shared-prefix engine schedule crashed before the "
                    f"write-barrier check completed ({type(e).__name__}: "
                    f"{e})"))
        return findings
    finally:
        eng_mod.ragged_model_step = real_step

    if violations:
        findings.append(Finding(
            rule=_RULE, file=path, line=line,
            message=f"{len(violations)} jitted launch(es) scattered K/V "
                    "into a shared page (refcount > 1) — the CoW barrier "
                    "did not privatize the scatter targets: "
                    + "; ".join(violations[:3])))

    # pool-algebra drain: everything retired, cache fully evicted — the
    # free list must be whole and every refcount zero
    engine.cache.evict(engine.pool.n_pages)
    pool = engine.pool
    usable = pool.n_pages - 1
    free = [int(p) for p in pool._free]
    if len(free) != len(set(free)):
        findings.append(Finding(
            rule=_RULE, file=path, line=line,
            message="double-free: the pool free list holds duplicate "
                    f"page ids after drain ({free})"))
    if pool.available != usable or any(r != 0 for r in pool._refs[1:]):
        held = [i for i in range(1, pool.n_pages) if pool._refs[i] > 0]
        findings.append(Finding(
            rule=_RULE, file=path, line=line,
            message="refcount leak: after retiring every request and "
                    f"evicting the whole cache, {usable - pool.available} "
                    f"page(s) never returned to the free list "
                    f"(still-referenced pages: {held})"))
    return findings


def _check_quant() -> List[Finding]:
    """Drive the same sharing-heavy schedule on an fp8-native pool; every
    CoW copy and every scatter is checked for (page, scale) atomicity,
    the latter against independently recomputed ground truth."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import ModelConfig, init_params
    from ..serving import engine as eng_mod
    from ..serving import model as serve_model

    path, line = _quant_anchor()
    findings: List[Finding] = []
    cfg = ModelConfig(vocab=61, d_model=32, n_layers=1, n_heads=2,
                      n_kv_heads=1, d_head=16, d_ff=64, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(1), cfg)
    page = 128
    rng = np.random.default_rng(0x90002)
    tmpl = rng.integers(1, 61, size=page)
    prompts = [np.concatenate([tmpl, rng.integers(1, 61, size=7)]),
               np.concatenate([tmpl, rng.integers(1, 61, size=11)]),
               tmpl.copy()]  # FULL-prompt hit: the CoW pair-copy event

    violations: List[str] = []
    copies = {"n": 0}
    real_step = eng_mod.ragged_model_step
    real_copy = serve_model._copy_pages_jit

    def checked_copy(state, src, dst):
        # the pair-copy contract: a privatized page column arrives with
        # its K/V bytes AND its scale column, byte-exactly (check on the
        # RETURNED state only — the input state is donated)
        ns = real_copy(state, src, dst)
        s_ix, d_ix = np.asarray(src), np.asarray(dst)
        copies["n"] += len(d_ix)
        if ns.k_scales is None or ns.v_scales is None:
            violations.append("CoW copy on the quantized pool returned a "
                              "state with no scale banks")
            return ns
        banks = (("K page", ns.k_pages), ("V page", ns.v_pages),
                 ("K scale", ns.k_scales), ("V scale", ns.v_scales))
        for name, bank in banks:
            for li, arr in enumerate(bank):
                a = np.asarray(arr)
                for s, d in zip(s_ix, d_ix):
                    if a[int(d)].tobytes() != a[int(s)].tobytes():
                        violations.append(
                            f"CoW copy {int(s)}->{int(d)} layer {li}: the "
                            f"{name} column was not carried to the private "
                            "page — the (page, scale) pair split")
        return ns

    def checked_step(params_, toks, q_lens, state, cfg_, **kw):
        # (1) pair residency: scale banks present and mirroring the page
        #     banks' [P, Nkv, page] geometry at every launch
        if state.k_scales is None or state.v_scales is None:
            violations.append(
                "a launch on the quantized pool carried no scale banks")
            return real_step(params_, toks, q_lens, state, cfg_, **kw)
        for li, (kpg, ksc) in enumerate(zip(state.k_pages, state.k_scales)):
            if tuple(ksc.shape) != tuple(kpg.shape[:3]):
                violations.append(
                    f"layer {li}: scale bank {tuple(ksc.shape)} does not "
                    f"mirror the page bank {tuple(kpg.shape[:3])}")
        # precompute scatter targets and ground-truth projections BEFORE
        # the launch (the state is donated to the jit); with n_layers=1
        # the layer-0 K/V rows are a pure function of the input tokens,
        # so the eager recomputation is exact modulo compile scheduling
        ql = np.asarray(q_lens)
        lens = np.asarray(state.lengths)
        table = np.asarray(state.page_table)
        pg = state.k_pages[0].shape[2]
        toks_np = np.asarray(toks)
        slots_n, qt = toks_np.shape
        live = ql > 0
        base = np.where(live, lens, 0)
        t_ix = np.arange(qt)[None, :]
        real = (t_ix < ql[:, None]) & live[:, None]
        pos = base[:, None] + t_ix
        safe_col = np.minimum(pos // pg, table.shape[1] - 1)
        pids = np.where(real, table[np.arange(slots_n)[:, None], safe_col], 0)
        offs = pos % pg
        x = jnp.asarray(params_["embed"]).astype(cfg_.dtype)[
            jnp.asarray(toks_np)]
        _, k, v = serve_model._qkv_proj(params_["layers"][0], x,
                                        jnp.asarray(pos), cfg_)
        k_true = np.asarray(jnp.moveaxis(k, 1, 2), np.float32)
        v_true = np.asarray(jnp.moveaxis(v, 1, 2), np.float32)

        out = real_step(params_, toks, q_lens, state, cfg_, **kw)
        ns = out[1]
        # (2) atomic scatter: every column this launch wrote must
        #     dequantize back to the true projection within quantization
        #     tolerance — stale scales leave a pair that is internally
        #     consistent but numerically wrong, so only ground truth
        #     catches the split
        kpg = np.asarray(ns.k_pages[0]).astype(np.float32)
        vpg = np.asarray(ns.v_pages[0]).astype(np.float32)
        ksc = np.asarray(ns.k_scales[0], np.float32)
        vsc = np.asarray(ns.v_scales[0], np.float32)
        for s in range(slots_n):
            for t in range(qt):
                if not real[s, t] or int(pids[s, t]) == 0:
                    continue
                pid, off = int(pids[s, t]), int(offs[s, t])
                for nm, bank, scales, true in (("K", kpg, ksc, k_true),
                                               ("V", vpg, vsc, v_true)):
                    deq = bank[pid, :, off] * scales[pid, :, off][:, None]
                    ref = true[s, t]
                    amax = np.abs(ref).max(axis=-1, keepdims=True)
                    tol = 0.07 * np.abs(ref) + 0.02 * amax + 1e-6
                    err = np.abs(deq - ref)
                    if np.any(err > tol):
                        violations.append(
                            f"slot {s} pos {int(pos[s, t])}: the stored "
                            f"{nm} column dequantizes {float(err.max()):.3g}"
                            " away from the true projection — the scatter "
                            "landed the page without its scale (pair "
                            "split)")
        return out

    eng_mod.ragged_model_step = checked_step
    serve_model._copy_pages_jit = checked_copy
    try:
        engine = eng_mod.RaggedServeEngine(
            params, cfg, slots=2, n_pages=12, page=page,
            max_pages_per_seq=4, prefix_cache=True, chunk=page,
            quantize="fp8")
        for wave in range(2):
            for p in prompts:
                engine.submit(p, 3)
            engine.run()
    except Exception as e:  # noqa: BLE001 — the failure IS the finding
        findings.append(Finding(
            rule=_QRULE, file=path, line=line,
            message="quantized-pool engine schedule crashed before the "
                    f"pair-atomicity check completed ({type(e).__name__}: "
                    f"{e})"))
        return findings
    finally:
        eng_mod.ragged_model_step = real_step
        serve_model._copy_pages_jit = real_copy

    if copies["n"] == 0:
        # the schedule MUST exercise privatization (the wave-2 full-
        # prompt hit); zero observed copies means the pair-copy check
        # proved nothing — surface that instead of passing silently
        findings.append(Finding(
            rule=_QRULE, file=path, line=line,
            message="the quantized drive observed zero CoW copies — the "
                    "(page, scale) pair-copy check did not run; the "
                    "schedule no longer exercises privatization"))
    if violations:
        findings.append(Finding(
            rule=_QRULE, file=path, line=line,
            message=f"{len(violations)} (page, scale) pair violation(s) "
                    "on the fp8-native pool: " + "; ".join(violations[:3])))

    # the quantized pool must drain by the same algebra
    engine.cache.evict(engine.pool.n_pages)
    pool = engine.pool
    usable = pool.n_pages - 1
    if pool.available != usable or any(r != 0 for r in pool._refs[1:]):
        held = [i for i in range(1, pool.n_pages) if pool._refs[i] > 0]
        findings.append(Finding(
            rule=_QRULE, file=path, line=line,
            message="refcount leak on the quantized pool: after retiring "
                    f"every request and evicting the whole cache, "
                    f"{usable - pool.available} page(s) never returned "
                    f"(still-referenced: {held})"))
    return findings


def check_all() -> List[Finding]:
    return _check_cow() + _check_quant()
