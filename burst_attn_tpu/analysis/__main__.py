"""burstlint CLI:  python -m burst_attn_tpu.analysis [--json] [paths...]

Exit status: 0 clean, 1 findings, 2 internal error.  Runs CPU-only (the
jaxpr family traces abstractly on simulated host devices); wired into
scripts/test.sh as the pre-test gate.
"""

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m burst_attn_tpu.analysis",
        description="burstlint: static ring/sharding/numerics verifier")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs for the AST rules (default: package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit machine-readable JSON")
    ap.add_argument("--sarif", metavar="PATH",
                    help="also write findings as SARIF 2.1.0 to PATH "
                         "(CI annotations); stdout output is unchanged")
    ap.add_argument("--changed-only", action="store_true",
                    help="incremental mode: lint files changed since the "
                         "merge-base with the default branch and skip "
                         "dynamic rule families whose watched sources "
                         "are untouched; falls back to a FULL run when "
                         "git is unavailable")
    ap.add_argument("--ast-only", action="store_true",
                    help="skip the jaxpr tracing family (fast editor hook)")
    ap.add_argument("--disable", action="append", default=[],
                    metavar="RULE", help="disable a rule by name")
    ap.add_argument("--list-rules", action="store_true",
                    help="print registered rules and exit")
    ap.add_argument("--cost-json", action="store_true",
                    help="print the burstcost static resource/roofline "
                         "table (schema burstcost-v2) as JSON and exit: "
                         "the full tuning-table x topology x wire-dtype x "
                         "pass matrix the autotuner prunes on and "
                         "fleet/sim.py prices replicas with")
    args = ap.parse_args(argv)

    # the jaxpr family needs 8 simulated devices and must never grab a TPU:
    # set up the backend BEFORE jax initializes (importing the package does
    # not import jax; the rule modules do, lazily)
    if not args.ast_only:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from .core import RULES, render, render_sarif, run_analysis

    if args.list_rules:
        # force registration of the lazy rule families
        from . import (astlint, costcheck, numerics,  # noqa: F401
                       obscheck, policycheck, poolcheck, protocheck,
                       ringcheck, servecheck)

        for name in sorted(RULES):
            r = RULES[name]
            print(f"{name:22s} [{r.kind}]  {r.doc}")
        return 0

    if args.cost_json:
        import json

        from . import costmodel

        try:
            print(json.dumps(costmodel.cost_table(), indent=1))
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"burstcost: internal error: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        return 0

    paths = None
    if args.paths:
        from .astlint import default_paths

        paths = []
        for p in args.paths:
            paths += default_paths(p) if os.path.isdir(p) else [p]
    try:
        findings = run_analysis(disable=args.disable, ast_only=args.ast_only,
                                paths=paths,
                                changed_only=args.changed_only)
    except Exception as e:  # noqa: BLE001 — CLI boundary: report, exit 2
        print(f"burstlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.sarif:
        sarif_dir = os.path.dirname(os.path.abspath(args.sarif))
        os.makedirs(sarif_dir, exist_ok=True)
        with open(args.sarif, "w") as fh:
            fh.write(render_sarif(findings))
    print(render(findings, args.as_json))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
