"""burstlint — static verification of the ring/sharding/numerics contracts.

Two families of checks (docs/analysis.md):

  * jaxpr-level verifiers (ringcheck, numerics): abstractly trace the
    public attention entry points under a matrix of simulated meshes and
    assert the structural ring invariants (single-cycle rotations, hop
    counts against the host-side schedule oracle, dq return-home, double
    ring prefetch distance, windowed truncation) plus fp32 accumulation
    in the kernels.  These catch topology-dependent bugs that an 8-device
    CPU-mesh test matrix can pass while real scale breaks.
  * AST-level lint rules (astlint): mechanical hygiene rules over the
    package source (silent exception swallowing, hard mesh.shape[axis]
    indexing, host transfers / time calls / Python branches / obs
    registry-span calls under jit — the latter paired with a jaxpr proof
    (obscheck) that the traced rings carry zero host-callback
    primitives).

CLI: python -m burst_attn_tpu.analysis [--json]
"""

from .core import Finding, Rule, RULES, rule, run_analysis  # noqa: F401
