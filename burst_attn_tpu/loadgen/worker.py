"""Serve worker process for the loadgen cluster.

Each worker is an isolated OS process (multiprocessing `spawn` context —
a clean interpreter, its own single-process CPU JAX runtime, its own obs
registry) running one serve engine and a small message loop.  Messages
travel as CRC-framed transport frames (fleet/transport.py,
QueueTransport over the spawn queues — the same protocol the socket
fleet speaks cross-host):

  router -> worker   ("submit", rrid, prompt list, max_new[, resume_toks])
                     ("fault", fault_kind, arg)   hog|unhog|stall|hang|raise
                     ("ping", seq)                heartbeat probe
                     ("stop",)                    finish backlog, export, exit
  worker -> router   ("ready", wid, pid)
                     ("restored", wid, info)      checkpoint recovery summary
                     ("accepted", wid, rrid)
                     ("rejected", wid, rrid, reason, retryable, message)
                     ("done", wid, rrid, tokens)
                     ("pong", wid, seq)
                     ("stopped", wid)
                     ("error", wid, message)      engine loop blew up

The "error" path is ordered for shutdown races: the worker exports its
obs snapshot FIRST (never a torn registry export), then sends the error
frame, then flushes the transport so the frame survives the process
dying immediately after — a worker erroring DURING stop still reports,
and the router's stop() collects it instead of dropping it.

Request ids on the wire are the ROUTER's (trace rids): the worker maps
its engine's local rids back before reporting, so the router never sees
worker-local numbering.

Crash consistency (`ckpt_spec`, serving/checkpoint.py): when enabled the
engine runs with a write-ahead TokenJournal (every generated token is
fsynced before its done record can leave the process) and the worker
snapshots the whole engine every `every` completions.  Three recovery
flows ride on that state:

  * reroute resume — a submit carrying `resume_toks` (the dead worker's
    journaled prefix for that rid) is admitted as prompt+prefix with the
    budget reduced; the prefix is prepended before reporting done, so
    the router sees the original request shape.  Requires greedy decode
    (the prefix must be the continuation the engine would have emitted).
  * restart restore — a replacement worker (`ckpt_spec["restore"]`)
    rebuilds its predecessor's engine from snapshot + journal
    (`recover_engine`), reports what it claimed via "restored", emits
    journal-complete requests as immediate dones, and rewrites a fresh
    journal so a SECOND failure recovers from this life alone.
  * accounting — `serve.recovered_tokens_resumed` counts tokens
    recovered without re-decoding, `serve.recovered_tokens_replayed`
    counts re-decoded ones (resume disabled, or journal lag); the
    acceptance gate asserts resumed recovery strictly beats
    replay-from-scratch on the same trace+fault schedule.

Obs discipline: the engine's serve.* instruments land in this process's
registry; the loop exports a full fsynced snapshot to the worker's JSONL
(tagged `process_index=wid`) every `export_every` completions and again
at clean shutdown.  A SIGKILLed worker therefore leaves its last
snapshot on disk — possibly with one torn final line, which is exactly
the case `obs.aggregate.load_records_tolerant` absorbs.

Fault injection runs INSIDE the worker because that is where the faults
live in production: "hog" grabs pages straight from the engine's pool
(forced pool exhaustion — admission and shed paths see real scarcity),
"unhog" releases them, "stall" freezes the engine loop (delayed retire /
GC pause stand-in) without touching the queue, "hang" wedges the WHOLE
loop — no stepping, no queue drain, no pong — which only the router's
heartbeat detector can distinguish from slow progress.  Worker kill is
not a message — the router SIGKILLs the process, the point being that no
cooperation is required.
"""

import os
import time


def build_engine(model_spec: dict, engine_spec: dict, journal=None):
    """Construct a serve engine from plain-dict specs (everything must be
    picklable across the spawn boundary, so no arrays/params travel —
    each process re-derives identical params from the shared seed).
    `engine_spec["kind"]`: "ragged" (RaggedServeEngine, default) or
    "legacy" (models/serve.py's ServeEngine)."""
    import jax
    import jax.numpy as jnp

    from ..admission import AdmissionPolicy
    from ..models import ModelConfig, ServeEngine, init_params
    from ..serving import RaggedServeEngine

    ms = dict(model_spec)
    # token-exactness across PROCESSES requires every process to compute
    # identical logits: pin matmul precision in whatever process builds an
    # engine (spawned workers don't inherit the parent's jax.config)
    jax.config.update("jax_default_matmul_precision",
                      ms.pop("matmul_precision", "highest"))
    seed = ms.pop("seed", 0)
    cfg = ModelConfig(attn_backend="jnp", remat=False, dtype=jnp.float32,
                      batch_axis=None, head_axis=None, **ms)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    es = dict(engine_spec)
    kind = es.pop("kind", "ragged")
    adm = es.pop("admission", None)
    if adm is not None:
        adm = AdmissionPolicy(**adm)
    cls = {"ragged": RaggedServeEngine, "legacy": ServeEngine}[kind]
    return cls(params, cfg, admission=adm, journal=journal, **es)


def _warm(eng) -> None:
    """Compile the prefill-chunk + decode launch widths BEFORE the worker
    reports ready: an XLA compile inside the serving loop blocks the
    queue drain for seconds — long enough to miss heartbeat pings and be
    falsely declared dead by a tight detector."""
    res = eng.try_submit([1] * 20, 2)
    if res.ok:
        eng.run()


def _export(obs_path: str, wid: int) -> None:
    from .. import obs
    from ..obs import spans as _spans

    obs.default_registry().export_jsonl(
        obs_path, extra_records=_spans.span_records(), process_index=wid)


def worker_main(wid: int, model_spec: dict, engine_spec: dict,
                obs_path: str, request_q, result_q,
                export_every: int = 4, ckpt_spec=None) -> None:
    """Entry point for one spawned worker (cluster.py passes this to
    multiprocessing.Process).  `ckpt_spec` (None disables checkpointing):
    {"journal": path, "snapshot": path, "every": N completions between
    snapshots, "resume": accept resume_toks prefixes, "restore": rebuild
    from the predecessor's snapshot+journal before going ready}."""
    # must land before the jax import inside build_engine: the cluster is
    # a CPU-mesh harness even on a TPU host
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ..fleet.transport import QueueTransport

    tr = QueueTransport(send_q=result_q, recv_q=request_q)
    try:
        ck = dict(ckpt_spec) if ckpt_spec else None
        journal = None
        rid_map = {}                  # engine rid -> router rid
        resume_prefix = {}            # engine rid -> resumed token prefix
        # warm before any journal/recovery state attaches: the warm
        # request must never land in the journal or a snapshot
        eng = build_engine(model_spec, engine_spec)
        _warm(eng)
        if ck is not None:
            from ..serving import checkpoint as ckpt

            if ck.get("restore"):
                # replacement life: recover the predecessor's engine, then
                # start journaling fresh (rewrite_journal) so a second
                # failure recovers from THIS life's journal alone
                info = ckpt.recover_engine(eng, ck.get("snapshot"),
                                           ck.get("journal"))
                rid_map = dict(info.rid_map)
                resume_prefix = {r: list(p)
                                 for r, p in info.resume_prefix.items()}
                journal = ckpt.rewrite_journal(eng, ck["journal"], rid_map,
                                               resume_prefix)
                eng.journal = journal
                live = [r for r in eng.slots if r is not None] \
                    + list(eng._queue)
                claimed = sorted(
                    {rid_map.get(r.rid, r.rid) for r in live}
                    | set(info.done))
                tr.send(("restored", wid, {
                    "claimed": claimed,
                    "replayed": {int(k): int(v)
                                 for k, v in info.replayed.items()},
                    "resumed": {int(k): int(v)
                                for k, v in info.resumed.items()},
                    "from_snapshot": info.from_snapshot,
                }))
                # requests the journal proves complete need no engine time
                for ext, toks in sorted(info.done.items()):
                    tr.send(("done", wid, int(ext),
                                  [int(t) for t in toks]))
            else:
                journal = ckpt.TokenJournal(ck["journal"], truncate=True)
                eng.journal = journal
        _export(obs_path, wid)  # baseline: even an early kill leaves a file
        tr.send(("ready", wid, os.getpid()))
        hogged = []                   # pages held by the "hog" fault
        stall_until = 0.0
        hang = False
        stopping = False
        n_since_export = 0
        n_since_ckpt = 0
        while True:
            if hang:
                # wedged, not dead: the process is alive (liveness polls
                # pass) but drains nothing and answers no pings — only the
                # heartbeat detector can declare this worker gone
                time.sleep(0.05)
                continue
            while True:
                msg = tr.recv()
                if msg is None:
                    break
                op = msg[0]
                if op == "submit":
                    rrid, prompt, max_new = msg[1], msg[2], msg[3]
                    resume_toks = msg[4] if len(msg) > 4 else None
                    if resume_toks and ck is not None \
                            and ck.get("resume", True):
                        comp = ckpt.trim_complete(
                            resume_toks, max_new, eng.eos_id)
                        if comp is not None:
                            # the dead worker journaled past the finish
                            # line — complete with zero engine time
                            ckpt.M_RECOVERED_RESUMED.inc(len(comp))
                            tr.send(("accepted", wid, rrid))
                            tr.send(("done", wid, rrid,
                                          [int(t) for t in comp]))
                            continue
                        res = eng.try_submit(
                            list(prompt) + [int(t) for t in resume_toks],
                            max_new - len(resume_toks))
                        if res.ok:
                            ckpt.M_RECOVERED_RESUMED.inc(
                                len(resume_toks))
                            rid_map[res.rid] = rrid
                            resume_prefix[res.rid] = \
                                [int(t) for t in resume_toks]
                            if journal is not None:
                                # journal the ORIGINAL request shape so
                                # a second recovery composes
                                journal.submit(res.rid, rrid, prompt,
                                               max_new)
                                journal.tokens(res.rid, resume_toks)
                                journal.sync()
                            tr.send(("accepted", wid, rrid))
                        else:
                            tr.send((
                                "rejected", wid, rrid,
                                res.reason.value if res.reason else None,
                                res.retryable, res.message))
                    else:
                        if resume_toks and ck is not None:
                            # resume disabled: the baseline path —
                            # every journaled token gets re-decoded
                            ckpt.M_RECOVERED_REPLAYED.inc(
                                len(resume_toks))
                        res = eng.try_submit(prompt, max_new)
                        if res.ok:
                            rid_map[res.rid] = rrid
                            if journal is not None:
                                journal.submit(res.rid, rrid, prompt,
                                               max_new)
                                journal.sync()
                            tr.send(("accepted", wid, rrid))
                        else:
                            tr.send((
                                "rejected", wid, rrid,
                                res.reason.value if res.reason else None,
                                res.retryable, res.message))
                elif op == "ping":
                    tr.send(("pong", wid, msg[1]))
                elif op == "fault":
                    _, fkind, arg = msg
                    if fkind == "hog":
                        n = min(int(arg), eng.pool.available)
                        if n > 0:
                            hogged += list(eng.pool.acquire(n))
                    elif fkind == "unhog":
                        if hogged:
                            eng.pool.release(hogged)
                            hogged = []
                    elif fkind == "stall":
                        stall_until = time.monotonic() + float(arg)
                    elif fkind == "hang":
                        hang = True
                    elif fkind == "raise":
                        raise RuntimeError(
                            "injected worker fault (raise)")
                    else:
                        tr.send(("error", wid,
                                      f"unknown fault {fkind!r}"))
                elif op == "stop":
                    stopping = True
                else:
                    tr.send(("error", wid, f"unknown op {op!r}"))
            if time.monotonic() < stall_until:
                time.sleep(0.002)
                continue
            if eng.pending or eng.live:
                for erid, toks in eng.step():
                    full = resume_prefix.pop(erid, []) \
                        + [int(t) for t in toks]
                    tr.send(("done", wid, rid_map.pop(erid), full))
                    n_since_export += 1
                    n_since_ckpt += 1
                if ck is not None and ck.get("snapshot") \
                        and n_since_ckpt >= int(ck.get("every", 2)):
                    ckpt.save_snapshot(
                        eng, ck["snapshot"],
                        extra={"rid_map": rid_map,
                               "resume_prefix": resume_prefix})
                    n_since_ckpt = 0
                if n_since_export >= export_every:
                    _export(obs_path, wid)
                    n_since_export = 0
            elif stopping:
                if journal is not None:
                    journal.close()
                _export(obs_path, wid)
                tr.send(("stopped", wid))
                return
            else:
                time.sleep(0.002)
    except Exception as e:  # noqa: BLE001 — report, then die visibly
        # obs snapshot FIRST (a torn registry export must never be the
        # price of an error), then the error frame, then a flush so the
        # frame survives this process dying right after
        try:
            _export(obs_path, wid)
        except Exception as ee:  # noqa: BLE001 — export is best-effort
            os.write(2, f"loadgen worker {wid}: obs export failed: "
                        f"{ee}\n".encode())
        try:
            tr.send(("error", wid, f"{type(e).__name__}: {e}"))
            tr.flush()
        except Exception:  # noqa: BLE001 — router gone; stderr is all
            os.write(2, f"loadgen worker {wid}: {e}\n".encode())
        raise
