"""Serve worker process for the loadgen cluster.

Each worker is an isolated OS process (multiprocessing `spawn` context —
a clean interpreter, its own single-process CPU JAX runtime, its own obs
registry) running one serve engine and a small message loop:

  router -> worker   ("submit", rrid, prompt list, max_new)
                     ("fault", fault_kind, arg)   hog | unhog | stall
                     ("stop",)                    finish backlog, export, exit
  worker -> router   ("ready", wid, pid)
                     ("accepted", wid, rrid)
                     ("rejected", wid, rrid, reason, retryable, message)
                     ("done", wid, rrid, tokens)
                     ("stopped", wid)
                     ("error", wid, message)      engine loop blew up

Request ids on the wire are the ROUTER's (trace rids): the worker maps
its engine's local rids back before reporting, so the router never sees
worker-local numbering.

Obs discipline: the engine's serve.* instruments land in this process's
registry; the loop exports a full fsynced snapshot to the worker's JSONL
(tagged `process_index=wid`) every `export_every` completions and again
at clean shutdown.  A SIGKILLed worker therefore leaves its last
snapshot on disk — possibly with one torn final line, which is exactly
the case `obs.aggregate.load_records_tolerant` absorbs.

Fault injection runs INSIDE the worker because that is where the faults
live in production: "hog" grabs pages straight from the engine's pool
(forced pool exhaustion — admission and shed paths see real scarcity),
"unhog" releases them, "stall" freezes the engine loop (delayed retire /
GC pause stand-in) without touching the queue.  Worker kill is not a
message — the router SIGKILLs the process, the point being that no
cooperation is required.
"""

import os
import queue
import time


def build_engine(model_spec: dict, engine_spec: dict):
    """Construct a serve engine from plain-dict specs (everything must be
    picklable across the spawn boundary, so no arrays/params travel —
    each process re-derives identical params from the shared seed).
    `engine_spec["kind"]`: "ragged" (RaggedServeEngine, default) or
    "legacy" (models/serve.py's ServeEngine)."""
    import jax
    import jax.numpy as jnp

    from ..admission import AdmissionPolicy
    from ..models import ModelConfig, ServeEngine, init_params
    from ..serving import RaggedServeEngine

    ms = dict(model_spec)
    # token-exactness across PROCESSES requires every process to compute
    # identical logits: pin matmul precision in whatever process builds an
    # engine (spawned workers don't inherit the parent's jax.config)
    jax.config.update("jax_default_matmul_precision",
                      ms.pop("matmul_precision", "highest"))
    seed = ms.pop("seed", 0)
    cfg = ModelConfig(attn_backend="jnp", remat=False, dtype=jnp.float32,
                      batch_axis=None, head_axis=None, **ms)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    es = dict(engine_spec)
    kind = es.pop("kind", "ragged")
    adm = es.pop("admission", None)
    if adm is not None:
        adm = AdmissionPolicy(**adm)
    cls = {"ragged": RaggedServeEngine, "legacy": ServeEngine}[kind]
    return cls(params, cfg, admission=adm, **es)


def _export(obs_path: str, wid: int) -> None:
    from .. import obs
    from ..obs import spans as _spans

    obs.default_registry().export_jsonl(
        obs_path, extra_records=_spans.span_records(), process_index=wid)


def worker_main(wid: int, model_spec: dict, engine_spec: dict,
                obs_path: str, request_q, result_q,
                export_every: int = 4) -> None:
    """Entry point for one spawned worker (cluster.py passes this to
    multiprocessing.Process)."""
    # must land before the jax import inside build_engine: the cluster is
    # a CPU-mesh harness even on a TPU host
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        eng = build_engine(model_spec, engine_spec)
        _export(obs_path, wid)  # baseline: even an early kill leaves a file
        result_q.put(("ready", wid, os.getpid()))
        rid_map = {}                  # engine rid -> router rid
        hogged = []                   # pages held by the "hog" fault
        stall_until = 0.0
        stopping = False
        n_since_export = 0
        while True:
            try:
                while True:
                    msg = request_q.get_nowait()
                    op = msg[0]
                    if op == "submit":
                        _, rrid, prompt, max_new = msg
                        res = eng.try_submit(prompt, max_new)
                        if res.ok:
                            rid_map[res.rid] = rrid
                            result_q.put(("accepted", wid, rrid))
                        else:
                            result_q.put((
                                "rejected", wid, rrid,
                                res.reason.value if res.reason else None,
                                res.retryable, res.message))
                    elif op == "fault":
                        _, fkind, arg = msg
                        if fkind == "hog":
                            n = min(int(arg), eng.pool.available)
                            if n > 0:
                                hogged += list(eng.pool.acquire(n))
                        elif fkind == "unhog":
                            if hogged:
                                eng.pool.release(hogged)
                                hogged = []
                        elif fkind == "stall":
                            stall_until = time.monotonic() + float(arg)
                        else:
                            result_q.put(("error", wid,
                                          f"unknown fault {fkind!r}"))
                    elif op == "stop":
                        stopping = True
                    else:
                        result_q.put(("error", wid, f"unknown op {op!r}"))
            except queue.Empty:
                pass
            if time.monotonic() < stall_until:
                time.sleep(0.002)
                continue
            if eng.pending or eng.live:
                for erid, toks in eng.step():
                    result_q.put(("done", wid, rid_map.pop(erid),
                                  [int(t) for t in toks]))
                    n_since_export += 1
                if n_since_export >= export_every:
                    _export(obs_path, wid)
                    n_since_export = 0
            elif stopping:
                _export(obs_path, wid)
                result_q.put(("stopped", wid))
                return
            else:
                time.sleep(0.002)
    except Exception as e:  # noqa: BLE001 — report, then die visibly
        try:
            result_q.put(("error", wid, f"{type(e).__name__}: {e}"))
        except Exception:  # noqa: BLE001
            os.write(2, f"loadgen worker {wid}: {e}\n".encode())
        raise
