"""SLO evaluation from merged obs exports + replay outcomes.

The serve SLOs this repo gates on (ISSUE 9 / ROADMAP item 5):

  ttft_p50_s / ttft_p99_s          time-to-first-token quantiles, from the
                                   `serve.ttft_s` histogram
  token_latency_p50_s / _p99_s     per-token latency quantiles, from
                                   `serve.token_latency_s`
  throughput_tokens_per_s          every generated token (counter) / window
  goodput_tokens_per_s             tokens of COMPLETED requests / window —
                                   tokens burned on requests that never
                                   finished (killed worker, shed after
                                   partial work) do not count
  shed_rate                        shed decisions / submit attempts

Quantiles come from histogram BUCKETS, not raw samples — the merged
multi-process export is the only thing that exists after a worker dies,
so the SLO layer reads exactly what `obs --merge` emits (bucket_counts
are per-bin, the `+Inf` overflow falls back to the observed max: the
honest bound when the tail escaped the bins).  Two schemas are handled:
export records (`bucket_edges`/`bucket_counts` lists) and live
`Histogram.get()` snapshots (`buckets` dict), the latter as before/after
window deltas so benches can scope to a measurement window.

`Objectives` + `evaluate` turn a report into a typed pass/fail with
human-readable violations — the gate surface bench_loadgen.py and the
cluster tests share.
"""

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..admission import RejectReason

# reason labels that count as LOAD SHED (retryable) rather than
# malformed-request rejection — derived from the enum, never restated
SHED_REASONS = frozenset(r.value for r in RejectReason if r.retryable)


def quantile_from_record(rec: dict, q: float) -> float:
    """Quantile from ONE merged-export histogram record
    (`bucket_edges` + per-bin `bucket_counts` + `overflow`).  Returns the
    upper edge of the bin where the cumulative count crosses q; overflow
    mass falls back to the record's `max`."""
    if not 0.0 < q <= 1.0:
        raise ValueError(f"q must be in (0, 1], got {q}")
    edges = rec.get("bucket_edges") or []
    counts = rec.get("bucket_counts") or []
    overflow = rec.get("overflow", 0)
    total = sum(counts) + overflow
    if total <= 0:
        return float(rec.get("max", 0.0) or 0.0)
    need, seen = q * total, 0
    for edge, count in zip(edges, counts):
        seen += count
        if seen >= need:
            return float(edge)
    return float(rec.get("max", 0.0) or 0.0)


def quantile_from_window(before: dict, after: dict, q: float) -> float:
    """Quantile of the observations that landed BETWEEN two
    `Histogram.get()` snapshots (`buckets` dict keyed by upper edge,
    "+Inf" = overflow).  Generalizes scripts/bench_serve.py's p99 helper
    to any quantile."""
    if not 0.0 < q <= 1.0:
        raise ValueError(f"q must be in (0, 1], got {q}")
    db = dict(before.get("buckets") or {})
    deltas = [(edge, count - db.get(edge, 0))
              for edge, count in (after.get("buckets") or {}).items()]
    finite = sorted(((float(e), d) for e, d in deltas if e != "+Inf"),
                    key=lambda ed: ed[0])
    overflow = sum(d for e, d in deltas if e == "+Inf")
    total = sum(d for _, d in finite) + overflow
    if total <= 0:
        return float(after.get("max", 0.0) or 0.0)
    need, seen = q * total, 0
    for edge, d in finite:
        seen += d
        if seen >= need:
            return edge
    return float(after.get("max", 0.0) or 0.0)


def find_metric(metrics: Sequence[dict], name: str,
                kind: Optional[str] = None) -> List[dict]:
    """All merged-export records for one metric name (label children of a
    counter each appear as their own record)."""
    return [rec for rec in metrics
            if rec.get("name") == name
            and (kind is None or rec.get("kind") == kind)]


def counter_total(metrics: Sequence[dict], name: str,
                  label: Optional[Tuple[str, frozenset]] = None) -> int:
    """Sum a counter's children; `label=("reason", {"queue-full", ...})`
    restricts to children whose label value is in the set."""
    total = 0
    for rec in find_metric(metrics, name, kind="counter"):
        labels = rec.get("labels") or {}
        if label is not None:
            key, allowed = label
            if labels.get(key) not in allowed:
                continue
        total += int(rec.get("value", 0))
    return total


def recovery_stats(recovery_s: Sequence[float]) -> Dict[str, object]:
    """Per-fault recovery-span stats (`ClusterReport.recovery_s()` — the
    virtual seconds from each fault to its last recovered completion).
    Nearest-rank quantiles over the RAW samples: recovery spans come from
    the router's fault ledger, not from histogram buckets, and a fault
    schedule has few events — bucketing would only lose the tail."""
    samples = sorted(float(s) for s in recovery_s)
    out: Dict[str, object] = {"recovery_count": len(samples)}
    if not samples:
        out.update({"recovery_p50_s": 0.0, "recovery_p99_s": 0.0,
                    "recovery_max_s": 0.0})
        return out
    n = len(samples)
    for q in (0.50, 0.99):
        idx = min(n - 1, max(0, int(-(-q * n // 1)) - 1))  # ceil(q*n) - 1
        out[f"recovery_p{int(q * 100)}_s"] = samples[idx]
    out["recovery_max_s"] = samples[-1]
    return out


def compute_slo(metrics: Sequence[dict], *, duration_s: float,
                completed_tokens: Optional[int] = None,
                n_done: Optional[int] = None,
                n_rejected: Optional[int] = None,
                recovery_s: Optional[Sequence[float]] = None
                ) -> Dict[str, object]:
    """One SLO report from a merged metrics view (`obs --merge` output or
    `aggregate.merge_files(...)[0]`).

    `duration_s` is the measurement window the rates divide by — VIRTUAL
    trace seconds when the caller replayed at a speed factor (rates then
    describe the modeled workload, invariant to replay speed).  The
    caller supplies completion-side numbers the metrics cannot know:
    `completed_tokens`/`n_done` come from replay outcomes (goodput counts
    only finished requests — a killed worker's partial tokens are not
    good work)."""
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    report: Dict[str, object] = {"duration_s": duration_s}
    for short, name in (("ttft", "serve.ttft_s"),
                        ("token_latency", "serve.token_latency_s")):
        recs = find_metric(metrics, name, kind="histogram")
        count = sum(int(r.get("count", 0)) for r in recs)
        report[f"{short}_count"] = count
        for q in (0.50, 0.99):
            key = f"{short}_p{int(q * 100)}_s"
            if len(recs) == 1:
                report[key] = quantile_from_record(recs[0], q)
            elif not recs or not count:
                report[key] = 0.0
            else:
                # edge-mismatched children survived the merge un-added
                # (mixed binaries); the max child quantile is the honest
                # conservative read
                report[key] = max(quantile_from_record(r, q) for r in recs)
    n_tokens = counter_total(metrics, "serve.tokens_generated")
    submitted = counter_total(metrics, "serve.requests_submitted")
    shed = counter_total(metrics, "serve.requests_rejected",
                         label=("reason", SHED_REASONS))
    invalid = counter_total(metrics, "serve.requests_rejected") - shed
    attempts = submitted + shed + invalid
    report.update({
        "tokens_generated": n_tokens,
        "throughput_tokens_per_s": n_tokens / duration_s,
        "requests_submitted": submitted,
        "requests_retired": counter_total(metrics, "serve.requests_retired"),
        "shed_decisions": shed,
        "invalid_rejections": invalid,
        "shed_rate": shed / attempts if attempts else 0.0,
    })
    if completed_tokens is not None:
        report["completed_tokens"] = int(completed_tokens)
        report["goodput_tokens_per_s"] = completed_tokens / duration_s
    if n_done is not None:
        report["n_done"] = int(n_done)
    if n_rejected is not None:
        report["n_rejected"] = int(n_rejected)
    if recovery_s is not None:
        report.update(recovery_stats(recovery_s))
    return report


@dataclass(frozen=True)
class Objectives:
    """SLO targets; None disables that check."""

    max_ttft_p99_s: Optional[float] = None
    max_token_p99_s: Optional[float] = None
    min_goodput_tokens_per_s: Optional[float] = None
    max_shed_rate: Optional[float] = None


def evaluate(report: Dict[str, object],
             objectives: Objectives) -> Tuple[bool, List[str]]:
    """(ok, violations) — each violation names the SLO, the observed
    value, and the bound, ready for a test assertion or a CI log."""
    checks = (
        ("ttft_p99_s", objectives.max_ttft_p99_s, "<="),
        ("token_latency_p99_s", objectives.max_token_p99_s, "<="),
        ("goodput_tokens_per_s", objectives.min_goodput_tokens_per_s, ">="),
        ("shed_rate", objectives.max_shed_rate, "<="),
    )
    violations = []
    for key, bound, sense in checks:
        if bound is None:
            continue
        value = report.get(key)
        if value is None:
            violations.append(f"{key}: objective set ({sense} {bound:g}) "
                              "but the report carries no value")
            continue
        ok = value <= bound if sense == "<=" else value >= bound
        if not ok:
            violations.append(f"{key}: {float(value):.6g} violates "
                              f"{sense} {bound:g}")
    return (not violations), violations


def format_slo(report: Dict[str, object]) -> str:
    """Human-readable one-per-line rendering (CLI / bench logs)."""
    order = ("duration_s", "ttft_p50_s", "ttft_p99_s",
             "token_latency_p50_s", "token_latency_p99_s",
             "throughput_tokens_per_s", "goodput_tokens_per_s",
             "completed_tokens", "tokens_generated", "requests_submitted",
             "requests_retired", "n_done", "n_rejected", "shed_decisions",
             "invalid_rejections", "shed_rate", "recovery_count",
             "recovery_p50_s", "recovery_p99_s", "recovery_max_s")
    lines = []
    for key in order:
        if key in report:
            v = report[key]
            lines.append(f"  {key:<26} "
                         + (f"{v:.6g}" if isinstance(v, float) else str(v)))
    for key in sorted(set(report) - set(order)):
        lines.append(f"  {key:<26} {report[key]}")
    return "\n".join(lines)
