"""Multi-process serve cluster: router, fault schedule, recovery proof.

N worker processes (loadgen/worker.py — each its own spawned interpreter
with a single-process CPU JAX runtime and its own obs registry) behind
one in-process router.  The router replays a Trace open-loop: arrivals
route to the least-loaded alive worker, retryable sheds back off and
re-route, and a FAULT SCHEDULE injects failures at virtual times:

  kill    SIGKILL the worker process (no cooperation, no cleanup — the
          real failure mode).  The router reroutes every rid the dead
          worker still owed to surviving workers; greedy decode
          regenerates each rerouted request's tokens EXACTLY, so the
          kill is invisible in the output stream — the property
          `assert_token_exact` gates against the single-process oracle.
  hog     force pool exhaustion inside the worker (pages acquired out
          from under admission) — sheds/deferrals must kick in, and
          `unhog` must let the backlog drain (bounded recovery).
  stall   freeze the worker's engine loop for S seconds (delayed-retire
          / GC-pause stand-in); queued work must survive untouched.

Wire-safety note: worker->router messages are small (a done record for a
canary request pickles well under PIPE_BUF = 4096 bytes), so kernel pipe
writes are atomic and a SIGKILL cannot tear a frame mid-message; each
worker also gets its OWN result queue so a dead worker's stream never
interleaves with a live one's.  The torn-write hazard that DOES exist —
a kill mid `export_jsonl` — lands in the worker's obs file, which is
exactly what `obs.aggregate.load_records_tolerant` absorbs at merge.

Every worker exports obs JSONL snapshots (`obs_w{wid}.jsonl`, tagged
process_index=wid); `merged()` folds them into the one job-level view
(`obs --merge` semantics) that loadgen/slo.py evaluates.
"""

import multiprocessing as mp
import os
import queue
import signal
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .driver import DONE, REJECTED, SHED, Outcome, ReplayReport
from .trace import Trace
from .worker import worker_main

FAULT_KINDS = ("kill", "hog", "unhog", "stall")


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: at virtual time `t`, do `kind` to `worker`.

    `kill` waits until the target holds at least one in-flight request
    (a kill that lands on an idle worker proves nothing about recovery);
    if the trace drains first, it fires on the idle worker anyway so the
    schedule always executes.  `arg`: pages to hog / stall seconds."""

    t: float
    kind: str
    worker: int
    arg: float = 0.0
    note: str = ""

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {FAULT_KINDS})")


@dataclass
class ClusterReport(ReplayReport):
    """ReplayReport plus the fault/recovery evidence the tests gate on:
    each kill records WHO died, WHAT was rerouted, and the virtual time
    by which every rerouted request completed."""

    kills: List[dict] = field(default_factory=list)
    obs_paths: List[str] = field(default_factory=list)

    def recovery_s(self) -> List[float]:
        """Per-kill recovery spans (virtual): last rerouted completion
        minus kill time; kills that rerouted nothing contribute 0."""
        out = []
        for k in self.kills:
            ts = [self.outcomes[rid].t_done for rid in k["rerouted"]
                  if self.outcomes[rid].t_done is not None]
            out.append(max(ts) - k["t"] if ts else 0.0)
        return out


class LoadGenCluster:
    """Spawn, replay, stop.  Use as a context manager — __exit__ always
    reaps worker processes, even when replay raised."""

    def __init__(self, model_spec: dict, engine_spec: dict, *,
                 n_workers: int, out_dir: str, export_every: int = 4,
                 start_timeout_s: float = 180.0):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.model_spec = dict(model_spec)
        self.engine_spec = dict(engine_spec)
        self.n_workers = n_workers
        self.out_dir = out_dir
        self.export_every = export_every
        self.start_timeout_s = start_timeout_s
        self._ctx = mp.get_context("spawn")
        self._procs: Dict[int, mp.Process] = {}
        self._req_q: Dict[int, object] = {}
        self._res_q: Dict[int, object] = {}
        self._alive: set = set()

    # -- lifecycle ---------------------------------------------------------

    def obs_path(self, wid: int) -> str:
        return os.path.join(self.out_dir, f"obs_w{wid}.jsonl")

    @property
    def obs_paths(self) -> List[str]:
        return [self.obs_path(w) for w in range(self.n_workers)]

    def start(self) -> None:
        # spawned children import the package (and therefore jax) BEFORE
        # worker_main runs, so the CPU pin must ride in via the inherited
        # environment — a TPU host must never hand its chips to workers
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.makedirs(self.out_dir, exist_ok=True)
        for wid in range(self.n_workers):
            path = self.obs_path(wid)
            if os.path.exists(path):
                os.remove(path)  # stale exports would pollute the merge
            self._req_q[wid] = self._ctx.Queue()
            self._res_q[wid] = self._ctx.Queue()
            proc = self._ctx.Process(
                target=worker_main,
                args=(wid, self.model_spec, self.engine_spec, path,
                      self._req_q[wid], self._res_q[wid], self.export_every),
                daemon=True, name=f"loadgen-worker-{wid}")
            proc.start()
            self._procs[wid] = proc
        deadline = time.monotonic() + self.start_timeout_s
        waiting = set(range(self.n_workers))
        while waiting:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"workers {sorted(waiting)} not ready within "
                    f"{self.start_timeout_s:g}s")
            for wid in sorted(waiting):
                msg = self._poll(wid)
                if msg is None:
                    continue
                if msg[0] == "ready":
                    waiting.discard(wid)
                    self._alive.add(wid)
                elif msg[0] == "error":
                    raise RuntimeError(f"worker {wid} failed to start: "
                                       f"{msg[2]}")
            time.sleep(0.01)

    def __enter__(self) -> "LoadGenCluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self, timeout_s: float = 60.0) -> None:
        """Graceful where possible (workers flush a final obs export),
        SIGKILL where not.  Idempotent."""
        for wid in sorted(self._alive):
            try:
                self._req_q[wid].put(("stop",))
            except (OSError, ValueError):
                self._alive.discard(wid)
        deadline = time.monotonic() + timeout_s
        pending = set(self._alive)
        while pending and time.monotonic() < deadline:
            for wid in sorted(pending):
                if not self._procs[wid].is_alive():
                    pending.discard(wid)
                    continue
                msg = self._poll(wid)
                if msg is not None and msg[0] == "stopped":
                    pending.discard(wid)
            time.sleep(0.01)
        for wid, proc in self._procs.items():
            if proc.is_alive():
                proc.terminate()
            proc.join(timeout=10)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=10)
        self._alive.clear()

    # -- plumbing ----------------------------------------------------------

    def _poll(self, wid: int):
        try:
            return self._res_q[wid].get_nowait()
        except queue.Empty:
            return None
        except (OSError, EOFError, ValueError):
            return None  # queue torn down under us (dead worker)

    def _kill(self, wid: int) -> None:
        proc = self._procs[wid]
        if proc.is_alive() and proc.pid:
            os.kill(proc.pid, signal.SIGKILL)
        proc.join(timeout=10)
        self._alive.discard(wid)

    # -- replay ------------------------------------------------------------

    def replay(self, trace: Trace, faults: Sequence[FaultEvent] = (), *,
               speed: float = 25.0, retry_backoff_s: float = 0.1,
               max_retries: int = 500,
               max_wall_s: float = 240.0) -> ClusterReport:
        """Replay `trace` through the cluster with `faults` injected at
        their virtual times.  Returns when every trace request reached a
        terminal outcome (done / rejected / shed) — including requests
        rerouted off killed workers."""
        if not self._alive:
            raise RuntimeError("cluster not started (use .start() or the "
                               "context manager)")
        vocab = trace.vocab
        arrivals = sorted(trace.requests, key=lambda r: (r.t_arrival, r.rid))
        by_rid = {r.rid: r for r in trace.requests}
        outcomes = {r.rid: Outcome(rid=r.rid, kind=r.kind,
                                   t_arrival=r.t_arrival)
                    for r in trace.requests}
        retry: List[tuple] = []            # (t_due_v, rid)
        owner: Dict[int, int] = {}         # rid -> wid while in flight
        outstanding = {wid: set() for wid in range(self.n_workers)}
        terminal: set = set()
        fault_q = sorted(faults, key=lambda f: (f.t, f.worker))
        kills: List[dict] = []
        t0 = time.perf_counter()

        def now_v() -> float:
            return (time.perf_counter() - t0) * speed

        def route(rid: int, t: float, rerouting: bool = False) -> None:
            if not self._alive:
                raise RuntimeError(
                    f"no workers alive to take rid {rid} "
                    f"({len(terminal)}/{len(outcomes)} terminal)")
            req = by_rid[rid]
            wid = min(self._alive,
                      key=lambda w: (len(outstanding[w]), w))
            owner[rid] = wid
            outstanding[wid].add(rid)
            if rerouting:
                outcomes[rid].retries += 1
            self._req_q[wid].put(("submit", rid,
                                  [int(x) for x in req.prompt(vocab)],
                                  req.max_new_tokens))

        def settle(msg) -> None:
            op = msg[0]
            if op == "accepted":
                _, wid, rid = msg
                if rid not in terminal:
                    outcomes[rid].t_submit = now_v()
            elif op == "done":
                _, wid, rid, toks = msg
                outstanding[wid].discard(rid)
                owner.pop(rid, None)
                if rid in terminal:
                    return  # late duplicate after a reroute race
                out = outcomes[rid]
                out.status = DONE
                out.tokens = [int(t) for t in toks]
                out.t_done = now_v()
                terminal.add(rid)
            elif op == "rejected":
                _, wid, rid, reason, retryable, _message = msg
                outstanding[wid].discard(rid)
                owner.pop(rid, None)
                if rid in terminal:
                    return
                out = outcomes[rid]
                if retryable and out.retries < max_retries:
                    out.retries += 1
                    retry.append((now_v() + retry_backoff_s, rid))
                else:
                    out.status = SHED if retryable else REJECTED
                    out.reason = reason
                    terminal.add(rid)
            elif op == "error":
                raise RuntimeError(f"worker {msg[1]} errored: {msg[2]}")
            # "ready"/"stopped" are lifecycle chatter — ignored here

        def reap(wid: int, t: float, scheduled: Optional[FaultEvent]) -> None:
            """A worker is gone (scheduled kill or crash): drain what it
            already delivered, then reroute everything it still owed."""
            while True:
                msg = self._poll(wid)
                if msg is None:
                    break
                settle(msg)
            orphans = sorted(outstanding[wid] - terminal)
            outstanding[wid].clear()
            kills.append({
                "t": t, "worker": wid, "rerouted": orphans,
                "scheduled": scheduled is not None,
                "note": scheduled.note if scheduled else "unscheduled exit",
            })
            for rid in orphans:
                route(rid, t, rerouting=True)

        i = 0
        while len(terminal) < len(outcomes):
            t = now_v()
            # 1) due faults
            while fault_q and fault_q[0].t <= t:
                ev = fault_q[0]
                if ev.worker not in self._alive:
                    fault_q.pop(0)
                    continue
                if ev.kind == "kill":
                    # wait for in-flight work unless none can ever come
                    work_possible = i < len(arrivals) or bool(retry)
                    if not outstanding[ev.worker] and work_possible:
                        break
                    fault_q.pop(0)
                    self._kill(ev.worker)
                    reap(ev.worker, t, ev)
                else:
                    fault_q.pop(0)
                    self._req_q[ev.worker].put(("fault", ev.kind, ev.arg))
            # 2) unscheduled deaths (crash ≠ kill fault, same recovery)
            for wid in sorted(self._alive):
                if not self._procs[wid].is_alive():
                    self._alive.discard(wid)
                    reap(wid, t, None)
            # 3) due arrivals + retries
            while i < len(arrivals) and arrivals[i].t_arrival <= t:
                route(arrivals[i].rid, t)
                i += 1
            if retry:
                retry.sort()
                while retry and retry[0][0] <= t:
                    _, rid = retry.pop(0)
                    if rid not in terminal:
                        route(rid, t)
            # 4) worker results
            idle = True
            for wid in sorted(self._alive):
                while True:
                    msg = self._poll(wid)
                    if msg is None:
                        break
                    idle = False
                    settle(msg)
            if idle:
                time.sleep(0.002)
            if time.perf_counter() - t0 > max_wall_s:
                n_out = sum(len(s) for s in outstanding.values())
                raise RuntimeError(
                    f"cluster replay exceeded max_wall_s={max_wall_s:g}: "
                    f"{len(terminal)}/{len(outcomes)} terminal, "
                    f"{i}/{len(arrivals)} arrived, {len(retry)} retrying, "
                    f"{n_out} in flight, alive={sorted(self._alive)}")
        return ClusterReport(outcomes=outcomes,
                             wall_s=time.perf_counter() - t0, speed=speed,
                             kills=kills, obs_paths=self.obs_paths)

    def merged(self, by_process: bool = False):
        """(metrics, spans, meta) — the per-worker obs exports folded into
        one job view with `obs --merge` semantics (counters summed,
        histograms bucket-added, gauges per-process; torn final lines
        from killed workers skipped with a `truncated_lines` count)."""
        from ..obs.aggregate import merge_files

        present = [p for p in self.obs_paths if os.path.exists(p)]
        if not present:
            raise FileNotFoundError(
                f"no worker obs exports under {self.out_dir!r} yet")
        return merge_files(present, by_process=by_process)
