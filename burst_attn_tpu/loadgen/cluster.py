"""Multi-process serve cluster: router, fault schedule, recovery proof.

N worker processes (loadgen/worker.py — each its own spawned interpreter
with a single-process CPU JAX runtime and its own obs registry) behind
one in-process router.  The router replays a Trace open-loop: arrivals
route to the least-loaded alive worker, retryable sheds back off
(seeded exponential + jitter, driver.RetryBackoff) and re-route, and a
FAULT SCHEDULE injects failures at virtual times:

  kill     SIGKILL the worker process (no cooperation, no cleanup — the
           real failure mode).  The router reroutes every rid the dead
           worker still owed to surviving workers.  Without
           checkpointing, greedy decode regenerates each rerouted
           request's tokens EXACTLY from scratch; with
           `checkpoint=True` the reroute carries the dead worker's
           journaled token prefix, so the receiving worker RESUMES
           (prompt+prefix prefill, budget reduced) instead of replaying
           — either way `assert_token_exact` gates against the
           single-process oracle.
  hog      force pool exhaustion inside the worker (pages acquired out
           from under admission) — sheds/deferrals must kick in, and
           `unhog` must let the backlog drain (bounded recovery).
  stall    freeze the worker's engine loop for S seconds (delayed-retire
           / GC-pause stand-in); queued work must survive untouched —
           and the worker still drains its queue, so heartbeat pings are
           answered: a stall must NOT trip the failure detector.
  hang     wedge the worker's WHOLE loop (no stepping, no queue drain,
           no pong) while the process stays alive — invisible to the
           liveness poll, detectable only by the heartbeat detector.
  restart  (requires `checkpoint=True`) SIGKILL the worker, then spawn a
           REPLACEMENT for the same wid that restores from the dead
           life's snapshot + journal (`recover_engine`) and finishes its
           claimed requests itself; orphans the replacement does not
           claim are rerouted from scratch.

Failure detection is two-layered: a passive liveness poll (a dead
process is reaped next tick) and an active HEARTBEAT detector — the
router pings every alive worker each `hb_interval_s` wall seconds, and a
worker silent for `hb_timeout_s` is declared dead (SIGKILL + reap,
`detected_by: "heartbeat"`), which is what catches hangs and wedges that
never exit.  hb_timeout_s defaults generous: a worker blocks on its
first jit compile without draining its queue, and that must not read as
death.

Wire-safety note: router<->worker messages travel as CRC-framed
transport frames (fleet/transport.py, QueueTransport over the spawn
queues — the identical protocol the socket fleet ships cross-host), so
every delivered message is integrity-checked, and the frames stay small
(a done record for a canary request is well under PIPE_BUF = 4096
bytes; resume prefixes are bounded by max_new_tokens) so kernel pipe
writes are atomic and a SIGKILL cannot tear a frame mid-message; each
worker also gets its OWN result queue so a dead worker's stream never
interleaves with a live one's.  Torn-write hazards that DO exist — a
kill mid `export_jsonl` or mid journal append — land in files whose
readers (`obs.aggregate.load_records_tolerant`,
`checkpoint.read_journal`) are torn-tail tolerant by contract.

Every worker exports obs JSONL snapshots (`obs_w{wid}.jsonl`; restart
replacements get generation-suffixed files so a dead life's last export
survives the merge), all tagged process_index=wid; `merged()` folds them
into the one job-level view (`obs --merge` semantics) that
loadgen/slo.py evaluates.
"""

import multiprocessing as mp
import os
import signal
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..fleet.transport import QueueTransport, TransportError
from .driver import DONE, REJECTED, SHED, Outcome, ReplayReport, RetryBackoff
from .trace import Trace
from .worker import worker_main

FAULT_KINDS = ("kill", "hog", "unhog", "stall", "hang", "restart",
               "raise")


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: at virtual time `t`, do `kind` to `worker`.

    `kill`/`restart` wait until the target holds at least one in-flight
    request (a kill that lands on an idle worker proves nothing about
    recovery) — and, with checkpointing enabled, until the target's
    journal shows at least one generated token for it (a kill before any
    token is durable proves nothing about RESUME-vs-replay); if the
    trace drains first, they fire anyway so the schedule always
    executes.  `arg`: pages to hog / stall seconds."""

    t: float
    kind: str
    worker: int
    arg: float = 0.0
    note: str = ""

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {FAULT_KINDS})")


def random_fault_schedule(seed: int, *, n_workers: int, t_max: float,
                          kinds: Sequence[str] = ("kill",),
                          n_events: int = 1,
                          arg: float = 0.0) -> List[FaultEvent]:
    """Deterministic random fault schedule for fuzzing: `n_events` faults
    drawn from `kinds` at uniform times in [0, t_max) on uniform workers.
    Same seed -> same schedule (numpy seed-sequence)."""
    rng = np.random.default_rng(int(seed))
    events = []
    for j in range(n_events):
        events.append(FaultEvent(
            t=float(rng.uniform(0.0, t_max)),
            kind=str(kinds[int(rng.integers(len(kinds)))]),
            worker=int(rng.integers(n_workers)), arg=arg,
            note=f"fuzz seed={seed} event={j}"))
    return sorted(events, key=lambda f: (f.t, f.worker))


@dataclass
class ClusterReport(ReplayReport):
    """ReplayReport plus the fault/recovery evidence the tests gate on:
    each kill records WHO died, HOW the death was detected
    (`detected_by`: liveness | heartbeat | scheduled fault), WHAT was
    rerouted or reclaimed, and the virtual time by which every such
    request completed.  `recovered_tokens_replayed` /
    `recovered_tokens_resumed` are the router-side recovery ledger
    (mirroring the workers' serve.recovered_tokens_* counters): tokens
    recoveries re-decoded vs tokens carried over without re-decoding —
    the acceptance gate asserts replayed(resume on) <
    replayed(resume off) on the same trace + fault schedule."""

    kills: List[dict] = field(default_factory=list)
    obs_paths: List[str] = field(default_factory=list)
    recovered_tokens_replayed: int = 0
    recovered_tokens_resumed: int = 0

    def recovery_s(self) -> List[float]:
        """Per-fault recovery spans (virtual): last rerouted/reclaimed
        completion minus fault time; faults that orphaned nothing
        contribute 0."""
        out = []
        for k in self.kills:
            ts = [self.outcomes[rid].t_done for rid in k["rerouted"]
                  if self.outcomes[rid].t_done is not None]
            out.append(max(ts) - k["t"] if ts else 0.0)
        return out


class LoadGenCluster:
    """Spawn, replay, stop.  Use as a context manager — __exit__ always
    reaps worker processes, even when replay raised.

    `checkpoint=True` turns on the crash-consistency layer
    (serving/checkpoint.py): every worker runs with a write-ahead token
    journal and snapshots its engine every `checkpoint_every`
    completions; reroutes then RESUME from the dead worker's journal
    (`resume=False` keeps journaling but replays rerouted requests from
    scratch — the accounting baseline), and the `restart` fault kind
    becomes available."""

    def __init__(self, model_spec: dict, engine_spec: dict, *,
                 n_workers: int, out_dir: str, export_every: int = 4,
                 start_timeout_s: float = 180.0, checkpoint: bool = False,
                 resume: bool = True, checkpoint_every: int = 2,
                 hb_interval_s: float = 0.5, hb_timeout_s: float = 60.0,
                 restart_timeout_s: float = 180.0):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.model_spec = dict(model_spec)
        self.engine_spec = dict(engine_spec)
        self.n_workers = n_workers
        self.out_dir = out_dir
        self.export_every = export_every
        self.start_timeout_s = start_timeout_s
        self.checkpoint = checkpoint
        self.resume = resume
        self.checkpoint_every = checkpoint_every
        self.hb_interval_s = hb_interval_s
        self.hb_timeout_s = hb_timeout_s
        self.restart_timeout_s = restart_timeout_s
        self._ctx = mp.get_context("spawn")
        self._procs: Dict[int, mp.Process] = {}
        self._req_q: Dict[int, object] = {}
        self._res_q: Dict[int, object] = {}
        self._tr: Dict[int, QueueTransport] = {}
        self._alive: set = set()
        self.worker_errors: List[tuple] = []   # (wid, message) from stop()
        self._gen: Dict[int, int] = {}       # wid -> restart generation
        self._obs_files: List[str] = []

    # -- lifecycle ---------------------------------------------------------

    def obs_path(self, wid: int) -> str:
        """Current-generation obs export path for `wid` (restart
        replacements write their own file so the dead life's export
        survives the merge)."""
        gen = self._gen.get(wid, 0)
        suffix = f"g{gen}" if gen else ""
        return os.path.join(self.out_dir, f"obs_w{wid}{suffix}.jsonl")

    def journal_path(self, wid: int) -> str:
        return os.path.join(self.out_dir, f"journal_w{wid}.jsonl")

    def snapshot_path(self, wid: int) -> str:
        return os.path.join(self.out_dir, f"ckpt_w{wid}.npz")

    @property
    def obs_paths(self) -> List[str]:
        """Every obs export path any worker life has written to."""
        return list(self._obs_files)

    def _spawn(self, wid: int, restore: bool = False) -> None:
        """Start (or, with restore=True, re-start from checkpoint) one
        worker process with fresh queues — stale submits in a dead
        worker's request queue must not replay into its replacement."""
        if restore:
            self._gen[wid] = self._gen.get(wid, 0) + 1
        path = self.obs_path(wid)
        if not restore:
            for stale in (path, self.journal_path(wid),
                          self.snapshot_path(wid)):
                if os.path.exists(stale):
                    os.remove(stale)  # stale state would pollute recovery
        if path not in self._obs_files:
            self._obs_files.append(path)
        ckpt_spec = None
        if self.checkpoint:
            ckpt_spec = {"journal": self.journal_path(wid),
                         "snapshot": self.snapshot_path(wid),
                         "every": self.checkpoint_every,
                         "resume": self.resume, "restore": restore}
        self._req_q[wid] = self._ctx.Queue()
        self._res_q[wid] = self._ctx.Queue()
        self._tr[wid] = QueueTransport(send_q=self._req_q[wid],
                                       recv_q=self._res_q[wid])
        proc = self._ctx.Process(
            target=worker_main,
            args=(wid, self.model_spec, self.engine_spec, path,
                  self._req_q[wid], self._res_q[wid], self.export_every,
                  ckpt_spec),
            daemon=True, name=f"loadgen-worker-{wid}")
        proc.start()
        self._procs[wid] = proc

    def start(self) -> None:
        # spawned children import the package (and therefore jax) BEFORE
        # worker_main runs, so the CPU pin must ride in via the inherited
        # environment — a TPU host must never hand its chips to workers
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.makedirs(self.out_dir, exist_ok=True)
        for wid in range(self.n_workers):
            self._spawn(wid)
        deadline = time.monotonic() + self.start_timeout_s
        waiting = set(range(self.n_workers))
        while waiting:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"workers {sorted(waiting)} not ready within "
                    f"{self.start_timeout_s:g}s")
            for wid in sorted(waiting):
                msg = self._poll(wid)
                if msg is None:
                    continue
                if msg[0] == "ready":
                    waiting.discard(wid)
                    self._alive.add(wid)
                elif msg[0] == "error":
                    raise RuntimeError(f"worker {wid} failed to start: "
                                       f"{msg[2]}")
            time.sleep(0.01)

    def __enter__(self) -> "LoadGenCluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self, timeout_s: float = 60.0) -> None:
        """Graceful where possible (workers flush a final obs export),
        SIGKILL where not.  Idempotent."""
        for wid in sorted(self._alive):
            try:
                self._send(wid, ("stop",))
            except TransportError:
                self._alive.discard(wid)
        deadline = time.monotonic() + timeout_s
        pending = set(self._alive)
        while pending and time.monotonic() < deadline:
            for wid in sorted(pending):
                alive = self._procs[wid].is_alive()
                msg = self._poll(wid)
                if msg is None:
                    if not alive:
                        pending.discard(wid)
                    continue
                if msg[0] == "stopped":
                    pending.discard(wid)
                elif msg[0] == "error":
                    # a worker erroring DURING stop still reports — its
                    # error frame is evidence, not noise (satellite: the
                    # old loop dropped these on the floor)
                    self.worker_errors.append((wid, msg[2]))
            time.sleep(0.01)
        # final drain: a worker that flushed its error frame and died
        # before we polled must not lose it to the terminate below
        for wid in sorted(self._tr):
            while True:
                msg = self._poll(wid)
                if msg is None:
                    break
                if msg[0] == "error":
                    self.worker_errors.append((wid, msg[2]))
        for wid, proc in self._procs.items():
            if proc.is_alive():
                proc.terminate()
            proc.join(timeout=10)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=10)
        self._alive.clear()

    # -- plumbing ----------------------------------------------------------

    def _poll(self, wid: int):
        return self._tr[wid].recv()

    def _send(self, wid: int, msg) -> None:
        self._tr[wid].send(msg)

    def inject_fault(self, wid: int, kind: str, arg: float = 0.0) -> None:
        """Send one fault message outside a replay schedule (tests use
        this to provoke shutdown races, e.g. kind="raise" then stop())."""
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        self._send(wid, ("fault", kind, arg))

    def _kill(self, wid: int) -> None:
        proc = self._procs[wid]
        if proc.is_alive() and proc.pid:
            os.kill(proc.pid, signal.SIGKILL)
        proc.join(timeout=10)
        self._alive.discard(wid)

    def _journal_resume_map(self, wid: int) -> Dict[int, List[int]]:
        """router rid -> journaled tokens from a DEAD worker's journal.
        OSError (no journal yet — killed before the first sync) is an
        empty map; a corrupt non-final line stays loud (ValueError)."""
        from ..serving.checkpoint import journal_tokens_by_ext

        try:
            return journal_tokens_by_ext(self.journal_path(wid))
        except OSError:
            return {}

    def _journal_has_progress(self, wid: int, rids) -> bool:
        """True when the LIVE worker's journal holds >= 1 token for one
        of `rids` that the journal does NOT already prove complete (read
        tolerantly: the worker may be mid-append; an unreadable/torn
        journal just means 'not yet').  The completeness check matters
        for arming kills: the journal is fsynced AHEAD of the done
        message (step() syncs before delivering), so a rid with tokens
        but no done record is guaranteed still mid-decode at file-read
        time — arming on it kills genuinely in-flight work, never a
        request whose done is merely still in the result queue."""
        from ..serving.checkpoint import journal_view

        try:
            view = journal_view(self.journal_path(wid))
        except (OSError, ValueError):
            return False
        for erid, sub in view.submits.items():
            ext = int(sub["ext"])
            toks = view.tokens.get(erid, [])
            if (ext in rids and toks and erid not in view.done
                    and len(toks) < int(sub["max_new"])):
                return True
        return False

    # -- replay ------------------------------------------------------------

    def replay(self, trace: Trace, faults: Sequence[FaultEvent] = (), *,
               speed: float = 25.0, retry_backoff_s: float = 0.1,
               max_retries: int = 500, max_wall_s: float = 240.0,
               backoff: Optional[RetryBackoff] = None) -> ClusterReport:
        """Replay `trace` through the cluster with `faults` injected at
        their virtual times.  Returns when every trace request reached a
        terminal outcome (done / rejected / shed) — including requests
        rerouted off killed workers and requests reclaimed by restarted
        replacements."""
        if not self._alive:
            raise RuntimeError("cluster not started (use .start() or the "
                               "context manager)")
        if any(f.kind == "restart" for f in faults) and not self.checkpoint:
            raise ValueError("the 'restart' fault requires checkpoint=True "
                             "(a replacement can only restore from a "
                             "checkpoint+journal)")
        bo = backoff if backoff is not None else RetryBackoff(
            base_s=retry_backoff_s,
            cap_s=max(retry_backoff_s * 40, 2.0))
        vocab = trace.vocab
        arrivals = sorted(trace.requests, key=lambda r: (r.t_arrival, r.rid))
        by_rid = {r.rid: r for r in trace.requests}
        outcomes = {r.rid: Outcome(rid=r.rid, kind=r.kind,
                                   t_arrival=r.t_arrival)
                    for r in trace.requests}
        retry: List[tuple] = []            # (t_due_v, rid)
        deferred: List[tuple] = []         # (rid, resume_toks|None): no
        #                                    capacity right now (restarting)
        owner: Dict[int, int] = {}         # rid -> wid while in flight
        outstanding = {wid: set() for wid in range(self.n_workers)}
        terminal: set = set()
        fault_q = sorted(faults, key=lambda f: (f.t, f.worker))
        kills: List[dict] = []
        restarting: Dict[int, dict] = {}   # wid -> pending-replacement state
        recov = {"replayed": 0, "resumed": 0}
        last_pong = {wid: time.monotonic() for wid in self._alive}
        hb_seq = 0
        last_hb = time.monotonic()
        t0 = time.perf_counter()

        def now_v() -> float:
            return (time.perf_counter() - t0) * speed

        def route(rid: int, t: float, rerouting: bool = False,
                  resume_toks: Optional[List[int]] = None) -> bool:
            """Send rid to the least-loaded alive worker; False when no
            worker can take it RIGHT NOW (all capacity is mid-restart —
            the caller defers and retries next tick)."""
            if not self._alive:
                if restarting:
                    return False
                raise RuntimeError(
                    f"no workers alive to take rid {rid} "
                    f"({len(terminal)}/{len(outcomes)} terminal)")
            req = by_rid[rid]
            wid = min(self._alive,
                      key=lambda w: (len(outstanding[w]), w))
            owner[rid] = wid
            outstanding[wid].add(rid)
            if rerouting:
                outcomes[rid].retries += 1
            msg = ("submit", rid, [int(x) for x in req.prompt(vocab)],
                   req.max_new_tokens)
            if resume_toks:
                msg = msg + ([int(x) for x in resume_toks],)
            self._send(wid, msg)
            return True

        def settle(msg) -> None:
            op = msg[0]
            if op == "accepted":
                _, wid, rid = msg
                if rid not in terminal:
                    outcomes[rid].t_submit = now_v()
            elif op == "done":
                _, wid, rid, toks = msg
                outstanding.get(wid, set()).discard(rid)
                owner.pop(rid, None)
                if rid in terminal:
                    return  # late duplicate after a reroute race
                out = outcomes[rid]
                out.status = DONE
                out.tokens = [int(t) for t in toks]
                out.t_done = now_v()
                terminal.add(rid)
            elif op == "rejected":
                _, wid, rid, reason, retryable, _message = msg
                outstanding.get(wid, set()).discard(rid)
                owner.pop(rid, None)
                if rid in terminal:
                    return
                out = outcomes[rid]
                if retryable and out.retries < max_retries:
                    out.retries += 1
                    retry.append((now_v() + bo.delay(rid, out.retries), rid))
                else:
                    out.status = SHED if retryable else REJECTED
                    out.reason = reason
                    terminal.add(rid)
            elif op == "pong":
                last_pong[msg[1]] = time.monotonic()
            elif op == "error":
                raise RuntimeError(f"worker {msg[1]} errored: {msg[2]}")
            # "ready"/"restored"/"stopped" are lifecycle chatter — the
            # start()/restart paths consume them; ignored here

        def reap(wid: int, t: float, scheduled: Optional[FaultEvent],
                 detected: str = "liveness") -> None:
            """A worker is gone (scheduled kill, crash, or heartbeat
            verdict): drain what it already delivered, then reroute
            everything it still owed — with its journaled token prefixes
            when checkpointing, so receivers resume instead of replay."""
            while True:
                msg = self._poll(wid)
                if msg is None:
                    break
                settle(msg)
            orphans = sorted(outstanding[wid] - terminal)
            outstanding[wid].clear()
            resume_map = (self._journal_resume_map(wid)
                          if self.checkpoint else {})
            kills.append({
                "t": t, "worker": wid, "rerouted": orphans,
                "scheduled": scheduled is not None, "detected_by": detected,
                "note": scheduled.note if scheduled else "unscheduled exit",
            })
            for rid in orphans:
                toks = resume_map.get(rid) or None
                if toks:
                    recov["resumed" if self.resume
                          else "replayed"] += len(toks)
                if not route(rid, t, rerouting=True, resume_toks=toks):
                    deferred.append((rid, toks))

        def fire_restart(ev: FaultEvent, t: float) -> None:
            """Kill + replace: the replacement restores from the dead
            life's snapshot+journal and claims its work itself; the
            router holds the orphans until "restored"/"ready" arrive."""
            self._kill(ev.worker)
            while True:
                msg = self._poll(ev.worker)
                if msg is None:
                    break
                settle(msg)
            orphans = sorted(outstanding[ev.worker] - terminal)
            outstanding[ev.worker].clear()
            self._spawn(ev.worker, restore=True)
            restarting[ev.worker] = {
                "deadline": time.monotonic() + self.restart_timeout_s,
                "orphans": orphans, "t": t, "note": ev.note,
                "restored": None, "ready": False,
            }

        def poll_restarting(t: float) -> None:
            for wid in sorted(restarting):
                st = restarting[wid]
                while True:
                    msg = self._poll(wid)
                    if msg is None:
                        break
                    if msg[0] == "restored":
                        st["restored"] = msg[2]
                    elif msg[0] == "ready":
                        st["ready"] = True
                    else:
                        settle(msg)  # journal-complete dones land here
                if st["ready"]:
                    info = st["restored"] or {}
                    recov["replayed"] += sum(
                        int(v) for v in (info.get("replayed") or {}).values())
                    recov["resumed"] += sum(
                        int(v) for v in (info.get("resumed") or {}).values())
                    claimed = {int(r) for r in info.get("claimed", [])}
                    self._alive.add(wid)
                    last_pong[wid] = time.monotonic()
                    for rid in sorted(claimed):
                        if rid not in terminal:
                            outstanding[wid].add(rid)
                            owner[rid] = wid
                    kills.append({
                        "t": st["t"], "worker": wid,
                        "rerouted": sorted(st["orphans"]),
                        "scheduled": True, "restarted": True,
                        "detected_by": "scheduled-restart",
                        "note": st["note"],
                    })
                    # anything the dead life owed that the replacement did
                    # not claim (e.g. submitted but never journaled) goes
                    # back through normal routing from scratch
                    for rid in sorted(set(st["orphans"]) - claimed):
                        if rid not in terminal \
                                and not route(rid, t, rerouting=True):
                            deferred.append((rid, None))
                    del restarting[wid]
                elif time.monotonic() > st["deadline"]:
                    raise RuntimeError(
                        f"restarted worker {wid} not ready within "
                        f"{self.restart_timeout_s:g}s")

        i = 0
        while len(terminal) < len(outcomes):
            t = now_v()
            # 1) due faults
            while fault_q and fault_q[0].t <= t:
                ev = fault_q[0]
                if ev.worker not in self._alive \
                        and ev.worker not in restarting:
                    fault_q.pop(0)
                    continue
                if ev.worker in restarting:
                    break  # re-evaluate once the replacement is up
                if ev.kind in ("kill", "restart"):
                    # wait for in-flight work — and, with checkpointing,
                    # for >= 1 durably journaled token (a pre-progress kill
                    # proves nothing about resume-vs-replay) — unless no
                    # work can ever come.  Settle what the target already
                    # delivered first: a done sitting in its result queue
                    # would otherwise arm the fault against a request
                    # that is no longer in flight.
                    while True:
                        msg = self._poll(ev.worker)
                        if msg is None:
                            break
                        settle(msg)
                    work_possible = (i < len(arrivals) or bool(retry)
                                     or bool(deferred))
                    armed = bool(outstanding[ev.worker])
                    if armed and self.checkpoint:
                        armed = self._journal_has_progress(
                            ev.worker, outstanding[ev.worker])
                    if not armed and work_possible:
                        break
                    fault_q.pop(0)
                    if ev.kind == "restart":
                        fire_restart(ev, t)
                    else:
                        self._kill(ev.worker)
                        reap(ev.worker, t, ev, detected="scheduled-kill")
                else:
                    fault_q.pop(0)
                    self._send(ev.worker, ("fault", ev.kind, ev.arg))
            # 2) unscheduled deaths (crash ≠ kill fault, same recovery)
            for wid in sorted(self._alive):
                if not self._procs[wid].is_alive():
                    self._alive.discard(wid)
                    reap(wid, t, None)
            # 2b) replacements coming up
            if restarting:
                poll_restarting(t)
            # 2c) heartbeat failure detector: ping every alive worker each
            # hb_interval_s; a worker silent past hb_timeout_s is declared
            # dead even though its process is still running (hang/wedge)
            now_w = time.monotonic()
            if now_w - last_hb >= self.hb_interval_s:
                last_hb = now_w
                hb_seq += 1
                for wid in sorted(self._alive):
                    try:
                        self._send(wid, ("ping", hb_seq))
                    except TransportError:
                        pass  # dying worker; the liveness reap covers it
                for wid in sorted(self._alive):
                    if now_w - last_pong.get(wid, now_w) > self.hb_timeout_s:
                        self._kill(wid)
                        reap(wid, t, None, detected="heartbeat")
            # 3) due arrivals + retries + deferred reroutes
            if deferred and self._alive:
                still = []
                for rid, toks in deferred:
                    if rid in terminal:
                        continue
                    if not route(rid, t, rerouting=True, resume_toks=toks):
                        still.append((rid, toks))
                deferred[:] = still
            while i < len(arrivals) and arrivals[i].t_arrival <= t:
                if not route(arrivals[i].rid, t):
                    break  # all capacity mid-restart; retry next tick
                i += 1
            if retry:
                retry.sort()
                while retry and retry[0][0] <= t:
                    if not self._alive:
                        break
                    _, rid = retry.pop(0)
                    if rid not in terminal:
                        if not route(rid, t):
                            deferred.append((rid, None))
            # 4) worker results
            idle = True
            for wid in sorted(self._alive):
                while True:
                    msg = self._poll(wid)
                    if msg is None:
                        break
                    idle = False
                    settle(msg)
            if idle:
                time.sleep(0.002)
            if time.perf_counter() - t0 > max_wall_s:
                n_out = sum(len(s) for s in outstanding.values())
                raise RuntimeError(
                    f"cluster replay exceeded max_wall_s={max_wall_s:g}: "
                    f"{len(terminal)}/{len(outcomes)} terminal, "
                    f"{i}/{len(arrivals)} arrived, {len(retry)} retrying, "
                    f"{len(deferred)} deferred, {n_out} in flight, "
                    f"alive={sorted(self._alive)}, "
                    f"restarting={sorted(restarting)}")
        # the trace can drain before a replacement finishes booting (the
        # dead life delivered its last done in the same tick it was
        # killed, so the restart held no orphans) — wait it out anyway:
        # the kills ledger entry and recovered-token accounting are part
        # of the report, and returning mid-boot would let stop() kill a
        # half-started process.  poll_restarting raises past the
        # restart_timeout_s deadline, so this cannot spin forever.
        while restarting:
            poll_restarting(now_v())
            if restarting:
                time.sleep(0.01)
        return ClusterReport(outcomes=outcomes,
                             wall_s=time.perf_counter() - t0, speed=speed,
                             kills=kills, obs_paths=self.obs_paths,
                             recovered_tokens_replayed=recov["replayed"],
                             recovered_tokens_resumed=recov["resumed"])

    def merged(self, by_process: bool = False):
        """(metrics, spans, meta) — the per-worker obs exports folded into
        one job view with `obs --merge` semantics (counters summed,
        histograms bucket-added, gauges per-process; torn final lines
        from killed workers skipped with a `truncated_lines` count)."""
        from ..obs.aggregate import merge_files

        present = [p for p in self.obs_paths if os.path.exists(p)]
        if not present:
            raise FileNotFoundError(
                f"no worker obs exports under {self.out_dir!r} yet")
        return merge_files(present, by_process=by_process)
