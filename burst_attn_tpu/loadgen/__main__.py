"""CLI: synthesize traces, replay them, evaluate SLOs.

    python -m burst_attn_tpu.loadgen gen --out results/traces/t.jsonl \
        --n 64 --seed 0 [--vocab 97] [--poison-rate 0.05] ...
    python -m burst_attn_tpu.loadgen replay --trace results/traces/t.jsonl \
        [--workers 2] [--speed 25] [--out-dir results/loadgen]
    python -m burst_attn_tpu.loadgen slo --obs 'results/loadgen/obs_w*.jsonl' \
        --duration-s 5.0

`replay --workers 1` uses the in-process driver; `--workers N>1` spins
up the fault-injection cluster (without faults — faults are a harness
API, scheduled from tests/benches, not flags).  Replay always checks the
completed tokens against the single-process oracle and exits 1 on any
corruption, 0 otherwise.
"""

import argparse
import json
import sys


def _cmd_gen(args) -> int:
    from .trace import save_trace, synthesize_trace

    trace = synthesize_trace(
        args.n, seed=args.seed, vocab=args.vocab,
        mean_interarrival_s=args.mean_interarrival_s,
        burst_factor=args.burst_factor, poison_rate=args.poison_rate,
        prompt_len_max=args.prompt_len_max, max_new_max=args.max_new_max,
        label=args.label)
    path = save_trace(trace, args.out)
    print(f"loadgen: wrote {len(trace.requests)} requests "
          f"({trace.duration_s:.3f} virtual s) to {path}")
    return 0


def _default_specs(vocab: int):
    model_spec = dict(vocab=vocab, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_head=16, d_ff=128, block_q=8,
                      block_kv=8, seed=0)
    engine_spec = dict(kind="ragged", slots=4, n_pages=6, page=128,
                       max_pages_per_seq=2, chunk=16, max_queue=32)
    return model_spec, engine_spec


def _cmd_replay(args) -> int:
    from .driver import assert_token_exact, oracle_replay, replay_trace
    from .trace import load_trace
    from .worker import build_engine

    trace = load_trace(args.trace)
    model_spec, engine_spec = _default_specs(trace.vocab)
    oracle_spec = dict(engine_spec, max_queue=None)
    if args.workers <= 1:
        eng = build_engine(model_spec, engine_spec)
        report = replay_trace(eng, trace, speed=args.speed)
    else:
        from .cluster import LoadGenCluster

        with LoadGenCluster(model_spec, engine_spec,
                            n_workers=args.workers,
                            out_dir=args.out_dir) as cluster:
            report = cluster.replay(trace, speed=args.speed)
    print(f"loadgen: {report.n_done} done, {report.n_rejected} rejected, "
          f"{report.n_shed} shed in {report.wall_s:.2f}s wall "
          f"(speed {report.speed:g})")
    oracle = oracle_replay(trace,
                           lambda: build_engine(model_spec, oracle_spec))
    try:
        assert_token_exact(report.completed(), oracle)
    except AssertionError as e:
        print(f"loadgen: {e}", file=sys.stderr)
        return 1
    print("loadgen: token-exact vs single-process oracle")
    return 0


def _cmd_slo(args) -> int:
    from ..obs.aggregate import merge_files
    from .slo import compute_slo, format_slo

    metrics, _spans, _meta = merge_files(args.obs)
    report = compute_slo(metrics, duration_s=args.duration_s)
    if args.as_json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(format_slo(report))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m burst_attn_tpu.loadgen")
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("gen", help="synthesize a replayable trace")
    g.add_argument("--out", required=True)
    g.add_argument("--n", type=int, default=64)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--vocab", type=int, default=97)
    g.add_argument("--mean-interarrival-s", type=float, default=0.05)
    g.add_argument("--burst-factor", type=float, default=8.0)
    g.add_argument("--poison-rate", type=float, default=0.0)
    g.add_argument("--prompt-len-max", type=int, default=64)
    g.add_argument("--max-new-max", type=int, default=48)
    g.add_argument("--label", default="cli")
    g.set_defaults(fn=_cmd_gen)

    r = sub.add_parser("replay", help="replay a trace (driver or cluster) "
                                      "and verify token-exactness")
    r.add_argument("--trace", required=True)
    r.add_argument("--workers", type=int, default=1)
    r.add_argument("--speed", type=float, default=25.0)
    r.add_argument("--out-dir", default="results/loadgen")
    r.set_defaults(fn=_cmd_replay)

    s = sub.add_parser("slo", help="SLO report from merged obs exports")
    s.add_argument("--obs", action="append", required=True, metavar="GLOB")
    s.add_argument("--duration-s", type=float, required=True)
    s.add_argument("--json", action="store_true", dest="as_json")
    s.set_defaults(fn=_cmd_slo)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
