"""loadgen: trace-replay load harness, fault injection, SLO gates.

Production-serve hardening for the serving stack (ISSUE 9 / ROADMAP
item 5), in four layers:

  trace    deterministic workload model — seeded ragged/bursty/poison
           traces serialized as replayable JSONL (loadgen/trace.py)
  driver   open-loop replay against one engine + the single-process
           token oracle and the zero-corruption diff (loadgen/driver.py)
  cluster  N spawned CPU serve workers behind a router with first-class
           fault injection (kill / pool-hog / stall / hang / restart), a
           heartbeat failure detector, journal-aware resume rerouting,
           and merged obs (loadgen/cluster.py, loadgen/worker.py)
  slo      p50/p99 TTFT + token latency, goodput, shed-rate, per-fault
           recovery percentiles from the merged export; Objectives
           pass/fail (loadgen/slo.py)

CLI: python -m burst_attn_tpu.loadgen {gen,replay,slo} ...
Docs: docs/loadgen.md
"""

from .cluster import (
    ClusterReport, FaultEvent, LoadGenCluster, random_fault_schedule,
)
from .driver import (
    Outcome, ReplayReport, RetryBackoff, assert_token_exact, diff_tokens,
    oracle_replay, replay_trace,
)
from .slo import Objectives, compute_slo, evaluate, format_slo, \
    recovery_stats
from .trace import Trace, TraceRequest, load_trace, save_trace, \
    synthesize_trace

__all__ = [
    "ClusterReport", "FaultEvent", "LoadGenCluster", "Objectives",
    "Outcome", "ReplayReport", "RetryBackoff", "Trace", "TraceRequest",
    "assert_token_exact", "compute_slo", "diff_tokens", "evaluate",
    "format_slo", "load_trace", "oracle_replay", "random_fault_schedule",
    "recovery_stats", "replay_trace", "save_trace", "synthesize_trace",
]
