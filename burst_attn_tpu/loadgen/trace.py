"""Deterministic serve-workload traces: synthesize, serialize, replay.

A trace is the unit of serve-hardening evidence: a SEEDED, wall-clock-free
description of heavy traffic (ragged prompt/output lengths, bursty
arrivals, a sprinkling of poison requests) that replays byte-identically
anywhere — the single-process oracle, the multi-process cluster, and a CI
lane three months from now all see the same requests at the same virtual
times.  Determinism rules:

  * every sampled quantity comes from ONE `np.random.default_rng(seed)`
    stream in a fixed draw order — same seed, same trace, bit-for-bit;
  * prompts are NOT stored as tokens: each request carries a
    `prompt_seed` and regenerates its tokens on demand (`prompt()`), so
    a million-token trace file stays kilobytes and the oracle can never
    see different tokens than the cluster;
  * arrival times are virtual seconds from trace start — the replayers
    (loadgen/driver.py, loadgen/cluster.py) map them to wall time with a
    `speed` factor; nothing in this module reads a clock.

Arrival model: a two-state Markov-modulated process (calm | burst).  The
state flips ahead of each arrival (`p_enter_burst` / `p_exit_burst`), and
interarrival gaps are exponential at the calm rate or `burst_factor`×
faster inside a burst — the clumpy, overdispersed arrivals (CV > 1) that
actually stress admission control, rather than a smooth Poisson stream.

Poison requests model malformed traffic the engines must reject without
taking a worker down: empty prompts, zero budgets, and prompts too large
for any pool (`poison-oversize`).

Serialized form (JSONL, `results/traces/*.jsonl`): one `trace-meta`
header line with the full synthesis recipe, then one `trace-request`
line per request.  `load_trace` is strict — a trace is CI input, not
best-effort telemetry.
"""

import json
import os
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

TRACE_VERSION = 1

REQUEST_KINDS = ("normal", "poison-empty", "poison-budget",
                 "poison-oversize", "shared_prefix")
POISON_KINDS = tuple(k for k in REQUEST_KINDS if k.startswith("poison"))


@dataclass(frozen=True)
class TraceRequest:
    """One replayable request: WHEN it arrives and WHAT it asks for."""

    rid: int
    t_arrival: float            # virtual seconds from trace start
    prompt_len: int
    prompt_seed: int            # tokens regenerate from this (see prompt())
    max_new_tokens: int
    kind: str = "normal"        # REQUEST_KINDS
    # shared_prefix requests: the first `overlap_len` tokens regenerate
    # from `template_seed` (drawn from a small per-trace template pool),
    # the remaining prompt_len - overlap_len from prompt_seed — every
    # request on the same template shares a bit-identical prefix, which
    # is what the serving prefix cache hits on
    template_seed: int = -1
    overlap_len: int = 0

    @property
    def poison(self) -> bool:
        return self.kind in POISON_KINDS

    def prompt(self, vocab: int) -> np.ndarray:
        """The request's tokens, regenerated deterministically — every
        replayer and the oracle derive the identical [prompt_len] int32
        array from (prompt_seed, prompt_len, vocab) — plus
        (template_seed, overlap_len) for shared_prefix requests."""
        if self.prompt_len <= 0:
            return np.zeros((0,), np.int32)
        if self.kind == "shared_prefix" and self.overlap_len > 0:
            tmpl = np.random.default_rng(self.template_seed).integers(
                1, vocab, size=self.overlap_len)
            tail = np.random.default_rng(self.prompt_seed).integers(
                1, vocab, size=self.prompt_len - self.overlap_len)
            return np.concatenate([tmpl, tail]).astype(np.int32)
        rng = np.random.default_rng(self.prompt_seed)
        return rng.integers(1, vocab, size=self.prompt_len).astype(np.int32)


@dataclass
class Trace:
    """A meta header (the synthesis recipe) + arrival-ordered requests."""

    meta: Dict[str, object]
    requests: List[TraceRequest] = field(default_factory=list)

    @property
    def vocab(self) -> int:
        return int(self.meta["vocab"])

    @property
    def duration_s(self) -> float:
        """Virtual span from trace start to the last arrival."""
        return max((r.t_arrival for r in self.requests), default=0.0)

    def normal(self) -> List[TraceRequest]:
        return [r for r in self.requests if not r.poison]

    def prompts(self) -> Dict[int, np.ndarray]:
        return {r.rid: r.prompt(self.vocab) for r in self.requests}


def synthesize_trace(
    n_requests: int,
    *,
    seed: int,
    vocab: int,
    mean_interarrival_s: float = 0.05,
    burst_factor: float = 8.0,
    p_enter_burst: float = 0.15,
    p_exit_burst: float = 0.35,
    prompt_len_log_mean: float = 2.5,
    prompt_len_log_sigma: float = 0.6,
    prompt_len_min: int = 1,
    prompt_len_max: int = 64,
    max_new_mean: float = 12.0,
    max_new_min: int = 1,
    max_new_max: int = 48,
    poison_rate: float = 0.0,
    oversize_len: int = 100_000,
    shared_fraction: float = 0.0,
    n_templates: int = 4,
    template_len: int = 256,
    label: str = "synthetic",
) -> Trace:
    """Seeded workload synthesis (see the module docstring for the
    models).  Prompt lengths are clipped lognormal (ragged, heavy-ish
    tail), decode budgets clipped geometric, arrivals Markov-modulated
    exponential.  No wall-clock, no global RNG — the same call is the
    same trace forever.

    `shared_fraction` > 0 turns that fraction of normal requests into
    `shared_prefix` requests: each picks one of `n_templates` seeded
    templates and prepends its `template_len` tokens to the privately
    drawn suffix (so its total prompt is template + lognormal tail).
    Guarded draws keep shared_fraction=0 traces BIT-IDENTICAL to
    pre-ISSUE-13 synthesis."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if not 0.0 <= poison_rate < 1.0:
        raise ValueError(f"poison_rate must be in [0, 1), got {poison_rate}")
    if not 0.0 <= shared_fraction <= 1.0:
        raise ValueError(
            f"shared_fraction must be in [0, 1], got {shared_fraction}")
    rng = np.random.default_rng(seed)
    template_seeds: List[int] = []
    if shared_fraction > 0:
        if n_templates < 1:
            raise ValueError(f"n_templates must be >= 1, got {n_templates}")
        if template_len < 1:
            raise ValueError(f"template_len must be >= 1, got {template_len}")
        template_seeds = [int(s) for s in
                          rng.integers(0, 2**31 - 1, size=n_templates)]
    requests: List[TraceRequest] = []
    t = 0.0
    in_burst = False
    for rid in range(n_requests):
        # state flip AHEAD of each arrival, then the gap at the state rate
        if in_burst:
            in_burst = rng.random() >= p_exit_burst
        else:
            in_burst = rng.random() < p_enter_burst
        scale = mean_interarrival_s / (burst_factor if in_burst else 1.0)
        t += float(rng.exponential(scale))
        kind = "normal"
        if poison_rate and rng.random() < poison_rate:
            kind = POISON_KINDS[int(rng.integers(0, len(POISON_KINDS)))]
        prompt_len = int(np.clip(
            round(rng.lognormal(prompt_len_log_mean, prompt_len_log_sigma)),
            prompt_len_min, prompt_len_max))
        max_new = int(np.clip(rng.geometric(1.0 / max_new_mean),
                              max_new_min, max_new_max))
        if kind == "poison-empty":
            prompt_len = 0
        elif kind == "poison-budget":
            max_new = 0
        elif kind == "poison-oversize":
            prompt_len = oversize_len
        template_seed, overlap_len = -1, 0
        if (kind == "normal" and shared_fraction > 0
                and rng.random() < shared_fraction):
            kind = "shared_prefix"
            template_seed = template_seeds[
                int(rng.integers(0, len(template_seeds)))]
            overlap_len = template_len
            prompt_len += template_len  # template + the drawn private tail
        requests.append(TraceRequest(
            rid=rid, t_arrival=round(t, 6), prompt_len=prompt_len,
            prompt_seed=int(rng.integers(0, 2**31 - 1)),
            max_new_tokens=max_new, kind=kind,
            template_seed=template_seed, overlap_len=overlap_len))
    meta = {
        "version": TRACE_VERSION, "label": label, "seed": int(seed),
        "vocab": int(vocab), "n_requests": int(n_requests),
        "mean_interarrival_s": mean_interarrival_s,
        "burst_factor": burst_factor, "p_enter_burst": p_enter_burst,
        "p_exit_burst": p_exit_burst,
        "prompt_len_log_mean": prompt_len_log_mean,
        "prompt_len_log_sigma": prompt_len_log_sigma,
        "prompt_len_min": prompt_len_min, "prompt_len_max": prompt_len_max,
        "max_new_mean": max_new_mean, "max_new_min": max_new_min,
        "max_new_max": max_new_max, "poison_rate": poison_rate,
        "oversize_len": oversize_len,
        "shared_fraction": shared_fraction, "n_templates": n_templates,
        "template_len": template_len,
        "duration_s": round(t, 6),
    }
    return Trace(meta=meta, requests=requests)


def save_trace(trace: Trace, path: str) -> str:
    """JSONL: `trace-meta` header first, one `trace-request` per line.
    Deterministic bytes for a deterministic trace (sorted keys)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        # discriminator key is "record", NOT "kind" — requests already
        # carry a `kind` field (normal | poison-*)
        f.write(json.dumps({"record": "trace-meta", **trace.meta},
                           sort_keys=True) + "\n")
        for req in trace.requests:
            f.write(json.dumps({"record": "trace-request", **asdict(req)},
                               sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return path


def load_trace(path: str) -> Trace:
    """Strict parse: a trace is replay input, so any malformed line or a
    missing/incompatible header raises ValueError."""
    meta: Optional[dict] = None
    requests: List[TraceRequest] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON: {e}") from e
            tag = rec.pop("record", None) if isinstance(rec, dict) else None
            if tag == "trace-meta":
                if meta is not None:
                    raise ValueError(f"{path}:{i}: duplicate trace-meta")
                if rec.get("version") != TRACE_VERSION:
                    raise ValueError(
                        f"{path}:{i}: trace version {rec.get('version')!r} "
                        f"!= supported {TRACE_VERSION}")
                meta = rec
            elif tag == "trace-request":
                if rec.get("kind", "normal") not in REQUEST_KINDS:
                    raise ValueError(
                        f"{path}:{i}: unknown request kind {rec.get('kind')!r}")
                requests.append(TraceRequest(**rec))
            else:
                raise ValueError(f"{path}:{i}: not a trace record: "
                                 f"{line[:80]}")
    if meta is None:
        raise ValueError(f"{path}: no trace-meta header")
    return Trace(meta=meta, requests=requests)
