"""Deterministic serve-workload traces: synthesize, serialize, replay.

A trace is the unit of serve-hardening evidence: a SEEDED, wall-clock-free
description of heavy traffic (ragged prompt/output lengths, bursty
arrivals, a sprinkling of poison requests) that replays byte-identically
anywhere — the single-process oracle, the multi-process cluster, and a CI
lane three months from now all see the same requests at the same virtual
times.  Determinism rules:

  * every sampled quantity comes from ONE `np.random.default_rng(seed)`
    stream in a fixed draw order — same seed, same trace, bit-for-bit;
  * prompts are NOT stored as tokens: each request carries a
    `prompt_seed` and regenerates its tokens on demand (`prompt()`), so
    a million-token trace file stays kilobytes and the oracle can never
    see different tokens than the cluster;
  * arrival times are virtual seconds from trace start — the replayers
    (loadgen/driver.py, loadgen/cluster.py) map them to wall time with a
    `speed` factor; nothing in this module reads a clock.

Arrival model: a two-state Markov-modulated process (calm | burst).  The
state flips ahead of each arrival (`p_enter_burst` / `p_exit_burst`), and
interarrival gaps are exponential at the calm rate or `burst_factor`×
faster inside a burst — the clumpy, overdispersed arrivals (CV > 1) that
actually stress admission control, rather than a smooth Poisson stream.

Poison requests model malformed traffic the engines must reject without
taking a worker down: empty prompts, zero budgets, and prompts too large
for any pool (`poison-oversize`).

Serialized form (JSONL, `results/traces/*.jsonl`): one `trace-meta`
header line with the full synthesis recipe, then one `trace-request`
line per request.  `load_trace` is strict — a trace is CI input, not
best-effort telemetry.
"""

import json
import os
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

TRACE_VERSION = 1

REQUEST_KINDS = ("normal", "poison-empty", "poison-budget",
                 "poison-oversize", "shared_prefix")
POISON_KINDS = tuple(k for k in REQUEST_KINDS if k.startswith("poison"))

# Trace-level synthesis families.  `bursty` is the original Markov-
# modulated process (synthesize_trace); `diurnal` rides a sinusoidal
# arrival intensity (synthesize_diurnal_trace); `heavy_tail` draws a
# Zipf tenant mix over shared-prefix templates
# (synthesize_heavy_tail_trace).  load_trace rejects unknown kinds the
# same way it rejects unknown request kinds — a trace is CI input.
TRACE_KINDS = ("bursty", "diurnal", "heavy_tail")


@dataclass(frozen=True)
class TraceRequest:
    """One replayable request: WHEN it arrives and WHAT it asks for."""

    rid: int
    t_arrival: float            # virtual seconds from trace start
    prompt_len: int
    prompt_seed: int            # tokens regenerate from this (see prompt())
    max_new_tokens: int
    kind: str = "normal"        # REQUEST_KINDS
    # shared_prefix requests: the first `overlap_len` tokens regenerate
    # from `template_seed` (drawn from a small per-trace template pool),
    # the remaining prompt_len - overlap_len from prompt_seed — every
    # request on the same template shares a bit-identical prefix, which
    # is what the serving prefix cache hits on
    template_seed: int = -1
    overlap_len: int = 0
    # multi-tenant fields (heavy_tail traces; scheduling policies in
    # fleet/policy.py key on them) — defaults keep legacy traces loading
    tenant: int = -1
    priority: int = 0

    @property
    def poison(self) -> bool:
        return self.kind in POISON_KINDS

    def prompt(self, vocab: int) -> np.ndarray:
        """The request's tokens, regenerated deterministically — every
        replayer and the oracle derive the identical [prompt_len] int32
        array from (prompt_seed, prompt_len, vocab) — plus
        (template_seed, overlap_len) for shared_prefix requests."""
        if self.prompt_len <= 0:
            return np.zeros((0,), np.int32)
        if self.kind == "shared_prefix" and self.overlap_len > 0:
            tmpl = np.random.default_rng(self.template_seed).integers(
                1, vocab, size=self.overlap_len)
            tail = np.random.default_rng(self.prompt_seed).integers(
                1, vocab, size=self.prompt_len - self.overlap_len)
            return np.concatenate([tmpl, tail]).astype(np.int32)
        rng = np.random.default_rng(self.prompt_seed)
        return rng.integers(1, vocab, size=self.prompt_len).astype(np.int32)


@dataclass
class Trace:
    """A meta header (the synthesis recipe) + arrival-ordered requests."""

    meta: Dict[str, object]
    requests: List[TraceRequest] = field(default_factory=list)

    @property
    def vocab(self) -> int:
        return int(self.meta["vocab"])

    @property
    def duration_s(self) -> float:
        """Virtual span from trace start to the last arrival."""
        return max((r.t_arrival for r in self.requests), default=0.0)

    def normal(self) -> List[TraceRequest]:
        return [r for r in self.requests if not r.poison]

    def prompts(self) -> Dict[int, np.ndarray]:
        return {r.rid: r.prompt(self.vocab) for r in self.requests}


def synthesize_trace(
    n_requests: int,
    *,
    seed: int,
    vocab: int,
    mean_interarrival_s: float = 0.05,
    burst_factor: float = 8.0,
    p_enter_burst: float = 0.15,
    p_exit_burst: float = 0.35,
    prompt_len_log_mean: float = 2.5,
    prompt_len_log_sigma: float = 0.6,
    prompt_len_min: int = 1,
    prompt_len_max: int = 64,
    max_new_mean: float = 12.0,
    max_new_min: int = 1,
    max_new_max: int = 48,
    poison_rate: float = 0.0,
    oversize_len: int = 100_000,
    shared_fraction: float = 0.0,
    n_templates: int = 4,
    template_len: int = 256,
    label: str = "synthetic",
) -> Trace:
    """Seeded workload synthesis (see the module docstring for the
    models).  Prompt lengths are clipped lognormal (ragged, heavy-ish
    tail), decode budgets clipped geometric, arrivals Markov-modulated
    exponential.  No wall-clock, no global RNG — the same call is the
    same trace forever.

    `shared_fraction` > 0 turns that fraction of normal requests into
    `shared_prefix` requests: each picks one of `n_templates` seeded
    templates and prepends its `template_len` tokens to the privately
    drawn suffix (so its total prompt is template + lognormal tail).
    Guarded draws keep shared_fraction=0 traces BIT-IDENTICAL to
    pre-ISSUE-13 synthesis."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if not 0.0 <= poison_rate < 1.0:
        raise ValueError(f"poison_rate must be in [0, 1), got {poison_rate}")
    if not 0.0 <= shared_fraction <= 1.0:
        raise ValueError(
            f"shared_fraction must be in [0, 1], got {shared_fraction}")
    rng = np.random.default_rng(seed)
    template_seeds: List[int] = []
    if shared_fraction > 0:
        if n_templates < 1:
            raise ValueError(f"n_templates must be >= 1, got {n_templates}")
        if template_len < 1:
            raise ValueError(f"template_len must be >= 1, got {template_len}")
        template_seeds = [int(s) for s in
                          rng.integers(0, 2**31 - 1, size=n_templates)]
    requests: List[TraceRequest] = []
    t = 0.0
    in_burst = False
    for rid in range(n_requests):
        # state flip AHEAD of each arrival, then the gap at the state rate
        if in_burst:
            in_burst = rng.random() >= p_exit_burst
        else:
            in_burst = rng.random() < p_enter_burst
        scale = mean_interarrival_s / (burst_factor if in_burst else 1.0)
        t += float(rng.exponential(scale))
        kind = "normal"
        if poison_rate and rng.random() < poison_rate:
            kind = POISON_KINDS[int(rng.integers(0, len(POISON_KINDS)))]
        prompt_len = int(np.clip(
            round(rng.lognormal(prompt_len_log_mean, prompt_len_log_sigma)),
            prompt_len_min, prompt_len_max))
        max_new = int(np.clip(rng.geometric(1.0 / max_new_mean),
                              max_new_min, max_new_max))
        if kind == "poison-empty":
            prompt_len = 0
        elif kind == "poison-budget":
            max_new = 0
        elif kind == "poison-oversize":
            prompt_len = oversize_len
        template_seed, overlap_len = -1, 0
        if (kind == "normal" and shared_fraction > 0
                and rng.random() < shared_fraction):
            kind = "shared_prefix"
            template_seed = template_seeds[
                int(rng.integers(0, len(template_seeds)))]
            overlap_len = template_len
            prompt_len += template_len  # template + the drawn private tail
        requests.append(TraceRequest(
            rid=rid, t_arrival=round(t, 6), prompt_len=prompt_len,
            prompt_seed=int(rng.integers(0, 2**31 - 1)),
            max_new_tokens=max_new, kind=kind,
            template_seed=template_seed, overlap_len=overlap_len))
    meta = {
        "version": TRACE_VERSION, "label": label, "seed": int(seed),
        "trace_kind": "bursty",
        "vocab": int(vocab), "n_requests": int(n_requests),
        "mean_interarrival_s": mean_interarrival_s,
        "burst_factor": burst_factor, "p_enter_burst": p_enter_burst,
        "p_exit_burst": p_exit_burst,
        "prompt_len_log_mean": prompt_len_log_mean,
        "prompt_len_log_sigma": prompt_len_log_sigma,
        "prompt_len_min": prompt_len_min, "prompt_len_max": prompt_len_max,
        "max_new_mean": max_new_mean, "max_new_min": max_new_min,
        "max_new_max": max_new_max, "poison_rate": poison_rate,
        "oversize_len": oversize_len,
        "shared_fraction": shared_fraction, "n_templates": n_templates,
        "template_len": template_len,
        "duration_s": round(t, 6),
    }
    return Trace(meta=meta, requests=requests)


def _clipped_lognormal(rng, log_mean, log_sigma, lo, hi, n) -> np.ndarray:
    v = np.rint(rng.lognormal(log_mean, log_sigma, size=n))
    return np.clip(v, lo, hi).astype(np.int64)


def _clipped_geometric(rng, mean, lo, hi, n) -> np.ndarray:
    return np.clip(rng.geometric(1.0 / mean, size=n), lo, hi).astype(np.int64)


def synthesize_diurnal_trace(
    n_requests: int,
    *,
    seed: int,
    vocab: int,
    period_s: float = 3600.0,
    mean_rate: float = 20.0,
    peak_to_trough: float = 4.0,
    prompt_len_log_mean: float = 2.5,
    prompt_len_log_sigma: float = 0.6,
    prompt_len_min: int = 1,
    prompt_len_max: int = 64,
    max_new_mean: float = 12.0,
    max_new_min: int = 1,
    max_new_max: int = 48,
    priority_fraction: float = 0.0,
    label: str = "diurnal",
) -> Trace:
    """Sinusoidal ("diurnal") arrival intensity: a nonhomogeneous
    Poisson process at rate(t) = mean_rate * (1 + A sin(2pi t/period)),
    with A chosen so peak rate / trough rate == peak_to_trough — the
    daily swell autoscaling policies must ride, compressed to whatever
    `period_s` the simulation budget affords.

    Arrivals come from exact time-rescaling: unit-exponential gaps
    accumulate to targets on the integrated intensity, inverted on a
    dense monotone grid (vectorized — a million requests synthesize in
    seconds).  Every stream draws from its own child generator
    `default_rng([seed, i])`, so the synthesis is seeded-deterministic
    and streams never perturb each other.  `priority_fraction` tags that
    fraction of requests priority 1 (the preemption class)."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if peak_to_trough < 1.0:
        raise ValueError(
            f"peak_to_trough must be >= 1, got {peak_to_trough}")
    if not 0.0 <= priority_fraction <= 1.0:
        raise ValueError(
            f"priority_fraction must be in [0, 1], got {priority_fraction}")
    n = int(n_requests)
    amp = (peak_to_trough - 1.0) / (peak_to_trough + 1.0)
    rng_arrival = np.random.default_rng([seed, 0])
    rng_len = np.random.default_rng([seed, 1])
    rng_budget = np.random.default_rng([seed, 2])
    rng_seed = np.random.default_rng([seed, 3])
    rng_prio = np.random.default_rng([seed, 4])

    targets = np.cumsum(rng_arrival.exponential(1.0, size=n))

    def big_lambda(t):  # integrated intensity
        w = 2.0 * np.pi / period_s
        return mean_rate * t + mean_rate * amp / w * (1.0 - np.cos(w * t))

    t_max = targets[-1] / mean_rate + period_s
    while big_lambda(t_max) < targets[-1]:
        t_max *= 2.0
    grid = np.linspace(0.0, t_max,
                       max(4096, int(t_max / period_s * 4096)) + 1)
    arrivals = np.interp(targets, big_lambda(grid), grid)

    prompt_lens = _clipped_lognormal(
        rng_len, prompt_len_log_mean, prompt_len_log_sigma,
        prompt_len_min, prompt_len_max, n)
    budgets = _clipped_geometric(
        rng_budget, max_new_mean, max_new_min, max_new_max, n)
    prompt_seeds = rng_seed.integers(0, 2**31 - 1, size=n)
    priorities = (rng_prio.random(n) < priority_fraction).astype(np.int64)

    requests = [TraceRequest(
        rid=rid, t_arrival=round(float(arrivals[rid]), 6),
        prompt_len=int(prompt_lens[rid]),
        prompt_seed=int(prompt_seeds[rid]),
        max_new_tokens=int(budgets[rid]),
        priority=int(priorities[rid])) for rid in range(n)]
    meta = {
        "version": TRACE_VERSION, "label": label, "seed": int(seed),
        "trace_kind": "diurnal",
        "vocab": int(vocab), "n_requests": n,
        "period_s": period_s, "mean_rate": mean_rate,
        "peak_to_trough": peak_to_trough,
        "prompt_len_log_mean": prompt_len_log_mean,
        "prompt_len_log_sigma": prompt_len_log_sigma,
        "prompt_len_min": prompt_len_min, "prompt_len_max": prompt_len_max,
        "max_new_mean": max_new_mean, "max_new_min": max_new_min,
        "max_new_max": max_new_max,
        "priority_fraction": priority_fraction,
        "duration_s": round(float(arrivals[-1]), 6),
    }
    return Trace(meta=meta, requests=requests)


def synthesize_heavy_tail_trace(
    n_requests: int,
    *,
    seed: int,
    vocab: int,
    n_tenants: int = 64,
    zipf_a: float = 1.2,
    mean_interarrival_s: float = 0.05,
    template_len: int = 256,
    shared_fraction: float = 1.0,
    tail_log_mean: float = 2.5,
    tail_log_sigma: float = 0.6,
    tail_min: int = 1,
    tail_max: int = 64,
    max_new_mean: float = 12.0,
    max_new_min: int = 1,
    max_new_max: int = 48,
    priority_tenants: int = 0,
    label: str = "heavy_tail",
) -> Trace:
    """Zipf tenant mix over shared-prefix templates: tenant k (rank
    order) arrives with probability proportional to (k+1)^-zipf_a, and
    every tenant owns ONE seeded template — the head tenants dominate
    traffic AND share prefixes, which is exactly the workload where the
    prefix cache's rich-get-richer routing bias fights tenant fairness.

    Arrivals are plain exponential (the tenant mix is the stressor
    here, not burstiness); `shared_fraction` of each tenant's requests
    carry its template as a `shared_prefix` overlap, the rest are
    private (`shared_fraction=0` produces a trace with no
    shared_prefix requests at all).  The first `priority_tenants`
    head tenants are tagged priority 1.  Child streams via
    `default_rng([seed, i])`, same determinism contract as the diurnal
    kind."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if n_tenants < 1:
        raise ValueError(f"n_tenants must be >= 1, got {n_tenants}")
    if not 0.0 <= shared_fraction <= 1.0:
        raise ValueError(
            f"shared_fraction must be in [0, 1], got {shared_fraction}")
    if template_len < 1:
        raise ValueError(f"template_len must be >= 1, got {template_len}")
    n = int(n_requests)
    rng_arrival = np.random.default_rng([seed, 0])
    rng_tenant = np.random.default_rng([seed, 1])
    rng_len = np.random.default_rng([seed, 2])
    rng_budget = np.random.default_rng([seed, 3])
    rng_seed = np.random.default_rng([seed, 4])
    rng_template = np.random.default_rng([seed, 5])
    rng_shared = np.random.default_rng([seed, 6])

    arrivals = np.cumsum(rng_arrival.exponential(mean_interarrival_s, size=n))
    weights = (np.arange(1, n_tenants + 1, dtype=np.float64)) ** (-zipf_a)
    cdf = np.cumsum(weights / weights.sum())
    tenants = np.searchsorted(cdf, rng_tenant.random(n), side="right")
    tenants = np.minimum(tenants, n_tenants - 1)
    tails = _clipped_lognormal(rng_len, tail_log_mean, tail_log_sigma,
                               tail_min, tail_max, n)
    budgets = _clipped_geometric(rng_budget, max_new_mean, max_new_min,
                                 max_new_max, n)
    prompt_seeds = rng_seed.integers(0, 2**31 - 1, size=n)
    template_seeds = rng_template.integers(0, 2**31 - 1, size=n_tenants)
    shared = rng_shared.random(n) < shared_fraction

    requests = []
    for rid in range(n):
        tenant = int(tenants[rid])
        if shared[rid]:
            kind = "shared_prefix"
            template_seed = int(template_seeds[tenant])
            overlap_len = template_len
            prompt_len = template_len + int(tails[rid])
        else:
            kind, template_seed, overlap_len = "normal", -1, 0
            prompt_len = int(tails[rid])
        requests.append(TraceRequest(
            rid=rid, t_arrival=round(float(arrivals[rid]), 6),
            prompt_len=prompt_len, prompt_seed=int(prompt_seeds[rid]),
            max_new_tokens=int(budgets[rid]), kind=kind,
            template_seed=template_seed, overlap_len=overlap_len,
            tenant=tenant,
            priority=1 if tenant < priority_tenants else 0))
    meta = {
        "version": TRACE_VERSION, "label": label, "seed": int(seed),
        "trace_kind": "heavy_tail",
        "vocab": int(vocab), "n_requests": n,
        "n_tenants": int(n_tenants), "zipf_a": zipf_a,
        "mean_interarrival_s": mean_interarrival_s,
        "template_len": int(template_len),
        "shared_fraction": shared_fraction,
        "tail_log_mean": tail_log_mean, "tail_log_sigma": tail_log_sigma,
        "tail_min": tail_min, "tail_max": tail_max,
        "max_new_mean": max_new_mean, "max_new_min": max_new_min,
        "max_new_max": max_new_max,
        "priority_tenants": int(priority_tenants),
        "duration_s": round(float(arrivals[-1]), 6),
    }
    return Trace(meta=meta, requests=requests)


def save_trace(trace: Trace, path: str) -> str:
    """JSONL: `trace-meta` header first, one `trace-request` per line.
    Deterministic bytes for a deterministic trace (sorted keys)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        # discriminator key is "record", NOT "kind" — requests already
        # carry a `kind` field (normal | poison-*)
        f.write(json.dumps({"record": "trace-meta", **trace.meta},
                           sort_keys=True) + "\n")
        for req in trace.requests:
            f.write(json.dumps({"record": "trace-request", **asdict(req)},
                               sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return path


def load_trace(path: str) -> Trace:
    """Strict parse: a trace is replay input, so any malformed line or a
    missing/incompatible header raises ValueError."""
    meta: Optional[dict] = None
    requests: List[TraceRequest] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON: {e}") from e
            tag = rec.pop("record", None) if isinstance(rec, dict) else None
            if tag == "trace-meta":
                if meta is not None:
                    raise ValueError(f"{path}:{i}: duplicate trace-meta")
                if rec.get("version") != TRACE_VERSION:
                    raise ValueError(
                        f"{path}:{i}: trace version {rec.get('version')!r} "
                        f"!= supported {TRACE_VERSION}")
                if rec.get("trace_kind", "bursty") not in TRACE_KINDS:
                    raise ValueError(
                        f"{path}:{i}: unknown trace kind "
                        f"{rec.get('trace_kind')!r} (one of {TRACE_KINDS})")
                meta = rec
            elif tag == "trace-request":
                if rec.get("kind", "normal") not in REQUEST_KINDS:
                    raise ValueError(
                        f"{path}:{i}: unknown request kind {rec.get('kind')!r}")
                requests.append(TraceRequest(**rec))
            else:
                raise ValueError(f"{path}:{i}: not a trace record: "
                                 f"{line[:80]}")
    if meta is None:
        raise ValueError(f"{path}: no trace-meta header")
    return Trace(meta=meta, requests=requests)
