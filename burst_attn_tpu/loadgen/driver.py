"""Open-loop trace replay against one serve engine + the token oracle.

The driver is the bridge between a Trace (virtual arrival times) and an
engine (RaggedServeEngine or models/serve.py's ServeEngine — anything
with `try_submit` / `step` / `pending` / `live`).  Replay is OPEN-LOOP:
arrivals fire at `t_arrival / speed` wall seconds after replay start
whether or not the engine has kept up — the workload does not slow down
because the server is struggling, which is exactly the regime where
admission control earns its keep.  Retryable sheds (pool-exhausted,
queue-full, admission-*) go to a virtual-time retry queue with backoff;
non-retryable rejections (poison requests) are terminal outcomes.

`oracle_replay` is the correctness reference: the same trace served
sequentially, one request at a time, on a fresh engine with no load
shedding — since greedy decode is batch-invariant (token-exact however
requests are batched, chunked, or speculated), any replay of the trace
that completes a request must emit EXACTLY the oracle's tokens for it.
`diff_tokens` turns that into the zero-token-corruption assertion the
cluster harness and tests gate on.
"""

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from .trace import Trace

# outcome.status vocabulary
DONE = "done"            # completed; tokens are the engine's output
REJECTED = "rejected"    # non-retryable typed rejection (poison et al.)
SHED = "shed"            # retryable sheds exhausted max_retries


@dataclass(frozen=True)
class RetryBackoff:
    """Seeded exponential backoff with jitter, in VIRTUAL seconds.

    A constant backoff resubmits an entire shed wave in lockstep — every
    rejected request comes back at the same instant and is shed again
    (retry storm).  `delay(rid, attempt)` decorrelates them: the base
    delay doubles per attempt (capped), and a per-(seed, rid, attempt)
    jitter in [1-jitter, 1+jitter] spreads requests apart.  Fully
    deterministic: the same seed gives the same schedule, different rids
    get independent streams (numpy's seed-sequence spawning — no shared
    RNG state, so the schedule is independent of call order)."""

    base_s: float = 0.05
    cap_s: float = 2.0
    factor: float = 2.0
    jitter: float = 0.5
    seed: int = 0

    def __post_init__(self):
        if self.base_s <= 0:
            raise ValueError(f"base_s must be > 0, got {self.base_s}")
        if self.cap_s < self.base_s:
            raise ValueError(f"cap_s {self.cap_s} < base_s {self.base_s}")
        if self.factor < 1.0:
            raise ValueError(f"factor must be >= 1, got {self.factor}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def delay(self, rid: int, attempt: int) -> float:
        """Virtual-seconds delay before retry number `attempt` (1-based)
        of request `rid`."""
        if attempt < 1:
            raise ValueError(f"attempt must be >= 1, got {attempt}")
        det = min(self.base_s * self.factor ** (attempt - 1), self.cap_s)
        u = np.random.default_rng(
            [int(self.seed), int(rid), int(attempt)]).random()
        return det * (1.0 + self.jitter * (2.0 * u - 1.0))


@dataclass
class Outcome:
    """What the replay ultimately did with one trace request."""

    rid: int
    kind: str
    status: str = DONE
    reason: Optional[str] = None      # RejectReason value when not DONE
    tokens: List[int] = field(default_factory=list)
    retries: int = 0                  # sheds absorbed before the outcome
    t_arrival: float = 0.0            # virtual seconds (from the trace)
    t_submit: Optional[float] = None  # virtual seconds at accepted submit
    t_done: Optional[float] = None    # virtual seconds at completion


@dataclass
class ReplayReport:
    """Replay outcomes plus the timing context SLO evaluation needs."""

    outcomes: Dict[int, Outcome]
    wall_s: float                     # real seconds the replay took
    speed: float                      # virtual seconds per wall second

    @property
    def duration_v(self) -> float:
        """Virtual span covered (last completion or arrival)."""
        ts = [o.t_done for o in self.outcomes.values() if o.t_done is not None]
        ts += [o.t_arrival for o in self.outcomes.values()]
        return max(ts, default=0.0)

    def by_status(self, status: str) -> List[Outcome]:
        return [o for o in self.outcomes.values() if o.status == status]

    @property
    def n_done(self) -> int:
        return len(self.by_status(DONE))

    @property
    def n_rejected(self) -> int:
        return len(self.by_status(REJECTED))

    @property
    def n_shed(self) -> int:
        return len(self.by_status(SHED))

    @property
    def completed_tokens(self) -> int:
        return sum(len(o.tokens) for o in self.by_status(DONE))

    def completed(self) -> Dict[int, List[int]]:
        """trace rid -> tokens for every completed request (the side the
        oracle diff compares)."""
        return {o.rid: o.tokens for o in self.by_status(DONE)}


def replay_trace(engine, trace: Trace, *, speed: float = 50.0,
                 retry_backoff_s: float = 0.05, max_retries: int = 200,
                 max_wall_s: float = 300.0,
                 backoff: Optional[RetryBackoff] = None) -> ReplayReport:
    """Replay `trace` open-loop against `engine` (already constructed —
    any admission policy / max_queue it carries is what gets exercised).

    `speed` maps virtual trace seconds to wall time (virtual = wall *
    speed), so a 5-virtual-second trace replays in ~0.1 wall seconds at
    the default; timestamps in the report stay in VIRTUAL seconds and are
    therefore speed-invariant.  Retry delays are virtual too: seeded
    exponential backoff + jitter (`backoff`, defaulting to a
    RetryBackoff seeded at `retry_backoff_s` base).
    """
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    bo = backoff if backoff is not None else RetryBackoff(
        base_s=retry_backoff_s, cap_s=max(retry_backoff_s * 40, 2.0))
    vocab = trace.vocab
    arrivals = sorted(trace.requests, key=lambda r: (r.t_arrival, r.rid))
    retry: List[tuple] = []           # (t_due_v, trace rid)
    by_rid = {r.rid: r for r in trace.requests}
    rid_map: Dict[int, int] = {}      # engine rid -> trace rid
    outcomes: Dict[int, Outcome] = {
        r.rid: Outcome(rid=r.rid, kind=r.kind, t_arrival=r.t_arrival)
        for r in trace.requests}

    def _submit(req, now_v: float) -> None:
        out = outcomes[req.rid]
        res = engine.try_submit(req.prompt(vocab), req.max_new_tokens)
        if res.ok:
            rid_map[res.rid] = req.rid
            out.status = DONE         # provisional; completion fills tokens
            out.reason = None
            out.t_submit = now_v
        elif res.retryable and out.retries < max_retries:
            out.retries += 1
            retry.append((now_v + bo.delay(req.rid, out.retries), req.rid))
        else:
            out.status = SHED if res.retryable else REJECTED
            out.reason = res.reason.value if res.reason else None

    t0 = time.perf_counter()
    i = 0
    while True:
        now_v = (time.perf_counter() - t0) * speed
        while i < len(arrivals) and arrivals[i].t_arrival <= now_v:
            _submit(arrivals[i], now_v)
            i += 1
        if retry:
            retry.sort()
            while retry and retry[0][0] <= now_v:
                _, rid = retry.pop(0)
                _submit(by_rid[rid], now_v)
        if engine.pending or engine.live:
            for erid, toks in engine.step():
                out = outcomes[rid_map.pop(erid)]
                out.tokens = [int(t) for t in toks]
                out.t_done = (time.perf_counter() - t0) * speed
        elif i < len(arrivals) or retry:
            # open-loop gap: nothing due yet, nothing in flight
            time.sleep(0.001)
        else:
            break
        if time.perf_counter() - t0 > max_wall_s:
            raise RuntimeError(
                f"replay exceeded max_wall_s={max_wall_s:g}: "
                f"{i}/{len(arrivals)} arrived, {len(retry)} retrying, "
                f"pending={engine.pending}, live={engine.live}")
    return ReplayReport(outcomes=outcomes,
                        wall_s=time.perf_counter() - t0, speed=speed)


def oracle_replay(trace: Trace,
                  make_engine: Callable[[], object]) -> Dict[int, List[int]]:
    """trace rid -> tokens, serving each servable request ALONE on a
    fresh engine from `make_engine` (built with no admission policy so
    nothing is shed).  This is the token-exactness reference: greedy
    decode is batch-invariant, so any engine/cluster replay that
    completes rid must produce exactly these tokens.  Poison requests
    that the engine rejects simply have no oracle entry."""
    eng = make_engine()
    vocab = trace.vocab
    out: Dict[int, List[int]] = {}
    for req in sorted(trace.requests, key=lambda r: r.rid):
        res = eng.try_submit(req.prompt(vocab), req.max_new_tokens)
        if not res.ok:
            continue
        done = eng.run()
        out[req.rid] = [int(t) for t in done[res.rid]]
    return out


def diff_tokens(completed: Dict[int, List[int]],
                oracle: Dict[int, List[int]]) -> List[str]:
    """Zero-token-corruption check: every completed request's tokens must
    equal the oracle's, byte for byte.  Returns human-readable mismatch
    lines (empty = exact); completing a request the oracle could not
    serve is itself a mismatch."""
    bad = []
    for rid in sorted(completed):
        if rid not in oracle:
            bad.append(f"rid {rid}: completed but the oracle rejected it")
        elif completed[rid] != oracle[rid]:
            want, got = oracle[rid], completed[rid]
            n = next((k for k, (a, b) in enumerate(zip(want, got)) if a != b),
                     min(len(want), len(got)))
            bad.append(f"rid {rid}: tokens diverge at position {n}: "
                       f"oracle {want[n:n + 4]}... vs replay {got[n:n + 4]}..."
                       f" (lengths {len(want)} vs {len(got)})")
    return bad


def assert_token_exact(completed: Dict[int, List[int]],
                       oracle: Dict[int, List[int]]) -> None:
    bad = diff_tokens(completed, oracle)
    if bad:
        raise AssertionError(
            "token corruption: replay diverged from the single-process "
            "oracle on " + f"{len(bad)} request(s):\n  " + "\n  ".join(bad))
