from . import layouts, ring

__all__ = ["layouts", "ring"]
