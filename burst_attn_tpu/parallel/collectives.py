"""Collectives beyond the ring — parity with the reference's comm.py surface
(broadcast :16, all_reduce :67, synchronize :336, gather_obj :345, rank/size
helpers :74-101), expressed as XLA collectives / jax utilities.

Inside shard_map these are one-op wrappers over lax primitives; outside, the
host-level helpers use jax.experimental.multihost_utils (the multi-controller
analogue of the reference's object gather over NCCL)."""

import jax
import jax.numpy as jnp
from jax import lax
from ..utils.compat import axis_size


# ---- in-shard_map collectives (SPMD) ----


def all_reduce(x, axis_name: str, op: str = "sum"):
    """Reference comm.all_reduce (comm.py:67): psum/pmax/pmin/pmean."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown op {op!r}")


def broadcast(x, axis_name: str, root: int = 0):
    """Reference comm.broadcast (comm.py:16): every member gets root's copy."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def rank(axis_name: str):
    """Reference comm.get_rank (comm.py:74-101)."""
    return lax.axis_index(axis_name)


def world_size(axis_name: str):
    return axis_size(axis_name)


# ---- host-level helpers (multi-controller) ----


def synchronize():
    """Barrier across processes (reference comm.synchronize, comm.py:336)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("burst_attn_tpu.synchronize")
    else:
        for d in jax.live_arrays():
            d.block_until_ready()


def gather_obj(obj):
    """Gather a picklable object from every process to all processes
    (reference comm.gather_obj, comm.py:345)."""
    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(obj)
