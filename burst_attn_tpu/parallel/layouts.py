"""Sequence-sharding layouts: contiguous, zigzag-half, and striped.

A layout is a permutation of the global sequence: after permuting, contiguous
equal chunks over the ring (device-major order: partition p = inter_rank *
intra_size + intra_rank) give each device its layout chunk.  This replaces
the reference's ad-hoc chunk gathering (test/test_burst.py:44-58):

  * contig : identity — chunk p holds global tokens [p*C, (p+1)*C)
  * zigzag : chunk p holds global chunks p and 2W-1-p of size S/(2W)
             (test_burst.py:46-52, `half_reputation=True`)
  * striped: chunk p holds global tokens p, p+W, p+2W, ...
             (test_burst.py:55-58)

All helpers are pure index math (numpy at trace time) so they can be used
both host-side (to shard test inputs) and inside jitted code (jnp.take with a
constant permutation folds into a gather XLA handles well).
"""

import numpy as np
import jax.numpy as jnp

from ..ops.masks import LAYOUTS


def seq_permutation(layout: str, seq_len: int, world: int) -> np.ndarray:
    """perm[i] = global token index that position i of the layout-ordered
    sequence holds.  Concatenating the per-device chunks (device-major) of the
    permuted sequence reproduces the layout."""
    if seq_len % world != 0:
        raise ValueError(f"seq_len {seq_len} not divisible by world {world}")
    if layout == "contig":
        return np.arange(seq_len)
    elif layout == "zigzag":
        if seq_len % (2 * world) != 0:
            raise ValueError(f"zigzag needs seq_len % (2*world) == 0, got {seq_len}, {world}")
        c = seq_len // (2 * world)
        chunks = np.arange(seq_len).reshape(2 * world, c)
        order = []
        for p in range(world):
            order.append(chunks[p])
            order.append(chunks[2 * world - 1 - p])
        return np.concatenate(order)
    elif layout == "striped":
        # position (p, i) -> global token p + i*world
        return np.arange(seq_len).reshape(seq_len // world, world).T.reshape(-1)
    raise ValueError(f"unknown layout {layout!r}; expected one of {LAYOUTS}")


def inverse_permutation(perm: np.ndarray) -> np.ndarray:
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    return inv


def to_layout(x, layout: str, world: int, axis: int):
    """Permute the global sequence axis into layout order."""
    perm = seq_permutation(layout, x.shape[axis], world)
    return jnp.take(x, jnp.asarray(perm), axis=axis)


def from_layout(x, layout: str, world: int, axis: int):
    """Inverse of to_layout: back to natural token order."""
    perm = inverse_permutation(seq_permutation(layout, x.shape[axis], world))
    return jnp.take(x, jnp.asarray(perm), axis=axis)


def shard_chunks(x, layout: str, world: int, axis: int):
    """Split a global array into the per-partition chunks (list of length
    world) each ring member holds under `layout`.  Host/test helper."""
    xl = to_layout(x, layout, world, axis)
    return [c for c in jnp.split(xl, world, axis=axis)]


def position_ids(layout: str, seq_len: int, world: int) -> np.ndarray:
    """[world, seq_len // world] global position of each local token — feed to
    rotary/position embeddings so models see true positions under any layout."""
    return seq_permutation(layout, seq_len, world).reshape(world, -1)
