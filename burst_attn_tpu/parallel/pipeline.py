"""Pipeline parallelism: GPipe-style microbatching over a mesh axis.

Absent from the reference (SURVEY.md §2.4: DP/TP/PP delegated to host
frameworks); first-class here because a complete framework offers every
standard parallelism axis next to the sequence ring.

TPU-first formulation — no schedulers, no per-stage processes, no streams:
the whole pipeline is ONE `lax.scan` inside `shard_map`.  Stage p holds its
slice of the parameters (leading stage axis sharded over the `pp` mesh
axis).  At schedule tick t, every stage applies its stage function to the
activation it holds and `ppermute`s the result one hop forward; stage 0
injects microbatch t while t < M, stage P-1 banks finished microbatches.
After M + P - 1 ticks all M microbatches are through.  The classic GPipe
"bubble" is the (P-1)/(M+P-1) fraction of ticks a stage computes garbage
(masked out) — exactly as in the paper, amortized by more microbatches.

Gradients need no pipeline-aware code at all: `jax.grad` of scan+ppermute IS
the reverse pipeline schedule (AD transposes ppermute to the reverse
permutation and walks the scan backward), with activation rematerialization
handled by `jax.checkpoint` on the stage function if requested.

    out = pipeline(stage_fn, stage_params, x, mesh=mesh, axis="pp",
                   microbatches=8)

stage_fn   : (params_slice, activation [mb, ...]) -> activation [mb, ...]
stage_params: pytree whose leaves have a leading [P, ...] stage axis
x          : [B, ...] global batch (B divisible by microbatches)
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from ..utils.compat import axis_size, shard_map


def pipeline_shard(stage_fn, stage_params, x_mb, axis: str):
    """Per-shard pipeline body — call inside shard_map.

    stage_params: this stage's params (leading stage axis already sliced to
    size 1 by shard_map; squeezed here).  x_mb: [M, mb, ...] microbatched
    input (replicated across stages; only stage 0 reads it).  Returns
    [M, mb, ...] outputs (valid on every stage after the final psum).
    """
    n_stages = axis_size(axis)
    stage = lax.axis_index(axis)
    params = jax.tree.map(lambda a: jnp.squeeze(a, axis=0), stage_params)
    m = x_mb.shape[0]
    ticks = m + n_stages - 1

    buf = jnp.zeros_like(x_mb[0])          # activation arriving from the left
    out = jnp.zeros_like(x_mb)             # banked results (stage P-1 only)

    def body(carry, t):
        buf, out = carry
        # stage 0 injects microbatch t (clamped read; masked after m)
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, m - 1), axis=0, keepdims=False
        )
        cur = jnp.where(stage == 0, inject, buf)
        y = stage_fn(params, cur)
        # microbatch id leaving the LAST stage at tick t is t - (P-1);
        # bank it with a select (uniform SPMD program, no per-device branch)
        mb_id = t - (n_stages - 1)
        bank = (stage == n_stages - 1) & (mb_id >= 0)
        banked = lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(mb_id, 0, m - 1), axis=0
        )
        out = jnp.where(bank, banked, out)
        nxt = lax.ppermute(
            y, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
        )
        return (nxt, out), None

    (_, out), _ = lax.scan(body, (buf, out), jnp.arange(ticks))
    # results live on the last stage only; zeros elsewhere -> psum replicates
    return lax.psum(out, axis)


def pipeline(stage_fn, stage_params, x, *, mesh, axis: str = "pp",
             microbatches: int, remat: bool = False):
    """Run `x` through P pipeline stages (P = mesh.shape[axis]).

    stage_params leaves carry a leading [P, ...] stage axis (see
    `stack_stages`); each stage applies `stage_fn(params_p, act)`.
    `remat=True` wraps the stage in jax.checkpoint — the standard GPipe
    memory/recompute trade for long pipelines.
    Returns stage_fn applied P times: [B, ...] with B preserved.
    """
    b = x.shape[0]
    if b % microbatches:
        raise ValueError(f"batch {b} not divisible by microbatches {microbatches}")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    x_mb = x.reshape(microbatches, b // microbatches, *x.shape[1:])

    spec_params = jax.tree.map(lambda _: P(axis), stage_params)
    out = shard_map(
        partial(pipeline_shard, fn, axis=axis),
        mesh=mesh,
        in_specs=(spec_params, P()),
        out_specs=P(),
        check_vma=False,
    )(stage_params, x_mb)
    return out.reshape(b, *x.shape[1:])


def stack_stages(per_stage_params):
    """[pytree_stage0, pytree_stage1, ...] -> one pytree with leading [P,...]
    stage axis (the layout `pipeline` expects, sharded over the pp axis)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)
