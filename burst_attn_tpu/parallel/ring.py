"""Ring transport over mesh axes — the TPU-native equivalent of the
reference's NCCL P2P layer (burst_attn/comm.py).

Every reference primitive maps to an XLA collective on a named mesh axis:

  Ring._make_ring_ops / batch_isend_irecv  -> lax.ppermute (async
      collective-permute; XLA overlaps it with compute, replacing the
      reference's CUDA stream/event choreography, comm.py:267-282)
  double ring intra/inter streams          -> two mesh axes ("inter","intra")
  even/odd deadlock ordering (comm.py:166) -> not needed (ppermute is one op)
  all_reduce / broadcast (comm.py:16,67)   -> lax.psum / device_put+pjit

The partition-id schedule (reference get_partition_id,
burst_attn_interface.py:20-37) tracks which global sequence partition a
device's rotating buffer holds at ring round r.
"""

import jax
import jax.numpy as jnp
from jax import lax
from ..utils.compat import axis_size


def ppermute_next(x, axis_name: str):
    """Rotate a pytree one hop forward (rank i -> i+1) along a mesh axis."""
    return ppermute_by(x, axis_name, 1)


def ppermute_by(x, axis_name: str, hops: int):
    """Rotate a pytree `hops` positions forward in ONE collective.

    A ppermute is an arbitrary permutation — jumping h hops costs one
    collective, not h.  The windowed ring uses this to skip its dead
    middle rounds (parallel/burst.py round truncation) without paying
    their payload traffic.  hops is static; hops % world == 0 is a no-op."""
    n = axis_size(axis_name)
    h = hops % n
    if h == 0:
        return x
    perm = [(i, (i + h) % n) for i in range(n)]
    # named_scope: pure metadata so each hop is identifiable on the xprof
    # timeline under the obs span naming convention (docs/observability.md);
    # adds no equations, so burstlint's jaxpr rules see the same program
    with jax.named_scope(f"obs.ring.hop{h}.{axis_name}"):
        return jax.tree.map(lambda t: lax.ppermute(t, axis_name, perm), x)


# Symmetric wire quantization of rotating ring payloads (ROADMAP item 5).
# One representable-range constant per wire dtype: int8 maps amax -> +-127;
# fp8 (e4m3fn, no inf) maps amax -> +-448, its finite max.  Scales are
# per-BLOCK SCALARS (amax over the reduced axes, keepdims) so they ride the
# ring as O(1) fp32 sub-payloads next to the 1 B/elem tensors — the scan
# ring rotates them in the same pytree, the fused kernels in parallel scale
# slot banks on the same semaphores (ops/fused_ring.py).  Accumulation is
# NEVER quantized: dequantize() is applied before any dot/add fold, exactly
# like ops/ragged_paged.py's int8 pool rescale.
WIRE_QMAX = {"int8": 127.0, "fp8": 448.0}


def wire_quantize(x, wire, axes):
    """(payload, scale) for one ring hop.  `axes` are the amax-reduction
    axes (everything inside one scale block); scale keeps dims so
    dequantization is a broadcast multiply.  wire=None passes `x` through
    with scale=None — callers on the dense path never see a new op."""
    if wire is None:
        return x, None
    if wire not in WIRE_QMAX:
        raise ValueError(f"wire must be None, 'int8' or 'fp8', got {wire!r}")
    f = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / WIRE_QMAX[wire]
    if wire == "int8":
        q = jnp.clip(jnp.round(f / scale), -127.0, 127.0).astype(jnp.int8)
    else:
        q = (f / scale).astype(jnp.float8_e4m3fn)
    return q, scale


def wire_dequantize(q, scale, dtype):
    """Inverse of wire_quantize: rescale in fp32, then cast to the compute
    dtype the dense ring would have shipped.  scale=None is the dense
    pass-through."""
    if scale is None:
        return q
    return (q.astype(jnp.float32) * scale).astype(dtype)


def ring_round_counts(n_inter: int, n_intra: int, r_live=None):
    """Host-side accounting of ONE forward ring schedule: (rounds,
    intra_hops, inter_hops).  The obs dispatch instrumentation
    (parallel/burst._note_dispatch) records these per traced program, so
    `burst.ring_rounds` / `burst.ring_hops` always agree with the schedule
    the verifier proves (burstlint ring-order) instead of being counted by
    hand at the call site.

    Single ring (n_inter == 1): a windowed contig ring truncates to
    `r_live` live rounds (parallel/burst._r_live) — r_live-1 KV hops.
    Double ring: every cycle runs n_intra rounds with n_intra-1 intra hops
    (the last round of a cycle consumes without sending), plus one
    prefetched inter hop per cycle boundary.

    Derived from the schedule IR's scan lowering (parallel/schedule):
    the counts reported here are the hop totals of the same compiled
    program burstlint simulation-proves, not a hand-kept formula."""
    from . import schedule

    if n_inter == 1:
        live = n_intra if r_live is None else r_live
        prog = schedule.compile_fwd("uni", n_intra, r_live=live)
    else:
        prog = schedule.compile_fwd("double", n_intra, n_inter)
    totals = schedule.hop_totals(prog)
    return prog.n_rounds, totals["intra"], totals["inter"]


def axis_ranks(intra_axis: str, inter_axis):
    """(inter_rank, intra_rank, inter_size, intra_size) for this device."""
    intra_rank = lax.axis_index(intra_axis)
    intra_size = axis_size(intra_axis)
    if inter_axis is None:
        return jnp.int32(0), intra_rank, 1, intra_size
    return lax.axis_index(inter_axis), intra_rank, axis_size(inter_axis), intra_size


def my_partition(intra_axis: str, inter_axis) -> jnp.ndarray:
    inter_rank, intra_rank, _, intra_size = axis_ranks(intra_axis, inter_axis)
    return inter_rank * intra_size + intra_rank


def ring_schedule(intra_size: int, inter_size: int = 1):
    """Host-side expected schedule: [world, rounds] array where entry
    (device, r) is the partition id device holds at ring round r.

    The debug/verification analogue of the reference's per-rank `record` list
    logged each run (burst_attn_interface.py:213-217,249,290-293,392): the
    distributed schedule (partition_at_round inside shard_map) must replay
    these rows exactly — asserted in tests/test_schedule.py.
    """
    import numpy as np

    world = inter_size * intra_size
    rounds = world
    out = np.empty((world, rounds), dtype=np.int64)
    for dev in range(world):
        inter_rank, intra_rank = divmod(dev, intra_size)
        for r in range(rounds):
            c, s = divmod(r, intra_size)
            out[dev, r] = ((inter_rank - c) % inter_size) * intra_size + (
                (intra_rank - s) % intra_size
            )
    return out


def neighbor_ids(axis_name: str):
    """(me, right, left) traced int32 rank ids on `axis_name`.

    `right` (me + 1) is the ring SEND target — the same direction every
    ppermute_next rotation and the reference's NCCL ring use — and `left`
    is the rank whose sends land in our buffers.  Exported for the fused
    ring kernel (ops/fused_ring.py), whose in-kernel RDMA must target the
    identical neighbor the XLA ring would, so the two paths hold the same
    partition at every round (asserted by burstlint's fused-ring rules).
    """
    me = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    return me, (me + 1) % n, (me - 1) % n


def device_roles(intra_axis: str, inter_axis=None, mesh_axes=None,
                 factor=None, home_offsets=()):
    """Traced LOGICAL device ids for the fused kernels' RDMA targets.

    Mosaic linearizes LOGICAL ids over the mesh's axis order (row-major
    strides over `mesh.axis_names`), so on a multi-axis mesh a neighbor id
    must be computed from EVERY axis index, varying only the ring
    coordinate — that is the structural proof that extra (batch/head/pp)
    axes never alias ring traffic, and what lets the fused kernels run on
    pp×tp×sp meshes.  `mesh_axes` is the host-provided ordered
    ((name, size), ...) of all mesh axes (burst_attn passes
    mesh.shape.items()); None = the ring axes are the only axes in scope
    (the legacy single-axis contract).  `factor` = (n_inter, n_intra)
    grids a DOUBLE-ring schedule onto a flat ring axis (inter-major) when
    no separate inter axis exists.  Returns a dict of traced int32 ids:
    me, cw_dst/cw_src (intra ring right/left), ccw_dst/ccw_src, and —
    when an inter dimension exists — inter_dst/inter_src; `home{i}` ids
    for each requested (inter_off, intra_off) in `home_offsets`.
    """
    if mesh_axes is None:
        mesh_axes = ((intra_axis, axis_size(intra_axis)),)
        if inter_axis is not None:
            mesh_axes = ((inter_axis, axis_size(inter_axis)),) + mesh_axes
    sizes = [int(sz) for _, sz in mesh_axes]
    strides = [1] * len(sizes)
    for a in range(len(sizes) - 2, -1, -1):
        strides[a] = strides[a + 1] * sizes[a + 1]
    idx = {name: lax.axis_index(name) for name, _ in mesh_axes}
    me = jnp.int32(0)
    for (name, _), st in zip(mesh_axes, strides):
        me = me + idx[name] * jnp.int32(st)
    names = [name for name, _ in mesh_axes]
    ai = names.index(intra_axis)
    st_intra, n_intra_ax = strides[ai], sizes[ai]

    def _with_intra(new_idx):
        return me + (new_idx - idx[intra_axis]) * jnp.int32(st_intra)

    if factor is not None:
        n_i, n_s = factor
        if n_i * n_s != n_intra_ax:
            raise ValueError(
                f"factor {factor} does not tile the ring axis "
                f"({n_intra_ax} devices)")
        flat = idx[intra_axis]
        ii, si = flat // n_s, flat % n_s

        def ring_id(di, ds):
            return _with_intra(((ii + di) % n_i) * n_s + (si + ds) % n_s)
    elif inter_axis is not None:
        bi = names.index(inter_axis)
        st_inter, n_i = strides[bi], sizes[bi]
        n_s = n_intra_ax

        def ring_id(di, ds):
            out = _with_intra((idx[intra_axis] + ds) % n_s)
            return out + (((idx[inter_axis] + di) % n_i)
                          - idx[inter_axis]) * jnp.int32(st_inter)
    else:
        n_i, n_s = 1, n_intra_ax

        def ring_id(di, ds):
            return _with_intra((idx[intra_axis] + ds) % n_s)

    roles = {
        "me": me,
        "cw_dst": ring_id(0, 1), "cw_src": ring_id(0, -1),
        "ccw_dst": ring_id(0, -1), "ccw_src": ring_id(0, 1),
        "inter_dst": ring_id(1, 0), "inter_src": ring_id(-1, 0),
    }
    for j, (h_i, h_s) in enumerate(home_offsets):
        roles[f"home{j}"] = ring_id(h_i, h_s)
    return {k: jnp.asarray(v, jnp.int32) for k, v in roles.items()}


def ring_coords(intra_axis: str, inter_axis=None, factor=None):
    """Traced (inter_rank, intra_rank, n_inter, n_intra) of this device's
    position in the (possibly factored) ring — the coordinates
    schedule.partition_for_round consumes."""
    if factor is not None:
        n_i, n_s = factor
        flat = lax.axis_index(intra_axis)
        return flat // n_s, flat % n_s, n_i, n_s
    if inter_axis is None:
        return jnp.int32(0), lax.axis_index(intra_axis), 1, \
            axis_size(intra_axis)
    return (lax.axis_index(inter_axis), lax.axis_index(intra_axis),
            axis_size(inter_axis), axis_size(intra_axis))


def fused_slot_schedule(world: int, slots: int):
    """Host-side KV-slot schedule of the fused ring kernel: [world] int array
    where entry r is the communication-buffer slot holding the chunk a
    device consumes at ring round r.

    The kernel (ops/fused_ring.py) reads THIS array (via scalar prefetch)
    for every slot choice — the send at round r goes from slot[r] into the
    right neighbor's slot[r+1] — so the schedule here is the single source
    of truth, and burstlint verifies it against an independent derivation
    plus a delivery proof (analysis/oracle.verify_fused_ring): neighbor-only
    sends, exactly world-1 hops per chunk, and no slot overwritten before
    its last read under the kernel's capacity handshake.

    With `slots` = 2 this is plain double buffering (slot parity r % 2);
    more slots deepen the pipeline so a send may run `slots - 1` rounds
    ahead of compute before the handshake blocks it.

    Since the schedule-IR refactor this is a VIEW of the compiled "uni"
    program (parallel/schedule.compile_fwd) — the same IR the kernels
    scalar-prefetch — kept for its callers and as the legacy surface
    burstlint's independent-derivation check pins.
    """
    import numpy as np

    from . import schedule

    if world < 1 or slots < 2:
        raise ValueError(f"need world >= 1 and slots >= 2, got "
                         f"world={world}, slots={slots}")
    prog = schedule.compile_fwd("uni", world, slots=slots)
    return np.asarray(prog.col(schedule.CONSUME_SLOT), dtype=np.int64)


def fused_bwd_slot_schedule(world: int, slots: int):
    """Host-side slot schedule of the fused ring BACKWARD kernel
    (ops/fused_ring_bwd.py): [world] int array where entry r is the
    communication-buffer slot holding (a) the q-side bundle (delta|o, do,
    q, lse) and (b) the arriving dq partial a device consumes at backward
    ring round r.

    The two concurrent streams share one slot cycle but live in DISJOINT
    buffers with disjoint semaphores, and their sends are phase-shifted:
    the bundle for round r+1 leaves at round r's FIRST grid step (like the
    forward's KV rotation), while the dq partial for round r+1 streams out
    block-by-block DURING round r, each block sent as soon as its local
    contribution is folded in — "one hop behind the bundle".  Round world-1
    does not send the bundle onward; its dq blocks take the final
    return-home hop into the right neighbor's dedicated home slot (index
    `min(slots, world)`, outside this cycle) instead.

    burstlint re-derives this schedule independently and proves both
    streams by simulation (analysis/oracle.verify_fused_ring_bwd):
    neighbor-only sends, world-1 ring hops per bundle, every dq partial
    arriving home exactly once with all `world` contributions, and no slot
    overwritten before its last read under the capacity handshake.

    Like fused_slot_schedule, now a view of the compiled "uni" backward
    program (parallel/schedule.compile_bwd).
    """
    import numpy as np

    from . import schedule

    if world < 1 or slots < 2:
        raise ValueError(f"need world >= 1 and slots >= 2, got "
                         f"world={world}, slots={slots}")
    prog = schedule.compile_bwd("uni", world, slots=slots, dq_slots=slots)
    return np.asarray(prog.col(schedule.CONSUME_SLOT), dtype=np.int64)


def partition_at_round(r, intra_axis: str, inter_axis):
    """Global partition id of the KV (fwd) / query-side (bwd) payload held at
    0-indexed ring round r under the (double-)ring schedule.

    With the forward rotation i -> i+1, after c inter hops and s intra hops a
    device holds the payload of (inter_rank - c, intra_rank - s); flattened
    partition id = inter*I + intra.  Matches the reference's formula
    (burst_attn_interface.py:27-36) and, for a single ring, is equivalent to
    its `round_r = r` shortcut (the <=-rank comparisons agree).
    """
    inter_rank, intra_rank, inter_size, intra_size = axis_ranks(intra_axis, inter_axis)
    c = r // intra_size
    s = r % intra_size
    return ((inter_rank - c) % inter_size) * intra_size + (intra_rank - s) % intra_size
