"""Ulysses (DeepSpeed-style) all-to-all sequence parallelism.

Absent from the reference (SURVEY.md §2.4: "no all-to-all anywhere") but a
natural complement to the burst ring: instead of rotating KV around a ring,
each device exchanges its sequence shard for a head shard (one all-to-all),
runs FULL-sequence attention on its subset of heads, and exchanges back.

Trade-offs vs the ring (why both belong in the framework):
  * comm volume: 2 all-to-alls of the activations vs W-1 KV rotations —
    Ulysses moves less data when W is large and heads are plentiful;
  * no causal load-balance problem: every device sees the full sequence, so
    plain causal masking is already balanced (no zigzag/striped layouts);
  * hard cap: parallelism cannot exceed the KV head count (GQA limits it),
    where the ring scales with sequence length alone.

TPU mapping: `lax.all_to_all` along the mesh axis inside shard_map (XLA
lowers it onto ICI), local attention = the Pallas flash kernel (or the jnp
tile off-TPU); differentiable end to end, so no hand-written VJP is needed —
the transpose of all-to-all is all-to-all and XLA inserts it.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from ..utils.compat import shard_map


def _local_attention(q, k, v, scale, causal, backend, block_q, block_kv,
                     window=None, segment_ids=None):
    if backend == "pallas":
        from ..ops.pallas_flash import flash_attention

        return flash_attention(q, k, v, scale, causal, block_q, block_kv,
                               window=window, segment_ids=segment_ids)
    from ..ops.tile import single_device_attention

    return single_device_attention(q, k, v, scale, causal, window=window,
                                   segment_ids=segment_ids)


def _ulysses_shard(q, k, v, seg=None, *, axis, scale, causal, backend,
                   block_q, block_kv, window=None):
    """Per-shard [B, N, S/W, D] -> [B, N, S/W, D] with full-seq attention on
    N/W heads in between.  `seg` [B, S] (the FULL sequence's packed ids,
    replicated — after the all-to-all every device holds the whole
    sequence, so the ids need no exchange)."""
    # scatter heads (axis 1), gather sequence (axis 2)
    qh = lax.all_to_all(q, axis, split_axis=1, concat_axis=2, tiled=True)
    kh = lax.all_to_all(k, axis, split_axis=1, concat_axis=2, tiled=True)
    vh = lax.all_to_all(v, axis, split_axis=1, concat_axis=2, tiled=True)
    o = _local_attention(qh, kh, vh, scale, causal, backend, block_q, block_kv,
                         window, segment_ids=seg)
    # scatter sequence back, gather heads
    return lax.all_to_all(o, axis, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attn(
    q,
    k,
    v,
    *,
    mesh,
    seq_axis: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
    backend: str = "auto",
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    batch_axes=None,
    head_axes=None,
    window: Optional[int] = None,
    segment_ids=None,
) -> jax.Array:
    """All-to-all sequence-parallel attention on global [B, N, S, D] arrays.

    S is sharded over `seq_axis` in NATURAL token order (no ring layouts);
    `head_axes` optionally shards heads over a tensor-parallel axis riding
    alongside (the all-to-all then exchanges the LOCAL heads of each tp
    group).  Requires per-tp-group head counts divisible by the seq axis
    size W for both q and kv heads.  `segment_ids` [B, S] int32 packs
    multiple documents (attention stays in-segment); the ids enter the
    shard replicated over the sequence axis.
    """
    from .burst import _resolve_backend

    w = mesh.shape.get(seq_axis, 1)
    tp = 1
    if head_axes is not None:
        for a in ((head_axes,) if isinstance(head_axes, str) else head_axes):
            tp *= mesh.shape.get(a, 1)
    if (q.shape[1] // tp) % w or (k.shape[1] // tp) % w:
        raise ValueError(
            f"ulysses needs per-group q heads {q.shape[1]}/{tp} and kv heads "
            f"{k.shape[1]}/{tp} divisible by the '{seq_axis}' axis size {w}"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window is not None and not causal:
        raise ValueError("window attention requires causal=True")
    from ..ops.tuning import resolve_blocks

    block_q, block_kv = resolve_blocks(block_q, block_kv)[:2]
    shard = partial(
        _ulysses_shard,
        axis=seq_axis,
        scale=scale,
        causal=causal,
        backend=_resolve_backend(backend),
        block_q=block_q,
        block_kv=block_kv,
        window=window,
    )
    qkv_spec = P(batch_axes, head_axes, seq_axis, None)
    if segment_ids is not None:
        fn = shard_map(
            shard,
            mesh=mesh,
            in_specs=(qkv_spec,) * 3 + (P(batch_axes, None),),
            out_specs=qkv_spec,
            check_vma=False,
        )
        return fn(q, k, v, jnp.asarray(segment_ids, jnp.int32))
    fn = shard_map(
        shard,
        mesh=mesh,
        in_specs=(qkv_spec,) * 3,
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v)
