"""Burst (ring) attention — the core distributed op.

TPU-native rebuild of the reference's OpBurstAttn / OpBurstAttnStrip
(burst_attn/burst_attn_interface.py:161-613):

  torch.autograd.Function            -> jax.custom_vjp (burst_attn_shard)
  Python ring loop + CUDA streams +
    double buffers (comm.py:267-301) -> lax.scan whose body issues the
                                        collective-permute BEFORE the tile
                                        compute; XLA's async collective
                                        permute gives the comm/compute
                                        overlap, the scan carry is the
                                        double buffer
  NCCL P2P ring                      -> lax.ppermute on a named mesh axis
  double ring (intra-node ring nested
    in inter-node ring, inter hop
    prefetched one intra-cycle early
    on its own stream, comm.py:221-254) -> a static Python loop over inter
                                        cycles: the inter-axis ppermute of
                                        the cycle base is issued at cycle
                                        start and consumed at cycle end, so
                                        XLA has the whole intra cycle to
                                        hide the DCN hop
  dq add-and-forward ring
    (comm.py:187-218)                -> dq_intra rotates with the q-side
                                        payload; at each cycle boundary it is
                                        folded into an inter-ring running sum
                                        and restarted at zero; one final
                                        inter+intra hop returns dq home
  causal zigzag 3-way case split /
    striped shift-by-one slicing     -> one uniform tile parameterized by
                                        runtime MaskSpec scalars (ops/masks.py)

Data conventions: per-shard q, k, v are [B, N, S_local, D] ("bnsd"), where the
global sequence is permuted into layout order (parallel/layouts.py) and
chunked device-major over (inter, intra): partition id = inter_rank *
intra_size + intra_rank.
"""

import logging
from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import obs
from ..obs import devstats
from ..ops import tile as jnp_tile
from ..ops.masks import (full_spec, live_round_prefix, round_spec, spec_live,
                         spec_pair_count)
from .ring import (ppermute_by, ppermute_next, my_partition,
                   partition_at_round, ring_round_counts,
                   wire_dequantize, wire_quantize)
from ..utils.compat import axis_size, shard_map

logger = logging.getLogger("burst_attn_tpu")

# -- obs dispatch instrumentation (host boundary only — see _note_dispatch).
# These counters advance when a program is DISPATCHED (once per trace under
# jit, once per call eagerly): the unit for "which path did the ring take",
# not per-step execution counts (docs/observability.md, "per-trace").
_M_DISPATCH = obs.counter(
    "burst.dispatch", "ring dispatches by path (fused kernel vs scan ring)")
_M_FALLBACK = obs.counter(
    "burst.fused_fallback", "fused_ring dispatches declined, by reason")
_M_ROUNDS = obs.counter(
    "burst.ring_rounds", "scheduled ring rounds (incl. the self round)")
_M_HOPS = obs.counter(
    "burst.ring_hops", "scheduled KV ring hops, by mesh axis role")
_M_WIRE = obs.counter(
    "burst.wire_bytes",
    "scheduled ring payload bytes per round by pass and stream "
    "(parallel/schedule.wire_round_bytes; shrinks under cfg.wire_dtype)")


@dataclass(frozen=True)
class BurstConfig:
    """Static configuration for burst attention.

    Mirrors the kwargs of the reference's burst_attn_func
    (burst_attn_interface.py:135-158); process_group/double_group become mesh
    axis names, the flash/triton/math backend switch becomes jnp vs pallas,
    and `deterministic` is always true on TPU (XLA reductions are
    deterministic) — kept for API parity.
    """

    causal: bool = False
    layout: str = "zigzag"  # "zigzag" | "striped" | "contig" (causal schedule)
    scale: Optional[float] = None  # default 1/sqrt(head_dim)
    intra_axis: str = "sp"
    inter_axis: Optional[str] = None  # set for the hierarchical double ring
    # "jnp" | "pallas" | "fused_ring".  fused_ring runs the WHOLE forward
    # ring inside one Pallas kernel with in-kernel RDMA KV rotation
    # (ops/fused_ring.py); configs the fused kernel does not cover (double
    # ring, window, segments, cross-attention, off-TPU without
    # BURST_FUSED_INTERPRET) and the backward fall back to the scan ring
    # with the pallas (TPU) / jnp (CPU) tile backend.
    backend: str = "jnp"
    optimize_bwd_comm: bool = True  # rotate delta=sum(o*do) [B,N,S] f32, not o
    # kernel blocks; None = resolved from the per-TPU-generation table
    # (ops/tuning.py) by resolved_blocks() in the tile dispatch, with bwd
    # blocks never defaulting larger than the fwd ones (a caller who tunes
    # block_q/block_kv down for VMEM keeps that budget in the backward too).
    # burst_attn() pre-resolves these at construction.
    block_q: Optional[int] = None
    block_kv: Optional[int] = None
    block_q_bwd: Optional[int] = None
    block_kv_bwd: Optional[int] = None
    deterministic: bool = True
    # Sliding-window (band) causal attention: each query sees its last
    # `window` positions.  Contig layout only (ops/masks.round_spec explains
    # why the load-balancing permutations can't express a band); rounds
    # wholly outside the band are dead and skipped block-wise.
    window: Optional[int] = None
    # Packed-segment length bound: a PROMISE that no segment in the
    # segment_ids the caller feeds spans more than this many tokens.  It is
    # a CONTRACT, not a runtime check (ids are traced values — validating
    # per batch would defeat jit): with contig-causal single rings the
    # occupancy compiler uses it to ELIDE ring rounds whose chunk distance
    # exceeds the bound (ops/masks.live_delta_table), exactly like `window`
    # elides rounds past the band.  Ids that break the promise silently
    # drop attention pairs.  Ignored by zigzag/striped (their token
    # interleaving defeats any per-round distance bound) and by non-causal
    # rings (wrap-around makes the live set a non-prefix band).
    max_segment_len: Optional[int] = None
    # Wire precision of the ROTATING ring payloads (ROADMAP item 5): None
    # ships the caller's dtypes bit-exactly; "int8"/"fp8" quantize the fwd
    # K/V blocks, the bwd q-side bundle (delta|o, do, q — lse stays fp32)
    # and the fp32 dq partials to 1 B/elem, with per-block fp32 SCALAR
    # scales riding the same payload (scan ring: extra pytree leaves in the
    # rotating tuple; fused kernels: parallel scale slot banks on the same
    # semaphores/credits, ops/fused_ring*.py).  fp32 ACCUMULATION is never
    # touched — every quantized tensor is rescaled before any dot/add, like
    # ops/ragged_paged.py's int8 pool path — so the cost is a pinned
    # quantization tolerance, not a different algorithm.  Resident tensors
    # and the purely-local math never see the wire dtype.
    wire_dtype: Optional[str] = None
    # Fused ring kernel knobs (backend="fused_ring" only): KV communication
    # slot count (>= 2) and the fused grid's q-row / kv-sweep blocks; None =
    # the per-TPU-generation table (ops/tuning.py resolve_fused).  The
    # *_bwd / bwd_slots trio tunes the fused BACKWARD kernel (bundle + dq
    # ring, ops/fused_ring_bwd.py) independently.
    fused_kv_slots: Optional[int] = None
    fused_block_q: Optional[int] = None
    fused_block_kv: Optional[int] = None
    fused_bwd_slots: Optional[int] = None
    fused_block_q_bwd: Optional[int] = None
    fused_block_kv_bwd: Optional[int] = None
    # Schedule-IR topology selection (parallel/schedule.py): "auto" = uni
    # on a flat ring, double when an inter axis (or fused_seq_factor) is
    # present; "bidi" opts the flat ring into the counter-rotating
    # bidirectional schedule (both ICI directions, per-direction slot
    # banks; worlds < 3 degrade to uni).  fused_seq_factor = (n_inter,
    # n_intra) grids the DOUBLE-ring schedule onto a flat ring axis
    # (inter-major device order) — the schedule the reference's
    # hierarchical ring runs, without needing a second mesh axis.
    fused_topology: str = "auto"
    fused_seq_factor: Optional[Tuple[int, int]] = None
    # per-direction slot knobs for the second bank (bidi ccw / double
    # inter prefetch); None = the per-generation table (ops/tuning.py)
    fused_ccw_slots: Optional[int] = None
    fused_bwd_ccw_slots: Optional[int] = None
    # Ordered ((axis name, size), ...) of ALL mesh axes, host-filled by
    # burst_attn: the fused kernels compute full LOGICAL RDMA ids from it
    # (parallel/ring.device_roles), which is what makes multi-axis
    # (pp x tp x sp) meshes safe to fuse.  None = the ring axes are the
    # only axes in scope (direct burst_attn_shard users on bigger meshes
    # fall back to the scan ring unless they fill this in).
    mesh_axes: Optional[Tuple[Tuple[str, int], ...]] = None
    # Structural causal scheduling (reference burst_attn_interface.py:221-235,
    # :303-367): zigzag rounds dispatch through a 3-way lax.cond whose
    # branches run statically-sliced dense tiles (full q x half kv / half q x
    # full kv) or a triangular-grid causal tile, instead of one uniform
    # masked tile whose rectangular grid is ~half dead steps.  Striped rounds
    # use the triangular grid directly (every round is full-window causal).
    case_split: bool = True

    def __post_init__(self):
        # validate here, not only in burst_attn(): direct BurstConfig users
        # (burst_attn_shard inside their own shard_map, the pp trainer)
        # must hit the same wall — a window on a zigzag/striped ring would
        # silently band the PERMUTED local order
        if self.window is not None:
            if self.layout != "contig":
                raise ValueError(
                    "window attention requires layout='contig' (the "
                    "zigzag/striped load-balancing permutations break the "
                    f"band structure); got layout={self.layout!r}")
            if not self.causal:
                raise ValueError("window attention requires causal=True")
            if self.window < 1:
                raise ValueError(f"window must be >= 1, got {self.window}")
        if self.max_segment_len is not None and self.max_segment_len < 1:
            raise ValueError(
                f"max_segment_len must be >= 1, got {self.max_segment_len}")
        if self.wire_dtype not in (None, "int8", "fp8"):
            raise ValueError(
                f"wire_dtype must be None, 'int8' or 'fp8', got "
                f"{self.wire_dtype!r}")
        if self.fused_topology not in ("auto", "uni", "bidi", "double"):
            raise ValueError(
                f"fused_topology must be auto|uni|bidi|double, got "
                f"{self.fused_topology!r}")
        if self.fused_seq_factor is not None:
            f = tuple(self.fused_seq_factor)
            if len(f) != 2 or any(x < 1 for x in f):
                raise ValueError(
                    f"fused_seq_factor must be (n_inter, n_intra) positive "
                    f"ints, got {self.fused_seq_factor!r}")
            object.__setattr__(self, "fused_seq_factor", f)
        if self.mesh_axes is not None:
            object.__setattr__(self, "mesh_axes",
                               tuple((str(a), int(sz))
                                     for a, sz in self.mesh_axes))

    def resolved_blocks(self):
        """ResolvedBlocks with None fields filled from the
        per-TPU-generation table (ops/tuning.py) — the one source of block
        defaults."""
        from ..ops.tuning import resolve_blocks

        return resolve_blocks(self.block_q, self.block_kv,
                              self.block_q_bwd, self.block_kv_bwd)


# ---------------------------------------------------------------------------
# tile dispatch


def _tile_backend(cfg) -> str:
    """Per-round tile backend.  "fused_ring" maps to the equivalent tile
    backend for everything that stays on the scan ring — the backward pass
    and any forward the fused kernel declines (see _fwd_impl) — so a
    fused_ring config degrades to the best scan path instead of erroring."""
    if cfg.backend != "fused_ring":
        return cfg.backend
    return "pallas" if jax.default_backend() == "tpu" else "jnp"


def _tile_fwd(cfg, q, k, v, m, lse, acc, scale, spec, triangular=False,
              segments=None):
    if _tile_backend(cfg) == "pallas":
        from ..ops import pallas_flash

        rb = cfg.resolved_blocks()
        bq, bkv = rb.block_q, rb.block_kv
        return pallas_flash.flash_fwd(
            q, k, v, m, lse, acc, scale, spec,
            block_q=bq, block_kv=bkv, triangular=triangular,
            window=cfg.window, segments=segments,
        )
    if m is None:
        # jnp oracle has no None-carry fast path; materialize the empty
        # state it stands for (CPU-only — XLA folds the constants anyway)
        b, n, s, d = q.shape
        m, lse, acc = jnp_tile.init_state(b, n, s, d)
    return jnp_tile.tile_fwd(q, k, v, m, lse, acc, scale, spec,
                             window=cfg.window, segments=segments)


def _tile_bwd(cfg, do, q, k, v, delta, lse, scale, spec, triangular=False,
              segments=None):
    if _tile_backend(cfg) == "pallas":
        from ..ops import pallas_flash

        rb = cfg.resolved_blocks()
        bq, bkv = rb.block_q_bwd, rb.block_kv_bwd
        return pallas_flash.flash_bwd(
            do, q, k, v, delta, lse, scale, spec, block_q=bq, block_kv=bkv,
            triangular=triangular, window=cfg.window, segments=segments,
        )
    return jnp_tile.tile_bwd(do, q, k, v, delta, lse, scale, spec,
                             window=cfg.window, segments=segments)


def _sizes(cfg):
    intra = axis_size(cfg.intra_axis)
    inter = axis_size(cfg.inter_axis) if cfg.inter_axis is not None else 1
    return inter, intra


def _r_live(cfg, s, s_kv, n_inter, n_intra):
    """Static live-round count of a truncatable SINGLE contig ring (shared
    by fwd and bwd — the two passes' truncation must stay in lockstep with
    ops/masks' occupancy algebra).  Both the window band and the
    max_segment_len reach bound produce a live-round PREFIX on contig
    causal rings (masks.live_round_prefix; windowed rings reproduce the
    historical closed form min(W, (s + window - 2) // s + 1)).  n_intra =
    no truncation."""
    if ((cfg.window is not None or cfg.max_segment_len is not None)
            and cfg.layout == "contig" and cfg.causal
            and n_inter == 1 and n_intra > 1 and s_kv == s):
        return live_round_prefix(
            "contig", s, n_intra, causal=True, window=cfg.window,
            max_segment_len=cfg.max_segment_len)
    return n_intra


# ---------------------------------------------------------------------------
# forward


def _fwd_impl(q, k, v, cfg: BurstConfig, seg=None, collect=False):
    """Ring forward. Per-shard shapes q [B,N,S,D], k/v [B,Nk,S,D].

    Reference call stack SURVEY.md §3.1 / burst_attn_interface.py:170-253.
    Returns (o, lse) with o [B,N,S,D] in q.dtype, lse [B,N,S] f32 — plus a
    per-shard obs.devstats.DevStats as a third element when `collect`.

    `collect` is a STATIC flag: every stats equation sits behind
    `if collect`, so the collect=False trace is bit-identical to a build
    without devstats at all (proved by burstlint `devstats-pure`).

    `seg` [B, S] int32 (optional): packed-sequence ids for the LOCAL shard,
    in the same layout order as q/k/v.  The kv-side ids ride the KV ring
    (one extra tiny int32 array in the rotating payload); the q-side ids
    stay resident.  Attention never crosses a segment boundary.
    """
    if cfg.backend == "fused_ring":
        # tentpole fast path: the whole W-round ring in one Pallas kernel
        # with in-kernel RDMA KV rotation (ops/fused_ring.py).  Declined
        # configs fall through to the scan ring below with the tile backend
        # from _tile_backend (the returned reason says why — logged once
        # per trace so a silently-degraded bench run is visible).
        from ..ops import fused_ring

        reason = fused_ring.supported(cfg, q.shape, k.shape, seg is not None)
        if reason is None:
            return fused_ring.fused_ring_fwd(q, k, v, cfg, seg=seg,
                                             collect_stats=collect)
        logger.info("fused_ring backend falling back to the scan ring: %s",
                    reason)

    b, n, s, d = q.shape
    scale = cfg.scale if cfg.scale is not None else d**-0.5
    n_inter, n_intra = _sizes(cfg)
    part_me = my_partition(cfg.intra_axis, cfg.inter_axis)
    wire = cfg.wire_dtype

    def compute(st, kv_c, r):
        kv_part = partition_at_round(r, cfg.intra_axis, cfg.inter_axis)
        if wire is not None:
            # rescale-on-consume: dequantize the rotating payload to the
            # compute dtype BEFORE any tile math — the fp32 accumulation
            # below never sees the wire dtype
            if seg is not None:
                k8, ksc, v8, vsc, kvseg_c = kv_c
            else:
                k8, ksc, v8, vsc = kv_c
                kvseg_c = None
            k_c = wire_dequantize(k8, ksc, k.dtype)
            v_c = wire_dequantize(v8, vsc, v.dtype)
        elif seg is not None:
            k_c, v_c, kvseg_c = kv_c
        else:
            k_c, v_c = kv_c
            kvseg_c = None
        segs = None if seg is None else (seg, kvseg_c)
        s_kv = k_c.shape[2]
        if cfg.causal and cfg.case_split and cfg.layout == "zigzag" and s_kv == s:
            # 3-way structural split (reference burst_attn_interface.py:221-235)
            half = s // 2

            def eq_case(st):
                # own partition: plain causal on the local layout
                spec = round_spec(part_me, part_me, s, s_kv, True, "zigzag")
                return _tile_fwd(cfg, q, k_c, v_c, *st, scale, spec,
                                 triangular=True, segments=segs)

            def past_case(st):
                # kv's first half entirely in the local past: dense half-kv
                return _tile_fwd(
                    cfg, q, k_c[:, :, :half], v_c[:, :, :half], *st, scale,
                    full_spec(s, half),
                    segments=None if seg is None else (seg, kvseg_c[:, :half]),
                )

            def future_case(st):
                # only the local q's second half attends (to all of kv)
                m, lse, acc = st
                m2, lse2, acc2 = _tile_fwd(
                    cfg, q[:, :, half:], k_c, v_c,
                    m[:, :, half:], lse[:, :, half:], acc[:, :, half:],
                    scale, full_spec(s - half, s_kv),
                    segments=None if seg is None else (seg[:, half:], kvseg_c),
                )
                # write the updated half back in place rather than
                # rebuilding the full [B,N,S,D] f32 state via concatenate —
                # one fewer full-state HBM copy per future round
                upd = lambda a, bpart: lax.dynamic_update_slice_in_dim(
                    a, bpart, half, axis=2)
                return upd(m, m2), upd(lse, lse2), upd(acc, acc2)

            return lax.cond(
                kv_part == part_me, eq_case,
                lambda st: lax.cond(kv_part < part_me, past_case, future_case, st),
                st,
            )
        if cfg.causal and cfg.case_split and cfg.layout == "striped" and s_kv == s:
            # every striped round is full-window causal (offset 0 or -1):
            # the triangular grid applies round-independently
            spec = round_spec(part_me, kv_part, s, s_kv, True, "striped")
            return _tile_fwd(cfg, q, k_c, v_c, *st, scale, spec,
                             triangular=True, segments=segs)
        spec = round_spec(part_me, kv_part, s, s_kv, cfg.causal, cfg.layout,
                          window=cfg.window)
        if cfg.layout == "contig" and cfg.causal:
            # contig-causal rings have provably dead rounds (futures; with a
            # window also everything beyond the band's reach): skip the
            # whole kernel launch, not just its blocks (ops/masks.spec_live).
            # Windowed LIVE rounds also take the BAND grid: every live
            # round's offset is a nonneg multiple of the chunk length
            # (delta = r*s, and blocks divide s or flash_fwd's ragged path
            # declines the grid), so delta ≡ 0 (mod bkv) and the band
            # alignment enumeration behind fwd_band_nb applies round-
            # independently — the kernel's _kv_jmin/_kv_jmax read the
            # traced offset.  The bwd side has been banded since round 3
            # (bwd_band_nbq in the rect fused sweep).
            band = cfg.window is not None
            return lax.cond(
                spec_live(spec, cfg.window),
                lambda st_: _tile_fwd(cfg, q, k_c, v_c, *st_, scale, spec,
                                      triangular=band, segments=segs),
                lambda st_: st_,
                st)
        return _tile_fwd(cfg, q, k_c, v_c, *st, scale, spec, segments=segs)

    def round_tally(r):
        # devstats (collect only): one round's (live, attended pairs) from
        # the UNIFORM mask spec — by construction the same attended set the
        # case-split branches compute (ops/masks.py module docstring), so
        # the tally is layout-exact without touching the kernels.
        kv_part = partition_at_round(r, cfg.intra_axis, cfg.inter_axis)
        sp_u = round_spec(part_me, kv_part, s, k.shape[2], cfg.causal,
                          cfg.layout, window=cfg.window)
        return (spec_live(sp_u, cfg.window).astype(jnp.int32),
                spec_pair_count(sp_u, s, k.shape[2], cfg.window))

    def tally_add(dv, r):
        live, pairs = round_tally(r)
        return dv[0] + live, dv[1] + pairs

    # Static round truncation (windowed single ring): round r's kv offset is
    # delta = r*s for r <= me and negative (future, dead) past that, so
    # every round >= r_live is dead on EVERY device — don't run them and
    # don't pay their kv permutes.  (The double ring keeps the per-round
    # lax.cond skip instead: its visit order interleaves inter hops, so the
    # live set is not a prefix.)
    r_live = _r_live(cfg, s, k.shape[2], n_inter, n_intra)

    if wire is None:
        kv = (k, v) if seg is None else (k, v, seg)
    else:
        # Quantize ONCE at ring entry with per-(batch, kv-head) scalar
        # scales (amax over the local (s, d) chunk): the KV payload rotates
        # unchanged, so quantize-at-entry is exactly quantize-on-send on
        # every hop.  The peeled self round below still reads the resident
        # full-precision k/v — only bytes that actually cross a link are
        # quantized.  The int32 seg ids ride unquantized.
        k8, ksc = wire_quantize(k, wire, (2, 3))
        v8, vsc = wire_quantize(v, wire, (2, 3))
        kv = (k8, ksc, v8, vsc) if seg is None else (k8, ksc, v8, vsc, seg)
    kv_base = kv

    # Round 0 is ALWAYS the self round (partition_at_round(0) == part_me:
    # c = s = 0 in ring.py:81-94), so it is peeled out of the scan with a
    # STATICALLY EMPTY carry: the kernel seeds its state from constants
    # (flash_fwd m=lse=acc=None) instead of reading a materialized
    # init_state — the [B,N,S,D] f32 zeros accumulator never exists in
    # HBM.  Self-rounds are also exactly the full-window-causal specs the
    # triangular / band grids require, so every layout's round 0 gets the
    # all-live grid, including contig (whose later rounds are
    # offset-shifted and stay rectangular).
    segs0 = None if seg is None else (seg, seg)
    spec0 = round_spec(part_me, part_me, s, k.shape[2], cfg.causal,
                       cfg.layout, window=cfg.window)
    tri0 = cfg.causal and k.shape[2] == s
    # obs.* named scopes: per-round xprof labels matching the span naming
    # convention (docs/observability.md) — metadata only, no equations
    with jax.named_scope("obs.ring.round0_self"):
        state = _tile_fwd(cfg, q, k, v, None, None, None, scale, spec0,
                          triangular=tri0, segments=segs0)
    if collect:
        # devstats accumulators ride NEXT TO the state; every touch is
        # behind `if collect` so the stats-off trace stays bit-identical
        dv = tally_add((jnp.int32(0), jnp.float32(0.0)), jnp.int32(0))
        rounds_exec = 1

    for c in range(n_inter):
        if c < n_inter - 1:
            # prefetch next cycle's base one full intra-cycle early
            # (reference: comm.py:229-237); consumed at the cycle boundary.
            kv_base_next = ppermute_next(kv_base, cfg.inter_axis)
        start = 1 if c == 0 else 0  # cycle 0's round 0 was peeled above
        if c == 0 and r_live == 1:
            # the peel was cycle 0's only live round; no intra permutes
            if c < n_inter - 1:
                kv = kv_base = kv_base_next
            continue
        if c == 0:
            kv = ppermute_next(kv, cfg.intra_axis)  # round-0 send
        if r_live - 1 > start:

            def body(carry, s_idx, c=c):
                if collect:
                    kv_c, st, dv_c = carry
                else:
                    kv_c, st = carry
                kv_next = ppermute_next(kv_c, cfg.intra_axis)  # overlaps compute
                st = compute(st, kv_c, c * n_intra + s_idx)
                if collect:
                    dv_c = tally_add(dv_c, c * n_intra + s_idx)
                    return (kv_next, st, dv_c), None
                return (kv_next, st), None

            with jax.named_scope(f"obs.ring.cycle{c}.scan_rounds"):
                if collect:
                    (kv, state, dv), _ = lax.scan(
                        body, (kv, state, dv), jnp.arange(start, r_live - 1))
                    rounds_exec += r_live - 1 - start
                else:
                    (kv, state), _ = lax.scan(body, (kv, state),
                                              jnp.arange(start, r_live - 1))
        # last round of the cycle: no intra send (reference comm.py:238-251)
        with jax.named_scope(f"obs.ring.cycle{c}.last_round"):
            state = compute(state, kv, jnp.int32(c * n_intra + r_live - 1))
        if collect:
            dv = tally_add(dv, jnp.int32(c * n_intra + r_live - 1))
            rounds_exec += 1
        if c < n_inter - 1:
            kv = kv_base = kv_base_next
    m, lse, acc = state
    o = jnp_tile.finalize(m, lse, acc, q.dtype)
    if collect:
        qam = jnp.float32(0.0)
        if wire is not None:
            # finite-range gauge: the largest |value| the wire quantizer
            # mapped to its top code this dispatch (saturating blocks show
            # up as a growing gauge, not silent clipping)
            qam = jnp.maximum(jnp.max(jnp.abs(k.astype(jnp.float32))),
                              jnp.max(jnp.abs(v.astype(jnp.float32))))
        stats = devstats.ring_stats(
            rounds=rounds_exec, rounds_live=dv[0], attn_pairs=dv[1],
            total_pairs=float(rounds_exec) * s * k.shape[2], head_dim=d,
            rounds_elided=n_inter * n_intra - rounds_exec,
            m=m, lse=lse, acc=acc, quant_absmax=qam)
        return o, lse, stats
    return o, lse


# ---------------------------------------------------------------------------
# backward


def _bwd_impl(cfg: BurstConfig, q, k, v, o, lse, do, seg=None):
    """Communication-optimized ring backward (SURVEY.md §3.2).

    K, V stay resident; the query-side payload (delta|o, do, q, lse) rotates
    like KV did in forward; dq rides a concurrent accumulating ring and is
    returned home by one extra hop (burst_attn_interface.py:255-398).
    With packed sequences (`seg`), the q-side ids rotate with the payload
    while the resident kv side keeps the local ids.

    This is the ONE backward dispatch point (all four custom_vjp twins
    route here): with `backend="fused_ring"` both rotating streams run
    inside a single Pallas kernel (ops/fused_ring_bwd.py) when the bwd
    gate admits the config; declined configs fall through to the scan
    ring below with the tile backend from _tile_backend.
    """
    if cfg.backend == "fused_ring":
        from ..ops import fused_ring, fused_ring_bwd

        reason = fused_ring.supported(cfg, q.shape, k.shape, seg is not None,
                                      pass_="bwd")
        if reason is None:
            return fused_ring_bwd.fused_ring_bwd(cfg, q, k, v, o, lse, do,
                                                 seg=seg)
        logger.info("fused_ring backward falling back to the scan ring: %s",
                    reason)

    b, n, s, d = q.shape
    scale = cfg.scale if cfg.scale is not None else d**-0.5
    n_inter, n_intra = _sizes(cfg)
    part_me = my_partition(cfg.intra_axis, cfg.inter_axis)

    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    if cfg.optimize_bwd_comm:
        # ring payload shrinks by a factor of head_dim
        # (reference burst_attn_interface.py:269-278)
        payload = (delta, do, q, lse)
    else:
        payload = (o, do, q, lse)
    if seg is not None:
        payload = payload + (seg,)

    wire = cfg.wire_dtype
    if wire is not None:
        # quantize the q-side bundle once at ring entry (it rotates
        # unchanged): per-(batch, head) scalar scales; lse stays fp32 (its
        # absolute accuracy sets every softmax rescale downstream)
        first_p, do_p, q_p, lse_p = payload[:4]
        f8, fsc = wire_quantize(first_p, wire,
                                (2,) if cfg.optimize_bwd_comm else (2, 3))
        do8, dosc = wire_quantize(do_p, wire, (2, 3))
        q8, qsc = wire_quantize(q_p, wire, (2, 3))
        payload = (f8, fsc, do8, dosc, q8, qsc, lse_p) + payload[4:]

    if wire is None:
        dq_hop = ppermute_next
    else:
        def dq_hop(g, axis):
            # the dq add-and-forward ring: quantize-before-send with a
            # REFRESHED per-(batch, head) scale (the partial grew by one
            # local contribution since the last hop), dequantize-after-
            # receive back to fp32 — the fold itself stays full precision
            g8, gsc = wire_quantize(g, wire, (2, 3))
            g8, gsc = ppermute_next((g8, gsc), axis)
            return wire_dequantize(g8, gsc, jnp.float32)

    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    dq_intra = jnp.zeros(q.shape, jnp.float32)
    dq_inter = jnp.zeros(q.shape, jnp.float32)

    def compute(pay, r):
        q_part = partition_at_round(r, cfg.intra_axis, cfg.inter_axis)
        # roles flip vs forward: the rotating payload is the query side,
        # local k/v are resident.
        if wire is not None:
            f8, fsc, do8, dosc, q8, qsc, lse_r = pay[:7]
            qseg_r = pay[7] if seg is not None else None
            first = wire_dequantize(
                f8, fsc, jnp.float32 if cfg.optimize_bwd_comm else o.dtype)
            do_r = wire_dequantize(do8, dosc, do.dtype)
            q_r = wire_dequantize(q8, qsc, q.dtype)
        elif seg is not None:
            first, do_r, q_r, lse_r, qseg_r = pay
        else:
            first, do_r, q_r, lse_r = pay
            qseg_r = None
        segs = None if seg is None else (qseg_r, seg)
        if cfg.optimize_bwd_comm:
            delta_r = first
        else:
            delta_r = jnp.sum(first.astype(jnp.float32) * do_r.astype(jnp.float32), axis=-1)
        if cfg.causal and cfg.case_split and cfg.layout == "zigzag":
            # 3-way structural split, bwd roles (reference :303-367)
            half = s // 2

            def eq_case(_):
                spec = round_spec(part_me, part_me, s, s, True, "zigzag")
                return _tile_bwd(cfg, do_r, q_r, k, v, delta_r, lse_r, scale,
                                 spec, triangular=True, segments=segs)

            def kv_past_case(_):
                # resident kv precedes the rotated q side: only kv's first
                # half participates -> dense tile, zero-padded dk/dv
                dq_c, dk_h, dv_h = _tile_bwd(
                    cfg, do_r, q_r, k[:, :, :half], v[:, :, :half],
                    delta_r, lse_r, scale, full_spec(s, half),
                    segments=None if seg is None else (qseg_r, seg[:, :half]),
                )
                pad = lambda g: jnp.concatenate(
                    [g, jnp.zeros((b,) + g.shape[1:2] + (s - half, d), g.dtype)], axis=2)
                return dq_c, pad(dk_h), pad(dv_h)

            def q_future_case(_):
                # only the rotated q side's second half attends
                dq_h, dk_c, dv_c = _tile_bwd(
                    cfg, do_r[:, :, half:], q_r[:, :, half:], k, v,
                    delta_r[:, :, half:], lse_r[:, :, half:],
                    scale, full_spec(s - half, s),
                    segments=None if seg is None else (qseg_r[:, half:], seg),
                )
                dq_c = jnp.concatenate(
                    [jnp.zeros((b, n, half, d), dq_h.dtype), dq_h], axis=2)
                return dq_c, dk_c, dv_c

            return lax.cond(
                q_part == part_me, eq_case,
                lambda _: lax.cond(part_me < q_part, kv_past_case, q_future_case, None),
                None,
            )
        if cfg.causal and cfg.case_split and cfg.layout == "striped":
            spec = round_spec(q_part, part_me, s, s, True, "striped")
            return _tile_bwd(cfg, do_r, q_r, k, v, delta_r, lse_r, scale, spec,
                             triangular=True, segments=segs)
        # cross-attention (s_kv_local != s): the resident kv side's length
        # comes from k, not from the rotating q payload
        spec = round_spec(q_part, part_me, s, k.shape[2], cfg.causal,
                          cfg.layout, window=cfg.window)
        if cfg.layout == "contig" and cfg.causal:
            # dead-round skip, bwd roles (fwd comment above): contribute
            # exact zeros without touching the kernels
            return lax.cond(
                spec_live(spec, cfg.window),
                lambda _: _tile_bwd(cfg, do_r, q_r, k, v, delta_r, lse_r,
                                    scale, spec, segments=segs),
                lambda _: (jnp.zeros((b, n, s, d), jnp.float32),
                           jnp.zeros(k.shape, jnp.float32),
                           jnp.zeros(v.shape, jnp.float32)),
                None)
        return _tile_bwd(cfg, do_r, q_r, k, v, delta_r, lse_r, scale, spec,
                         segments=segs)

    # Static round truncation, bwd roles (fwd comment in _fwd_impl): with the
    # q side rotating, round r's offset is delta = -r*s (dead causally) for
    # r <= me and (W-r)*s past the wrap — so the LIVE rounds are round 0
    # plus a tail of r_live-1 rounds at the end of the schedule.  The
    # payload jumps the dead middle in ONE ppermute (an arbitrary
    # permutation costs one collective regardless of hop count) instead of
    # paying a q-sized transfer per dead round.  Round 0's dq (the OWN
    # chunk's gradient) does not ride along at all — a full circle would
    # return it exactly where it started — it is held out in dq_home and
    # folded in after the ring's return-home hop.
    r_live = _r_live(cfg, s, k.shape[2], n_inter, n_intra)
    truncated = r_live < n_intra
    dq_home = None

    pay_base = payload
    for c in range(n_inter):
        if c < n_inter - 1:
            pay_base_next = ppermute_next(pay_base, cfg.inter_axis)
        if c > 0:
            # cycle boundary: fold the intra accumulator into the inter-ring
            # running sum (add-and-forward, reference comm.py:187-218) and
            # restart the intra accumulator at zero.
            dq_inter = dq_hop(dq_inter + dq_intra, cfg.inter_axis)
            dq_intra = jnp.zeros_like(dq_intra)
        # ---- first round of the cycle (r = c*I): no dq rotation ----
        dqc, dkc, dvc = compute(payload, jnp.int32(c * n_intra))
        if truncated:
            dq_home = dqc
        else:
            dq_intra = dq_intra + dqc
        dk = dk + dkc
        dv = dv + dvc
        if r_live > 1:
            # start == 1 without truncation; the jump is a single hop then.
            # dq_intra is still all-zero at the jump when truncated
            # (rotation-invariant), so only the payload travels.
            start = n_intra - (r_live - 1)
            payload = ppermute_by(payload, cfg.intra_axis, start)
            if n_intra - 1 > start:

                def body(carry, s_idx, c=c):
                    pay, dq_i, dk_c, dv_c = carry
                    pay_next = ppermute_next(pay, cfg.intra_axis)
                    # dq leaves with the payload it accumulated for; the
                    # arriving dq belongs to the payload we hold this round.
                    dq_rot = dq_hop(dq_i, cfg.intra_axis)
                    dqc, dkc, dvc = compute(pay, c * n_intra + s_idx)
                    return (pay_next, dq_rot + dqc, dk_c + dkc, dv_c + dvc), None

                (payload, dq_intra, dk, dv), _ = lax.scan(
                    body, (payload, dq_intra, dk, dv),
                    jnp.arange(start, n_intra - 1)
                )
            # ---- last round of the cycle: rotate dq but not the payload ----
            dq_rot = dq_hop(dq_intra, cfg.intra_axis)
            dqc, dkc, dvc = compute(payload, jnp.int32(c * n_intra + n_intra - 1))
            dq_intra = dq_rot + dqc
            dk = dk + dkc
            dv = dv + dvc
        if c < n_inter - 1:
            payload = pay_base = pay_base_next

    # final return-home hops (reference burst_attn_interface.py:391-396,
    # comm.py:206-216): fold, one inter hop, one intra hop; then the
    # held-out round-0 dq (truncated rings only — it never traveled).
    dq = dq_inter + dq_intra
    if cfg.inter_axis is not None:
        dq = dq_hop(dq, cfg.inter_axis)
    if r_live > 1:
        dq = dq_hop(dq, cfg.intra_axis)
    if dq_home is not None:
        dq = dq + dq_home
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp


def burst_attn_shard(q, k, v, cfg: BurstConfig, segment_ids=None,
                     collect_stats: bool = False):
    """Burst attention on per-shard arrays — call inside shard_map.

    q: [B, N, S_local, D]; k, v: [B, Nk, Skv_local, D] (GQA when Nk < N;
    Skv_local != S_local is CROSS-attention — non-causal contig only).
    segment_ids: optional [B, S_local] int32 packed-sequence ids for the
    LOCAL shard, in the same layout order as q/k/v (use
    layouts.to_layout(ids, layout, world, axis=1) for zigzag/striped).
    Returns o: [B, N, S_local, D] in q.dtype — or (o, DevStats) with
    per-shard in-graph ring telemetry when `collect_stats`
    (obs/devstats.py; the stats ride the forward, gradients are untouched).
    """
    if q.shape[2] != k.shape[2] and (
            cfg.causal or cfg.window is not None or segment_ids is not None):
        # causal cross-lengths have no defined diagonal alignment (and the
        # zigzag/striped bwd case splits assume equal shards); the single
        # segment_ids array covers both sides only when lengths match.
        # Fail here, loudly — the fwd would otherwise run and the bwd die
        # inside a lax.cond with an opaque shape error.
        raise ValueError(
            f"cross-attention (s_q {q.shape[2]} != s_kv {k.shape[2]}) "
            "supports non-causal contig without segment_ids only")
    if collect_stats:
        if segment_ids is None:
            return _burst_attn_shard_stats(q, k, v, cfg)
        return _burst_attn_shard_stats_seg(q, k, v, segment_ids, cfg)
    if segment_ids is None:
        return _burst_attn_shard_plain(q, k, v, cfg)
    return _burst_attn_shard_seg(q, k, v, segment_ids, cfg)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _burst_attn_shard_plain(q, k, v, cfg: BurstConfig):
    o, _ = _fwd_impl(q, k, v, cfg)
    return o


def _vjp_fwd(q, k, v, cfg):
    o, lse = _fwd_impl(q, k, v, cfg)
    return o, (q, k, v, o, lse)


def _vjp_bwd(cfg, residuals, do):
    q, k, v, o, lse = residuals
    dq, dk, dv = _bwd_impl(cfg, q, k, v, o, lse, do)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_burst_attn_shard_plain.defvjp(_vjp_fwd, _vjp_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _burst_attn_shard_seg(q, k, v, seg, cfg: BurstConfig):
    o, _ = _fwd_impl(q, k, v, cfg, seg=seg)
    return o


def _seg_vjp_fwd(q, k, v, seg, cfg):
    o, lse = _fwd_impl(q, k, v, cfg, seg=seg)
    return o, (q, k, v, seg, o, lse)


def _seg_vjp_bwd(cfg, residuals, do):
    import numpy as np

    q, k, v, seg, o, lse = residuals
    dq, dk, dv = _bwd_impl(cfg, q, k, v, o, lse, do, seg=seg)
    dseg = np.zeros(seg.shape, dtype=jax.dtypes.float0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dseg


_burst_attn_shard_seg.defvjp(_seg_vjp_fwd, _seg_vjp_bwd)


# stats-collecting twins: (o, DevStats) outputs, IDENTICAL backward.  The
# stats are forward-only telemetry — their cotangents are dropped and the
# residuals/bwd math are byte-for-byte the plain path's, so grads under
# collect_stats=True equal the plain grads bit-for-bit
# (tests/test_devstats.py asserts this on the 8-dev mesh).


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _burst_attn_shard_stats(q, k, v, cfg: BurstConfig):
    o, _, stats = _fwd_impl(q, k, v, cfg, collect=True)
    return o, stats


def _stats_vjp_fwd(q, k, v, cfg):
    o, lse, stats = _fwd_impl(q, k, v, cfg, collect=True)
    return (o, stats), (q, k, v, o, lse)


def _stats_vjp_bwd(cfg, residuals, cts):
    do, _dstats = cts  # stats are telemetry: cotangent ignored
    q, k, v, o, lse = residuals
    dq, dk, dv = _bwd_impl(cfg, q, k, v, o, lse, do)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_burst_attn_shard_stats.defvjp(_stats_vjp_fwd, _stats_vjp_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _burst_attn_shard_stats_seg(q, k, v, seg, cfg: BurstConfig):
    o, _, stats = _fwd_impl(q, k, v, cfg, seg=seg, collect=True)
    return o, stats


def _stats_seg_vjp_fwd(q, k, v, seg, cfg):
    o, lse, stats = _fwd_impl(q, k, v, cfg, seg=seg, collect=True)
    return (o, stats), (q, k, v, seg, o, lse)


def _stats_seg_vjp_bwd(cfg, residuals, cts):
    import numpy as np

    do, _dstats = cts
    q, k, v, seg, o, lse = residuals
    dq, dk, dv = _bwd_impl(cfg, q, k, v, o, lse, do, seg=seg)
    dseg = np.zeros(seg.shape, dtype=jax.dtypes.float0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dseg


_burst_attn_shard_stats_seg.defvjp(_stats_seg_vjp_fwd, _stats_seg_vjp_bwd)


# ---------------------------------------------------------------------------
# global-array wrapper


# (reason-string prefix -> bounded label) for burst.fused_fallback: the
# supported() reasons embed shapes/budgets, which would explode counter
# cardinality if used as labels verbatim.  Since the schedule-IR refactor
# the "double ring" and generic multi-axis rows only exist as
# interpret-mode emulation limits (labelled interpret-*); on hardware both
# trace fused.
_FALLBACK_LABELS = (
    ("off-TPU", "off-tpu"),
    ("interpret-mode remote DMA", "interpret-single-axis"),
    ("double ring inter axis", "double-ring-axis-unbound"),
    # "sliding window" / "packed segments" rows are GONE: since the
    # occupancy compiler both run fused (window as a static band predicate,
    # segments via a gathered id side table) with dead rounds elided from
    # the program — there is no such decline reason left to label
    ("cross-attention", "cross-attn"),
    ("world < 2", "world-lt-2"),
    ("ring axis", "multi-axis-no-mesh"),
    ("axis env unavailable", "axis-env-unavailable"),
    ("topology config invalid", "topology-invalid"),
    ("schedule compiler declined", "schedule-compiler"),
    ("VMEM plan", "vmem-budget"),
)


def _fallback_label(reason: str) -> str:
    for prefix, label in _FALLBACK_LABELS:
        if reason.startswith(prefix):
            return label
    return "other"


def _note_dispatch(cfg: BurstConfig, mesh, q_shape, k_shape, has_seg: bool,
                   batch_axes, head_axes) -> None:
    """Record one ring dispatch in the obs registry (burst.dispatch /
    burst.fused_fallback / burst.ring_rounds / burst.ring_hops).

    Host-boundary code: called from burst_attn BEFORE shard_map, never from
    inside the traced shard program (burstlint `obs-jit-safe`).  The fused
    gate is re-evaluated here with explicit world/extra_axes through the
    SAME fused_ring.supported predicate the traced dispatch runs, on the
    same per-shard shapes, so these counters cannot drift from _fwd_impl's
    real decision."""
    from ..ops import fused_ring

    def _sizes_of(axes):
        if axes is None:
            return 1, []
        axes = axes if isinstance(axes, (tuple, list)) else (axes,)
        names = [a for a in axes if a is not None]
        prod = 1
        for a in names:
            prod *= mesh.shape.get(a, 1)
        return prod, [a for a in names if mesh.shape.get(a, 1) > 1]

    n_intra = mesh.shape.get(cfg.intra_axis, 1)
    n_inter = (mesh.shape.get(cfg.inter_axis, 1)
               if cfg.inter_axis is not None else 1)
    world = n_inter * n_intra
    b_div, extra_b = _sizes_of(batch_axes)
    h_div, extra_h = _sizes_of(head_axes)
    q_local = (max(1, q_shape[0] // b_div), max(1, q_shape[1] // h_div),
               max(1, q_shape[2] // world), q_shape[3])
    k_local = (max(1, k_shape[0] // b_div), max(1, k_shape[1] // h_div),
               max(1, k_shape[2] // world), k_shape[3])
    path, reason = "scan", None
    if cfg.backend == "fused_ring":
        reason = fused_ring.supported(cfg, q_local, k_local, has_seg,
                                      world=n_intra, n_inter=n_inter,
                                      extra_axes=extra_b + extra_h)
        path = "fused" if reason is None else "scan"
        # the backward runs its own gate at _bwd_impl's dispatch point; a
        # bwd-only decline (e.g. the bwd VMEM plan overflowing while the
        # fwd fits) must be distinguishable in obs output, so the fallback
        # counter is labeled by pass
        reason_bwd = fused_ring.supported(cfg, q_local, k_local, has_seg,
                                          world=n_intra, n_inter=n_inter,
                                          extra_axes=extra_b + extra_h,
                                          pass_="bwd")
        if reason_bwd is not None:
            _M_FALLBACK.inc(reason=_fallback_label(reason_bwd),
                            **{"pass": "bwd"})
    _M_DISPATCH.inc(path=path, backend=cfg.backend, tile=_tile_backend(cfg))
    if reason is not None:
        _M_FALLBACK.inc(reason=_fallback_label(reason), **{"pass": "fwd"})
    r_live = _r_live(cfg, q_local[2], k_local[2], n_inter, n_intra)
    rounds, intra_hops, inter_hops = ring_round_counts(n_inter, n_intra,
                                                       r_live)
    _M_ROUNDS.inc(rounds)
    if intra_hops:
        _M_HOPS.inc(intra_hops, axis="intra")
    if inter_hops:
        _M_HOPS.inc(inter_hops, axis="inter")
    # per-round wire bytes from the ONE shared derivation
    # (schedule.wire_round_bytes) — the same numbers ring_overlap records
    # and tests/test_wire_quant.py replays against the compiled program
    from . import schedule as sched_ir

    b_l, n_l, s_l, d_l = q_local
    fwd_b = sched_ir.wire_round_bytes("fwd", cfg.wire_dtype, b=b_l, n=n_l,
                                      n_kv=k_local[1], s=s_l, d=d_l)
    bwd_b = sched_ir.wire_round_bytes("bwd", cfg.wire_dtype, b=b_l, n=n_l,
                                      n_kv=k_local[1], s=s_l, d=d_l,
                                      opt_comm=cfg.optimize_bwd_comm)
    _M_WIRE.inc(fwd_b["kv"], **{"pass": "fwd", "dir": "kv"})
    _M_WIRE.inc(bwd_b["bundle"], **{"pass": "bwd", "dir": "bundle"})
    _M_WIRE.inc(bwd_b["dq"], **{"pass": "bwd", "dir": "dq"})


def _resolve_backend(backend: str) -> str:
    if backend == "auto":
        if jax.default_backend() == "tpu":
            try:
                from ..ops import pallas_flash  # noqa: F401

                return "pallas"
            except ImportError:
                return "jnp"
        return "jnp"
    return backend


def burst_attn(
    q,
    k,
    v,
    *,
    mesh,
    seq_axes=("sp",),
    causal: bool = False,
    layout: str = "zigzag",
    scale: Optional[float] = None,
    backend: str = "auto",
    optimize_bwd_comm: bool = True,
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    block_q_bwd: Optional[int] = None,
    block_kv_bwd: Optional[int] = None,
    batch_axes=None,
    head_axes=None,
    case_split: bool = True,
    window: Optional[int] = None,
    segment_ids=None,
    max_segment_len: Optional[int] = None,
    fused_kv_slots: Optional[int] = None,
    fused_block_q: Optional[int] = None,
    fused_block_kv: Optional[int] = None,
    fused_bwd_slots: Optional[int] = None,
    fused_block_q_bwd: Optional[int] = None,
    fused_block_kv_bwd: Optional[int] = None,
    fused_topology: str = "auto",
    fused_seq_factor: Optional[Tuple[int, int]] = None,
    fused_ccw_slots: Optional[int] = None,
    fused_bwd_ccw_slots: Optional[int] = None,
    wire_dtype: Optional[str] = None,
    collect_stats: bool = False,
) -> jax.Array:
    """Burst attention on global arrays [B, N, S, D]; S must already be in
    layout order (parallel/layouts.to_layout) for causal runs.

    seq_axes: mesh axis name(s) the sequence is sharded over — ("sp",) for a
    single ring or ("inter", "intra") for the hierarchical double ring.
    batch_axes / head_axes: mesh axis name(s) batch / heads are sharded over
    (data / tensor parallelism riding alongside the sequence ring — the
    reference's process_group mechanism, burst_attn_interface.py:144-145).
    segment_ids: optional [B, S] int32 packed-sequence ids (non-negative),
    permuted into the SAME layout order as the sequence; attention never
    crosses a segment boundary — the kv-side ids ride the KV ring.
    max_segment_len: optional PROMISE that no segment spans more than this
    many tokens (a contract, not a runtime check — see
    BurstConfig.max_segment_len); contig-causal single rings use it to
    statically elide ring rounds no segment can reach.
    wire_dtype: "int8" | "fp8" | None — quantize the ROTATING ring payloads
    (fwd K/V, bwd bundle, dq partials; lse exempt) with per-block fp32
    scales riding the same transport; None = the per-generation table
    default (ops/tuning.py fused_wire_dtype, itself None = bit-exact wire).
    fp32 accumulation is untouched; see docs/fused_ring.md for the pinned
    tolerances.
    collect_stats: return `(o, obs.devstats.DevStats)` instead of `o` —
    in-graph ring telemetry with a leading per-device axis of length
    `world` (batch/head replica groups are pre-reduced in-graph).  Fold it
    into the host registry with `stats.publish()` AFTER the step; gradients
    through `o` are bit-identical to the collect_stats=False path.
    """
    if isinstance(seq_axes, str):
        seq_axes = (seq_axes,)
    if len(seq_axes) == 1:
        inter_axis, intra_axis = None, seq_axes[0]
    elif len(seq_axes) == 2:
        inter_axis, intra_axis = seq_axes
    else:
        raise ValueError(f"seq_axes must have 1 or 2 names, got {seq_axes}")
    from ..ops.tuning import block_defaults, resolve_blocks

    # window validation lives in BurstConfig.__post_init__ (constructed below)
    block_q, block_kv, block_q_bwd, block_kv_bwd, _ = resolve_blocks(
        block_q, block_kv, block_q_bwd, block_kv_bwd)
    if wire_dtype is None:
        # per-generation wire default (every table row is None today — the
        # wire stays bit-exact unless the caller opts in per call)
        wire_dtype = block_defaults().fused_wire_dtype
    cfg = BurstConfig(
        causal=causal,
        layout=layout,
        scale=scale,
        intra_axis=intra_axis,
        inter_axis=inter_axis,
        backend=_resolve_backend(backend),
        optimize_bwd_comm=optimize_bwd_comm,
        block_q=block_q,
        block_kv=block_kv,
        block_q_bwd=block_q_bwd,
        block_kv_bwd=block_kv_bwd,
        case_split=case_split,
        window=window,
        max_segment_len=max_segment_len,
        fused_kv_slots=fused_kv_slots,
        fused_block_q=fused_block_q,
        fused_block_kv=fused_block_kv,
        fused_bwd_slots=fused_bwd_slots,
        fused_block_q_bwd=fused_block_q_bwd,
        fused_block_kv_bwd=fused_block_kv_bwd,
        fused_topology=fused_topology,
        fused_seq_factor=fused_seq_factor,
        fused_ccw_slots=fused_ccw_slots,
        fused_bwd_ccw_slots=fused_bwd_ccw_slots,
        wire_dtype=wire_dtype,
        # the host knows the mesh's full axis order: the fused kernels
        # compute multi-axis LOGICAL RDMA ids from it (ring.device_roles)
        mesh_axes=tuple((str(a), int(sz)) for a, sz in mesh.shape.items()),
    )
    _note_dispatch(cfg, mesh, q.shape, k.shape, segment_ids is not None,
                   batch_axes, head_axes)
    seq_spec = seq_axes if len(seq_axes) > 1 else intra_axis
    spec = P(batch_axes, head_axes, seq_spec, None)
    if collect_stats:
        # stats come back stacked over the ring axis (leading axis length
        # world); batch/head replica groups are reduced IN-GRAPH so every
        # ring position reports one consistent value no matter how many
        # dp/tp shards ride alongside (devstats.cross_reduce)
        def _flat(axes):
            if axes is None:
                return ()
            axes = axes if isinstance(axes, (tuple, list)) else (axes,)
            return tuple(a for a in axes
                         if a is not None and mesh.shape.get(a, 1) > 1)

        extra_axes = _flat(batch_axes) + _flat(head_axes)
        stats_spec = jax.tree.map(
            lambda _: P(seq_spec), devstats.DevStats(*devstats.DevStats._fields))

        def run_stats(q, k, v, seg=None):
            o, st = burst_attn_shard(q, k, v, cfg, seg, collect_stats=True)
            # custom_vjp outputs look differentiable to an OUTER grad trace
            # (the in-call stop_gradient is opaque to it); re-severing here
            # keeps the pmax/pmin cross-reduce off the autodiff path
            st = jax.tree.map(lax.stop_gradient, st)
            st = devstats.cross_reduce(st, extra_axes)
            return o, devstats.expand_device_axis(st)

        if segment_ids is not None:
            seg_spec = P(batch_axes, seq_spec)
            fn = shard_map(
                run_stats, mesh=mesh,
                in_specs=(spec, spec, spec, seg_spec),
                out_specs=(spec, stats_spec), check_vma=False,
            )
            return fn(q, k, v, jnp.asarray(segment_ids, jnp.int32))
        fn = shard_map(
            run_stats, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=(spec, stats_spec), check_vma=False,
        )
        return fn(q, k, v)
    if segment_ids is not None:
        seg_spec = P(batch_axes, seq_spec)
        fn = shard_map(
            lambda q, k, v, seg: burst_attn_shard(q, k, v, cfg, seg),
            mesh=mesh,
            in_specs=(spec, spec, spec, seg_spec),
            out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v, jnp.asarray(segment_ids, jnp.int32))
    fn = shard_map(
        partial(burst_attn_shard, cfg=cfg),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# reference-style aliases (burst_attn_interface.py:109-158 parity)


def burst_attn_func(
    q,
    k,
    v,
    softmax_scale=None,
    flash: str = "auto",
    causal: bool = False,
    optimize_bwd_comm: bool = True,
    deterministic: bool = True,
    *,
    mesh,
    seq_axes=("sp",),
):
    """Reference-parity entry point: zigzag-half causal layout.

    `flash` selects the tile backend ("auto" | "pallas" | "jnp"), replacing
    the reference's "cuda"/"triton"/math switch.  `deterministic` is accepted
    for parity; the TPU path is always deterministic.
    """
    del deterministic
    return burst_attn(
        q, k, v, mesh=mesh, seq_axes=seq_axes, causal=causal, layout="zigzag",
        scale=softmax_scale, backend=flash, optimize_bwd_comm=optimize_bwd_comm,
    )


def burst_attn_func_striped(
    q,
    k,
    v,
    softmax_scale=None,
    flash: str = "auto",
    causal: bool = False,
    optimize_bwd_comm: bool = True,
    deterministic: bool = True,
    *,
    mesh,
    seq_axes=("sp",),
):
    """Reference-parity entry point: striped causal layout
    (burst_attn_interface.py:109, OpBurstAttnStrip)."""
    del deterministic
    return burst_attn(
        q, k, v, mesh=mesh, seq_axes=seq_axes, causal=causal, layout="striped",
        scale=softmax_scale, backend=flash, optimize_bwd_comm=optimize_bwd_comm,
    )
