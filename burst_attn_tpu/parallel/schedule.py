"""Ring-schedule IR and compiler (ROADMAP item 1).

The hand-built slot schedules this module replaces
(`ring.fused_slot_schedule` / `ring.fused_bwd_slot_schedule`) encoded
exactly one topology: a unidirectional single ring.  Here a ring schedule
is a small compiled PROGRAM — per-round consume/send/recv/credit ops per
stream — emitted once by `compile_fwd` / `compile_bwd` and lowered twice:

  * `scan_events(program)` flattens it to the ordered (cls, axis, hops)
    collective stream the scan ring issues (`parallel/ring.ring_round_counts`
    derives its hop accounting from this, and burstlint matches the traced
    scan program against the same stream via analysis/oracle.py);
  * `to_table(program)` packs it into the int32 scalar-prefetch table the
    fused Pallas kernels interpret (ops/fused_ring.py reads the payload
    columns, ops/fused_ring_bwd.py additionally the dq columns) — the
    kernels contain NO schedule logic of their own.

Topologies the compiler emits (all simulation-proven by
analysis/oracle.verify_ring_program before any kernel may consume them —
the proof obligation lives with the compiler, not with each new PR):

  "uni"    the classic single ring: every chunk travels world-1 cw hops.
           Reproduces the legacy hand-built schedules bit for bit.
  "bidi"   counter-rotating bidirectional ring (TASP, arXiv 2509.26541):
           the payload stream is split across BOTH ICI directions — chunks
           for offsets 1..ceil((W-1)/2) arrive clockwise, offsets
           1..floor((W-1)/2) counter-clockwise, interleaved round-robin.
           Each direction owns its own slot bank and DMA semaphores, each
           in-flight transfer has TWO rounds of compute to hide under, and
           both link directions carry traffic concurrently — on comm-bound
           configs the effective per-hop latency halves.
  "double" the hierarchical double ring (BurstAttention's signature
           schedule): n_inter cycles of n_intra intra hops; the inter-hop
           payload (the next cycle's base chunk) is issued ONE FULL
           INTRA-CYCLE early into a dedicated prefetch bank, so the slow
           inter link hides behind n_intra rounds of compute.  Works on a
           two-axis ("inter", "intra") mesh or factored onto a flat ring
           axis (`n_inter * n_intra == world`).

Program shape.  Payload movement is expressed through at most two send
CHANNELS, each owning a slot BANK on the receiving side:

  channel 0  "cw" sends (uni/bidi) or intra-ring sends (double) -> bank 0
  channel 1  "ccw" sends (bidi) or inter-prefetch sends (double) -> bank 1

Per round the table row says which (bank, slot) compute consumes, whether
that slot's recv semaphores must be awaited first, which channels send
(src bank/slot, dst slot), and the capacity-credit ops (grant/take per
bank) that make slot reuse safe — the same handshake the hand-built
kernels used, now ASSIGNED BY THE COMPILER from the write/read event
order and checked (grant strictly before take) at compile time.

Backward programs add the dq ring plan: per round, which dq bank the
local contribution folds into, whether a partial arrives (one hop behind
the bundle), and the send kind — onward ring hop, direct return-home hop
(a single RDMA to the partition owner, `home_offsets` away), or the
double ring's cycle-boundary fold into the inter accumulator and the
final composed (inter+1, intra+1) home hop.

Everything here is host-side python/numpy: programs are compiled once per
(topology, world, slots) at trace time and are hashable static metadata
from the kernels' point of view.
"""

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

import numpy as np

TOPOLOGIES = ("uni", "bidi", "double")

# wire precision of the rotating payloads (ROADMAP item 5): None ships the
# caller's dtypes; "int8"/"fp8" quantize every rotating operand except lse
# (which stays fp32 — it is already tiny and exponent-critical) to 1-byte
# symmetric per-block values with an fp32 scale riding the SAME slot: the
# scale sub-buffer is a parallel bank indexed by the identical slot ids,
# its transfers signal the identical send/recv semaphores, and its reuse is
# licensed by the identical per-slot credits — no new columns exist in the
# op table, which is exactly what oracle.verify_ring_program checks.
WIRE_DTYPES = (None, "int8", "fp8")

# ---------------------------------------------------------------------------
# table column layout (shared by both fused kernels; bwd extends fwd).
# Columns 0..4 are reserved for the per-round mask-spec scalars
# (ops/masks.round_spec via pallas_flash._spec_array) which the kernel
# ENTRY fills in — they are traced values (they depend on the device's
# partition), while everything the compiler emits is a host integer.

SPEC0 = 0                 # q_lo, q_hi, kv_hi, causal, offset
CONSUME_BANK = 5
CONSUME_SLOT = 6
RECV = 7                  # 1 = wait payload recv sems on the consume slot
SEND0 = 8                 # channel-0 send issued at this round's first step
SRC_BANK0 = 9
SRC_SLOT0 = 10
DST_SLOT0 = 11
GRANT0 = 12               # bank-0 slot+1 whose credit this round grants
TAKE0 = 13                # 1 = this round's send takes its dst slot's credit
SEND1 = 14
SRC_SLOT1 = 15            # channel-1 sends always source from bank 1
DST_SLOT1 = 16
GRANT1 = 17
TAKE1 = 18
FWD_COLS = 19

DQ_BANK = 19              # which dq ring this round's contribution folds into
DQ_RECV = 20              # 1 = a partial arrives (one hop behind the bundle)
DQ_SLOT = 21
DQ_SEND = 22              # 0 none | 1 ring | 2 home | 3 boundary | 4 final
DQ_DST_SLOT = 23
DQ_GRANT0 = 24
DQ_TAKE0 = 25
DQ_GRANT1 = 26
DQ_TAKE1 = 27
DQI_RECV = 28             # double: consume the held inter partial this round
DQI_SLOT = 29
DQI_DST_SLOT = 30
BWD_COLS = 31

# dq send kinds
DQ_NONE, DQ_RING, DQ_HOME, DQ_BOUNDARY, DQ_FINAL = 0, 1, 2, 3, 4

# meta-row entries (appended by the kernel entry as TRACED device ids —
# the compiler never sees concrete ranks): me, channel-0 dst/src neighbor,
# channel-1 dst/src neighbor, dq home targets per dq bank
META_ME = 0
META_CH0_DST = 1
META_CH0_SRC = 2
META_CH1_DST = 3
META_CH1_SRC = 4
META_HOME0 = 5
META_HOME1 = 6


@dataclass(frozen=True)
class RingProgram:
    """One compiled ring schedule (see module docstring)."""

    kind: str                     # "fwd" | "bwd"
    topology: str                 # "uni" | "bidi" | "double"
    n_inter: int
    n_intra: int
    slots: Tuple[int, ...]        # payload slots per bank (len = n_banks)
    channels: Tuple[str, ...]     # channel dirs: subset of (cw, ccw, inter)
    copy_in: Tuple[Tuple[int, int], ...]  # round-0 local copies (bank, slot)
    rows: Dict[str, Tuple[int, ...]] = field(hash=False)
    # per-round rotation of the consumed payload: partition =
    # ((inter_rank - rot_inter) % I) * N + ((intra_rank - rot_intra) % N)
    rot_inter: Tuple[int, ...] = ()
    rot_intra: Tuple[int, ...] = ()
    # bwd only: dq ring geometry
    dq_slots: Tuple[int, ...] = ()          # ring slots per dq bank (no home)
    home_offsets: Tuple[Tuple[int, int], ...] = ()  # per dq bank:
    #   (inter_off, intra_off) — the final home hop targets the device
    #   `offset` positions forward of the sender
    # wire precision of the rotating payloads (see WIRE_DTYPES): purely a
    # payload-encoding attribute — the op table is IDENTICAL to the dense
    # compile of the same topology (asserted by burstlint), only the slot
    # dtypes, the scale sub-banks and the remote-DMA census change
    wire: Optional[str] = None

    @property
    def world(self) -> int:
        return self.n_inter * self.n_intra

    @property
    def n_rounds(self) -> int:
        return len(self.rot_intra)

    @property
    def n_banks(self) -> int:
        return len(self.slots)

    @property
    def n_dq_banks(self) -> int:
        return len(self.dq_slots)

    def col(self, name_idx: int) -> Tuple[int, ...]:
        return tuple(self.rows[_COL_NAMES[name_idx]])

    def to_table(self) -> np.ndarray:
        """[n_rounds, FWD_COLS|BWD_COLS] int32 op table (spec cols zeroed —
        the kernel entry fills them with traced per-round mask scalars)."""
        ncols = BWD_COLS if self.kind == "bwd" else FWD_COLS
        out = np.zeros((self.n_rounds, ncols), dtype=np.int32)
        for idx in range(5, ncols):
            out[:, idx] = self.rows[_COL_NAMES[idx]]
        return out

    def export(self) -> dict:
        """Plain-dict form for the analysis oracle: everything the
        simulation proof needs, nothing it must trust the compiler for."""
        return {
            "kind": self.kind, "topology": self.topology,
            "n_inter": self.n_inter, "n_intra": self.n_intra,
            "slots": self.slots, "channels": self.channels,
            "copy_in": self.copy_in, "rot_inter": self.rot_inter,
            "rot_intra": self.rot_intra, "dq_slots": self.dq_slots,
            "home_offsets": self.home_offsets, "wire": self.wire,
            "rows": {k: tuple(v) for k, v in self.rows.items()},
        }


_COL_NAMES = {
    CONSUME_BANK: "consume_bank", CONSUME_SLOT: "consume_slot", RECV: "recv",
    SEND0: "send0", SRC_BANK0: "src_bank0", SRC_SLOT0: "src_slot0",
    DST_SLOT0: "dst_slot0", GRANT0: "grant0", TAKE0: "take0",
    SEND1: "send1", SRC_SLOT1: "src_slot1", DST_SLOT1: "dst_slot1",
    GRANT1: "grant1", TAKE1: "take1",
    DQ_BANK: "dq_bank", DQ_RECV: "dq_recv", DQ_SLOT: "dq_slot",
    DQ_SEND: "dq_send", DQ_DST_SLOT: "dq_dst_slot",
    DQ_GRANT0: "dq_grant0", DQ_TAKE0: "dq_take0",
    DQ_GRANT1: "dq_grant1", DQ_TAKE1: "dq_take1",
    DQI_RECV: "dqi_recv", DQI_SLOT: "dqi_slot",
    DQI_DST_SLOT: "dqi_dst_slot",
}


class ScheduleError(ValueError):
    """A requested schedule cannot be compiled (bad topology/shape) or an
    emitted schedule failed a compile-time obligation (credit ordering)."""


# ---------------------------------------------------------------------------
# credit assignment: the one place the capacity handshake is derived


def _assign_credits(n_rounds: int, slots: int, writes, reads):
    """Derive the per-round capacity-credit schedule for one slot bank.

    writes: ordered [(round, slot)] REMOTE writes into the bank (the
    neighbor's sends, in issue order; the local round-0 copy-in is version
    0 of its slot and prepended by the caller when it exists).
    reads:  [(round, slot)] every read of the bank (consume + send-source).

    Credits are PER SLOT (the kernel's free semaphore is an array indexed
    like the bank): a write that reuses a slot takes that slot's credit at
    its round (take flag — the slot is the send's dst slot, already in the
    table), and the reader grants it at the end of the round holding the
    LAST read of the version being overwritten (grant column = slot + 1).
    A single fungible pool would be unsound for multi-bank-cycle
    schedules: a grant meant to free slot A could be consumed early by a
    write into slot B, silently licensing an overwrite-before-read — the
    oracle's maximally-ahead simulation exposes exactly that.  Compile-
    time obligations: at most one grant per round per bank, and every
    grant round strictly precedes its take round (else hardware
    deadlocks on an ungranted credit).
    """
    grants = [0] * n_rounds  # slot + 1; 0 = no grant
    takes = [0] * n_rounds
    per_slot_writes: Dict[int, List[int]] = {}
    write_meta = []  # (round, slot, version_index)
    for rnd, slot in writes:
        per_slot_writes.setdefault(slot, []).append(rnd)
        write_meta.append((rnd, slot, len(per_slot_writes[slot]) - 1))
    last_read: Dict[Tuple[int, int], int] = {}
    for rnd, slot in reads:
        versions = per_slot_writes.get(slot, [])
        vi = 0
        for j, wr in enumerate(versions):
            if wr <= rnd:
                vi = j
        key = (slot, vi)
        last_read[key] = max(last_read.get(key, -1), rnd)
    for rnd, slot, vi in write_meta:
        if vi == 0:
            continue  # first use of the slot: no credit needed
        takes[rnd] += 1
        prev_key = (slot, vi - 1)
        g = last_read.get(prev_key)
        if g is None:
            raise ScheduleError(
                f"slot {slot} version {vi - 1} overwritten without ever "
                "being read — aliased slot assignment")
        if g >= rnd:
            raise ScheduleError(
                f"credit deadlock: grant for slot {slot} at round {g} does "
                f"not precede the take at round {rnd}")
        if grants[g]:
            raise ScheduleError(
                f"round {g} would grant credits for two slots of one bank "
                f"({grants[g] - 1} and {slot})")
        grants[g] = slot + 1
    if sum(1 for g in grants if g) != sum(takes):
        raise ScheduleError(
            f"unbalanced credits: {sum(1 for g in grants if g)} granted, "
            f"{sum(takes)} taken")
    return grants, takes


def _assign_dq_credits(n_rounds: int, servings):
    """Credits for a dq accumulating ring, whose slots are written twice
    per serving (remote arrival, then the owner's local merged writeback).

    servings: ordered [(round, slot, arrival)] — the rounds this dq bank
    is the active ring, the slot serving them, and whether a partial
    ARRIVES (one hop behind) or the round seeds a fresh partial.  An
    arrival's send was issued during the sender's PREVIOUS serving round
    of this bank (one hop behind by construction), so when it reuses a
    slot the take lands on that round and the grant on the slot's previous
    serving round — which must strictly precede it or the ring deadlocks.
    """
    grants = [0] * n_rounds  # slot + 1; 0 = no grant (per-slot credits)
    takes = [0] * n_rounds
    prev_of_slot: Dict[int, int] = {}
    for k, (rnd, slot, arrival) in enumerate(servings):
        if arrival and k > 0:
            sender_round = servings[k - 1][0]
            if slot in prev_of_slot:
                t_prev = prev_of_slot[slot]
                if t_prev >= sender_round:
                    raise ScheduleError(
                        f"dq credit deadlock: slot {slot} last served at "
                        f"round {t_prev}, rewritten by the send at round "
                        f"{sender_round}")
                takes[sender_round] += 1
                if grants[t_prev]:
                    raise ScheduleError(
                        f"round {t_prev} would grant dq credits for two "
                        f"slots ({grants[t_prev] - 1} and {slot})")
                grants[t_prev] = slot + 1
        prev_of_slot[slot] = rnd
    return grants, takes


# ---------------------------------------------------------------------------
# forward compiler


def _blank_rows(n_rounds: int, ncols: int) -> Dict[str, List[int]]:
    return {name: [0] * n_rounds for idx, name in _COL_NAMES.items()
            if idx < ncols}


def _bidi_order(world: int) -> List[Tuple[str, int]]:
    """Global sweep order of the counter-rotating ring: the self round,
    then cw offset c and ccw offset u interleaved (cw first).  cw carries
    offsets 1..ceil((W-1)/2), ccw offsets 1..floor((W-1)/2)."""
    h_cw = (world - 1 + 1) // 2
    h_ccw = (world - 1) // 2
    order: List[Tuple[str, int]] = [("cw", 0)]
    for j in range(1, max(h_cw, h_ccw) + 1):
        if j <= h_cw:
            order.append(("cw", j))
        if j <= h_ccw:
            order.append(("ccw", j))
    return order


def compile_fwd(topology: str, n_intra: int, n_inter: int = 1, *,
                slots: int = 2, slots1: Optional[int] = None,
                r_live: Optional[int] = None,
                wire: Optional[str] = None) -> RingProgram:
    """Compile a forward (KV-rotation) ring schedule.

    n_intra/n_inter: ring factorization (uni/bidi use n_inter == 1; double
    requires both >= 2, world = n_inter * n_intra).  slots: payload slots
    of bank 0 (>= 2); slots1: bank 1 (default = slots for bidi, 2 for the
    double prefetch bank).

    r_live: occupancy truncation (dead-round ELISION).  When the per-round
    occupancy (ops/masks.live_round_prefix, built on spec_pair_count) says
    only ring offsets {0..r_live-1} ever attend a pair, the compiled
    program keeps exactly those rounds and OMITS every op of the dead
    tail: no consume, no send/recv, no credit traffic — the elided rounds
    do not exist in the table, so the kernel issues no RDMA and sweeps no
    KV for them.  uni keeps its first r_live rounds; bidi degrades to the
    cw-only prefix program (serving offsets 0..r_live-1 down one direction
    is strictly cheaper than splitting a short prefix across two streams,
    and the bidi interleave's tail is not a round prefix); double keeps
    the first r_live rounds of its (cycle-major) visit order — whose flat
    offset IS the round index, so prefix truncation applies directly, and
    the inter prefetch for a cycle that would start at or past r_live is
    elided with it.

    wire: wire precision of the rotating payloads (WIRE_DTYPES) — attached
    to the program so `expected_remote_dma` and `wire_round_bytes` account
    the scale sub-payloads; the op table itself is identical to the dense-
    precision compile (scales ride the same slots, sems and credits).
    """
    if topology not in TOPOLOGIES:
        raise ScheduleError(f"unknown topology {topology!r}")
    if wire not in WIRE_DTYPES:
        raise ScheduleError(f"unknown wire dtype {wire!r} "
                            f"(must be one of {WIRE_DTYPES})")
    if slots < 2:
        raise ScheduleError(f"need slots >= 2, got {slots}")
    world = n_inter * n_intra
    if world < 1:
        raise ScheduleError(f"need world >= 1, got {world}")
    if topology != "double" and n_inter != 1:
        raise ScheduleError(f"{topology} rings need n_inter == 1")
    if topology == "double" and (n_inter < 2 or n_intra < 1):
        raise ScheduleError(
            f"double ring needs n_inter >= 2 and n_intra >= 1, got "
            f"{n_inter}x{n_intra}")
    if r_live is not None:
        if not (1 <= r_live <= world):
            raise ScheduleError(
                f"r_live must be in [1, world={world}], got {r_live}")
        if r_live == world:
            r_live = None  # no dead tail: compile the dense program

    if topology == "uni":
        prog = _compile_fwd_uni(world, slots, r_live)
    elif topology == "bidi":
        if r_live is not None:
            # a truncated bidi degrades to the cw-only prefix program: the
            # live offsets {0..r_live-1} all fit one direction, and the
            # bidi interleave's own tail is not a round prefix
            prog = _compile_fwd_uni(world, slots, r_live)
        else:
            prog = _compile_fwd_bidi(world, slots,
                                     slots if slots1 is None else slots1)
    else:
        prog = _compile_fwd_double(n_inter, n_intra, slots,
                                   2 if slots1 is None else slots1, r_live)
    return prog if wire is None else replace(prog, wire=wire)


def _compile_fwd_uni(world: int, slots: int, r_live=None) -> RingProgram:
    n_rounds = world if r_live is None else r_live
    c0 = min(slots, world)
    rows = _blank_rows(n_rounds, FWD_COLS)
    writes = [(0, 0)]  # copy-in = version 0 of slot 0
    reads = []
    for r in range(n_rounds):
        slot = r % c0
        rows["consume_slot"][r] = slot
        rows["recv"][r] = int(r > 0)
        reads.append((r, slot))
        if r < n_rounds - 1:
            rows["send0"][r] = 1
            rows["src_slot0"][r] = slot
            rows["dst_slot0"][r] = (r + 1) % c0
            writes.append((r, (r + 1) % c0))
            reads.append((r, slot))
    grants, takes = _assign_credits(n_rounds, c0, writes, reads)
    rows["grant0"], rows["take0"] = grants, takes
    return RingProgram(
        kind="fwd", topology="uni", n_inter=1, n_intra=world,
        slots=(c0,), channels=("cw",), copy_in=((0, 0),),
        rows={k: tuple(v) for k, v in rows.items()},
        rot_inter=(0,) * n_rounds, rot_intra=tuple(range(n_rounds)))


def _compile_fwd_bidi(world: int, slots: int, slots1: int) -> RingProgram:
    order = _bidi_order(world)
    n_rounds = len(order)
    assert n_rounds == world
    h_cw = sum(1 for d, _ in order if d == "cw") - 1
    h_ccw = sum(1 for d, _ in order if d == "ccw")
    c0 = min(slots, h_cw + 1) if h_cw else 1
    c0 = max(c0, 1)
    c1 = max(min(slots1, h_ccw + 1), 1) if h_ccw else 1
    rows = _blank_rows(n_rounds, FWD_COLS)
    rot = []
    writes0, reads0 = [(0, 0)], []
    writes1, reads1 = ([(0, 0)], []) if h_ccw else ([], [])
    copy_in = ((0, 0), (1, 0)) if h_ccw else ((0, 0),)
    for r, (d, j) in enumerate(order):
        bank = 0 if d == "cw" else 1
        c = c0 if bank == 0 else c1
        slot = j % c
        rot.append(j if d == "cw" else -j)
        rows["consume_bank"][r] = bank
        rows["consume_slot"][r] = slot
        rows["recv"][r] = int(j > 0)
        (reads0 if bank == 0 else reads1).append((r, slot))
        # onward send of the just-consumed chunk, same direction
        last = (j == h_cw) if d == "cw" else (j == h_ccw)
        if not last:
            dst = (j + 1) % c
            if bank == 0:
                rows["send0"][r] = 1
                rows["src_slot0"][r] = slot
                rows["dst_slot0"][r] = dst
                writes0.append((r, dst))
                reads0.append((r, slot))
            else:
                rows["send1"][r] = 1
                rows["src_slot1"][r] = slot
                rows["dst_slot1"][r] = dst
                writes1.append((r, dst))
                reads1.append((r, slot))
        # round 0 additionally launches the ccw stream from the bank-1 copy
        if r == 0 and h_ccw:
            rows["send1"][r] = 1
            rows["src_slot1"][r] = 0
            rows["dst_slot1"][r] = 1 % c1
            writes1.append((r, 1 % c1))
            reads1.append((r, 0))
    grants, takes = _assign_credits(n_rounds, c0, writes0, reads0)
    rows["grant0"], rows["take0"] = grants, takes
    if h_ccw:
        grants, takes = _assign_credits(n_rounds, c1, writes1, reads1)
        rows["grant1"], rows["take1"] = grants, takes
    channels = ("cw", "ccw") if h_ccw else ("cw",)
    slots_t = (c0, c1) if h_ccw else (c0,)
    return RingProgram(
        kind="fwd", topology="bidi", n_inter=1, n_intra=world,
        slots=slots_t, channels=channels, copy_in=copy_in,
        rows={k: tuple(v) for k, v in rows.items()},
        rot_inter=(0,) * n_rounds, rot_intra=tuple(rot))


def _compile_fwd_double(n_inter: int, n_intra: int, slots: int,
                        slots1: int, r_live=None) -> RingProgram:
    if slots1 < 2:
        raise ScheduleError(f"double ring needs >= 2 prefetch slots, "
                            f"got {slots1}")
    # dead-round elision: the double ring's visit order is cycle-major, so
    # a round's flat ring offset IS its index — an occupancy prefix of
    # r_live live offsets keeps exactly the first r_live rounds.  Every op
    # whose PURPOSE lies past the horizon goes with them: the intra send
    # feeding round r+1 >= r_live, and the whole inter prefetch of a cycle
    # whose first round (c+1)*n_intra >= r_live.
    n_rounds = n_inter * n_intra if r_live is None else r_live
    c0 = min(slots, n_intra)  # intra bank cycles within one cycle
    c1 = min(slots1, n_inter)
    rows = _blank_rows(n_rounds, FWD_COLS)
    rot_i, rot_s = [], []
    writes0, reads0 = [], []
    writes1, reads1 = [(0, 0)], []  # copy-in: cycle-0 base in prefetch slot 0
    for c in range(n_inter):
        base_slot = c % c1
        for s in range(n_intra):
            r = c * n_intra + s
            if r >= n_rounds:
                break
            rot_i.append(c)
            rot_s.append(s)
            if s == 0:
                # consume the cycle base from the prefetch bank
                rows["consume_bank"][r] = 1
                rows["consume_slot"][r] = base_slot
                rows["recv"][r] = int(c > 0)
                reads1.append((r, base_slot))
                if c < n_inter - 1 and (c + 1) * n_intra < n_rounds:
                    # the signature move: next cycle's base leaves NOW, one
                    # full intra-cycle before its first-step consume
                    rows["send1"][r] = 1
                    rows["src_slot1"][r] = base_slot
                    rows["dst_slot1"][r] = (c + 1) % c1
                    writes1.append((r, (c + 1) % c1))
                    reads1.append((r, base_slot))
                if n_intra > 1 and r + 1 < n_rounds:
                    # intra ring launch: base -> intra-right's bank-0 slot
                    rows["send0"][r] = 1
                    rows["src_bank0"][r] = 1
                    rows["src_slot0"][r] = base_slot
                    rows["dst_slot0"][r] = 1 % c0
                    writes0.append((r, 1 % c0))
                    reads1.append((r, base_slot))
            else:
                slot = s % c0
                rows["consume_slot"][r] = slot
                rows["recv"][r] = 1
                reads0.append((r, slot))
                if s < n_intra - 1 and r + 1 < n_rounds:
                    rows["send0"][r] = 1
                    rows["src_slot0"][r] = slot
                    rows["dst_slot0"][r] = (s + 1) % c0
                    writes0.append((r, (s + 1) % c0))
                    reads0.append((r, slot))
    grants, takes = _assign_credits(n_rounds, c0, writes0, reads0)
    rows["grant0"], rows["take0"] = grants, takes
    grants, takes = _assign_credits(n_rounds, c1, writes1, reads1)
    rows["grant1"], rows["take1"] = grants, takes
    return RingProgram(
        kind="fwd", topology="double", n_inter=n_inter, n_intra=n_intra,
        slots=(c0, c1), channels=("cw", "inter"), copy_in=((1, 0),),
        rows={k: tuple(v) for k, v in rows.items()},
        rot_inter=tuple(rot_i), rot_intra=tuple(rot_s))


# ---------------------------------------------------------------------------
# backward compiler: the q-side bundle replays the forward movement; the
# dq plan is layered on top


def compile_bwd(topology: str, n_intra: int, n_inter: int = 1, *,
                slots: int = 2, slots1: Optional[int] = None,
                dq_slots: Optional[int] = None,
                r_live: Optional[int] = None,
                wire: Optional[str] = None) -> RingProgram:
    """Compile a backward schedule: the bundle rotates exactly like the
    forward KV (same banks/channels/credits), and a dq plan rides along —
    one accumulating ring per direction, each one hop behind its bundle,
    with a direct return-home RDMA at the end (see module docstring).

    r_live: occupancy truncation (see compile_fwd).  The backward's roles
    flip — the q bundle rotates past resident KV — so a live-offset
    PREFIX {0..K} means the bundle must visit offsets 0..K of the OTHER
    direction: the truncated program rotates the bundle counter-clockwise
    for K hops (each device serves q-parts me, me+1, .., me+K in order)
    and the dq partial rides one hop behind on the same ccw stream, with
    a single +K return-home RDMA.  That is strictly fewer rounds, sends
    and credits than the dense program's round-0-plus-tail live set.
    uni/bidi only (a truncated bidi bwd uses the same single-direction
    program); the double bwd keeps its dense dq plan — its cycle-boundary
    folds are not prefix-truncatable — and relies on the in-kernel mask
    predication for dead rounds.  r_live == 1 is refused: the program
    would need a zero-offset self-home hop (callers route the self-only
    case to the scan ring).
    """
    world = n_inter * n_intra
    if wire not in WIRE_DTYPES:
        raise ScheduleError(f"unknown wire dtype {wire!r} "
                            f"(must be one of {WIRE_DTYPES})")
    if r_live is not None:
        if not (1 <= r_live <= world):
            raise ScheduleError(
                f"r_live must be in [1, world={world}], got {r_live}")
        if r_live < world and topology in ("uni", "bidi"):
            if r_live == 1:
                raise ScheduleError(
                    "bwd r_live truncation needs r_live >= 2 (a self-only "
                    "ring has no dq return hop)")
            prog = _compile_bwd_truncated(world, r_live, slots,
                                          slots if dq_slots is None
                                          else dq_slots)
            return prog if wire is None else replace(prog, wire=wire)
        r_live = None  # dense (r_live == world, or double: see docstring)
    fwd = compile_fwd(topology, n_intra, n_inter, slots=slots, slots1=slots1)
    n_rounds = fwd.n_rounds
    rows = {k: list(v) for k, v in fwd.rows.items()}
    for idx in range(FWD_COLS, BWD_COLS):
        rows[_COL_NAMES[idx]] = [0] * n_rounds
    dq_c = min(max(2, slots if dq_slots is None else dq_slots), n_rounds)
    world = fwd.world

    if topology in ("uni", "bidi"):
        order = ([("cw", j) for j in range(world)] if topology == "uni"
                 else _bidi_order(world))
        h = {"cw": 0, "ccw": 0}
        for d, j in order:
            h[d] = max(h[d], j)
        c_by = {"cw": min(dq_c, h["cw"] + 1) if h["cw"] else 1,
                "ccw": min(dq_c, h["ccw"] + 1) if h["ccw"] else 1}
        servings = {"cw": [], "ccw": []}
        for r, (d, j) in enumerate(order):
            bank = 0 if d == "cw" else 1
            c = c_by[d]
            slot = j % c
            rows["dq_bank"][r] = bank
            rows["dq_slot"][r] = slot
            # each direction's ring SEEDS at its first serving round (cw:
            # the self round j=0; ccw: j=1, the first ccw bundle) — no
            # partial is in flight yet there
            seed = j == (0 if d == "cw" else 1)
            rows["dq_recv"][r] = int(not seed)
            servings[d].append((r, slot, not seed))
            if j < h[d]:
                rows["dq_send"][r] = DQ_RING
                rows["dq_dst_slot"][r] = (j + 1) % c
            else:
                rows["dq_send"][r] = DQ_HOME
        for d, bank in (("cw", 0), ("ccw", 1)):
            if not servings[d]:
                continue
            grants, takes = _assign_dq_credits(n_rounds, servings[d])
            rows[f"dq_grant{bank}"] = grants
            rows[f"dq_take{bank}"] = takes
        if topology == "uni":
            dq_slots_t = (c_by["cw"],)
            homes = ((0, -h["cw"] % world),)
        else:
            dq_slots_t = ((c_by["cw"], c_by["ccw"]) if h["ccw"]
                          else (c_by["cw"],))
            homes = (((0, -h["cw"] % world), (0, h["ccw"]))
                     if h["ccw"] else ((0, -h["cw"] % world),))
    else:  # double
        n_i, n_s = fwd.n_inter, fwd.n_intra
        c0 = min(dq_c, n_s)
        c1 = min(2, n_i)
        servings0 = []  # intra dq ring
        servings1 = []  # inter (boundary) ping/pong accumulator
        for c in range(n_i):
            for s in range(n_s):
                r = c * n_s + s
                slot = s % c0
                rows["dq_slot"][r] = slot
                rows["dq_recv"][r] = int(s > 0)
                servings0.append((r, slot, s > 0))
                boundary = s == n_s - 1
                if not boundary:
                    rows["dq_send"][r] = DQ_RING
                    rows["dq_dst_slot"][r] = (s + 1) % c0
                else:
                    if c > 0:
                        rows["dqi_recv"][r] = 1
                        rows["dqi_slot"][r] = (c - 1) % c1
                        servings1.append((r, (c - 1) % c1, True))
                    if c < n_i - 1:
                        rows["dq_send"][r] = DQ_BOUNDARY
                        rows["dqi_dst_slot"][r] = c % c1
                    else:
                        rows["dq_send"][r] = DQ_FINAL
        grants, takes = _assign_dq_credits(n_rounds, servings0)
        rows["dq_grant0"], rows["dq_take0"] = grants, takes
        grants, takes = _assign_dq_credits(n_rounds, servings1)
        rows["dq_grant1"], rows["dq_take1"] = grants, takes
        dq_slots_t = (c0, c1)
        homes = ((1, 1),)  # composed inter+1, intra+1 final hop
    return RingProgram(
        kind="bwd", topology=topology, n_inter=fwd.n_inter,
        n_intra=fwd.n_intra, slots=fwd.slots, channels=fwd.channels,
        copy_in=fwd.copy_in, rows={k: tuple(v) for k, v in rows.items()},
        rot_inter=fwd.rot_inter, rot_intra=fwd.rot_intra,
        dq_slots=dq_slots_t, home_offsets=homes, wire=wire)


def _compile_bwd_truncated(world: int, r_live: int, slots: int,
                           dq_slots: int) -> RingProgram:
    """Occupancy-truncated backward: one ccw bundle stream, one ccw dq ring.

    Round j consumes the bundle of q-part me+j (rot_intra[j] = -j mod
    world): the bundle seeds locally (copy_in), travels ccw one hop per
    round, and stops after K = r_live - 1 hops — beyond that every q-part
    is outside the live band on every device, so the rounds are simply
    absent.  The dq partial for the held bundle accumulates one hop behind
    on the same stream (seeded at round 0, ring-forwarded ccw, merged on
    arrival), and at round K the finished partial — by then K devices
    ccw-forward of its owner — returns home with one +K cw RDMA
    (home_offsets (0, K)).  Credits come from the same assigners as every
    other program; the oracle proves delivery/credits/home on the export
    like any dense schedule."""
    n_rounds = r_live
    k_last = r_live - 1
    c0 = max(min(slots, r_live), 1)
    rows = _blank_rows(n_rounds, BWD_COLS)
    writes = [(0, 0)]  # copy-in = version 0 of slot 0
    reads = []
    for j in range(n_rounds):
        slot = j % c0
        rows["consume_slot"][j] = slot
        rows["recv"][j] = int(j > 0)
        reads.append((j, slot))
        if j < k_last:
            rows["send0"][j] = 1
            rows["src_slot0"][j] = slot
            rows["dst_slot0"][j] = (j + 1) % c0
            writes.append((j, (j + 1) % c0))
            reads.append((j, slot))
    grants, takes = _assign_credits(n_rounds, c0, writes, reads)
    rows["grant0"], rows["take0"] = grants, takes
    dq_c = min(max(2, dq_slots), r_live) if k_last else 1
    servings = []
    for j in range(n_rounds):
        slot = j % dq_c
        rows["dq_slot"][j] = slot
        rows["dq_recv"][j] = int(j > 0)
        servings.append((j, slot, j > 0))
        if j < k_last:
            rows["dq_send"][j] = DQ_RING
            rows["dq_dst_slot"][j] = (j + 1) % dq_c
        else:
            rows["dq_send"][j] = DQ_HOME
    grants, takes = _assign_dq_credits(n_rounds, servings)
    rows["dq_grant0"], rows["dq_take0"] = grants, takes
    return RingProgram(
        kind="bwd", topology="uni", n_inter=1, n_intra=world,
        slots=(c0,), channels=("ccw",), copy_in=((0, 0),),
        rows={k: tuple(v) for k, v in rows.items()},
        rot_inter=(0,) * n_rounds,
        rot_intra=tuple((world - j) % world for j in range(n_rounds)),
        dq_slots=(dq_c,), home_offsets=((0, k_last),))


# ---------------------------------------------------------------------------
# lowerings


def scan_events(program: RingProgram):
    """Lower to the scan ring's ordered collective stream — the oracle's
    (cls, axis, hops) vocabulary (analysis/oracle.py).  This is the stream
    `parallel/burst._fwd_impl` realizes with lax.ppermute for the uni and
    double topologies; bidi is a fused-only topology (the scan ring's
    ppermute is already asynchronous — there is nothing to counter-rotate
    around) but still lowers here so the verifier can account its hops."""
    ev = []
    if program.topology == "double":
        # row-driven so r_live-truncated programs account only the sends
        # they kept; identical to the legacy cycle-major enumeration for
        # dense programs (send1 precedes send0 within a round)
        for r in range(program.n_rounds):
            if program.rows["send1"][r]:
                ev.append(("pay", "inter", 1))
            if program.rows["send0"][r]:
                ev.append(("pay", "intra", 1))
        return ev
    if program.topology == "uni":
        # the truncated bwd program rotates its single stream ccw
        sign = -1 if program.channels == ("ccw",) else 1
        return [("pay", "intra", sign)] * (program.n_rounds - 1)
    # bidi: one event per send, signed direction via hops +-1
    for r in range(program.n_rounds):
        if program.rows["send0"][r]:
            ev.append(("pay", "intra", 1))
        if program.rows["send1"][r]:
            ev.append(("pay", "intra", -1))
    return ev


def hop_totals(program: RingProgram):
    """Per-axis payload hop totals of the compiled schedule — what
    `parallel/ring.ring_round_counts` reports per dispatch."""
    totals = {"intra": 0, "inter": 0}
    for cls, axis, hops in scan_events(program):
        totals[axis] += abs(hops)
    return totals


def quantized_operands(program: RingProgram) -> int:
    """Payload-bundle operands that carry a quantized wire encoding (and
    therefore an extra scale transfer per send site) under this program's
    wire dtype: fwd rotates k+v (both quantized); the bwd bundle rotates
    (delta|o, do, q, lse) of which lse is exempt — it stays fp32.  Zero
    when the program ships dense payloads."""
    if program.wire is None:
        return 0
    return 2 if program.kind == "fwd" else 3


def expected_remote_dma(program: RingProgram, operands_ch: int = 2) -> int:
    """Remote dma_start CALL SITES the fused kernel lowered from this
    program must contain — the fused-ring-fused census (burstlint).

    operands_ch: arrays per payload send (fwd: k+v = 2; bwd bundle: 4).
    Channel 0 contributes one site per (operand, src bank) it ever sources
    from; channel 1 one per operand; each dq bank one ring site (if it has
    ring sends) and one home/boundary/final site.

    A quantized program (program.wire) adds the scale sub-payload sites:
    one extra transfer per QUANTIZED operand per payload send site
    (quantized_operands — lse never quantizes), and every dq site doubles
    (the streamed dq partial is int8|fp8 + its refreshed per-block scale).
    The scale transfers ride the same semaphores and credits, so they add
    call sites but no schedule rows."""
    per_send = operands_ch + quantized_operands(program)
    n = 0
    src_banks0 = {program.rows["src_bank0"][r]
                  for r in range(program.n_rounds)
                  if program.rows["send0"][r]}
    n += per_send * len(src_banks0)
    if any(program.rows["send1"][r] for r in range(program.n_rounds)):
        n += per_send
    if program.kind == "bwd":
        dq_mult = 2 if program.wire is not None else 1
        kinds = {program.rows["dq_send"][r] for r in range(program.n_rounds)}
        for bank in range(program.n_dq_banks):
            ring = any(program.rows["dq_send"][r] == DQ_RING
                       and program.rows["dq_bank"][r] == bank
                       for r in range(program.n_rounds))
            n += int(ring) * dq_mult
        n += int(DQ_HOME in kinds and 0 in
                 {program.rows["dq_bank"][r] for r in range(program.n_rounds)
                  if program.rows["dq_send"][r] == DQ_HOME}) * dq_mult
        n += int(any(program.rows["dq_send"][r] == DQ_HOME
                     and program.rows["dq_bank"][r] == 1
                     for r in range(program.n_rounds))) * dq_mult
        n += int(DQ_BOUNDARY in kinds) * dq_mult
        n += int(DQ_FINAL in kinds) * dq_mult
    return n


# ---------------------------------------------------------------------------
# wire byte accounting — the ONE derivation of per-round ring bytes.  The
# obs dispatch counters (parallel/burst._note_dispatch), the comm-floor
# benchmark (benchmarks/ring_overlap.py) and the schedule-replay test
# (tests/test_wire_quant.py) all call this helper, so they cannot drift
# from each other by construction.


def wire_itemsize(wire: Optional[str], dense_itemsize: int = 4) -> int:
    """Bytes per element a rotating operand ships under a wire dtype."""
    if wire is None:
        return dense_itemsize
    if wire not in WIRE_DTYPES:
        raise ScheduleError(f"unknown wire dtype {wire!r}")
    return 1


def wire_round_bytes(pass_: str, wire: Optional[str], *, b: int, n: int,
                     n_kv: int, s: int, d: int, opt_comm: bool = True,
                     itemsize: int = 4) -> Dict[str, int]:
    """Per-ROUND per-DEVICE payload bytes each rotating stream ships over
    one ring hop, by stream name:

      fwd  {"kv": ...}                    the k+v chunk (+ scales)
      bwd  {"bundle": ..., "dq": ...}     the q-side bundle (+ scales) and
                                          the streamed dq partial

    `itemsize` is the dense per-element width of the caller's tensors
    (4 for the fp32 comm-floor rows — the acceptance baseline).  Quantized
    streams ship 1 byte/element plus one fp32 scale per quantized block at
    the scan ring's granularity (fwd: per (batch, kv head); bundle: per
    (batch, head) per operand; dq: per (batch, head)); lse always ships
    b*n*s fp32.  Shapes are PER-SHARD.

    This is THE byte derivation: the burst.wire_bytes counters integrate
    it per dispatch, and the burstcost roofline re-derives it
    independently (analysis/costmodel.stream_bytes) with the
    cost-model-consistent lint rule pinning the two equal — a change
    here that the model doesn't mirror fails the gate."""
    wi = wire_itemsize(wire, itemsize)
    scale_b = 0 if wire is None else 4
    if pass_ == "fwd":
        kv = 2 * b * n_kv * s * d * wi + 2 * b * n_kv * scale_b
        return {"kv": kv}
    if pass_ != "bwd":
        raise ValueError(f"pass_ must be 'fwd' or 'bwd', got {pass_!r}")
    # bundle: (delta | o), do, q quantize; lse stays fp32
    first = b * n * s * (4 if wire is None else 1) if opt_comm \
        else b * n * s * d * wi
    bundle = (first + 2 * b * n * s * d * wi      # do + q
              + b * n * s * 4                      # lse (fp32, exempt)
              + 3 * b * n * scale_b)               # delta|o, do, q scales
    dq = b * n * s * d * (4 if wire is None else 1) + b * n * scale_b
    return {"bundle": bundle, "dq": dq}


def partition_for_round(program: RingProgram, r: int, inter_rank, intra_rank):
    """Traced (or host) partition id of the payload consumed at round r:
    the IR's rotation pair applied to this device's ring coordinates.
    Matches parallel/ring.partition_at_round for the uni/double visit
    order (asserted in tests/test_schedule_ir.py)."""
    n_i, n_s = program.n_inter, program.n_intra
    ci = (inter_rank - program.rot_inter[r]) % n_i
    si = (intra_rank - program.rot_intra[r]) % n_s
    return ci * n_s + si


def bank_dirs(program: RingProgram) -> Tuple[str, ...]:
    """Human/obs labels of the slot banks, in bank order: the devstats
    `slot_use{dir=...}` label values (docs/observability.md)."""
    return program.channels
