"""Mixture-of-Experts with expert parallelism over an "ep" mesh axis.

Absent from the reference (SURVEY.md §2.4 — DP/TP/PP/EP all delegated to
host frameworks); here it completes the framework's parallelism axes
(dp / sp ring / tp / pp / ep).

TPU-first formulation — the Shazeer dense-dispatch einsum form, which XLA
maps straight onto the MXU (no scatter/gather, no dynamic shapes, no
sorting):

  router logits  [T, E]  -> top-k gates + expert assignment
  dispatch       [T, E, C] one-hot (token t -> slot c of expert e)
  expert inputs  = einsum('tec,td->ecd', dispatch, x)
  expert outputs = per-expert MLP on [E, C, d]
  combined       = einsum('tec,ecd->td', combine, expert_out)

Capacity C bounds each expert's work (static shapes!); tokens routed past
an expert's capacity are DROPPED (their combine weight is zero) — the
standard GShard/Switch trade, surfaced in the aux metrics.

Expert parallelism = sharding the E axis of the expert MLP over "ep" inside
shard_map: each device dispatches its LOCAL tokens to all E experts, a
`lax.all_to_all` regroups [E, C, d] so each device holds its E/ep experts'
slots from every peer, the local expert MLPs run, and a second all_to_all
routes results home.  Combined with dp on the token axis this is exactly
the GShard data+expert layout.

Load balancing: the standard Switch aux loss (mean fraction of tokens per
expert x mean router prob per expert, scaled by E) is returned alongside
the output so the trainer can add `aux_weight * aux_loss`.
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from ..utils.compat import shard_map


class MoEParams(NamedTuple):
    router: jax.Array   # [d, E]
    w_gate: jax.Array   # [E, d, f]
    w_up: jax.Array     # [E, d, f]
    w_down: jax.Array   # [E, f, d]


def init_moe_params(key, d: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32) -> MoEParams:
    kr, kg, ku, kd = jax.random.split(key, 4)
    init = jax.nn.initializers.normal(stddev=0.02)
    return MoEParams(
        router=init(kr, (d, n_experts), jnp.float32),  # router math in fp32
        w_gate=init(kg, (n_experts, d, d_ff), dtype),
        w_up=init(ku, (n_experts, d, d_ff), dtype),
        w_down=init(kd, (n_experts, d_ff, d), dtype),
    )


def _routing(x, router, top_k: int, capacity: int):
    """Dense dispatch/combine tensors for [T, d] tokens.

    Returns (dispatch [T, E, C] float, combine [T, E, C] float,
    aux_loss scalar, dropped fraction scalar).
    """
    t, _ = x.shape
    e = router.shape[1]
    logits = x.astype(jnp.float32) @ router          # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k expert choice per token; gates renormalized over the chosen k
    gate_vals, expert_idx = lax.top_k(probs, top_k)  # [T, K]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    # position of each (token, choice) in its expert's queue: priority by
    # token order within each k-level, k-levels sequential (Switch style)
    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    fill = jnp.zeros((e,), jnp.int32)                # slots used per expert
    kept = jnp.zeros((), jnp.float32)
    for k in range(top_k):
        onehot = jax.nn.one_hot(expert_idx[:, k], e, dtype=jnp.int32)  # [T, E]
        pos = fill[None, :] + jnp.cumsum(onehot, axis=0) - onehot       # [T, E]
        pos_tok = jnp.sum(pos * onehot, axis=1)                         # [T]
        in_cap = pos_tok < capacity
        slot = jax.nn.one_hot(
            jnp.where(in_cap, pos_tok, capacity), capacity, dtype=jnp.float32
        )  # overflow -> all-zero row (one_hot of out-of-range)
        d_k = onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d_k
        combine = combine + d_k * gate_vals[:, k][:, None, None]
        fill = fill + jnp.sum(onehot * in_cap[:, None].astype(jnp.int32), axis=0)
        kept = kept + jnp.sum(in_cap.astype(jnp.float32))

    # Switch aux loss: E * mean_e(frac_tokens_e * mean_prob_e), over the
    # TOP-1 assignment
    top1 = jax.nn.one_hot(expert_idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.sum(jnp.mean(top1, axis=0) * jnp.mean(probs, axis=0))
    dropped = 1.0 - kept / (t * top_k)
    return dispatch, combine, aux, dropped


def _expert_mlp(p: MoEParams, h):
    """SwiGLU per expert: h [E, C, d] -> [E, C, d]."""
    g = jnp.einsum("ecd,edf->ecf", h, p.w_gate)
    u = jnp.einsum("ecd,edf->ecf", h, p.w_up)
    return jnp.einsum("ecf,efd->ecd", jax.nn.silu(g) * u, p.w_down)


def moe_shard(p: MoEParams, x, *, top_k: int, capacity: int, axis: Optional[str]):
    """Per-shard MoE on [T_local, d] tokens — call inside shard_map.

    With `axis`, the expert dimension of p is already sliced to E/ep by
    shard_map; two all_to_alls move dispatched tokens to their experts'
    devices and back (GShard).  Without, plain dense MoE.
    """
    dispatch, combine, aux, dropped = _routing(x, p.router, top_k, capacity)
    h = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32)).astype(x.dtype)
    if axis is not None:
        # [E, C, d] -> exchange: split E over the ep group, concat peers'
        # slots along capacity -> [E/ep, C * ep, d]
        h = lax.all_to_all(h, axis, split_axis=0, concat_axis=1, tiled=True)
    out = _expert_mlp(p, h)
    if axis is not None:
        out = lax.all_to_all(out, axis, split_axis=1, concat_axis=0, tiled=True)
    y = jnp.einsum("tec,ecd->td", combine, out.astype(jnp.float32))
    # aux/dropped are per-shard means over local tokens; average over peers
    if axis is not None:
        aux = lax.pmean(aux, axis)
        dropped = lax.pmean(dropped, axis)
    return y.astype(x.dtype), aux, dropped


def capacity_for(tokens: int, experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Per-group expert slot count — the ONE place the capacity rounding
    policy lives (consumers: moe_apply, the LM's dense-path _mlp, and the
    pipeline-parallel _moe_block; a policy change must move all three in
    lockstep or pp-vs-regular MoE parity silently breaks)."""
    return max(1, int(capacity_factor * top_k * tokens / experts))


def moe_apply(p: MoEParams, x, *, mesh=None, axis: Optional[str] = "ep",
              top_k: int = 2, capacity_factor: float = 1.25):
    """MoE layer on [B, T, d] (or [T, d]) tokens.

    With mesh+axis: expert-parallel over `axis` — p's expert dimension must
    be sharded P(axis) and x replicated/sharded over the OTHER axes.  The
    token dim is flattened locally; capacity is per LOCAL token count.
    Returns (y, aux_loss, dropped_fraction).
    """
    squeeze = x.ndim == 2
    if squeeze:
        x = x[None]
    b, t, d = x.shape
    e = p.router.shape[1]

    if mesh is None or axis is None:
        cap = capacity_for(b * t, e, top_k, capacity_factor)
        y, aux, dropped = moe_shard(
            p, x.reshape(b * t, d), top_k=top_k, capacity=cap, axis=None
        )
        y = y.reshape(b, t, d)
        return (y[0] if squeeze else y), aux, dropped

    ep = mesh.shape.get(axis, 1)
    if e % ep:
        raise ValueError(f"experts {e} not divisible by ep axis size {ep}")
    if t % ep:
        raise ValueError(f"tokens {t} not divisible by ep axis size {ep}")
    cap = capacity_for(b * t // ep, e, top_k, capacity_factor)

    def body(p_shard, x_shard):
        bb, tt, _ = x_shard.shape
        y, aux, dropped = moe_shard(
            p_shard, x_shard.reshape(bb * tt, d), top_k=top_k, capacity=cap,
            axis=axis,
        )
        return y.reshape(bb, tt, d), aux, dropped

    pspec = MoEParams(P(), P(axis), P(axis), P(axis))
    y, aux, dropped = shard_map(
        body, mesh=mesh,
        in_specs=(pspec, P(None, axis, None)),
        out_specs=(P(None, axis, None), P(), P()),
        check_vma=False,
    )(p, x)
    return (y[0] if squeeze else y), aux, dropped
