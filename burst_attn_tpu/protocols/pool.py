"""The PagePool refcount machine: free-list + refcount algebra, pure.

State is the WHOLE allocator (free list order matters — acquire pops
from the tail exactly like `PagePool.acquire`, so the ids the machine
hands out are bit-identical to production's).  Page 0 is the reserved
write sink and never enters the free list or carries a ref.

Events:

  ("acquire", n)        -> outputs (("acquired", ids),)
  ("share", ids)        -> outputs ()
  ("release", ids)      -> outputs (("freed", ids_returned_to_free),)
  ("write", pid)        -> outputs ()     the CoW write barrier: writing
                           a page held at refcount > 1 is the silent
                           cross-request corruption pagepool-cow-safe
                           hunts; the machine raises CowViolation
  ("cow", pid)          -> outputs (("cow", old, new),)  privatize one
                           shared page: acquire a private replacement,
                           drop one ref from the shared original

Exceptions keep production's types AND messages: `PoolExhausted` is a
RuntimeError, `PoolRefError` is a ValueError — `PagePool` re-raises
them verbatim, so every existing caller and test sees the exact
historical behavior.
"""

from typing import Iterable, List, NamedTuple, Tuple

from . import ProtocolError


class PoolExhausted(ProtocolError, RuntimeError):
    pass


class PoolRefError(ProtocolError, ValueError):
    pass


class CowViolation(ProtocolError):
    """A write targeted a page held at refcount > 1 (missing CoW)."""


class PoolState(NamedTuple):
    n_pages: int
    free: Tuple[int, ...]   # tail = next page handed out (stack order)
    refs: Tuple[int, ...]   # len == n_pages; refs[0] unused (sink)


def init(n_pages: int) -> PoolState:
    return PoolState(n_pages=n_pages,
                     free=tuple(range(n_pages - 1, 0, -1)),
                     refs=(0,) * n_pages)


def from_lists(n_pages: int, free: Iterable[int],
               refs: Iterable[int]) -> PoolState:
    return PoolState(n_pages=int(n_pages),
                     free=tuple(int(p) for p in free),
                     refs=tuple(int(r) for r in refs))


def available(st: PoolState) -> int:
    return len(st.free)


def in_use(st: PoolState) -> int:
    return st.n_pages - 1 - len(st.free)


def conserved(st: PoolState) -> bool:
    """The conservation law proto-pool-conserved proves: every usable
    page is EITHER on the free list (refcount 0) or referenced
    (refcount > 0), never both, never neither, never twice."""
    free = set(st.free)
    if len(free) != len(st.free):
        return False  # duplicate free-list entry (double-free)
    held = {i for i in range(1, st.n_pages) if st.refs[i] > 0}
    if free & held:
        return False  # freed page still referenced
    if any(st.refs[i] != 0 for i in st.free):
        return False
    return free | held == set(range(1, st.n_pages))


def step(st: PoolState, event: Tuple) -> Tuple[PoolState, Tuple]:
    kind = event[0]
    if kind == "acquire":
        n = int(event[1])
        if n > len(st.free):
            raise PoolExhausted(
                f"page pool exhausted: want {n}, have {len(st.free)}")
        ids = [st.free[-1 - k] for k in range(n)]  # pop order
        refs = list(st.refs)
        for i in ids:
            refs[i] = 1
        nxt = PoolState(st.n_pages, st.free[:len(st.free) - n], tuple(refs))
        return nxt, (("acquired", tuple(ids)),)
    if kind == "share":
        ids = [int(i) for i in event[1]]
        for i in ids:
            if not 0 < i < st.n_pages:
                raise PoolRefError(f"bad page id {i}")
            if st.refs[i] == 0:
                raise PoolRefError(
                    f"page {i} is free; share() needs a live page")
        refs = list(st.refs)
        for i in ids:
            refs[i] += 1
        return PoolState(st.n_pages, st.free, tuple(refs)), ()
    if kind == "release":
        # an over-release would put the page on the free list while
        # another sequence still references it — corrupt both, silently
        ids = [int(i) for i in event[1]]
        counts: dict = {}
        for i in ids:
            counts[i] = counts.get(i, 0) + 1
        for i, c in counts.items():
            if not 0 < i < st.n_pages:  # page 0 is the reserved sink
                raise PoolRefError(f"bad page id {i}")
            if st.refs[i] < c:
                raise PoolRefError(
                    f"page {i} released {c}x but has {st.refs[i]} refs")
        refs = list(st.refs)
        free: List[int] = list(st.free)
        for i in ids:
            refs[i] -= 1
            if refs[i] == 0:
                free.append(i)
        return PoolState(st.n_pages, tuple(free), tuple(refs)), ()
    if kind == "write":
        pid = int(event[1])
        if pid and st.refs[pid] > 1:
            raise CowViolation(
                f"write to page {pid} at refcount {st.refs[pid]} "
                f"without a CoW copy first")
        return st, ()
    if kind == "cow":
        # serving/model.cow_pages, reduced to its pool algebra: the
        # caller owns one of `pid`'s refs; acquire a private replacement
        # and move that ref onto it (the copy itself is device work the
        # machine does not model)
        pid = int(event[1])
        if not 0 < pid < st.n_pages or st.refs[pid] == 0:
            raise PoolRefError(f"cow of non-live page {pid}")
        st2, out = step(st, ("acquire", 1))
        new = out[0][1][0]
        st3, _ = step(st2, ("release", (pid,)))
        return st3, (("cow", pid, new),)
    raise ValueError(f"unknown pool event {event!r}")
