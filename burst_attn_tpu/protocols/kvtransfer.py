"""The transactional KV transfer machine: stage/commit/abort + the
sender's hold-until-ack plan, pure.

Receiver side extracts `fleet/kvplane.KvReceiver` + its `commit`:
staging NEVER touches the pool; `commit` checks every control
precondition — staging present, transfer complete, table width, dead
slot, pool availability — BEFORE acquiring a single page, in exactly
production's order (the data-shape preconditions, page geometry and
layer counts, are arrays production checks between `complete` and the
table-width check; the machine does not model payload bytes).
Production's `KvReceiver.commit` runs `commit_preconditions` and then
takes its page ids from this machine's acquire, asserting they match
the real pool's — the decision path IS the spec.

Sender side extracts the prefill worker's ship loop
(`fleet/fleet.prefill_main`): one transfer is the frame sequence
`sender_plan(n_pages)` — kv_begin(seq 0), kv_page(seq 1..n),
kv_end(seq n+1) — after which the worker HOLDS the shipped pages in
`pending` until the router's kv_ack (post-commit) or kv_abort retires
them.  `PAGE_CREDIT_WINDOW` pins the flow-control contract: the sender
may ship the whole transfer without per-page credits, because the one
ack only arrives AFTER kv_end reaches the replica and commits — a
per-page credit window against a commit-time-only ack is the classic
circular wait `proto-no-deadlock` exists to catch.

Receiver state/events:

  ("begin", rid, n)     stage a transfer (re-begin replaces staging)
  ("page", rid, j)      stage page j (KvStagingError without a begin)
  ("abort", rid)        drop staging; outputs ("aborted", rid) if any
  ("commit", rid, slot) preconditions -> acquire -> install -> unstage;
                        outputs ("committed", rid, ids)
  ("crash",)            staging vanishes (it was process memory); the
                        pool/slots of the crashed process die with it —
                        the MODEL decides what a restart restores
                        (snapshot state), the machine just clears
"""

from typing import NamedTuple, Tuple

from . import ProtocolError, pool as pool_proto


class KvStagingError(ProtocolError, KeyError):
    pass


class KvCommitError(ProtocolError, ValueError):
    pass


class KvSlotLive(ProtocolError, RuntimeError):
    pass


# -- sender ------------------------------------------------------------------

# Flow-control contract (see module docstring): None = the sender may
# ship every frame of one transfer without waiting for credits.  The
# proto-no-deadlock mutation sets this to a small window to seed the
# ack/credit circular wait.
PAGE_CREDIT_WINDOW = None

# Pair contract for natively quantized pools (int8/fp8 storage with
# per-token fp32 scale columns): one kv_page frame carries the page AND
# its scale sidecar, staged and committed as a unit — kvplane.add_page
# rejects a frame missing its sidecars whole, and commit scatters both
# under one release-on-failure block.  The proto-transfer-atomic
# mutation flips this to False (frames carry the page half only) and the
# quantized transfer model's pair-landing invariant fires.
SCALE_PAIRED = True


def pair_members(j: int) -> Tuple[Tuple[str, int], ...]:
    """The staging units one quantized kv_page frame carries: the page
    column and the scale sidecar ride the SAME frame, so they can only
    land (or abort, or die) together."""
    if SCALE_PAIRED:
        return (("page", int(j)), ("scale", int(j)))
    return (("page", int(j)),)


def sender_plan(n_pages: int) -> Tuple[Tuple[str, int], ...]:
    """The exact (op, seq) frame sequence one transfer ships, in order.
    `prefill_main` iterates this to build its kv_begin/kv_page/kv_end
    frames; the checker's sender model walks the same tuple."""
    return ((("kv_begin", 0),)
            + tuple(("kv_page", j + 1) for j in range(int(n_pages)))
            + (("kv_end", int(n_pages) + 1),))


class SendState(NamedTuple):
    n_pages: int
    next_i: int            # index into sender_plan
    pages_acked: int       # per-page credits returned (always 0 today)
    holding: Tuple[int, ...]  # pool pages pinned until kv_ack/kv_abort
    acked: bool


def send_init(n_pages: int, holding: Tuple[int, ...]) -> SendState:
    return SendState(int(n_pages), 0, 0, tuple(holding), False)


def send_enabled(st: SendState) -> bool:
    """May the sender ship its next frame?  Encodes the credit contract
    — with PAGE_CREDIT_WINDOW None this is just 'plan not exhausted'."""
    plan = sender_plan(st.n_pages)
    if st.acked or st.next_i >= len(plan):
        return False
    if PAGE_CREDIT_WINDOW is not None:
        pages_in_flight = max(0, st.next_i - 1) - st.pages_acked
        if plan[st.next_i][0] == "kv_page" \
                and pages_in_flight >= PAGE_CREDIT_WINDOW:
            return False
    return True


def send_step(st: SendState, event: Tuple) -> Tuple[SendState, Tuple]:
    kind = event[0]
    if kind == "send":
        if not send_enabled(st):
            raise KvCommitError("sender has no frame to send "
                                "(plan exhausted, acked, or out of credits)")
        op, seq = sender_plan(st.n_pages)[st.next_i]
        return st._replace(next_i=st.next_i + 1), ((op, seq),)
    if kind == "ack":
        # the router's kv_ack: the replica committed; retire the held
        # pages (the caller releases st.holding from its pool)
        return (st._replace(acked=True, holding=()),
                (("retire", st.holding),))
    if kind == "crash":
        # the prefill worker died: held pages die with its pool; the
        # router's heartbeat path re-ships from a sibling
        return send_init(st.n_pages, ())._replace(acked=st.acked), ()
    raise ValueError(f"unknown sender event {event!r}")


# -- receiver ----------------------------------------------------------------


class RecvState(NamedTuple):
    # ((rid, n_pages, got_frozenset), ...) sorted by rid
    staging: Tuple[Tuple[int, int, frozenset], ...]
    pool: pool_proto.PoolState
    # slots[i] = (live: 0|1, pages held by that slot)
    slots: Tuple[Tuple[int, Tuple[int, ...]], ...]
    table_width: int


def recv_init(pool: pool_proto.PoolState, n_slots: int,
              table_width: int) -> RecvState:
    return RecvState((), pool, ((0, ()),) * n_slots, int(table_width))


def staged_entry(st: RecvState, rid: int):
    for ent in st.staging:
        if ent[0] == rid:
            return ent
    return None


def _set_staging(st: RecvState, rid: int, ent) -> RecvState:
    rest = tuple(e for e in st.staging if e[0] != rid)
    if ent is not None:
        rest = tuple(sorted(rest + (ent,)))
    return st._replace(staging=rest)


def staging_complete(ent) -> bool:
    _, n, got = ent
    return len(got) == n and all(j in got for j in range(n))


def commit_preconditions(st: RecvState, rid: int, slot: int) -> int:
    """Every CONTROL precondition of a commit, checked with zero pool
    mutation, production's order and messages.  Returns n_pages.  This
    is the seam the proto-transfer-atomic mutation no-ops: skipping it
    commits half-shipped transfers and leaks acquired pages."""
    ent = staged_entry(st, rid)
    if ent is None:
        raise KvStagingError(f"commit for rid {rid} with no staging")
    _, n, got = ent
    if not staging_complete(ent):
        raise KvCommitError(
            f"rid {rid} staged {len(got)}/{n} pages; transfer incomplete")
    if n > st.table_width:
        raise KvCommitError(f"transfer needs {n} pages > table width "
                            f"{st.table_width}")
    if st.slots[slot][0]:
        raise KvSlotLive(f"slot {slot} is still live; retire it first")
    if pool_proto.available(st.pool) < n:
        raise pool_proto.PoolExhausted(
            f"page pool exhausted: want {n}, have "
            f"{pool_proto.available(st.pool)}")
    return n


def recv_step(st: RecvState, event: Tuple) -> Tuple[RecvState, Tuple]:
    kind = event[0]
    if kind == "begin":
        rid, n = int(event[1]), int(event[2])
        # a re-shipped attempt for the same rid replaces stale staging
        return _set_staging(st, rid, (rid, n, frozenset())), ()
    if kind == "page":
        rid, j = int(event[1]), int(event[2])
        ent = staged_entry(st, rid)
        if ent is None:
            raise KvStagingError(f"kv_page for rid {rid} with no kv_begin")
        rid_, n, got = ent
        return _set_staging(st, rid, (rid, n, got | {j})), ()
    if kind == "abort":
        rid = int(event[1])
        ent = staged_entry(st, rid)
        if ent is None:
            return st, ()
        # drop staging; pool untouched by construction
        return _set_staging(st, rid, None), (("aborted", rid),)
    if kind == "commit":
        rid, slot = int(event[1]), int(event[2])
        n = commit_preconditions(st, rid, slot)
        npool, out = pool_proto.step(st.pool, ("acquire", n))
        ids = out[0][1]
        slots = list(st.slots)
        slots[slot] = (1, ids)
        st = _set_staging(
            st._replace(pool=npool, slots=tuple(slots)), rid, None)
        return st, (("committed", rid, ids),)
    if kind == "retire":
        slot = int(event[1])
        live, ids = st.slots[slot]
        if not live:
            return st, ()
        npool, _ = pool_proto.step(st.pool, ("release", ids))
        slots = list(st.slots)
        slots[slot] = (0, ())
        return st._replace(pool=npool, slots=tuple(slots)), ()
    if kind == "crash":
        return st._replace(staging=()), ()
    raise ValueError(f"unknown receiver event {event!r}")
