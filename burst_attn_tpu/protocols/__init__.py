"""Pure protocol state machines — the specs burstcheck model-checks.

Each module here extracts ONE production protocol into a pure

    step(state, event) -> (state, outputs)

transition function over immutable (hashable) state.  The production
classes (`PagePool`, `TokenJournal`, `FrameBuffer`/`Dedup`,
`KvReceiver`) DELEGATE their decisions to these functions, and the
model checker (`analysis/modelcheck.py`) explores exactly the same
functions over all bounded interleavings with crashes injected at
every step — so the checked model cannot drift from shipped behavior
(code-is-spec, stateright/dslabs style).

Conventions shared by every machine:

  * state is a NamedTuple of plain hashable values (tuples, ints,
    frozensets) — the checker hashes states for dedup, so no lists,
    dicts, or arrays;
  * events are tuples `(kind, *args)`; outputs are a tuple of the same
    shape (observable effects the caller applies: metrics, payload
    moves, admitted decisions);
  * transition functions raise the SAME exception types with the SAME
    messages production historically raised — the delegating classes
    re-raise them verbatim, which is what keeps the existing test
    matrix byte-stable across this refactor;
  * a `("crash",)` event is defined wherever a process death has
    protocol-visible semantics (buffered-not-durable state vanishes);
    the checker injects it between any two steps.

Modules:

  pool        PagePool refcount/free-list algebra + the CoW write barrier
  journal     write-ahead token journal (append/sync/deliver/recover)
  transport   frame parse (CRC/torn-tail) + (rid, seq) dedup
  kvtransfer  transactional KV page transfer (stage/commit/abort + the
              sender's hold-until-ack plan)
"""


class ProtocolError(Exception):
    """Base for machine-raised protocol violations (each machine also
    derives from the builtin type production historically raised, so
    delegating call sites keep their existing `except` behavior)."""


# submodules import ProtocolError from the package, so it must exist first
from . import journal, kvtransfer, pool, transport  # noqa: E402,F401
