"""The frame-transport machine: incremental parse + dedup, pure.

Extracts `fleet/transport.FrameBuffer` (the live receive path) and
`Dedup` into transition functions.  `FrameBuffer.feed`/`eof` delegate
here byte-for-byte — the pure parser owns the CRC-reject / lost-sync /
torn-tail policy and the class only applies the outputs (frame queue +
obs counters) — so the checker's wire model parses with EXACTLY the
code a socket does.

Wire state is the unconsumed byte buffer plus the two loss counters;
events:

  ("feed", chunk: bytes)  -> outputs: ("frame", payload) per clean
                             frame, ("crc_reject",) per dropped frame
                             (framing intact, payload mangled), and a
                             final ("desync", msg) when the stream
                             loses sync (bad magic / absurd length) —
                             an OUTPUT, not a raise, so frames parsed
                             earlier in the same chunk still deliver;
                             the bad bytes stay buffered, so every
                             later feed re-reports the desync (exactly
                             FrameBuffer's historical raise-per-feed)
  ("eof",)                -> outputs: ("torn",) if a partial frame was
                             pending (peer died mid-send)

Dedup state is the seen (rid, seq) set; events:

  ("frame", rid, seq)     -> (("accept",),) first time, (("dup",),)
                             on redelivery
  ("forget", rid)         -> ()  a new transfer attempt restarts rid's
                             seq space
"""

import struct
import zlib
from typing import NamedTuple, Tuple

from . import ProtocolError

MAGIC = b"BAF1"
_HEADER = struct.Struct("!4sII")  # magic, payload length, crc32(payload)
MAX_FRAME = 1 << 28  # 256 MiB: a corrupt length field must not OOM us


class WireDesync(ProtocolError):
    """Broken magic or absurd length: the byte stream lost sync and no
    later frame boundary can be trusted.  The machine reports this as a
    ("desync", msg) OUTPUT (so same-chunk frames still deliver);
    `FrameBuffer.feed` turns it into fleet/transport.FrameError with
    the same message, and the checker's wire model treats it as a
    terminal channel state."""


class WireState(NamedTuple):
    buf: bytes
    crc_rejected: int
    torn: int


def wire_init() -> WireState:
    return WireState(b"", 0, 0)


def wire_step(st: WireState, event: Tuple) -> Tuple[WireState, Tuple]:
    kind = event[0]
    if kind == "feed":
        buf = st.buf + bytes(event[1])
        rejected = st.crc_rejected
        out = []
        while len(buf) >= _HEADER.size:
            magic, length, crc = _HEADER.unpack_from(buf)
            if magic != MAGIC or length > MAX_FRAME:
                out.append(("desync",
                            f"stream lost sync (magic={bytes(magic)!r}, "
                            f"length={length})"))
                break
            end = _HEADER.size + length
            if len(buf) < end:
                break  # incomplete frame; wait for more bytes
            payload = bytes(buf[_HEADER.size:end])
            buf = buf[end:]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                rejected += 1
                out.append(("crc_reject",))
                continue  # drop; sender retry re-ships
            out.append(("frame", payload))
        return WireState(buf, rejected, st.torn), tuple(out)
    if kind == "eof":
        if st.buf:
            return WireState(b"", st.crc_rejected, st.torn + 1), (("torn",),)
        return st, ()
    raise ValueError(f"unknown wire event {event!r}")


class DedupState(NamedTuple):
    seen: frozenset


def dedup_init() -> DedupState:
    return DedupState(frozenset())


def dedup_step(st: DedupState, event: Tuple) -> Tuple[DedupState, Tuple]:
    kind = event[0]
    if kind == "frame":
        key = (event[1], event[2])
        if key in st.seen:
            return st, (("dup",),)
        return DedupState(st.seen | {key}), (("accept",),)
    if kind == "forget":
        rid = event[1]
        return DedupState(frozenset(k for k in st.seen
                                    if k[0] != rid)), ()
    raise ValueError(f"unknown dedup event {event!r}")
