"""The write-ahead token journal machine: delivered ⟹ durable, pure.

Mirrors `serving/checkpoint.TokenJournal` + `journal_view`: appends
BUFFER (a file object's userspace buffer), `sync` (flush + fsync) folds
the buffered records into the DURABLE view, and a crash drops whatever
was buffered but not yet synced.  The delivery barrier the engines
enforce (step() syncs before returning results) becomes an explicit
machine transition: `("deliver", rid, n_total)` asserts that the
caller-visible stream of `rid` — `n_total` tokens — is already durable,
raising `DurabilityViolation` otherwise.  Production's
`TokenJournal.delivered()` runs exactly this transition, so a future
engine edit that returns tokens before the sync barrier fails LOUDLY in
every test that drives an engine, not just in the checker.

State (all hashable; per-rid maps are sorted tuples of pairs):

  buffered       record tuples in append order, not yet durable:
                 ("tokens", rid, k) | ("done", rid) | ("reset", rid)
                 | ("submit", rid)
  durable        ((rid, n_tokens), ...) — the folded on-disk view
                 (resets applied, exactly journal_view's fold)
  durable_done   rids whose "done" record is on disk
  delivered      ((rid, n_tokens), ...) — the high-water mark of what
                 callers have SEEN (not part of the file; the invariant
                 ties it to `durable`)

Events:

  ("append", kind, rid, k)   buffer one record (k = token count; 0 for
                             submit/done/reset)
  ("sync",)                  fold buffered into durable
  ("deliver", rid, n)        caller observes rid at n total tokens;
                             raises DurabilityViolation if n exceeds
                             the durable count
  ("crash",)                 buffered records vanish; durable survives;
                             `delivered` survives too — the caller
                             already saw those tokens, which is exactly
                             why the invariant matters after recovery

Pipelined delivery lag (ISSUE 20): the pipelined RaggedServeEngine
samples each launch's tokens ON DEVICE and reads them back one step
late, so a token exists for one tick in neither `buffered` nor
`durable` — it is not yet a journal event at all.  The machine needs no
new event kind for this: the deferred readback appends, syncs, and only
then delivers (analysis/modelcheck.journal_model's "pipelined launch" /
"pipelined step boundary" transitions), and a crash mid-flight simply
means the token was never journaled and recovery regenerates it.  What
the lag changes is WHEN deliver runs — one step after generation — and
the checker re-proves delivered ⟹ durable over every interleaving of
the lagged and synchronous boundaries.
"""

from typing import NamedTuple, Tuple

from . import ProtocolError


class DurabilityViolation(ProtocolError, RuntimeError):
    """Tokens reached a caller before their journal records were
    fsynced — a crash now would un-happen delivered output."""


class JournalState(NamedTuple):
    buffered: Tuple[Tuple, ...]
    durable: Tuple[Tuple[int, int], ...]
    durable_done: Tuple[int, ...]
    delivered: Tuple[Tuple[int, int], ...]


def init() -> JournalState:
    return JournalState((), (), (), ())


def _get(pairs: Tuple[Tuple[int, int], ...], rid: int) -> int:
    for r, n in pairs:
        if r == rid:
            return n
    return 0


def _set(pairs: Tuple[Tuple[int, int], ...], rid: int,
         n: int) -> Tuple[Tuple[int, int], ...]:
    out = tuple((r, v) for r, v in pairs if r != rid)
    return tuple(sorted(out + ((rid, n),)))


def durable_tokens(st: JournalState, rid: int) -> int:
    return _get(st.durable, rid)


def delivered_tokens(st: JournalState, rid: int) -> int:
    return _get(st.delivered, rid)


def durable_within_delivered(st: JournalState) -> bool:
    """The safety invariant proto-journal-durable proves over every
    interleaving: no caller ever saw a token that is not on disk."""
    return all(n <= _get(st.durable, rid) for rid, n in st.delivered)


def step(st: JournalState, event: Tuple) -> Tuple[JournalState, Tuple]:
    kind = event[0]
    if kind == "append":
        rkind, rid = event[1], int(event[2])
        k = int(event[3]) if len(event) > 3 else 0
        if rkind not in ("tokens", "done", "reset", "submit"):
            raise ValueError(f"unknown journal record kind {rkind!r}")
        if rkind == "tokens" and k <= 0:
            return st, ()  # TokenJournal.tokens() drops empty appends
        rec = (rkind, rid, k) if rkind == "tokens" else (rkind, rid)
        return st._replace(buffered=st.buffered + (rec,)), ()
    if kind == "sync":
        durable, done = st.durable, st.durable_done
        for rec in st.buffered:
            rkind, rid = rec[0], rec[1]
            if rkind == "tokens":
                durable = _set(durable, rid, _get(durable, rid) + rec[2])
            elif rkind == "reset":
                durable = _set(durable, rid, 0)
            elif rkind == "done" and rid not in done:
                done = tuple(sorted(done + (rid,)))
        return JournalState((), durable, done, st.delivered), ()
    if kind == "deliver":
        rid, n = int(event[1]), int(event[2])
        have = _get(st.durable, rid)
        if n > have:
            raise DurabilityViolation(
                f"rid {rid}: delivering {n} token(s) but only {have} "
                f"are durable — sync() must run before results leave "
                f"the engine")
        if n > _get(st.delivered, rid):
            st = st._replace(delivered=_set(st.delivered, rid, n))
        return st, ()
    if kind == "crash":
        return st._replace(buffered=()), ()
    raise ValueError(f"unknown journal event {event!r}")
