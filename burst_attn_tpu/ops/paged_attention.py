"""Ragged paged-attention decode kernel (TPU serving-side native kernel).

The reference is a training-time op library; its inference story stops at
"call the op".  A complete framework serves, and serving on TPU wants a
PAGED KV cache: the dense [B, Nkv, max_seq, D] cache the basic decoder uses
(models/decode.py) allocates worst-case memory per sequence and pays
O(max_seq) attention compute per decode step regardless of the actual
context length.  This module provides the kernel half of the paged design
(models/paged_decode.py holds the pool/cache manager):

  * KV lives in a shared pool of fixed-size pages `[n_pages, Nkv, page, D]`.
    A sequence owns a list of pages (its row of the page table); memory
    scales with TOKENS IN USE, not max_seq, and sequences of wildly
    different lengths batch together (ragged batching).
  * One decode step attends each sequence's single new query against its
    own pages only.  The Pallas grid walks `(batch, kv-head, page-slot)`;
    the PAGE TABLE is delivered via scalar prefetch and consulted in the
    kv index maps, so each grid step DMAs exactly the pool page it needs —
    the gather never materializes a contiguous copy of the cache.
  * Ragged lengths: slots past a sequence's live page count are clamped to
    its last live page (Pallas collapses consecutive identical block
    indexes into one fetch) and skipped by predication; the final partial
    page is masked by position.  Cost per sequence ∝ its length.

GQA folds the query-head group into the kernel's q tile: q arrives
[B, Nkv, G, D] (G = n_heads / n_kv_heads query rows per kv head) and each
grid step computes a [G, page] score tile — at G=8, d=128 this is a real
MXU tile, not a matvec.

Reference parity anchor: the closest reference analogue is the flash-attn
CUDA decode path (burst_utils.py:149-176 drives the same kernels at T=1);
paged layout + ragged batching are TPU-first extensions (no reference
equivalent — see PAPERS.md "Ragged Paged Attention").
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_flash import LOG2E, NEG_INF, VMEM_LIMIT, _interpret_default
from ..utils.compat import tpu_compiler_params


def _pad_group(q):
    """Pad the query-group dim to the 8-sublane minimum tile."""
    g = q.shape[2]
    gp = max(8, -(-g // 8) * 8)
    if gp != g:
        q = jnp.pad(q, [(0, 0), (0, 0), (0, gp - g), (0, 0)])
    return q, gp


def _decode_kernel(
    table_ref, n_live_ref, len_ref, lo_ref,  # scalar prefetch
    *refs,
    scale, page, n_slots, quant,
):
    if quant:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    live = (j < n_live_ref[b]) & (j >= lo_ref[b] // page)

    @pl.when(live)
    def _accum():
        q = q_ref[0, 0, :, :] * (scale * LOG2E)
        k_tile = k_ref[0, :, :]
        if quant:
            k_tile = k_tile.astype(jnp.bfloat16)
        s = jax.lax.dot_general(
            q, k_tile, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if quant:
            # per-token dequant folds into a column rescale of the scores:
            # q . (k_t * s_t) = (q . k_t) * s_t.  The matmuls run in bf16
            # (int8 casts exactly — |v| <= 127 — and fp8 e4m3's 4-bit
            # mantissa embeds in bf16's 8); quantization buys MEMORY, not
            # MXU throughput here.  One [G, page] multiply on the VPU.
            s = s * ks_ref[0]  # [1, page] broadcast over [G, page]
        # mask the final partial page's tail and (sliding window) the
        # positions below the window's lower edge
        pos = j * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = (pos < len_ref[b]) & (pos >= lo_ref[b])
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.where(m_prev >= m_new, 1.0, jnp.exp2(m_prev - m_new))
        p = jnp.exp2(s - m_new)
        p = jnp.where(valid, p, 0.0)
        m_scr[:] = m_new
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        if quant:
            # symmetric trick on the v side: p @ (v_t * s_t) = (p * s_t) @ v_t
            pv = jax.lax.dot_general(
                (p * vs_ref[0]).astype(jnp.bfloat16),
                v_ref[0, :, :].astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            pv = jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0, :, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(j == n_slots - 1)
    def _finish():
        # empty sequences (l == 0) emit zeros rather than NaN
        l = jnp.where(l_scr[:] > 0, l_scr[:], 1.0)
        o_ref[0, 0, :, :] = (acc_scr[:] / l).astype(o_ref.dtype)


# 1 B/elem pool storage dtypes and the full-range absmax each scale maps
# onto: int8 rounds into [-127, 127]; fp8 e4m3fn casts into +-448 (the
# format's largest finite).  Both dequantize as a per-token column rescale
# inside the kernels, so they share every downstream code path.
QUANT_DTYPES = {
    "int8": (jnp.int8, 127.0),
    "fp8": (jnp.float8_e4m3fn, 448.0),
}


def _quant_range(dtype):
    """(canonical name, full-scale range) for a 1 B pool dtype."""
    dt = jnp.dtype(dtype)
    for name, (cand, rng) in QUANT_DTYPES.items():
        if dt == jnp.dtype(cand):
            return name, rng
    raise ValueError(f"unsupported quantized pool dtype {dt!r} "
                     f"(one of {sorted(QUANT_DTYPES)})")


def quantize_tokens(x, dtype=jnp.int8):
    """Per-token symmetric quantization of [..., T, D] K/V rows into a
    1 B/elem pool dtype: returns (quantized values, f32 scales [..., T]).
    scale = max|x| / range per token (127 for int8, 448 for fp8 e4m3fn);
    zero rows get scale 1 (they dequantize to exact zeros).  int8 rounds
    and clips; fp8 casts directly (the cast IS the rounding)."""
    name, rng = _quant_range(dtype)
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    s = jnp.where(amax > 0, amax / rng, 1.0)
    xs = x.astype(jnp.float32) / s[..., None]
    if name == "int8":
        q = jnp.clip(jnp.round(xs), -rng, rng).astype(jnp.int8)
    else:
        q = xs.astype(jnp.float8_e4m3fn)
    return q, s


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           k_scales=None, v_scales=None,
                           window=None, scale=None, interpret=None):
    """One ragged decode step against a paged KV pool.

    q          [B, Nkv, G, D]   one new token per sequence, query heads
                                grouped under their kv head (G >= 1)
    k_pages    [P, Nkv, page, D]  shared pool (page = tokens per page,
    v_pages    [P, Nkv, page, D]   a multiple of 128)
    page_table [B, S] int32     pool page id per (sequence, slot); slots
                                at or past ceil(len/page) are ignored
    lengths    [B] int32        live tokens per sequence (0 = empty)
    window     static int       sliding-window attention: the new token (at
                                position lengths-1) sees only the last
                                `window` positions — pages fully below the
                                band are skipped, so cost ∝ window
    k_scales / v_scales  [P, Nkv, page] f32: per-token dequant scales for
                QUANTIZED pools (quantize_tokens: int8 or fp8 e4m3fn —
                the kernel is dtype-agnostic, both cast exactly to bf16)
                — both or neither.  The dequant rides the matmuls as
                column rescales; pool memory halves vs bf16 (1 B + 4 B
                scale per 128·2B token), quarters vs fp32.

    Returns [B, Nkv, G, D] attention output in q's dtype.
    """
    b, n_kv, g, d = q.shape
    page = k_pages.shape[2]
    n_slots = page_table.shape[1]
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = _interpret_default()
    q, gp = _pad_group(q)

    n_live = -(-lengths // page)  # pages in use per sequence
    # lower edge of the visible band (matches models/decode.py:108-111:
    # the query at position len-1 sees positions >= len - window)
    if window is None:
        lo = jnp.zeros_like(lengths)
    else:
        lo = jnp.maximum(lengths - window, 0)

    def q_map(b_, h, j, table, n_live_, len_, lo_):
        return (b_, h, 0, 0)

    def kv_map(b_, h, j, table, n_live_, len_, lo_):
        # clamp dead slots into the live band: consecutive duplicate
        # indexes collapse into a single DMA.  max(n_live-1, 0) keeps empty
        # sequences in range (their steps are fully predicated off).
        slot = jnp.clip(j, lo_[b_] // page, jnp.maximum(n_live_[b_] - 1, 0))
        return (table[b_, slot], h, 0, 0)

    quant = k_scales is not None
    if quant != (v_scales is not None):
        raise ValueError("k_scales and v_scales must be given together")
    kernel = functools.partial(
        _decode_kernel, scale=scale, page=page, n_slots=n_slots, quant=quant,
    )
    in_specs = [
        pl.BlockSpec((1, 1, gp, d), q_map),
        pl.BlockSpec((None, 1, page, d), kv_map),
        pl.BlockSpec((None, 1, page, d), kv_map),
    ]
    inputs = [page_table, n_live, lengths, lo, q, k_pages, v_pages]
    if quant:
        def sc_map(b_, h, j, table, n_live_, len_, lo_):
            return kv_map(b_, h, j, table, n_live_, len_, lo_)[:3] + (0,)

        # scales reshape to [P, Nkv, 1, page] so the block's LAST TWO dims
        # are (1, page) — legal Mosaic tiling for any Nkv (a [P, Nkv, page]
        # block would put the size-1 block dim against Nkv)
        in_specs.append(pl.BlockSpec((None, 1, 1, page), sc_map))
        in_specs.append(pl.BlockSpec((None, 1, 1, page), sc_map))
        inputs += [k_scales[:, :, None, :], v_scales[:, :, None, :]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, n_kv, n_slots),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, gp, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((gp, 1), jnp.float32),
            pltpu.VMEM((gp, 1), jnp.float32),
            pltpu.VMEM((gp, d), jnp.float32),
        ],
    )
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, gp, d), q.dtype),
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*inputs)
    return o[:, :, :g, :]


def paged_decode_reference(q, k_pages, v_pages, page_table, lengths,
                           window=None, scale=None,
                           k_scales=None, v_scales=None):
    """jnp oracle for the kernel: gathers each sequence's pages into a
    contiguous cache and runs dense masked attention.  O(B·S·page) memory —
    tests only.  Quantized pools (int8/fp8) dequantize with the per-token
    scales first."""
    if k_scales is not None:
        k_pages = k_pages.astype(jnp.float32) * k_scales[..., None]
        v_pages = v_pages.astype(jnp.float32) * v_scales[..., None]
    b, n_kv, g, d = q.shape
    page = k_pages.shape[2]
    n_slots = page_table.shape[1]
    if scale is None:
        scale = d**-0.5
    k = k_pages[page_table]  # [B, S, Nkv, page, D]
    v = v_pages[page_table]
    k = jnp.moveaxis(k, 2, 1).reshape(b, n_kv, n_slots * page, d)
    v = jnp.moveaxis(v, 2, 1).reshape(b, n_kv, n_slots * page, d)
    s = jnp.einsum("bngd,bnjd->bngj", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    pos = jnp.arange(n_slots * page)[None, :]
    valid = pos < lengths[:, None]
    if window is not None:
        valid = valid & (pos >= jnp.maximum(lengths - window, 0)[:, None])
    s = jnp.where(valid[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(valid[:, None, None, :], p, 0.0)  # all-masked rows -> 0
    return jnp.einsum("bngj,bnjd->bngd", p, v.astype(jnp.float32)).astype(q.dtype)
