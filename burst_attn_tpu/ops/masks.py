"""Per-round attention mask specifications for ring attention.

The reference implements causal load balancing with three *structural* code
paths per ring round (full / first-half-KV / second-half-Q for the zigzag
layout, and a shift-by-one tensor slicing for the striped layout — see
burst_attn/burst_attn_interface.py:221-235, :303-367, :454-475 in the
reference).  On TPU we instead parameterize ONE uniform attention tile by five
runtime scalars, so every ring round is the same traced computation (scan
body) and XLA/Pallas can skip the masked-out work via dynamic loop bounds:

    q_lo, q_hi : active query-row range [q_lo, q_hi)    (local indices)
    kv_hi      : active key/value-column range [0, kv_hi)
    causal     : 1 if a causal constraint applies
    offset     : col j visible from row i  iff  j <= i + offset

This reproduces the reference's case analysis exactly:

zigzag layout (rank p holds global chunks p and 2W-1-p, concatenated):
  * kv_part == q_part : plain causal on the local layout (offset 0).  Valid
    because both halves are internally contiguous and the first half precedes
    the second globally.
  * kv_part <  q_part : kv's first half is entirely in the local q's past and
    its second half entirely in the future -> full q x first-half kv,
    non-causal (reference's `split_kv` branch).
  * kv_part >  q_part : local q's first half sees nothing; its second half
    sees everything -> second-half q x full kv, non-causal.

striped layout (rank p holds global tokens p, p+W, p+2W, ...):
  local token i on rank a is global a + i*W; causality  b + jW <= a + iW
  reduces to  j <= i  when  b <= a  (offset 0) and  j <= i-1  otherwise
  (offset -1) — the reference's shift-by-one slicing
  (burst_attn_interface.py:463-475) expressed as a mask.

contig layout (plain contiguous chunks, the naive causal ring): kv_part <
q_part -> full, == -> causal, > -> fully masked.  Not load balanced; kept as
the ring-attention baseline (reference benchmarks/ring_attn.py).
"""

from typing import NamedTuple

import numpy as np
import jax.numpy as jnp

LAYOUTS = ("contig", "zigzag", "striped")


class MaskSpec(NamedTuple):
    """Runtime scalars (all int32) describing one ring round's mask."""

    q_lo: jnp.ndarray
    q_hi: jnp.ndarray
    kv_hi: jnp.ndarray
    causal: jnp.ndarray
    offset: jnp.ndarray


def _i32(x):
    return jnp.asarray(x, dtype=jnp.int32)


def full_spec(s_q: int, s_kv: int) -> MaskSpec:
    return MaskSpec(_i32(0), _i32(s_q), _i32(s_kv), _i32(0), _i32(0))


def round_spec(q_part, kv_part, s_q: int, s_kv: int, causal: bool, layout: str,
               window=None) -> MaskSpec:
    """Mask spec for one ring round.

    q_part / kv_part: global partition ids (traced int32 scalars) of the
    sequence chunks held by the query side and key/value side of this round.
    s_q / s_kv: static local sub-sequence lengths.  causal/layout: static.

    `window` (static int, None = unlimited) adds a sliding-window lower
    bound: each query attends to at most `window` keys ending at its causal
    position.  Supported for the "contig" layout only — in natural token
    order every ring round is the band `j <= i + delta` with `delta =
    (q_part - kv_part) * s` (a traced offset), so one offset-form spec plus
    the static window covers all rounds.  The zigzag/striped permutations
    interleave two token ranges per shard, which breaks the single-band
    structure a 5-scalar spec can express.
    """
    if window is not None:
        if layout != "contig":
            raise ValueError(
                f"window attention supports layout='contig' only, got "
                f"{layout!r} (the zigzag/striped load-balancing permutations "
                "break the band structure)")
        if not causal:
            raise ValueError("window attention requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        delta = (_i32(q_part) * s_q - _i32(kv_part) * s_kv).astype(jnp.int32)
        return MaskSpec(_i32(0), _i32(s_q), _i32(s_kv), _i32(1), delta)
    if not causal:
        return full_spec(s_q, s_kv)
    if layout == "zigzag":
        assert s_q % 2 == 0 and s_kv % 2 == 0, "zigzag needs even local seqlen"
        eq = q_part == kv_part
        q_lo = jnp.where(kv_part > q_part, s_q // 2, 0).astype(jnp.int32)
        kv_hi = jnp.where(kv_part < q_part, s_kv // 2, s_kv).astype(jnp.int32)
        return MaskSpec(q_lo, _i32(s_q), kv_hi, eq.astype(jnp.int32), _i32(0))
    elif layout == "striped":
        offset = jnp.where(kv_part <= q_part, 0, -1).astype(jnp.int32)
        return MaskSpec(_i32(0), _i32(s_q), _i32(s_kv), _i32(1), offset)
    elif layout == "contig":
        q_hi = jnp.where(kv_part > q_part, 0, s_q).astype(jnp.int32)
        return MaskSpec(_i32(0), q_hi, _i32(s_kv), (q_part == kv_part).astype(jnp.int32), _i32(0))
    else:
        raise ValueError(f"unknown layout {layout!r}; expected one of {LAYOUTS}")


def spec_live(spec: MaskSpec, window=None):
    """Traced bool scalar: does ANY (row, col) of this round's tile attend?

    False for a contig-causal ring's future rounds (q_hi == 0) and for
    windowed rounds whose whole band lies outside the resident kv chunk —
    the ring caller wraps the tile computation in `lax.cond` on this and
    skips the kernel launch entirely on dead rounds.  With window << seq a
    contig windowed ring of W devices has only ~ceil(window/chunk)+1 live
    rounds per device; every other round previously ran a full grid of
    masked-out blocks."""
    live = (spec.q_hi > spec.q_lo) & (spec.kv_hi > 0)
    # causal: some row must see col 0 (the earliest col of the chunk)
    live = live & ((spec.causal == 0) | (spec.q_hi - 1 + spec.offset >= 0))
    if window is not None:
        # band union over rows starts at q_lo + offset - window + 1; the
        # round is dead when that already exceeds the last kv col
        live = live & (spec.q_lo + spec.offset - window + 1 <= spec.kv_hi - 1)
    return live


def spec_pair_count(spec: MaskSpec, s_q: int, s_kv: int, window=None):
    """Traced f32 scalar: number of attending (row, col) pairs of one
    round's tile — the closed-ish form of `dense_mask(...).sum()` without
    materializing the [s_q, s_kv] mask (an O(s_q) row sweep instead of
    O(s_q * s_kv) booleans).

    This is the devstats mask-occupancy numerator (obs/devstats.py): per
    ring round, each live row i contributes the clamped width of its
    visible column interval [max(0, i + offset - window + 1),
    min(kv_hi - 1, i + offset)] (the causal/window band), or the full
    [0, kv_hi) range when the round is non-causal.  Asserted equal to the
    dense-mask sum in tests/test_devstats.py."""
    rows = jnp.arange(s_q, dtype=jnp.int32)
    in_row = (rows >= spec.q_lo) & (rows < spec.q_hi)
    hi = jnp.where(spec.causal > 0,
                   jnp.minimum(spec.kv_hi - 1, rows + spec.offset),
                   spec.kv_hi - 1)
    lo = jnp.zeros_like(rows)
    if window is not None:
        lo = jnp.where(spec.causal > 0,
                       jnp.maximum(lo, rows + spec.offset - window + 1), lo)
    n = jnp.clip(hi - lo + 1, 0, s_kv)
    return jnp.sum(jnp.where(in_row, n, 0)).astype(jnp.float32)


def _host_round_pairs(layout: str, q_part: int, kv_part: int, s: int,
                      causal: bool, window=None) -> int:
    """Host (numpy) twin of `spec_pair_count(round_spec(...))` for CONCRETE
    partition ids.  live_delta_table runs from inside traced callers
    (fused_ring.supported under shard_map), where even constant jnp ops
    become tracers — so the occupancy table needs an all-host evaluation.
    Mirrors round_spec's spec algebra field by field; pinned equal to the
    traced closed form and the dense-mask sum in tests/test_masks.py."""
    if window is not None:
        if layout != "contig":
            raise ValueError(
                f"window attention supports layout='contig' only, got "
                f"{layout!r}")
        if not causal:
            raise ValueError("window attention requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        q_lo, q_hi, kv_hi, caus, off = 0, s, s, 1, (q_part - kv_part) * s
    elif not causal:
        q_lo, q_hi, kv_hi, caus, off = 0, s, s, 0, 0
    elif layout == "zigzag":
        q_lo = s // 2 if kv_part > q_part else 0
        kv_hi = s // 2 if kv_part < q_part else s
        q_hi, caus, off = s, int(kv_part == q_part), 0
    elif layout == "striped":
        q_lo, q_hi, kv_hi, caus = 0, s, s, 1
        off = 0 if kv_part <= q_part else -1
    elif layout == "contig":
        q_lo, kv_hi, off = 0, s, 0
        q_hi = 0 if kv_part > q_part else s
        caus = int(q_part == kv_part)
    else:
        raise ValueError(f"unknown layout {layout!r}; expected one of {LAYOUTS}")
    rows = np.arange(s, dtype=np.int64)
    in_row = (rows >= q_lo) & (rows < q_hi)
    hi = np.minimum(kv_hi - 1, rows + off) if caus else np.full_like(rows, kv_hi - 1)
    lo = np.zeros_like(rows)
    if window is not None and caus:
        lo = np.maximum(lo, rows + off - window + 1)
    n = np.clip(hi - lo + 1, 0, s)
    return int(np.sum(np.where(in_row, n, 0)))


def live_delta_table(layout: str, s: int, world: int, *, causal: bool,
                     window=None, max_segment_len=None):
    """Per-ring-offset occupancy of a schedule: `live[delta]` is True iff
    ANY device's round at ring offset `delta` (q_part - kv_part = delta mod
    world, equal local chunk lengths `s`) attends at least one (row, col)
    pair.  This is `spec_pair_count` — the closed-form per-round occupancy —
    evaluated over the whole ring, and it is what the schedule compiler
    (parallel/schedule.py) consumes to ELIDE dead rounds: a False entry
    means no consume/send/recv/credit op for that offset anywhere on the
    ring, so the compiled program simply omits the round.

    `max_segment_len` (static int, contig layout only) adds the packed-
    segment reach bound: two chunks `delta` apart hold tokens at least
    `(delta-1)*s + 1` positions apart (adjacent chunks touch at distance 1),
    and tokens of one segment are at most `max_segment_len - 1` apart — so
    offsets past the bound cannot share a segment on any device.  It is a
    CONTRACT about the ids the caller will feed (not validated per batch
    under jit — document, don't trace); zigzag/striped interleave token
    ranges per shard, so no per-offset segment bound exists there and the
    argument is ignored for those layouts.

    Offset 0 (the self round) is always live.  All inputs are concrete
    host ints; the result is a host tuple of bools.
    """
    if world < 1:
        raise ValueError(f"need world >= 1, got {world}")
    live = [True]
    for delta in range(1, world):
        if not causal:
            alive = True
        else:
            alive = any(
                _host_round_pairs(layout, p, (p - delta) % world, s,
                                  True, window=window) > 0
                for p in range(world))
        if (alive and max_segment_len is not None and layout == "contig"):
            # min token distance between chunks delta apart vs the max
            # within-segment distance; without causality the kv chunk also
            # sits (world - delta) chunks AHEAD on wrapping devices, so the
            # live set is a prefix+suffix band and `live_round_prefix`
            # correctly refuses to truncate it
            dist = (delta - 1) * s + 1
            if not causal:
                dist = min(dist, (world - delta - 1) * s + 1)
            alive = dist <= max_segment_len - 1
        live.append(bool(alive))
    return tuple(live)


def live_round_prefix(layout: str, s: int, world: int, *, causal: bool,
                      window=None, max_segment_len=None) -> int:
    """Static live-round count when the live offsets form a PREFIX
    {0..K}: returns K + 1, or `world` (no truncation) when the live set is
    not a prefix (zigzag/striped, or any non-band structure).  This is the
    `r_live` the schedule compiler and the scan ring's static truncation
    share — contig windowed rings reproduce the historical closed form
    min(world, (s + window - 2) // s + 1) (asserted in tests)."""
    live = live_delta_table(layout, s, world, causal=causal, window=window,
                            max_segment_len=max_segment_len)
    k = max(i for i, alive in enumerate(live) if alive)
    if all(live[:k + 1]):
        return k + 1
    return world


def dense_mask(spec: MaskSpec, s_q: int, s_kv: int, window=None) -> jnp.ndarray:
    """Materialize the [s_q, s_kv] boolean mask (True = attend).

    Used by the jnp tile (the numerics oracle) and by tests; the Pallas
    kernels compute the same predicate block-wise with dynamic loop bounds.
    `window` (static) keeps only the last `window` visible columns of each
    row's causal range: cols > rows + offset - window.
    """
    rows = jnp.arange(s_q, dtype=jnp.int32)[:, None]
    cols = jnp.arange(s_kv, dtype=jnp.int32)[None, :]
    m = (rows >= spec.q_lo) & (rows < spec.q_hi) & (cols < spec.kv_hi)
    causal_ok = jnp.where(spec.causal > 0, cols <= rows + spec.offset, True)
    if window is not None:
        causal_ok = causal_ok & (cols > rows + spec.offset - window)
    return m & causal_ok
