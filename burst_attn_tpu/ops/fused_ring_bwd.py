"""Fused on-device ring attention BACKWARD: all W backward rounds in ONE
Pallas kernel — the comm-optimized BurstAttention backward (SURVEY §3.2)
with both of its concurrent streams carried by in-kernel inter-chip RDMA
instead of per-round `lax.ppermute` collectives between kernel launches.

Roles flip versus the fused forward (ops/fused_ring.py): K and V stay
RESIDENT on their home device for the whole kernel (dk/dv accumulate in
fp32 locally and never move), while two streams rotate concurrently:

  * the q-side BUNDLE — (delta, do, q, lse) in `optimize_bwd_comm` form
    (delta = sum(o * do) [B, N, S] f32; with the optimization off, o rides
    instead and delta is recomputed per tile, reproducing the reference's
    payload trade) — rotates exactly like the forward's KV: the round-r+1
    send leaves at round r's FIRST grid step from the slot the exported
    schedule names, and is in flight for the entire round-r compute sweep.
  * the dq RING — the fp32 partial gradient of whichever partition's bundle
    a device holds — follows ONE HOP BEHIND: a block's dq cannot leave until
    the local contribution is folded in, so each [bq, D] row-block streams
    out the moment its grid step finishes, arriving at the right neighbor
    before that neighbor's next round needs it.  At the last round the
    stream takes its return-home hop into a dedicated HOME slot on the
    right neighbor (one extra hop, exactly the scan backward's final
    ppermute), which the epilogue copies into the dq output.

Slot choreography for both streams comes from ONE exported schedule
(parallel/ring.fused_bwd_slot_schedule, scalar-prefetched into the kernel);
burstlint re-derives it independently and PROVES delivery, hop counts,
exactly-once dq return-home and overwrite-before-read safety by simulation
(analysis/oracle.verify_fused_ring_bwd), then checks the traced program
contains zero XLA collectives and the expected remote-copy census
(fused-ring-schedule / fused-ring-fused, bwd families).

Compute path.  Per grid step (r, b, h, i) the kernel folds bundle q-block i
against the WHOLE resident KV chunk (copied HBM -> VMEM once per (round,
batch, kv-head), as in the forward): per kv block j it forms
p = exp2(s·scale·log2e − lse·log2e) from the FINAL lse riding the bundle
(no online softmax in the backward — p is the true probability), then
dv += pᵀ·do, ds = p·(dp − delta), dk += dsᵀ·q, dq_local += ds·k, all f32
accumulated with the trailing *scale of ds deferred exactly like
pallas_flash's backward kernels.  dk/dv live in VMEM for a (b, kv-head)
segment and round-trip the output buffers between rounds (zero-initialized
at round 0, final at round W-1); masks reuse the SAME per-round
ops/masks.round_spec scalars the scan backward computes, with q/kv roles
swapped, so the two paths mask identically by construction.

Interpret mode, supported matrix, and fallback behavior mirror the forward
(docs/fused_ring.md): `fused_ring.supported(..., pass_="bwd")` gates the
dispatch in parallel/burst._bwd_impl, and any declined config takes the
scan-ring backward for that pass only.

Semaphore ledger (everything drains to zero; N = B*Nq*nqb grid steps per
round, C = slot count, world = W):

  precv[slot]   +4 per arriving bundle (left, rounds 1..W-1: one increment
                per operand), -4 at the round's first grid step
  psend[slot]   +4 per outgoing bundle send (rounds 0..W-2), -4 at the same
                round's last grid step (drain)
  dqrecv[slot]  +N from the left neighbor's streamed round-(r-1) dq blocks,
                -N at round r's first grid step
  dqsend[slot]  +N per round's streamed sends (rounds 0..W-2), -N at that
                round's last grid step
  home_sem[0/1] +N each during round W-1 (our sends out / left's blocks
                in), both -N at the globally last grid step before the
                HOME-slot -> dq output copy
  free_pay/free_dq (hw only)  capacity handshake per stream, the forward's
                formula: grants at the end of rounds 0..W-1-C, one credit
                taken per send round >= C-1; granted == taken == max(0, W-C).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .masks import round_spec
from .pallas_flash import (
    BIG_LSE,
    LOG2E,
    NEG_INF,
    VMEM_LIMIT,
    _block_full,
    _block_has_work,
    _block_mask,
    _pack,
    _pick_block,
    _spec_array,
)
from .tuning import resolve_fused
from ..parallel.ring import (
    fused_bwd_slot_schedule,
    my_partition,
    neighbor_ids,
    partition_at_round,
)
from ..utils.compat import axis_size, tpu_compiler_params

# barrier-semaphore namespace, distinct from the fused forward's (13) so a
# program tracing both kernels never aliases their startup barriers
_COLLECTIVE_ID = 14


def _col_from_pack(pack, bq, lp):
    """[bq // lp, lp] packed row-stat tile -> (bq, 1) column (element t of
    the flat row vector lives at pack[t // lp, t % lp] — same layout as
    pallas_flash's packed stats, read from a VMEM tile instead of a ref)."""
    if lp == 1:
        return pack
    rep = jnp.repeat(pack, lp, axis=0)  # (bq, lp); row t = pack[t // lp]
    t_lane = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 0) % lp
    c_idx = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 1)
    return jnp.sum(jnp.where(t_lane == c_idx, rep, 0.0), axis=1, keepdims=True)


# ---------------------------------------------------------------------------
# kernel


def _fused_bwd_kernel(
    sched_ref,
    first_hbm, do_hbm, q_hbm, lse_hbm, k_hbm, v_hbm,
    dq_ref, dk_ref, dv_ref,
    *rest,
    world, slots, scale, bq, bkv, lp, nqb, nkb, group, n_b, n_h,
    hw_sync, collect, opt_comm,
):
    """One grid step = bundle q-block i of head h, batch b_, bwd ring round r.

    sched_ref is the [world + 1, 6] prefetch table: rows 0..world-1 hold the
    per-round (q_lo, q_hi, kv_hi, causal, offset, slot) — mask scalars from
    ops/masks.round_spec with the q side being the ROTATING bundle and the
    kv side the resident chunk — and row `world` holds (me, right, left,
    0, 0, 0) neighbor ids.

    `collect` (static) appends one more OUTPUT before the scratch refs: a
    [1, slots] int32 SMEM array counting bundle consumes per communication
    slot — the devstats bwd slot-reuse counter (obs/devstats.py), written
    with pure scalar increments at round boundaries so the compute/DMA
    choreography (and dq/dk/dv) is bit-identical to collect=off.

    `opt_comm` (static) selects the bundle's first operand: delta in packed
    [.., rows, lp] f32 form (on) or o in [.., bq, D] form (off, delta
    recomputed per tile) — the reference's optimize_bwd_comm trade.
    """
    if collect:
        slot_use_ref = rest[0]
        rest = rest[1:]
    (firstbuf, dobuf, qbuf, lsebuf, dqbuf,
     kchunk, vchunk, dk_acc, dv_acc,
     q_t, do_t, first_t, lse_t, dq_arr, dq_scr,
     cp_sem, chunk_sem, kvio_sem, tile_sem, dqio_sem,
     psend, precv, dqsend, dqrecv, home_sem,
     free_pay, free_dq) = rest

    r = pl.program_id(0)
    b_ = pl.program_id(1)
    h = pl.program_id(2)
    i = pl.program_id(3)
    right = sched_ref[world, 1]
    left = sched_ref[world, 2]
    slot = sched_ref[r, 5]
    first_of_round = (b_ == 0) & (h == 0) & (i == 0)
    last_of_round = (b_ == n_b - 1) & (h == n_h - 1) & (i == nqb - 1)
    n_steps = n_b * n_h * nqb  # dq blocks streamed per round
    home = slots  # dedicated return-home slot, outside the ring cycle

    if collect:
        @pl.when(first_of_round)
        def _slot_tally():
            @pl.when(r == 0)
            def _zero():
                for j in range(slots):
                    slot_use_ref[0, j] = 0

            slot_use_ref[0, slot] = slot_use_ref[0, slot] + 1

    # ---- round choreography (first grid step of the round only) ----
    @pl.when(first_of_round & (r == 0))
    def _copy_in():
        # local bundle -> slot[0]: one HBM->HBM copy per operand so every
        # later round (compute reads, RDMA sends) addresses the slot
        # buffers uniformly
        cps = [
            pltpu.make_async_copy(first_hbm, firstbuf.at[slot], cp_sem.at[0]),
            pltpu.make_async_copy(do_hbm, dobuf.at[slot], cp_sem.at[1]),
            pltpu.make_async_copy(q_hbm, qbuf.at[slot], cp_sem.at[2]),
            pltpu.make_async_copy(lse_hbm, lsebuf.at[slot], cp_sem.at[3]),
        ]
        for c in cps:
            c.start()
        for c in cps:
            c.wait()

    if hw_sync:
        @pl.when(first_of_round & (r == 0))
        def _barrier():
            # neighbors must have entered the kernel (buffers live) before
            # any RDMA writes their slots
            bar = pltpu.get_barrier_semaphore()
            pltpu.semaphore_signal(bar, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_signal(bar, inc=1, device_id=right,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_wait(bar, 2)

    @pl.when(first_of_round & (r > 0))
    def _recv_wait():
        # round r's bundle (4 operands) and every streamed dq block of the
        # left neighbor's previous round must have LANDED in slot[r]
        pltpu.semaphore_wait(precv.at[slot], 4)
        pltpu.semaphore_wait(dqrecv.at[slot], n_steps)

    @pl.when(first_of_round & (r < world - 1))
    def _send_bundle():
        dst_slot = sched_ref[r + 1, 5]
        if hw_sync:
            @pl.when(r >= slots - 1)
            def _capacity():
                # target slots were last read by the neighbor at round
                # r + 1 - slots; take one credit per stream proving both
                # the bundle slot and the dq slot finished
                pltpu.semaphore_wait(free_pay, 1)
                pltpu.semaphore_wait(free_dq, 1)
        for src in (firstbuf, dobuf, qbuf, lsebuf):
            pltpu.make_async_remote_copy(
                src_ref=src.at[slot], dst_ref=src.at[dst_slot],
                send_sem=psend.at[dst_slot], recv_sem=precv.at[dst_slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL).start()
        # no wait here: the transfers overlap this whole round's sweep; the
        # drain wait sits at the round's LAST grid step below

    # ---- per-(round, batch, kv-head) chunk load: HBM -> VMEM, plus the
    # fp32 dk/dv accumulator carry (outputs double as the between-round
    # staging, like the forward's acc scratch) ----
    @pl.when((i == 0) & (h % group == 0))
    def _chunk_load():
        kvh = h // group
        lk = pltpu.make_async_copy(k_hbm.at[b_, kvh], kchunk, chunk_sem.at[0])
        lv = pltpu.make_async_copy(v_hbm.at[b_, kvh], vchunk, chunk_sem.at[1])
        lk.start()
        lv.start()

        @pl.when(r > 0)
        def _carry_load():
            ldk = pltpu.make_async_copy(dk_ref.at[b_, kvh], dk_acc,
                                        kvio_sem.at[0])
            ldv = pltpu.make_async_copy(dv_ref.at[b_, kvh], dv_acc,
                                        kvio_sem.at[1])
            ldk.start()
            ldv.start()
            ldk.wait()
            ldv.wait()

        @pl.when(r == 0)
        def _carry_zero():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        lk.wait()
        lv.wait()

    # ---- per-step bundle tile loads: slot HBM -> VMEM ----
    tl = [
        pltpu.make_async_copy(qbuf.at[slot, b_, h, i], q_t, tile_sem.at[0]),
        pltpu.make_async_copy(dobuf.at[slot, b_, h, i], do_t, tile_sem.at[1]),
        pltpu.make_async_copy(firstbuf.at[slot, b_, h, i], first_t,
                              tile_sem.at[2]),
        pltpu.make_async_copy(lsebuf.at[slot, b_, h, i], lse_t,
                              tile_sem.at[3]),
    ]
    for c in tl:
        c.start()

    # start the arriving-dq load early: it is only needed at the merge,
    # after the whole local sweep
    @pl.when(r > 0)
    def _dq_arr_start():
        pltpu.make_async_copy(dqbuf.at[slot, b_, h, i], dq_arr,
                              dqio_sem.at[0]).start()

    for c in tl:
        c.wait()

    # ---- local sweep over the resident chunk (no online softmax: p is
    # the true probability from the bundle's final lse) ----
    spec_r = tuple(sched_ref[r, c] for c in range(5))
    r0 = i * bq
    lse_col = _col_from_pack(lse_t[:], bq, lp)
    # fully-masked rows carry lse = -inf; BIG_LSE makes p underflow to 0
    # on the fast path without an elementwise select (pallas_flash idiom)
    lse_col = jnp.where(lse_col == NEG_INF, BIG_LSE, lse_col * LOG2E)
    q_raw = q_t[:]
    do_raw = do_t[:]
    if opt_comm:
        delta_col = _col_from_pack(first_t[:], bq, lp)
    else:
        delta_col = jnp.sum(
            first_t[:].astype(jnp.float32) * do_raw.astype(jnp.float32),
            axis=1, keepdims=True)
    q_sc = q_raw * (scale * LOG2E)
    dq_scr[:] = jnp.zeros_like(dq_scr)

    def _fold(c0, mask):
        ks = kchunk[pl.ds(c0, bkv), :]
        vs = vchunk[pl.ds(c0, bkv), :]
        s_t = jax.lax.dot_general(
            q_sc, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp is independent of the softmax: issue it before the VPU chain
        dp = jax.lax.dot_general(
            do_raw, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp2(s_t - lse_col)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        # trailing *scale of ds deferred to the dq merge / final dk store
        ds = p * (dp - delta_col)
        dv_acc[pl.ds(c0, bkv), :] = dv_acc[pl.ds(c0, bkv), :] + \
            jax.lax.dot_general(
                p.astype(do_raw.dtype), do_raw, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        dk_acc[pl.ds(c0, bkv), :] = dk_acc[pl.ds(c0, bkv), :] + \
            jax.lax.dot_general(
                ds.astype(q_raw.dtype), q_raw, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(ks.dtype), ks, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    for j in range(nkb):
        c0 = j * bkv
        live = _block_has_work(spec_r, r0, c0, bq, bkv)
        full = _block_full(spec_r, r0, c0, bq, bkv)

        @pl.when(live & full)
        def _fast(c0=c0):
            _fold(c0, None)

        @pl.when(live & ~full)
        def _masked(c0=c0):
            _fold(c0, _block_mask(spec_r, r0, c0, bq, bkv))

    # ---- dq merge: arriving partial (one hop behind) + local contribution,
    # staged back into the slot and streamed onward immediately ----
    @pl.when(r > 0)
    def _dq_merge():
        pltpu.make_async_copy(dqbuf.at[slot, b_, h, i], dq_arr,
                              dqio_sem.at[0]).wait()
        dq_scr[:] = dq_arr[:] + dq_scr[:] * scale

    @pl.when(r == 0)
    def _dq_init():
        # round 0 starts this partition's accumulation: no arrival to merge
        dq_scr[:] = dq_scr[:] * scale

    wb = pltpu.make_async_copy(dq_scr, dqbuf.at[slot, b_, h, i],
                               dqio_sem.at[1])
    wb.start()
    wb.wait()

    @pl.when(r < world - 1)
    def _dq_send_ring():
        # the concurrent dq stream: this block's partial leaves NOW, while
        # later blocks of the same round are still computing — it lands in
        # the right neighbor's slot[r+1] before its round r+1 first-step wait
        dst_slot = sched_ref[r + 1, 5]
        pltpu.make_async_remote_copy(
            src_ref=dqbuf.at[slot, b_, h, i],
            dst_ref=dqbuf.at[dst_slot, b_, h, i],
            send_sem=dqsend.at[dst_slot], recv_sem=dqrecv.at[dst_slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL).start()

    @pl.when(r == world - 1)
    def _dq_send_home():
        # return-home hop: the fully-accumulated partition gradient lands in
        # the right neighbor's dedicated HOME slot (its owner)
        pltpu.make_async_remote_copy(
            src_ref=dqbuf.at[slot, b_, h, i],
            dst_ref=dqbuf.at[home, b_, h, i],
            send_sem=home_sem.at[0], recv_sem=home_sem.at[1],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL).start()

    # ---- dk/dv segment epilogue: stage the fp32 accumulators back to the
    # output buffers (final at the last round, with ds's deferred scale) ----
    @pl.when((i == nqb - 1) & (h % group == group - 1))
    def _kv_store():
        kvh = h // group

        @pl.when(r == world - 1)
        def _final_scale():
            dk_acc[:] = dk_acc[:] * scale

        sk = pltpu.make_async_copy(dk_acc, dk_ref.at[b_, kvh], kvio_sem.at[2])
        sv = pltpu.make_async_copy(dv_acc, dv_ref.at[b_, kvh], kvio_sem.at[3])
        sk.start()
        sv.start()
        sk.wait()
        sv.wait()

    # ---- round epilogue (last grid step of the round only) ----
    @pl.when(last_of_round & (r < world - 1))
    def _send_drain():
        # outgoing RDMA read slot[r]; everything must be out the door before
        # the left neighbor may overwrite the slots (free credits below) and
        # before the kernel may exit with a live DMA
        dst_slot = sched_ref[r + 1, 5]
        pltpu.semaphore_wait(psend.at[dst_slot], 4)
        pltpu.semaphore_wait(dqsend.at[dst_slot], n_steps)

    @pl.when(last_of_round & (r == world - 1))
    def _home_epilogue():
        # drain our own return-home sends, wait for the left neighbor's
        # full set of home blocks, then land the HOME slot in the output
        pltpu.semaphore_wait(home_sem.at[0], n_steps)
        pltpu.semaphore_wait(home_sem.at[1], n_steps)
        cp = pltpu.make_async_copy(dqbuf.at[home], dq_ref, cp_sem.at[0])
        cp.start()
        cp.wait()

    if hw_sync:
        @pl.when(last_of_round & (r <= world - 1 - slots))
        def _grant_free():
            # slot[r] of both streams has no further readers here: every
            # grid step consumed its tiles, our onward sends drained — the
            # LEFT neighbor (writer of our slots) may target them again
            pltpu.semaphore_signal(free_pay, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_signal(free_dq, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)


# ---------------------------------------------------------------------------
# shard-level entry point


def fused_ring_bwd(cfg, q, k, v, o, lse, do, *, interpret=None,
                   collect_stats=False):
    """Backward burst attention on per-shard arrays via the fused ring
    kernel — the drop-in twin of parallel/burst._bwd_impl's scan ring.

    Call inside shard_map on the ring axis: q/o/do [B, N, S, D], k/v
    [B, Nk, S, D], lse [B, N, S] f32 (the forward residuals in layout
    order).  Returns (dq, dk, dv) in float32 — the caller casts back to
    the input dtypes, exactly like the scan backward — plus the kernel's
    [1, slots] int32 bundle slot-consume counters when `collect_stats`
    (the devstats bwd slot-reuse channel; the stats-off call emits the
    identical kernel with no extra output).  Callers must have checked
    `fused_ring.supported(..., pass_="bwd")` first.
    """
    b, n, s, d = q.shape
    n_kv = k.shape[1]
    assert n % n_kv == 0, f"GQA needs Nq % Nk == 0, got {n} % {n_kv}"
    group = n // n_kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = cfg.scale if cfg.scale is not None else d ** -0.5
    world = axis_size(cfg.intra_axis)
    rf = resolve_fused(cfg.fused_block_q, cfg.fused_block_kv,
                       cfg.fused_kv_slots,
                       block_q_bwd=cfg.fused_block_q_bwd,
                       block_kv_bwd=cfg.fused_block_kv_bwd,
                       bwd_slots=cfg.fused_bwd_slots)
    slots = min(rf.bwd_slots, world)
    bq = _pick_block(s, rf.block_q_bwd)
    bkv = _pick_block(s, rf.block_kv_bwd)
    lp = _pick_block(bq, 128)
    nqb = s // bq
    rows = bq // lp
    nkb = s // bkv

    # [world + 1, 6] schedule table (see _fused_bwd_kernel docstring): mask
    # scalars reuse the SAME per-round specs the scan backward computes —
    # q side = rotating bundle partition, kv side = resident local chunk
    part_me = my_partition(cfg.intra_axis, None)
    slot_sched = fused_bwd_slot_schedule(world, slots)
    table = []
    for r in range(world):
        sp = round_spec(partition_at_round(r, cfg.intra_axis, None), part_me,
                        s, s, cfg.causal, cfg.layout)
        table.append(jnp.concatenate(
            [_spec_array(sp),
             jnp.asarray([int(slot_sched[r])], jnp.int32)]))
    me, right, left = neighbor_ids(cfg.intra_axis)
    table.append(jnp.stack([jnp.asarray(me, jnp.int32),
                            jnp.asarray(right, jnp.int32),
                            jnp.asarray(left, jnp.int32),
                            jnp.int32(0), jnp.int32(0), jnp.int32(0)]))
    sched = jnp.stack(table)

    # bundle operands, pre-blocked so every slot/tile address is integer
    # indexing ([B, N, nqb, bq, D] is the same memory as [B, N, S, D]);
    # rank-3 stats ride in pallas_flash's packed [.., rows, lp] layout
    q_in = q.reshape(b, n, nqb, bq, d)
    do_in = do.reshape(b, n, nqb, bq, d)
    lse_in = _pack(lse.astype(jnp.float32), lp).reshape(b, n, nqb, rows, lp)
    if cfg.optimize_bwd_comm:
        delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                        axis=-1)
        first_in = _pack(delta, lp).reshape(b, n, nqb, rows, lp)
        first_slot_shape = (b, n, nqb, rows, lp)
        first_tile_shape = (rows, lp)
        first_dtype = jnp.float32
    else:
        # ring payload grows by a factor of head_dim; delta is recomputed
        # per tile from the rotated (o, do) pair (reference parity)
        first_in = o.reshape(b, n, nqb, bq, d)
        first_slot_shape = (b, n, nqb, bq, d)
        first_tile_shape = (bq, d)
        first_dtype = o.dtype

    kernel = functools.partial(
        _fused_bwd_kernel, world=world, slots=slots, scale=scale, bq=bq,
        bkv=bkv, lp=lp, nqb=nqb, nkb=nkb, group=group, n_b=b, n_h=n,
        hw_sync=not interpret, collect=collect_stats,
        opt_comm=cfg.optimize_bwd_comm,
    )

    out_specs = [
        pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),  # dq
        pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),  # dk
        pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),  # dv
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, n, nqb, bq, d), jnp.float32),
        jax.ShapeDtypeStruct((b, n_kv, s, d), jnp.float32),
        jax.ShapeDtypeStruct((b, n_kv, s, d), jnp.float32),
    ]
    if collect_stats:
        out_specs.append(
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.SMEM))
        out_shape.append(jax.ShapeDtypeStruct((1, slots), jnp.int32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(world, b, n, nqb),
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)] * 6,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.ANY((slots,) + first_slot_shape, first_dtype),  # firstbuf
            pltpu.ANY((slots, b, n, nqb, bq, d), do.dtype),       # dobuf
            pltpu.ANY((slots, b, n, nqb, bq, d), q.dtype),        # qbuf
            pltpu.ANY((slots, b, n, nqb, rows, lp), jnp.float32),  # lsebuf
            # dq ring slots + the dedicated return-home slot (index `slots`)
            pltpu.ANY((slots + 1, b, n, nqb, bq, d), jnp.float32),  # dqbuf
            pltpu.VMEM((s, d), k.dtype),                  # kchunk
            pltpu.VMEM((s, d), v.dtype),                  # vchunk
            pltpu.VMEM((s, d), jnp.float32),              # dk_acc
            pltpu.VMEM((s, d), jnp.float32),              # dv_acc
            pltpu.VMEM((bq, d), q.dtype),                 # q_t
            pltpu.VMEM((bq, d), do.dtype),                # do_t
            pltpu.VMEM(first_tile_shape, first_dtype),    # first_t
            pltpu.VMEM((rows, lp), jnp.float32),          # lse_t
            pltpu.VMEM((bq, d), jnp.float32),             # dq_arr
            pltpu.VMEM((bq, d), jnp.float32),             # dq_scr
            pltpu.SemaphoreType.DMA((4,)),                # cp_sem
            pltpu.SemaphoreType.DMA((2,)),                # chunk_sem
            pltpu.SemaphoreType.DMA((4,)),                # kvio_sem
            pltpu.SemaphoreType.DMA((4,)),                # tile_sem
            pltpu.SemaphoreType.DMA((2,)),                # dqio_sem
            pltpu.SemaphoreType.DMA((slots,)),            # psend
            pltpu.SemaphoreType.DMA((slots,)),            # precv
            pltpu.SemaphoreType.DMA((slots,)),            # dqsend
            pltpu.SemaphoreType.DMA((slots,)),            # dqrecv
            pltpu.SemaphoreType.DMA((2,)),                # home_sem
            pltpu.SemaphoreType.REGULAR,                  # free_pay
            pltpu.SemaphoreType.REGULAR,                  # free_dq
        ],
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # sequential by construction: the ring choreography, the VMEM
        # dk/dv accumulators and the dq stream all assume one core walks
        # the grid in order — a megacore split would race them
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("arbitrary",) * 4,
            collective_id=_COLLECTIVE_ID,
        ),
        interpret=interpret,
    )(sched, first_in, do_in, q_in, lse_in, k, v)
    dq = outs[0].reshape(b, n, s, d)
    if not collect_stats:
        return dq, outs[1], outs[2]
    return dq, outs[1], outs[2], outs[3]
