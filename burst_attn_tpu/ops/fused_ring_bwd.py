"""Fused on-device ring attention BACKWARD: all R backward rounds in ONE
Pallas kernel — the comm-optimized BurstAttention backward with both of its
concurrent streams carried by in-kernel inter-chip RDMA instead of
per-round `lax.ppermute` collectives between kernel launches.

Roles flip versus the fused forward (ops/fused_ring.py): K and V stay
RESIDENT on their home device for the whole kernel (fp32 dk/dv accumulate
in VMEM per (batch, kv-head) segment and never move), while the q-side
BUNDLE — (delta|o, do, q, lse) in `optimize_bwd_comm` form — rotates
exactly like the forward's KV, and the dq partial gradients ride
accumulating rings ONE HOP BEHIND their bundles: a block's dq cannot leave
until the local contribution is folded in, so each [bq, D] row-block
streams out the moment its grid step finishes.

Schedule IR.  Like the forward, this kernel interprets a compiled
`RingProgram` (parallel/schedule.compile_bwd) and contains no topology
logic of its own.  The bundle movement reuses the forward program's
channel/bank/credit columns; the dq plan adds per-round columns saying
which dq ring the local contribution folds into, whether a partial
arrives, and the send kind:

  RING      onward hop to the bank's direction neighbor (cw / ccw / intra)
  HOME      the direction's terminal round: the completed partial takes ONE
            direct RDMA to its partition's owner (`home_offsets` away — the
            uni ring's right neighbor; a bidi ring's two directions end
            ceil/floor((W-1)/2) hops out on opposite sides)
  BOUNDARY  double ring, end of a non-final cycle: fold the held inter
            partial, hop the sum one inter step (the scan backward's
            dq_inter add-and-forward), into a 2-slot ping/pong bank
  FINAL     double ring, end of the last cycle: fold and take the composed
            (inter+1, intra+1) home hop — one RDMA where the scan path
            pays two ppermutes

Topologies: uni (exactly the hand-built PR-5 choreography), bidi (two
counter-rotating bundle+dq ring pairs, per-direction banks/semaphores; the
owner receives its gradient as TWO complementary partials — cw carrying
contributions from the self round and the clockwise visitors, ccw from the
counter-clockwise visitors — summed outside the kernel), and double (the
hierarchical ring with the bundle's inter prefetch one full intra-cycle
early).  Every program is simulation-proven by burstlint before trust
(analysis/oracle.verify_ring_program: bundle delivery + slot safety per
bank and the dq streams' exactly-once return-home with all `world`
contributions).

Compute path.  Per grid step (r, b, h, i) the kernel folds bundle q-block i
against the WHOLE resident KV chunk: per kv block j it forms
p = exp2(s·scale·log2e − lse·log2e) from the FINAL lse riding the bundle
(no online softmax — p is the true probability), then dv += pᵀ·do,
ds = p·(dp − delta), dk += dsᵀ·q, dq_local += ds·k, all f32 accumulated
with the trailing *scale of ds deferred exactly like pallas_flash's
backward kernels.  Masks reuse the SAME per-round ops/masks.round_spec
scalars the scan backward computes, with q/kv roles swapped, so the two
paths mask identically by construction.

Semaphore ledger (everything drains to zero; N = B*Nq*nqb grid steps per
round):

  precv[bank][slot]   +4 per arriving bundle (+7 under wire_dtype: three
                      fp32 scale sub-payloads ride the same slot), -same
                      at the consuming round's first grid step
  psend[bank][slot]   +4 (+7 wire) per outgoing bundle send, -same at the
                      same round's last grid step (drain)
  dqrecv[bank][slot]  +N from the writer's streamed previous-serving
                      blocks, -N at the serving round's first grid step
  dqsend[bank][slot]  +N per round's streamed ring sends, -N at that
                      round's last grid step
  dqi send/recv[slot] (double) +N per boundary stream, drained at the
                      boundary's last step / waited at the next boundary's
                      first step
  home{b} send/recv   +N each around a HOME/FINAL round; drained/waited at
                      the terminal epilogue before the output copy
  free_pay[bank][slot], free_dq[bank][slot], free_dqi[slot] (hw only)
                      per-SLOT capacity credits, compiler-assigned
                      (GRANT columns carry slot+1, takes ride the sends)
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_flash import (
    BIG_LSE,
    LOG2E,
    NEG_INF,
    VMEM_LIMIT,
    _block_full,
    _block_has_work,
    _block_mask,
    _pack,
    _pick_block,
    _seg_uniform_eq,
)
from .tuning import resolve_fused
from .fused_ring import (build_sched_table, dma_sem_wait, gather_seg_table,
                         kernel_statics, _SENDC, _GRANTC)
from ..parallel import schedule as sched_ir
from ..parallel.ring import WIRE_QMAX, wire_quantize
from ..utils.compat import axis_size, tpu_compiler_params

# barrier-semaphore namespace, distinct from the fused forward's (13) so a
# program tracing both kernels never aliases their startup barriers
_COLLECTIVE_ID = 14

_LOGICAL = None  # filled lazily to keep module import light


def _wire_quant_tile(x, wire):
    """In-kernel symmetric quantization of one fp32 tile: per-block scalar
    scale (the dq ring's refreshed per-hop scale).  Mirrors
    parallel/ring.wire_quantize with keepdims collapsed to a 0-d scalar."""
    amax = jnp.max(jnp.abs(x))
    sc = jnp.maximum(amax, 1e-30) / WIRE_QMAX[wire]
    if wire == "int8":
        q = jnp.clip(jnp.round(x / sc), -127.0, 127.0).astype(jnp.int8)
    else:
        q = (x / sc).astype(jnp.float8_e4m3fn)
    return q, sc


def _col_from_pack(pack, bq, lp):
    """[bq // lp, lp] packed row-stat tile -> (bq, 1) column (element t of
    the flat row vector lives at pack[t // lp, t % lp] — same layout as
    pallas_flash's packed stats, read from a VMEM tile instead of a ref)."""
    if lp == 1:
        return pack
    rep = jnp.repeat(pack, lp, axis=0)  # (bq, lp); row t = pack[t // lp]
    t_lane = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 0) % lp
    c_idx = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 1)
    return jnp.sum(jnp.where(t_lane == c_idx, rep, 0.0), axis=1, keepdims=True)


def bwd_statics(prog):
    """Static dq-plan structure of a compiled backward program: which dq
    ring banks exist, where each home send happens, whether the double
    ring's inter (dqi) machinery is present.  Like fused_ring.kernel_
    statics this decides which code the kernel EMITS, so the remote-DMA
    census is a function of the program alone."""
    rows = prog.rows
    R = prog.n_rounds
    ring_banks = tuple(sorted({rows["dq_bank"][r] for r in range(R)
                               if rows["dq_send"][r] == sched_ir.DQ_RING}))
    serve_banks = tuple(sorted({rows["dq_bank"][r] for r in range(R)}))
    home_rounds = {}
    for r in range(R):
        if rows["dq_send"][r] == sched_ir.DQ_HOME:
            home_rounds[rows["dq_bank"][r]] = r
        elif rows["dq_send"][r] == sched_ir.DQ_FINAL:
            home_rounds[0] = r
    has_dqi = any(rows["dqi_recv"][r] or
                  rows["dq_send"][r] == sched_ir.DQ_BOUNDARY
                  for r in range(R))
    take_banks = tuple(b for b in range(2)
                       if any(rows[f"dq_take{b}"][r] for r in range(R)))
    grant_banks = tuple(b for b in range(2)
                        if any(rows[f"dq_grant{b}"][r] for r in range(R)))
    return dict(ring_banks=ring_banks, serve_banks=serve_banks,
                home_rounds=home_rounds, has_dqi=has_dqi,
                take_banks=take_banks, grant_banks=grant_banks)


# ---------------------------------------------------------------------------
# kernel


def _fused_bwd_kernel(
    sched_ref,
    first_hbm, do_hbm, q_hbm, lse_hbm, k_hbm, v_hbm,
    *refs,
    prog, statics, dq_statics, scale, bq, bkv, lp, nqb, nkb, group,
    n_b, n_h, hw_sync, collect, opt_comm, wnd, has_seg, wire,
):
    """One grid step = bundle q-block i of head h, batch b_, bwd ring round r.

    sched_ref is the [R + 1, BWD_COLS] prefetch table (parallel/schedule.py
    column constants): per-round mask scalars (q side = rotating bundle
    partition, kv side = resident chunk), the bundle's bank/slot/send/credit
    columns, and the dq plan; row R holds the traced neighbor/home ids.

    `collect` (static) appends one more OUTPUT after dq/dk/dv: a
    [n_banks, max_slots] int32 SMEM array counting bundle consumes per
    (bank, slot) — the devstats bwd slot-reuse counter with per-direction
    rows, written with pure scalar increments at round boundaries so the
    compute/DMA choreography (and dq/dk/dv) is bit-identical to
    collect=off.
    """
    R = prog.n_rounds
    n_banks = prog.n_banks
    dq_banks = prog.n_dq_banks if prog.topology != "double" else 1
    home_banks = sorted(dq_statics["home_rounds"])
    has_dqi = dq_statics["has_dqi"]
    refs = list(refs)
    # wire-quantized bundles carry three per-(batch, head) fp32 scale
    # inputs right after the six bundle/kv operands (lse never quantizes)
    if wire is not None:
        fsc_hbm = refs.pop(0)    # [B, N, 1, 1] f32 first/delta scales
        dosc_hbm = refs.pop(0)   # [B, N, 1, 1] f32 do scales
        qsc_hbm = refs.pop(0)    # [B, N, 1, 1] f32 q scales
    # optional segment-id inputs ride after the six bundle/kv operands:
    # local KV-side ids resident in VMEM, the gathered ring-wide table in
    # ANY (roles swapped vs the forward — the ROTATING side is q here)
    if has_seg:
        segkv_ref = refs.pop(0)  # [1, 1, s] VMEM block: LOCAL kv ids
        sega_hbm = refs.pop(0)   # [B, world, s, 1] ANY: every shard's ids
    # outputs first: dq per home bank (+ their scale outputs under wire),
    # dk, dv, (slot_use)
    dq_refs = [refs.pop(0) for _ in home_banks]
    dqsc_refs = [refs.pop(0) for _ in home_banks] if wire is not None \
        else []
    dk_ref = refs.pop(0)
    dv_ref = refs.pop(0)
    if collect:
        slot_use_ref = refs.pop(0)
    firstbuf, dobuf, qbuf, lsebuf = [], [], [], []
    for _ in range(n_banks):
        firstbuf.append(refs.pop(0))
        dobuf.append(refs.pop(0))
        qbuf.append(refs.pop(0))
        lsebuf.append(refs.pop(0))
    fscbuf, doscbuf, qscbuf = [], [], []
    if wire is not None:
        # fp32 scale sub-banks: same slot indices, same send/recv
        # semaphores and capacity credits as the bundle banks they scale
        for _ in range(n_banks):
            fscbuf.append(refs.pop(0))
            doscbuf.append(refs.pop(0))
            qscbuf.append(refs.pop(0))
    dqbuf = [refs.pop(0) for _ in range(dq_banks)]
    dqscbuf = [refs.pop(0) for _ in range(dq_banks)] if wire is not None \
        else []
    dqibuf = refs.pop(0) if has_dqi else None
    dqiscbuf = refs.pop(0) if has_dqi and wire is not None else None
    (kchunk, vchunk, dk_acc, dv_acc,
     q_t, do_t, first_t, lse_t, dq_arr, dqi_arr, dq_scr,
     cp_sem, chunk_sem, kvio_sem, tile_sem, dqio_sem) = refs[:16]
    refs = refs[16:]
    if wire is not None:
        # (1, 1) f32 scale tiles + the dq re-quantization staging pair
        (fsc_t, dosc_t, qsc_t, dqsc_arr, dqisc_arr, dq_q, dqsc_w) = refs[:7]
        refs = refs[7:]
    psend, precv, free_pay = [], [], []
    for _ in range(n_banks):
        psend.append(refs.pop(0))
        precv.append(refs.pop(0))
        free_pay.append(refs.pop(0))
    dqsend, dqrecv, free_dq = [], [], []
    for _ in range(dq_banks):
        dqsend.append(refs.pop(0))
        dqrecv.append(refs.pop(0))
        free_dq.append(refs.pop(0))
    if has_dqi:
        dqi_send = refs.pop(0)
        dqi_recv = refs.pop(0)
        free_dqi = refs.pop(0)
    home_sems = {b: refs.pop(0) for b in home_banks}
    if has_seg:
        segbuf = refs.pop(0)     # VMEM (s, 1) int32: this round's q ids
        seg_sem = refs.pop(0)
    assert not refs, f"{len(refs)} scratch refs left over"

    LOGICAL = pltpu.DeviceIdType.LOGICAL
    r = pl.program_id(0)
    b_ = pl.program_id(1)
    h = pl.program_id(2)
    i = pl.program_id(3)
    bank = sched_ref[r, sched_ir.CONSUME_BANK]
    slot = sched_ref[r, sched_ir.CONSUME_SLOT]
    dq_bank_c = sched_ref[r, sched_ir.DQ_BANK]
    dq_slot = sched_ref[r, sched_ir.DQ_SLOT]
    dq_kind = sched_ref[r, sched_ir.DQ_SEND]
    first_of_round = (b_ == 0) & (h == 0) & (i == 0)
    last_of_round = (b_ == n_b - 1) & (h == n_h - 1) & (i == nqb - 1)
    n_steps = n_b * n_h * nqb  # dq blocks streamed per round

    def dq_banked(fn):
        """Run fn(bank) under a pl.when for each dq ring bank."""
        if prog.topology == "double":
            fn(0)
            return
        for b in range(dq_banks):
            pl.when(dq_bank_c == b)(functools.partial(fn, b))

    if collect:
        @pl.when(first_of_round)
        def _slot_tally():
            @pl.when(r == 0)
            def _zero():
                for bb in range(slot_use_ref.shape[0]):
                    for j in range(slot_use_ref.shape[1]):
                        slot_use_ref[bb, j] = 0

            slot_use_ref[bank, slot] = slot_use_ref[bank, slot] + 1

    # ---- round choreography (first grid step of the round only) ----
    # every site that moves "the bundle" walks this list: the four dense
    # operands plus, under wire, the three scale sub-banks riding the same
    # slots/semaphores (sends, recv waits, drains and copy_in stay in sync
    # by construction)
    bundle_hbm = [first_hbm, do_hbm, q_hbm, lse_hbm]
    bundle_bufs = [firstbuf, dobuf, qbuf, lsebuf]
    if wire is not None:
        bundle_hbm += [fsc_hbm, dosc_hbm, qsc_hbm]
        bundle_bufs += [fscbuf, doscbuf, qscbuf]
    per_op = len(bundle_bufs)

    @pl.when(first_of_round & (r == 0))
    def _copy_in():
        # local bundle -> its program-designated slot(s): one HBM->HBM copy
        # per operand per launch bank
        cps = []
        for idx, (cb, cslot) in enumerate(prog.copy_in):
            for j, (src, bufs) in enumerate(zip(bundle_hbm, bundle_bufs)):
                cps.append(pltpu.make_async_copy(
                    src, bufs[cb].at[cslot], cp_sem.at[per_op * idx + j]))
        for c in cps:
            c.start()
        for c in cps:
            c.wait()

    if hw_sync:
        @pl.when(first_of_round & (r == 0))
        def _barrier():
            # every RDMA peer must have entered the kernel (buffers live)
            # before any send targets its slots; home peers are covered by
            # the ring peers' transitive barrier (the home hop happens
            # R - 1 rounds later)
            bar = pltpu.get_barrier_semaphore()
            n_sig = 0
            for ch in statics["ch_active"]:
                pltpu.semaphore_signal(
                    bar, inc=1, device_id=sched_ref[R, _SENDC[ch][4]],
                    device_id_type=LOGICAL)
                pltpu.semaphore_signal(
                    bar, inc=1, device_id=sched_ref[R, _GRANTC[ch][1]],
                    device_id_type=LOGICAL)
                n_sig += 2
            pltpu.semaphore_wait(bar, n_sig)

    @pl.when(first_of_round & (sched_ref[r, sched_ir.RECV] == 1))
    def _recv_wait():
        # round r's bundle (4 operands) must have LANDED in its slot
        for b in statics["consume_banks"]:
            @pl.when(bank == b)
            def _w(b=b):
                # one wait per operand transfer; together they retire the
                # full bundle regardless of landing order
                for bufs in bundle_bufs:
                    dma_sem_wait(precv[b].at[slot], bufs[b].at[slot])

    @pl.when(first_of_round & (sched_ref[r, sched_ir.DQ_RECV] == 1))
    def _dq_recv_wait():
        # every streamed dq block of the writer's previous serving round:
        # the n_steps block transfers sum to exactly one slot entry (2x
        # transfers under wire: each block's payload plus its scale)
        def _w(b):
            dma_sem_wait(dqrecv[b].at[dq_slot], dqbuf[b].at[dq_slot])
            if wire is not None:
                dma_sem_wait(dqrecv[b].at[dq_slot], dqscbuf[b].at[dq_slot])

        dq_banked(_w)

    if has_dqi:
        @pl.when(first_of_round & (sched_ref[r, sched_ir.DQI_RECV] == 1))
        def _dqi_recv_wait():
            dqi_slot = sched_ref[r, sched_ir.DQI_SLOT]
            dma_sem_wait(dqi_recv.at[dqi_slot], dqibuf.at[dqi_slot])
            if wire is not None:
                dma_sem_wait(dqi_recv.at[dqi_slot], dqiscbuf.at[dqi_slot])

    for ch in statics["ch_active"]:
        send_c, src_c, dst_c, take_c, meta_dst = _SENDC[ch]

        @pl.when(first_of_round & (sched_ref[r, send_c] == 1))
        def _send_bundle(ch=ch, src_c=src_c, dst_c=dst_c, take_c=take_c,
                         meta_dst=meta_dst):
            dst_slot = sched_ref[r, dst_c]
            src_slot = sched_ref[r, src_c]
            dst_dev = sched_ref[R, meta_dst]
            if hw_sync and ch in statics["take_chs"]:
                @pl.when(sched_ref[r, take_c] == 1)
                def _capacity():
                    pltpu.semaphore_wait(free_pay[ch].at[dst_slot], 1)

            def _emit(sb):
                for bufs in bundle_bufs:
                    pltpu.make_async_remote_copy(
                        src_ref=bufs[sb].at[src_slot],
                        dst_ref=bufs[ch].at[dst_slot],
                        send_sem=psend[ch].at[dst_slot],
                        recv_sem=precv[ch].at[dst_slot],
                        device_id=dst_dev, device_id_type=LOGICAL).start()
                # no wait here: the transfers overlap this whole round's
                # sweep; the drain wait sits at the round's LAST grid step

            src_banks = statics["src_banks0"] if ch == 0 else (1,)
            if len(src_banks) == 1:
                _emit(src_banks[0])
            else:
                for sb in src_banks:
                    pl.when(sched_ref[r, sched_ir.SRC_BANK0] == sb)(
                        functools.partial(_emit, sb))

    # ---- per-(round, batch, kv-head) chunk load: HBM -> VMEM, plus the
    # fp32 dk/dv accumulator carry (outputs double as the between-round
    # staging, like the forward's acc scratch) ----
    @pl.when((i == 0) & (h % group == 0))
    def _chunk_load():
        kvh = h // group
        lk = pltpu.make_async_copy(k_hbm.at[b_, kvh], kchunk, chunk_sem.at[0])
        lv = pltpu.make_async_copy(v_hbm.at[b_, kvh], vchunk, chunk_sem.at[1])
        lk.start()
        lv.start()

        @pl.when(r > 0)
        def _carry_load():
            ldk = pltpu.make_async_copy(dk_ref.at[b_, kvh], dk_acc,
                                        kvio_sem.at[0])
            ldv = pltpu.make_async_copy(dv_ref.at[b_, kvh], dv_acc,
                                        kvio_sem.at[1])
            ldk.start()
            ldv.start()
            ldk.wait()
            ldv.wait()

        @pl.when(r == 0)
        def _carry_zero():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        lk.wait()
        lv.wait()

    # ---- per-(round, batch) segment-id row: gathered table -> VMEM ----
    if has_seg:
        @pl.when((i == 0) & (h == 0))
        def _seg_load():
            # the rotating bundle's partition (appended table column)
            # selects which shard's ids this round's q blocks carry
            part = sched_ref[r, sched_ir.BWD_COLS]
            cp = pltpu.make_async_copy(sega_hbm.at[b_, part], segbuf,
                                       seg_sem.at[0])
            cp.start()
            cp.wait()

    # ---- per-step bundle tile loads: slot HBM -> VMEM (started in the
    # consume bank's branch, awaited unconditionally so the arriving-dq
    # load below overlaps them) ----
    for b in statics["consume_banks"]:
        @pl.when(bank == b)
        def _tile_start(b=b):
            pltpu.make_async_copy(qbuf[b].at[slot, b_, h, i], q_t,
                                  tile_sem.at[0]).start()
            pltpu.make_async_copy(dobuf[b].at[slot, b_, h, i], do_t,
                                  tile_sem.at[1]).start()
            pltpu.make_async_copy(firstbuf[b].at[slot, b_, h, i], first_t,
                                  tile_sem.at[2]).start()
            pltpu.make_async_copy(lsebuf[b].at[slot, b_, h, i], lse_t,
                                  tile_sem.at[3]).start()
            if wire is not None:
                pltpu.make_async_copy(fscbuf[b].at[slot, b_, h], fsc_t,
                                      tile_sem.at[4]).start()
                pltpu.make_async_copy(doscbuf[b].at[slot, b_, h], dosc_t,
                                      tile_sem.at[5]).start()
                pltpu.make_async_copy(qscbuf[b].at[slot, b_, h], qsc_t,
                                      tile_sem.at[6]).start()

    # start the arriving-dq loads early: they are only needed at the merge,
    # after the whole local sweep
    @pl.when(sched_ref[r, sched_ir.DQ_RECV] == 1)
    def _dq_arr_start():
        def _s(b):
            pltpu.make_async_copy(dqbuf[b].at[dq_slot, b_, h, i], dq_arr,
                                  dqio_sem.at[0]).start()
            if wire is not None:
                pltpu.make_async_copy(dqscbuf[b].at[dq_slot, b_, h, i],
                                      dqsc_arr, dqio_sem.at[3]).start()

        dq_banked(_s)

    if has_dqi:
        @pl.when(sched_ref[r, sched_ir.DQI_RECV] == 1)
        def _dqi_arr_start():
            pltpu.make_async_copy(
                dqibuf.at[sched_ref[r, sched_ir.DQI_SLOT], b_, h, i],
                dqi_arr, dqio_sem.at[2]).start()
            if wire is not None:
                pltpu.make_async_copy(
                    dqiscbuf.at[sched_ref[r, sched_ir.DQI_SLOT], b_, h, i],
                    dqisc_arr, dqio_sem.at[5]).start()

    tiles = [q_t, do_t, first_t, lse_t]
    if wire is not None:
        tiles += [fsc_t, dosc_t, qsc_t]
    for j, tile in enumerate(tiles):
        dma_sem_wait(tile_sem.at[j], tile)

    # ---- local sweep over the resident chunk (no online softmax: p is
    # the true probability from the bundle's final lse) ----
    spec_r = tuple(sched_ref[r, c] for c in range(5))
    r0 = i * bq
    lse_col = _col_from_pack(lse_t[:], bq, lp)
    # fully-masked rows carry lse = -inf; BIG_LSE makes p underflow to 0
    # on the fast path without an elementwise select (pallas_flash idiom)
    lse_col = jnp.where(lse_col == NEG_INF, BIG_LSE, lse_col * LOG2E)
    if wire is None:
        q_raw = q_t[:]
        do_raw = do_t[:]
        first_f = first_t[:]
    else:
        # in-tile column rescale BEFORE any accumulation: the quantized
        # bundle dequantizes against its per-(batch, head) scale tiles
        q_raw = q_t[:].astype(jnp.float32) * qsc_t[0, 0]
        do_raw = do_t[:].astype(jnp.float32) * dosc_t[0, 0]
        first_f = first_t[:].astype(jnp.float32) * fsc_t[0, 0]
    if opt_comm:
        delta_col = _col_from_pack(first_f, bq, lp)
    else:
        delta_col = jnp.sum(
            first_f.astype(jnp.float32) * do_raw.astype(jnp.float32),
            axis=1, keepdims=True)
    q_sc = q_raw * (scale * LOG2E)
    dq_scr[:] = jnp.zeros_like(dq_scr)

    def _fold(c0, mask):
        ks = kchunk[pl.ds(c0, bkv), :]
        vs = vchunk[pl.ds(c0, bkv), :]
        s_t = jax.lax.dot_general(
            q_sc, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp is independent of the softmax: issue it before the VPU chain
        dp = jax.lax.dot_general(
            do_raw, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp2(s_t - lse_col)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        # trailing *scale of ds deferred to the dq merge / final dk store
        ds = p * (dp - delta_col)
        dv_acc[pl.ds(c0, bkv), :] = dv_acc[pl.ds(c0, bkv), :] + \
            jax.lax.dot_general(
                p.astype(do_raw.dtype), do_raw, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        dk_acc[pl.ds(c0, bkv), :] = dk_acc[pl.ds(c0, bkv), :] + \
            jax.lax.dot_general(
                ds.astype(q_raw.dtype), q_raw, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(ks.dtype), ks, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    segq = segbuf[pl.ds(r0, bq), :] if has_seg else None      # (bq, 1)
    for j in range(nkb):
        c0 = j * bkv
        live = _block_has_work(spec_r, r0, c0, bq, bkv, wnd)
        full = _block_full(spec_r, r0, c0, bq, bkv, wnd)
        if has_seg:
            segk = segkv_ref[0, :, pl.ds(c0, bkv)]            # (1, bkv)
            seg_pair = (segq, segk)
            # fast path also needs single-segment uniformity (see fwd)
            fast = full & _seg_uniform_eq(segq, segk)
        else:
            seg_pair = None
            fast = full

        @pl.when(live & fast)
        def _fast(c0=c0):
            _fold(c0, None)

        @pl.when(live & ~fast)
        def _masked(c0=c0, seg_pair=seg_pair):
            _fold(c0, _block_mask(spec_r, r0, c0, bq, bkv, wnd,
                                  seg=seg_pair))

    # ---- dq merge: arriving partial (one hop behind) + local contribution
    # (+ the held inter partial at double-ring boundaries), staged back into
    # the slot and streamed onward immediately ----
    @pl.when(sched_ref[r, sched_ir.DQ_RECV] == 1)
    def _dq_merge():
        dma_sem_wait(dqio_sem.at[0], dq_arr)
        if wire is None:
            arr = dq_arr[:]
        else:
            # rescale the arriving quantized partial with the scale that
            # rode its slot before folding into the fp32 accumulator
            dma_sem_wait(dqio_sem.at[3], dqsc_arr)
            arr = dq_arr[:].astype(jnp.float32) * dqsc_arr[0, 0]
        dq_scr[:] = arr + dq_scr[:] * scale

    @pl.when(sched_ref[r, sched_ir.DQ_RECV] == 0)
    def _dq_seed():
        # this direction's ring starts here: no arrival to merge
        dq_scr[:] = dq_scr[:] * scale

    if has_dqi:
        @pl.when(sched_ref[r, sched_ir.DQI_RECV] == 1)
        def _dqi_merge():
            dma_sem_wait(dqio_sem.at[2], dqi_arr)
            if wire is None:
                arr = dqi_arr[:]
            else:
                dma_sem_wait(dqio_sem.at[5], dqisc_arr)
                arr = dqi_arr[:].astype(jnp.float32) * dqisc_arr[0, 0]
            dq_scr[:] = dq_scr[:] + arr

    def _wb(b):
        if wire is None:
            wb = pltpu.make_async_copy(
                dq_scr, dqbuf[b].at[dq_slot, b_, h, i], dqio_sem.at[1])
            wb.start()
            wb.wait()
        else:
            # re-quantize the folded fp32 partial with its REFRESHED
            # per-block scale; payload and scale land in parallel slots
            qt, sc = _wire_quant_tile(dq_scr[:], wire)
            dq_q[:] = qt
            dqsc_w[:] = sc[None, None]
            wb1 = pltpu.make_async_copy(
                dq_q, dqbuf[b].at[dq_slot, b_, h, i], dqio_sem.at[1])
            wb2 = pltpu.make_async_copy(
                dqsc_w, dqscbuf[b].at[dq_slot, b_, h, i], dqio_sem.at[4])
            wb1.start()
            wb2.start()
            wb1.wait()
            wb2.wait()

    dq_banked(_wb)

    for b in dq_statics["ring_banks"]:
        @pl.when((dq_kind == sched_ir.DQ_RING) & (dq_bank_c == b))
        def _dq_send_ring(b=b):
            # the concurrent dq stream: this block's partial leaves NOW,
            # while later blocks of the same round are still computing —
            # it lands in the bank's direction neighbor before that
            # neighbor's next serving round needs it
            dst_slot = sched_ref[r, sched_ir.DQ_DST_SLOT]
            if hw_sync and b in dq_statics["take_banks"] \
                    and prog.topology != "double":
                @pl.when(first_of_round
                         & (sched_ref[r, sched_ir.DQ_TAKE0 if b == 0 else
                                      sched_ir.DQ_TAKE1] == 1))
                def _cap():
                    pltpu.semaphore_wait(free_dq[b].at[dst_slot], 1)
            pltpu.make_async_remote_copy(
                src_ref=dqbuf[b].at[dq_slot, b_, h, i],
                dst_ref=dqbuf[b].at[dst_slot, b_, h, i],
                send_sem=dqsend[b].at[dst_slot],
                recv_sem=dqrecv[b].at[dst_slot],
                device_id=sched_ref[R, _SENDC[b][4]],
                device_id_type=LOGICAL).start()
            if wire is not None:
                # the block's refreshed scale rides the same slot credits
                pltpu.make_async_remote_copy(
                    src_ref=dqscbuf[b].at[dq_slot, b_, h, i],
                    dst_ref=dqscbuf[b].at[dst_slot, b_, h, i],
                    send_sem=dqsend[b].at[dst_slot],
                    recv_sem=dqrecv[b].at[dst_slot],
                    device_id=sched_ref[R, _SENDC[b][4]],
                    device_id_type=LOGICAL).start()

    if hw_sync and prog.topology == "double" and 0 in \
            dq_statics["take_banks"]:
        # double ring: the intra dq stream's takes (DQ_TAKE0) ride the
        # ring-send rounds; emitted once at the round's first step
        @pl.when(first_of_round & (sched_ref[r, sched_ir.DQ_TAKE0] == 1))
        def _dq_cap_double():
            pltpu.semaphore_wait(
                free_dq[0].at[sched_ref[r, sched_ir.DQ_DST_SLOT]], 1)

    for b in home_banks:
        kinds = (sched_ir.DQ_HOME,) if prog.topology != "double" else \
            (sched_ir.DQ_FINAL,)

        @pl.when((dq_kind == kinds[0]) & (dq_bank_c == (b if prog.topology
                                                        != "double" else 0)))
        def _dq_send_home(b=b):
            # return-home hop: the completed partial lands in its OWNER's
            # dedicated home slot (index dq_slots[b], outside the ring
            # cycle) — one direct RDMA, `home_offsets[b]` positions away
            src_b = b if prog.topology != "double" else 0
            home_idx = prog.dq_slots[src_b]
            home_dev = sched_ref[R, sched_ir.META_HOME0 if b == 0
                                 else sched_ir.META_HOME1]
            pltpu.make_async_remote_copy(
                src_ref=dqbuf[src_b].at[dq_slot, b_, h, i],
                dst_ref=dqbuf[src_b].at[home_idx, b_, h, i],
                send_sem=home_sems[b].at[0], recv_sem=home_sems[b].at[1],
                device_id=home_dev, device_id_type=LOGICAL).start()
            if wire is not None:
                pltpu.make_async_remote_copy(
                    src_ref=dqscbuf[src_b].at[dq_slot, b_, h, i],
                    dst_ref=dqscbuf[src_b].at[home_idx, b_, h, i],
                    send_sem=home_sems[b].at[0],
                    recv_sem=home_sems[b].at[1],
                    device_id=home_dev, device_id_type=LOGICAL).start()

    if has_dqi:
        @pl.when(dq_kind == sched_ir.DQ_BOUNDARY)
        def _dq_send_boundary():
            # cycle boundary: the folded (inter_held + cycle partial) block
            # hops one inter step into the ping/pong accumulator bank
            dst_slot = sched_ref[r, sched_ir.DQI_DST_SLOT]
            if hw_sync and 1 in dq_statics["take_banks"]:
                @pl.when(first_of_round
                         & (sched_ref[r, sched_ir.DQ_TAKE1] == 1))
                def _cap():
                    pltpu.semaphore_wait(free_dqi.at[dst_slot], 1)
            pltpu.make_async_remote_copy(
                src_ref=dqbuf[0].at[dq_slot, b_, h, i],
                dst_ref=dqibuf.at[dst_slot, b_, h, i],
                send_sem=dqi_send.at[dst_slot],
                recv_sem=dqi_recv.at[dst_slot],
                device_id=sched_ref[R, sched_ir.META_CH1_DST],
                device_id_type=LOGICAL).start()
            if wire is not None:
                pltpu.make_async_remote_copy(
                    src_ref=dqscbuf[0].at[dq_slot, b_, h, i],
                    dst_ref=dqiscbuf.at[dst_slot, b_, h, i],
                    send_sem=dqi_send.at[dst_slot],
                    recv_sem=dqi_recv.at[dst_slot],
                    device_id=sched_ref[R, sched_ir.META_CH1_DST],
                    device_id_type=LOGICAL).start()

    # ---- dk/dv segment epilogue: stage the fp32 accumulators back to the
    # output buffers (final at the last round, with ds's deferred scale) ----
    @pl.when((i == nqb - 1) & (h % group == group - 1))
    def _kv_store():
        kvh = h // group

        @pl.when(r == R - 1)
        def _final_scale():
            dk_acc[:] = dk_acc[:] * scale

        sk = pltpu.make_async_copy(dk_acc, dk_ref.at[b_, kvh], kvio_sem.at[2])
        sv = pltpu.make_async_copy(dv_acc, dv_ref.at[b_, kvh], kvio_sem.at[3])
        sk.start()
        sv.start()
        sk.wait()
        sv.wait()

    # ---- round epilogue (last grid step of the round only) ----
    for ch in statics["ch_active"]:
        send_c, _, dst_c, _, _ = _SENDC[ch]

        @pl.when(last_of_round & (sched_ref[r, send_c] == 1))
        def _bundle_drain(ch=ch, dst_c=dst_c):
            dst_slot = sched_ref[r, dst_c]
            for bufs in bundle_bufs:
                dma_sem_wait(psend[ch].at[dst_slot], bufs[ch].at[dst_slot])

    for b in dq_statics["ring_banks"]:
        @pl.when(last_of_round & (dq_kind == sched_ir.DQ_RING)
                 & (dq_bank_c == b))
        def _dq_drain(b=b):
            ds_ = sched_ref[r, sched_ir.DQ_DST_SLOT]
            dma_sem_wait(dqsend[b].at[ds_], dqbuf[b].at[ds_])
            if wire is not None:
                dma_sem_wait(dqsend[b].at[ds_], dqscbuf[b].at[ds_])

    if has_dqi:
        @pl.when(last_of_round & (dq_kind == sched_ir.DQ_BOUNDARY))
        def _dqi_drain():
            ds_ = sched_ref[r, sched_ir.DQI_DST_SLOT]
            dma_sem_wait(dqi_send.at[ds_], dqibuf.at[ds_])
            if wire is not None:
                dma_sem_wait(dqi_send.at[ds_], dqiscbuf.at[ds_])

    for b in home_banks:
        send_round = dq_statics["home_rounds"][b]

        @pl.when(last_of_round & (r == send_round))
        def _home_drain(b=b):
            # our outgoing home blocks must be out the door before exit;
            # the n_steps block sends sum to one home-slot entry
            src_bank = b if prog.topology != "double" else 0
            home_idx = prog.dq_slots[src_bank]
            dma_sem_wait(home_sems[b].at[0], dqbuf[src_bank].at[home_idx])
            if wire is not None:
                dma_sem_wait(home_sems[b].at[0],
                             dqscbuf[src_bank].at[home_idx])

    @pl.when(last_of_round & (r == R - 1))
    def _home_epilogue():
        # wait every home bank's arrivals, then land each home slot in its
        # own dq output (multiple partials are summed OUTSIDE the kernel —
        # one jnp add against one extra output, instead of a block loop in
        # the final grid step; under wire the quantized partial and its
        # scales come out as separate outputs and dequantize in XLA)
        cps = []
        for j, b in enumerate(home_banks):
            src_bank = b if prog.topology != "double" else 0
            home_idx = prog.dq_slots[src_bank]
            dma_sem_wait(home_sems[b].at[1], dqbuf[src_bank].at[home_idx])
            cps.append(pltpu.make_async_copy(
                dqbuf[src_bank].at[home_idx], dq_refs[j],
                cp_sem.at[(2 if wire is not None else 1) * j]))
            if wire is not None:
                dma_sem_wait(home_sems[b].at[1],
                             dqscbuf[src_bank].at[home_idx])
                cps.append(pltpu.make_async_copy(
                    dqscbuf[src_bank].at[home_idx], dqsc_refs[j],
                    cp_sem.at[2 * j + 1]))
        for cp in cps:
            cp.start()
        for cp in cps:
            cp.wait()

    if hw_sync:
        for b in statics["grant_banks"]:
            grant_c, meta_src = _GRANTC[b]

            @pl.when(last_of_round & (sched_ref[r, grant_c] > 0))
            def _grant_pay(b=b, grant_c=grant_c, meta_src=meta_src):
                pltpu.semaphore_signal(
                    free_pay[b].at[sched_ref[r, grant_c] - 1], inc=1,
                    device_id=sched_ref[R, meta_src],
                    device_id_type=LOGICAL)

        for b in dq_statics["grant_banks"]:
            grant_c = sched_ir.DQ_GRANT0 if b == 0 else sched_ir.DQ_GRANT1
            is_dqi = has_dqi and b == 1

            @pl.when(last_of_round & (sched_ref[r, grant_c] > 0))
            def _grant_dq(b=b, grant_c=grant_c, is_dqi=is_dqi):
                # the dq bank's writer is its channel's upstream neighbor
                sem = free_dqi if is_dqi else free_dq[b]
                meta_src = _GRANTC[1 if is_dqi else b][1]
                pltpu.semaphore_signal(
                    sem.at[sched_ref[r, grant_c] - 1], inc=1,
                    device_id=sched_ref[R, meta_src],
                    device_id_type=LOGICAL)


# ---------------------------------------------------------------------------
# shard-level entry point


def fused_ring_bwd(cfg, q, k, v, o, lse, do, *, seg=None, interpret=None,
                   collect_stats=False):
    """Backward burst attention on per-shard arrays via the fused ring
    kernel — the drop-in twin of parallel/burst._bwd_impl's scan ring.

    Call inside shard_map on the ring axis: q/o/do [B, N, S, D], k/v
    [B, Nk, S, D], lse [B, N, S] f32 (the forward residuals in layout
    order), `seg` [B, S] optional packed-segment ids (gathered ring-wide
    once at entry; the ROTATING side here is the q bundle, so each
    round's q ids come off the side table and the kv ids stay local).
    Returns (dq, dk, dv) in float32 — the caller casts back to
    the input dtypes, exactly like the scan backward — plus the kernel's
    [n_banks, slots] int32 bundle slot-consume counters when
    `collect_stats` (the devstats bwd slot-reuse channel, one row per
    direction bank).  Callers must have checked
    `fused_ring.supported(..., pass_="bwd")` first.
    """
    from .fused_ring import hw_trace_forced, resolve_topology, _compile_for

    b, n, s, d = q.shape
    n_kv = k.shape[1]
    assert n % n_kv == 0, f"GQA needs Nq % Nk == 0, got {n} % {n_kv}"
    group = n // n_kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu" and not hw_trace_forced()
    scale = cfg.scale if cfg.scale is not None else d ** -0.5
    n_intra_ax = axis_size(cfg.intra_axis)
    n_inter_ax = (axis_size(cfg.inter_axis)
                  if cfg.inter_axis is not None else 1)
    topology, t_inter, t_intra = resolve_topology(cfg, n_intra_ax,
                                                  n_inter_ax)
    prog = _compile_for(cfg, topology, t_inter, t_intra, "bwd", s=s)
    statics = kernel_statics(prog)
    dq_statics = bwd_statics(prog)
    R = prog.n_rounds
    rf = resolve_fused(cfg.fused_block_q, cfg.fused_block_kv,
                       cfg.fused_kv_slots,
                       block_q_bwd=cfg.fused_block_q_bwd,
                       block_kv_bwd=cfg.fused_block_kv_bwd,
                       bwd_slots=cfg.fused_bwd_slots,
                       ccw_slots=getattr(cfg, "fused_ccw_slots", None),
                       bwd_ccw_slots=getattr(cfg, "fused_bwd_ccw_slots",
                                             None),
                       wire_dtype=getattr(cfg, "wire_dtype", None))
    wire = rf.wire_dtype
    bq = _pick_block(s, rf.block_q_bwd)
    bkv = _pick_block(s, rf.block_kv_bwd)
    lp = _pick_block(bq, 128)
    nqb = s // bq
    rows = bq // lp
    nkb = s // bkv

    # mask scalars with q/kv roles swapped: q side = rotating bundle
    # partition, kv side = resident local chunk
    sched, _specs = build_sched_table(cfg, prog, s, s, swap_roles=True,
                                      with_part=seg is not None)

    # bundle operands, pre-blocked so every slot/tile address is integer
    # indexing ([B, N, nqb, bq, D] is the same memory as [B, N, S, D]);
    # rank-3 stats ride in pallas_flash's packed [.., rows, lp] layout
    # wire mode quantizes the three rotating payloads ONCE at entry (the
    # bundle never changes as it circles, so quantize-at-entry is exactly
    # quantize-on-send); lse stays fp32
    if wire is not None:
        q_q, qsc = wire_quantize(q, wire, (2, 3))      # scales (b, n, 1, 1)
        do_q, dosc = wire_quantize(do, wire, (2, 3))
        q_in = q_q.reshape(b, n, nqb, bq, d)
        do_in = do_q.reshape(b, n, nqb, bq, d)
    else:
        q_in = q.reshape(b, n, nqb, bq, d)
        do_in = do.reshape(b, n, nqb, bq, d)
    lse_in = _pack(lse.astype(jnp.float32), lp).reshape(b, n, nqb, rows, lp)
    if cfg.optimize_bwd_comm:
        delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                        axis=-1)
        if wire is not None:
            delta, fsc = wire_quantize(delta, wire, (2,))  # (b, n, 1)
            fsc = fsc[..., None]
        first_in = _pack(delta, lp).reshape(b, n, nqb, rows, lp)
        first_slot_shape = (b, n, nqb, rows, lp)
        first_tile_shape = (rows, lp)
        first_dtype = delta.dtype
    else:
        # ring payload grows by a factor of head_dim; delta is recomputed
        # per tile from the rotated (o, do) pair (reference parity)
        first_in = o
        if wire is not None:
            first_in, fsc = wire_quantize(o, wire, (2, 3))
        first_in = first_in.reshape(b, n, nqb, bq, d)
        first_slot_shape = (b, n, nqb, bq, d)
        first_tile_shape = (bq, d)
        first_dtype = first_in.dtype

    kernel = functools.partial(
        _fused_bwd_kernel, prog=prog, statics=statics,
        dq_statics=dq_statics, scale=scale, bq=bq, bkv=bkv, lp=lp, nqb=nqb,
        nkb=nkb, group=group, n_b=b, n_h=n, hw_sync=not interpret,
        collect=collect_stats, opt_comm=cfg.optimize_bwd_comm,
        wnd=cfg.window, has_seg=seg is not None, wire=wire,
    )

    home_banks = sorted(dq_statics["home_rounds"])
    dq_ring_banks = prog.n_dq_banks if topology != "double" else 1
    has_dqi = dq_statics["has_dqi"]

    dq_out_dtype = jnp.float32 if wire is None else jnp.dtype(
        jnp.int8 if wire == "int8" else jnp.float8_e4m3fn)
    out_specs = [pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
                 for _ in home_banks]                      # dq partial(s)
    out_shape = [jax.ShapeDtypeStruct((b, n, nqb, bq, d), dq_out_dtype)
                 for _ in home_banks]
    if wire is not None:
        # the arriving quantized partials' per-block scales, dequantized
        # against their payload outputs by XLA just below
        out_specs += [pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
                      for _ in home_banks]
        out_shape += [jax.ShapeDtypeStruct((b, n, nqb, 1, 1), jnp.float32)
                      for _ in home_banks]
    out_specs += [
        pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),  # dk
        pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),  # dv
    ]
    out_shape += [
        jax.ShapeDtypeStruct((b, n_kv, s, d), jnp.float32),
        jax.ShapeDtypeStruct((b, n_kv, s, d), jnp.float32),
    ]
    if collect_stats:
        out_specs.append(
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.SMEM))
        out_shape.append(jax.ShapeDtypeStruct(
            (prog.n_banks, max(prog.slots)), jnp.int32))

    dq_ring_dtype = jnp.float32 if wire is None else dq_out_dtype
    scratch = []
    for bank in range(prog.n_banks):
        sl = prog.slots[bank]
        scratch += [
            pltpu.ANY((sl,) + first_slot_shape, first_dtype),   # firstbuf
            pltpu.ANY((sl, b, n, nqb, bq, d), do_in.dtype),     # dobuf
            pltpu.ANY((sl, b, n, nqb, bq, d), q_in.dtype),      # qbuf
            pltpu.ANY((sl, b, n, nqb, rows, lp), jnp.float32),  # lsebuf
        ]
    if wire is not None:
        for bank in range(prog.n_banks):
            sl = prog.slots[bank]
            scratch += [
                pltpu.ANY((sl, b, n, 1, 1), jnp.float32),   # fscbuf
                pltpu.ANY((sl, b, n, 1, 1), jnp.float32),   # doscbuf
                pltpu.ANY((sl, b, n, 1, 1), jnp.float32),   # qscbuf
            ]
    dq_bank_slots = []
    for bank in range(dq_ring_banks):
        # ring slots + (when this bank receives a home stream) the
        # dedicated return-home slot just past them
        extra = 1 if bank in home_banks or topology == "double" else 0
        dq_bank_slots.append(prog.dq_slots[bank] + extra)
        scratch.append(pltpu.ANY(
            (prog.dq_slots[bank] + extra, b, n, nqb, bq, d), dq_ring_dtype))
    if wire is not None:
        for sl in dq_bank_slots:
            scratch.append(pltpu.ANY((sl, b, n, nqb, 1, 1),
                                     jnp.float32))          # dqscbuf
    if has_dqi:
        scratch.append(pltpu.ANY((prog.dq_slots[1], b, n, nqb, bq, d),
                                 dq_ring_dtype))            # dqibuf
        if wire is not None:
            scratch.append(pltpu.ANY((prog.dq_slots[1], b, n, nqb, 1, 1),
                                     jnp.float32))          # dqiscbuf
    scratch += [
        pltpu.VMEM((s, d), k.dtype),                  # kchunk
        pltpu.VMEM((s, d), v.dtype),                  # vchunk
        pltpu.VMEM((s, d), jnp.float32),              # dk_acc
        pltpu.VMEM((s, d), jnp.float32),              # dv_acc
        pltpu.VMEM((bq, d), q_in.dtype),              # q_t
        pltpu.VMEM((bq, d), do_in.dtype),             # do_t
        pltpu.VMEM(first_tile_shape, first_dtype),    # first_t
        pltpu.VMEM((rows, lp), jnp.float32),          # lse_t
        pltpu.VMEM((bq, d), dq_ring_dtype),           # dq_arr
        pltpu.VMEM((bq, d), dq_ring_dtype),           # dqi_arr
        pltpu.VMEM((bq, d), jnp.float32),             # dq_scr
        pltpu.SemaphoreType.DMA((max(
            (7 if wire is not None else 4) * len(prog.copy_in),
            (2 if wire is not None else 1) * len(home_banks)),)),  # cp_sem
        pltpu.SemaphoreType.DMA((2,)),                # chunk_sem
        pltpu.SemaphoreType.DMA((4,)),                # kvio_sem
        pltpu.SemaphoreType.DMA((7 if wire is not None else 4,)),  # tile_sem
        pltpu.SemaphoreType.DMA((6 if wire is not None else 3,)),  # dqio_sem
    ]
    if wire is not None:
        scratch += [
            pltpu.VMEM((1, 1), jnp.float32),          # fsc_t
            pltpu.VMEM((1, 1), jnp.float32),          # dosc_t
            pltpu.VMEM((1, 1), jnp.float32),          # qsc_t
            pltpu.VMEM((1, 1), jnp.float32),          # dqsc_arr
            pltpu.VMEM((1, 1), jnp.float32),          # dqisc_arr
            pltpu.VMEM((bq, d), dq_ring_dtype),       # dq_q
            pltpu.VMEM((1, 1), jnp.float32),          # dqsc_w
        ]
    for bank in range(prog.n_banks):
        sl = prog.slots[bank]
        scratch += [
            pltpu.SemaphoreType.DMA((sl,)),           # psend[bank]
            pltpu.SemaphoreType.DMA((sl,)),           # precv[bank]
            pltpu.SemaphoreType.REGULAR((sl,)),       # free_pay[bank]
        ]
    for bank in range(dq_ring_banks):
        sl = prog.dq_slots[bank]
        scratch += [
            pltpu.SemaphoreType.DMA((sl,)),           # dqsend[bank]
            pltpu.SemaphoreType.DMA((sl,)),           # dqrecv[bank]
            pltpu.SemaphoreType.REGULAR((sl,)),       # free_dq[bank]
        ]
    if has_dqi:
        scratch += [
            pltpu.SemaphoreType.DMA((prog.dq_slots[1],)),   # dqi_send
            pltpu.SemaphoreType.DMA((prog.dq_slots[1],)),   # dqi_recv
            pltpu.SemaphoreType.REGULAR((prog.dq_slots[1],)),  # free_dqi
        ]
    for _ in home_banks:
        scratch.append(pltpu.SemaphoreType.DMA((2,)))  # home_sems[b]

    in_specs = [pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)] * 6
    inputs = [sched, first_in, do_in, q_in, lse_in, k, v]
    if wire is not None:
        # per-(batch, head) bundle scales: popped by the kernel right
        # after the six dense operands, ahead of any segment inputs
        in_specs += [pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)] * 3
        inputs += [fsc, dosc, qsc]
    if seg is not None:
        # local KV-side ids resident per batch; the gathered table (q-side
        # orientation: [B, world, S, 1]) stays in ANY space
        in_specs.append(pl.BlockSpec((1, 1, s),
                                     lambda r, b_, h, i, sp: (b_, 0, 0)))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY))
        inputs.append(seg.astype(jnp.int32)[:, None, :])
        inputs.append(jnp.swapaxes(gather_seg_table(seg, cfg), 2, 3))
        scratch += [
            pltpu.VMEM((s, 1), jnp.int32),       # segbuf
            pltpu.SemaphoreType.DMA((1,)),       # seg_sem
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(R, b, n, nqb),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # sequential by construction: the ring choreography, the VMEM
        # dk/dv accumulators and the dq streams all assume one core walks
        # the grid in order — a megacore split would race them
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("arbitrary",) * 4,
            collective_id=_COLLECTIVE_ID,
        ),
        interpret=interpret,
    )(*inputs)
    # a bidi owner receives its gradient as two complementary directional
    # partials; the sum is one fused XLA add — everything else already
    # happened in-kernel.  Wire mode lands the partials quantized with
    # their per-block scales in trailing outputs: dequantize (one rescale
    # per home bank), THEN sum — the accumulators inside the kernel were
    # fp32 throughout, only the return-home hop crossed the wire narrow.
    nh = len(home_banks)
    if wire is None:
        dq = outs[0]
        for j in range(1, nh):
            dq = dq + outs[j]
        n_out = nh
    else:
        dq = outs[0].astype(jnp.float32) * outs[nh]
        for j in range(1, nh):
            dq = dq + outs[j].astype(jnp.float32) * outs[nh + j]
        n_out = 2 * nh
    dq = dq.reshape(b, n, s, d)
    dk, dv = outs[n_out], outs[n_out + 1]
    if not collect_stats:
        return dq, dk, dv
    return dq, dk, dv, outs[n_out + 2]
