"""Pallas TPU flash-attention kernel family with ring carry-in state.

The native-kernel layer of the framework: the TPU equivalent of the
reference's two GPU kernel backends — the flash-attn v2 CUDA kernels
(reference burst_attn/burst_utils.py:149-248) and the carry-in Triton kernel
(reference burst_attn/lao.py:67-213, whose forward ACCEPTS previous
(m, lse, acc_o) so the online softmax continues across ring rounds).

Design (see SURVEY.md §2.3, §7):

  * `flash_fwd` — one ring round.  Takes carry state (m, lse, acc) and folds
    in one KV block's contribution, exactly like lao.py's `_fwd_kernel`
    carry-in args (M_in, Lse_in, O_in; lao.py:107-114) but expressed as a
    Pallas grid over (batch, head, q-block, kv-block) with the running
    (m, l, acc) held in VMEM scratch across the innermost kv iterations.
  * `flash_bwd` — one backward ring round, split into a dq kernel and a
    dk/dv kernel (both deterministic — no atomics, unlike lao.py's
    atomic-add dq path, lao.py:473-482).  Takes the precomputed
    delta = sum(o*do) (the reference's optimize_bwd_comm quantity /
    flash-attn softmax_d input, burst_utils.py:195-229) and the FINAL lse.
  * Every ring round is the SAME compiled kernel, parameterized by the five
    runtime MaskSpec scalars (ops/masks.py) delivered via scalar prefetch;
    index maps clamp the kv-block index so fully-masked blocks are neither
    fetched nor computed (the TPU analogue of the reference's 3-way causal
    case split, burst_attn_interface.py:221-235).

State layout.  The per-row softmax stats (m, lse, delta) are logically
[B, N, S] float32.  Mosaic requires the last two block dims to be
(8k, 128k)-aligned or equal to the array dims, and the lane-replicated
[B, N, S, 128] layout used by stock kernels inflates HBM 128x — untenable at
ring scale (B·N·S grows to millions of rows).  We instead reshape to
[B, N, S/LP, LP] (LP = 128 when possible; a free, layout-preserving reshape)
and give each (batch, head) program the whole head's stats as one block
(block dims == array dims, always legal).  In-kernel, rows for one q-block
are unpacked (LP lanes -> bq sublanes) with an exact repeat+select, and
packed back with a native lane-reducing reshape.
"""

import functools
import logging
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .masks import MaskSpec

logger = logging.getLogger("burst_attn_tpu")
# re-exported here for kernel users; defined in ops/tuning.py so jnp-only
# paths (burst.py's backend fallback) can resolve blocks without importing
# this module
from .tuning import resolve_blocks  # noqa: F401
from ..utils.compat import tpu_compiler_params

NEG_INF = float("-inf")
# stand-in for -inf lse rows in the backward kernels: exp(s - BIG_LSE)
# underflows to exactly 0 for any finite score s
BIG_LSE = 1e30
# The kernels run the online softmax in base 2: log2(e) is folded into the
# q-block scaling so every transcendental is a bare exp2 (exp(x) lowers to
# exp2(x*log2e) + a mul on the VPU; the kernels are VPU-bound so the dropped
# [bq, bkv] multiplies are measurable).  All kernel INTERFACES stay in the
# natural-log domain (m, lse), converted on the [bq, 1] columns at init/finish.
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453
# Mosaic's default scoped-VMEM budget is 16 MiB; v5e has far more physical
# VMEM and the larger budget admits 2048-wide kv blocks.  BURST_VMEM_LIMIT
# (bytes, read at import) exists for cliff experiments: the limit bounds how
# aggressively Mosaic double-buffers, so it interacts with the block-area
# cliff law in ops/tuning.py.
VMEM_LIMIT = int(os.environ.get("BURST_VMEM_LIMIT", 100 * 1024 * 1024))


def _interpret_default():
    return jax.default_backend() != "tpu"


def _tri_disabled():
    """BURST_NO_TRI=1 turns the wrapped-diagonal causal grids off globally
    (escape hatch: the rectangular grids are the longer-validated path).
    Checked at trace time; "", "0", and "false" mean off (triangular on)."""
    return os.environ.get("BURST_NO_TRI", "").strip().lower() not in ("", "0", "false")


def _tri_bwd_disabled():
    """Backward-scoped triangular disable: BURST_NO_TRI turns off every
    wrapped-diagonal grid; BURST_NO_TRI_BWD turns off only flash_bwd's.
    probe_tri_bwd sets the latter on a backward compile failure — the
    forward tri/band grids have an independent (much smaller) VMEM
    footprint, and demoting them for a bwd-only Mosaic rejection would be
    an unrelated performance regression (round-4 advisor finding)."""
    return _tri_disabled() or os.environ.get(
        "BURST_NO_TRI_BWD", "").strip().lower() not in ("", "0", "false")


def _fwd_loop_default():
    """BURST_FWD_LOOP=1 makes flash_fwd's fori_loop sub-block sweep
    (`loop_sweep`) the default.  Exists so the cliff-break experiment
    (sweep_blocks --fwd-loop; docs §3) can be PROMOTED for a bench run
    without a code edit mid-tunnel-window — if the loop sweep legalizes
    4096-wide kv blocks, rerun `BURST_FWD_LOOP=1 BURST_ALLOW_CLIFF=1
    python bench.py` with retuned blocks before changing defaults."""
    return os.environ.get("BURST_FWD_LOOP", "").strip().lower() not in ("", "0", "false")


def _bwd_loop_default():
    """BURST_BWD_LOOP=1 makes the tri backward's fori_loop sub-block sweep
    the default — same promotion mechanism as BURST_FWD_LOOP (see
    _fwd_loop_default): if the loop body's buffer reuse moves the bwd VMEM
    cliff (sweep_blocks --bwd ...xtrix1024 with it set), rerun bench with
    retuned bwd blocks before changing ops/tuning.py defaults."""
    return os.environ.get("BURST_BWD_LOOP", "").strip().lower() not in ("", "0", "false")


def _pick_block(seq: int, block: int) -> int:
    """Largest block <= `block` that divides seq (seq lengths are powers of
    two in practice, so this is normally min(block, seq))."""
    block = min(block, seq)
    while seq % block:
        block -= 1
    return block


# Ragged sequence support: flash_fwd/flash_bwd pad awkward sequence lengths
# up to a multiple of the packed-stats lane width, so _pick_block always has
# a >= 128-ish divisor to work with instead of silently degrading to a
# near-1 block (and a catastrophic grid) on prime/odd lengths.  The pad
# region is masked out for free: MaskSpec row/col bounds stay in true
# coordinates, so padded rows/cols fail `rows < q_hi` / `cols < kv_hi` in
# every kernel mask, and callers slice the outputs back.
_SEQ_ALIGN = 128


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def _padded_len(s: int, block: int) -> int:
    """Sequence length the kernel should actually run at: `s` itself when the
    requested block tiles it exactly, or when it fits one small (sub-align)
    block; otherwise the next 128-aligned length, with the pad masked out.
    (A 128-aligned s is its own ceiling, so good-divisor cases like
    s=2176/block=2048 fall through unchanged.)"""
    if s % block == 0 or (block >= s and s <= _SEQ_ALIGN):
        return s
    return _ceil_to(s, _SEQ_ALIGN)


def _pad_seq(x, s_pad: int, fill=0.0):
    """Pad dim 2 (sequence) of [B, N, S, ...] up to s_pad with `fill`."""
    s = x.shape[2]
    if s == s_pad:
        return x
    pad = [(0, 0)] * x.ndim
    pad[2] = (0, s_pad - s)
    return jnp.pad(x, pad, constant_values=fill)


def _pad_seg(seg, s_pad: int, fill):
    """Pad dim 1 (sequence) of a [B, S] segment-id array with `fill`.

    The sentinels (-1 q-side, -2 kv-side) keep the two pads from matching
    each other; real-vs-pad pairs are already dead structurally — the spec's
    q_hi/kv_hi bounds stay in TRUE coordinates, so any block touching pad
    rows/cols takes the masked path and the bounds test kills those pairs
    regardless of ids.  Callers should still use non-negative segment ids
    (negatives are reserved for padding; see flash_attention docstring)."""
    s = seg.shape[1]
    if s == s_pad:
        return seg
    return jnp.pad(seg, [(0, 0), (0, s_pad - s)], constant_values=fill)


def _spec_array(spec: MaskSpec):
    return jnp.stack(
        [
            jnp.asarray(spec.q_lo, jnp.int32),
            jnp.asarray(spec.q_hi, jnp.int32),
            jnp.asarray(spec.kv_hi, jnp.int32),
            jnp.asarray(spec.causal, jnp.int32),
            jnp.asarray(spec.offset, jnp.int32),
        ]
    )


def _block_mask(spec_ref, r0, c0, bq, bkv, wnd=None, seg=None):
    """[bq, bkv] bool mask for the tile at rows r0.., cols c0.. (True=attend).

    `wnd` is the STATIC sliding-window width (None = unlimited); when None
    the generated code is identical to the pre-window kernels — windowed
    runs are the only ones that pay for the extra band term.  `seg` =
    (q_seg [bq, 1], kv_seg [1, bkv]) packed-sequence id tiles: attention
    never crosses a segment boundary (the broadcast compare is the only
    cost, and only on blocks that take the masked path)."""
    rows = r0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
    cols = c0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
    q_lo, q_hi, kv_hi = spec_ref[0], spec_ref[1], spec_ref[2]
    causal, offset = spec_ref[3], spec_ref[4]
    m = (rows >= q_lo) & (rows < q_hi) & (cols < kv_hi)
    m = m & ((causal == 0) | (cols <= rows + offset))
    if wnd is not None:
        m = m & (cols > rows + offset - wnd)
    if seg is not None:
        m = m & (seg[0] == seg[1])
    return m


def _seg_uniform_eq(qs, ks):
    """Scalar: True iff both segment tiles are single-segment AND equal —
    the condition under which a structurally-full block needs no segment
    masking (the fast path stays fast on the unpacked interior)."""
    return ((jnp.max(qs) == jnp.min(qs)) & (jnp.max(ks) == jnp.min(ks))
            & (jnp.max(qs) == jnp.max(ks)))


def _block_has_work(spec_ref, r0, c0, bq, bkv, wnd=None):
    q_lo, q_hi, kv_hi = spec_ref[0], spec_ref[1], spec_ref[2]
    causal, offset = spec_ref[3], spec_ref[4]
    ok = (r0 < q_hi) & (r0 + bq > q_lo) & (c0 < kv_hi)
    ok = ok & ((causal == 0) | (c0 <= r0 + bq - 1 + offset))
    if wnd is not None:
        # union of the rows' visible bands is [r0+offset-wnd+1, ...): a
        # block wholly left of it is dead
        ok = ok & (c0 + bkv - 1 > r0 + offset - wnd)
    return ok


def _block_full(spec_ref, r0, c0, bq, bkv, wnd=None):
    """True iff every (row, col) of the tile is visible — the fast path can
    skip mask construction and the elementwise selects entirely.  On a causal
    64-block grid ~97% of live blocks are interior, and the kernels are
    VPU-bound, so this matters more than any matmul tuning."""
    q_lo, q_hi, kv_hi = spec_ref[0], spec_ref[1], spec_ref[2]
    causal, offset = spec_ref[3], spec_ref[4]
    ok = (r0 >= q_lo) & (r0 + bq <= q_hi) & (c0 + bkv <= kv_hi)
    ok = ok & ((causal == 0) | (c0 + bkv - 1 <= r0 + offset))
    if wnd is not None:
        # intersection of the rows' bands starts at r0+bq-1+offset-wnd+1
        ok = ok & (c0 > r0 + bq - 1 + offset - wnd)
    return ok


def _kv_jmax(spec_ref, i, bq, bkv, n_kv_blocks):
    """Last useful kv-block index for q-block i (for DMA index clamping)."""
    kv_hi, causal, offset = spec_ref[2], spec_ref[3], spec_ref[4]
    hi = jnp.where(causal > 0, jnp.minimum(kv_hi, i * bq + bq + offset), kv_hi)
    return jnp.clip((hi + bkv - 1) // bkv - 1, 0, n_kv_blocks - 1)


def _q_imin(spec_ref, j, bq, bkv, n_q_blocks):
    """First useful q-block index for kv-block j (bwd dk/dv clamping)."""
    q_lo, causal, offset = spec_ref[0], spec_ref[3], spec_ref[4]
    lo = jnp.where(causal > 0, jnp.maximum(q_lo, j * bkv - offset), q_lo)
    return jnp.clip(lo // bq, 0, n_q_blocks - 1)


def _kv_jmin(spec_ref, i, bq, bkv, n_kv_blocks, wnd):
    """First useful kv-block index for q-block i under a sliding window
    (DMA clamping: left-of-band blocks are dead, and clamping their fetch
    index to the first live block makes them free — same trick as _kv_jmax
    on the causal side).  Row i*bq's band starts at r0 + offset - wnd + 1."""
    offset = spec_ref[4]
    lo = i * bq + offset - wnd + 1
    return jnp.clip(lo // bkv, 0, n_kv_blocks - 1)


def _q_imax(spec_ref, j, bq, bkv, n_q_blocks, wnd):
    """Last useful q-block index for kv-block j under a sliding window:
    rows beyond c0 + bkv - 1 + wnd - 1 - offset have their whole band left
    of this kv block."""
    offset = spec_ref[4]
    hi = j * bkv + bkv - 1 + wnd - 1 - offset
    return jnp.clip(hi // bq, 0, n_q_blocks - 1)


# ---------------------------------------------------------------------------
# packed-stats helpers (see "State layout" in the module docstring)


def _read_rows(state_ref, i, bq, lp):
    """Rows [i*bq, (i+1)*bq) of a packed [1, 1, S/lp, lp] stats ref -> (bq, 1)."""
    rows = bq // lp
    pack = state_ref[0, 0, pl.ds(i * rows, rows), :]
    if lp == 1:
        return pack
    rep = jnp.repeat(pack, bq // rows, axis=0)  # (bq, lp); row t = pack[t//lp]
    t_lane = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 0) % lp
    c_idx = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 1)
    return jnp.sum(jnp.where(t_lane == c_idx, rep, 0.0), axis=1, keepdims=True)


def _write_rows(state_ref, i, col, bq, lp):
    """Inverse of _read_rows: store (bq, 1) into rows of the packed ref."""
    rows = bq // lp
    state_ref[0, 0, pl.ds(i * rows, rows), :] = jnp.reshape(col, (rows, lp))


def _pack(x, lp):
    """[B, N, S] -> [B, N, S/lp, lp] (free, layout-preserving reshape)."""
    b, n, s = x.shape
    return x.reshape(b, n, s // lp, lp)


def _gqa_group(n: int, n_kv: int) -> int:
    assert n % n_kv == 0, f"GQA needs Nq % Nk == 0, got {n} % {n_kv}"
    return n // n_kv


def _make_index_maps(bq, bkv, nqb, nkb, group, wnd=None):
    """Shared fwd/bwd(dq) index maps over the (batch, head, q-block, kv-block)
    grid; kv fetches are clamped to the [first, last] useful block so
    fully-masked blocks are never DMA'd (the lower clamp only exists under a
    sliding window — without one, block 0 is always live causally)."""

    def q_map(b_, h, i, j, sp):
        return (b_, h, i, 0)

    def kv_map(b_, h, i, j, sp):
        j_eff = jnp.minimum(j, _kv_jmax(sp, i, bq, bkv, nkb))
        if wnd is not None:
            j_eff = jnp.maximum(j_eff, _kv_jmin(sp, i, bq, bkv, nkb, wnd))
        return (b_, h // group, j_eff, 0)

    def state_map(b_, h, i, j, sp):
        return (b_, h, 0, 0)

    return q_map, kv_map, state_map


def _unpack(x):
    b, n, r, lp = x.shape
    return x.reshape(b, n, r * lp)


# ---------------------------------------------------------------------------
# forward


def _lcm(a, b):
    import math

    return a * b // math.gcd(a, b)


def fwd_band_nb(bq, bkv, window):
    """Exact max kv-block count a q-row-block's sliding-window band can
    intersect, over the alignments the band contract can produce
    (r0 = i*bq, offset in {0, -1} — and, shift-invariantly, any offset
    ≡ 0 (mod bkv): the windowed contig ring's live rounds pass offset
    r*s with bkv | s, which lands on the off=0 alignment class; keep the
    enumeration in residues, never absolute offsets).  A closed-form upper bound
    ((bq+window-2)//bkv + 2) overcounts by one at every aligned config —
    e.g. window=4K, bq=bkv=2048 intersects at most 3 blocks, not 4 — and a
    permanently-dead extra grid step per row is exactly the overhead the
    band grid exists to remove."""
    best = 0
    for r0 in range(0, _lcm(bq, bkv), bq):  # residues cycle at lcm
        for off in (0, -1):
            jmin = (r0 + off - window + 1) // bkv
            jmax = (r0 + bq - 1 + off) // bkv
            best = max(best, jmax - jmin + 1)
    return best


def bwd_band_nb(bq, bkv, window):
    """Exact max q-block count whose band can reach a kv block (the fused
    bwd sweep length), over reachable alignments c0 = j*bkv, offset 0/-1.
    Mirror of fwd_band_nb with the roles swapped (_q_imin/_q_imax)."""
    best = 0
    for c0 in range(0, _lcm(bq, bkv), bkv):
        for off in (0, -1):
            imin = (c0 - off) // bq          # first causal q row's block
            imax = (c0 + bkv - 1 + window - 1 - off) // bq
            best = max(best, imax - imin + 1)
    return best


def _tri_coords(nqb, r):
    """Wrapped-diagonal coordinates for the static-causal triangular grid,
    generalized to TALL q blocks: block_q = r * block_kv.

    Grid dims (b, h, p, j') with p in [0, nqb/2), j' in [0, (nqb+1)*r):
    row pair p covers q-block p (kv-blocks 0..(p+1)*r-1, segment A =
    j' < (p+1)*r) then q-block nqb-1-p (kv-blocks 0..(nqb-p)*r-1,
    segment B) — (p+1)*r + (nqb-p)*r = (nqb+1)*r steps, ALL live.  The
    rectangular grid spends ~half its steps on clamped/dead causal blocks
    (~1.9us each of pure grid overhead on v5e at seq=64K, where causal fwd
    measured 150 TFLOPs/s vs 172 non-causal — the all-live grid closes
    most of that gap; measured values in README.md's performance section
    and sweep_blocks output).

    Why tall blocks: at fixed block AREA (the measured VMEM cliff bound,
    docs/design.md §3) the kernel's K/V streaming traffic scales as
    1/block_q — each kv block fetched serves more query rows — while the
    grid STEP COUNT, the diagonal's masked fraction (2/(nqb+1)), and the
    pipeline's scoped-VMEM demand are all r-invariant.  At seq=64K the
    2048x2048 forward moves ~16.9 GB of K/V (HBM-bound at ~819 GB/s);
    4096x1024 moves half that for the same step count.

    Per segment the last r steps overlap the diagonal and take the masked
    path (the `masked` return); every earlier step is statically full
    under offset 0/-1.  Requires block_q % block_kv == 0 and an even
    q-block count."""
    p_ = pl.program_id(2)
    j_ = pl.program_id(3)
    lena = (p_ + 1) * r
    segb = j_ >= lena
    i = jnp.where(segb, nqb - 1 - p_, p_)
    jrel = jnp.where(segb, j_ - lena, j_)
    seg_len = jnp.where(segb, (nqb - p_) * r, lena)
    is_init = (j_ == 0) | (j_ == lena)
    is_fin = (j_ == lena - 1) | (j_ == (nqb + 1) * r - 1)
    masked = jrel >= seg_len - r
    return i, jrel, is_init, is_fin, masked


def _fwd_kernel(
    spec_ref,
    q_ref, k_ref, v_ref,
    *rest,
    scale, bq, bkv, bkv_compute, lp, n_kv_blocks, cast_p, tri, wnd=None,
    seg=False, emit_o=False, loop=False, ablate=None, band_nb=None,
    carry=True, tri_r=1,
):
    if carry:
        m_in_ref, lse_in_ref, acc_in_ref = rest[:3]
        rest = rest[3:]
    if seg:
        qseg_ref, kvseg_ref = rest[0], rest[1]
        rest = rest[2:]
    m_out_ref, lse_out_ref, acc_out_ref, m_scr, l_scr, acc_scr = rest
    if tri:
        nqb = n_kv_blocks // tri_r  # s_q == s_kv; bq == tri_r * bkv
        i, j, is_init, is_fin, tri_masked = _tri_coords(nqb, tri_r)
    elif band_nb is not None:
        # band grid (see flash_fwd): dim 3 walks only the <=band_nb kv
        # blocks that can intersect q-block i's sliding-window band, instead
        # of all n_kv_blocks — the all-live-steps idea of the tri grid
        # applied to the window structure
        i = pl.program_id(2)
        c = pl.program_id(3)
        j = _kv_jmin(spec_ref, i, bq, bkv, n_kv_blocks, wnd) + c
        is_init = c == 0
        is_fin = c == band_nb - 1
    else:
        i = pl.program_id(2)
        j = pl.program_id(3)
        is_init = j == 0
        is_fin = j == n_kv_blocks - 1
    r0 = i * bq
    c0 = j * bkv

    @pl.when(is_init)
    def _init():
        if carry:
            m0 = _read_rows(m_in_ref, i, bq, lp)
            lse0 = _read_rows(lse_in_ref, i, bq, lp)
            # scratch m is kept in the base-2 scaled domain (see LOG2E note)
            m_scr[:] = m0 * LOG2E
            # linear-scale running sum relative to m: l = exp(lse - m);
            # 0 if empty
            l_scr[:] = jnp.where(m0 == NEG_INF, 0.0, jnp.exp(lse0 - m0))
            acc_scr[:] = acc_in_ref[0, 0, :, :]
        else:
            # statically-empty carry (single-device / first ring round):
            # the empty state is a constant, so the [bq, d] f32 acc-in DMA
            # per row visit — and XLA's materialization of the whole
            # [B, N, S, D] zeros input — never happen (measured-relevant:
            # that is ~2 GB of dead HBM traffic per 64K-seq forward)
            m_scr[:] = jnp.full_like(m_scr, NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

    if tri:
        # every tri step is live; only the r diagonal-overlap blocks at each
        # segment's end are partially masked (r = 1: exactly the final step)
        fast_cond = ~tri_masked
        masked_cond = tri_masked
    else:
        live = _block_has_work(spec_ref, r0, c0, bq, bkv, wnd) & (
            j <= _kv_jmax(spec_ref, i, bq, bkv, n_kv_blocks)
        )
        full = _block_full(spec_ref, r0, c0, bq, bkv, wnd)
        fast_cond = live & full
        masked_cond = live & ~full
    if seg:
        # packed sequences: only blocks wholly inside ONE shared segment may
        # skip masking; mixed blocks join the masked path (cheap scalar test)
        qs_tile = qseg_ref[0, :, :]   # [bq, 1]
        ks_tile = kvseg_ref[0, :, :]  # [1, bkv]
        seg_ok = _seg_uniform_eq(qs_tile, ks_tile)
        was_live = fast_cond | masked_cond
        fast_cond = fast_cond & seg_ok
        masked_cond = was_live & ~fast_cond

    # scale (and the base-2 conversion) folded into the [bq, d] q block
    # (one small mul, hoisted out of the sub-block loop) instead of the
    # [bq, bkv] score matrix — the kernel is VPU-bound, not MXU-bound
    q = q_ref[0, 0, :, :] * (scale * LOG2E)

    def _score(u):
        cs = pl.ds(u * bkv_compute, bkv_compute)
        return jax.lax.dot_general(
            q, k_ref[0, 0, cs, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def _softmax(s, mask, m_prev, l_prev):
        """VPU half of one sub-block fold: returns (m_new, l_new, alpha, p)."""
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.where(m_prev >= m_new, 1.0, jnp.exp2(m_prev - m_new))
        p = jnp.exp2(s - m_new)
        if mask is not None:
            # guards the all-masked-row nan (s = m_new = -inf)
            p = jnp.where(mask, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        return m_new, l_new, alpha, p.astype(v_ref.dtype) if cast_p else p

    def _pv(u, p):
        cs = pl.ds(u * bkv_compute, bkv_compute)
        return jax.lax.dot_general(
            p, v_ref[0, 0, cs, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def _sweep_loop(masked):
        """lax.fori_loop variant of _sweep: the pend (alpha, p) rides the
        loop CARRY instead of Python-unrolled values.

        Why this exists: Mosaic allocates the unrolled pipeline's
        intermediates SSA-style — every stage's [bq, bkc] f32 tiles stay
        live for the whole body, so scoped-VMEM demand grows with
        n_sub·bq·bkc = bq·bkv (the measured block-area cliff,
        docs/design.md §3).  A fori_loop body reuses its buffers per
        iteration, capping demand at ~2 stages independent of bkv — the
        experiment that could admit bkv=4096 and halve the grid's step
        count.  Selected by flash_fwd's loop_sweep flag."""
        m0 = m_scr[:]
        l0 = l_scr[:]
        acc0 = acc_scr[:]
        n_sub = bkv // bkv_compute

        def mask_of(u):
            if not masked:
                return None
            cols = (c0 + u * bkv_compute
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv_compute), 1))
            rows = r0 + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bkv_compute), 0)
            q_lo, q_hi, kv_hi = spec_ref[0], spec_ref[1], spec_ref[2]
            causal, offset = spec_ref[3], spec_ref[4]
            mk = (rows >= q_lo) & (rows < q_hi) & (cols < kv_hi)
            mk = mk & ((causal == 0) | (cols <= rows + offset))
            if wnd is not None:
                mk = mk & (cols > rows + offset - wnd)
            if seg:
                ks_u = jax.lax.dynamic_slice(
                    ks_tile, (0, u * bkv_compute), (1, bkv_compute))
                mk = mk & (qs_tile == ks_u)
            return mk

        def step_body(u, carry):
            m_c, l_c, acc_c, alpha_p, p_p = carry
            cs = pl.ds(u * bkv_compute, bkv_compute)
            s_u = jax.lax.dot_general(
                q, k_ref[0, 0, cs, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # fold the carried pend FIRST: its pv matmul is independent of
            # this iteration's VPU chain and queues right behind s_u
            cs_prev = pl.ds((u - 1) * bkv_compute, bkv_compute)
            acc_c = acc_c * alpha_p + jax.lax.dot_general(
                p_p, v_ref[0, 0, cs_prev, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_c, l_c, alpha, p = _softmax(s_u, mask_of(u), m_c, l_c)
            return m_c, l_c, acc_c, alpha, p

        # iteration 0 outside the loop (no pend to fold yet)
        m1, l1, alpha1, p1 = _softmax(_score(0), mask_of(0), m0, l0)
        if n_sub > 1:
            m1, l1, acc1, alpha_last, p_last = jax.lax.fori_loop(
                1, n_sub, step_body, (m1, l1, acc0, alpha1, p1))
            u_last = n_sub - 1
        else:
            acc1, alpha_last, p_last, u_last = acc0, alpha1, p1, 0
        acc1 = acc1 * alpha_last + _pv(u_last, p_last)
        m_scr[:], l_scr[:], acc_scr[:] = m1, l1, acc1

    def _sweep(masked):
        """Three-stage software pipeline over compute sub-blocks (splash-style
        bkv vs bkv_compute).  With in-order issue and async MXU execution, the
        stagger means no MXU op ever waits on the VPU softmax chain:

            issue s(u+1)      [MXU]  — independent of everything in flight
            softmax(u)        [VPU]  — consumes s(u), overlaps s(u+1)
            acc += pv(u-1)    [MXU]  — its p tile was finished LAST iteration

        The acc update is deferred one sub-block (the alpha rescale composes:
        acc_u = acc_{u-1}*alpha_u + pv_u applied one step late), drained after
        the loop.  State (m, l, acc) is loop-carried by VALUE and written back
        to scratch once per grid step.  With a single sub-block
        (bkv_compute == bkv) this degenerates to the plain serial fold."""
        m, l, acc = m_scr[:], l_scr[:], acc_scr[:]
        n_sub = bkv // bkv_compute
        s_cur = _score(0)
        pend = None  # (u, alpha, p) awaiting its pv matmul + acc fold
        for u in range(n_sub):
            s_next = _score(u + 1) if u + 1 < n_sub else None
            mask = (
                _block_mask(spec_ref, r0, c0 + u * bkv_compute, bq,
                            bkv_compute, wnd,
                            seg=(qs_tile,
                                 ks_tile[:, u * bkv_compute:
                                         (u + 1) * bkv_compute]) if seg
                            else None)
                if masked else None
            )
            if ablate == "nosoftmax":
                # perf-debug ONLY (wrong numerics): p := s, softmax chain
                # skipped — times the MXU/pipeline ceiling with zero VPU work
                alpha, p = jnp.float32(1.0), (
                    s_cur.astype(v_ref.dtype) if cast_p else s_cur)
            else:
                m, l, alpha, p = _softmax(s_cur, mask, m, l)
            if pend is not None:
                acc = acc * pend[1] + _pv(pend[0], pend[2])
            pend = (u, alpha, p)
            s_cur = s_next
        # NOTE on the step-tail drain: deferring this final pv across the
        # grid step (the backward's _flush_dk trick) was measured on v5e and
        # REGRESSES fwd 157.6 -> 123.7 TFLOPs/s — the [bq, bkc] p stash
        # write/read costs more than the drained bubble.  The nosoftmax
        # ablation (sweep_blocks --ablate-fwd) bounds the whole VPU chain's
        # exposure at ~8% (206 ms vs 223 ms at seq=64K): the fwd ceiling is
        # per-grid-step overhead, not softmax scheduling.
        acc = acc * pend[1] + _pv(pend[0], pend[2])
        m_scr[:], l_scr[:], acc_scr[:] = m, l, acc

    sweep = _sweep_loop if loop else _sweep

    @pl.when(fast_cond)
    def _compute_fast():
        sweep(False)

    @pl.when(masked_cond)
    def _compute_masked():
        sweep(True)

    @pl.when(is_fin)
    def _finish():
        m = m_scr[:] * LN2  # back to the natural-log domain
        l = l_scr[:]
        _write_rows(m_out_ref, i, m, bq, lp)
        lse = jnp.where(l > 0, m + jnp.log(l), NEG_INF)
        _write_rows(lse_out_ref, i, lse, bq, lp)
        if emit_o:
            # fused finalize: o = acc * exp(m - lse) = acc / l — emit the
            # normalized output in the caller's dtype and skip the separate
            # [B,N,S,D]-f32 finalize pass (and its HBM round trip) entirely
            acc_out_ref[0, 0, :, :] = jnp.where(
                l > 0, acc_scr[:] / l, 0.0).astype(acc_out_ref.dtype)
        else:
            acc_out_ref[0, 0, :, :] = acc_scr[:]


def flash_fwd(q, k, v, m, lse, acc, scale, spec: MaskSpec, *,
              block_q=1024, block_kv=1024, block_kv_compute=None,
              interpret=None, cast_p=True, triangular=False, window=None,
              segments=None, emit_o=False, loop_sweep=False, _ablate=None):
    """One online-softmax ring round on TPU.  Same contract as
    ops/tile.py:tile_fwd: returns updated (m, lse, acc).

    m = lse = acc = None declares a STATICALLY EMPTY carry (the state a
    fresh init_state would hold): the kernel skips the three state inputs
    entirely and seeds its scratch from constants, eliminating both XLA's
    materialization of the [B,N,S,D] f32 zeros accumulator and the
    per-row-visit acc-in DMA — ~2 GB of dead HBM traffic per 64K-seq
    single-device forward.

    q [B,N,S,D]; k, v [B,Nk,Skv,D] (GQA when Nk < N); m, lse [B,N,S] f32;
    acc [B,N,S,D] f32.  `spec` scalars may be traced values.
    `block_kv_compute` (<= block_kv) sets the in-kernel compute sub-block
    width (see _fwd_kernel._sweep); the default min(block_kv, 1024) is the
    measured v5e optimum (two pipelined sub-blocks per 2048 memory block:
    150 vs 134 TFLOPs/s plain at seq=64K; 512 regresses).

    `triangular=True` selects the wrapped-diagonal all-live grid (see
    _tri_coords) — valid ONLY when the caller statically knows `spec` is
    full-window causal: q_lo=0, q_hi=S, kv_hi=S, causal, offset in {0, -1}
    (at block granularity both offsets have work confined to kv-block
    j <= q-block i with only the diagonal block partial, which is what the
    grid assumes; the diagonal's mask itself uses the real spec scalars, so
    both offsets compute correctly — the striped ring rounds rely on this).
    With `window` set, triangular=True instead selects the BAND grid, whose
    precondition is wider: offset in {0, -1} OR any offset ≡ 0 (mod bkv) —
    the windowed contig ring's live rounds have offset r*s with bkv | s,
    and the band width enumeration is shift-invariant at block-aligned
    offsets (the kernel's _kv_jmin/_kv_jmax read the traced offset; see
    fwd_band_nb).  Do NOT tighten either grid to absolute offsets.
    Falls back to the rectangular grid when the square-tiling preconditions
    don't hold.
    """
    if interpret is None:
        interpret = _interpret_default()
    if not loop_sweep and _ablate is None and _fwd_loop_default():
        loop_sweep = True  # BURST_FWD_LOOP promotion (see _fwd_loop_default)
    if _ablate is not None and loop_sweep:
        raise ValueError("_ablate has no loop_sweep variant — the ablation "
                         "would silently time the full softmax chain")
    carry = m is not None
    assert (lse is None) == (acc is None) == (not carry), \
        "m, lse, acc must be all None (empty carry) or all present"
    b, n, s_q, d = q.shape
    n_kv, s_kv = k.shape[1], k.shape[2]
    group = _gqa_group(n, n_kv)
    sq_pad, skv_pad = _padded_len(s_q, block_q), _padded_len(s_kv, block_kv)
    if sq_pad != s_q or skv_pad != s_kv:
        # ragged lengths: pad, run, slice back (spec bounds stay in true
        # coordinates so the pad region is masked; tri grids assume exact
        # full-window tiling, so the padded call is rectangular)
        if segments is not None:
            # pad ids never match each other or any real segment
            segments = (_pad_seg(segments[0], sq_pad, -1),
                        _pad_seg(segments[1], skv_pad, -2))
        m2, lse2, acc2 = flash_fwd(
            _pad_seq(q, sq_pad), _pad_seq(k, skv_pad), _pad_seq(v, skv_pad),
            _pad_seq(m, sq_pad, float("-inf")) if carry else None,
            (_pad_seq(lse, sq_pad, float("-inf")) if carry else None),
            _pad_seq(acc, sq_pad) if carry else None,
            scale, spec, block_q=block_q, block_kv=block_kv,
            block_kv_compute=block_kv_compute, interpret=interpret,
            cast_p=cast_p, triangular=False, window=window, segments=segments,
            emit_o=emit_o, loop_sweep=loop_sweep, _ablate=_ablate,
        )
        return m2[:, :, :s_q], lse2[:, :, :s_q], acc2[:, :, :s_q]
    bq = _pick_block(s_q, block_q)
    bkv = _pick_block(s_kv, block_kv)
    if block_kv_compute is None:
        block_kv_compute = min(bkv, 1024)
    bkc = _pick_block(bkv, block_kv_compute)
    lp = _pick_block(bq, 128)
    nqb = s_q // bq
    nkb = s_kv // bkv
    tri = (bool(triangular) and window is None and not _tri_disabled()
           and bq % bkv == 0 and s_q == s_kv and nqb % 2 == 0 and nqb >= 2)
    tri_r = bq // bkv if tri else 1  # tall-q aspect (see _tri_coords)
    # band grid: the window analogue of the tri grid.  A q-block's band can
    # intersect at most band_nb kv blocks (exact max over the reachable
    # alignments r0 = i*bq and offsets {0,-1}), so the kv grid dim shrinks
    # from nkb to band_nb — at window=4K/seq=64K/bkv=2048 that is 3 steps
    # per row instead of 32, and per-grid-step overhead is what dominates
    # small-window runs (measured 53 band-TFLOPs/s at window=4K vs 158
    # full-causal, results/results_window.jsonl).  Same caller contract as
    # tri (static full-window causal, offset 0/-1), which `triangular=True`
    # already promises.
    band_nb = None
    if bool(triangular) and window is not None and not _tri_disabled():
        nb = min(nkb, fwd_band_nb(bq, bkv, window))
        if nb < nkb:
            band_nb = nb
    if tri:
        def q_map(b_, h, p, jp, sp):
            return (b_, h, jnp.where(jp >= (p + 1) * tri_r, nqb - 1 - p, p), 0)

        def kv_map(b_, h, p, jp, sp):
            lena = (p + 1) * tri_r
            return (b_, h // group, jnp.where(jp >= lena, jp - lena, jp), 0)

        def state_map(b_, h, p, jp, sp):
            return (b_, h, 0, 0)

        grid = (b, n, nqb // 2, (nqb + 1) * tri_r)
    elif band_nb is not None:
        def q_map(b_, h, i, c, sp):
            return (b_, h, i, 0)

        def kv_map(b_, h, i, c, sp):
            j = _kv_jmin(sp, i, bq, bkv, nkb, window) + c
            j_eff = jnp.minimum(j, _kv_jmax(sp, i, bq, bkv, nkb))
            return (b_, h // group, j_eff, 0)

        def state_map(b_, h, i, c, sp):
            return (b_, h, 0, 0)

        grid = (b, n, nqb, band_nb)
    else:
        q_map, kv_map, state_map = _make_index_maps(bq, bkv, nqb, nkb, group,
                                                    wnd=window)
        grid = (b, n, nqb, nkb)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, bq=bq, bkv=bkv, bkv_compute=bkc, lp=lp,
        n_kv_blocks=nkb, cast_p=cast_p, tri=tri, wnd=window,
        seg=segments is not None, emit_o=emit_o, loop=loop_sweep,
        ablate=_ablate, band_nb=band_nb, carry=carry, tri_r=tri_r,
    )
    state_block = pl.BlockSpec((1, 1, s_q // lp, lp), state_map)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
    ]
    inputs = [_spec_array(spec), q, k, v]
    if carry:
        in_specs += [state_block, state_block,
                     pl.BlockSpec((1, 1, bq, d), q_map)]
        inputs += [_pack(m, lp), _pack(lse, lp), acc]
    if segments is not None:
        q_seg, kv_seg = segments
        # ids as [B, S, 1] (q rows along sublanes) / [B, 1, S] (kv along
        # lanes) so the in-kernel compare broadcasts without relayout
        in_specs.append(pl.BlockSpec(
            (1, bq, 1), lambda b_, h, i, j, sp: (b_, q_map(b_, h, i, j, sp)[2], 0)))
        in_specs.append(pl.BlockSpec(
            (1, 1, bkv), lambda b_, h, i, j, sp: (b_, 0, kv_map(b_, h, i, j, sp)[2])))
        inputs.append(jnp.asarray(q_seg, jnp.int32)[:, :, None])
        inputs.append(jnp.asarray(kv_seg, jnp.int32)[:, None, :])
    out_shape = [
        jax.ShapeDtypeStruct((b, n, s_q // lp, lp), jnp.float32),
        jax.ShapeDtypeStruct((b, n, s_q // lp, lp), jnp.float32),
        # emit_o: the third output is the NORMALIZED o in q's dtype (fused
        # finalize, see _finish) instead of the raw f32 accumulator
        jax.ShapeDtypeStruct((b, n, s_q, d),
                             q.dtype if emit_o else jnp.float32),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            state_block,
            state_block,
            pl.BlockSpec((1, 1, bq, d), q_map),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
    )
    m_new, lse_new, acc_new = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # q-block dim must be "arbitrary": the packed m/lse out blocks are
        # shared by every q-block of a head, so a megacore split over dim 2
        # would race the partial writes.
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*inputs)
    return _unpack(m_new), _unpack(lse_new), acc_new


# ---------------------------------------------------------------------------
# backward: dq kernel


def _dq_kernel(
    spec_ref,
    do_ref, q_ref, k_ref, v_ref, delta_ref, lse_ref,
    *rest,
    scale, bq, bkv, lp, n_kv_blocks, wnd=None, seg=False,
):
    if seg:
        qseg_ref, kvseg_ref = rest[0], rest[1]
        rest = rest[2:]
    dq_ref, dq_scr, lse_scr, delta_scr = rest
    i = pl.program_id(2)
    j = pl.program_id(3)
    r0 = i * bq
    c0 = j * bkv

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)
        lse = _read_rows(lse_ref, i, bq, lp)
        # fully-masked rows have lse = -inf; substituting a large positive
        # value makes p = exp2(s - BIG) underflow to 0 without an elementwise
        # select over the [bq, bkv] tile.  lse converted to base 2 (LOG2E).
        lse_scr[:] = jnp.where(lse == NEG_INF, BIG_LSE, lse * LOG2E)
        delta_scr[:] = _read_rows(delta_ref, i, bq, lp)

    live = _block_has_work(spec_ref, r0, c0, bq, bkv, wnd) & (
        j <= _kv_jmax(spec_ref, i, bq, bkv, n_kv_blocks)
    )
    full = _block_full(spec_ref, r0, c0, bq, bkv, wnd)
    seg_tiles = None
    if seg:
        # mixed-segment blocks lose only the fast path; dead-block pruning
        # (live) keys on the causal structure and stays valid
        seg_tiles = (qseg_ref[0, :, :], kvseg_ref[0, :, :])
        full = full & _seg_uniform_eq(*seg_tiles)

    def _accum(mask):
        q = q_ref[0, 0, :, :] * (scale * LOG2E)
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        # dp is independent of the softmax: issue it before the VPU chain
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        p = jnp.exp2(s - lse_scr[:])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        # the trailing *scale of ds is deferred to _finish (constant across j)
        ds = p * (dp - delta_scr[:])
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(live & full)
    def _compute_fast():
        _accum(None)

    @pl.when(live & ~full)
    def _compute_masked():
        _accum(_block_mask(spec_ref, r0, c0, bq, bkv, wnd, seg=seg_tiles))

    @pl.when(j == n_kv_blocks - 1)
    def _finish():
        dq_ref[0, 0, :, :] = dq_scr[:] * scale


# ---------------------------------------------------------------------------
# backward: dk/dv kernel
#
# Grid innermost dimension iterates over (gqa-group, q-block) pairs so each
# (batch, kv-head, kv-block) program accumulates contributions from every
# query head it serves — the group reduction of ops/tile.py:tile_bwd done
# in-kernel without atomics.


def _dkdv_kernel(
    spec_ref,
    do_ref, q_ref, k_ref, v_ref, delta_ref, lse_ref,
    *rest,
    scale, bq, bkv, lp, n_q_blocks, group, wnd=None, seg=False,
):
    if seg:
        qseg_ref, kvseg_ref = rest[0], rest[1]
        rest = rest[2:]
    dk_ref, dv_ref, dk_scr, dv_scr = rest
    j = pl.program_id(2)
    t = pl.program_id(3)
    iq = t % n_q_blocks
    r0 = iq * bq
    c0 = j * bkv

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = _block_has_work(spec_ref, r0, c0, bq, bkv, wnd) & (
        iq >= _q_imin(spec_ref, j, bq, bkv, n_q_blocks)
    )
    full = _block_full(spec_ref, r0, c0, bq, bkv, wnd)
    seg_tiles = None
    if seg:
        seg_tiles = (qseg_ref[0, :, :], kvseg_ref[0, :, :])
        full = full & _seg_uniform_eq(*seg_tiles)

    def _accum(mask):
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse_row = _read_rows(lse_ref, iq, bq, lp)
        lse_row = jnp.where(lse_row == NEG_INF, BIG_LSE, lse_row * LOG2E)
        delta_row = _read_rows(delta_ref, iq, bq, lp)

        s = jax.lax.dot_general(
            q * (scale * LOG2E), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dp is independent of the softmax: issue it before the VPU chain
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        p = jnp.exp2(s - lse_row)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        # trailing *scale of ds deferred to _finish; dk uses the RAW q block
        ds = p * (dp - delta_row)
        # dv += p^T @ do
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dk += ds^T @ q
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(live & full)
    def _compute_fast():
        _accum(None)

    @pl.when(live & ~full)
    def _compute_masked():
        _accum(_block_mask(spec_ref, r0, c0, bq, bkv, wnd, seg=seg_tiles))

    @pl.when(t == n_q_blocks * group - 1)
    def _finish():
        dk_ref[0, 0, :, :] = dk_scr[:] * scale
        dv_ref[0, 0, :, :] = dv_scr[:]


# ---------------------------------------------------------------------------
# shared fused-backward tile body (used by both the rectangular and the
# wrapped-diagonal fused kernels, which differ only in scheduling and in
# where dq accumulates — threaded in via `dq_update`)


def _flush_dk(dk_scr, ds_pend, q_pend, pend_flag):
    """Deferred dk accumulation for the previous live step's ds tile.
    Issued at step START, before this step's s/dp matmuls, so the MXU
    queue [dk, s, dp, dv] is entirely independent of this step's VPU
    p/ds chain: p is ready when dv's turn comes (one matmul after its
    dependency s), and ds is ready when the final dq issues — no MXU op
    waits on the VPU in steady state.  dv is NOT deferred: its operand p
    is finished two matmul-slots before dv's queue position, so deferring
    it only adds scratch-stash traffic.  (Measured on v5e at seq=64K:
    no deferral 166.5 TFLOPs/s; dv+dk deferred 169.6; flush nested after
    s/dp instead of step start 165.2.)"""
    dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
        ds_pend[:], q_pend[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    pend_flag[0] = 0


def _bwd_accum_tile(
    do_ref, q_ref, k_ref, v_ref, delta_ref, lse_ref,
    dv_scr, ds_pend, q_pend, pend_flag,
    iq, mask, *, scale, bq, lp, dq_update,
):
    """One fused-backward block pair: s/dp matmuls, p/ds VPU chain, inline
    dv accumulation, dq via `dq_update(ds, k)`, and the dk pend stash (in
    the bf16 the matmul would cast to anyway — numerics unchanged; the next
    step's _flush_dk issues it behind that step's own s/dp)."""
    q = q_ref[0, 0, :, :]
    k = k_ref[0, 0, :, :]
    v = v_ref[0, 0, :, :]
    do = do_ref[0, 0, :, :]
    lse_row = _read_rows(lse_ref, iq, bq, lp)
    lse_row = jnp.where(lse_row == NEG_INF, BIG_LSE, lse_row * LOG2E)
    delta_row = _read_rows(delta_ref, iq, bq, lp)

    # s and dp are independent MXU ops issued back to back; the VPU
    # p/ds chain overlaps them and the flush matmul queued before
    s = jax.lax.dot_general(
        q * (scale * LOG2E), k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    p = jnp.exp2(s - lse_row)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_row)
    dq_update(ds, k)
    ds_pend[:] = ds.astype(q.dtype)
    q_pend[:] = q
    pend_flag[0] = 1


# ---------------------------------------------------------------------------
# backward: fused kernel (dq + dk + dv in one pass)
#
# The split dq/dkdv kernels each recompute s and dp — 7 matmuls and 2
# softmax-exp passes per block pair where 5 and 1 suffice.  The fused kernel
# keeps dk/dv in VMEM scratch (kv-block-major grid) and accumulates dq
# IN PLACE in HBM via input_output_aliasing (the megablox gmm pattern):
# each visit reads the aliased dq block, adds this block's contribution, and
# writes it back.  Two structural rules make this race-free:
#   * q-blocks iterate DESCENDING within each kv sweep, so a dq block
#     written in sweep j is re-read in sweep j+1 exactly one full sweep
#     (nqb*group grid steps) later — far outside the pipeline's prefetch
#     lookahead.  Ascending order would re-read the last diagonal block only
#     one step after its write.
#   * index-map clamping maps skipped (masked) steps onto the first live
#     block, so consecutive duplicate indices collapse into one
#     fetch/flush; duplicate visits rewrite identical content.
# Gated on n_q_blocks * group >= 4 (below that the split kernels are used;
# the separation argument needs a reasonably long sweep).


def _bwd_fused_iq(spec_ref, j, c, bq, bkv, n_q_blocks, wnd):
    """Shared kernel/index-map iq schedule for the fused bwd sweep: descend
    from the sweep's bottom-most useful q block.  Without a window that is
    n_q_blocks-1; with one it is _q_imax (rows below it have their whole
    band left of kv block j), floored at imin so an empty column still
    yields a deterministic (passthrough-written) block.  Returns
    (iq_clamped, clamped): clamped steps revisit imin's block and must not
    write dq.  Note the band preserves the descending-separation argument:
    a block written at step c_w of sweep j re-appears in sweep j+1 at
    c_r = c_w + (imax(j+1) - imax(j)) >= c_w, i.e. a full sweep later."""
    imin = _q_imin(spec_ref, j, bq, bkv, n_q_blocks)
    if wnd is None:
        imax = n_q_blocks - 1
    else:
        imax = jnp.maximum(_q_imax(spec_ref, j, bq, bkv, n_q_blocks, wnd),
                           imin)
    iq_raw = imax - c
    return jnp.maximum(iq_raw, imin), iq_raw < imin


def _bwd_fused_kernel(
    spec_ref,
    do_ref, q_ref, k_ref, v_ref, delta_ref, lse_ref, dq_in_ref,
    *rest,
    scale, bq, bkv, lp, n_q_blocks, group, nbq, wnd=None, seg=False,
):
    if seg:
        qseg_ref, kvseg_ref = rest[0], rest[1]
        rest = rest[2:]
    (dq_out_ref, dk_ref, dv_ref,
     dk_scr, dv_scr, ds_pend, q_pend, pend_flag) = rest
    j = pl.program_id(2)
    t = pl.program_id(3)
    # descending within the (possibly window-banded) sweep of nbq steps
    iq, clamped = _bwd_fused_iq(spec_ref, j, t % nbq, bq, bkv, n_q_blocks,
                                wnd)
    r0 = iq * bq
    c0 = j * bkv

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        pend_flag[0] = 0

    # clamped steps revisit block imin, whose live visit came just before
    # them in the descending sweep; they must not touch dq_out or they'd
    # overwrite that visit's accumulation with the stale dq_in buffer
    live = _block_has_work(spec_ref, r0, c0, bq, bkv, wnd) & ~clamped
    full = _block_full(spec_ref, r0, c0, bq, bkv, wnd)
    if seg:
        # packed sequences: only single-segment-matching blocks skip the
        # mask (same classification as _fwd_kernel)
        qs_tile = qseg_ref[0, :, :]   # [bq, 1]
        ks_tile = kvseg_ref[0, :, :]  # [1, bkv]
        seg_ok = _seg_uniform_eq(qs_tile, ks_tile)
        fast_cond = live & full & seg_ok
        masked_cond = live & ~(full & seg_ok)
    else:
        fast_cond = live & full
        masked_cond = live & ~full

    @pl.when(pend_flag[0] == 1)
    def _flush_prev():
        _flush_dk(dk_scr, ds_pend, q_pend, pend_flag)

    def _dq_update(ds, k):
        # in-place dq accumulation (ds*scale deferred to the caller's epilog
        # would lose the per-visit accumulation — apply it here instead)
        dq_out_ref[0, 0, :, :] = dq_in_ref[0, 0, :, :] + scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def _accum(mask):
        _bwd_accum_tile(
            do_ref, q_ref, k_ref, v_ref, delta_ref, lse_ref,
            dv_scr, ds_pend, q_pend, pend_flag,
            iq, mask, scale=scale, bq=bq, lp=lp, dq_update=_dq_update,
        )

    @pl.when(fast_cond)
    def _compute_fast():
        _accum(None)

    @pl.when(masked_cond)
    def _compute_masked():
        _accum(_block_mask(spec_ref, r0, c0, bq, bkv, wnd,
                           seg=(qs_tile, ks_tile) if seg else None))

    @pl.when(~live & ~clamped)
    def _passthrough():
        # an unclamped dead block (fully-masked column / row range) gets its
        # own buffer flush at the next index change; keep its content valid
        dq_out_ref[0, 0, :, :] = dq_in_ref[0, 0, :, :]

    @pl.when(t == nbq * group - 1)
    def _finish():
        # drain: this sweep's last live step just stashed its pend tiles
        @pl.when(pend_flag[0] == 1)
        def _drain():
            _flush_dk(dk_scr, ds_pend, q_pend, pend_flag)

        dk_ref[0, 0, :, :] = dk_scr[:] * scale
        dv_ref[0, 0, :, :] = dv_scr[:]


def _flush_dk_sub(dk_scr, ds_pend, q_pend, pend_flag, bkvc):
    """Sub-block-width deferred dk flush (tri kernel): pend_flag holds
    (flag, sub-block index); the dk rows are a dynamic slice of the kv-block
    scratch.  Same scheduling argument as _flush_dk — the matmul issues at
    the NEXT step's start, ahead of that step's VPU dependencies."""
    rows = pl.ds(pend_flag[1] * bkvc, bkvc)
    dk_scr[rows, :] = dk_scr[rows, :] + jax.lax.dot_general(
        ds_pend[:], q_pend[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    pend_flag[0] = 0


def _bwd_accum_tile_sub(
    do_ref, q_ref, k_ref, v_ref, delta_ref, lse_ref,
    dv_scr, dk_scr, ds_pend, q_pend, pend_flag,
    iq, masked, mask_of, *, scale, bq, bkvc, n_sub, lp, dq_update,
):
    """Fused-backward block pair with the kv block split into compute
    sub-blocks — the backward analogue of _fwd_kernel._sweep.

    Why: the un-sub-blocked tile keeps four [bq, bkv] f32 intermediates
    (s, dp, p, ds) live at once, which is what pins the backward's VMEM
    cliff one power of two below the forward's (see ops/tuning.py).
    Splitting the kv dimension into n_sub pieces shrinks the live
    intermediates to [bq, bkvc], buying the same grid-step count at double
    the kv block — fewer steps, same math.

    Scheduling per sub-block u: s(u) and dp(u) issue back to back on the
    MXU; the VPU p/ds chain for u overlaps dv(u)'s matmul (its operand p is
    ready one slot earlier) and dk(u-1)'s — dk is deferred ONE SUB-BLOCK
    in-step (plain values, the q operand is the same all step) and one
    GRID STEP for the final sub-block (scratch stash, _flush_dk_sub).  dq
    accumulates across sub-blocks in a [bq, d] f32 value and folds into the
    resident output buffer once per step."""
    q = q_ref[0, 0, :, :]
    do = do_ref[0, 0, :, :]
    lse_row = _read_rows(lse_ref, iq, bq, lp)
    lse_row = jnp.where(lse_row == NEG_INF, BIG_LSE, lse_row * LOG2E)
    delta_row = _read_rows(delta_ref, iq, bq, lp)
    qs = q * (scale * LOG2E)
    dq_acc = None
    prev = None  # (u, ds cast) awaiting its dk matmul
    for u in range(n_sub):
        rows = slice(u * bkvc, (u + 1) * bkvc)
        k_u = k_ref[0, 0, rows, :]
        v_u = v_ref[0, 0, rows, :]
        s = jax.lax.dot_general(
            qs, k_u, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_u, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        p = jnp.exp2(s - lse_row)
        if masked:
            p = jnp.where(mask_of(u), p, 0.0)
        dv_scr[rows, :] = dv_scr[rows, :] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_row)
        dq_u = jax.lax.dot_general(
            ds.astype(k_u.dtype), k_u, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dq_acc = dq_u if dq_acc is None else dq_acc + dq_u
        if prev is not None:
            pu, pds = prev
            prows = slice(pu * bkvc, (pu + 1) * bkvc)
            dk_scr[prows, :] = dk_scr[prows, :] + jax.lax.dot_general(
                pds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        prev = (u, ds.astype(q.dtype))
    dq_update(dq_acc)
    ds_pend[:] = prev[1]
    q_pend[:] = q
    pend_flag[0] = 1
    pend_flag[1] = prev[0]


def _bwd_accum_tile_sub_loop(
    do_ref, q_ref, k_ref, v_ref, delta_ref, lse_ref,
    dv_scr, dk_scr, ds_pend, q_pend, pend_flag,
    iq, masked, mask_of, *, scale, bq, bkvc, n_sub, lp, dq_update,
):
    """lax.fori_loop variant of _bwd_accum_tile_sub — the backward analogue
    of _fwd_kernel._sweep_loop.

    Why this exists: the unrolled sub-block loop's intermediates are
    allocated SSA-style, so scoped-VMEM demand grows with n_sub·bq·bkvc =
    bq·bkv — the measured backward cliff at 1024x2048 area, which
    sub-blocking alone did NOT move (docs/design.md §3's negative result:
    the round-2 `_bwd_accum_tile_sub` experiment).  A fori_loop body
    reuses its buffers per iteration, capping demand at ~2 stages
    independent of bkv — the experiment that could admit 4096-wide kv
    blocks and halve the backward's grid-step count.  Selected by
    flash_bwd's loop_sweep flag (BURST_BWD_LOOP promotes it).

    Scheduling: same dk deferral as the unrolled variant — dk(u-1) rides
    the loop CARRY (its ds tile, cast to the matmul dtype) and issues at
    the top of iteration u, ahead of u's VPU chain; the final sub-block's
    dk crosses the grid step through the ds_pend/q_pend scratch stash
    exactly like the unrolled path (_flush_dk_sub reads pend_flag[1]).
    `mask_of` here must accept a TRACED u (the tri kernel passes its
    iota-based builder, not the static-slice closure)."""
    q = q_ref[0, 0, :, :]
    do = do_ref[0, 0, :, :]
    lse_row = _read_rows(lse_ref, iq, bq, lp)
    lse_row = jnp.where(lse_row == NEG_INF, BIG_LSE, lse_row * LOG2E)
    delta_row = _read_rows(delta_ref, iq, bq, lp)
    qs = q * (scale * LOG2E)

    def one(u, prev_ds, dq_acc, fold_prev):
        rows = pl.ds(u * bkvc, bkvc)
        k_u = k_ref[0, 0, rows, :]
        v_u = v_ref[0, 0, rows, :]
        s = jax.lax.dot_general(
            qs, k_u, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_u, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if fold_prev:
            # the carried pend's dk: independent of this iteration's VPU
            # chain, queues right behind s/dp
            prows = pl.ds((u - 1) * bkvc, bkvc)
            dk_scr[prows, :] = dk_scr[prows, :] + jax.lax.dot_general(
                prev_ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        p = jnp.exp2(s - lse_row)
        if masked:
            p = jnp.where(mask_of(u), p, 0.0)
        dv_scr[rows, :] = dv_scr[rows, :] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_row)
        dq_acc = dq_acc + jax.lax.dot_general(
            ds.astype(k_u.dtype), k_u, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return ds.astype(q.dtype), dq_acc

    dq0 = jnp.zeros((bq, q_ref.shape[-1]), jnp.float32)
    ds_last, dq_acc = one(0, None, dq0, False)
    if n_sub > 1:
        def body(u, carry):
            prev_ds, dq_c = carry
            return one(u, prev_ds, dq_c, True)

        ds_last, dq_acc = jax.lax.fori_loop(
            1, n_sub, body, (ds_last, dq_acc))
    dq_update(dq_acc)
    ds_pend[:] = ds_last
    q_pend[:] = q
    pend_flag[0] = 1
    pend_flag[1] = n_sub - 1


def _bwd_fused_tri_kernel(
    spec_ref,
    do_ref, q_ref, k_ref, v_ref, delta_ref, lse_ref,
    *rest,
    scale, bq, bkv, bkvc, lp, nqb, nkb, ratio, seg=False, loop=False,
):
    """Wrapped-diagonal causal backward (static full-window causal with
    offset 0 or -1 — see the flash_fwd docstring's triangular contract —
    and group=1).

    Grid (b, h, p, c) with p in [0, nkb/2), c in [0, C] where
    C = 2*nqb - ratio*(nkb-1), ratio = bkv//bq: pair p processes kv-block
    nkb-1-p (segment A: its live q-blocks, descending) then kv-block p
    (segment B) — every step computes a live block, eliminating the
    rectangular grid's ~half dead steps.  dq accumulates IN the whole-head
    output buffer (constant block index -> VMEM-resident until the head
    changes), so there is no in-place HBM aliasing and no write/read
    separation constraint.  dk/dv write at segment ends through an output
    index map lagged one step (jsel(c-1)), with one trailing no-compute step
    (c == C) to flush the final dk pend and write segment B's dk/dv.
    """
    if seg:
        qseg_ref, kvseg_ref = rest[0], rest[1]
        rest = rest[2:]
    (dq_ref, dk_ref, dv_ref,
     dk_scr, dv_scr, ds_pend, q_pend, pend_flag) = rest
    p = pl.program_id(2)
    c = pl.program_id(3)
    j_hi = nkb - 1 - p
    len_a = nqb - ratio * j_hi
    ncols = 2 * nqb - ratio * (nkb - 1)
    seg_b = c >= len_a
    iq = jnp.where(seg_b, nqb - 1 - (c - len_a), nqb - 1 - c)
    jk = jnp.where(seg_b, p, j_hi)
    r0 = iq * bq
    c0 = jk * bkv

    compute = c < ncols

    @pl.when((p == 0) & (c == 0))
    def _init_head():
        dq_ref[0, 0, :, :] = jnp.zeros_like(dq_ref[0, 0, :, :])
        pend_flag[0] = 0

    # flush the previous step's deferred dk BEFORE this step's matmuls and
    # before any segment reinit (the pend belongs to the previous segment's
    # kv block when c == len_a)
    @pl.when(pend_flag[0] == 1)
    def _flush_prev():
        _flush_dk_sub(dk_scr, ds_pend, q_pend, pend_flag, bkvc)

    # segment writeout: at c == len_a write segment A's dk/dv (out index map
    # lags one step, so the block still points at kv j_hi); at c == ncols
    # (the trailing step) write segment B's
    @pl.when((c == len_a) | (c == ncols))
    def _writeout():
        dk_ref[0, 0, :, :] = dk_scr[:] * scale
        dv_ref[0, 0, :, :] = dv_scr[:]

    @pl.when((c == 0) | (c == len_a))
    def _init_seg():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # the diagonal blocks are the trailing `ratio` steps of each segment
    full = jnp.where(seg_b, c < ncols - ratio, c < len_a - ratio)
    if seg:
        # packed sequences: a structurally-full block still needs masking
        # unless both tiles share one segment (same as the fwd tri grid —
        # seg only widens which steps take the masked path)
        qs_tile = qseg_ref[0, :, :]   # [bq, 1]
        ks_tile = kvseg_ref[0, :, :]  # [1, bkv]
        full = full & _seg_uniform_eq(qs_tile, ks_tile)

    def _dq_update(dq_acc):
        # dq accumulates straight into the resident whole-head out buffer
        rows = pl.ds(iq * bq, bq)
        dq_ref[0, 0, rows, :] = dq_ref[0, 0, rows, :] + scale * dq_acc

    def _mask_of(u):
        # u is a Python int (the sub-block loop is unrolled): static slice
        seg_u = (qs_tile, ks_tile[:, u * bkvc:(u + 1) * bkvc]) if seg else None
        return _block_mask(spec_ref, r0, c0 + u * bkvc, bq, bkvc, seg=seg_u)

    def _mask_of_dyn(u):
        # traced u (the fori_loop sweep): same shared predicate —
        # _block_mask takes traced r0/c0 everywhere already; only the seg
        # tile needs a dynamic slice instead of the unrolled static one
        seg_u = None
        if seg:
            seg_u = (qs_tile,
                     jax.lax.dynamic_slice(ks_tile, (0, u * bkvc), (1, bkvc)))
        return _block_mask(spec_ref, r0, c0 + u * bkvc, bq, bkvc, seg=seg_u)

    def _accum(masked):
        accum = _bwd_accum_tile_sub_loop if loop else _bwd_accum_tile_sub
        accum(
            do_ref, q_ref, k_ref, v_ref, delta_ref, lse_ref,
            dv_scr, dk_scr, ds_pend, q_pend, pend_flag,
            iq, masked, _mask_of_dyn if loop else _mask_of,
            scale=scale, bq=bq, bkvc=bkvc, n_sub=bkv // bkvc, lp=lp,
            dq_update=_dq_update,
        )

    @pl.when(compute & full)
    def _compute_fast():
        _accum(False)

    @pl.when(compute & ~full)
    def _compute_masked():
        _accum(True)


def _flash_bwd_fused_tri(do, q, k, v, delta, lse, scale, spec, *,
                         block_q, block_kv, interpret, block_kv_compute=None,
                         segments=None, loop_sweep=False):
    b, n, s_q, d = q.shape
    s_kv = k.shape[2]
    bq = _pick_block(s_q, block_q)
    bkv = _pick_block(s_kv, block_kv)
    if block_kv_compute is None:
        bkvc = bkv
    else:
        bkvc = _pick_block(bkv, block_kv_compute)
    lp = _pick_block(bq, 128)
    nqb = s_q // bq
    nkb = s_kv // bkv
    ratio = bkv // bq
    ncols = 2 * nqb - ratio * (nkb - 1)

    def iq_of(p, c):
        j_hi = nkb - 1 - p
        len_a = nqb - ratio * j_hi
        i = jnp.where(c >= len_a, nqb - 1 - (c - len_a), nqb - 1 - c)
        return jnp.clip(i, 0, nqb - 1)

    def q_map(b_, h, p, c, sp):
        return (b_, h, iq_of(p, c), 0)

    def kv_map(b_, h, p, c, sp):
        return (b_, h, jnp.where(c >= nqb - ratio * (nkb - 1 - p), p, nkb - 1 - p), 0)

    def kv_out_map(b_, h, p, c, sp):
        # lagged one step so the c == len_a / c == ncols writeouts land on
        # the segment that just ended
        cl = jnp.maximum(c, 1) - 1
        return (b_, h, jnp.where(cl >= nqb - ratio * (nkb - 1 - p), p, nkb - 1 - p), 0)

    def state_map(b_, h, p, c, sp):
        return (b_, h, 0, 0)

    def dq_map(b_, h, p, c, sp):
        return (b_, h, 0, 0)

    state_block = pl.BlockSpec((1, 1, s_q // lp, lp), state_map)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
        state_block,
        state_block,
    ]
    inputs = [_spec_array(spec), do, q, k, v, _pack(delta, lp),
              _pack(lse, lp)]
    if segments is not None:
        in_specs.append(pl.BlockSpec(
            (1, bq, 1),
            lambda b_, h, p, c, sp: (b_, q_map(b_, h, p, c, sp)[2], 0)))
        in_specs.append(pl.BlockSpec(
            (1, 1, bkv),
            lambda b_, h, p, c, sp: (b_, 0, kv_map(b_, h, p, c, sp)[2])))
        inputs.append(jnp.asarray(segments[0], jnp.int32)[:, :, None])
        inputs.append(jnp.asarray(segments[1], jnp.int32)[:, None, :])
    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_fused_tri_kernel, scale=scale, bq=bq, bkv=bkv, bkvc=bkvc,
            lp=lp, nqb=nqb, nkb=nkb, ratio=ratio, seg=segments is not None,
            loop=loop_sweep,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, n, nkb // 2, ncols + 1),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, s_q, d), dq_map),
                pl.BlockSpec((1, 1, bkv, d), kv_out_map),
                pl.BlockSpec((1, 1, bkv, d), kv_out_map),
            ],
            scratch_shapes=[
                pltpu.VMEM((bkv, d), jnp.float32),
                pltpu.VMEM((bkv, d), jnp.float32),
                pltpu.VMEM((bq, bkvc), q.dtype),
                pltpu.VMEM((bq, d), q.dtype),
                pltpu.SMEM((2,), jnp.int32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, n, s_q, d), jnp.float32),
            jax.ShapeDtypeStruct((b, n, s_kv, d), jnp.float32),
            jax.ShapeDtypeStruct((b, n, s_kv, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*inputs)
    return dq, dk, dv


def bwd_band_nbq(bq, bkv, nqb, window):
    """Static q-block count of a fused-bwd window band sweep (exact over
    reachable alignments, see bwd_band_nb); nqb when no window."""
    if window is None:
        return nqb
    return min(nqb, bwd_band_nb(bq, bkv, window))


def _flash_bwd_fused(do, q, k, v, delta, lse, scale, spec, *,
                     block_q, block_kv, interpret, window=None,
                     segments=None):
    b, n, s_q, d = q.shape
    n_kv, s_kv = k.shape[1], k.shape[2]
    group = _gqa_group(n, n_kv)
    bq = _pick_block(s_q, block_q)
    bkv = _pick_block(s_kv, block_kv)
    lp = _pick_block(bq, 128)
    nqb = s_q // bq
    nkb = s_kv // bkv
    # window: sweep only the q blocks whose band can touch kv block j
    nbq = bwd_band_nbq(bq, bkv, nqb, window)

    def qh_of(h, t):
        return h * group + t // nbq

    def bq_map(b_, h, j, t, sp):
        iq, _ = _bwd_fused_iq(sp, j, t % nbq, bq, bkv, nqb, window)
        return (b_, qh_of(h, t), iq, 0)

    def bstate_map(b_, h, j, t, sp):
        return (b_, qh_of(h, t), 0, 0)

    def bkv_map(b_, h, j, t, sp):
        return (b_, h, j, 0)

    bstate_block = pl.BlockSpec((1, 1, s_q // lp, lp), bstate_map)
    dq0 = jnp.zeros((b, n, s_q, d), jnp.float32)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), bq_map),
        pl.BlockSpec((1, 1, bq, d), bq_map),
        pl.BlockSpec((1, 1, bkv, d), bkv_map),
        pl.BlockSpec((1, 1, bkv, d), bkv_map),
        bstate_block,
        bstate_block,
        pl.BlockSpec((1, 1, bq, d), bq_map),
    ]
    inputs = [_spec_array(spec), do, q, k, v, _pack(delta, lp),
              _pack(lse, lp), dq0]
    if segments is not None:
        # seg ids appended AFTER dq0 so the alias index below stays stable
        in_specs.append(pl.BlockSpec(
            (1, bq, 1),
            lambda b_, h, j, t, sp: (b_, bq_map(b_, h, j, t, sp)[2], 0)))
        in_specs.append(pl.BlockSpec(
            (1, 1, bkv), lambda b_, h, j, t, sp: (b_, 0, j)))
        inputs.append(jnp.asarray(segments[0], jnp.int32)[:, :, None])
        inputs.append(jnp.asarray(segments[1], jnp.int32)[:, None, :])
    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_fused_kernel, scale=scale, bq=bq, bkv=bkv, lp=lp,
            n_q_blocks=nqb, group=group, nbq=nbq, wnd=window,
            seg=segments is not None,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, n_kv, nkb, nbq * group),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, bq, d), bq_map),
                pl.BlockSpec((1, 1, bkv, d), bkv_map),
                pl.BlockSpec((1, 1, bkv, d), bkv_map),
            ],
            scratch_shapes=[
                pltpu.VMEM((bkv, d), jnp.float32),
                pltpu.VMEM((bkv, d), jnp.float32),
                # deferred-flush pend tiles (see _flush_dk);
                # q.dtype matches the casts the stash performs
                pltpu.VMEM((bq, bkv), q.dtype),
                pltpu.VMEM((bq, d), q.dtype),
                pltpu.SMEM((1,), jnp.int32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, n, s_q, d), jnp.float32),
            jax.ShapeDtypeStruct((b, n_kv, s_kv, d), jnp.float32),
            jax.ShapeDtypeStruct((b, n_kv, s_kv, d), jnp.float32),
        ],
        # flattened input index 7 = dq0 (after the scalar-prefetch spec array)
        input_output_aliases={7: 0},
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*inputs)
    return dq, dk, dv


def _tri_bwd_other_residents(bq, bkv, d, itemsize=2, bkvc=None):
    """Estimated VMEM held by everything EXCEPT the whole-head dq output in
    the triangular fused bwd kernel: double-buffered input blocks (do, q,
    k, v) and dk/dv f32 output blocks, plus the ds/q deferral stashes
    (sub-block width when block_kv_compute is set).  Packed delta/lse
    blocks are negligible next to these."""
    if bkvc is None:
        bkvc = bkv
    blocks = 2 * (2 * bq * d * itemsize      # do, q
                  + 2 * bkv * d * itemsize   # k, v
                  + 2 * bkv * d * 4)         # dk, dv out (f32)
    scratch = bq * bkvc * itemsize + bq * d * itemsize  # ds stash, q stash
    return blocks + scratch


def tri_bwd_supported(s_q, s_kv, n, n_kv, d, *, block_q, block_kv,
                      block_kv_compute=None) -> bool:
    """Whether flash_bwd(triangular=True) will actually use the
    wrapped-diagonal kernel (vs silently falling back to the rectangular
    fused kernel): group=1 only, square even block tiling, and the
    whole-head dq output buffer must fit the VMEM budget.

    The dq budget is derived from VMEM_LIMIT minus an estimate of the other
    residents, at half utilization — Mosaic's own overheads aren't modeled,
    and a config that passes this gate but fails to compile has no automatic
    fallback inside burst_attn (only the BURST_NO_TRI{,_BWD} env vars), so
    the gate errs conservative."""
    bq = _pick_block(s_q, block_q)
    bkv = _pick_block(s_kv, block_kv)
    nkb = s_kv // bkv
    # clamp the sub-block exactly as _flash_bwd_fused_tri will, so the
    # estimate charges the scratch the kernel actually allocates
    bkvc = None if block_kv_compute is None else _pick_block(bkv, block_kv_compute)
    dq_budget = VMEM_LIMIT // 2 - _tri_bwd_other_residents(
        bq, bkv, d, bkvc=bkvc)
    return (
        n == n_kv and s_q == s_kv and bkv % bq == 0
        and nkb % 2 == 0 and nkb >= 2
        and s_q * d * 4 <= dq_budget
    )


def probe_tri_bwd(s, d, *, n=1, n_kv=None, segments=False, block_q=None,
                  block_kv=None, block_kv_compute=None,
                  loop_sweep=False) -> bool:
    """ACTUALLY compile the wrapped-diagonal fused backward at sequence
    length s and report whether it succeeds; returns False WITHOUT
    compiling when production would never take the tri path (GQA — the
    tri kernel is group=1 only — or a failed tri_bwd_supported gate).
    The compile itself runs at b = n = 1: the whole-head dq residency
    that decides compilability is per-(batch, head).  `segments=True`
    compiles the packed-sequence variant (its segment-id input blocks and
    masking add VMEM residents — a segment-free pass does not prove the
    packed kernel compiles).  On compile failure, set BURST_NO_TRI_BWD=1
    for this process so every later triangular=True BACKWARD call takes
    the rectangular fused kernel instead of crashing the caller's (much
    larger) jit; the forward tri/band grids (independent, smaller VMEM
    footprint) stay enabled.

    Why this exists: tri_bwd_supported is a hand model of Mosaic's VMEM
    residency, explicitly conservative but unverified on generations
    without a measured BlockTable row — a config that passes the gate but
    fails Mosaic has no automatic fallback inside a traced program (a
    pallas lowering error surfaces when the ENCLOSING jit compiles, where
    flash_bwd can no longer catch it).  Costs one real kernel compile
    (minutes on a cold remote-compile cache) — production entry points run
    it by default through the memoized ensure_tri_bwd wrapper
    (make_train_step's first step; models/runner.py at startup, opt out
    with --no-probe-tri-bwd)."""
    from .masks import round_spec

    n_kv = n if n_kv is None else n_kv
    _, _, bq, bkv, _ = resolve_blocks(None, None, block_q, block_kv)
    if not tri_bwd_supported(s, s, n, n_kv, d, block_q=bq, block_kv=bkv,
                             block_kv_compute=block_kv_compute):
        return False
    if _interpret_default():
        return True  # interpret mode always "compiles"
    spec = round_spec(jnp.int32(0), jnp.int32(0), s, s, True, "contig")
    args = [jnp.zeros((1, 1, s, d), jnp.bfloat16) for _ in range(4)] + [
        jnp.zeros((1, 1, s), jnp.float32), jnp.zeros((1, 1, s), jnp.float32)]
    segs = (jnp.zeros((1, s), jnp.int32),) * 2 if segments else None
    try:
        jax.jit(lambda do, q, k, v, delta, lse: flash_bwd(
            do, q, k, v, delta, lse, d**-0.5, spec, block_q=bq, block_kv=bkv,
            triangular=True, fused=True, block_kv_compute=block_kv_compute,
            loop_sweep=loop_sweep, segments=segs,
        )).lower(*args).compile()
        return True
    except Exception as e:  # noqa: BLE001 — any compile failure means rect
        logger.warning(
            "tri bwd at s=%d blocks %dx%d%s passed the VMEM gate but FAILED "
            "to compile (%s: %.120s); setting BURST_NO_TRI_BWD=1 — this "
            "process falls back to the rectangular fused backward (forward "
            "tri/band grids stay on)", s, bq, bkv,
            " (packed)" if segments else "",
            type(e).__name__, str(e))
        os.environ["BURST_NO_TRI_BWD"] = "1"
        return False


# ensure_tri_bwd memo: one real probe compile per distinct config per
# process.  Keyed on device kind too — a process can see CPU (interpret)
# first and TPU later (tests monkeypatching _interpret_default rely on
# the non-interpret key being distinct).
_TRI_BWD_PROBED: dict = {}


def ensure_tri_bwd(s, d, *, n=1, n_kv=None, segments=False, block_q=None,
                   block_kv=None, block_kv_compute=None,
                   loop_sweep=False) -> bool:
    """Memoized probe_tri_bwd — THE default startup gate for production
    entry points (trainer first step, runner, benchmarks): call before
    the enclosing jit compiles so a config that passes tri_bwd_supported
    but fails Mosaic degrades to the rectangular fused backward
    automatically instead of surfacing a raw lowering error from inside
    the caller's (much larger) compile.  Costs at most ONE real kernel
    compile per distinct (device kind, shape, blocks, variant) per
    process; returns instantly once the backward tri path is already
    disabled (a previous probe failed, or BURST_NO_TRI{,_BWD} is set)."""
    if _tri_bwd_disabled():
        return False
    key = (
        jax.devices()[0].device_kind if jax.devices() else "cpu",
        _interpret_default(), s, d, n, n_kv, segments, block_q, block_kv,
        block_kv_compute, loop_sweep,
    )
    if key not in _TRI_BWD_PROBED:
        _TRI_BWD_PROBED[key] = probe_tri_bwd(
            s, d, n=n, n_kv=n_kv, segments=segments, block_q=block_q,
            block_kv=block_kv, block_kv_compute=block_kv_compute,
            loop_sweep=loop_sweep)
    return _TRI_BWD_PROBED[key]


def flash_bwd(do, q, k, v, delta, lse, scale, spec: MaskSpec, *,
              block_q=1024, block_kv=1024, interpret=None, fused=None,
              triangular=False, window=None, segments=None,
              block_kv_compute=None, loop_sweep=False):
    """One backward ring round on TPU.  Same contract as ops/tile.py:tile_bwd:
    returns (dq [B,N,S,D], dk [B,Nk,Skv,D], dv [B,Nk,Skv,D]) in float32.

    delta = sum(o*do, -1) [B,N,S] f32 (precomputed; reference
    burst_attn_interface.py:269-278); lse is the FINAL log-sum-exp.

    `fused` selects the single-pass dq+dk+dv kernel (default on real TPU when
    the sweep is long enough for its aliasing-separation argument; see
    _bwd_fused_kernel).  The split kernels remain for interpret mode and
    short sweeps.  `triangular=True` selects the wrapped-diagonal causal
    grid (same caller contract as flash_fwd's triangular: full-window
    causal, offset 0 or -1) when tri_bwd_supported() holds; an explicit
    fused=False takes precedence so the split kernels can always be
    A/B-compared.  `block_kv_compute` (tri path only) splits the kv block
    into compute sub-blocks — see _bwd_accum_tile_sub.
    """
    if interpret is None:
        interpret = _interpret_default()
    if not loop_sweep and _bwd_loop_default():
        loop_sweep = True  # BURST_BWD_LOOP promotion (see _bwd_loop_default)
    b, n, s_q, d = q.shape
    n_kv, s_kv = k.shape[1], k.shape[2]
    group = _gqa_group(n, n_kv)
    sq_pad, skv_pad = _padded_len(s_q, block_q), _padded_len(s_kv, block_kv)
    if sq_pad != s_q or skv_pad != s_kv:
        # ragged lengths: pad, run, slice back (see flash_fwd).  lse pads
        # with 0 (not -inf) so the kernels' exp(s - lse) stays finite before
        # the mask select zeroes the padded rows' contributions.
        if segments is not None:
            segments = (_pad_seg(segments[0], sq_pad, -1),
                        _pad_seg(segments[1], skv_pad, -2))
        dq, dk, dv = flash_bwd(
            _pad_seq(do, sq_pad), _pad_seq(q, sq_pad),
            _pad_seq(k, skv_pad), _pad_seq(v, skv_pad),
            _pad_seq(delta, sq_pad), _pad_seq(lse, sq_pad),
            scale, spec, block_q=block_q, block_kv=block_kv,
            interpret=interpret, fused=fused, triangular=False, window=window,
            segments=segments, block_kv_compute=block_kv_compute,
        )
        return dq[:, :, :s_q], dk[:, :, :s_kv], dv[:, :, :s_kv]
    bq = _pick_block(s_q, block_q)
    bkv = _pick_block(s_kv, block_kv)
    lp = _pick_block(bq, 128)
    nqb = s_q // bq
    nkb = s_kv // bkv
    explicit_split = fused is False
    if window is not None:
        # the wrapped-diagonal tri grid assumes full-window causality; a
        # window instead takes the BANDED fused sweep below.  Segments ride
        # BOTH fused kernels' masked paths (round-2 verdict item 5 — neither
        # mode downgrades to the 7-matmul split kernels any more).
        triangular = False
    if fused is None:
        fused = (not interpret
                 and bwd_band_nbq(bq, bkv, s_q // bq, window) * group >= 4)
    tri = (
        bool(triangular) and not explicit_split and not _tri_bwd_disabled()
        and tri_bwd_supported(s_q, s_kv, n, n_kv, d, block_q=bq, block_kv=bkv,
                              block_kv_compute=block_kv_compute)
    )
    if tri:
        return _flash_bwd_fused_tri(
            do, q, k, v, delta, lse, scale, spec,
            block_q=block_q, block_kv=block_kv, interpret=interpret,
            block_kv_compute=block_kv_compute, segments=segments,
            loop_sweep=loop_sweep,
        )
    if fused:
        return _flash_bwd_fused(
            do, q, k, v, delta, lse, scale, spec,
            block_q=block_q, block_kv=block_kv, interpret=interpret,
            window=window, segments=segments,
        )

    # ---- dq ----
    q_map, kv_map, state_map = _make_index_maps(bq, bkv, nqb, nkb, group,
                                                wnd=window)
    state_block = pl.BlockSpec((1, 1, s_q // lp, lp), state_map)
    dq_in_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
        state_block,
        state_block,
    ]
    dq_inputs = [_spec_array(spec), do, q, k, v, _pack(delta, lp),
                 _pack(lse, lp)]
    if segments is not None:
        q_seg3 = jnp.asarray(segments[0], jnp.int32)[:, :, None]
        kv_seg3 = jnp.asarray(segments[1], jnp.int32)[:, None, :]
        dq_in_specs.append(pl.BlockSpec(
            (1, bq, 1), lambda b_, h, i, j, sp: (b_, q_map(b_, h, i, j, sp)[2], 0)))
        dq_in_specs.append(pl.BlockSpec(
            (1, 1, bkv), lambda b_, h, i, j, sp: (b_, 0, kv_map(b_, h, i, j, sp)[2])))
        dq_inputs += [q_seg3, kv_seg3]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, bq=bq, bkv=bkv, lp=lp, n_kv_blocks=nkb,
            wnd=window, seg=segments is not None,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, n, nqb, nkb),
            in_specs=dq_in_specs,
            out_specs=pl.BlockSpec((1, 1, bq, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, n, s_q, d), jnp.float32),
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dq_inputs)

    # ---- dk/dv ----
    def qh_of(h, t):
        return h * group + t // nqb

    def bq_map(b_, h, j, t, sp):
        iq = jnp.maximum(t % nqb, _q_imin(sp, j, bq, bkv, nqb))
        if window is not None:
            iq = jnp.minimum(iq, _q_imax(sp, j, bq, bkv, nqb, window))
        return (b_, qh_of(h, t), iq, 0)

    def bstate_map(b_, h, j, t, sp):
        return (b_, qh_of(h, t), 0, 0)

    def bkv_map(b_, h, j, t, sp):
        return (b_, h, j, 0)

    bstate_block = pl.BlockSpec((1, 1, s_q // lp, lp), bstate_map)
    dkdv_in_specs = [
        pl.BlockSpec((1, 1, bq, d), bq_map),
        pl.BlockSpec((1, 1, bq, d), bq_map),
        pl.BlockSpec((1, 1, bkv, d), bkv_map),
        pl.BlockSpec((1, 1, bkv, d), bkv_map),
        bstate_block,
        bstate_block,
    ]
    dkdv_inputs = [_spec_array(spec), do, q, k, v, _pack(delta, lp),
                   _pack(lse, lp)]
    if segments is not None:
        dkdv_in_specs.append(pl.BlockSpec(
            (1, bq, 1),
            lambda b_, h, j, t, sp: (b_, bq_map(b_, h, j, t, sp)[2], 0)))
        dkdv_in_specs.append(pl.BlockSpec(
            (1, 1, bkv), lambda b_, h, j, t, sp: (b_, 0, j)))
        dkdv_inputs += [q_seg3, kv_seg3]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkdv_kernel, scale=scale, bq=bq, bkv=bkv, lp=lp,
            n_q_blocks=nqb, group=group, wnd=window,
            seg=segments is not None,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, n_kv, nkb, nqb * group),
            in_specs=dkdv_in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, bkv, d), bkv_map),
                pl.BlockSpec((1, 1, bkv, d), bkv_map),
            ],
            scratch_shapes=[
                pltpu.VMEM((bkv, d), jnp.float32),
                pltpu.VMEM((bkv, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, n_kv, s_kv, d), jnp.float32),
            jax.ShapeDtypeStruct((b, n_kv, s_kv, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dkdv_inputs)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# single-device flash attention (the "flash" benchmark baseline, and a
# standalone fused attention op — reference role: flash_attn_func on one GPU,
# test/test_burst.py:175-184)


def flash_attention(q, k, v, scale=None, causal=False, block_q=None, block_kv=None,
                    block_q_bwd=None, block_kv_bwd=None, block_kv_compute=None,
                    window=None, segment_ids=None):
    """Fused single-device flash attention.  q,k,v [B,N,S,D] -> o [B,N,S,D].

    Block sizes default per TPU generation from ops/tuning.py (v5e measured
    optimum: fwd 2048x2048 with 1024-wide compute sub-blocks, fused backward
    1024x2048); the bwd blocks never default larger than the fwd blocks so a
    caller who shrinks the fwd blocks for VMEM keeps that budget in bwd.
    block_kv_compute splits the fwd kv memory block into compute sub-blocks
    (see flash_fwd).

    `window` (static int) enables sliding-window attention: each query
    attends to its last `window` positions (inclusive of itself); requires
    causal=True.  Both directions run BAND grids (fwd band_nb /
    bwd _bwd_fused_iq): the grid enumerates only blocks intersecting the
    band, so cost scales with window, not sequence.

    `segment_ids` [B, S] int32 (non-negative; negatives are reserved for
    internal padding) packs multiple documents into one row — attention
    never crosses a segment boundary.  Blocks wholly inside one segment
    keep the fast path; only boundary-straddling blocks pay for the id
    compare, in the forward and in the fused (tri or rect) backward
    alike."""
    if segment_ids is None:
        return _flash_attention_plain(q, k, v, scale, causal, block_q,
                                      block_kv, block_q_bwd, block_kv_bwd,
                                      block_kv_compute, window)
    return _flash_attention_seg(q, k, v, segment_ids, scale, causal, block_q,
                                block_kv, block_q_bwd, block_kv_bwd,
                                block_kv_compute, window)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash_attention_plain(q, k, v, scale=None, causal=False, block_q=None,
                           block_kv=None, block_q_bwd=None, block_kv_bwd=None,
                           block_kv_compute=None, window=None):
    o, _ = _flash_attention_fwd_impl(q, k, v, scale, causal, block_q, block_kv,
                                     block_kv_compute, window)
    return o


def _flash_attention_fwd_impl(q, k, v, scale, causal, block_q, block_kv,
                              block_kv_compute=None, window=None,
                              segment_ids=None):
    from .masks import round_spec

    b, n, s, d = q.shape
    if scale is None:
        scale = d**-0.5
    if window is not None and not causal:
        raise ValueError("window attention requires causal=True")
    block_q, block_kv, _, _, block_kv_compute = resolve_blocks(
        block_q, block_kv, block_kv_compute=block_kv_compute)
    # single-device: the windowed spec is the plain causal spec (delta = 0);
    # the static `window` is what narrows the band
    spec = round_spec(jnp.int32(0), jnp.int32(0), s, k.shape[2], causal, "contig")
    segs = None if segment_ids is None else (segment_ids, segment_ids)
    # m = lse = acc = None: statically-empty carry — no zeros materialization,
    # no acc-in DMA (see flash_fwd docstring)
    _, lse, o = flash_fwd(
        q, k, v, None, None, None, scale, spec, block_q=block_q, block_kv=block_kv,
        block_kv_compute=block_kv_compute,
        # the spec here is statically known to be plain full-window causal,
        # exactly the triangular grid's precondition (tri declines windows;
        # segment masking composes with the tri grid — the in-kernel seg_ok
        # test just widens which blocks take the masked path).  emit_o fuses
        # the finalize into the kernel's last visit of each q block: no
        # one-round ring carry is needed here, so the raw f32 accumulator
        # never has to reach HBM
        triangular=causal, window=window, segments=segs, emit_o=True,
    )
    return o, lse


def _flash_attention_vjp_fwd(q, k, v, scale, causal, block_q, block_kv,
                             block_q_bwd, block_kv_bwd, block_kv_compute,
                             window):
    o, lse = _flash_attention_fwd_impl(q, k, v, scale, causal, block_q, block_kv,
                                       block_kv_compute, window)
    return o, (q, k, v, o, lse)


def _flash_attention_vjp_bwd(scale, causal, block_q, block_kv, block_q_bwd,
                             block_kv_bwd, block_kv_compute, window, res, do):
    from .masks import round_spec

    q, k, v, o, lse = res
    d = q.shape[-1]
    if scale is None:
        scale = d**-0.5
    _, _, block_q_bwd, block_kv_bwd, _ = resolve_blocks(
        block_q, block_kv, block_q_bwd, block_kv_bwd)
    spec = round_spec(jnp.int32(0), jnp.int32(0), q.shape[2], k.shape[2], causal, "contig")
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    dq, dk, dv = flash_bwd(
        do, q, k, v, delta, lse, scale, spec,
        block_q=block_q_bwd, block_kv=block_kv_bwd,
        # statically known plain full-window causal here (same as the fwd)
        triangular=causal, window=window,
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention_plain.defvjp(_flash_attention_vjp_fwd, _flash_attention_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash_attention_seg(q, k, v, segment_ids, scale=None, causal=False,
                         block_q=None, block_kv=None, block_q_bwd=None,
                         block_kv_bwd=None, block_kv_compute=None, window=None):
    o, _ = _flash_attention_fwd_impl(q, k, v, scale, causal, block_q, block_kv,
                                     block_kv_compute, window, segment_ids)
    return o


def _flash_attention_seg_vjp_fwd(q, k, v, segment_ids, scale, causal, block_q,
                                 block_kv, block_q_bwd, block_kv_bwd,
                                 block_kv_compute, window):
    o, lse = _flash_attention_fwd_impl(q, k, v, scale, causal, block_q,
                                       block_kv, block_kv_compute, window,
                                       segment_ids)
    return o, (q, k, v, segment_ids, o, lse)


def _flash_attention_seg_vjp_bwd(scale, causal, block_q, block_kv, block_q_bwd,
                                 block_kv_bwd, block_kv_compute, window, res,
                                 do):
    import numpy as np

    from .masks import round_spec

    q, k, v, segment_ids, o, lse = res
    d = q.shape[-1]
    if scale is None:
        scale = d**-0.5
    _, _, block_q_bwd, block_kv_bwd, _ = resolve_blocks(
        block_q, block_kv, block_q_bwd, block_kv_bwd)
    spec = round_spec(jnp.int32(0), jnp.int32(0), q.shape[2], k.shape[2],
                      causal, "contig")
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    dq, dk, dv = flash_bwd(
        do, q, k, v, delta, lse, scale, spec,
        # statically plain full-window causal; segments compose with the
        # tri bwd kernel (a window instead selects the banded fused sweep)
        triangular=causal, window=window,
        block_q=block_q_bwd, block_kv=block_kv_bwd,
        segments=(segment_ids, segment_ids),
    )
    # integer inputs carry symbolic-zero (float0) cotangents
    dseg = np.zeros(segment_ids.shape, dtype=jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dseg)


_flash_attention_seg.defvjp(_flash_attention_seg_vjp_fwd,
                            _flash_attention_seg_vjp_bwd)
