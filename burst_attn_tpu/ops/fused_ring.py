"""Fused on-device ring attention: the whole R-round forward ring in ONE
Pallas kernel, with neighbor KV rotation done by in-kernel inter-chip RDMA
(`pltpu.make_async_remote_copy`) instead of per-round `lax.ppermute`
collectives between per-round kernel launches.

Why.  The scan-based ring (parallel/burst._fwd_impl) realizes BurstAttention's
comm/compute overlap as "XLA hopefully schedules the async collective-permute
behind the next round's pallas_call" — every round pays a kernel relaunch plus
an XLA collective boundary, and the overlap is a compiler scheduling outcome,
not a property of the program.  Here the overlap is owned by the kernel by
construction.

Schedule IR.  Since the schedule-compiler refactor this kernel contains NO
topology logic of its own: it interprets a compiled `RingProgram`
(parallel/schedule.py), delivered as an int32 scalar-prefetch table whose
per-round rows say which (bank, slot) compute consumes, whether its recv
semaphores must be awaited, which send channels fire (src bank/slot, dst
slot), and the per-slot capacity-credit ops.  One kernel body therefore runs
every topology the compiler can emit:

  uni     the classic single ring (one slot bank, chunks travel W-1 cw hops)
  bidi    counter-rotating bidirectional ring: chunks for offsets
          1..ceil((W-1)/2) arrive clockwise, 1..floor((W-1)/2) counter-
          clockwise, interleaved — per-DIRECTION slot banks and DMA
          semaphores, both ICI directions carrying traffic concurrently,
          and every transfer gets TWO rounds of compute to hide under.
  double  the hierarchical double ring: the next cycle's base chunk leaves
          on the inter channel ONE FULL INTRA-CYCLE before its consume,
          into a dedicated prefetch bank — BurstAttention's signature
          trick, previously scan-only.  Runs on a two-axis
          ("inter", "intra") mesh or factored onto a flat ring axis.

Every program is simulation-proven by burstlint before trust (analysis/
oracle.verify_ring_program: delivery of the declared rotation, exactly-once
consumption, per-slot overwrite-before-read safety against a maximally-
ahead sender, prefetch distance >= one intra cycle).

Slot choreography per round (first grid step): wait the consume slot's recv
semaphores if the table says a chunk landed remotely, then start every
flagged channel send — the transfer is in flight for the entire round-r
compute sweep.  Capacity credits are PER SLOT (`free` is a semaphore array
per bank): a send whose dst slot is being reused takes that slot's credit;
the slot's last reader granted it to the bank's writer at its own round
end.  Multi-axis meshes are safe because every RDMA target is a full
LOGICAL device id computed from ALL mesh axis indices with only the ring
coordinate varied (parallel/ring.device_roles) — extra pp/tp/dp axes can
never alias ring traffic.

Compute path.  Per grid step (r, b, h, i) the kernel folds q-block i against
the WHOLE resident KV chunk: the chunk is copied HBM-slot -> VMEM once per
(round, batch, kv-head) and every q-block sweeps it from VMEM.  m/l row
stats live VMEM-resident for the entire kernel (packed [B, N, S/lp, lp]),
the [bq, D] f32 accumulator round-trips an HBM scratch between rounds with
the load overlapped, rounds merge split-k style.  Masks reuse the SAME
per-round `ops/masks.round_spec` scalars the scan ring computes — the
partition each round holds comes from the program's rotation schedule.

Interpret mode.  jax's dma_start discharge rule emulates remote copies over
a single named mesh axis, so THIS kernel — same banks, same compiled
schedule, same masks — runs on a simulated CPU mesh (tests/
test_fused_ring.py, tests/test_fused_topologies.py; double-ring schedules
run factored onto the flat axis there).  Remote semaphore signals are not
emulated, so the capacity handshake and the startup barrier are statically
gated on `interpret`; a TWO-axis mesh cannot be discharged at all, which is
why `supported` declines multi-axis/two-axis-double configs in interpret
mode only — on hardware they run fused.

The BACKWARD has its own kernel (ops/fused_ring_bwd.py) interpreting the
compiled backward program, gated by the same predicate with pass_="bwd".
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .masks import live_round_prefix, round_spec, spec_live, spec_pair_count
from .pallas_flash import (
    LN2,
    LOG2E,
    NEG_INF,
    VMEM_LIMIT,
    _block_full,
    _block_has_work,
    _block_mask,
    _pick_block,
    _seg_uniform_eq,
    _spec_array,
    _unpack,
)
from .tuning import resolve_fused
from ..parallel import schedule as sched_ir
from ..parallel.ring import device_roles, ring_coords, wire_quantize
from ..utils.compat import axis_size, tpu_compiler_params

# barrier-semaphore namespace for the startup neighbor barrier; any stable
# id distinct from other collective pallas kernels in the same program works
_COLLECTIVE_ID = 13


def interpret_enabled() -> bool:
    """BURST_FUSED_INTERPRET=1 lets `backend="fused_ring"` run the REAL fused
    kernel under the pallas interpreter off-TPU (the parity tests set this);
    default off-TPU behavior is the scan-ring fallback, because the
    interpreted ring is orders of magnitude slower than the jnp scan path."""
    return os.environ.get("BURST_FUSED_INTERPRET", "").strip().lower() not in (
        "", "0", "false")


def hw_trace_forced() -> bool:
    """BURST_FUSED_ASSUME_TPU=1 makes the dispatch/kernels TRACE the
    hardware program off-TPU (full semaphore choreography, no interpret
    gate) — for burstlint's structural checks of topologies the interpret
    discharge cannot execute (two-axis double rings, multi-axis meshes).
    Tracing never runs the program; executing such a trace off-TPU fails."""
    return os.environ.get("BURST_FUSED_ASSUME_TPU", "").strip().lower() not in (
        "", "0", "false")


def _extra_named_axes(intra_axis: str, inter_axis=None):
    """Other size>1 named axes bound in the current trace (shard_map scope).

    Ring traffic addresses neighbors by LOGICAL device id; with extra
    partitioned axes that id must be computed from every axis index
    (parallel/ring.device_roles), which needs the mesh's axis order — so
    the gate below requires `cfg.mesh_axes` whenever this returns a
    non-empty list.  Returns None when the axis-env API is unavailable
    (reported as its own distinct reason, not as a multi-axis decline)."""
    try:
        from jax._src.core import get_axis_env

        sizes = dict(get_axis_env().axis_sizes)
    except Exception:  # noqa: BLE001 — private-API probe; absence != error
        return None
    skip = {intra_axis, inter_axis}
    return [a for a, sz in sizes.items()
            if a is not None and a not in skip and sz and sz > 1]


def resolve_topology(cfg, n_intra: int, n_inter: int = 1):
    """(topology, n_inter, n_intra) the fused kernels will run for cfg.

    A real inter axis (or cfg.fused_seq_factor on a flat ring) selects the
    double ring; `fused_topology="bidi"` opts the flat ring into the
    counter-rotating schedule (worlds < 3 degrade to uni — there is no
    second direction to use); default is uni."""
    if cfg.fused_seq_factor is not None:
        f_i, f_s = cfg.fused_seq_factor
        if n_inter > 1:
            raise ValueError("fused_seq_factor is for flat ring axes; this "
                             "config already has an inter axis")
        if f_i * f_s != n_intra:
            raise ValueError(
                f"fused_seq_factor {cfg.fused_seq_factor} does not tile the "
                f"ring axis ({n_intra} devices)")
        return ("double", f_i, f_s) if f_i > 1 else ("uni", 1, n_intra)
    if n_inter > 1:
        return "double", n_inter, n_intra
    topo = cfg.fused_topology
    if topo in ("auto", "uni"):
        return "uni", 1, n_intra
    if topo == "bidi":
        return ("bidi" if n_intra >= 3 else "uni"), 1, n_intra
    if topo == "double":
        # double requested without an inter axis or factor: nothing to nest
        return "uni", 1, n_intra
    raise ValueError(f"unknown fused_topology {topo!r}")


def occupancy_r_live(cfg, world: int, s):
    """Static live-round prefix the occupancy compiler should truncate the
    schedule to, or None for a dense program.  Windowed and length-bounded
    packed-segment contig-causal rings have a closed-form live set
    {0..r_live-1} (masks.live_round_prefix); handing it to the schedule
    compiler ELIDES the dead rounds outright — no RDMA, no KV sweep, no
    slot traffic.  `s` is the per-shard sequence length (None when the
    caller has no shape in hand, e.g. a shape-free structural probe)."""
    seg_l = getattr(cfg, "max_segment_len", None)
    if s is None or (cfg.window is None and seg_l is None):
        return None
    r_live = live_round_prefix(cfg.layout, s, world, causal=cfg.causal,
                               window=cfg.window, max_segment_len=seg_l)
    return None if r_live >= world else r_live


def _compile_for(cfg, topology: str, n_inter: int, n_intra: int,
                 pass_: str = "fwd", s=None):
    rf = resolve_fused(cfg.fused_block_q, cfg.fused_block_kv,
                       cfg.fused_kv_slots,
                       block_q_bwd=getattr(cfg, "fused_block_q_bwd", None),
                       block_kv_bwd=getattr(cfg, "fused_block_kv_bwd", None),
                       bwd_slots=getattr(cfg, "fused_bwd_slots", None),
                       ccw_slots=getattr(cfg, "fused_ccw_slots", None),
                       bwd_ccw_slots=getattr(cfg, "fused_bwd_ccw_slots",
                                             None),
                       wire_dtype=getattr(cfg, "wire_dtype", None))
    r_live = occupancy_r_live(cfg, n_inter * n_intra, s)
    # the program carries the wire dtype so expected_remote_dma and the
    # byte accounting describe the SAME transfers the kernels emit (each
    # quantized operand send fires a second remote copy for its scale)
    if pass_ == "fwd":
        return sched_ir.compile_fwd(topology, n_intra, n_inter,
                                    slots=rf.kv_slots, slots1=rf.ccw_slots,
                                    r_live=r_live, wire=rf.wire_dtype)
    return sched_ir.compile_bwd(topology, n_intra, n_inter,
                                slots=rf.bwd_slots, slots1=rf.bwd_ccw_slots,
                                dq_slots=rf.bwd_slots, r_live=r_live,
                                wire=rf.wire_dtype)


def supported(cfg, q_shape, k_shape, has_segments: bool, *,
              interpret=None, world=None, extra_axes=None, n_inter=None,
              pass_="fwd"):
    """None if the fused ring can run this config, else a reason string the
    dispatch logs / the tests assert on.  By default must be called at
    trace time (inside shard_map) — the axis-env and mesh-size probes read
    the trace context.  Passing `world` (ring axis size), `n_inter`
    (inter axis size) and `extra_axes` (other partitioned mesh axes)
    explicitly makes the predicate host-callable with PER-SHARD shapes:
    the obs dispatch instrumentation (parallel/burst._note_dispatch)
    evaluates the same gate the traced dispatch runs, so the
    `burst.dispatch`/`burst.fused_fallback` counters cannot drift from the
    real decision logic.

    `pass_` ("fwd" | "bwd") selects which kernel's gate to evaluate: the
    structural constraints are shared, but each pass has its own blocks and
    VMEM plan, so a shard can be fused in one pass and fall back in the
    other — parallel/burst._bwd_impl runs this with pass_="bwd" at its
    single dispatch point."""
    if pass_ not in ("fwd", "bwd"):
        raise ValueError(f"pass_ must be 'fwd' or 'bwd', got {pass_!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu" and not hw_trace_forced()
    if interpret and not interpret_enabled():
        return "off-TPU (set BURST_FUSED_INTERPRET=1 to run interpreted)"
    # sliding window and packed segments are fused configs since the
    # occupancy compiler: the window is a static band the sweeps predicate
    # on, segment ids ride a gathered side table, and dead rounds are
    # ELIDED from the program — the only windowed decline left is the
    # degenerate r_live == 1 bwd (via the schedule-compiler probe below)
    b, n, s, d = q_shape
    if k_shape[2] != s:
        return "cross-attention shard lengths"
    if world is None:
        world = axis_size(cfg.intra_axis)
    if n_inter is None:
        if cfg.inter_axis is None:
            n_inter = 1
        else:
            try:
                n_inter = axis_size(cfg.inter_axis)
            except Exception:  # noqa: BLE001 — axis not bound in this trace
                return (f"double ring inter axis {cfg.inter_axis!r} is not "
                        "bound in this trace")
    if world * n_inter < 2:
        return "world < 2 (nothing to rotate)"
    try:
        topology, t_inter, t_intra = resolve_topology(cfg, world, n_inter)
    except ValueError as e:
        return f"topology config invalid: {e}"
    if interpret and cfg.inter_axis is not None and n_inter > 1:
        # jax's dma_start discharge emulates a single named axis only; the
        # two-axis double ring runs fused on hardware (or factored onto a
        # flat axis in tests) but must decline under emulation
        return ("interpret-mode remote DMA is single-axis (two-axis double "
                "ring runs fused on hardware)")
    extra = _extra_named_axes(cfg.intra_axis, cfg.inter_axis) \
        if extra_axes is None else list(extra_axes)
    if extra is None:
        # distinct from the multi-axis decline: the axis env could not be
        # probed at all, so ring isolation is unprovable — misattributing
        # this as "multi-axis" would skew the fallback counters
        return "axis env unavailable (cannot prove ring isolation)"
    if extra:
        if interpret:
            return ("interpret-mode remote DMA is single-axis (multi-axis "
                    "mesh runs fused on hardware)")
        mesh_names = {a for a, _ in (cfg.mesh_axes or ())}
        missing = [a for a in extra if a not in mesh_names]
        if missing:
            return (f"ring axis must be the only partitioned axis in scope "
                    f"(found {extra}; pass mesh_axes via burst_attn to "
                    "prove ring isolation)")
    try:
        prog = _compile_for(cfg, topology, t_inter, t_intra, pass_, s=s)
    except sched_ir.ScheduleError as e:
        return f"schedule compiler declined: {e}"
    rf = resolve_fused(cfg.fused_block_q, cfg.fused_block_kv,
                       cfg.fused_kv_slots,
                       block_q_bwd=getattr(cfg, "fused_block_q_bwd", None),
                       block_kv_bwd=getattr(cfg, "fused_block_kv_bwd", None),
                       bwd_slots=getattr(cfg, "fused_bwd_slots", None),
                       ccw_slots=getattr(cfg, "fused_ccw_slots", None),
                       bwd_ccw_slots=getattr(cfg, "fused_bwd_ccw_slots",
                                             None),
                       wire_dtype=getattr(cfg, "wire_dtype", None))
    del prog
    wi = rf.wire_itemsize  # rotating-payload tiles: 1 B/elem when quantized
    if pass_ == "bwd":
        # VMEM plan, bwd roles: resident k+v chunk, fp32 dk/dv accumulators,
        # the per-step bundle tiles (q, do, delta|o, lse, arriving dq, local
        # dq, inter-held dq) — 4-byte worst case (rotating tiles priced at
        # the wire itemsize), so an oversized shard falls back instead of
        # failing Mosaic allocation mid-ring
        bqb = _pick_block(s, rf.block_q_bwd)
        vmem = 2 * s * d * 4 + 2 * s * d * 4 + 3 * bqb * d * wi \
            + 4 * bqb * d * 4
        if vmem > rf.vmem_budget:
            return (f"VMEM plan {vmem} bytes exceeds fused budget "
                    f"{rf.vmem_budget} (bwd)")
        return None
    # VMEM plan: resident k+v chunk (wire itemsize — they arrive over the
    # ring), packed m/l stats, acc staging — counted against the
    # per-generation budget so an oversized shard falls back instead of
    # failing Mosaic allocation mid-ring
    bq = _pick_block(s, rf.block_q)
    vmem = 2 * s * d * wi + 2 * b * n * s * 4 + 3 * bq * d * 4
    if vmem > rf.vmem_budget:
        return (f"VMEM plan {vmem} bytes exceeds fused budget "
                f"{rf.vmem_budget}")
    return None


# ---------------------------------------------------------------------------
# packed m/l stats access ([B, N, S/lp, lp] refs — pallas_flash's packed
# layout with explicit (batch, head) indices instead of pre-blocked refs)


def _stat_read(ref, b_, h, i, bq, lp):
    """Rows [i*bq, (i+1)*bq) of a packed [B, N, S/lp, lp] stats ref -> (bq, 1)."""
    rows = bq // lp
    pack = ref[b_, h, pl.ds(i * rows, rows), :]
    if lp == 1:
        return pack
    rep = jnp.repeat(pack, lp, axis=0)  # (bq, lp); row t = pack[t // lp]
    t_lane = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 0) % lp
    c_idx = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 1)
    return jnp.sum(jnp.where(t_lane == c_idx, rep, 0.0), axis=1, keepdims=True)


def _stat_write(ref, b_, h, i, col, bq, lp):
    rows = bq // lp
    ref[b_, h, pl.ds(i * rows, rows), :] = jnp.reshape(col, (rows, lp))


def dma_sem_wait(sem_view, ref):
    """Retire one completed DMA on a DMA semaphore: `tpu.wait_dma` with the
    transfer-sized ref (descriptor form — `pltpu.semaphore_wait` only
    admits REGULAR/barrier semaphore avals at trace time, so a DMA-sem
    wait spelled that way traces under the interpreter's int16 stand-in
    but fails the hardware trace; burstlint's BURST_FUSED_ASSUME_TPU
    census caught exactly that).  The ref must cover the same elements as
    the transfer(s) being retired — wait_dma blocks until the semaphore
    holds the ref's size, then decrements by it, which is also what the
    interpret discharge rules do (dma_start adds sizes, dma_wait
    subtracts them)."""
    pltpu.make_async_copy(ref, ref, sem_view).wait()


# ---------------------------------------------------------------------------
# static program views the kernel codegen branches on


def kernel_statics(prog):
    """The compiled program's static structure: which banks are consumed,
    which channels send (and from which src banks), where credits flow.
    Python-level — this decides which code the kernel EMITS, so the traced
    program (and burstlint's remote-DMA census, schedule.expected_remote_
    dma) is a function of the program alone."""
    rows = prog.rows
    R = prog.n_rounds
    consume_banks = tuple(sorted({rows["consume_bank"][r] for r in range(R)}))
    ch_active = tuple(ch for ch in range(prog.n_banks)
                      if any(rows[f"send{ch}"][r] for r in range(R)))
    src_banks0 = tuple(sorted({rows["src_bank0"][r] for r in range(R)
                               if rows["send0"][r]})) or (0,)
    grant_banks = tuple(b for b in range(prog.n_banks)
                        if any(rows[f"grant{b}"][r] for r in range(R)))
    take_chs = tuple(ch for ch in ch_active
                     if any(rows[f"take{ch}"][r] for r in range(R)))
    return dict(consume_banks=consume_banks, ch_active=ch_active,
                src_banks0=src_banks0, grant_banks=grant_banks,
                take_chs=take_chs)


_SENDC = {0: (sched_ir.SEND0, sched_ir.SRC_SLOT0, sched_ir.DST_SLOT0,
              sched_ir.TAKE0, sched_ir.META_CH0_DST),
          1: (sched_ir.SEND1, sched_ir.SRC_SLOT1, sched_ir.DST_SLOT1,
              sched_ir.TAKE1, sched_ir.META_CH1_DST)}
_GRANTC = {0: (sched_ir.GRANT0, sched_ir.META_CH0_SRC),
           1: (sched_ir.GRANT1, sched_ir.META_CH1_SRC)}


# ---------------------------------------------------------------------------
# kernel


def _fused_fwd_kernel(
    sched_ref,
    q_ref, k_hbm, v_hbm,
    *rest,
    prog, statics, scale, bq, bkv, lp, nqb, nkb, group, n_b, n_h, hw_sync,
    collect, wnd, has_seg, wire,
):
    """One grid step = q-block i of head h, batch b_, ring round r.

    sched_ref is the [R + 1, FWD_COLS] prefetch table: rows 0..R-1 hold the
    per-round mask scalars (cols 0..4, ops/masks.round_spec) plus the
    compiled program's op columns (parallel/schedule.py col constants);
    row R holds the traced neighbor ids (META_* slots).

    `collect` (static) appends one more OUTPUT before the scratch refs: a
    [n_banks, max_slots] int32 SMEM array counting, per (bank, slot), how
    many rounds consumed a chunk there — the devstats slot-reuse counter
    with its per-direction rows (obs/devstats.py, dir=cw|ccw labels).
    Pure scalar writes at round boundaries; the compute/DMA choreography
    is untouched, so stats-off and stats-on kernels produce bit-identical
    o/lse.

    Semaphore ledger (everything drains to zero; DMA sems count transfer
    sizes — dma_sem_wait retires a slot-sized transfer):
      krecv/vrecv[bank][slot]  +1 transfer per arriving send, -1 at the
                               consuming round's first grid step
      ksend/vsend[bank][slot]  +1 transfer per outgoing send (by dst
                               slot), -1 at the same round's last grid
                               step (drain)
      free[bank][slot] (hw)    per-SLOT capacity credit: the slot's last
                               reader signals the bank's writer (GRANT
                               column = slot + 1); a send whose TAKE flag
                               is set waits its dst slot's credit first.
                               Grants emitted == takes consumed, per slot
                               (compiler-checked, oracle-proven).
    """
    R = prog.n_rounds
    n_banks = prog.n_banks
    rest = list(rest)
    # remaining positional refs: [kscale, vscale] inputs when wire, [segq,
    # sega] inputs when has_seg, then the two outputs, the optional stats
    # output, then the scratch refs
    if wire is not None:
        ksc_hbm = rest.pop(0)    # [B, Nk, 1, 1] f32 per-chunk scales
        vsc_hbm = rest.pop(0)
    if has_seg:
        segq_ref = rest.pop(0)   # [1, s, 1] VMEM block: LOCAL segment ids
        sega_hbm = rest.pop(0)   # [B, world, 1, s] ANY: every shard's ids
    o_ref = rest.pop(0)
    lse_ref = rest.pop(0)
    if collect:
        slot_use_ref = rest.pop(0)
    kbufs, vbufs = [], []
    for _ in range(n_banks):
        kbufs.append(rest.pop(0))
        vbufs.append(rest.pop(0))
    kscbufs, vscbufs = [], []
    if wire is not None:
        # fp32 scale sub-banks: same slot indices, same send/recv
        # semaphores and capacity credits as the payload banks they scale —
        # the schedule grows no new columns for them
        for _ in range(n_banks):
            kscbufs.append(rest.pop(0))
            vscbufs.append(rest.pop(0))
    kchunk = rest.pop(0)
    vchunk = rest.pop(0)
    if wire is not None:
        ksc_t = rest.pop(0)      # VMEM (1, 1) f32 per-chunk scale tiles
        vsc_t = rest.pop(0)
    (mstat, lstat, accbuf, acc_in, acc_scr, m_sw, l_sw,
     cp_sem, chunk_sem, acc_sem) = rest[:10]
    rest = rest[10:]
    ksend, krecv, vsend, vrecv, free = [], [], [], [], []
    for _ in range(n_banks):
        ksend.append(rest.pop(0))
        krecv.append(rest.pop(0))
        vsend.append(rest.pop(0))
        vrecv.append(rest.pop(0))
        free.append(rest.pop(0))
    if has_seg:
        segbuf = rest.pop(0)     # VMEM (1, s) int32: this round's kv ids
        seg_sem = rest.pop(0)

    r = pl.program_id(0)
    b_ = pl.program_id(1)
    h = pl.program_id(2)
    i = pl.program_id(3)
    bank = sched_ref[r, sched_ir.CONSUME_BANK]
    slot = sched_ref[r, sched_ir.CONSUME_SLOT]
    first_of_round = (b_ == 0) & (h == 0) & (i == 0)
    last_of_round = (b_ == n_b - 1) & (h == n_h - 1) & (i == nqb - 1)

    if collect:
        @pl.when(first_of_round)
        def _slot_tally():
            # devstats slot-reuse counter: zero once at round 0, then one
            # scalar SMEM increment per round for the (bank, slot) consumed
            @pl.when(r == 0)
            def _zero():
                for bb in range(slot_use_ref.shape[0]):
                    for j in range(slot_use_ref.shape[1]):
                        slot_use_ref[bb, j] = 0

            slot_use_ref[bank, slot] = slot_use_ref[bank, slot] + 1

    # ---- round choreography (first grid step of the round only) ----
    @pl.when(first_of_round & (r == 0))
    def _copy_in():
        # local chunk -> its program-designated slot(s): one HBM->HBM copy
        # per bank the schedule launches from, so every later round
        # (compute reads, RDMA sends) addresses the banks uniformly
        cps = []
        per = 2 if wire is None else 4
        for idx, (cb, cslot) in enumerate(prog.copy_in):
            cps.append(pltpu.make_async_copy(k_hbm, kbufs[cb].at[cslot],
                                             cp_sem.at[per * idx]))
            cps.append(pltpu.make_async_copy(v_hbm, vbufs[cb].at[cslot],
                                             cp_sem.at[per * idx + 1]))
            if wire is not None:
                cps.append(pltpu.make_async_copy(
                    ksc_hbm, kscbufs[cb].at[cslot], cp_sem.at[per * idx + 2]))
                cps.append(pltpu.make_async_copy(
                    vsc_hbm, vscbufs[cb].at[cslot], cp_sem.at[per * idx + 3]))
        for c in cps:
            c.start()
        for c in cps:
            c.wait()

    if hw_sync:
        @pl.when(first_of_round & (r == 0))
        def _barrier():
            # every RDMA peer must have entered the kernel (buffers live)
            # before any send targets its slots
            bar = pltpu.get_barrier_semaphore()
            n_sig = 0
            for ch in statics["ch_active"]:
                _, _, _, _, meta_dst = _SENDC[ch]
                pltpu.semaphore_signal(
                    bar, inc=1, device_id=sched_ref[R, meta_dst],
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                pltpu.semaphore_signal(
                    bar, inc=1, device_id=sched_ref[R, _GRANTC[ch][1]],
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                n_sig += 2
            pltpu.semaphore_wait(bar, n_sig)

    @pl.when(first_of_round & (sched_ref[r, sched_ir.RECV] == 1))
    def _recv_wait():
        # round r's chunk must have LANDED in its slot before compute or
        # the onward send may read it
        for b in statics["consume_banks"]:
            @pl.when(bank == b)
            def _wait_bank(b=b):
                dma_sem_wait(krecv[b].at[slot], kbufs[b].at[slot])
                dma_sem_wait(vrecv[b].at[slot], vbufs[b].at[slot])
                if wire is not None:
                    # the scale sub-payloads ride the SAME recv semaphores:
                    # retire the payload-sized transfer first, then the
                    # scale-sized one — the sem drains to zero either way
                    dma_sem_wait(krecv[b].at[slot], kscbufs[b].at[slot])
                    dma_sem_wait(vrecv[b].at[slot], vscbufs[b].at[slot])

    for ch in statics["ch_active"]:
        send_c, src_c, dst_c, take_c, meta_dst = _SENDC[ch]

        @pl.when(first_of_round & (sched_ref[r, send_c] == 1))
        def _send_onward(ch=ch, send_c=send_c, src_c=src_c, dst_c=dst_c,
                         take_c=take_c, meta_dst=meta_dst):
            dst_slot = sched_ref[r, dst_c]
            src_slot = sched_ref[r, src_c]
            dst_dev = sched_ref[R, meta_dst]
            if hw_sync and ch in statics["take_chs"]:
                @pl.when(sched_ref[r, take_c] == 1)
                def _capacity():
                    # dst slot is being reused: take ITS credit, granted by
                    # the receiver after the slot's previous last read
                    pltpu.semaphore_wait(free[ch].at[dst_slot], 1)

            def _emit(sb):
                sk = pltpu.make_async_remote_copy(
                    src_ref=kbufs[sb].at[src_slot],
                    dst_ref=kbufs[ch].at[dst_slot],
                    send_sem=ksend[ch].at[dst_slot],
                    recv_sem=krecv[ch].at[dst_slot],
                    device_id=dst_dev,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                sv = pltpu.make_async_remote_copy(
                    src_ref=vbufs[sb].at[src_slot],
                    dst_ref=vbufs[ch].at[dst_slot],
                    send_sem=vsend[ch].at[dst_slot],
                    recv_sem=vrecv[ch].at[dst_slot],
                    device_id=dst_dev,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                sk.start()
                sv.start()
                if wire is not None:
                    # quantize-on-send is free here — the payload banks hold
                    # wire-dtype data end to end; each operand's scale rides
                    # as a second remote copy on the SAME sem pair
                    ssk = pltpu.make_async_remote_copy(
                        src_ref=kscbufs[sb].at[src_slot],
                        dst_ref=kscbufs[ch].at[dst_slot],
                        send_sem=ksend[ch].at[dst_slot],
                        recv_sem=krecv[ch].at[dst_slot],
                        device_id=dst_dev,
                        device_id_type=pltpu.DeviceIdType.LOGICAL)
                    ssv = pltpu.make_async_remote_copy(
                        src_ref=vscbufs[sb].at[src_slot],
                        dst_ref=vscbufs[ch].at[dst_slot],
                        send_sem=vsend[ch].at[dst_slot],
                        recv_sem=vrecv[ch].at[dst_slot],
                        device_id=dst_dev,
                        device_id_type=pltpu.DeviceIdType.LOGICAL)
                    ssk.start()
                    ssv.start()
                # no wait here: the transfer overlaps this whole round's
                # sweep; the drain wait sits at the round's LAST grid step

            src_banks = statics["src_banks0"] if ch == 0 else (1,)
            if len(src_banks) == 1:
                _emit(src_banks[0])
            else:
                for sb in src_banks:
                    pl.when(sched_ref[r, sched_ir.SRC_BANK0] == sb)(
                        functools.partial(_emit, sb))

    # ---- per-(round, batch, kv-head) chunk load: HBM slot -> VMEM ----
    @pl.when((i == 0) & (h % group == 0))
    def _chunk_load():
        kvh = h // group
        for b in statics["consume_banks"]:
            @pl.when(bank == b)
            def _load_bank(b=b):
                cps = [pltpu.make_async_copy(kbufs[b].at[slot, b_, kvh],
                                             kchunk, chunk_sem.at[0]),
                       pltpu.make_async_copy(vbufs[b].at[slot, b_, kvh],
                                             vchunk, chunk_sem.at[1])]
                if wire is not None:
                    cps.append(pltpu.make_async_copy(
                        kscbufs[b].at[slot, b_, kvh], ksc_t,
                        chunk_sem.at[2]))
                    cps.append(pltpu.make_async_copy(
                        vscbufs[b].at[slot, b_, kvh], vsc_t,
                        chunk_sem.at[3]))
                for c in cps:
                    c.start()
                for c in cps:
                    c.wait()

    # ---- per-(round, batch) segment-id row: gathered table -> VMEM ----
    if has_seg:
        @pl.when((i == 0) & (h == 0))
        def _seg_load():
            # the rotating side's partition (appended table column) selects
            # which shard's ids this round's kv chunk carries
            part = sched_ref[r, sched_ir.FWD_COLS]
            cp = pltpu.make_async_copy(sega_hbm.at[b_, part], segbuf,
                                       seg_sem.at[0])
            cp.start()
            cp.wait()

    # ---- start the acc carry load early: it overlaps the whole sweep ----
    @pl.when(r > 0)
    def _acc_load_start():
        pltpu.make_async_copy(accbuf.at[b_, h, i], acc_in,
                              acc_sem.at[0]).start()

    # ---- local online-softmax sweep over this round's chunk ----
    spec_r = tuple(sched_ref[r, c] for c in range(5))
    r0 = i * bq
    m_sw[:] = jnp.full_like(m_sw, NEG_INF)
    l_sw[:] = jnp.zeros_like(l_sw)
    acc_scr[:] = jnp.zeros_like(acc_scr)
    q_t = q_ref[0, 0, :, :] * (scale * LOG2E)

    def _fold(c0, mask):
        ks = kchunk[pl.ds(c0, bkv), :]
        if wire is not None:
            # in-tile rescale on consume (ops/ragged_paged.py's int8-pool
            # idiom): the wire-dtype tile is cast up and the per-chunk
            # scalar scale folds into the score AFTER the dot — never a
            # raw int8/fp8 operand into the MXU, never an unscaled value
            # into the fp32 accumulators
            ks = ks.astype(jnp.float32)
        s_t = jax.lax.dot_general(
            q_t, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if wire is not None:
            s_t = s_t * ksc_t[0, 0]
        if mask is not None:
            s_t = jnp.where(mask, s_t, NEG_INF)
        m_prev = m_sw[:]
        m_new = jnp.maximum(m_prev, jnp.max(s_t, axis=1, keepdims=True))
        alpha = jnp.where(m_prev >= m_new, 1.0, jnp.exp2(m_prev - m_new))
        p = jnp.exp2(s_t - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)  # all-masked-row nan guard
        l_sw[:] = l_sw[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_sw[:] = m_new
        if wire is None:
            pv = jax.lax.dot_general(
                p.astype(vchunk.dtype), vchunk[pl.ds(c0, bkv), :],
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        else:
            pv = jax.lax.dot_general(
                p, vchunk[pl.ds(c0, bkv), :].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * vsc_t[0, 0]
        acc_scr[:] = acc_scr[:] * alpha + pv

    segq = segq_ref[0, pl.ds(r0, bq), :] if has_seg else None   # (bq, 1)
    for j in range(nkb):
        c0 = j * bkv
        live = _block_has_work(spec_r, r0, c0, bq, bkv, wnd)
        full = _block_full(spec_r, r0, c0, bq, bkv, wnd)
        if has_seg:
            segk = segbuf[:, pl.ds(c0, bkv)]                    # (1, bkv)
            seg_pair = (segq, segk)
            # the fast path must also be single-segment-uniform: a
            # structurally-full block can still straddle a packing boundary
            fast = full & _seg_uniform_eq(segq, segk)
        else:
            seg_pair = None
            fast = full

        @pl.when(live & fast)
        def _fast(c0=c0):
            _fold(c0, None)

        @pl.when(live & ~fast)
        def _masked(c0=c0, seg_pair=seg_pair):
            _fold(c0, _block_mask(spec_r, r0, c0, bq, bkv, wnd,
                                  seg=seg_pair))

    # ---- merge with the carried state (split-k style combine) ----
    @pl.when(r == 0)
    def _init_state():
        # round 0 is always the self round: no carry, state = local sweep
        _stat_write(mstat, b_, h, i, m_sw[:], bq, lp)
        _stat_write(lstat, b_, h, i, l_sw[:], bq, lp)

    @pl.when(r > 0)
    def _merge():
        m1 = _stat_read(mstat, b_, h, i, bq, lp)
        l1 = _stat_read(lstat, b_, h, i, bq, lp)
        m2, l2 = m_sw[:], l_sw[:]
        m = jnp.maximum(m1, m2)
        a1 = jnp.where(m1 == NEG_INF, 0.0, jnp.exp2(m1 - m))
        a2 = jnp.where(m2 == NEG_INF, 0.0, jnp.exp2(m2 - m))
        pltpu.make_async_copy(accbuf.at[b_, h, i], acc_in,
                              acc_sem.at[0]).wait()
        acc_scr[:] = acc_in[:] * a1 + acc_scr[:] * a2
        _stat_write(mstat, b_, h, i, m, bq, lp)
        _stat_write(lstat, b_, h, i, l1 * a1 + l2 * a2, bq, lp)

    @pl.when(r < R - 1)
    def _acc_store():
        st = pltpu.make_async_copy(acc_scr, accbuf.at[b_, h, i],
                                   acc_sem.at[1])
        st.start()
        st.wait()

    @pl.when(r == R - 1)
    def _finalize():
        # fused finalize: o = acc / l in the caller's dtype; lse back to the
        # natural-log domain, packed rows into the resident lse out block
        m = _stat_read(mstat, b_, h, i, bq, lp)
        l = _stat_read(lstat, b_, h, i, bq, lp)
        o_ref[0, 0, :, :] = jnp.where(
            l > 0, acc_scr[:] / l, 0.0).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m * LN2 + jnp.log(l), NEG_INF)
        rows = bq // lp
        lse_ref[b_, h, pl.ds(i * rows, rows), :] = jnp.reshape(
            lse, (rows, lp))

    # ---- round epilogue (last grid step of the round only) ----
    for ch in statics["ch_active"]:
        send_c, _, dst_c, _, _ = _SENDC[ch]

        @pl.when(last_of_round & (sched_ref[r, send_c] == 1))
        def _send_drain(ch=ch, dst_c=dst_c):
            # our outgoing RDMA read its src slot; it must be out the door
            # before the writer may overwrite that slot (free credit below)
            # and before the kernel may exit with a live DMA
            dst_slot = sched_ref[r, dst_c]
            dma_sem_wait(ksend[ch].at[dst_slot], kbufs[ch].at[dst_slot])
            dma_sem_wait(vsend[ch].at[dst_slot], vbufs[ch].at[dst_slot])
            if wire is not None:
                dma_sem_wait(ksend[ch].at[dst_slot],
                             kscbufs[ch].at[dst_slot])
                dma_sem_wait(vsend[ch].at[dst_slot],
                             vscbufs[ch].at[dst_slot])

    if hw_sync:
        for b in statics["grant_banks"]:
            grant_c, meta_src = _GRANTC[b]

            @pl.when(last_of_round & (sched_ref[r, grant_c] > 0))
            def _grant_free(b=b, grant_c=grant_c, meta_src=meta_src):
                # the named slot has no further readers here — its writer
                # (the bank's upstream neighbor) may target it again
                pltpu.semaphore_signal(
                    free[b].at[sched_ref[r, grant_c] - 1], inc=1,
                    device_id=sched_ref[R, meta_src],
                    device_id_type=pltpu.DeviceIdType.LOGICAL)


# ---------------------------------------------------------------------------
# shard-level entry point


def build_sched_table(cfg, prog, s_q: int, s_kv: int, *, swap_roles=False,
                      with_part=False):
    """The [R + 1, cols] traced prefetch table for a compiled program:
    per-round mask-spec scalars (the partition each round holds comes from
    the program's rotation applied to this device's ring coordinates) next
    to the program's op columns, plus the META neighbor-id row from
    parallel/ring.device_roles.  `swap_roles` builds backward-orientation
    specs (the rotating payload is the q side, the resident chunk the kv
    side).  `with_part` appends one extra column holding the rotating
    side's PARTITION id per round — the packed-segment kernels use it to
    pick that round's segment-id row out of the gathered side table.
    Returns (table, specs) — the per-round MaskSpecs are reused for
    devstats occupancy tallies."""
    inter_rank, intra_rank, _, _ = ring_coords(
        cfg.intra_axis, cfg.inter_axis, cfg.fused_seq_factor)
    me_part = inter_rank * prog.n_intra + intra_rank
    op_table = prog.to_table()
    ncols = op_table.shape[1] + int(with_part)
    rows = []
    specs = []
    for r in range(prog.n_rounds):
        part_r = sched_ir.partition_for_round(prog, r, inter_rank,
                                              intra_rank)
        if swap_roles:
            sp = round_spec(part_r, me_part, s_q, s_kv, cfg.causal,
                            cfg.layout, window=cfg.window)
        else:
            sp = round_spec(me_part, part_r, s_q, s_kv, cfg.causal,
                            cfg.layout, window=cfg.window)
        specs.append(sp)
        row = [_spec_array(sp), jnp.asarray(op_table[r, 5:], jnp.int32)]
        if with_part:
            row.append(jnp.reshape(jnp.asarray(part_r, jnp.int32), (1,)))
        rows.append(jnp.concatenate(row))
    roles = device_roles(cfg.intra_axis, cfg.inter_axis,
                         mesh_axes=cfg.mesh_axes,
                         factor=cfg.fused_seq_factor,
                         home_offsets=prog.home_offsets)
    dirs = prog.channels
    meta = [roles["me"]]
    meta.append(roles[f"{dirs[0]}_dst"])
    meta.append(roles[f"{dirs[0]}_src"])
    if len(dirs) > 1:
        meta.append(roles[f"{dirs[1]}_dst"])
        meta.append(roles[f"{dirs[1]}_src"])
    else:
        meta += [jnp.int32(0), jnp.int32(0)]
    for j in range(2):
        meta.append(roles.get(f"home{j}", jnp.int32(0)))
    meta += [jnp.int32(0)] * (ncols - len(meta))
    rows.append(jnp.stack([jnp.asarray(x, jnp.int32) for x in meta]))
    return jnp.stack(rows), specs


def gather_seg_table(seg, cfg):
    """[B, world, 1, S] int32 side table of EVERY ring shard's segment ids,
    in partition order, from this shard's [B, S] local ids.  One all_gather
    at entry (ids are tiny next to KV) — the ring itself still moves zero
    XLA collectives; burstlint's zero-collective census counts ppermute/
    all_to_all, and the fused-path contract is "no per-round collectives",
    which a single O(S) prologue gather keeps.  Partition id ordering:
    inter-major (inter_rank * n_intra + intra_rank), which for both the
    flat and the factored ring equals the gather order (ring.ring_coords
    maps flat rank f to (f // n_s, f % n_s))."""
    x = jax.lax.all_gather(seg.astype(jnp.int32), cfg.intra_axis)
    if cfg.inter_axis is not None:
        x = jax.lax.all_gather(x, cfg.inter_axis)
        x = x.reshape((-1,) + x.shape[2:])
    x = jnp.moveaxis(x, 0, 1)          # [B, world, S]
    return x[:, :, None, :]


def fused_ring_fwd(q, k, v, cfg, *, seg=None, interpret=None,
                   collect_stats=False):
    """Forward burst attention on per-shard arrays via the fused ring kernel.

    Call inside shard_map on the ring axis (same contract as
    parallel/burst._fwd_impl): q [B, N, S, D], k/v [B, Nk, S, D] in layout
    order, `seg` [B, S] optional packed-segment ids (attention never
    crosses a segment boundary; ids are gathered ring-wide once at entry
    and each round's row rides the prefetch table's partition column).
    Returns (o [B, N, S, D] in q.dtype, lse [B, N, S] f32) — plus a
    per-shard obs.devstats.DevStats when `collect_stats`: mask occupancy and
    liveness are derived in-graph from the SAME sched-table specs the kernel
    masks by, per-(bank, slot) reuse counts come out of the kernel itself as
    an extra scalar (SMEM) output, and lse/o health is computed on the
    results.  The stats-off call emits the identical kernel (no extra
    output), so traces without stats are bit-identical to pre-devstats
    builds.  Callers must have checked `supported` first.
    """
    b, n, s, d = q.shape
    n_kv = k.shape[1]
    assert n % n_kv == 0, f"GQA needs Nq % Nk == 0, got {n} % {n_kv}"
    group = n // n_kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu" and not hw_trace_forced()
    scale = cfg.scale if cfg.scale is not None else d ** -0.5
    n_intra_ax = axis_size(cfg.intra_axis)
    n_inter_ax = (axis_size(cfg.inter_axis)
                  if cfg.inter_axis is not None else 1)
    topology, t_inter, t_intra = resolve_topology(cfg, n_intra_ax,
                                                  n_inter_ax)
    prog = _compile_for(cfg, topology, t_inter, t_intra, "fwd", s=s)
    statics = kernel_statics(prog)
    R = prog.n_rounds
    rf = resolve_fused(cfg.fused_block_q, cfg.fused_block_kv,
                       cfg.fused_kv_slots,
                       ccw_slots=getattr(cfg, "fused_ccw_slots", None),
                       wire_dtype=getattr(cfg, "wire_dtype", None))
    wire = rf.wire_dtype
    bq = _pick_block(s, rf.block_q)
    bkv = _pick_block(s, rf.block_kv)
    lp = _pick_block(bq, 128)
    nqb = s // bq
    nkb = s // bkv

    sched, specs = build_sched_table(cfg, prog, s, s,
                                     with_part=seg is not None)

    if wire is not None:
        # quantize ONCE on the host graph before the kernel: the payload
        # rotates unchanged, so pre-quantizing the local chunk == quantize-
        # on-send at every hop.  Per-(batch, kv-head) scalar scales travel
        # as fp32 sub-banks next to the wire-dtype slot banks.
        k_in, kscale = wire_quantize(k, wire, (2, 3))
        v_in, vscale = wire_quantize(v, wire, (2, 3))
    else:
        k_in, v_in = k, v

    kernel = functools.partial(
        _fused_fwd_kernel, prog=prog, statics=statics, scale=scale, bq=bq,
        bkv=bkv, lp=lp, nqb=nqb, nkb=nkb, group=group, n_b=b, n_h=n,
        hw_sync=not interpret, collect=collect_stats,
        wnd=cfg.window, has_seg=seg is not None, wire=wire,
    )

    def q_map(r, b_, h, i, sp):
        return (b_, h, i, 0)

    out_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        # whole-array resident block: written row-range-wise at the last
        # round, flushed once (block dims == array dims, always legal)
        pl.BlockSpec((b, n, s // lp, lp),
                     lambda r, b_, h, i, sp: (0, 0, 0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, n, s, d), q.dtype),
        jax.ShapeDtypeStruct((b, n, s // lp, lp), jnp.float32),
    ]
    max_slots = max(prog.slots)
    if collect_stats:
        # devstats slot-reuse counts: whole-array SMEM output, scalar writes
        # only at round boundaries (see _fused_fwd_kernel); one row per
        # bank/direction (dir=cw|ccw in the published counter)
        out_specs.append(
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.SMEM))
        out_shape.append(
            jax.ShapeDtypeStruct((prog.n_banks, max_slots), jnp.int32))

    scratch = []
    for bank in range(prog.n_banks):
        scratch.append(pltpu.ANY((prog.slots[bank], b, n_kv, s, d),
                                 k_in.dtype))
        scratch.append(pltpu.ANY((prog.slots[bank], b, n_kv, s, d),
                                 v_in.dtype))
    if wire is not None:
        for bank in range(prog.n_banks):
            # scale sub-banks: same slot layout, fp32, O(1) per chunk
            scratch.append(pltpu.ANY((prog.slots[bank], b, n_kv, 1, 1),
                                     jnp.float32))
            scratch.append(pltpu.ANY((prog.slots[bank], b, n_kv, 1, 1),
                                     jnp.float32))
    scratch += [
        pltpu.VMEM((s, d), k_in.dtype),               # kchunk
        pltpu.VMEM((s, d), v_in.dtype),               # vchunk
    ]
    if wire is not None:
        scratch += [
            pltpu.VMEM((1, 1), jnp.float32),          # ksc_t
            pltpu.VMEM((1, 1), jnp.float32),          # vsc_t
        ]
    scratch += [
        pltpu.VMEM((b, n, s // lp, lp), jnp.float32),  # mstat (base-2)
        pltpu.VMEM((b, n, s // lp, lp), jnp.float32),  # lstat (linear)
        pltpu.ANY((b, n, nqb, bq, d), jnp.float32),   # accbuf (carry)
        pltpu.VMEM((bq, d), jnp.float32),             # acc_in
        pltpu.VMEM((bq, d), jnp.float32),             # acc_scr
        pltpu.VMEM((bq, 1), jnp.float32),             # m_sw
        pltpu.VMEM((bq, 1), jnp.float32),             # l_sw
        pltpu.SemaphoreType.DMA(
            ((2 if wire is None else 4) * len(prog.copy_in),)),  # cp_sem
        pltpu.SemaphoreType.DMA((2 if wire is None else 4,)),  # chunk_sem
        pltpu.SemaphoreType.DMA((2,)),                # acc_sem
    ]
    for bank in range(prog.n_banks):
        scratch += [
            pltpu.SemaphoreType.DMA((prog.slots[bank],)),   # ksend[bank]
            pltpu.SemaphoreType.DMA((prog.slots[bank],)),   # krecv[bank]
            pltpu.SemaphoreType.DMA((prog.slots[bank],)),   # vsend[bank]
            pltpu.SemaphoreType.DMA((prog.slots[bank],)),   # vrecv[bank]
            pltpu.SemaphoreType.REGULAR((prog.slots[bank],)),  # free[bank]
        ]

    in_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
    ]
    inputs = [sched, q, k_in, v_in]
    if wire is not None:
        # per-block fp32 scales ride along as ANY inputs; the kernel pops
        # them right after k/v and copies them into the scale slot banks
        in_specs.append(pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY))
        inputs.append(kscale)
        inputs.append(vscale)
    if seg is not None:
        # local ids resident per batch; the gathered ring-wide table stays
        # in ANY space and the kernel pulls one partition's row per round
        in_specs.append(pl.BlockSpec((1, s, 1),
                                     lambda r, b_, h, i, sp: (b_, 0, 0)))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY))
        inputs.append(seg.astype(jnp.int32)[:, :, None])
        inputs.append(gather_seg_table(seg, cfg))
        scratch += [
            pltpu.VMEM((1, s), jnp.int32),       # segbuf
            pltpu.SemaphoreType.DMA((1,)),       # seg_sem
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(R, b, n, nqb),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # everything is sequential by construction: the ring choreography,
        # the VMEM-resident stats, and the acc carry all assume one core
        # walks the grid in order — a megacore split would race them
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("arbitrary",) * 4,
            collective_id=_COLLECTIVE_ID,
        ),
        interpret=interpret,
    )(*inputs)
    o, lse_packed = outs[0], outs[1]
    lse = _unpack(lse_packed)
    if not collect_stats:
        return o, lse
    from ..obs import devstats

    # occupancy/liveness from the SAME per-round specs the kernel masks by;
    # the fused ring executes every scheduled round (band-dead blocks are
    # in-kernel masked) and the occupancy compiler has already ELIDED the
    # fully-dead rounds — rounds_elided counts what never launched.
    # Segment occupancy is data-dependent and NOT in these tallies: pair
    # counts stay band-only (documented in docs/observability.md).
    pairs = sum(spec_pair_count(sp, s, s, window=cfg.window) for sp in specs)
    live = sum(spec_live(sp, cfg.window).astype(jnp.int32) for sp in specs)
    slot_use = outs[2]
    qam = 0.0
    if wire is not None:
        f32 = jnp.float32
        qam = jnp.maximum(jnp.max(jnp.abs(k.astype(f32))),
                          jnp.max(jnp.abs(v.astype(f32))))
    stats = devstats.ring_stats(
        rounds=R, rounds_live=live, attn_pairs=pairs,
        total_pairs=float(R) * s * s, head_dim=d,
        m=None,  # the running row max never leaves the kernel
        lse=lse, acc=o, fused_rounds=R, rounds_elided=prog.world - R,
        slot_use=slot_use[0],
        slot_use_ccw=slot_use[1] if prog.n_banks > 1 else None,
        quant_absmax=qam)
    return o, lse, stats
