"""Fused on-device ring attention: the whole W-round forward ring in ONE
Pallas kernel, with neighbor KV rotation done by in-kernel inter-chip RDMA
(`pltpu.make_async_remote_copy`) instead of per-round `lax.ppermute`
collectives between per-round kernel launches.

Why.  The scan-based ring (parallel/burst._fwd_impl) realizes BurstAttention's
comm/compute overlap as "XLA hopefully schedules the async collective-permute
behind the next round's pallas_call" — every round pays a kernel relaunch plus
an XLA collective boundary, and the overlap is a compiler scheduling outcome,
not a property of the program.  Here the overlap is owned by the kernel by
construction:

  * KV communication buffers are a rotating set of `slots` (>= 2, from the
    per-generation table in ops/tuning.py) HBM slots per operand; the slot a
    round reads and the slot a send writes come from ONE exported schedule
    (parallel/ring.fused_slot_schedule), delivered to the kernel via scalar
    prefetch — burstlint re-derives and proves that schedule independently
    (analysis/oracle.verify_fused_ring) and matches it against this module.
  * At the first grid step of round r the kernel waits the slot's recv
    semaphore (round r's chunk has LANDED), then immediately starts the RDMA
    of that chunk to the right neighbor's slot[r+1].  The transfer is in
    flight for the entire round-r compute sweep — one full round of
    FlashAttention tiles across every (batch, head, q-block) — before round
    r+1 waits on it.  No collective barrier ever splits the instruction
    stream.
  * Double-buffer safety is a semaphore protocol, not a compiler contract:
    DMA send/recv semaphores per slot, plus (hardware only) a capacity
    handshake — a device signals its LEFT neighbor's free semaphore when a
    slot's last reader is done, and a sender must take one free credit before
    overwriting a previously-used remote slot.  All semaphores provably
    drain to zero (counts are matched per round; see the choreography notes
    in _fused_fwd_kernel).

Compute path.  Per grid step (r, b, h, i) the kernel folds q-block i against
the WHOLE resident KV chunk: the chunk is copied HBM-slot -> VMEM once per
(round, batch, kv-head) and every q-block sweeps it from VMEM — KV streaming
traffic is per-chunk, not per-(q-block, kv-block) as in the scan path's
per-round grids.  The online-softmax state is split by size: m/l row stats
live VMEM-resident for the entire kernel (packed [B, N, S/lp, lp] exactly
like pallas_flash's packed-stats layout), while the [bq, D] f32 accumulator
round-trips an HBM scratch between rounds via manual async copies (the same
traffic the scan path pays implicitly via its m/lse/acc in/out operands).
Rounds merge by the standard two-state softmax combine (split-k style), so
the acc load overlaps the whole local sweep.

Interpret mode.  jax's dma_start discharge rule emulates remote copies over
a single named mesh axis, so THIS kernel — same slots, same schedule, same
masks — runs on a simulated CPU mesh (tests/test_fused_ring.py).  Remote
semaphore signals are not emulated, so the hardware-only capacity handshake
and the startup barrier are statically gated on `interpret` (in the
discharged program every copy lands synchronously at issue, so the hazards
those guards exist for cannot occur).

Supported: single ring (no inter axis), equal q/kv shard lengths, no sliding
window, no packed segments, world >= 2, ring axis the only size>1 named axis
in scope.  Everything else falls back to the scan ring in parallel/burst.py
(see `supported`).  The BACKWARD has its own fused kernel
(ops/fused_ring_bwd.py: the q-side bundle plus a concurrent dq ring rotate
while K, V stay resident), gated by the same predicate with pass_="bwd" —
configs either kernel declines take the scan ring for that pass only.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .masks import round_spec, spec_live, spec_pair_count
from .pallas_flash import (
    LN2,
    LOG2E,
    NEG_INF,
    VMEM_LIMIT,
    _block_full,
    _block_has_work,
    _block_mask,
    _pick_block,
    _spec_array,
    _unpack,
)
from .tuning import resolve_fused
from ..parallel.ring import (
    fused_slot_schedule,
    my_partition,
    neighbor_ids,
    partition_at_round,
)
from ..utils.compat import axis_size, tpu_compiler_params

# barrier-semaphore namespace for the startup neighbor barrier; any stable
# id distinct from other collective pallas kernels in the same program works
_COLLECTIVE_ID = 13


def interpret_enabled() -> bool:
    """BURST_FUSED_INTERPRET=1 lets `backend="fused_ring"` run the REAL fused
    kernel under the pallas interpreter off-TPU (the parity tests set this);
    default off-TPU behavior is the scan-ring fallback, because the
    interpreted ring is orders of magnitude slower than the jnp scan path."""
    return os.environ.get("BURST_FUSED_INTERPRET", "").strip().lower() not in (
        "", "0", "false")


def _extra_named_axes(intra_axis: str):
    """Other size>1 named axes bound in the current trace (shard_map scope).

    The kernel addresses its neighbor by LOGICAL device id computed from the
    ring axis index alone, which is only the right address when the ring
    axis is the sole partitioned axis; jax's interpret-mode DMA discharge
    has the same single-axis restriction.  Returns None when the axis-env
    API is unavailable (treated as unknown -> unsupported, fail safe)."""
    try:
        from jax._src.core import get_axis_env

        sizes = dict(get_axis_env().axis_sizes)
    except Exception:  # noqa: BLE001 — private-API probe; absence != error
        return None
    return [a for a, sz in sizes.items()
            if a is not None and a != intra_axis and sz and sz > 1]


def supported(cfg, q_shape, k_shape, has_segments: bool, *,
              interpret=None, world=None, extra_axes=None, pass_="fwd"):
    """None if the fused ring can run this config, else a reason string the
    dispatch logs / the tests assert on.  By default must be called at
    trace time (inside shard_map) — the axis-env and mesh-size probes read
    the trace context.  Passing `world` (ring axis size) and `extra_axes`
    (other partitioned mesh axes) explicitly makes the predicate host-
    callable with PER-SHARD shapes: the obs dispatch instrumentation
    (parallel/burst._note_dispatch) evaluates the same gate the traced
    dispatch runs, so the `burst.dispatch`/`burst.fused_fallback` counters
    cannot drift from the real decision logic.

    `pass_` ("fwd" | "bwd") selects which kernel's gate to evaluate: the
    structural constraints are shared, but each pass has its own blocks and
    VMEM plan (the bwd keeps fp32 dk/dv accumulators resident where the fwd
    keeps packed m/l stats), so a shard can be fused in one pass and fall
    back in the other — parallel/burst._bwd_impl runs this with
    pass_="bwd" at its single dispatch point."""
    if pass_ not in ("fwd", "bwd"):
        raise ValueError(f"pass_ must be 'fwd' or 'bwd', got {pass_!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret and not interpret_enabled():
        return "off-TPU (set BURST_FUSED_INTERPRET=1 to run interpreted)"
    if cfg.inter_axis is not None:
        return "double ring (inter axis) not fused yet"
    if cfg.window is not None:
        return "sliding window not fused yet"
    if has_segments:
        return "packed segments not fused yet"
    b, n, s, d = q_shape
    if k_shape[2] != s:
        return "cross-attention shard lengths"
    if world is None:
        world = axis_size(cfg.intra_axis)
    if world < 2:
        return "world < 2 (nothing to rotate)"
    extra = _extra_named_axes(cfg.intra_axis) if extra_axes is None \
        else list(extra_axes)
    if extra is None or extra:
        return (f"ring axis must be the only partitioned axis in scope "
                f"(found {extra})")
    rf = resolve_fused(cfg.fused_block_q, cfg.fused_block_kv,
                       cfg.fused_kv_slots,
                       block_q_bwd=getattr(cfg, "fused_block_q_bwd", None),
                       block_kv_bwd=getattr(cfg, "fused_block_kv_bwd", None),
                       bwd_slots=getattr(cfg, "fused_bwd_slots", None))
    if pass_ == "bwd":
        # VMEM plan, bwd roles: resident k+v chunk, fp32 dk/dv accumulators,
        # the per-step bundle tiles (q, do, delta|o, lse, arriving dq, local
        # dq) — 4-byte worst case, so an oversized shard falls back instead
        # of failing Mosaic allocation mid-ring
        bqb = _pick_block(s, rf.block_q_bwd)
        vmem = 2 * s * d * 4 + 2 * s * d * 4 + 6 * bqb * d * 4
        if vmem > rf.vmem_budget:
            return (f"VMEM plan {vmem} bytes exceeds fused budget "
                    f"{rf.vmem_budget} (bwd)")
        return None
    # VMEM plan: resident k+v chunk, packed m/l stats, acc staging — counted
    # against the per-generation budget (4-byte worst case per element) so
    # an oversized shard falls back instead of failing Mosaic allocation
    # mid-ring
    bq = _pick_block(s, rf.block_q)
    vmem = 2 * s * d * 4 + 2 * b * n * s * 4 + 3 * bq * d * 4
    if vmem > rf.vmem_budget:
        return (f"VMEM plan {vmem} bytes exceeds fused budget "
                f"{rf.vmem_budget}")
    return None


# ---------------------------------------------------------------------------
# packed m/l stats access ([B, N, S/lp, lp] refs — pallas_flash's packed
# layout with explicit (batch, head) indices instead of pre-blocked refs)


def _stat_read(ref, b_, h, i, bq, lp):
    """Rows [i*bq, (i+1)*bq) of a packed [B, N, S/lp, lp] stats ref -> (bq, 1)."""
    rows = bq // lp
    pack = ref[b_, h, pl.ds(i * rows, rows), :]
    if lp == 1:
        return pack
    rep = jnp.repeat(pack, lp, axis=0)  # (bq, lp); row t = pack[t // lp]
    t_lane = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 0) % lp
    c_idx = jax.lax.broadcasted_iota(jnp.int32, (bq, lp), 1)
    return jnp.sum(jnp.where(t_lane == c_idx, rep, 0.0), axis=1, keepdims=True)


def _stat_write(ref, b_, h, i, col, bq, lp):
    rows = bq // lp
    ref[b_, h, pl.ds(i * rows, rows), :] = jnp.reshape(col, (rows, lp))


# ---------------------------------------------------------------------------
# kernel


def _fused_fwd_kernel(
    sched_ref,
    q_ref, k_hbm, v_hbm,
    o_ref, lse_ref,
    *rest,
    world, slots, scale, bq, bkv, lp, nqb, nkb, group, n_b, n_h, hw_sync,
    collect,
):
    """One grid step = q-block i of head h, batch b_, ring round r.

    sched_ref is the [world + 1, 6] prefetch table: rows 0..world-1 hold the
    per-round (q_lo, q_hi, kv_hi, causal, offset, slot) — mask scalars from
    ops/masks.round_spec plus the exported slot schedule — and row `world`
    holds (me, right, left, 0, 0, 0) neighbor ids.

    `collect` (static) appends one more OUTPUT before the scratch refs: a
    [1, slots] int32 SMEM array counting, per communication slot, how many
    rounds consumed a chunk out of that slot — the devstats slot-reuse
    counter (obs/devstats.py).  Pure scalar writes at the first grid step
    of each round; the compute/DMA choreography is untouched, so stats-off
    and stats-on kernels produce bit-identical o/lse.

    Semaphore ledger (everything drains to zero):
      krecv/vrecv[slot]  +1 per arriving send (left neighbor, rounds 1..W-1)
                         -1 at the round's first grid step
      ksend/vsend[slot]  +1 per outgoing send (rounds 0..W-2)
                         -1 at the same round's last grid step (drain)
      free_sem (hw only) +1 from the right neighbor when our send's target
                         slot is reusable; sends at rounds >= slots-1 take
                         one credit; we grant the LEFT neighbor a credit at
                         the end of rounds 0..W-1-slots.  Credits granted ==
                         credits taken == max(0, W-1-(slots-1)).
    """
    if collect:
        slot_use_ref = rest[0]
        rest = rest[1:]
    (kbuf, vbuf, kchunk, vchunk, mstat, lstat, accbuf, acc_in, acc_scr,
     m_sw, l_sw, cp_sem, chunk_sem, acc_sem, ksend, krecv, vsend, vrecv,
     free_sem) = rest

    r = pl.program_id(0)
    b_ = pl.program_id(1)
    h = pl.program_id(2)
    i = pl.program_id(3)
    right = sched_ref[world, 1]
    left = sched_ref[world, 2]
    slot = sched_ref[r, 5]
    first_of_round = (b_ == 0) & (h == 0) & (i == 0)
    last_of_round = (b_ == n_b - 1) & (h == n_h - 1) & (i == nqb - 1)

    if collect:
        @pl.when(first_of_round)
        def _slot_tally():
            # devstats slot-reuse counter: zero once at round 0, then one
            # scalar SMEM increment per round for the slot being consumed
            @pl.when(r == 0)
            def _zero():
                for j in range(slots):
                    slot_use_ref[0, j] = 0

            slot_use_ref[0, slot] = slot_use_ref[0, slot] + 1

    # ---- round choreography (first grid step of the round only) ----
    @pl.when(first_of_round & (r == 0))
    def _copy_in():
        # local chunk -> slot[0]: one HBM->HBM copy so every later round
        # (compute reads, RDMA sends) addresses kbuf/vbuf slots uniformly
        ck = pltpu.make_async_copy(k_hbm, kbuf.at[slot], cp_sem.at[0])
        cv = pltpu.make_async_copy(v_hbm, vbuf.at[slot], cp_sem.at[1])
        ck.start()
        cv.start()
        ck.wait()
        cv.wait()

    if hw_sync:
        @pl.when(first_of_round & (r == 0))
        def _barrier():
            # neighbors must have entered the kernel (buffers live) before
            # any RDMA writes their slots
            bar = pltpu.get_barrier_semaphore()
            pltpu.semaphore_signal(bar, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_signal(bar, inc=1, device_id=right,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_wait(bar, 2)

    @pl.when(first_of_round & (r > 0))
    def _recv_wait():
        # round r's chunk must have LANDED in slot[r] before compute or the
        # onward send may read it
        pltpu.semaphore_wait(krecv.at[slot], 1)
        pltpu.semaphore_wait(vrecv.at[slot], 1)

    @pl.when(first_of_round & (r < world - 1))
    def _send_onward():
        dst_slot = sched_ref[r + 1, 5]
        if hw_sync:
            @pl.when(r >= slots - 1)
            def _capacity():
                # target slot was last read by the neighbor at round
                # r + 1 - slots; take one free credit proving it finished
                pltpu.semaphore_wait(free_sem, 1)
        sk = pltpu.make_async_remote_copy(
            src_ref=kbuf.at[slot], dst_ref=kbuf.at[dst_slot],
            send_sem=ksend.at[dst_slot], recv_sem=krecv.at[dst_slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        sv = pltpu.make_async_remote_copy(
            src_ref=vbuf.at[slot], dst_ref=vbuf.at[dst_slot],
            send_sem=vsend.at[dst_slot], recv_sem=vrecv.at[dst_slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        sk.start()
        sv.start()
        # no wait here: the transfer overlaps this whole round's sweep; the
        # drain wait sits at the round's LAST grid step below

    # ---- per-(round, batch, kv-head) chunk load: HBM slot -> VMEM ----
    @pl.when((i == 0) & (h % group == 0))
    def _chunk_load():
        kvh = h // group
        lk = pltpu.make_async_copy(kbuf.at[slot, b_, kvh], kchunk,
                                   chunk_sem.at[0])
        lv = pltpu.make_async_copy(vbuf.at[slot, b_, kvh], vchunk,
                                   chunk_sem.at[1])
        lk.start()
        lv.start()
        lk.wait()
        lv.wait()

    # ---- start the acc carry load early: it overlaps the whole sweep ----
    @pl.when(r > 0)
    def _acc_load_start():
        pltpu.make_async_copy(accbuf.at[b_, h, i], acc_in,
                              acc_sem.at[0]).start()

    # ---- local online-softmax sweep over this round's chunk ----
    spec_r = tuple(sched_ref[r, c] for c in range(5))
    r0 = i * bq
    m_sw[:] = jnp.full_like(m_sw, NEG_INF)
    l_sw[:] = jnp.zeros_like(l_sw)
    acc_scr[:] = jnp.zeros_like(acc_scr)
    q_t = q_ref[0, 0, :, :] * (scale * LOG2E)

    def _fold(c0, mask):
        ks = kchunk[pl.ds(c0, bkv), :]
        s_t = jax.lax.dot_general(
            q_t, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if mask is not None:
            s_t = jnp.where(mask, s_t, NEG_INF)
        m_prev = m_sw[:]
        m_new = jnp.maximum(m_prev, jnp.max(s_t, axis=1, keepdims=True))
        alpha = jnp.where(m_prev >= m_new, 1.0, jnp.exp2(m_prev - m_new))
        p = jnp.exp2(s_t - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)  # all-masked-row nan guard
        l_sw[:] = l_sw[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_sw[:] = m_new
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(vchunk.dtype), vchunk[pl.ds(c0, bkv), :],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    for j in range(nkb):
        c0 = j * bkv
        live = _block_has_work(spec_r, r0, c0, bq, bkv)
        full = _block_full(spec_r, r0, c0, bq, bkv)

        @pl.when(live & full)
        def _fast(c0=c0):
            _fold(c0, None)

        @pl.when(live & ~full)
        def _masked(c0=c0):
            _fold(c0, _block_mask(spec_r, r0, c0, bq, bkv))

    # ---- merge with the carried state (split-k style combine) ----
    @pl.when(r == 0)
    def _init_state():
        # round 0 is always the self round: no carry, state = local sweep
        _stat_write(mstat, b_, h, i, m_sw[:], bq, lp)
        _stat_write(lstat, b_, h, i, l_sw[:], bq, lp)

    @pl.when(r > 0)
    def _merge():
        m1 = _stat_read(mstat, b_, h, i, bq, lp)
        l1 = _stat_read(lstat, b_, h, i, bq, lp)
        m2, l2 = m_sw[:], l_sw[:]
        m = jnp.maximum(m1, m2)
        a1 = jnp.where(m1 == NEG_INF, 0.0, jnp.exp2(m1 - m))
        a2 = jnp.where(m2 == NEG_INF, 0.0, jnp.exp2(m2 - m))
        pltpu.make_async_copy(accbuf.at[b_, h, i], acc_in,
                              acc_sem.at[0]).wait()
        acc_scr[:] = acc_in[:] * a1 + acc_scr[:] * a2
        _stat_write(mstat, b_, h, i, m, bq, lp)
        _stat_write(lstat, b_, h, i, l1 * a1 + l2 * a2, bq, lp)

    @pl.when(r < world - 1)
    def _acc_store():
        st = pltpu.make_async_copy(acc_scr, accbuf.at[b_, h, i],
                                   acc_sem.at[1])
        st.start()
        st.wait()

    @pl.when(r == world - 1)
    def _finalize():
        # fused finalize: o = acc / l in the caller's dtype; lse back to the
        # natural-log domain, packed rows into the resident lse out block
        m = _stat_read(mstat, b_, h, i, bq, lp)
        l = _stat_read(lstat, b_, h, i, bq, lp)
        o_ref[0, 0, :, :] = jnp.where(
            l > 0, acc_scr[:] / l, 0.0).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m * LN2 + jnp.log(l), NEG_INF)
        rows = bq // lp
        lse_ref[b_, h, pl.ds(i * rows, rows), :] = jnp.reshape(
            lse, (rows, lp))

    # ---- round epilogue (last grid step of the round only) ----
    @pl.when(last_of_round & (r < world - 1))
    def _send_drain():
        # our outgoing RDMA read slot[r]; it must be out the door before the
        # left neighbor may overwrite that slot (free credit below) and
        # before the kernel may exit with a live DMA
        dst_slot = sched_ref[r + 1, 5]
        pltpu.semaphore_wait(ksend.at[dst_slot], 1)
        pltpu.semaphore_wait(vsend.at[dst_slot], 1)

    if hw_sync:
        @pl.when(last_of_round & (r <= world - 1 - slots))
        def _grant_free():
            # slot[r] has no further readers here: every q-block consumed it
            # and our own onward send drained — the LEFT neighbor (writer of
            # our slots) may now target it at its round r + slots - 1
            pltpu.semaphore_signal(free_sem, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)


# ---------------------------------------------------------------------------
# shard-level entry point


def fused_ring_fwd(q, k, v, cfg, *, interpret=None, collect_stats=False):
    """Forward burst attention on per-shard arrays via the fused ring kernel.

    Call inside shard_map on the ring axis (same contract as
    parallel/burst._fwd_impl): q [B, N, S, D], k/v [B, Nk, S, D] in layout
    order.  Returns (o [B, N, S, D] in q.dtype, lse [B, N, S] f32) — plus a
    per-shard obs.devstats.DevStats when `collect_stats`: mask occupancy and
    liveness are derived in-graph from the SAME sched-table specs the kernel
    masks by, slot-reuse counts come out of the kernel itself as an extra
    scalar (SMEM) output, and lse/o health is computed on the results.  The
    stats-off call emits the identical kernel (no extra output), so traces
    without stats are bit-identical to pre-devstats builds.
    Callers must have checked `supported` first.
    """
    b, n, s, d = q.shape
    n_kv = k.shape[1]
    assert n % n_kv == 0, f"GQA needs Nq % Nk == 0, got {n} % {n_kv}"
    group = n // n_kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = cfg.scale if cfg.scale is not None else d ** -0.5
    world = axis_size(cfg.intra_axis)
    rf = resolve_fused(cfg.fused_block_q, cfg.fused_block_kv,
                       cfg.fused_kv_slots)
    slots = min(rf.kv_slots, world)
    bq = _pick_block(s, rf.block_q)
    bkv = _pick_block(s, rf.block_kv)
    lp = _pick_block(bq, 128)
    nqb = s // bq
    nkb = s // bkv

    # [world + 1, 6] schedule table (see _fused_fwd_kernel docstring): mask
    # scalars reuse the SAME per-round specs the scan ring computes, so the
    # two paths mask identically by construction
    part_me = my_partition(cfg.intra_axis, None)
    slot_sched = fused_slot_schedule(world, slots)
    rows = []
    specs = []  # per-round MaskSpecs, reused for devstats occupancy tallies
    for r in range(world):
        sp = round_spec(part_me, partition_at_round(r, cfg.intra_axis, None),
                        s, s, cfg.causal, cfg.layout)
        specs.append(sp)
        rows.append(jnp.concatenate(
            [_spec_array(sp),
             jnp.asarray([int(slot_sched[r])], jnp.int32)]))
    me, right, left = neighbor_ids(cfg.intra_axis)
    rows.append(jnp.stack([jnp.asarray(me, jnp.int32),
                           jnp.asarray(right, jnp.int32),
                           jnp.asarray(left, jnp.int32),
                           jnp.int32(0), jnp.int32(0), jnp.int32(0)]))
    sched = jnp.stack(rows)

    kernel = functools.partial(
        _fused_fwd_kernel, world=world, slots=slots, scale=scale, bq=bq,
        bkv=bkv, lp=lp, nqb=nqb, nkb=nkb, group=group, n_b=b, n_h=n,
        hw_sync=not interpret, collect=collect_stats,
    )

    def q_map(r, b_, h, i, sp):
        return (b_, h, i, 0)

    out_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        # whole-array resident block: written row-range-wise at the last
        # round, flushed once (block dims == array dims, always legal)
        pl.BlockSpec((b, n, s // lp, lp),
                     lambda r, b_, h, i, sp: (0, 0, 0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, n, s, d), q.dtype),
        jax.ShapeDtypeStruct((b, n, s // lp, lp), jnp.float32),
    ]
    if collect_stats:
        # devstats slot-reuse counts: whole-array SMEM output, scalar writes
        # only at round boundaries (see _fused_fwd_kernel)
        out_specs.append(
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.SMEM))
        out_shape.append(jax.ShapeDtypeStruct((1, slots), jnp.int32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(world, b, n, nqb),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), q_map),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.ANY((slots, b, n_kv, s, d), k.dtype),   # kbuf
            pltpu.ANY((slots, b, n_kv, s, d), v.dtype),   # vbuf
            pltpu.VMEM((s, d), k.dtype),                  # kchunk
            pltpu.VMEM((s, d), v.dtype),                  # vchunk
            pltpu.VMEM((b, n, s // lp, lp), jnp.float32),  # mstat (base-2)
            pltpu.VMEM((b, n, s // lp, lp), jnp.float32),  # lstat (linear)
            pltpu.ANY((b, n, nqb, bq, d), jnp.float32),   # accbuf (carry)
            pltpu.VMEM((bq, d), jnp.float32),             # acc_in
            pltpu.VMEM((bq, d), jnp.float32),             # acc_scr
            pltpu.VMEM((bq, 1), jnp.float32),             # m_sw
            pltpu.VMEM((bq, 1), jnp.float32),             # l_sw
            pltpu.SemaphoreType.DMA((2,)),                # cp_sem
            pltpu.SemaphoreType.DMA((2,)),                # chunk_sem
            pltpu.SemaphoreType.DMA((2,)),                # acc_sem
            pltpu.SemaphoreType.DMA((slots,)),            # ksend
            pltpu.SemaphoreType.DMA((slots,)),            # krecv
            pltpu.SemaphoreType.DMA((slots,)),            # vsend
            pltpu.SemaphoreType.DMA((slots,)),            # vrecv
            pltpu.SemaphoreType.REGULAR,                  # free_sem
        ],
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        # everything is sequential by construction: the ring choreography,
        # the VMEM-resident stats, and the acc carry all assume one core
        # walks the grid in order — a megacore split would race them
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("arbitrary",) * 4,
            collective_id=_COLLECTIVE_ID,
        ),
        interpret=interpret,
    )(sched, q, k, v)
    o, lse_packed = outs[0], outs[1]
    lse = _unpack(lse_packed)
    if not collect_stats:
        return o, lse
    from ..obs import devstats

    # occupancy/liveness from the SAME per-round specs the kernel masks by;
    # the fused ring executes every scheduled round (dead contig-causal
    # rounds run fully masked instead of being cond-skipped)
    pairs = sum(spec_pair_count(sp, s, s) for sp in specs)
    live = sum(spec_live(sp).astype(jnp.int32) for sp in specs)
    stats = devstats.ring_stats(
        rounds=world, rounds_live=live, attn_pairs=pairs,
        total_pairs=float(world) * s * s, head_dim=d,
        m=None,  # the running row max never leaves the kernel
        lse=lse, acc=o, fused_rounds=world, slot_use=outs[2])
    return o, lse, stats
