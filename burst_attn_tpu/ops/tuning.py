"""Per-TPU-generation kernel block-size table.

SURVEY.md §7 build order item 2 calls for a "block-size autotuning table per
TPU generation": the measured optimum differs per chip (VMEM size, MXU/VPU
ratio), and the v5e numbers baked into the kernel defaults were found with
`benchmarks/sweep_blocks.py`.  This table keys those measurements by
`jax.devices()[0].device_kind` so other generations get a sane starting
point and a re-sweep has one place to record results.

Values are (fwd block_q, fwd block_kv, fwd block_kv_compute,
bwd block_q, bwd block_kv).  The v5e row is measured (seq=64K, 32 heads,
d=128, causal bf16); other rows start from the v5e optimum scaled by VMEM
headroom and are marked estimated until swept on hardware.
"""

from typing import NamedTuple, Optional

import jax


class BlockTable(NamedTuple):
    fwd_block_q: int
    fwd_block_kv: int
    fwd_block_kv_compute: Optional[int]
    bwd_block_q: int
    bwd_block_kv: int
    measured: bool  # False = extrapolated, re-sweep on hardware


# keyed by substrings of jax Device.device_kind (lowercased)
_TABLE = {
    # measured with benchmarks/sweep_blocks.py on one v5e chip; see
    # docs/design.md §3 for the cliff analysis
    "v5 lite": BlockTable(2048, 2048, 1024, 1024, 2048, True),
    "v5e": BlockTable(2048, 2048, 1024, 1024, 2048, True),
    # v4/v5p have larger cores (two TensorCores, more VMEM per core);
    # same shape defaults until swept
    "v5p": BlockTable(2048, 2048, 1024, 1024, 2048, False),
    "v4": BlockTable(2048, 2048, 1024, 1024, 2048, False),
    # v6e (Trillium): bigger MXU; start from the v5e optimum
    "v6": BlockTable(2048, 2048, 1024, 1024, 2048, False),
}

_DEFAULT = BlockTable(2048, 2048, 1024, 1024, 2048, False)


def block_defaults(device=None) -> BlockTable:
    """Best-known kernel blocks for `device` (default: first jax device).

    Off-TPU (CPU interpret runs) the values only affect tiling granularity,
    not correctness; the default row is returned.
    """
    if device is None:
        devs = jax.devices()
        if not devs:
            return _DEFAULT
        device = devs[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, row in _TABLE.items():
        if key in kind:
            return row
    return _DEFAULT


def resolve_blocks(block_q=None, block_kv=None, block_q_bwd=None,
                   block_kv_bwd=None, block_kv_compute="unset"):
    """Fill unspecified kernel block sizes from the per-generation table.

    The bwd defaults never exceed the (resolved) fwd blocks, so a caller who
    shrinks the fwd blocks for VMEM keeps that budget in bwd; likewise the
    compute sub-block never exceeds the kv memory block.  Returns
    (block_q, block_kv, block_q_bwd, block_kv_bwd) — or a 5-tuple ending in
    block_kv_compute when it is passed (None = use the table value).
    """
    t = block_defaults()
    bq = t.fwd_block_q if block_q is None else block_q
    bkv = t.fwd_block_kv if block_kv is None else block_kv
    bqb = min(t.bwd_block_q, bq) if block_q_bwd is None else block_q_bwd
    bkvb = min(t.bwd_block_kv, bkv) if block_kv_bwd is None else block_kv_bwd
    if block_kv_compute == "unset":
        return bq, bkv, bqb, bkvb
    if block_kv_compute is None:
        block_kv_compute = (bkv if t.fwd_block_kv_compute is None
                            else min(t.fwd_block_kv_compute, bkv))
    return bq, bkv, bqb, bkvb, block_kv_compute
