"""Per-TPU-generation kernel block-size table.

SURVEY.md §7 build order item 2 calls for a "block-size autotuning table per
TPU generation": the measured optimum differs per chip (VMEM size, MXU/VPU
ratio), and the v5e numbers baked into the kernel defaults were found with
`benchmarks/sweep_blocks.py`.  This table keys those measurements by
`jax.devices()[0].device_kind` so other generations get a sane starting
point and a re-sweep has one place to record results.

Values are (fwd block_q, fwd block_kv, fwd block_kv_compute,
bwd block_q, bwd block_kv).  The v5e row is measured (seq=64K, 32 heads,
d=128, causal bf16); other rows start from the v5e optimum scaled by VMEM
headroom and are marked estimated until swept on hardware.
"""

import logging
from typing import NamedTuple, Optional

import jax

logger = logging.getLogger("burst_attn_tpu")


class BlockTable(NamedTuple):
    fwd_block_q: int
    fwd_block_kv: int
    fwd_block_kv_compute: Optional[int]
    bwd_block_q: int
    bwd_block_kv: int
    measured: bool  # False = extrapolated, re-sweep on hardware
    # VMEM-cliff clamp budgets (elements of q-block x kv-block area a fwd /
    # bwd grid step may keep live before throughput collapses ~3x).  The
    # v5e values are MEASURED (benchmarks/cliff_probe.py); other
    # generations scale them by their larger per-core VMEM and inherit
    # measured=False until a sweep pins them.
    fwd_cliff_area: int = 2048 * 2048
    bwd_cliff_area: int = 1024 * 2048
    # Fused ring kernel (ops/fused_ring.py): KV communication-slot count
    # (2 = plain double buffering; more slots let the RDMA pipeline run
    # deeper ahead of compute at the cost of one extra KV chunk of HBM per
    # slot) and the q-row block of its grid.  The fused kernel's sweep
    # reads KV from a VMEM-resident chunk, so — unlike the scan-path
    # kernels — its row block does NOT gate KV streaming traffic; 512 rows
    # keeps the per-step acc/stat state small while giving the MXU full
    # [512, kv] tiles.  Estimated until swept on hardware
    # (benchmarks/ring_overlap.py reports per-config timings to retune).
    fused_kv_slots: int = 2
    fused_block_q: int = 512
    fused_block_kv: int = 512
    # VMEM budget (bytes) the fused kernel may plan against for its
    # resident KV chunk + stats; above it the dispatch falls back to the
    # scan ring rather than risk a Mosaic allocation failure mid-ring.
    fused_vmem_budget: int = 96 * 1024 * 1024
    # Fused ring BACKWARD kernel (ops/fused_ring_bwd.py): bundle/dq slot
    # count and its grid blocks.  The bwd grid step keeps ~5 [bq, D] tiles
    # plus two [bq, bkv] intermediates live on top of the resident KV chunk
    # and the fp32 dk/dv accumulators, so its q block defaults one power of
    # two below the forward's — mirroring the scan kernels' fwd/bwd block
    # asymmetry.  Estimated until swept on hardware
    # (benchmarks/ring_overlap.py --pass bwd reports per-config timings).
    fused_bwd_slots: int = 2
    fused_block_q_bwd: int = 256
    fused_block_kv_bwd: int = 512
    # Counter-rotating (bidi) / double-ring second bank: slots of the ccw
    # direction (bidi) or the inter prefetch bank (double ring; the
    # compiler clamps it to the cycle count).  Per ISSUE 6 these are
    # per-DIRECTION knobs: the two ICI directions can be tuned
    # independently when one carries more traffic (e.g. a torus wraparound
    # link shared with another ring).  Estimated until swept on hardware
    # (benchmarks/ring_overlap.py --topology bidi reports per-direction
    # comm floors to retune).
    fused_ccw_slots: int = 2
    fused_bwd_ccw_slots: int = 2
    # Wire precision of the ROTATING ring payloads (parallel/schedule.py
    # WIRE_DTYPES): None ships the caller's dtypes; "int8"/"fp8" quantize
    # the fwd K/V chunks, the bwd q-side bundle (lse exempt) and the dq
    # partials to 1 byte/element with per-block fp32 scales riding the same
    # HBM slots — ÷4 ring bytes vs the fp32 scan payloads.  Per-generation
    # because the win is a function of the ICI:FLOPs ratio: every row stays
    # None (bit-exact payloads) until an on-chip sweep
    # (benchmarks/ring_overlap.py --wire-dtype) shows the comm floor is the
    # bottleneck for that generation's links, at which point the measured
    # row may opt in.  burst_attn(..., wire_dtype=...) overrides per call.
    fused_wire_dtype: Optional[str] = None


class ResolvedBlocks(NamedTuple):
    """Uniform return of resolve_blocks(): always all five fields, so call
    sites never branch on arity (callers that don't use the compute
    sub-block just ignore the last field)."""

    block_q: int
    block_kv: int
    block_q_bwd: int
    block_kv_bwd: int
    block_kv_compute: Optional[int]


_TABLE = {
    # measured with benchmarks/sweep_blocks.py on one v5e chip; see
    # docs/design.md §3 for the cliff analysis
    "v5e": BlockTable(2048, 2048, 1024, 1024, 2048, True),
    # v4/v5p have roughly twice the v5e per-core VMEM, so the area at which
    # a grid step's live blocks spill — the cliff — should sit one power of
    # two higher; block shapes stay at the v5e optimum until swept
    "v5p": BlockTable(2048, 2048, 1024, 1024, 2048, False,
                      fwd_cliff_area=2 * 2048 * 2048,
                      bwd_cliff_area=2 * 1024 * 2048),
    "v4": BlockTable(2048, 2048, 1024, 1024, 2048, False,
                     fwd_cliff_area=2 * 2048 * 2048,
                     bwd_cliff_area=2 * 1024 * 2048),
    # v6e (Trillium): bigger MXU, comparable VMEM — keep the v5e budgets
    "v6": BlockTable(2048, 2048, 1024, 1024, 2048, False),
}

# Ordered (substring, canonical row) aliases over the device_kind strings JAX
# runtimes actually report — "TPU v5 lite" / "TPU v5e" (v5e), "TPU v5p" and
# sometimes bare "TPU v5" (v5p), "TPU v6 lite" / "TPU v6e" / Trillium, "TPU
# v4".  Order matters: the v5e spellings must be tried before the bare "v5"
# catch-all, and "v5p" before "v5".
_KIND_ALIASES = (
    ("v5 lite", "v5e"),
    ("v5litepod", "v5e"),
    ("v5e", "v5e"),
    ("v5p", "v5p"),
    ("v5", "v5p"),
    ("v6 lite", "v6"),
    ("v6e", "v6"),
    ("trillium", "v6"),
    ("v6", "v6"),
    ("v4", "v4"),
)

_DEFAULT = BlockTable(2048, 2048, 1024, 1024, 2048, False)

_logged_kinds = set()


def _log_resolution(kind: str, canonical: Optional[str], row: BlockTable,
                    platform: str) -> None:
    """Log each device kind's table resolution once per process — an
    unmeasured row on real TPU hardware means the defaults are
    extrapolations and a re-sweep (benchmarks/sweep_blocks.py) is due
    (round-1 verdict item 8)."""
    if kind in _logged_kinds:
        return
    _logged_kinds.add(kind)
    if platform != "tpu":
        return  # off-TPU the values only affect tiling granularity
    if row.measured:
        logger.info("kernel blocks for %r: measured row %r", kind, canonical)
    else:
        logger.warning(
            "kernel blocks for %r resolved to %s row (NOT measured on this "
            "generation — defaults extrapolated from the v5e sweep; run "
            "`python -m benchmarks.sweep_blocks` and record the optimum in "
            "burst_attn_tpu/ops/tuning.py)",
            kind, repr(canonical) if canonical else "the default",
        )


def generations():
    """Every named generation row of the tuning table plus the "default"
    fallback, in sorted order — the static iteration domain of the cost
    verifier (analysis/costmodel.py), which must prove budgets for rows a
    CPU lint host can never resolve through jax.devices()."""
    return tuple(sorted(_TABLE)) + ("default",)


def generation_row(kind: str) -> BlockTable:
    """The BlockTable row for a canonical generation name (or "default"),
    with no device in hand — the device-free twin of block_defaults()."""
    if kind == "default":
        return _DEFAULT
    if kind not in _TABLE:
        raise KeyError(
            f"unknown generation {kind!r}; expected one of {generations()}")
    return _TABLE[kind]


def canonical_kind(device=None):
    """Canonical generation name ("v5e"/"v5p"/"v4"/"v6") for a device's
    device_kind, or None when unrecognized — the one place device-kind
    strings are interpreted (consumers: the block table here, peak-FLOPs
    tables in benchmarks)."""
    if device is None:
        devs = jax.devices()
        if not devs:
            return None
        device = devs[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, canonical in _KIND_ALIASES:
        if key in kind:
            return canonical
    return None


def block_defaults(device=None) -> BlockTable:
    """Best-known kernel blocks for `device` (default: first jax device).

    Off-TPU (CPU interpret runs) the values only affect tiling granularity,
    not correctness; the default row is returned.
    """
    if device is None:
        devs = jax.devices()
        if not devs:
            return _DEFAULT
        device = devs[0]
    kind = getattr(device, "device_kind", "").lower()
    platform = getattr(device, "platform", "")
    canonical = canonical_kind(device)
    row = _TABLE[canonical] if canonical else _DEFAULT
    _log_resolution(kind, canonical, row, platform)
    return row


# Measured VMEM-cliff law (benchmarks/cliff_probe.py on v5e, traces under
# results/cliff_traces/): a fwd grid step whose q-block x kv-block AREA
# exceeds the generation's budget collapses ~3x (57 TFLOPs/s at 2048x4096
# on v5e — at EVERY compute-sub-block size, so it is not score
# materialization or pipeline overlap; halving bq to 1024 recovers 142).
# The backward's per-step residency is larger (5 matmul operands + dk/dv
# scratch), so its cliff sits one power of two lower.  It's a cliff, not a
# slope — exceeding the budget is never a trade-off worth making, hence a
# clamp rather than a warning.  The budgets live in the per-generation
# BlockTable rows (a v5p with twice the VMEM must not be clamped to v5e's
# areas); unknown device kinds inherit the v5e-measured values through the
# BlockTable field defaults (and _DEFAULT).


def _cliff_ok():
    """BURST_ALLOW_CLIFF=1 disables the clamp (sweeps/probes must be able
    to measure the cliff configs themselves)."""
    import os

    return os.environ.get("BURST_ALLOW_CLIFF", "") not in ("", "0")


def _clamp_cliff(bq: int, bkv: int, area: int, which: str):
    if bq * bkv <= area or _cliff_ok():
        return bq, bkv
    new_bkv = max(area // bq, 128)
    logger.warning(
        "%s blocks %dx%d exceed the measured VMEM-cliff area (%d); clamping "
        "kv block to %d (see results/cliff_probe.jsonl; BURST_ALLOW_CLIFF=1 to "
        "measure cliff configs anyway)", which, bq, bkv, area, new_bkv)
    return bq, new_bkv


class ResolvedFused(NamedTuple):
    """resolve_fused() result: the fused ring kernels' static plan knobs
    (forward KV ring AND backward bundle/dq ring — one resolution so the
    two passes can never read different generation rows)."""

    block_q: int
    block_kv: int
    kv_slots: int
    vmem_budget: int
    block_q_bwd: int
    block_kv_bwd: int
    bwd_slots: int
    ccw_slots: int
    bwd_ccw_slots: int
    wire_dtype: Optional[str] = None

    @property
    def wire_itemsize(self) -> int:
        """Bytes/element of the rotating payload banks (slot byte budgets
        in supported()'s VMEM plans price quantized banks at 1 B/elem; the
        per-block fp32 scales are O(1) per chunk and priced separately)."""
        return 4 if self.wire_dtype is None else 1


def resolve_fused(block_q=None, block_kv=None, kv_slots=None,
                  device=None, block_q_bwd=None, block_kv_bwd=None,
                  bwd_slots=None, ccw_slots=None,
                  bwd_ccw_slots=None, wire_dtype=None,
                  table: Optional[BlockTable] = None) -> ResolvedFused:
    """Fill the fused ring kernels' knobs from the per-generation table.

    kv_slots / bwd_slots < 2 cannot double-buffer (the send target would
    be the slot being computed on) and is rejected rather than silently
    bumped — an explicit wrong config should fail loudly, only the table
    default is implicit.  The bwd blocks never default LARGER than the
    (resolved) fwd blocks, mirroring resolve_blocks: a caller who tunes
    the fwd blocks down for VMEM keeps that budget in the backward.
    ccw_slots / bwd_ccw_slots tune the SECOND slot bank (the ccw direction
    of a bidi ring, or the double ring's inter prefetch bank) per pass.
    wire_dtype=None means "use the generation's fused_wire_dtype default"
    (itself None on every row today — the wire stays bit-exact unless the
    caller opts in per call).  `table` bypasses the device probe with an
    explicit BlockTable row — how the static cost verifier resolves every
    generation's knobs through the SAME defaulting algebra the dispatch
    runs, from a host with no TPU."""
    t = block_defaults(device) if table is None else table
    bq = t.fused_block_q if block_q is None else block_q
    bkv = t.fused_block_kv if block_kv is None else block_kv
    slots = t.fused_kv_slots if kv_slots is None else kv_slots
    bqb = min(t.fused_block_q_bwd, bq) if block_q_bwd is None else block_q_bwd
    bkvb = (min(t.fused_block_kv_bwd, bkv) if block_kv_bwd is None
            else block_kv_bwd)
    bslots = t.fused_bwd_slots if bwd_slots is None else bwd_slots
    cslots = t.fused_ccw_slots if ccw_slots is None else ccw_slots
    bcslots = (t.fused_bwd_ccw_slots if bwd_ccw_slots is None
               else bwd_ccw_slots)
    if slots < 2:
        raise ValueError(f"fused ring needs kv_slots >= 2, got {slots}")
    if bslots < 2:
        raise ValueError(f"fused ring bwd needs bwd_slots >= 2, got {bslots}")
    if cslots < 2:
        raise ValueError(f"fused ring needs ccw_slots >= 2, got {cslots}")
    if bcslots < 2:
        raise ValueError(
            f"fused ring bwd needs bwd_ccw_slots >= 2, got {bcslots}")
    wire = t.fused_wire_dtype if wire_dtype is None else wire_dtype
    if wire not in (None, "int8", "fp8"):
        raise ValueError(
            f"wire_dtype must be None, 'int8' or 'fp8', got {wire!r}")
    return ResolvedFused(bq, bkv, slots, t.fused_vmem_budget,
                         bqb, bkvb, bslots, cslots, bcslots, wire)


def resolve_blocks(block_q=None, block_kv=None, block_q_bwd=None,
                   block_kv_bwd=None, block_kv_compute=None,
                   device=None,
                   table: Optional[BlockTable] = None) -> ResolvedBlocks:
    """Fill unspecified kernel block sizes from the per-generation table.

    The bwd defaults never exceed the (resolved) fwd blocks, so a caller who
    shrinks the fwd blocks for VMEM keeps that budget in bwd; likewise the
    compute sub-block never exceeds the kv memory block.  Explicit configs
    past the generation's measured VMEM cliff are clamped (see
    _clamp_cliff; budgets come from the device's BlockTable row).  Always
    returns a 5-field ResolvedBlocks; callers without a compute sub-block
    ignore the last field.  `table` bypasses the device probe with an
    explicit BlockTable row (see resolve_fused).
    """
    t = block_defaults(device) if table is None else table
    bq = t.fwd_block_q if block_q is None else block_q
    bkv = t.fwd_block_kv if block_kv is None else block_kv
    bqb = min(t.bwd_block_q, bq) if block_q_bwd is None else block_q_bwd
    bkvb = min(t.bwd_block_kv, bkv) if block_kv_bwd is None else block_kv_bwd
    bq, bkv = _clamp_cliff(bq, bkv, t.fwd_cliff_area, "fwd")
    bqb, bkvb = _clamp_cliff(bqb, bkvb, t.bwd_cliff_area, "bwd")
    if block_kv_compute is None:
        block_kv_compute = (bkv if t.fwd_block_kv_compute is None
                            else min(t.fwd_block_kv_compute, bkv))
    else:
        block_kv_compute = min(block_kv_compute, bkv)
    return ResolvedBlocks(bq, bkv, bqb, bkvb, block_kv_compute)
