"""Dense reference attention — the test oracle.

Equivalent role to the reference's single-device flash oracle in
test/test_burst.py:175-184: full-sequence attention computed directly (no
tiling, fp32 softmax), against which the distributed op is compared chunk-wise
with the reference's tolerances (test/checker.py:10).
"""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("causal", "window"))
def dense_attention(q, k, v, scale=None, causal=False, segment_ids=None,
                    window=None):
    """q, k, v: [B, N, S, D] (kv heads may be fewer — GQA). Returns [B, N, S, D].
    segment_ids [B, S]: packed-sequence mask (attention stays in-segment).
    window (static int, needs causal): each query sees its last `window`
    positions inclusive — same contract as flash_attention(window=)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    from .tile import _expand_kv

    k = _expand_kv(k, q.shape[1])
    v = _expand_kv(v, q.shape[1])
    s = jnp.einsum("bnid,bnjd->bnij", q, k, preferred_element_type=jnp.float32) * scale
    s_q, s_kv = q.shape[2], k.shape[2]
    mask = jnp.ones((1, 1, s_q, s_kv), bool)
    if causal:
        rows = jnp.arange(s_q)[:, None]
        cols = jnp.arange(s_kv)[None, :]
        mask = mask & (cols <= rows)
        if window is not None:
            mask = mask & (cols > rows - window)
    if segment_ids is not None:
        mask = mask & (segment_ids[:, None, :, None]
                       == segment_ids[:, None, None, :])
    s = jnp.where(mask, s, float("-inf"))
    p = jax.nn.softmax(s, axis=-1)
    # every row keeps at least its diagonal (j == i passes both the causal
    # and the segment test), so no all-masked-row NaN guard is needed
    return jnp.einsum("bnij,bnjd->bnid", p, v.astype(jnp.float32)).astype(q.dtype)
