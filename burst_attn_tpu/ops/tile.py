"""Pure-jnp online-softmax attention tile with carry-in state.

One ring round of FlashAttention-style attention: given carry state
(m = running row max, lse = running log-sum-exp, acc = unnormalized output
accumulator) from previous rounds, fold in the contribution of one KV block.

This is the numerics oracle for the framework — the TPU-native analogue of
the reference's pure-torch tile (burst_attn/burst_utils.py:42-101) and of the
carry-in Triton kernel (burst_attn/lao.py:67-213).  It runs on any backend
(CPU included), is exactly what the Pallas kernels must reproduce, and is the
default backend for simulated-mesh tests.

Conventions (all differ deliberately from the reference's torch layout mix):
  q, k, v : [B, N, S, D]  ("bnsd"; contiguous [S, D] per head — TPU friendly)
  m, lse  : [B, N, S]     float32, initialized to -inf
  acc     : [B, N, S, D]  float32, initialized to 0, unnormalized
  final   : o = acc * exp(m - lse)   (guarded for fully-masked rows)

GQA: N query heads, Nk kv heads with N % Nk == 0; kv head g serves query
heads [g*G, (g+1)*G).
"""

from functools import partial

import jax
import jax.numpy as jnp

from .masks import MaskSpec, dense_mask

NEG_INF = float("-inf")


def init_state(batch, heads, seq, dim):
    m = jnp.full((batch, heads, seq), NEG_INF, dtype=jnp.float32)
    lse = jnp.full((batch, heads, seq), NEG_INF, dtype=jnp.float32)
    acc = jnp.zeros((batch, heads, seq, dim), dtype=jnp.float32)
    return m, lse, acc


def _expand_kv(x, n_q_heads):
    """Repeat kv heads to match query heads (GQA)."""
    n_kv = x.shape[1]
    if n_kv == n_q_heads:
        return x
    assert n_q_heads % n_kv == 0, f"GQA needs Nq % Nk == 0, got {n_q_heads} % {n_kv}"
    return jnp.repeat(x, n_q_heads // n_kv, axis=1)


def _with_segments(mask, segments):
    """Intersect a [s_q, s_kv] structural mask with the packed-sequence
    (segment-ids) equality mask.  segments = (q_seg [B, s_q], kv_seg
    [B, s_kv]) int32; tokens attend only within their own segment.  Returns
    a [B, 1, s_q, s_kv] mask (batch-dependent)."""
    if segments is None:
        return mask
    q_seg, kv_seg = segments
    return (mask[None, None] &
            (q_seg[:, None, :, None] == kv_seg[:, None, None, :]))


def tile_fwd(q, k, v, m, lse, acc, scale, spec: MaskSpec, window=None,
             segments=None):
    """One online-softmax round; returns updated (m, lse, acc).
    `window` (static): sliding-window lower bound, see masks.dense_mask.
    `segments`: packed-sequence ids, see _with_segments."""
    s_q, s_kv = q.shape[2], k.shape[2]
    k = _expand_kv(k, q.shape[1])
    v = _expand_kv(v, q.shape[1])
    mask = _with_segments(dense_mask(spec, s_q, s_kv, window), segments)

    s = jnp.einsum("bnid,bnjd->bnij", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    s = jnp.where(mask, s, NEG_INF)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # alpha rescales the old accumulator; rows where m stays -inf keep alpha=1
    # (their acc is 0 anyway) to avoid -inf - -inf = nan.
    alpha = jnp.where(m >= m_new, 1.0, jnp.exp(m - m_new))
    p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
    l_step = jnp.sum(p, axis=-1)

    acc = acc * alpha[..., None] + jnp.einsum(
        "bnij,bnjd->bnid", p, v, preferred_element_type=jnp.float32
    )
    prior = jnp.where(jnp.isneginf(lse), 0.0, jnp.exp(lse - m_new))
    total = prior + l_step
    lse_new = jnp.where(total > 0, m_new + jnp.log(total), NEG_INF)
    return m_new, lse_new, acc


def finalize(m, lse, acc, dtype):
    """Normalize the accumulator: o = acc * exp(m - lse)."""
    o_scale = jnp.where(jnp.isneginf(lse), 0.0, jnp.exp(m - lse))
    return (acc * o_scale[..., None]).astype(dtype)


def tile_bwd(do, q, k, v, delta, lse, scale, spec: MaskSpec, window=None,
             segments=None):
    """One backward ring round; returns this round's (dq, dk, dv) in float32.

    delta = sum(o * do, axis=-1) [B, N, S] float32 (precomputed once — the
    reference's optimize_bwd_comm quantity, burst_attn_interface.py:269-278).
    lse is the FINAL log-sum-exp of the query rows, so p = exp(s - lse) is the
    true softmax probability; masked entries are forced to zero.
    """
    n_q = q.shape[1]
    n_kv = k.shape[1]
    s_q, s_kv = q.shape[2], k.shape[2]
    kx = _expand_kv(k, n_q)
    vx = _expand_kv(v, n_q)
    mask = _with_segments(dense_mask(spec, s_q, s_kv, window), segments)

    s = jnp.einsum("bnid,bnjd->bnij", q, kx, preferred_element_type=jnp.float32)
    s = s * scale
    p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)

    do32 = do.astype(jnp.float32)
    dv = jnp.einsum("bnij,bnid->bnjd", p, do32, preferred_element_type=jnp.float32)
    dp = jnp.einsum("bnid,bnjd->bnij", do32, vx, preferred_element_type=jnp.float32)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bnij,bnjd->bnid", ds, kx, preferred_element_type=jnp.float32)
    dk = jnp.einsum("bnij,bnid->bnjd", ds, q, preferred_element_type=jnp.float32)

    if n_kv != n_q:
        g = n_q // n_kv
        dk = dk.reshape(dk.shape[0], n_kv, g, s_kv, -1).sum(axis=2)
        dv = dv.reshape(dv.shape[0], n_kv, g, s_kv, -1).sum(axis=2)
    return dq, dk, dv


@partial(jax.jit, static_argnames=("causal", "window"))
def single_device_attention(q, k, v, scale=None, causal=False, window=None,
                            segment_ids=None):
    """Full attention on one device via the tile (a one-round "ring").
    `segment_ids` [B, S] int32 packs multiple sequences into one row:
    attention never crosses a segment boundary."""
    from .masks import round_spec

    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window is not None and not causal:
        raise ValueError("window attention requires causal=True")
    b, n, s, d = q.shape
    spec = round_spec(jnp.int32(0), jnp.int32(0), s, k.shape[2], causal, "contig")
    m, lse, acc = init_state(b, n, s, d)
    segs = None if segment_ids is None else (segment_ids, segment_ids)
    m, lse, acc = tile_fwd(q, k, v, m, lse, acc, scale, spec, window=window,
                           segments=segs)
    return finalize(m, lse, acc, q.dtype)
