from . import masks, tile, reference

__all__ = ["masks", "tile", "reference"]
