"""Ragged paged attention: ONE Pallas launch for a mixed prefill+decode
token batch against the paged KV pool.

ops/paged_attention.py serves exactly one query token per sequence per
launch — fine for pure decode, but a continuous-batching engine lives on
MIXED steps: some slots absorbing a prompt chunk (tens of query tokens),
others decoding (one), all against the same page pool.  Running prefill
and decode as separate programs forfeits the batch (two launches, two
sets of ragged predication, and the prefill chunk's MXU work cannot soak
up the decode slots' latency).  This kernel is the single-launch design
(PAPERS.md "Ragged Paged Attention"):

  * The grid walks `(sequence, kv-head, q-block, page-slot)`.  Each
    sequence brings its OWN query token count `q_lens[s]` (1 = decode,
    up to the chunk size = prefill); per-sequence page tables arrive via
    scalar prefetch and are consulted in the kv index maps, exactly like
    the decode kernel — each grid step DMAs one pool page.
  * GQA folds the query-head group INTO the q tile rows: block rows are
    laid out `token-major x group` (row r = token r//G, head r%G), so a
    decode step (1 token x G heads) and a prefill block (block_q tokens
    x G heads) are the same [rows, page] score tile shape.
  * Causality is enforced within each sequence: query token t of
    sequence s sits at absolute position `kv_lens[s] - q_lens[s] + t`
    and sees cached positions `<= ` that (sliding window optional).
    Page-slots wholly outside a block's visible band are predicated off
    and their DMAs clamped onto a live page (consecutive duplicate block
    indexes collapse into one fetch) — cost per sequence ∝ its length.
  * The inner online-softmax update is OP-FOR-OP the decode kernel's
    (same exp2 rebase, same masking order, same fp32 accumulation), so a
    decode row (q_len == 1) is BIT-IDENTICAL to paged_decode_attention —
    tested, not aspirational: the serving engine may route any step
    through either kernel and streams must not fork.

Interpret mode runs the same grid on CPU, which is how tier-1 proves the
mixed-batch parity (dense oracle + decode-kernel bit compare) off-TPU.
`ragged_supported()` is the capability probe: the serving engine falls
back to the dense-gather path (models/serving) with a labeled
`burst.fused_fallback{pass=serve}` counter instead of raising.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_flash import LOG2E, NEG_INF, VMEM_LIMIT, _interpret_default
from ..utils.compat import tpu_compiler_params

# hard ceiling on the padded rows-per-block tile (block_q * group rounded
# to sublanes): past this the [rows, page] score tile plus the fp32
# accumulator stops fitting VMEM comfortably at page=128, d=128
_MAX_BLOCK_ROWS = 1024


def _ragged_kernel(
    table_ref, n_live_ref, kvlen_ref, qlen_ref, lo_ref,  # scalar prefetch
    *refs,
    scale, page, n_slots, bq, g, quant, window,
):
    if quant:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    s_ = pl.program_id(0)
    qi = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_len = qlen_ref[s_]
    kv_len = kvlen_ref[s_]
    q_start = kv_len - q_len          # absolute position of query token 0
    t0 = qi * bq                      # first query token of this block
    # absolute position of the block's LAST real token: everything at or
    # below it is potentially visible, pages wholly above it are dead.
    # (for q_len == 1 this reduces to the decode kernel's j < n_live test)
    p_max = q_start + jnp.minimum(q_len, t0 + bq) - 1
    live = (t0 < q_len) & (j * page <= p_max) & (j >= lo_ref[s_] // page)

    @pl.when(live)
    def _accum():
        q = q_ref[0, 0, :, :] * (scale * LOG2E)
        k_tile = k_ref[0, :, :]
        if quant:
            k_tile = k_tile.astype(jnp.bfloat16)
        s = jax.lax.dot_general(
            q, k_tile, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if quant:
            # per-token dequant as a column rescale (decode kernel's trick)
            s = s * ks_ref[0]
        pos = j * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # row r = query token t0 + r//g at absolute position qp; rows past
        # q_len are wrapper padding — their outputs are sliced away, so
        # they need no extra masking (their q rows are zeros / pad tokens
        # and every op below is row-independent)
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        qp = q_start + t0 + row // g
        valid = pos <= qp
        if window is not None:
            valid &= pos >= qp - window + 1
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.where(m_prev >= m_new, 1.0, jnp.exp2(m_prev - m_new))
        p = jnp.exp2(s - m_new)
        p = jnp.where(valid, p, 0.0)
        m_scr[:] = m_new
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        if quant:
            pv = jax.lax.dot_general(
                (p * vs_ref[0]).astype(jnp.bfloat16),
                v_ref[0, :, :].astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            pv = jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0, :, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(j == n_slots - 1)
    def _finish():
        # fully-masked blocks (idle slot / past-q_len block) emit zeros
        l = jnp.where(l_scr[:] > 0, l_scr[:], 1.0)
        o_ref[0, 0, :, :] = (acc_scr[:] / l).astype(o_ref.dtype)


def _block_rows(block_q: int, group: int) -> int:
    """Padded rows per q block: block_q tokens x group heads, rounded up
    to the 8-sublane tile (>= 8, matching _pad_group for block_q == 1)."""
    return max(8, -(-(block_q * group) // 8) * 8)


def ragged_supported(*, n_kv_heads, n_q_heads, q_tokens, d_head, page,
                     quantized=False, block_q=8, interpret=None):
    """Capability probe: None when ragged_paged_attention can serve this
    shape, else a human-readable reason whose PREFIX is a stable key (the
    serving engine maps it to a bounded fallback-counter label).  Mirrors
    fused_ring.supported's contract: probe first, fall back loudly."""
    if interpret is None:
        interpret = _interpret_default()
    if q_tokens < 1:
        return f"empty q chunk: q_tokens {q_tokens} < 1"
    if n_q_heads % n_kv_heads:
        return (f"GQA group mismatch: {n_q_heads} query heads not a "
                f"multiple of {n_kv_heads} kv heads")
    if page % 128:
        return f"page size {page} is not a multiple of the 128 lane tile"
    group = n_q_heads // n_kv_heads
    bq = max(1, min(block_q, q_tokens))
    rows = _block_rows(bq, group)
    if rows > _MAX_BLOCK_ROWS:
        return (f"q-block rows {rows} (block_q {bq} x group {group}, "
                f"padded) exceed the {_MAX_BLOCK_ROWS}-row tile budget")
    # VMEM plan: q + o + acc tiles (fp32) plus a double-buffered k/v page
    kv_bytes = 1 if quantized else 2
    plan = (rows * d_head * 4 * 3          # q, o, acc
            + rows * (page + 2) * 4        # scores + m/l columns
            + 4 * page * d_head * kv_bytes)  # k/v pages, double buffered
    if plan > VMEM_LIMIT:
        return (f"VMEM plan {plan} bytes exceeds the {VMEM_LIMIT} budget "
                f"(page {page}, d_head {d_head}, rows {rows})")
    if not interpret and d_head % 128:
        # compiled Mosaic wants lane-aligned head dims; interpret mode
        # (CPU tier-1) has no such constraint
        return f"head dim {d_head} is not lane-aligned (128) for Mosaic"
    return None


def _fold_groups(q, n_kv, group, n_qblk, bq, rows):
    """[S, Nq, QT, D] -> [S, Nkv, n_qblk*rows, D] token-major x group row
    layout, zero-padded to bq tokens per block and `rows` sublanes."""
    s, _, qt, d = q.shape
    qtp = n_qblk * bq
    q = q.reshape(s, n_kv, group, qt, d)
    if qtp != qt:
        q = jnp.pad(q, [(0, 0), (0, 0), (0, 0), (0, qtp - qt), (0, 0)])
    q = jnp.moveaxis(q, 2, 3)                       # [S, Nkv, QTp, G, D]
    q = q.reshape(s, n_kv, n_qblk, bq * group, d)
    if rows != bq * group:
        q = jnp.pad(q, [(0, 0), (0, 0), (0, 0), (0, rows - bq * group),
                        (0, 0)])
    return q.reshape(s, n_kv, n_qblk * rows, d)


def _unfold_groups(o, n_q, group, n_qblk, bq, rows, qt):
    """Inverse of _fold_groups: [S, Nkv, n_qblk*rows, D] -> [S, Nq, QT, D]."""
    s, n_kv, _, d = o.shape
    o = o.reshape(s, n_kv, n_qblk, rows, d)[:, :, :, :bq * group]
    o = o.reshape(s, n_kv, n_qblk * bq, group, d)
    o = jnp.moveaxis(o, 3, 2)                       # [S, Nkv, G, QTp, D]
    return o.reshape(s, n_q, n_qblk * bq, d)[:, :, :qt]


def ragged_paged_attention(q, k_pages, v_pages, page_table, q_lens, kv_lens,
                           *, k_scales=None, v_scales=None, window=None,
                           scale=None, block_q=8, interpret=None):
    """Mixed prefill+decode ragged attention against a paged KV pool.

    q          [S, Nq, QT, D]   query tokens per slot; slot s's token t is
                                the token at absolute position
                                kv_lens[s] - q_lens[s] + t.  Rows at or
                                past q_lens[s] are padding (outputs there
                                are garbage the caller must ignore).
    k_pages    [P, Nkv, page, D]  shared pool — the new tokens' K/V must
    v_pages    [P, Nkv, page, D]  already be scattered in (the serving
                                  step scatters BEFORE attending)
    page_table [S, n_slots] int32 pool page per (slot, table column)
    q_lens     [S] int32        query tokens this launch (0 = idle slot;
                                1 = decode; >1 = prefill chunk)
    kv_lens    [S] int32        total live tokens INCLUDING this launch's
    window     static int       sliding-window band per query position
    k_scales / v_scales         per-token dequant scales for int8 pools
    block_q    static int       query tokens per grid block

    Returns [S, Nq, QT, D] in q's dtype.  A pure-decode batch (QT == 1)
    is bit-identical to paged_decode_attention on the same pool.
    """
    s, n_q, qt, d = q.shape
    n_kv = k_pages.shape[1]
    page = k_pages.shape[2]
    n_slots = page_table.shape[1]
    if n_q % n_kv:
        raise ValueError(f"{n_q} query heads not grouped by {n_kv} kv heads")
    group = n_q // n_kv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = _interpret_default()
    quant = k_scales is not None
    if quant != (v_scales is not None):
        raise ValueError("k_scales and v_scales must be given together")

    bq = max(1, min(block_q, qt))
    n_qblk = -(-qt // bq)
    rows = _block_rows(bq, group)
    q_rows = _fold_groups(q, n_kv, group, n_qblk, bq, rows)

    q_lens = q_lens.astype(jnp.int32)
    kv_lens = kv_lens.astype(jnp.int32)
    n_live = -(-kv_lens // page)
    if window is None:
        lo = jnp.zeros_like(kv_lens)
    else:
        # lower edge of query token 0's band (the widest in the batch);
        # per-row edges re-tighten inside the kernel.  q_len == 1 reduces
        # to the decode kernel's max(len - window, 0).
        lo = jnp.maximum(kv_lens - q_lens - window + 1, 0)

    def q_map(s_, h, qi, j, table, n_live_, kvlen_, qlen_, lo_):
        return (s_, h, qi, 0)

    def kv_map(s_, h, qi, j, table, n_live_, kvlen_, qlen_, lo_):
        # clamp dead page-slots into the live band (duplicate consecutive
        # indexes collapse into one DMA); empty slots stay in range
        slot = jnp.clip(j, lo_[s_] // page, jnp.maximum(n_live_[s_] - 1, 0))
        return (table[s_, slot], h, 0, 0)

    kernel = functools.partial(
        _ragged_kernel, scale=scale, page=page, n_slots=n_slots,
        bq=bq, g=group, quant=quant, window=window,
    )
    in_specs = [
        pl.BlockSpec((1, 1, rows, d), q_map),
        pl.BlockSpec((None, 1, page, d), kv_map),
        pl.BlockSpec((None, 1, page, d), kv_map),
    ]
    inputs = [page_table, n_live, kv_lens, q_lens, lo,
              q_rows, k_pages, v_pages]
    if quant:
        def sc_map(s_, h, qi, j, table, n_live_, kvlen_, qlen_, lo_):
            return kv_map(s_, h, qi, j, table, n_live_, kvlen_, qlen_,
                          lo_)[:3] + (0,)

        in_specs.append(pl.BlockSpec((None, 1, 1, page), sc_map))
        in_specs.append(pl.BlockSpec((None, 1, 1, page), sc_map))
        inputs += [k_scales[:, :, None, :], v_scales[:, :, None, :]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(s, n_kv, n_qblk, n_slots),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rows, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, n_kv, n_qblk * rows, d), q.dtype),
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(*inputs)
    return _unfold_groups(o, n_q, group, n_qblk, bq, rows, qt)


def ragged_paged_reference(q, k_pages, v_pages, page_table, q_lens, kv_lens,
                           *, k_scales=None, v_scales=None, window=None,
                           scale=None):
    """jnp oracle: dense-gathers every slot's pages and runs masked
    softmax with the per-row causal band.  O(S·n_slots·page) memory —
    tests only.  Padding rows (t >= q_lens) and idle slots emit zeros."""
    if k_scales is not None:
        k_pages = k_pages.astype(jnp.float32) * k_scales[..., None]
        v_pages = v_pages.astype(jnp.float32) * v_scales[..., None]
    s, n_q, qt, d = q.shape
    n_kv = k_pages.shape[1]
    page = k_pages.shape[2]
    n_slots = page_table.shape[1]
    group = n_q // n_kv
    if scale is None:
        scale = d**-0.5
    k = k_pages[page_table]  # [S, n_slots, Nkv, page, D]
    v = v_pages[page_table]
    k = jnp.moveaxis(k, 2, 1).reshape(s, n_kv, n_slots * page, d)
    v = jnp.moveaxis(v, 2, 1).reshape(s, n_kv, n_slots * page, d)
    qg = q.reshape(s, n_kv, group, qt, d)
    sc = jnp.einsum("bngtd,bnjd->bngtj", qg.astype(jnp.float32),
                    k.astype(jnp.float32)) * scale
    qp = (kv_lens - q_lens)[:, None] + jnp.arange(qt)[None, :]  # [S, QT]
    col = jnp.arange(n_slots * page)[None, None, :]
    valid = col <= qp[:, :, None]
    if window is not None:
        valid &= col >= (qp[:, :, None] - window + 1)
    valid &= (jnp.arange(qt)[None, :] < q_lens[:, None])[:, :, None]
    sc = jnp.where(valid[:, None, None, :, :], sc, NEG_INF)
    p = jax.nn.softmax(sc, axis=-1)
    p = jnp.where(valid[:, None, None, :, :], p, 0.0)  # masked rows -> 0
    o = jnp.einsum("bngtj,bnjd->bngtd", p, v.astype(jnp.float32))
    return o.reshape(s, n_q, qt, d).astype(q.dtype)
