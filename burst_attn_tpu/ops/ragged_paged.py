"""Ragged paged attention: ONE Pallas launch for a mixed prefill+decode
token batch against the paged KV pool.

ops/paged_attention.py serves exactly one query token per sequence per
launch — fine for pure decode, but a continuous-batching engine lives on
MIXED steps: some slots absorbing a prompt chunk (tens of query tokens),
others decoding (one), all against the same page pool.  Running prefill
and decode as separate programs forfeits the batch (two launches, two
sets of ragged predication, and the prefill chunk's MXU work cannot soak
up the decode slots' latency).  This kernel is the single-launch design
(PAPERS.md "Ragged Paged Attention"):

  * The grid walks `(sequence, kv-head, q-block, page-slot)`.  Each
    sequence brings its OWN query token count `q_lens[s]` (1 = decode,
    up to the chunk size = prefill); per-sequence page tables arrive via
    scalar prefetch and are consulted in the kv index maps, exactly like
    the decode kernel — each grid step DMAs one pool page.
  * GQA folds the query-head group INTO the q tile rows: block rows are
    laid out `token-major x group` (row r = token r//G, head r%G), so a
    decode step (1 token x G heads) and a prefill block (block_q tokens
    x G heads) are the same [rows, page] score tile shape.
  * Causality is enforced within each sequence: query token t of
    sequence s sits at absolute position `kv_lens[s] - q_lens[s] + t`
    and sees cached positions `<= ` that (sliding window optional).
    Page-slots wholly outside a block's visible band are predicated off
    and their DMAs clamped onto a live page (consecutive duplicate block
    indexes collapse into one fetch) — cost per sequence ∝ its length.
  * The inner online-softmax update is OP-FOR-OP the decode kernel's
    (same exp2 rebase, same masking order, same fp32 accumulation), so a
    decode row (q_len == 1) is BIT-IDENTICAL to paged_decode_attention —
    tested, not aspirational: the serving engine may route any step
    through either kernel and streams must not fork.

Interpret mode runs the same grid on CPU, which is how tier-1 proves the
mixed-batch parity (dense oracle + decode-kernel bit compare) off-TPU.
`ragged_supported()` is the capability probe: the serving engine falls
back to the dense-gather path (models/serving) with a labeled
`burst.fused_fallback{pass=serve}` counter instead of raising.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_flash import LOG2E, NEG_INF, VMEM_LIMIT, _interpret_default
from ..utils.compat import tpu_compiler_params

# hard ceiling on the padded rows-per-block tile (block_q * group rounded
# to sublanes): past this the [rows, page] score tile plus the fp32
# accumulator stops fitting VMEM comfortably at page=128, d=128
_MAX_BLOCK_ROWS = 1024


def _ragged_kernel(
    table_ref, n_live_ref, kvlen_ref, qlen_ref, lo_ref,  # scalar prefetch
    *refs,
    scale, page, n_slots, bq, g, quant, window, emit_partials=False,
):
    if quant:
        q_ref, k_ref, v_ref, ks_ref, vs_ref = refs[:5]
        rest = refs[5:]
    else:
        q_ref, k_ref, v_ref = refs[:3]
        rest = refs[3:]
    if emit_partials:
        o_ref, m_ref, l_ref = rest[:3]
        m_scr, l_scr, acc_scr = rest[3:]
    else:
        o_ref = rest[0]
        m_scr, l_scr, acc_scr = rest[1:]
    s_ = pl.program_id(0)
    qi = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_len = qlen_ref[s_]
    kv_len = kvlen_ref[s_]
    q_start = kv_len - q_len          # absolute position of query token 0
    t0 = qi * bq                      # first query token of this block
    # absolute position of the block's LAST real token: everything at or
    # below it is potentially visible, pages wholly above it are dead.
    # (for q_len == 1 this reduces to the decode kernel's j < n_live test)
    p_max = q_start + jnp.minimum(q_len, t0 + bq) - 1
    live = (t0 < q_len) & (j * page <= p_max) & (j >= lo_ref[s_] // page)

    @pl.when(live)
    def _accum():
        q = q_ref[0, 0, :, :] * (scale * LOG2E)
        k_tile = k_ref[0, :, :]
        if quant:
            # int8 and fp8 e4m3 both embed EXACTLY in bf16 (8-bit mantissa
            # covers int8's 8 bits and e4m3's 4-bit mantissa); the fp32
            # column rescale below is the whole dequant for either dtype
            k_tile = k_tile.astype(jnp.bfloat16)
        s = jax.lax.dot_general(
            q, k_tile, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if quant:
            # per-token dequant as a column rescale (decode kernel's trick)
            s = s * ks_ref[0]
        pos = j * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # row r = query token t0 + r//g at absolute position qp; rows past
        # q_len are wrapper padding — their outputs are sliced away, so
        # they need no extra masking (their q rows are zeros / pad tokens
        # and every op below is row-independent)
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        qp = q_start + t0 + row // g
        valid = pos <= qp
        if window is not None:
            valid &= pos >= qp - window + 1
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.where(m_prev >= m_new, 1.0, jnp.exp2(m_prev - m_new))
        p = jnp.exp2(s - m_new)
        p = jnp.where(valid, p, 0.0)
        m_scr[:] = m_new
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        if quant:
            pv = jax.lax.dot_general(
                (p * vs_ref[0]).astype(jnp.bfloat16),
                v_ref[0, :, :].astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            pv = jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0, :, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(j == n_slots - 1)
    def _finish():
        if emit_partials:
            # split-k contract (models/dist_decode._merge, base-2 domain):
            # hand back the UNNORMALIZED accumulator plus the (m, l)
            # running-softmax state so the caller can LSE-merge this
            # partial with another band's.  m/l broadcast across the lane
            # tile; the host reads lane 0.
            o_ref[0, 0, :, :] = acc_scr[:]
            m_ref[0, 0, :, :] = jnp.broadcast_to(m_scr[:], m_ref.shape[2:])
            l_ref[0, 0, :, :] = jnp.broadcast_to(l_scr[:], l_ref.shape[2:])
        else:
            # fully-masked blocks (idle slot / past-q_len block) emit zeros
            l = jnp.where(l_scr[:] > 0, l_scr[:], 1.0)
            o_ref[0, 0, :, :] = (acc_scr[:] / l).astype(o_ref.dtype)


def _block_rows(block_q: int, group: int) -> int:
    """Padded rows per q block: block_q tokens x group heads, rounded up
    to the 8-sublane tile (>= 8, matching _pad_group for block_q == 1)."""
    return max(8, -(-(block_q * group) // 8) * 8)


def ragged_supported(*, n_kv_heads, n_q_heads, q_tokens, d_head, page,
                     quantized=False, block_q=8, interpret=None):
    """Capability probe: None when ragged_paged_attention can serve this
    shape, else a human-readable reason whose PREFIX is a stable key (the
    serving engine maps it to a bounded fallback-counter label).  Mirrors
    fused_ring.supported's contract: probe first, fall back loudly."""
    if interpret is None:
        interpret = _interpret_default()
    if q_tokens < 1:
        return f"empty q chunk: q_tokens {q_tokens} < 1"
    if n_q_heads % n_kv_heads:
        return (f"GQA group mismatch: {n_q_heads} query heads not a "
                f"multiple of {n_kv_heads} kv heads")
    if page % 128:
        return f"page size {page} is not a multiple of the 128 lane tile"
    group = n_q_heads // n_kv_heads
    bq = max(1, min(block_q, q_tokens))
    rows = _block_rows(bq, group)
    if rows > _MAX_BLOCK_ROWS:
        return (f"q-block rows {rows} (block_q {bq} x group {group}, "
                f"padded) exceed the {_MAX_BLOCK_ROWS}-row tile budget")
    # VMEM plan: q + o + acc tiles (fp32) plus a double-buffered k/v page
    kv_bytes = 1 if quantized else 2
    plan = (rows * d_head * 4 * 3          # q, o, acc
            + rows * (page + 2) * 4        # scores + m/l columns
            + 4 * page * d_head * kv_bytes)  # k/v pages, double buffered
    if plan > VMEM_LIMIT:
        return (f"VMEM plan {plan} bytes exceeds the {VMEM_LIMIT} budget "
                f"(page {page}, d_head {d_head}, rows {rows})")
    if not interpret and d_head % 128:
        # compiled Mosaic wants lane-aligned head dims; interpret mode
        # (CPU tier-1) has no such constraint
        return f"head dim {d_head} is not lane-aligned (128) for Mosaic"
    return None


def _fold_groups(q, n_kv, group, n_qblk, bq, rows):
    """[S, Nq, QT, D] -> [S, Nkv, n_qblk*rows, D] token-major x group row
    layout, zero-padded to bq tokens per block and `rows` sublanes."""
    s, _, qt, d = q.shape
    qtp = n_qblk * bq
    q = q.reshape(s, n_kv, group, qt, d)
    if qtp != qt:
        q = jnp.pad(q, [(0, 0), (0, 0), (0, 0), (0, qtp - qt), (0, 0)])
    q = jnp.moveaxis(q, 2, 3)                       # [S, Nkv, QTp, G, D]
    q = q.reshape(s, n_kv, n_qblk, bq * group, d)
    if rows != bq * group:
        q = jnp.pad(q, [(0, 0), (0, 0), (0, 0), (0, rows - bq * group),
                        (0, 0)])
    return q.reshape(s, n_kv, n_qblk * rows, d)


def _unfold_groups(o, n_q, group, n_qblk, bq, rows, qt):
    """Inverse of _fold_groups: [S, Nkv, n_qblk*rows, D] -> [S, Nq, QT, D]."""
    s, n_kv, _, d = o.shape
    o = o.reshape(s, n_kv, n_qblk, rows, d)[:, :, :, :bq * group]
    o = o.reshape(s, n_kv, n_qblk * bq, group, d)
    o = jnp.moveaxis(o, 3, 2)                       # [S, Nkv, G, QTp, D]
    return o.reshape(s, n_q, n_qblk * bq, d)[:, :, :qt]


def ragged_paged_attention(q, k_pages, v_pages, page_table, q_lens, kv_lens,
                           *, k_scales=None, v_scales=None, window=None,
                           scale=None, block_q=8, interpret=None,
                           ctx_lo=None, emit_partials=False):
    """Mixed prefill+decode ragged attention against a paged KV pool.

    q          [S, Nq, QT, D]   query tokens per slot; slot s's token t is
                                the token at absolute position
                                kv_lens[s] - q_lens[s] + t.  Rows at or
                                past q_lens[s] are padding (outputs there
                                are garbage the caller must ignore).
    k_pages    [P, Nkv, page, D]  shared pool — the new tokens' K/V must
    v_pages    [P, Nkv, page, D]  already be scattered in (the serving
                                  step scatters BEFORE attending)
    page_table [S, n_slots] int32 pool page per (slot, table column)
    q_lens     [S] int32        query tokens this launch (0 = idle slot;
                                1 = decode; >1 = prefill chunk)
    kv_lens    [S] int32        total live tokens INCLUDING this launch's
    window     static int       sliding-window band per query position
    k_scales / v_scales         per-token fp32 dequant scales for 1 B/elem
                                (int8 or fp8-e4m3) pools; either quantized
                                dtype rides the same bf16-embed + column
                                rescale, so the kernel never branches on it
    block_q    static int       query tokens per grid block

    ctx_lo / emit_partials are the split-k hooks the grouped shared-prefix
    front-end (ragged_paged_attention_grouped) drives; plain callers leave
    them at their defaults and the traced program is unchanged:

    ctx_lo     [S] int32        PAGE-ALIGNED per-slot lower context bound —
                                pool positions below it are excluded (their
                                page-slots predicated off, exactly the `lo`
                                page-skip the window path uses)
    emit_partials  static bool  return the unnormalized split-k partial
                                (acc [S,Nq,QT,D], m [S,Nq,QT,1],
                                l [S,Nq,QT,1], all fp32, base-2 softmax
                                domain) instead of the normalized output

    Returns [S, Nq, QT, D] in q's dtype.  A pure-decode batch (QT == 1)
    is bit-identical to paged_decode_attention on the same pool.
    """
    s, n_q, qt, d = q.shape
    n_kv = k_pages.shape[1]
    page = k_pages.shape[2]
    n_slots = page_table.shape[1]
    if n_q % n_kv:
        raise ValueError(f"{n_q} query heads not grouped by {n_kv} kv heads")
    group = n_q // n_kv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = _interpret_default()
    quant = k_scales is not None
    if quant != (v_scales is not None):
        raise ValueError("k_scales and v_scales must be given together")

    bq = max(1, min(block_q, qt))
    n_qblk = -(-qt // bq)
    rows = _block_rows(bq, group)
    q_rows = _fold_groups(q, n_kv, group, n_qblk, bq, rows)

    q_lens = q_lens.astype(jnp.int32)
    kv_lens = kv_lens.astype(jnp.int32)
    n_live = -(-kv_lens // page)
    if window is None:
        lo = jnp.zeros_like(kv_lens)
    else:
        # lower edge of query token 0's band (the widest in the batch);
        # per-row edges re-tighten inside the kernel.  q_len == 1 reduces
        # to the decode kernel's max(len - window, 0).
        lo = jnp.maximum(kv_lens - q_lens - window + 1, 0)
    if ctx_lo is not None:
        # page-aligned exclusion of a shared-prefix band: whole pages drop
        # out of the `live` predicate, so no sub-page masking is needed
        lo = jnp.maximum(lo, ctx_lo.astype(jnp.int32))

    def q_map(s_, h, qi, j, table, n_live_, kvlen_, qlen_, lo_):
        return (s_, h, qi, 0)

    def kv_map(s_, h, qi, j, table, n_live_, kvlen_, qlen_, lo_):
        # clamp dead page-slots into the live band (duplicate consecutive
        # indexes collapse into one DMA); empty slots stay in range
        slot = jnp.clip(j, lo_[s_] // page, jnp.maximum(n_live_[s_] - 1, 0))
        return (table[s_, slot], h, 0, 0)

    kernel = functools.partial(
        _ragged_kernel, scale=scale, page=page, n_slots=n_slots,
        bq=bq, g=group, quant=quant, window=window,
        emit_partials=emit_partials,
    )
    in_specs = [
        pl.BlockSpec((1, 1, rows, d), q_map),
        pl.BlockSpec((None, 1, page, d), kv_map),
        pl.BlockSpec((None, 1, page, d), kv_map),
    ]
    inputs = [page_table, n_live, kv_lens, q_lens, lo,
              q_rows, k_pages, v_pages]
    if quant:
        def sc_map(s_, h, qi, j, table, n_live_, kvlen_, qlen_, lo_):
            return kv_map(s_, h, qi, j, table, n_live_, kvlen_, qlen_,
                          lo_)[:3] + (0,)

        in_specs.append(pl.BlockSpec((None, 1, 1, page), sc_map))
        in_specs.append(pl.BlockSpec((None, 1, 1, page), sc_map))
        inputs += [k_scales[:, :, None, :], v_scales[:, :, None, :]]
    out_spec = pl.BlockSpec((1, 1, rows, d), q_map)
    out_shape = jax.ShapeDtypeStruct((s, n_kv, n_qblk * rows, d), q.dtype)
    if emit_partials:
        # acc stays fp32 (unnormalized); m/l ride in d-wide lane tiles
        f32 = functools.partial(jax.ShapeDtypeStruct,
                                (s, n_kv, n_qblk * rows, d))
        out_shape = (f32(jnp.float32), f32(jnp.float32), f32(jnp.float32))
        out_spec = (out_spec, out_spec, out_spec)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(s, n_kv, n_qblk, n_slots),
        in_specs=in_specs,
        out_specs=out_spec,
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=VMEM_LIMIT,
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(*inputs)
    unfold = functools.partial(_unfold_groups, n_q=n_q, group=group,
                               n_qblk=n_qblk, bq=bq, rows=rows, qt=qt)
    if emit_partials:
        acc, m, l = o
        return unfold(acc), unfold(m)[..., :1], unfold(l)[..., :1]
    return unfold(o)


def ragged_paged_attention_grouped(
        q, k_pages, v_pages, page_table, q_lens, kv_lens, *,
        group_id, shared_table, shared_lens,
        k_scales=None, v_scales=None, window=None, scale=None,
        block_q=8, interpret=None):
    """Shared-prefix grouped variant: score each group's shared pages ONCE,
    LSE-merge with every member's private-suffix partial.

    Co-batched requests admitted through the prefix cache pin the SAME
    physical pages for their common prompt prefix.  The plain launch walks
    every slot's full page table, re-fetching (and re-scoring) those pages
    per member.  Here the pool gather for the shared band happens once per
    GROUP (`k_pages[shared_table]` — G x n_sh pages instead of S x n_slots),
    members score against the group buffer, and the result is merged with
    the private band exactly the way models/dist_decode._merge folds split-k
    partials — in the kernel's base-2 softmax domain, so the merge algebra
    matches the one-launch online softmax op for op.

    group_id     [S] int32      group index per slot; slots whose group has
                                shared_lens == 0 degenerate to the plain
                                launch result (merge with an empty partial)
    shared_table [G, n_sh] int32  pool pages of each group's shared prefix
                                (page-0 padded past its length)
    shared_lens  [G] int32      shared tokens per group — MUST be a page
                                multiple (the private band's page-skip is
                                whole-page)

    Every member's shared pages must be a prefix of its own page table
    (the admission path guarantees this: hit pages are assigned before
    private pages), and causal masking is applied per query row, so a
    query INSIDE the shared band (mid-prefill after a partial hit) still
    sees exactly positions <= its own.

    Returns [S, Nq, QT, D] in q's dtype — numerically equal to the plain
    launch up to split-k merge reassociation (parity-tested, not bitwise).
    """
    s, n_q, qt, d = q.shape
    n_kv = k_pages.shape[1]
    page = k_pages.shape[2]
    group = n_q // n_kv
    n_sh = shared_table.shape[1]
    if scale is None:
        scale = d**-0.5
    group_id = group_id.astype(jnp.int32)
    shared_lens = shared_lens.astype(jnp.int32)
    ctx_lo = shared_lens[group_id]

    # private band: the one-launch kernel, pages below the shared boundary
    # predicated off, partials handed back unnormalized
    acc_p, m_p, l_p = ragged_paged_attention(
        q, k_pages, v_pages, page_table, q_lens, kv_lens,
        k_scales=k_scales, v_scales=v_scales, window=window, scale=scale,
        block_q=block_q, interpret=interpret,
        ctx_lo=ctx_lo, emit_partials=True)

    # shared band: ONE pool gather per group, then a broadcast view per
    # member.  The quant path mirrors the kernel's precision op for op
    # (k scored as bf16 with a post-dot column rescale, p*v folded through
    # bf16) so grouped-vs-plain parity holds at merge-reassociation level
    # rather than dequant level.
    quant = k_scales is not None
    g_n = shared_table.shape[0]

    def _flat(pages, width=d):
        t = jnp.moveaxis(pages, 2, 1)          # [G, Nkv, n_sh, page, ...]
        return t.reshape(g_n, n_kv, n_sh * page, *t.shape[4:])[group_id]

    k_s = _flat(k_pages[shared_table])         # [S, Nkv, Tsh, D]
    v_s = _flat(v_pages[shared_table])
    qg = q.reshape(s, n_kv, group, qt, d).astype(jnp.float32)
    qg = qg * (scale * LOG2E)                  # base-2 domain, as the kernel
    if quant:
        k_cols = _flat(k_scales[shared_table][..., None])[..., 0]
        v_cols = _flat(v_scales[shared_table][..., None])[..., 0]
        sc = jnp.einsum("bngtd,bnjd->bngtj", qg, k_s.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32)
        sc = sc * k_cols[:, :, None, None, :]
    else:
        sc = jnp.einsum("bngtd,bnjd->bngtj", qg, k_s.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
    qp = (kv_lens - q_lens)[:, None] + jnp.arange(qt)[None, :]   # [S, QT]
    col = jnp.arange(n_sh * page)
    valid = (col[None, None, :] <= qp[:, :, None])
    valid &= col[None, None, :] < shared_lens[group_id][:, None, None]
    if window is not None:
        valid &= col[None, None, :] >= qp[:, :, None] - window + 1
    sc = jnp.where(valid[:, None, None, :, :], sc, NEG_INF)
    m_s = jnp.max(sc, axis=-1, keepdims=True)        # [S,Nkv,G,QT,1]
    p = jnp.where(valid[:, None, None, :, :], jnp.exp2(sc - m_s), 0.0)
    l_s = jnp.sum(p, axis=-1, keepdims=True)
    if quant:
        acc_s = jnp.einsum(
            "bngtj,bnjd->bngtd",
            (p * v_cols[:, :, None, None, :]).astype(jnp.bfloat16),
            v_s.astype(jnp.bfloat16), preferred_element_type=jnp.float32)
    else:
        acc_s = jnp.einsum("bngtj,bnjd->bngtd", p, v_s.astype(jnp.float32),
                           preferred_element_type=jnp.float32)
    m_s = m_s.reshape(s, n_q, qt, 1)
    l_s = l_s.reshape(s, n_q, qt, 1)
    acc_s = acc_s.reshape(s, n_q, qt, d)

    # split-k merge (dist_decode._merge in base 2, -inf guarded the way
    # the kernel guards its alpha rebase)
    m_g = jnp.maximum(m_p, m_s)
    a_p = jnp.where(m_p >= m_g, 1.0, jnp.exp2(m_p - m_g))
    a_s = jnp.where(m_s >= m_g, 1.0, jnp.exp2(m_s - m_g))
    l_g = l_p * a_p + l_s * a_s
    acc_g = acc_p * a_p + acc_s * a_s
    o = acc_g / jnp.where(l_g > 0, l_g, 1.0)
    return o.astype(q.dtype)


def ragged_paged_reference(q, k_pages, v_pages, page_table, q_lens, kv_lens,
                           *, k_scales=None, v_scales=None, window=None,
                           scale=None):
    """jnp oracle: dense-gathers every slot's pages and runs masked
    softmax with the per-row causal band.  O(S·n_slots·page) memory —
    tests only.  Padding rows (t >= q_lens) and idle slots emit zeros."""
    if k_scales is not None:
        k_pages = k_pages.astype(jnp.float32) * k_scales[..., None]
        v_pages = v_pages.astype(jnp.float32) * v_scales[..., None]
    s, n_q, qt, d = q.shape
    n_kv = k_pages.shape[1]
    page = k_pages.shape[2]
    n_slots = page_table.shape[1]
    group = n_q // n_kv
    if scale is None:
        scale = d**-0.5
    k = k_pages[page_table]  # [S, n_slots, Nkv, page, D]
    v = v_pages[page_table]
    k = jnp.moveaxis(k, 2, 1).reshape(s, n_kv, n_slots * page, d)
    v = jnp.moveaxis(v, 2, 1).reshape(s, n_kv, n_slots * page, d)
    qg = q.reshape(s, n_kv, group, qt, d)
    sc = jnp.einsum("bngtd,bnjd->bngtj", qg.astype(jnp.float32),
                    k.astype(jnp.float32)) * scale
    qp = (kv_lens - q_lens)[:, None] + jnp.arange(qt)[None, :]  # [S, QT]
    col = jnp.arange(n_slots * page)[None, None, :]
    valid = col <= qp[:, :, None]
    if window is not None:
        valid &= col >= (qp[:, :, None] - window + 1)
    valid &= (jnp.arange(qt)[None, :] < q_lens[:, None])[:, :, None]
    sc = jnp.where(valid[:, None, None, :, :], sc, NEG_INF)
    p = jax.nn.softmax(sc, axis=-1)
    p = jnp.where(valid[:, None, None, :, :], p, 0.0)  # masked rows -> 0
    o = jnp.einsum("bngtj,bnjd->bngtd", p, v.astype(jnp.float32))
    return o.reshape(s, n_q, qt, d).astype(q.dtype)
