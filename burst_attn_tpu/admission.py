"""Typed submission outcomes + SLO-gated admission control for the serve
engines.

Both engines (models/serve.ServeEngine and serving.RaggedServeEngine)
share this vocabulary so a request ROUTER — loadgen/cluster.py, or any
frontend — can tell pool pressure from queue pressure without scraping
the `serve.requests_rejected{reason=…}` counter:

  * `RejectReason` — one enum member per rejection label.  The enum VALUE
    is the counter label string, asserted in tests, so dashboards and
    router code speak the same names.
  * `InvalidRequest` / `LoadShed` — the typed exceptions `submit()`
    raises.  They subclass ValueError / RuntimeError respectively, so
    every pre-existing caller (and `pytest.raises`) keeps working; new
    callers read `.reason` instead of parsing messages.
  * `SubmitResult` + `Engine.try_submit()` — the non-raising surface a
    router actually wants: a request id on success, a typed reason (and
    a `retryable` bit: sheds clear, malformed never does) on rejection.
  * `AdmissionPolicy` — hysteresis load-shedding driven by the SAME live
    values the `serve.queue_depth` / `serve.page_pool_occupancy` gauges
    export.  The hard checks shed only at exhaustion (pool literally out
    of pages, queue literally full); the policy sheds EARLY — above a
    high-water mark — and keeps shedding until pressure falls back below
    a low-water mark, so a saturated engine drains instead of oscillating
    admit/shed at the cliff edge.  Ordering extends the PR 7 contract:
    pool pressure sheds before queue pressure.

Host-side only (no jax imports): admission decisions happen between
jitted steps, exactly like the page pool itself.
"""

import enum
from dataclasses import dataclass, field
from typing import Optional


class RejectReason(str, enum.Enum):
    """Why `submit()` refused a request.  Values ARE the
    `serve.requests_rejected{reason=…}` counter labels."""

    # malformed / permanently unservable (InvalidRequest — never retry)
    EMPTY_PROMPT = "empty-prompt"
    BAD_BUDGET = "bad-budget"
    TABLE_WIDTH = "table-width"
    POOL_SIZE = "pool-size"
    # transient load sheds (LoadShed — retry after backoff)
    POOL_EXHAUSTED = "pool-exhausted"
    QUEUE_FULL = "queue-full"
    ADMISSION_POOL = "admission-pool"
    ADMISSION_QUEUE = "admission-queue"

    def __str__(self) -> str:  # counter label / log friendly
        return self.value

    @property
    def retryable(self) -> bool:
        """Sheds clear when load drops; malformed requests never will."""
        return self in _RETRYABLE


_RETRYABLE = frozenset({
    RejectReason.POOL_EXHAUSTED, RejectReason.QUEUE_FULL,
    RejectReason.ADMISSION_POOL, RejectReason.ADMISSION_QUEUE,
})


class SubmitRejected(Exception):
    """Mixin base for typed submit() rejections; `.reason` is the enum."""

    def __init__(self, reason: RejectReason, message: str):
        super().__init__(message)
        self.reason = RejectReason(reason)


class InvalidRequest(SubmitRejected, ValueError):
    """Malformed / permanently unservable — retrying can never succeed."""


class LoadShed(SubmitRejected, RuntimeError):
    """Transient overload shed — retry once pressure drops."""


@dataclass(frozen=True)
class SubmitResult:
    """`try_submit()` outcome: `rid` on success, typed `reason` on
    rejection (plus the human-readable message for logs)."""

    rid: Optional[int] = None
    reason: Optional[RejectReason] = None
    message: str = ""

    @property
    def ok(self) -> bool:
        return self.rid is not None

    @property
    def retryable(self) -> bool:
        return self.reason is not None and self.reason.retryable


@dataclass
class AdmissionPolicy:
    """Hysteresis load shedding from live queue depth + pool occupancy.

    `decide()` is called by `submit()` with the same values the engine's
    gauges export (`serve.page_pool_occupancy` as a fraction of usable
    pages, `serve.queue_depth` as a count).  Each pressure axis carries a
    high/low water mark: shedding STARTS when the live value crosses the
    high mark and STOPS only when it falls back below the low mark — an
    engine at the cliff edge drains a real margin before re-admitting
    instead of flapping.  Pool pressure is evaluated (and shed) before
    queue pressure, extending the hard-shed ordering.

    Set a high mark to None to disable that axis.  One policy instance
    belongs to ONE engine (it carries hysteresis state).
    """

    pool_high: Optional[float] = 0.95
    pool_low: float = 0.80
    queue_high: Optional[int] = None
    queue_low: int = 0
    shed_pool: int = field(default=0, init=False)   # decisions, for tests
    shed_queue: int = field(default=0, init=False)
    _pool_shedding: bool = field(default=False, init=False)
    _queue_shedding: bool = field(default=False, init=False)

    def __post_init__(self):
        if self.pool_high is not None and not self.pool_low <= self.pool_high:
            raise ValueError(
                f"pool_low {self.pool_low} must be <= pool_high "
                f"{self.pool_high}")
        if (self.queue_high is not None
                and not self.queue_low <= self.queue_high):
            raise ValueError(
                f"queue_low {self.queue_low} must be <= queue_high "
                f"{self.queue_high}")

    def decide(self, *, queue_depth: int,
               pool_occupancy: float) -> Optional[RejectReason]:
        """Typed shed reason, or None to admit.  Updates hysteresis state."""
        if self.pool_high is not None:
            if self._pool_shedding:
                self._pool_shedding = pool_occupancy >= self.pool_low
            elif pool_occupancy >= self.pool_high:
                self._pool_shedding = True
            if self._pool_shedding:
                self.shed_pool += 1
                return RejectReason.ADMISSION_POOL
        if self.queue_high is not None:
            if self._queue_shedding:
                self._queue_shedding = queue_depth > self.queue_low
            elif queue_depth >= self.queue_high:
                self._queue_shedding = True
            if self._queue_shedding:
                self.shed_queue += 1
                return RejectReason.ADMISSION_QUEUE
        return None
