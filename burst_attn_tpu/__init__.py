"""burst-attn-tpu: TPU-native distributed (ring) exact attention.

A from-scratch JAX/XLA/Pallas framework with the capabilities of
MayDomine/Burst-Attention (see SURVEY.md): sequence-parallel exact attention
with FlashAttention-style online softmax, `lax.ppermute` rings under
`shard_map`, hierarchical double ring over an ("inter", "intra") device mesh,
causal load balancing via zigzag-half and striped token layouts, and a
communication-optimized backward pass (rotating query-side tensors plus an
accumulating-dq ring).

Public API (reference parity: burst_attn/burst_attn_interface.py:109-158):
    burst_attn            -- global-array entry point (applies shard_map)
    burst_attn_shard      -- per-shard entry point (call inside shard_map)
    burst_attn_func       -- reference-style alias (zigzag layout)
    burst_attn_func_striped -- reference-style alias (striped layout)
    BurstConfig           -- static configuration
"""

from .parallel.burst import (
    BurstConfig,
    burst_attn,
    burst_attn_shard,
    burst_attn_func,
    burst_attn_func_striped,
)
from .parallel.ulysses import ulysses_attn
from .parallel.pipeline import pipeline, stack_stages
from .parallel.moe import MoEParams, init_moe_params, moe_apply
from .parallel import layouts
from .ops import masks, tile, reference
from . import obs

__all__ = [
    "obs",
    "BurstConfig",
    "burst_attn",
    "burst_attn_shard",
    "burst_attn_func",
    "burst_attn_func_striped",
    "ulysses_attn",
    "pipeline",
    "stack_stages",
    "MoEParams",
    "init_moe_params",
    "moe_apply",
    "layouts",
    "masks",
    "tile",
    "reference",
]

__version__ = "0.1.0"
