"""ctypes binding for the native C++ prefetching token loader.

The native side (native/dataloader.cpp) mmaps a "BATD" token file and keeps
`queue_depth` ready [batch, seq_len+1] int32 buffers filled by worker
threads; this side hands out (inputs, targets) numpy views and optionally
`jax.device_put`s them.  The loader is deterministic and seekable, so
checkpoint resume (utils/checkpoint.py) just calls `seek(step)`.

Sharding for data parallelism is window-interleaved: rank r of R owns
windows w ≡ r (mod R) — disjoint across ranks, no coordination.  In a
multi-process run pass `shard_id=jax.process_index()`.

The shared library is compiled on first use with the system g++ (the image
has no pybind11; a plain C ABI + ctypes keeps the binding dependency-free)
and cached beside the source.
"""

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from ..obs.logs import get_logger, safe_warn

_logger = get_logger("burst_attn_tpu.data")

_MAGIC = 0x44544142  # "BATD"
_HEADER = 16

_lib = None
_lib_lock = threading.Lock()


def _native_dir() -> Path:
    return Path(__file__).resolve().parents[2] / "native"


def _build_lib(src: Path, out: Path) -> None:
    out.parent.mkdir(parents=True, exist_ok=True)
    # pid-unique tmp + atomic rename: concurrent first-use builds (several
    # data-parallel processes starting at once) must not interleave writes
    tmp = out.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           str(src), "-o", str(tmp)]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, out)
    finally:
        tmp.unlink(missing_ok=True)


def _load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        src = _native_dir() / "dataloader.cpp"
        out = _native_dir() / "build" / "libdataloader.so"
        if not out.exists() or out.stat().st_mtime < src.stat().st_mtime:
            _build_lib(src, out)
        lib = ctypes.CDLL(str(out))
        lib.dl_open.restype = ctypes.c_void_p
        lib.dl_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.dl_next.restype = ctypes.c_int64
        lib.dl_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
        lib.dl_seek.restype = None
        lib.dl_seek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.dl_num_tokens.restype = ctypes.c_int64
        lib.dl_num_tokens.argtypes = [ctypes.c_void_p]
        lib.dl_windows_per_epoch.restype = ctypes.c_int64
        lib.dl_windows_per_epoch.argtypes = [ctypes.c_void_p]
        lib.dl_close.restype = None
        lib.dl_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def write_token_file(path, tokens: np.ndarray) -> None:
    """Write a BATD token file (uint16 when vocab fits, else uint32)."""
    tokens = np.asarray(tokens)
    if tokens.ndim != 1:
        tokens = tokens.reshape(-1)
    if np.issubdtype(tokens.dtype, np.signedinteger) and tokens.min() < 0:
        raise ValueError("token ids must be non-negative")
    dtype = np.uint16 if tokens.max() < 2**16 else np.uint32
    header = np.array([_MAGIC, 1, dtype().itemsize, 0], np.uint32)
    with open(path, "wb") as f:
        f.write(header.tobytes())
        f.write(np.ascontiguousarray(tokens, dtype).tobytes())


def read_token_file(path) -> np.ndarray:
    """Read a whole BATD file back (for tests / inspection)."""
    raw = Path(path).read_bytes()
    header = np.frombuffer(raw[:_HEADER], np.uint32)
    if header[0] != _MAGIC or header[1] != 1:
        raise ValueError(f"{path}: not a BATD v1 file")
    dtype = np.uint16 if header[2] == 2 else np.uint32
    return np.frombuffer(raw[_HEADER:], dtype)


class DataLoader:
    """Iterator of (inputs [B,S] int32, targets [B,S] int32) batches.

    Targets are inputs shifted by one token (next-token LM objective); the
    native side delivers [B, S+1] windows so both returned arrays are views
    of ONE per-call buffer (no slice copies; the buffer is freshly allocated
    each call, so batches stay valid indefinitely and can be device_put
    asynchronously while the workers fill the next window).
    """

    def __init__(
        self,
        path,
        batch: int,
        seq_len: int,
        *,
        shard_id: int = 0,
        num_shards: int = 1,
        seed: int = 0,
        shuffle: bool = True,
        num_threads: int = 2,
        queue_depth: int = 4,
    ):
        self._lib = _load_lib()
        self._h = self._lib.dl_open(
            str(path).encode(), seq_len, batch, shard_id, num_shards,
            seed, num_threads, queue_depth, int(shuffle),
        )
        if not self._h:
            raise ValueError(
                f"dl_open failed for {path} (bad file/params: batch={batch}, "
                f"seq_len={seq_len}, shard {shard_id}/{num_shards}; the file "
                f"needs >= num_shards * (seq_len+1) tokens)")
        self.batch = batch
        self.seq_len = seq_len
        self.step = 0

    @property
    def num_tokens(self) -> int:
        return self._lib.dl_num_tokens(self._h)

    @property
    def windows_per_epoch(self) -> int:
        """Windows owned by THIS shard per epoch."""
        return self._lib.dl_windows_per_epoch(self._h)

    def seek(self, step: int) -> None:
        """Reposition so the next batch is `step` (checkpoint resume)."""
        self._lib.dl_seek(self._h, step)
        self.step = step

    def next(self) -> Tuple[np.ndarray, np.ndarray]:
        window = np.empty((self.batch, self.seq_len + 1), np.int32)
        ptr = window.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        got = self._lib.dl_next(self._h, ptr)
        if got < 0:
            raise RuntimeError("dl_next failed")
        self.step = got + 1
        return window[:, :-1], window[:, 1:]

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.dl_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception as e:  # noqa: BLE001 — __del__ must not raise
            # interpreter teardown: even logging can fail here, so route
            # through obs.safe_warn (swallow-proof; failed emissions are
            # kept in obs.logs._DROPPED instead of vanishing)
            safe_warn(_logger, "DataLoader.__del__: close failed (%s: %s)",
                      type(e).__name__, e)
