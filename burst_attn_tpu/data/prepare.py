"""Corpus preparation CLI: raw text/binary files -> a BATD token shard.

Byte-level encoding (vocab 256) is the self-contained default — no external
tokenizer artifacts needed; pass --vocab-offset to reserve low ids for
special tokens.  For subword vocabularies, tokenize externally and call
data.write_token_file on the id array instead.

    python -m burst_attn_tpu.data.prepare --out corpus.batd a.txt b.txt
"""

import argparse
from pathlib import Path

import numpy as np

from .loader import write_token_file


def encode_bytes(paths, vocab_offset: int = 0, doc_sep: int = -1):
    """Concatenate files as uint8 streams (+offset), optionally separated by
    a document-separator id.  Returns one int32 token array."""
    parts = []
    for p in paths:
        data = np.frombuffer(Path(p).read_bytes(), np.uint8).astype(np.int32)
        parts.append(data + vocab_offset)
        if doc_sep >= 0:
            parts.append(np.array([doc_sep], np.int32))
    if doc_sep >= 0 and parts:
        parts.pop()  # no trailing separator
    return np.concatenate(parts) if parts else np.zeros((0,), np.int32)


def main(argv=None):
    p = argparse.ArgumentParser(description="Pack files into a BATD token shard.")
    p.add_argument("inputs", nargs="+", help="text/binary files (read as bytes)")
    p.add_argument("--out", required=True)
    p.add_argument("--vocab-offset", type=int, default=0,
                   help="add this to every byte id (reserve special tokens)")
    p.add_argument("--doc-sep", type=int, default=-1,
                   help="token id inserted between files (-1 = none)")
    args = p.parse_args(argv)
    tokens = encode_bytes(args.inputs, args.vocab_offset, args.doc_sep)
    if not len(tokens):
        raise SystemExit("no tokens produced")
    write_token_file(args.out, tokens)
    print(f"{args.out}: {len(tokens)} tokens "
          f"(vocab needs >= {int(tokens.max()) + 1})")


if __name__ == "__main__":
    main()
