"""Data subsystem: native (C++) token loader + dataset file utilities."""

from .loader import DataLoader, write_token_file, read_token_file

__all__ = ["DataLoader", "write_token_file", "read_token_file"]
