"""Pure fleet scheduling policies: one decision surface, two executors.

The router inside `FleetCluster` (fleet.py) and the discrete-event
simulator (sim.py) must make the SAME decisions from the same state, or
every policy the simulator finds at 1000 replicas is fiction at 10.
This module is that shared surface: a `FleetState` protocol describing
what a scheduler may observe, and pure `(state, event) -> decision`
functions for each decision the fleet takes — route, admit/shed,
preempt, autoscale.  Both executors delegate here (the delegation is
spy-asserted in tests/test_fleet_sim.py, the same pattern protocols/
uses for the serving state machines), so a policy promoted from the
simulator IS the policy production runs.

Purity contract (burstlint rule `policy-pure`, analysis/policycheck.py,
AST-proven with zero suppressions):

  * no wall clock — time is whatever the executor's event loop says;
  * no RNG, global or seeded — decisions are functions of state only;
  * no module state — every call is replayable; tick counters thread
    through arguments and return values;
  * no transport — policies see gauges, never sockets or queues.

Decision semantics (bit-identical to the pre-refactor inline router,
pinned by tests):

  route_least_loaded   min over replicas of the admission-gauge score
                       `(slots_free <= 0, occ + staged, wid)` — fewest
                       live+staged sequences, preferring a free slot.
  autoscale            pressure ticks (queue waiting AND zero free
                       slots) against `scale_up_after` with boot-aware
                       capacity; per-replica idle ticks against
                       `scale_down_after`, at most one retirement per
                       tick, never below `min_decode`, never while the
                       queue is non-empty.

The simulator-searched policies (affinity / ttft_tpot / fair_tenant /
priority_preempt) live here too, so the promotion gate (docs/fleet.md)
is a one-line default change, not a port.
"""

from typing import (Callable, Dict, Mapping, NamedTuple, Optional, Sequence,
                    Tuple)

try:  # 3.8+: structural typing for the state the executors expose
    from typing import Protocol
except ImportError:  # pragma: no cover - ancient interpreter
    Protocol = object  # type: ignore[assignment]


class ReplicaView(NamedTuple):
    """One decode replica's admission gauges, as the router sees them
    (ride every pong/done/admitted message in the real fleet; maintained
    incrementally by the simulator)."""

    wid: int
    occ: int = 0            # live decode sequences
    staged: int = 0         # transfers staged, not yet admitted
    slots_free: int = 1     # free decode slots reported
    quiet: bool = False     # no work, no staging, nothing in flight
    templates: Tuple[int, ...] = ()  # warm shared-prefix template seeds


class RunView(NamedTuple):
    """One live decode run, as preemption candidates are presented."""

    rid: int
    priority: int = 0
    kv_tokens: int = 0      # resident KV length = evict-and-resume price


class ReqView(NamedTuple):
    """One request at a decision point (routing / admission / dequeue)."""

    rid: int
    prompt_len: int = 0
    max_new_tokens: int = 0
    tenant: int = -1
    priority: int = 0
    template_seed: int = -1
    overlap_len: int = 0


class FleetState(Protocol):
    """What a policy may observe.  Executors implement this however they
    like (the fleet snapshots its gauge dicts; the simulator exposes an
    incrementally-maintained candidate index) — policies only read it.

    `replicas` must be wid-sorted and must always contain the replica a
    full least-loaded scan would pick (an executor may pre-filter for
    scale, but never drop the argmin)."""

    replicas: Sequence[ReplicaView]
    queue_depth: int        # requests waiting for a prefill worker
    wait_for_decode: int    # queue + complete transfers with no replica
    booting: int            # spawned replicas that have not reported ready

    def warm_candidates(self, template_seed: int) -> Sequence[ReplicaView]:
        """Replicas holding `template_seed` warm (prefix pages resident)."""
        ...


class FleetView(NamedTuple):
    """Concrete `FleetState`: the snapshot the real router builds per
    decision (replicas wid-sorted)."""

    replicas: Tuple[ReplicaView, ...] = ()
    queue_depth: int = 0
    wait_for_decode: int = 0
    booting: int = 0

    def warm_candidates(self, template_seed: int) -> Tuple[ReplicaView, ...]:
        return tuple(r for r in self.replicas
                     if template_seed in r.templates)


class ScaleParams(NamedTuple):
    """Autoscale thresholds (FleetCluster constructor knobs)."""

    scale_up_after: int
    scale_down_after: int
    max_decode: int
    min_decode: int


class ScaleDecision(NamedTuple):
    """What one autoscale tick decided.  `up` and `down` may BOTH fire
    in one tick (the pre-refactor router allowed it: pressure can come
    from unassigned transfers while the prefill queue is empty and some
    replica has idled past its threshold)."""

    up: bool = False
    down: Optional[int] = None


# --------------------------------------------------------------------------
# routing


def route_least_loaded(state: FleetState,
                       req: Optional[ReqView] = None) -> Optional[int]:
    """Production default: fewest live+staged sequences, preferring
    replicas that report a free slot.  Bit-identical to the pre-refactor
    `FleetCluster._pick_decode` score."""
    best = None
    best_score = None
    for r in state.replicas:
        score = (r.slots_free <= 0, r.occ + r.staged, r.wid)
        if best_score is None or score < best_score:
            best, best_score = r.wid, score
    return best


def route_affinity(state: FleetState,
                   req: Optional[ReqView] = None) -> Optional[int]:
    """TTFT-greedy: a replica with the request's template warm skips
    shipping the shared prefix, so always take one if it has a free
    slot — accepting the prefix cache's rich-get-richer load skew."""
    if req is not None and req.template_seed >= 0 and req.overlap_len > 0:
        best = None
        best_score = None
        for r in state.warm_candidates(req.template_seed):
            if r.slots_free <= 0:
                continue
            score = (r.occ + r.staged, r.wid)
            if best_score is None or score < best_score:
                best, best_score = r.wid, score
        if best is not None:
            return best
    return route_least_loaded(state, req)


TTFT_TPOT_OCC_CAP = 3  # warm-affinity detour allowed this far above argmin


def route_ttft_tpot(state: FleetState,
                    req: Optional[ReqView] = None) -> Optional[int]:
    """TTFT-vs-TPOT aware: take the warm replica (TTFT win: no prefix
    re-ship) only while its load stays within `TTFT_TPOT_OCC_CAP` of the
    least-loaded choice — past that, the co-resident decode slowdown
    (TPOT) outweighs the ship saving and we fall back to least-loaded."""
    fallback = route_least_loaded(state, req)
    if req is None or req.template_seed < 0 or req.overlap_len <= 0 \
            or fallback is None:
        return fallback
    floor = None
    for r in state.replicas:
        if r.wid == fallback:
            floor = r.occ + r.staged
            break
    if floor is None:
        return fallback
    best = None
    best_score = None
    for r in state.warm_candidates(req.template_seed):
        if r.slots_free <= 0 or r.occ + r.staged > floor + TTFT_TPOT_OCC_CAP:
            continue
        score = (r.occ + r.staged, r.wid)
        if best_score is None or score < best_score:
            best, best_score = r.wid, score
    return best if best is not None else fallback


# name -> module attribute; executors resolve through getattr so tests
# can spy on the delegation by monkeypatching the function object
ROUTE_POLICY_FUNCS: Dict[str, str] = {
    "least_loaded": "route_least_loaded",
    "affinity": "route_affinity",
    "ttft_tpot": "route_ttft_tpot",
}


# --------------------------------------------------------------------------
# admission / shedding / dequeue order


def admit_or_shed(state: FleetState, req: ReqView, pending: int,
                  max_pending: Optional[int]) -> str:
    """Hard load shed at the decode boundary: with `max_pending` set, a
    best-effort (priority <= 0) request arriving to a full pending queue
    is shed; priority traffic is never shed (it preempts instead)."""
    if max_pending is not None and pending >= max_pending \
            and req.priority <= 0:
        return "shed"
    return "admit"


def next_waiting_fcfs(waiting: Sequence[ReqView],
                      served_by_tenant: Mapping[int, int]) -> int:
    """Dequeue in arrival order."""
    return 0


def next_waiting_fair_tenant(waiting: Sequence[ReqView],
                             served_by_tenant: Mapping[int, int]) -> int:
    """Tenant-fair dequeue: serve the waiting request whose tenant has
    been served least (ties broken by arrival order) — the counterweight
    to the prefix cache's rich-get-richer bias."""
    best = 0
    best_served = None
    for i, req in enumerate(waiting):
        served = served_by_tenant.get(req.tenant, 0)
        if best_served is None or served < best_served:
            best, best_served = i, served
    return best


# --------------------------------------------------------------------------
# preemption


def preempt_victim(runs: Sequence[RunView], priority: int) -> Optional[int]:
    """Evict-and-resume victim choice when a priority request finds no
    free slot: the strictly-lower-priority run with the least resident
    KV — the cheapest to re-ship on resume, since the snapshot+journal
    machinery makes eviction lose zero decoded tokens (the resume price
    is shipping `kv_tokens` worth of pages back, never a re-decode)."""
    best = None
    best_score = None
    for r in runs:
        if r.priority >= priority:
            continue
        score = (r.priority, r.kv_tokens, r.rid)
        if best_score is None or score < best_score:
            best, best_score = r.rid, score
    return best


# --------------------------------------------------------------------------
# autoscale


def autoscale(state: FleetState, params: ScaleParams, pressure_ticks: int,
              idle_ticks: Mapping[int, int]
              ) -> Tuple[ScaleDecision, int, Dict[int, int]]:
    """One autoscale tick.  Returns the decision plus the threaded tick
    state (pure: the executor owns the counters between calls).

    Bit-identical to the pre-refactor inline block: pressure = work
    waiting for decode AND zero free slots, reset on any relief;
    capacity counts booting replicas so a slow boot cannot stack
    spawns past `max_decode`; idle ticks advance per wid-sorted replica
    and at most ONE retirement fires per tick (the scan stops there,
    leaving later replicas' counters untouched, exactly like the old
    loop's `break`)."""
    free = 0
    for r in state.replicas:
        free += r.slots_free
    pressure_ticks = pressure_ticks + 1 \
        if (state.wait_for_decode > 0 and free == 0) else 0
    up = False
    if pressure_ticks >= params.scale_up_after \
            and len(state.replicas) + state.booting < params.max_decode:
        pressure_ticks = 0
        up = True
    ticks = dict(idle_ticks)
    down = None
    for r in state.replicas:
        ticks[r.wid] = ticks.get(r.wid, 0) + 1 if r.quiet else 0
        if ticks[r.wid] >= params.scale_down_after \
                and len(state.replicas) > params.min_decode \
                and state.queue_depth == 0:
            ticks.pop(r.wid)
            down = r.wid
            break
    return ScaleDecision(up=up, down=down), pressure_ticks, ticks


# --------------------------------------------------------------------------
# the policy space the simulator sweeps


class PolicySpec(NamedTuple):
    """One schedulable policy: the full decision bundle the simulator
    executes and the fleet could adopt.  `route`/`next_waiting`/
    `preempt` name module attributes (resolved via getattr, so spies
    see the delegation); `max_pending` None disables shedding."""

    name: str
    route: str = "route_least_loaded"
    next_waiting: str = "next_waiting_fcfs"
    preempt: bool = False
    max_pending: Optional[int] = None


POLICIES: Dict[str, PolicySpec] = {
    "least_loaded": PolicySpec("least_loaded"),
    "affinity": PolicySpec("affinity", route="route_affinity"),
    "ttft_tpot": PolicySpec("ttft_tpot", route="route_ttft_tpot"),
    "fair_tenant": PolicySpec(
        "fair_tenant", next_waiting="next_waiting_fair_tenant"),
    "priority_preempt": PolicySpec("priority_preempt", preempt=True),
}

# FleetCluster's shipped default; sim.promote_policy guards any change
DEFAULT_ROUTE_POLICY = "least_loaded"
