"""The KV transfer plane: pool pages on the wire, transactionally.

`ring_prefill_to_pages` (serving/handoff.py) lands a prompt's K/V in the
PREFILL worker's pool pages, in layout order.  To hand the request to a
decode replica on another host those pages must move — and the handoff's
permutation-invariance argument (decode attends every cached position;
validity is table membership, never ordering) means they move VERBATIM:
page j of the slot's table row on the prefill side becomes page j of the
replica's table row, whatever physical pool ids each side assigned.  No
re-layout, no reordering, byte-identical payloads — the tests compare
`page_bytes` on both ends.  A natively quantized pool (int8/fp8) ships
its 1 B/elem pages the same way, with the per-token fp32 scale columns
riding each kv_page frame as sidecars — a (page, scale) pair stages,
commits, and aborts as one unit, so the transfer machine's exactly-once
page landing is exactly-once pair landing; both ends must agree on the
pool dtype (checked before a single page is acquired).

The transfer is TRANSACTIONAL on the receive side:

    kv_begin(meta)  ->  stage (zero pool mutation)
    kv_page(j) x n  ->  stage (zero pool mutation)
    commit()        ->  precondition-check, acquire, scatter, table row
    abort()         ->  drop staging (zero pool mutation, nothing leaks)

`commit` checks EVERY precondition (page-shape match, table width, live
slot, pool availability for pages + the decode budget) before acquiring
a single page, and releases on any scatter failure — an aborted or
half-shipped transfer leaves both pools exactly as they were, which the
fleet's kill-mid-transfer tests assert as "zero page leaks".
"""

import hashlib
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models.paged_decode import PagePool, PagedState, _write_table_row
from ..protocols import kvtransfer as _kvp, pool as _pool_proto

M_KV_PAGES_SHIPPED = obs.counter(
    "fleet.kv_pages_shipped", "pool pages serialized onto the wire")
M_KV_BYTES_SHIPPED = obs.counter(
    "fleet.kv_bytes_shipped", "KV payload bytes serialized")
M_KV_COMMITTED = obs.counter(
    "fleet.kv_transfers_committed", "transfers admitted by a replica")
M_KV_ABORTED = obs.counter(
    "fleet.kv_transfers_aborted", "transfers aborted with staging dropped")


def export_slot_pages(state: PagedState, slot: int) -> Tuple[dict, List[dict]]:
    """Serialize one live slot's pages in TABLE ORDER.

    Returns (meta, pages): meta describes the stream (page geometry,
    layer/head counts, dtype, token length); pages[j] holds table column
    j's per-layer K and V arrays [n_kv, page, d_head] as numpy — page j
    on the wire is position range [j*page, (j+1)*page) in layout order,
    exactly what the sender's table row j pointed at.

    A quantized pool (int8/fp8 native storage) ships its 1 B/elem pages
    VERBATIM plus fp32 scale sidecars: pages[j]["ks"]/["vs"] carry table
    column j's per-layer [n_kv, page] dequant columns, and
    meta["quantized"] is True so the receive side can refuse a
    cross-precision commit before touching its pool.  A (page, scale)
    pair always rides in ONE kv_page frame — the transfer machine's
    exactly-once page landing is exactly-once PAIR landing."""
    length = int(state.lengths[slot])
    if length == 0:
        raise ValueError(f"slot {slot} is not live; nothing to export")
    quant = state.k_scales is not None
    page = int(state.k_pages[0].shape[2])
    n_pages = -(-length // page)
    row = np.asarray(state.page_table[slot])[:n_pages]
    k_host = [np.asarray(kp) for kp in state.k_pages]
    v_host = [np.asarray(vp) for vp in state.v_pages]
    if quant:
        ks_host = [np.asarray(s) for s in state.k_scales]
        vs_host = [np.asarray(s) for s in state.v_scales]
    meta = {
        "length": length,
        "page": page,
        "n_pages": int(n_pages),
        "n_layers": len(state.k_pages),
        "n_kv": int(state.k_pages[0].shape[1]),
        "d_head": int(state.k_pages[0].shape[3]),
        "dtype": str(np.asarray(state.k_pages[0]).dtype),
        "quantized": quant,
    }
    pages = []
    for j, pid in enumerate(row):
        pg = {"k": [k_host[li][int(pid)] for li in range(meta["n_layers"])],
              "v": [v_host[li][int(pid)] for li in range(meta["n_layers"])]}
        if quant:
            pg["ks"] = [ks_host[li][int(pid)]
                        for li in range(meta["n_layers"])]
            pg["vs"] = [vs_host[li][int(pid)]
                        for li in range(meta["n_layers"])]
        pages.append(pg)
        M_KV_PAGES_SHIPPED.inc()
        M_KV_BYTES_SHIPPED.inc(sum(a.nbytes for a in _page_arrays(pg)))
    return meta, pages


def _page_arrays(pg: dict) -> List[np.ndarray]:
    """Every array of one page message in canonical order: k, v, then the
    scale sidecars when the pool is quantized."""
    arrays = list(pg["k"]) + list(pg["v"])
    if "ks" in pg:
        arrays += list(pg["ks"]) + list(pg["vs"])
    return arrays


def page_bytes(pg: dict) -> bytes:
    """Canonical byte string of one page message (k then v then the scale
    sidecars, layer order) — the unit the byte-identity tests and
    `page_digest` hash.  A quantized page's digest covers its scales, so
    a (page, scale) pair that forked anywhere on the wire cannot match."""
    return b"".join(np.ascontiguousarray(a).tobytes()
                    for a in _page_arrays(pg))


def page_digest(pg: dict) -> str:
    return hashlib.sha256(page_bytes(pg)).hexdigest()


class KvReceiver:
    """Staging area + transactional commit on the decode side.  Staging
    never touches the pool; only `commit` does, and only after every
    precondition passes.

    Control decisions — staging lifecycle, commit preconditions, and
    the page ids a commit acquires — come from the PURE machine
    `protocols.kvtransfer.recv_step`, the same transition function
    burstcheck's transfer model explores (proto-transfer-atomic).  This
    class keeps the payload arrays (which the machine does not model)
    in lockstep with the machine's staging set and asserts the real
    pool hands out exactly the ids the machine computed."""

    def __init__(self):
        self._staging: Dict[int, dict] = {}
        self._proto = _kvp.RecvState((), _pool_proto.init(1), (), 0)

    def _proto_step(self, event):
        self._proto, outs = _kvp.recv_step(self._proto, event)
        return outs

    def begin(self, rid: int, meta: dict) -> None:
        # a re-shipped attempt for the same rid replaces stale staging
        self._proto_step(("begin", rid, int(meta["n_pages"])))
        self._staging[rid] = {"meta": dict(meta), "pages": {}}

    def add_page(self, rid: int, j: int, pg: dict) -> None:
        # machine first: it owns the "page with no begin" staging check;
        # shape validation failures roll the (pure, free to keep) prior
        # machine state back so payloads and staging never diverge
        prev = self._proto
        self._proto_step(("page", rid, int(j)))
        st = self._staging[rid]
        meta = st["meta"]
        try:
            want = (meta["n_kv"], meta["page"], meta["d_head"])
            for a in list(pg["k"]) + list(pg["v"]):
                if tuple(np.shape(a)) != want:
                    raise ValueError(
                        f"page {j} shape {np.shape(a)} != {want}")
            if len(pg["k"]) != meta["n_layers"] \
                    or len(pg["v"]) != meta["n_layers"]:
                raise ValueError(f"page {j} layer count mismatch")
            if meta.get("quantized"):
                # quantized streams stage (page, scale) PAIRS: a frame
                # missing its sidecars (or malformed) is rejected whole,
                # so staging can never hold a page without its scales
                if "ks" not in pg or "vs" not in pg:
                    raise ValueError(
                        f"page {j}: quantized stream frame is missing "
                        f"its scale sidecars")
                want_s = (meta["n_kv"], meta["page"])
                for a in list(pg["ks"]) + list(pg["vs"]):
                    if tuple(np.shape(a)) != want_s:
                        raise ValueError(
                            f"page {j} scale shape {np.shape(a)} != "
                            f"{want_s}")
                if len(pg["ks"]) != meta["n_layers"] \
                        or len(pg["vs"]) != meta["n_layers"]:
                    raise ValueError(f"page {j} scale layer count mismatch")
            elif "ks" in pg or "vs" in pg:
                raise ValueError(
                    f"page {j}: scale sidecars on a full-precision stream")
        except ValueError:
            self._proto = prev
            raise
        st["pages"][int(j)] = pg

    def complete(self, rid: int) -> bool:
        ent = _kvp.staged_entry(self._proto, rid)
        return ent is not None and _kvp.staging_complete(ent)

    def staged(self, rid: int) -> Optional[dict]:
        return self._staging.get(rid)

    def staging_count(self) -> int:
        return len(self._staging)

    def abort(self, rid: int) -> bool:
        """Drop staging for `rid`.  Pool untouched by construction."""
        dropped = bool(self._proto_step(("abort", rid)))
        self._staging.pop(rid, None)
        if dropped:
            M_KV_ABORTED.inc()
        return dropped

    def _proto_snapshot(self, state: PagedState, pool: PagePool,
                        n_slots: int) -> "_kvp.RecvState":
        """The machine's view of THIS commit: real staging + the real
        pool/slot occupancy (slot page sets are irrelevant to commit
        preconditions, so they stay empty)."""
        lengths = np.asarray(state.lengths)
        return _kvp.RecvState(
            staging=self._proto.staging,
            pool=pool.proto_state(),
            slots=tuple((1 if int(lengths[i]) else 0, ())
                        for i in range(n_slots)),
            table_width=int(state.page_table.shape[1]))

    def commit(self, rid: int, state: PagedState, pool: PagePool,
               slot: int) -> PagedState:
        """Scatter the staged pages into `slot`: all preconditions
        up-front, acquire-scatter-table under release-on-failure, then
        drop staging.  Raises with ZERO pool mutation when the transfer
        cannot be admitted (incomplete staging, live slot, table
        overflow, pool exhaustion)."""
        snap = self._proto_snapshot(state, pool, int(state.lengths.shape[0]))
        ent = _kvp.staged_entry(snap, rid)
        if ent is None:
            raise KeyError(f"commit for rid {rid} with no staging")
        if not _kvp.staging_complete(ent):
            raise ValueError(
                f"rid {rid} staged {len(ent[2])}/{ent[1]} pages; "
                f"transfer incomplete")
        st = self._staging[rid]
        meta = st["meta"]
        n = int(meta["n_pages"])
        page = int(state.k_pages[0].shape[2])
        if meta["page"] != page:
            raise ValueError(f"sender page size {meta['page']} != pool "
                             f"page size {page}")
        if len(state.k_pages) != meta["n_layers"]:
            raise ValueError("layer count mismatch")
        quant = state.k_scales is not None
        if bool(meta.get("quantized")) != quant:
            kind = ["full-precision", "quantized"]
            raise ValueError(
                f"pool precision mismatch: sender "
                f"{kind[bool(meta.get('quantized'))]}, receiver "
                f"{kind[quant]}")
        pool_dt = str(np.asarray(state.k_pages[0]).dtype)
        if str(meta["dtype"]) != pool_dt:
            raise ValueError(f"sender pool dtype {meta['dtype']} != "
                             f"receiver pool dtype {pool_dt}")
        # the remaining control preconditions + the acquire run the full
        # machine commit on the snapshot; the real pool then replays the
        # acquire and MUST hand out the machine's exact ids
        snap2, outs = _kvp.recv_step(snap, ("commit", rid, slot))
        ids = list(outs[0][2])
        got = pool.acquire(n)
        assert got == ids, (
            f"pool/machine divergence: machine acquired {ids}, "
            f"pool acquired {got}")
        try:
            idx = jnp.asarray(ids, jnp.int32)
            k_pages, v_pages = list(state.k_pages), list(state.v_pages)
            k_scales = list(state.k_scales) if quant else None
            v_scales = list(state.v_scales) if quant else None
            for li in range(meta["n_layers"]):
                k_stack = np.stack([st["pages"][j]["k"][li]
                                    for j in range(n)])
                v_stack = np.stack([st["pages"][j]["v"][li]
                                    for j in range(n)])
                dt = k_pages[li].dtype
                k_pages[li] = k_pages[li].at[idx].set(
                    jnp.asarray(k_stack, dt))
                v_pages[li] = v_pages[li].at[idx].set(
                    jnp.asarray(v_stack, dt))
                if quant:
                    # the scale sidecar lands in the SAME try block as its
                    # page: any failure releases every acquired id, so a
                    # page can never be resident without its scales
                    ks_stack = np.stack([st["pages"][j]["ks"][li]
                                         for j in range(n)])
                    vs_stack = np.stack([st["pages"][j]["vs"][li]
                                         for j in range(n)])
                    sdt = k_scales[li].dtype
                    k_scales[li] = k_scales[li].at[idx].set(
                        jnp.asarray(ks_stack, sdt))
                    v_scales[li] = v_scales[li].at[idx].set(
                        jnp.asarray(vs_stack, sdt))
            state = PagedState(tuple(k_pages), tuple(v_pages),
                               state.page_table, state.lengths,
                               tuple(k_scales) if quant else None,
                               tuple(v_scales) if quant else None)
            table = _write_table_row(state, slot, idx)
            lengths = state.lengths.at[slot].set(int(meta["length"]))
            state = PagedState(state.k_pages, state.v_pages, table,
                               lengths, state.k_scales, state.v_scales)
        except Exception:
            pool.release(ids)
            raise
        self._proto = self._proto._replace(staging=snap2.staging)
        del self._staging[rid]
        M_KV_COMMITTED.inc()
        return state
