"""Role-split serving fleet: ring-prefill pool -> KV plane -> decode pool.

The loadgen cluster (loadgen/cluster.py) proved the fault story on one
host with symmetric workers.  This module splits the roles the way
production servers do (ROADMAP item 2, Mooncake-style disaggregation):

  PREFILL workers   one handoff slot each: `ring_prefill_to_pages`
                    absorbs the prompt on an sp mesh, the slot's pages
                    serialize in table order (kvplane.export_slot_pages)
                    and ship as kv_begin/kv_page/kv_end frames.  Pages
                    are HELD until the router acks — a decode replica
                    dying mid-transfer costs a re-ship of the buffered
                    frames, never a re-prefill.
  DECODE replicas   a paged pool + `dist_paged_decode_step`, staging
                    transfers transactionally (kvplane.KvReceiver: admit
                    only after every page lands CRC-clean, zero pool
                    mutation on abort), journaling every sampled token
                    write-ahead, and snapshotting the whole pool every
                    `checkpoint_every` completions.
  ROUTER            one process, two pools, one transport protocol
                    (fleet/transport.py — mp queues in-process, TCP
                    sockets cross-host, SAME frames either way).  It
                    relays KV frames (and buffers them: the transfer can
                    be re-shipped to a sibling without the prefill
                    worker's involvement), routes on live admission
                    gauges from heartbeat pongs (slot occupancy, staged
                    transfers, pool availability), and drives failover.

Failure story, cross-boundary (mirrors cluster.py's matrix):

  kill prefill      mid-ship transfers abort on the decode side (staging
                    dropped, zero pages leaked — `abort_ok` carries the
                    replica's gauges as evidence) and the request re-runs
                    prefill on a sibling; a COMPLETE buffered transfer
                    proceeds without the dead sender.
  kill decode       un-admitted transfers re-ship to a sibling replica;
                    admitted streams resume from the dead replica's
                    journal — the router re-runs prefill with the
                    journaled prefix and the receiving replica
                    teacher-forces it (greedy decode: the prefix IS the
                    continuation), or completes directly when the
                    journal already covers the budget.
  restart decode    the replacement restores the dead life's paged
                    snapshot, rolls the journal forward (re-feeding only
                    the lag), claims its slots, and keeps decoding.
  hog / stall /     same semantics as the cluster, on either pool; a
  hang              hung member is caught by the heartbeat detector
                    generalized across both pools.
  die_mid_ship /    deterministic kill-mid-transfer arming (prefill dies
  die_mid_recv      after sending N pages / decode dies after receiving
                    N pages) for the zero-leak transaction tests.

Token-exactness: every request's full stream (first prefill-sampled
token + greedy decode) is compared against `fleet_oracle` — the same
model stepped in ONE process — and every admitted transfer's page
digests are compared sender vs receiver (byte-identical shipment).
"""

import multiprocessing as mp
import os
import signal
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..obs import trace as tracing
from ..loadgen.driver import DONE, Outcome, ReplayReport, RetryBackoff
from ..loadgen.trace import Trace
from ..protocols import kvtransfer as kv_proto
from . import kvplane
from . import policy as fleet_policy
from .transport import (
    Dedup, QueueTransport, SocketTransport, TransportError, accept, listen,
    send_with_retry,
)

FLEET_FAULT_KINDS = ("kill", "hog", "unhog", "stall", "hang", "restart",
                     "die_mid_ship", "die_mid_recv")
POOLS = ("prefill", "decode")

G_QUEUE_DEPTH = obs.gauge(
    "fleet.queue_depth", "requests waiting for a prefill worker")
G_DECODE_OCC = obs.gauge(
    "fleet.decode_occupancy", "live decode slots across the replica pool")
M_RESHIPS = obs.counter(
    "fleet.kv_reships", "buffered transfers re-shipped to a sibling")
M_SCALE_UPS = obs.counter(
    "fleet.scale_ups", "decode replicas spawned on sustained pressure")
M_SCALE_DOWNS = obs.counter(
    "fleet.scale_downs", "idle decode replicas retired")


@dataclass(frozen=True)
class FleetFault:
    """One scheduled fault: at virtual time `t`, do `kind` to `worker`
    of `pool`.  kill/restart wait for armed work exactly like
    cluster.FaultEvent (in-flight + journaled progress on decode;
    an assigned request on prefill); die_mid_ship/die_mid_recv arm
    inside the target and fire on its NEXT transfer (`arg` = pages to
    let through first)."""

    t: float
    pool: str
    worker: int
    kind: str
    arg: float = 0.0
    note: str = ""

    def __post_init__(self):
        if self.pool not in POOLS:
            raise ValueError(f"unknown pool {self.pool!r} (one of {POOLS})")
        if self.kind not in FLEET_FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {FLEET_FAULT_KINDS})")
        if self.kind == "die_mid_ship" and self.pool != "prefill":
            raise ValueError("die_mid_ship targets the prefill pool")
        if self.kind == "die_mid_recv" and self.pool != "decode":
            raise ValueError("die_mid_recv targets the decode pool")


@dataclass
class FleetReport(ReplayReport):
    """ReplayReport plus the fleet's evidence ledger: kills carry the
    pool they hit; `transfers` counts committed/aborted/re-shipped KV
    transactions with digest-comparison results and the zero-leak
    evidence (`aborts`: the replica's staging/availability gauges echoed
    after every abort/reject); `scale_events` logs autoscaling."""

    kills: List[dict] = field(default_factory=list)
    transfers: dict = field(default_factory=dict)
    scale_events: List[dict] = field(default_factory=list)
    obs_paths: List[str] = field(default_factory=list)
    recovered_tokens_replayed: int = 0
    recovered_tokens_resumed: int = 0

    def recovery_s(self) -> List[float]:
        """Per-fault recovery spans (virtual), cluster semantics."""
        out = []
        for k in self.kills:
            ts = [self.outcomes[rid].t_done for rid in k["rerouted"]
                  if self.outcomes[rid].t_done is not None]
            out.append(max(ts) - k["t"] if ts else 0.0)
        return out


# -- shared child plumbing --------------------------------------------------


def _build_model(model_spec: dict):
    """(params, cfg) re-derived from the spec's seed — cross-process
    token-exactness needs identical logits, so matmul precision pins
    here exactly like loadgen.worker.build_engine."""
    import jax
    import jax.numpy as jnp

    from ..models import ModelConfig, init_params

    ms = dict(model_spec)
    jax.config.update("jax_default_matmul_precision",
                      ms.pop("matmul_precision", "highest"))
    seed = ms.pop("seed", 0)
    cfg = ModelConfig(attn_backend="jnp", remat=False, dtype=jnp.float32,
                      batch_axis=None, head_axis=None, **ms)
    params = init_params(jax.random.PRNGKey(seed), cfg)
    return params, cfg


def _child_transport(conn):
    if conn[0] == "queue":
        _, req_q, res_q = conn
        return QueueTransport(send_q=res_q, recv_q=req_q)
    _, host, port, role, wid = conn
    tr = SocketTransport.connect(host, port, retries=30, rid=wid)
    tr.send(("hello", role, wid))
    return tr


def _export(obs_path: str, wid: int) -> None:
    from ..obs import spans as _spans
    from ..obs import trace as _trace

    extra = (_spans.span_records() + _trace.trace_records()
             + _trace.exemplar_records())
    obs.default_registry().export_jsonl(
        obs_path, extra_records=extra, process_index=wid)


def _dispatch_msg(rid: int, prompt, max_new: int, resume=None,
                  trace_wire=None):
    """Router -> prefill work tuple.  `resume` and `trace_wire` are
    OPTIONAL trailing elements, appended only when non-empty — a
    tracing-off run's frames encode byte-identical to a build without
    tracing (the zero-wire-cost-when-off bar)."""
    msg = ("prefill", int(rid), prompt, int(max_new))
    if resume or trace_wire:
        msg = msg + ([int(t) for t in (resume or [])],)
    if trace_wire:
        msg = msg + (list(trace_wire),)
    return msg


def _op(msg):
    return msg["op"] if isinstance(msg, dict) else msg[0]


def _die_loudly(role: str, wid: int, tr, obs_path: str, e: Exception):
    """Worker error path: flush obs BEFORE the error frame so the crash
    never leaves a torn registry export, then flush the transport so the
    frame survives this process dying right after (the satellite-1 race:
    error-during-stop must not vanish)."""
    try:
        _export(obs_path, wid)
    except Exception as ee:  # noqa: BLE001 — export is best-effort here
        os.write(2, f"fleet {role} {wid}: obs export failed: {ee}\n".encode())
    try:
        tr.send(("error", wid, f"{type(e).__name__}: {e}"))
        tr.flush()
    except Exception:  # noqa: BLE001 — transport gone with the router
        os.write(2, f"fleet {role} {wid}: {e}\n".encode())


# -- prefill worker ---------------------------------------------------------


def prefill_main(wid: int, model_spec: dict, prefill_spec: dict,
                 obs_path: str, conn) -> None:
    """One ring-prefill worker: a single handoff slot, prompt in ->
    pages out.  Pages are exported in table order with per-page sha256
    digests in the kv_begin meta and HELD until kv_ack/kv_abort retires
    the slot (the transactional sender side)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    tr = _child_transport(conn)
    try:
        import jax.numpy as jnp

        from ..models.paged_decode import init_paged_state, retire_slot
        from ..models.train import make_mesh
        from ..serving.handoff import ring_prefill_to_pages

        params, cfg = _build_model(model_spec)
        ps = dict(prefill_spec)
        if ps.get("trace"):
            tracing.enable()
        mesh = make_mesh({"sp": int(ps.get("sp", 2))})
        page = int(ps.get("page", 128))
        state, pool = init_paged_state(
            cfg, slots=1, n_pages=int(ps.get("n_pages", 4)), page=page,
            max_pages_per_seq=int(ps.get("max_pages_per_seq", 8)),
            quantize=ps.get("quantize", False))
        # warm-compile the ring pass on the fleet's prompt shape BEFORE
        # ready: a compile inside the message loop would miss heartbeats
        warm = jnp.zeros((int(ps.get("warm_len", page)),), jnp.int32)
        _, state = ring_prefill_to_pages(params, warm, state, pool, 0,
                                         cfg, mesh)
        state = retire_slot(state, pool, 0)
        _export(obs_path, wid)
        tr.send(("ready", wid, os.getpid()))

        backlog: deque = deque()
        pending: Dict[int, int] = {}   # rid -> n_pages awaiting ack
        hogged: List[int] = []
        stall_until = 0.0
        hang = False
        stopping = False
        die_mid_ship = None
        while True:
            if hang:
                time.sleep(0.05)
                continue
            while True:
                msg = tr.recv(timeout=0.0 if (backlog or pending) else 0.002)
                if msg is None:
                    break
                op = _op(msg)
                if op == "prefill":
                    backlog.append(msg)
                elif op in ("kv_ack", "kv_abort"):
                    rid = int(msg[1])
                    if pending.pop(rid, None) is not None:
                        state = retire_slot(state, pool, 0)
                elif op == "ping":
                    tr.send(("pong", wid, msg[1],
                             {"busy": bool(pending or backlog),
                              "avail": pool.available}))
                elif op == "fault":
                    _, fkind, arg = msg[0], msg[1], msg[2]
                    if fkind == "hog":
                        n = min(int(arg), pool.available)
                        if n > 0:
                            hogged += list(pool.acquire(n))
                    elif fkind == "unhog":
                        if hogged:
                            pool.release(hogged)
                            hogged = []
                    elif fkind == "stall":
                        stall_until = time.monotonic() + float(arg)
                    elif fkind == "hang":
                        hang = True
                    elif fkind == "die_mid_ship":
                        die_mid_ship = int(arg)
                    else:
                        tr.send(("error", wid, f"unknown fault {fkind!r}"))
                elif op == "stop":
                    stopping = True
                else:
                    tr.send(("error", wid, f"unknown op {op!r}"))
            if time.monotonic() < stall_until:
                time.sleep(0.002)
                continue
            if backlog and not pending:
                msg = backlog.popleft()
                rid, prompt, max_new = int(msg[1]), msg[2], int(msg[3])
                resume = [int(t) for t in (msg[4] if len(msg) > 4 and msg[4]
                                           else [])]
                # optional element 5: the router's trace context — spans
                # recorded here join the router's tree on merge
                tc = tracing.TraceContext.from_wire(
                    msg[5] if len(msg) > 5 else None)
                t_p0 = time.perf_counter()
                try:
                    logits, state = ring_prefill_to_pages(
                        params,
                        jnp.asarray([int(t) for t in prompt], jnp.int32),
                        state, pool, 0, cfg, mesh)
                except (RuntimeError, ValueError) as e:
                    # hogged/exhausted pool or a bad request shape: typed,
                    # retryable rejection — the router backs off & re-routes
                    tr.send({"op": "prefill_failed", "rid": rid,
                             "retryable": True,
                             "message": f"{type(e).__name__}: {e}"})
                    continue
                first = resume[0] if resume \
                    else int(np.asarray(logits).argmax())
                tracing.record_span(tc, "fleet.prefill", t_p0,
                                    time.perf_counter(),
                                    prompt_len=len(prompt))
                meta, pages = kvplane.export_slot_pages(state, 0)
                meta.update(
                    rid=rid, max_new=max_new, first_token=first,
                    resume_toks=resume, prompt_len=len(prompt),
                    digests=[kvplane.page_digest(pg) for pg in pages])
                if tc is not None and tracing.enabled():
                    # ride the context to the decode side in the transfer's
                    # meta — absent entirely when tracing is off
                    meta["trace"] = tc.to_wire()
                t_s0 = time.perf_counter()
                # the frame sequence (ops + seq numbers) comes from the
                # transfer machine's sender_plan — the same tuple the
                # burstcheck sender model walks, so the shipped protocol
                # cannot drift from the checked one.  The plan also pins
                # the credit contract: every frame ships without waiting
                # (the one kv_ack arrives only after the replica commits)
                for op, seq in kv_proto.sender_plan(len(pages)):
                    if op == "kv_begin":
                        frame = {"op": op, "rid": rid, "seq": seq,
                                 "meta": meta}
                    elif op == "kv_page":
                        j = seq - 1
                        if die_mid_ship is not None and j >= die_mid_ship:
                            tr.flush()  # delivered frames stay delivered
                            os._exit(17)
                        frame = {"op": op, "rid": rid, "seq": seq,
                                 "page": pages[j]}
                    else:  # kv_end
                        frame = {"op": op, "rid": rid, "seq": seq}
                    send_with_retry(tr, frame, rid=rid)
                tracing.record_span(tc, "fleet.ship", t_s0,
                                    time.perf_counter(),
                                    n_pages=len(pages))
                pending[rid] = int(meta["n_pages"])
            elif stopping and not backlog and not pending:
                _export(obs_path, wid)
                tr.send(("stopped", wid))
                tr.flush()
                return
            elif not backlog:
                time.sleep(0.002)
    except Exception as e:  # noqa: BLE001 — report, then die visibly
        _die_loudly("prefill", wid, tr, obs_path, e)
        raise


# -- decode replica ---------------------------------------------------------


def decode_main(wid: int, model_spec: dict, decode_spec: dict,
                obs_path: str, conn, ckpt_spec=None) -> None:
    """One paged-decode replica: transactional KV admission, batched
    greedy `dist_paged_decode_step` over its live slots, write-ahead
    token journal, paged snapshots every `every` completions, and a
    restore path (`ckpt_spec["restore"]`) that rebuilds the dead life's
    pool from snapshot + journal roll-forward."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    tr = _child_transport(conn)
    try:
        import jax.numpy as jnp

        from ..models.dist_decode import dist_paged_decode_step
        from ..models.paged_decode import (
            init_paged_state, provision_capacity, retire_slot,
        )
        from ..models.train import make_mesh
        from ..serving import checkpoint as ckpt

        params, cfg = _build_model(model_spec)
        ds = dict(decode_spec)
        if ds.get("trace"):
            tracing.enable()
        mesh = make_mesh({"sp": int(ds.get("sp", 2))})
        slots = int(ds.get("slots", 2))
        page = int(ds.get("page", 128))
        pool_args = dict(slots=slots, n_pages=int(ds.get("n_pages", 8)),
                         page=page,
                         max_pages_per_seq=int(ds.get("max_pages_per_seq",
                                                      4)),
                         quantize=ds.get("quantize", False))
        echo_digests = bool(ds.get("echo_digests"))
        export_every = int(ds.get("export_every", 4))
        ck = dict(ckpt_spec) if ckpt_spec else None

        live: Dict[int, dict] = {}   # slot -> {rid, max_new, tokens, fed}
        t_kv0: Dict[int, float] = {}  # rid -> first kv_begin recv time
        boot_dones: List[Tuple[int, List[int]]] = []
        restored_info = None
        if ck and ck.get("restore") and os.path.exists(ck["snapshot"]):
            state, pool, extra = ckpt.load_paged_snapshot(ck["snapshot"])
            try:
                jt = ckpt.journal_tokens_by_ext(ck["journal"])
                jdone = {int(view_sub["ext"])
                         for erid, view_sub
                         in ckpt.journal_view(ck["journal"]).submits.items()
                         if erid in ckpt.journal_view(ck["journal"]).done}
            except (OSError, ValueError):
                jt, jdone = {}, set()
            replayed, resumed = {}, {}
            for s_str, info in (extra.get("slots") or {}).items():
                s, rid = int(s_str), int(info["rid"])
                toks = [int(t) for t in info["tokens"]]
                lag = [int(t) for t in jt.get(rid, [])][len(toks):]
                live[s] = {"rid": rid, "max_new": int(info["max_new"]),
                           "tokens": toks + lag, "fed": int(info["fed"])}
                resumed[rid] = len(toks)
                replayed[rid] = len(lag)
            for rid in sorted(jdone):
                boot_dones.append((rid, [int(t) for t in jt.get(rid, [])]))
            restored_info = {
                "claimed": sorted(info["rid"] for info in live.values()),
                "replayed": replayed, "resumed": resumed,
                "from_snapshot": True,
            }
        else:
            state, pool = init_paged_state(cfg, **pool_args)
            if ck and ck.get("restore"):
                restored_info = {"claimed": [], "replayed": {},
                                 "resumed": {}, "from_snapshot": False}
        journal = None
        if ck:
            # fresh journal for THIS life (rewrite_journal semantics): a
            # second failure recovers from this life's records alone
            journal = ckpt.TokenJournal(ck["journal"], truncate=True)
            for info in live.values():
                journal.submit(info["rid"], info["rid"], [],
                               info["max_new"])
                journal.tokens(info["rid"], info["tokens"])
            journal.sync()
        # warm-compile the decode step on a throwaway state of identical
        # shapes — stepping the REAL state would append to restored slots
        wstate, _wpool = init_paged_state(cfg, **pool_args)
        dist_paged_decode_step(params, jnp.zeros((slots,), jnp.int32),
                               wstate, cfg, mesh)
        del wstate, _wpool
        _export(obs_path, wid)
        if restored_info is not None:
            tr.send(("restored", wid, restored_info))
        for rid, toks in boot_dones:
            tr.send({"op": "done", "rid": rid, "tokens": toks, "stats": {}})
        tr.send(("ready", wid, os.getpid()))

        receiver = kvplane.KvReceiver()
        dedup = Dedup()
        hogged: List[int] = []
        stall_until = 0.0
        hang = False
        stopping = False
        die_mid_recv = None
        recv_count = 0
        n_since_ckpt = 0
        n_since_export = 0

        def _stats() -> dict:
            return {"occ": len(live), "staged": receiver.staging_count(),
                    "avail": pool.available,
                    "slots_free": slots - len(live)}

        def _finish(s: int, st, info: dict):
            tc = info.get("_tc")  # absent on snapshot-restored slots
            if tc is not None:
                tracing.record_span(tc, "fleet.decode",
                                    info.get("_t_admit", 0.0),
                                    time.perf_counter(),
                                    tokens=len(info["tokens"]))
            if journal is not None:
                journal.done(info["rid"])
                journal.sync()
            tr.send({"op": "done", "rid": info["rid"],
                     "tokens": [int(t) for t in info["tokens"]],
                     "stats": _stats()})
            st = retire_slot(st, pool, s)
            del live[s]
            return st

        # a restored slot whose journal already covered the budget
        # finishes with zero engine time
        for s in sorted(live):
            if len(live[s]["tokens"]) >= live[s]["max_new"]:
                live[s]["tokens"] = live[s]["tokens"][:live[s]["max_new"]]
                state = _finish(s, state, live[s])

        while True:
            if hang:
                time.sleep(0.05)
                continue
            while True:
                msg = tr.recv(timeout=0.0 if live else 0.002)
                if msg is None:
                    break
                if isinstance(msg, dict):
                    op, rid = msg["op"], int(msg["rid"])
                    if op == "kv_begin":
                        dedup.forget_rid(rid)  # new attempt, new seq space
                        if dedup.accept(rid, 0):
                            t_kv0[rid] = time.perf_counter()
                            receiver.begin(rid, msg["meta"])
                    elif op == "kv_page":
                        if die_mid_recv is not None:
                            recv_count += 1
                            if recv_count > die_mid_recv:
                                tr.flush()
                                os._exit(19)
                        if dedup.accept(rid, int(msg["seq"])):
                            try:
                                receiver.add_page(rid, int(msg["seq"]) - 1,
                                                  msg["page"])
                            except (KeyError, ValueError) as e:
                                receiver.abort(rid)
                                tr.send({"op": "admit_reject", "rid": rid,
                                         "retryable": True,
                                         "message": str(e),
                                         "stats": _stats()})
                    elif op == "kv_end":
                        if not dedup.accept(rid, int(msg["seq"])):
                            continue
                        st = receiver.staged(rid)
                        meta = st["meta"] if st else {}
                        tc = tracing.TraceContext.from_wire(
                            meta.get("trace"))
                        s = next((x for x in range(slots) if x not in live),
                                 None)
                        t_c0 = time.perf_counter()
                        try:
                            if st is None or not receiver.complete(rid):
                                raise RuntimeError(
                                    f"rid {rid}: transfer incomplete")
                            if s is None:
                                raise RuntimeError("no free decode slot")
                            state = receiver.commit(rid, state, pool, s)
                            state = provision_capacity(
                                state, pool, s, int(meta["max_new"]))
                        except (RuntimeError, ValueError, KeyError) as e:
                            # transactional reject: commit is all-or-
                            # nothing; a provision failure AFTER commit
                            # retires the slot, so the pool is byte-for-
                            # byte where it started (zero-leak)
                            if s is not None and int(state.lengths[s]) != 0:
                                state = retire_slot(state, pool, s)
                            receiver.abort(rid)
                            t_kv0.pop(rid, None)
                            tr.send({"op": "admit_reject", "rid": rid,
                                     "retryable": True,
                                     "message": f"{type(e).__name__}: {e}",
                                     "stats": _stats()})
                            continue
                        now = time.perf_counter()
                        # transfer = first kv frame received -> commit
                        # start; commit = the staged-pages -> pool copy
                        tracing.record_span(tc, "fleet.transfer",
                                            t_kv0.pop(rid, t_c0), t_c0)
                        tracing.record_span(tc, "fleet.commit", t_c0, now)
                        toks = [int(t) for t in
                                (meta.get("resume_toks") or [])] \
                            or [int(meta["first_token"])]
                        info = {"rid": rid, "max_new": int(meta["max_new"]),
                                "tokens": toks, "fed": 0,
                                "_tc": tc, "_t_admit": now}
                        live[s] = info
                        if journal is not None:
                            journal.submit(rid, rid, [], info["max_new"])
                            journal.tokens(rid, toks)
                            journal.sync()
                        admitted = {"op": "admitted", "rid": rid, "slot": s,
                                    "stats": _stats()}
                        if echo_digests:
                            _, committed = kvplane.export_slot_pages(state,
                                                                     s)
                            admitted["digests"] = [
                                kvplane.page_digest(pg) for pg in committed]
                        tr.send(admitted)
                        if len(toks) >= info["max_new"]:
                            info["tokens"] = toks[:info["max_new"]]
                            state = _finish(s, state, info)
                            n_since_ckpt += 1
                    elif op == "kv_abort":
                        receiver.abort(rid)
                        t_kv0.pop(rid, None)
                        tr.send({"op": "abort_ok", "rid": rid,
                                 "stats": _stats()})
                    else:
                        tr.send(("error", wid, f"unknown op {op!r}"))
                else:
                    op = msg[0]
                    if op == "ping":
                        tr.send(("pong", wid, msg[1], _stats()))
                    elif op == "fault":
                        _, fkind, arg = msg[0], msg[1], msg[2]
                        if fkind == "hog":
                            n = min(int(arg), pool.available)
                            if n > 0:
                                hogged += list(pool.acquire(n))
                        elif fkind == "unhog":
                            if hogged:
                                pool.release(hogged)
                                hogged = []
                        elif fkind == "stall":
                            stall_until = time.monotonic() + float(arg)
                        elif fkind == "hang":
                            hang = True
                        elif fkind == "die_mid_recv":
                            die_mid_recv = int(arg)
                            recv_count = 0
                        else:
                            tr.send(("error", wid,
                                     f"unknown fault {fkind!r}"))
                    elif op == "stop":
                        stopping = True
                    else:
                        tr.send(("error", wid, f"unknown op {op!r}"))
            if time.monotonic() < stall_until:
                time.sleep(0.002)
                continue
            if live:
                feed = np.zeros((slots,), np.int32)
                stepped = dict(live)
                for s, info in stepped.items():
                    feed[s] = info["tokens"][info["fed"]]
                logits, state = dist_paged_decode_step(
                    params, jnp.asarray(feed), state, cfg, mesh)
                for s, info in stepped.items():
                    info["fed"] += 1
                    if info["fed"] == len(info["tokens"]) \
                            and len(info["tokens"]) < info["max_new"]:
                        row = np.asarray(logits[s])
                        if np.isnan(row).any():
                            raise RuntimeError(
                                f"decode slot {s} logits NaN-poisoned")
                        t = int(row.argmax())
                        info["tokens"].append(t)
                        if journal is not None:
                            journal.tokens(info["rid"], [t])
                            journal.sync()
                    if len(info["tokens"]) >= info["max_new"]:
                        state = _finish(s, state, info)
                        n_since_ckpt += 1
                        n_since_export += 1
                if ck and ck.get("snapshot") \
                        and n_since_ckpt >= int(ck.get("every", 2)):
                    ckpt.save_paged_snapshot(
                        ck["snapshot"], state, pool,
                        extra={"slots": {
                            str(s): {"rid": info["rid"],
                                     "max_new": info["max_new"],
                                     "tokens": list(info["tokens"]),
                                     "fed": info["fed"]}
                            for s, info in live.items()}})
                    n_since_ckpt = 0
                if n_since_export >= export_every:
                    _export(obs_path, wid)
                    n_since_export = 0
            elif stopping:
                if journal is not None:
                    journal.close()
                _export(obs_path, wid)
                tr.send(("stopped", wid))
                tr.flush()
                return
            else:
                time.sleep(0.002)
    except Exception as e:  # noqa: BLE001 — report, then die visibly
        _die_loudly("decode", wid, tr, obs_path, e)
        raise


# -- the single-process oracle ----------------------------------------------


def fleet_oracle(trace: Trace, model_spec: dict, *, prefill_spec=None,
                 decode_spec=None):
    """Per-request ground truth in ONE process: ring prefill -> in-
    process handoff -> greedy dist paged decode.  Returns (tokens_by_rid,
    digests_by_rid) — the token streams the fleet must match exactly and
    the page digests shipped transfers must match byte-for-byte."""
    import jax.numpy as jnp

    from ..models.paged_decode import init_paged_state, provision_capacity
    from ..models.train import make_mesh
    from ..serving.handoff import handoff_decode, ring_prefill_to_pages

    params, cfg = _build_model(model_spec)
    ps = dict(prefill_spec or {})
    ds = dict(decode_spec or {})
    mesh = make_mesh({"sp": int(ps.get("sp", 2))})
    pool_args = dict(slots=1, n_pages=int(ds.get("n_pages", 8)),
                     page=int(ds.get("page", 128)),
                     max_pages_per_seq=int(ds.get("max_pages_per_seq", 4)),
                     quantize=ds.get("quantize", False))
    tokens_by_rid, digests_by_rid = {}, {}
    for req in trace.requests:
        state, pool = init_paged_state(cfg, **pool_args)
        prompt = jnp.asarray([int(t) for t in req.prompt(trace.vocab)],
                             jnp.int32)
        logits, state = ring_prefill_to_pages(params, prompt, state, pool,
                                              0, cfg, mesh)
        first = int(np.asarray(logits).argmax())
        _, pages = kvplane.export_slot_pages(state, 0)
        digests_by_rid[req.rid] = [kvplane.page_digest(pg) for pg in pages]
        budget = int(req.max_new_tokens)
        state = provision_capacity(state, pool, 0, budget)
        toks, state = handoff_decode(params, state, cfg, mesh, slot=0,
                                     last_token=first, n_steps=budget - 1)
        tokens_by_rid[req.rid] = [first] + [int(t) for t in toks]
    return tokens_by_rid, digests_by_rid


# -- the router -------------------------------------------------------------


class FleetCluster:
    """Spawn both pools, replay a trace across the prefill/decode
    boundary, stop.  Context manager — __exit__ always reaps.

    transport="queue" runs the protocol over multiprocessing queues
    (in-process fleet); transport="socket" runs the SAME frames over
    localhost TCP — the cross-host deployment shape, minus the second
    machine."""

    def __init__(self, model_spec: dict, *, prefill_spec=None,
                 decode_spec=None, n_prefill: int = 1, n_decode: int = 1,
                 out_dir: str, transport: str = "queue",
                 checkpoint_every: int = 2, export_every: int = 4,
                 start_timeout_s: float = 600.0,
                 restart_timeout_s: float = 600.0,
                 hb_interval_s: float = 0.5, hb_timeout_s: float = 60.0,
                 autoscale: bool = False, max_decode: Optional[int] = None,
                 min_decode: int = 1, scale_check_interval_s: float = 0.4,
                 scale_up_after: int = 3, scale_down_after: int = 12,
                 router_policy: str = fleet_policy.DEFAULT_ROUTE_POLICY,
                 trace: bool = False):
        if n_prefill < 1 or n_decode < 1:
            raise ValueError("need >= 1 worker in each pool")
        if transport not in ("queue", "socket"):
            raise ValueError(f"unknown transport {transport!r}")
        if router_policy not in fleet_policy.ROUTE_POLICY_FUNCS:
            raise ValueError(
                f"unknown router_policy {router_policy!r} (one of "
                f"{sorted(fleet_policy.ROUTE_POLICY_FUNCS)})")
        self.model_spec = dict(model_spec)
        self.prefill_spec = dict(prefill_spec or {})
        self.decode_spec = dict(decode_spec or {})
        # request tracing rides the worker specs so restarts and
        # scale-ups inherit it; the key is absent when off, keeping
        # tracing-off spec dicts (and their pickled spawn args) unchanged
        self.trace_enabled = bool(trace)
        if self.trace_enabled:
            self.prefill_spec["trace"] = True
            self.decode_spec["trace"] = True
        self.n_prefill = n_prefill
        self.n_decode = n_decode
        self.out_dir = out_dir
        self.transport = transport
        self.checkpoint_every = checkpoint_every
        self.export_every = export_every
        self.start_timeout_s = start_timeout_s
        self.restart_timeout_s = restart_timeout_s
        self.hb_interval_s = hb_interval_s
        self.hb_timeout_s = hb_timeout_s
        self.autoscale = autoscale
        self.max_decode = max_decode if max_decode is not None else n_decode
        self.min_decode = min_decode
        self.scale_check_interval_s = scale_check_interval_s
        self.scale_up_after = scale_up_after
        self.scale_down_after = scale_down_after
        self.router_policy = router_policy
        self._ctx = mp.get_context("spawn")
        self._m: Dict[Tuple[str, int], dict] = {}  # (role, wid) -> member
        self._alive = {"prefill": set(), "decode": set()}
        self._gen: Dict[Tuple[str, int], int] = {}
        self._obs_files: List[str] = []
        self._next_decode_wid = n_decode
        self._listener = None
        self._port = None
        self.worker_errors: List[tuple] = []

    # -- paths ---------------------------------------------------------

    def obs_path(self, role: str, wid: int) -> str:
        gen = self._gen.get((role, wid), 0)
        suffix = f"g{gen}" if gen else ""
        return os.path.join(self.out_dir,
                            f"obs_{role[0]}{wid}{suffix}.jsonl")

    def journal_path(self, wid: int) -> str:
        return os.path.join(self.out_dir, f"fleet_journal_d{wid}.jsonl")

    def snapshot_path(self, wid: int) -> str:
        return os.path.join(self.out_dir, f"fleet_ckpt_d{wid}.npz")

    @property
    def obs_paths(self) -> List[str]:
        return list(self._obs_files)

    # -- lifecycle -----------------------------------------------------

    def _spawn(self, role: str, wid: int, restore: bool = False) -> None:
        if restore:
            self._gen[(role, wid)] = self._gen.get((role, wid), 0) + 1
        path = self.obs_path(role, wid)
        if role == "decode" and not restore:
            for stale in (path, self.journal_path(wid),
                          self.snapshot_path(wid)):
                if os.path.exists(stale):
                    os.remove(stale)
        if path not in self._obs_files:
            self._obs_files.append(path)
        if self.transport == "queue":
            req_q, res_q = self._ctx.Queue(), self._ctx.Queue()
            conn = ("queue", req_q, res_q)
            tr = QueueTransport(send_q=req_q, recv_q=res_q)
        else:
            conn = ("socket", "127.0.0.1", self._port, role, wid)
            tr = None  # attached on accept
        if role == "prefill":
            spec = dict(self.prefill_spec)
            args = (wid, self.model_spec, spec, path, conn)
            target = prefill_main
        else:
            spec = dict(self.decode_spec)
            spec.setdefault("export_every", self.export_every)
            ckpt_spec = {"journal": self.journal_path(wid),
                         "snapshot": self.snapshot_path(wid),
                         "every": self.checkpoint_every,
                         "restore": restore}
            args = (wid, self.model_spec, spec, path, conn, ckpt_spec)
            target = decode_main
        proc = self._ctx.Process(target=target, args=args, daemon=True,
                                 name=f"fleet-{role}-{wid}")
        proc.start()
        self._m[(role, wid)] = {"proc": proc, "tr": tr, "stats": {},
                                "last_pong": time.monotonic()}
        if self.transport == "socket":
            self._accept_one()

    def _accept_one(self) -> None:
        """Accept one member connection and bind it by its hello frame."""
        deadline = time.monotonic() + self.start_timeout_s
        while True:
            tr = accept(self._listener,
                        timeout_s=max(deadline - time.monotonic(), 1.0))
            hello = tr.recv(timeout=30.0)
            if hello is None or _op(hello) != "hello":
                tr.close()
                continue
            role, wid = str(hello[1]), int(hello[2])
            key = (role, wid)
            if key in self._m and self._m[key]["tr"] is None:
                self._m[key]["tr"] = tr
                return
            tr.close()  # stale reconnect from a dead life

    def start(self) -> None:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.makedirs(self.out_dir, exist_ok=True)
        if self.transport == "socket":
            self._listener, self._port = listen()
        for wid in range(self.n_prefill):
            self._spawn("prefill", wid)
        for wid in range(self.n_decode):
            self._spawn("decode", wid)
        deadline = time.monotonic() + self.start_timeout_s
        waiting = {("prefill", w) for w in range(self.n_prefill)} \
            | {("decode", w) for w in range(self.n_decode)}
        while waiting:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet members {sorted(waiting)} not ready within "
                    f"{self.start_timeout_s:g}s")
            for key in sorted(waiting):
                msg = self._poll(*key)
                if msg is None:
                    continue
                if _op(msg) == "ready":
                    waiting.discard(key)
                    self._alive[key[0]].add(key[1])
                    self._m[key]["last_pong"] = time.monotonic()
                elif _op(msg) == "error":
                    raise RuntimeError(
                        f"fleet {key[0]} {key[1]} failed to start: {msg[2]}")
            time.sleep(0.01)

    def __enter__(self) -> "FleetCluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self, timeout_s: float = 60.0) -> None:
        """Graceful where possible.  Late "error" frames are COLLECTED
        (self.worker_errors), never dropped — an engine that blew up
        during shutdown still reports, and its obs export (flushed
        before the error frame by contract) stays parseable."""
        for role in POOLS:
            for wid in sorted(self._alive[role]):
                try:
                    self._send(role, wid, ("stop",))
                except TransportError:
                    self._alive[role].discard(wid)
        deadline = time.monotonic() + timeout_s
        pending = {(r, w) for r in POOLS for w in self._alive[r]}
        while pending and time.monotonic() < deadline:
            for key in sorted(pending):
                alive = self._m[key]["proc"].is_alive()
                msg = self._poll(*key)
                if msg is None:
                    if not alive:
                        pending.discard(key)
                    continue
                if _op(msg) == "stopped":
                    pending.discard(key)
                elif _op(msg) == "error":
                    self.worker_errors.append((key, msg[2]))
            time.sleep(0.01)
        # final drain: a worker that died after sending "error" must not
        # lose the frame just because its process exited first
        for key, m in self._m.items():
            while True:
                msg = self._poll(*key)
                if msg is None:
                    break
                if _op(msg) == "error":
                    self.worker_errors.append((key, msg[2]))
        for m in self._m.values():
            if m["proc"].is_alive():
                m["proc"].terminate()
            m["proc"].join(timeout=10)
            if m["proc"].is_alive():
                m["proc"].kill()
                m["proc"].join(timeout=10)
        for s in self._alive.values():
            s.clear()

    # -- plumbing ------------------------------------------------------

    def _poll(self, role: str, wid: int):
        m = self._m.get((role, wid))
        if m is None or m["tr"] is None:
            return None
        return m["tr"].recv()

    def _send(self, role: str, wid: int, msg) -> None:
        m = self._m.get((role, wid))
        if m is None or m["tr"] is None:
            raise TransportError(f"no transport for {role} {wid}")
        m["tr"].send(msg)

    def _kill(self, role: str, wid: int) -> None:
        proc = self._m[(role, wid)]["proc"]
        if proc.is_alive() and proc.pid:
            os.kill(proc.pid, signal.SIGKILL)
        proc.join(timeout=10)
        self._alive[role].discard(wid)

    def _journal_resume_map(self, wid: int) -> Dict[int, List[int]]:
        from ..serving.checkpoint import journal_tokens_by_ext

        try:
            return journal_tokens_by_ext(self.journal_path(wid))
        except OSError:
            return {}

    def _journal_has_progress(self, wid: int, rids) -> bool:
        from ..serving.checkpoint import journal_view

        try:
            view = journal_view(self.journal_path(wid))
        except (OSError, ValueError):
            return False
        for erid, sub in view.submits.items():
            ext = int(sub["ext"])
            toks = view.tokens.get(erid, [])
            if (ext in rids and toks and erid not in view.done
                    and len(toks) < int(sub["max_new"])):
                return True
        return False

    # -- replay --------------------------------------------------------

    def replay(self, trace: Trace, faults: Sequence[FleetFault] = (), *,
               speed: float = 25.0, max_wall_s: float = 600.0,
               backoff: Optional[RetryBackoff] = None,
               max_attempts: int = 50) -> FleetReport:
        if not any(self._alive.values()):
            raise RuntimeError("fleet not started (use .start() or the "
                               "context manager)")
        bo = backoff if backoff is not None else RetryBackoff(base_s=0.02,
                                                              cap_s=1.0)
        vocab = trace.vocab
        arrivals = sorted(trace.requests, key=lambda r: (r.t_arrival, r.rid))
        by_rid = {r.rid: r for r in trace.requests}
        outcomes = {r.rid: Outcome(rid=r.rid, kind=r.kind,
                                   t_arrival=r.t_arrival)
                    for r in trace.requests}
        terminal: set = set()
        prefill_q: deque = deque()         # (rid, resume_toks|None)
        busy: Dict[int, Optional[int]] = {w: None
                                          for w in self._alive["prefill"]}
        transfers: Dict[int, dict] = {}
        reship: List[tuple] = []           # (t_due_v, rid)
        retryq: List[tuple] = []           # (t_due_v, rid, resume_toks)
        outstanding = {w: set() for w in self._alive["decode"]}
        if self.trace_enabled:
            tracing.enable()
        trace_ctx: Dict[int, tuple] = {}   # rid -> (ctx, t_dispatch_pc)
        kills: List[dict] = []
        ledger = {"committed": 0, "aborted": 0, "reshipped": 0,
                  "digest_checked": 0, "digest_mismatch": 0, "aborts": []}
        scale_events: List[dict] = []
        restarting: Dict[Tuple[str, int], dict] = {}
        recov = {"replayed": 0, "resumed": 0}
        fault_q = sorted(faults, key=lambda f: (f.t, f.pool, f.worker))
        hb_seq = 0
        last_hb = time.monotonic()
        last_scale = time.monotonic()
        pressure_ticks = 0
        idle_ticks: Dict[int, int] = {}
        t0 = time.perf_counter()

        def now_v() -> float:
            return (time.perf_counter() - t0) * speed

        def complete_direct(rid: int, toks: List[int], t: float) -> None:
            """The journal already covers the budget: done with zero
            engine time (router-side trim_complete)."""
            out = outcomes[rid]
            out.status = DONE
            out.tokens = [int(x) for x in toks[:by_rid[rid].max_new_tokens]]
            out.t_done = t
            terminal.add(rid)
            recov["resumed"] += len(out.tokens)

        def requeue(rid: int, toks, t: float) -> None:
            if rid in terminal:
                return
            outcomes[rid].retries += 1
            if toks and len(toks) >= by_rid[rid].max_new_tokens:
                complete_direct(rid, list(toks), t)
                return
            if toks:
                recov["resumed"] += len(toks)
            prefill_q.append((rid, list(toks) if toks else None))

        def settle(key, msg) -> None:
            role, wid = key
            op = _op(msg)
            if op == "pong":
                self._m[key]["last_pong"] = time.monotonic()
                if len(msg) > 3 and isinstance(msg[3], dict):
                    self._m[key]["stats"] = msg[3]
            elif op == "error":
                raise RuntimeError(f"fleet {role} {wid} errored: {msg[2]}")
            elif op == "prefill_failed":
                rid = int(msg["rid"])
                busy[wid] = None
                transfers.pop(rid, None)
                if rid not in terminal:
                    outcomes[rid].retries += 1
                    retryq.append((now_v()
                                   + bo.delay(rid, outcomes[rid].retries),
                                   rid, None))
            elif op == "kv_begin":
                rid = int(msg["rid"])
                transfers[rid] = {"frames": [msg], "meta": msg["meta"],
                                  "prefill": wid, "decode": None,
                                  "complete": False, "admitted": False,
                                  "attempts": 0}
                dw = self._pick_decode()
                if dw is not None:
                    transfers[rid]["decode"] = dw
                    self._forward(dw, msg)
            elif op in ("kv_page", "kv_end"):
                rid = int(msg["rid"])
                tf = transfers.get(rid)
                if tf is None:
                    return  # late frame for an already-settled transfer
                tf["frames"].append(msg)
                if op == "kv_end":
                    tf["complete"] = True
                if tf["decode"] is not None:
                    self._forward(tf["decode"], msg)
                elif op == "kv_end":
                    # born while no replica was alive (mid-restart): the
                    # buffered transfer waits in the re-ship queue
                    reship.append((now_v(), rid))
            elif op == "admitted":
                rid = int(msg["rid"])
                tf = transfers.pop(rid, None)
                if tf is None:
                    return
                ledger["committed"] += 1
                sent = tf["meta"].get("digests")
                got = msg.get("digests")
                if sent and got:
                    ledger["digest_checked"] += 1
                    if list(sent) != list(got):
                        ledger["digest_mismatch"] += 1
                pw = tf["prefill"]
                if pw in self._alive["prefill"]:
                    try:
                        self._send("prefill", pw, ("kv_ack", rid))
                    except TransportError:
                        pass  # liveness will reap; pages die with it
                    busy[pw] = None
                outstanding.setdefault(wid, set()).add(rid)
                if rid not in terminal:
                    outcomes[rid].t_submit = now_v()
                    if rid in trace_ctx:
                        # admission IS first token for the fleet: the
                        # decode replica was seeded with first_token
                        ctx, t_disp = trace_ctx[rid]
                        now_pc = time.perf_counter()
                        tracing.marker(ctx, "fleet.first_token", now_pc)
                        obs.histogram(
                            "fleet.ttft_s",
                            "router dispatch -> admitted latency"
                        ).observe(now_pc - t_disp)
                        tracing.note_ttft(ctx, now_pc - t_disp,
                                          metric="fleet.ttft_s")
            elif op == "admit_reject":
                rid = int(msg["rid"])
                st = msg.get("stats") or {}
                ledger["aborted"] += 1
                ledger["aborts"].append(
                    {"rid": rid, "kind": "reject", "decode": wid,
                     "staged_after": st.get("staged"),
                     "avail_after": st.get("avail"),
                     "message": msg.get("message", "")})
                tf = transfers.get(rid)
                if tf is not None:
                    tf["decode"] = None
                    tf["attempts"] += 1
                    if tf["attempts"] > max_attempts:
                        raise RuntimeError(
                            f"transfer rid {rid} rejected "
                            f"{tf['attempts']} times: {msg.get('message')}")
                    reship.append((now_v() + bo.delay(rid, tf["attempts"]),
                                   rid))
            elif op == "abort_ok":
                st = msg.get("stats") or {}
                ledger["aborts"].append(
                    {"rid": int(msg["rid"]), "kind": "abort", "decode": wid,
                     "staged_after": st.get("staged"),
                     "avail_after": st.get("avail")})
            elif op == "done":
                rid = int(msg["rid"])
                outstanding.get(wid, set()).discard(rid)
                if isinstance(msg, dict) and isinstance(msg.get("stats"),
                                                        dict):
                    self._m[key]["stats"] = msg["stats"]
                if rid in terminal:
                    return  # late duplicate after a reroute race
                out = outcomes[rid]
                out.status = DONE
                out.tokens = [int(t) for t in msg["tokens"]]
                out.t_done = now_v()
                terminal.add(rid)
                tci = trace_ctx.pop(rid, None)
                if tci is not None:
                    ctx, t_disp = tci
                    tracing.record_span(ctx, "fleet.request", t_disp,
                                        time.perf_counter(), root=True,
                                        rid=rid)
            # "ready"/"restored"/"stopped" are lifecycle chatter handled
            # by start()/restart/scale paths

        def reap_prefill(wid: int, t: float, detected: str,
                         note: str = "") -> None:
            while True:
                msg = self._poll("prefill", wid)
                if msg is None:
                    break
                settle(("prefill", wid), msg)
            rid = busy.get(wid)
            rerouted = []
            if rid is not None and rid not in terminal:
                tf = transfers.get(rid)
                if tf is not None and not tf["complete"]:
                    # half-shipped: abort the receiver's staging (zero
                    # pages leak — abort_ok's gauges prove it) and re-run
                    # prefill on a sibling
                    if tf["decode"] is not None \
                            and tf["decode"] in self._alive["decode"]:
                        try:
                            self._send("decode", tf["decode"],
                                       {"op": "kv_abort", "rid": rid})
                        except TransportError:
                            pass  # receiver dying too; its reap covers it
                    del transfers[rid]
                    requeue(rid, None, t)
                    rerouted.append(rid)
                elif tf is not None:
                    # fully buffered: the transfer outlives its sender
                    if tf["decode"] is None:
                        reship.append((t, rid))
                else:
                    requeue(rid, None, t)
                    rerouted.append(rid)
            busy.pop(wid, None)
            kills.append({"t": t, "pool": "prefill", "worker": wid,
                          "rerouted": rerouted, "detected_by": detected,
                          "note": note})

        def reap_decode(wid: int, t: float, detected: str,
                        note: str = "") -> None:
            while True:
                msg = self._poll("decode", wid)
                if msg is None:
                    break
                settle(("decode", wid), msg)
            orphans = sorted(outstanding.get(wid, set()) - terminal)
            outstanding.pop(wid, None)
            # un-admitted transfers aimed at the dead replica re-ship to
            # a sibling from the router's buffer — no prefill re-run
            for rid, tf in transfers.items():
                if tf["decode"] == wid and not tf["admitted"]:
                    tf["decode"] = None
                    tf["attempts"] += 1
                    reship.append((t, rid))
            resume_map = self._journal_resume_map(wid)
            kills.append({"t": t, "pool": "decode", "worker": wid,
                          "rerouted": orphans, "detected_by": detected,
                          "note": note})
            for rid in orphans:
                requeue(rid, resume_map.get(rid) or None, t)

        def fire_restart(ev: FleetFault, t: float) -> None:
            key = (ev.pool, ev.worker)
            self._kill(*key)
            while True:
                msg = self._poll(*key)
                if msg is None:
                    break
                settle(key, msg)
            resume_map = {}
            orphans: List[int] = []
            if ev.pool == "decode":
                # read the dead life's journal BEFORE the replacement
                # rewrites it: unclaimed orphans resume from this map
                resume_map = self._journal_resume_map(ev.worker)
                orphans = sorted(outstanding.get(ev.worker, set())
                                 - terminal)
                outstanding.pop(ev.worker, None)
                for rid, tf in transfers.items():
                    if tf["decode"] == ev.worker and not tf["admitted"]:
                        tf["decode"] = None
                        tf["attempts"] += 1
                        reship.append((t, rid))
            else:
                rid = busy.get(ev.worker)
                if rid is not None and rid not in terminal:
                    tf = transfers.get(rid)
                    if tf is not None and not tf["complete"]:
                        if tf["decode"] is not None:
                            try:
                                self._send("decode", tf["decode"],
                                           {"op": "kv_abort", "rid": rid})
                            except TransportError:
                                pass  # receiver reap covers it
                        transfers.pop(rid, None)
                        requeue(rid, None, t)
                        orphans.append(rid)
                busy.pop(ev.worker, None)
            self._spawn(ev.pool, ev.worker, restore=True)
            restarting[key] = {
                "deadline": time.monotonic() + self.restart_timeout_s,
                "orphans": orphans, "resume_map": resume_map, "t": t,
                "note": ev.note, "restored": None, "ready": False,
            }

        def poll_restarting(t: float) -> None:
            for key in sorted(restarting):
                st = restarting[key]
                while True:
                    msg = self._poll(*key)
                    if msg is None:
                        break
                    if _op(msg) == "restored":
                        st["restored"] = msg[2]
                    elif _op(msg) == "ready":
                        st["ready"] = True
                    else:
                        settle(key, msg)  # journal-complete dones
                if st["ready"]:
                    role, wid = key
                    info = st["restored"] or {}
                    recov["replayed"] += sum(
                        int(v) for v in (info.get("replayed") or {}).values())
                    recov["resumed"] += sum(
                        int(v) for v in (info.get("resumed") or {}).values())
                    claimed = {int(r) for r in info.get("claimed", [])}
                    self._alive[role].add(wid)
                    self._m[key]["last_pong"] = time.monotonic()
                    if role == "decode":
                        outstanding.setdefault(wid, set())
                        for rid in sorted(claimed):
                            if rid not in terminal:
                                outstanding[wid].add(rid)
                    else:
                        busy[wid] = None
                    kills.append({"t": st["t"], "pool": role, "worker": wid,
                                  "rerouted": sorted(st["orphans"]),
                                  "restarted": True,
                                  "detected_by": "scheduled-restart",
                                  "note": st["note"]})
                    for rid in sorted(set(st["orphans"]) - claimed):
                        requeue(rid, st["resume_map"].get(rid) or None, t)
                    del restarting[key]
                elif time.monotonic() > st["deadline"]:
                    raise RuntimeError(
                        f"restarted {key[0]} {key[1]} not ready within "
                        f"{self.restart_timeout_s:g}s")

        i = 0
        while len(terminal) < len(outcomes):
            t = now_v()
            # 1) due faults
            while fault_q and fault_q[0].t <= t:
                ev = fault_q[0]
                key = (ev.pool, ev.worker)
                if ev.worker not in self._alive[ev.pool] \
                        and key not in restarting:
                    fault_q.pop(0)
                    continue
                if key in restarting:
                    break
                if ev.kind in ("kill", "restart"):
                    while True:
                        msg = self._poll(*key)
                        if msg is None:
                            break
                        settle(key, msg)
                    work_possible = (i < len(arrivals) or bool(retryq)
                                     or bool(prefill_q) or bool(reship))
                    if ev.pool == "decode":
                        rids = outstanding.get(ev.worker, set())
                        armed = bool(rids) and self._journal_has_progress(
                            ev.worker, rids)
                    else:
                        armed = busy.get(ev.worker) is not None
                    if not armed and work_possible:
                        break
                    fault_q.pop(0)
                    if ev.kind == "restart":
                        fire_restart(ev, t)
                    else:
                        self._kill(*key)
                        if ev.pool == "prefill":
                            reap_prefill(ev.worker, t, "scheduled-kill",
                                         ev.note)
                        else:
                            reap_decode(ev.worker, t, "scheduled-kill",
                                        ev.note)
                else:
                    fault_q.pop(0)
                    try:
                        self._send(ev.pool, ev.worker,
                                   ("fault", ev.kind, ev.arg))
                    except TransportError:
                        pass  # dying member; liveness reap covers it
            # 2) unscheduled deaths
            for role, reaper in (("prefill", reap_prefill),
                                 ("decode", reap_decode)):
                for wid in sorted(self._alive[role]):
                    if not self._m[(role, wid)]["proc"].is_alive():
                        self._alive[role].discard(wid)
                        reaper(wid, t, "liveness")
            if restarting:
                poll_restarting(t)
            # 2c) heartbeat detector, both pools
            now_w = time.monotonic()
            if now_w - last_hb >= self.hb_interval_s:
                last_hb = now_w
                hb_seq += 1
                for role in POOLS:
                    for wid in sorted(self._alive[role]):
                        try:
                            self._send(role, wid, ("ping", hb_seq))
                        except TransportError:
                            pass  # dead member; liveness reap covers it
                for role, reaper in (("prefill", reap_prefill),
                                     ("decode", reap_decode)):
                    for wid in sorted(self._alive[role]):
                        if now_w - self._m[(role, wid)]["last_pong"] \
                                > self.hb_timeout_s:
                            self._kill(role, wid)
                            reaper(wid, t, "heartbeat")
            # 3) arrivals, retries, dispatch, re-ships
            while i < len(arrivals) and arrivals[i].t_arrival <= t:
                prefill_q.append((arrivals[i].rid, None))
                i += 1
            if retryq:
                retryq.sort()
                while retryq and retryq[0][0] <= t:
                    _, rid, toks = retryq.pop(0)
                    if rid not in terminal:
                        prefill_q.append((rid, toks))
            for wid in sorted(self._alive["prefill"]):
                if busy.get(wid) is None and prefill_q:
                    rid, toks = prefill_q.popleft()
                    if rid in terminal:
                        continue
                    req = by_rid[rid]
                    ctx = None
                    if tracing.enabled():
                        # retries reuse the first dispatch's context, so a
                        # rerouted request stays one tree
                        if rid in trace_ctx:
                            ctx = trace_ctx[rid][0]
                        else:
                            ctx = tracing.start_request(rid, prefix="fleet")
                            trace_ctx[rid] = (ctx, time.perf_counter())
                    msg = _dispatch_msg(
                        rid, [int(x) for x in req.prompt(vocab)],
                        req.max_new_tokens, resume=toks,
                        trace_wire=ctx.to_wire() if ctx else None)
                    try:
                        self._send("prefill", wid, msg)
                        busy[wid] = rid
                    except TransportError:
                        prefill_q.appendleft((rid, toks))
                        break
            if reship:
                reship.sort()
                still = []
                for due, rid in reship:
                    tf = transfers.get(rid)
                    if tf is None or rid in terminal:
                        continue
                    if due > t or not tf["complete"]:
                        still.append((due, rid))
                        continue
                    dw = self._pick_decode()
                    if dw is None:
                        still.append((due, rid))
                        continue
                    tf["decode"] = dw
                    ledger["reshipped"] += 1
                    M_RESHIPS.inc()
                    for fr in tf["frames"]:
                        self._forward(dw, fr)
                reship[:] = still
            # 4) member results
            idle = True
            for role in POOLS:
                for wid in sorted(self._alive[role]):
                    while True:
                        msg = self._poll(role, wid)
                        if msg is None:
                            break
                        idle = False
                        settle((role, wid), msg)
            # 5) load gauges + autoscale
            depth = len(prefill_q) + len(retryq) + len(reship)
            occ = sum(int(self._m[("decode", w)]["stats"].get("occ", 0))
                      for w in self._alive["decode"])
            G_QUEUE_DEPTH.set(depth)
            G_DECODE_OCC.set(occ)
            if self.autoscale \
                    and now_w - last_scale >= self.scale_check_interval_s:
                last_scale = now_w
                # capacity inside the policy = serving replicas + ones
                # still booting: a scale-up that hasn't reported ready
                # yet must count, or sustained pressure during its
                # (slow) boot spawns an unbounded pile past max_decode
                decision, pressure_ticks, idle_ticks = \
                    self._autoscale_decide(
                        depth=depth, outstanding=outstanding,
                        transfers=transfers, restarting=restarting,
                        pressure_ticks=pressure_ticks,
                        idle_ticks=idle_ticks)
                if decision.up:
                    wid = self._next_decode_wid
                    self._next_decode_wid += 1
                    self._spawn("decode", wid)
                    restarting[("decode", wid)] = {
                        "deadline": time.monotonic()
                        + self.restart_timeout_s,
                        "orphans": [], "resume_map": {}, "t": t,
                        "note": "scale-up", "restored": None,
                        "ready": False,
                    }
                    scale_events.append({"t": t, "action": "up",
                                         "worker": wid})
                    M_SCALE_UPS.inc()
                if decision.down is not None:
                    wid = decision.down
                    self._alive["decode"].discard(wid)
                    try:
                        self._send("decode", wid, ("stop",))
                    except TransportError:
                        pass  # already gone; terminate below anyway
                    self._m[("decode", wid)]["proc"].join(timeout=30)
                    if self._m[("decode", wid)]["proc"].is_alive():
                        self._m[("decode", wid)]["proc"].terminate()
                    scale_events.append({"t": t, "action": "down",
                                         "worker": wid})
                    M_SCALE_DOWNS.inc()
            if idle:
                time.sleep(0.002)
            if time.perf_counter() - t0 > max_wall_s:
                raise RuntimeError(
                    f"fleet replay exceeded max_wall_s={max_wall_s:g}: "
                    f"{len(terminal)}/{len(outcomes)} terminal, "
                    f"{i}/{len(arrivals)} arrived, depth={depth}, "
                    f"transfers={sorted(transfers)}, "
                    f"alive={[sorted(self._alive[r]) for r in POOLS]}, "
                    f"restarting={sorted(restarting)}")
        while restarting:
            poll_restarting(now_v())
            if restarting:
                time.sleep(0.01)
        if self.trace_enabled:
            # the router's own spans (fleet.request roots, first_token
            # markers) join the workers' in --merge; exported only when
            # tracing so untraced runs keep their historical process set
            rpath = os.path.join(self.out_dir, "obs_router.jsonl")
            _export(rpath, 1000)
            if rpath not in self._obs_files:
                self._obs_files.append(rpath)
        return FleetReport(
            outcomes=outcomes, wall_s=time.perf_counter() - t0, speed=speed,
            kills=kills, transfers=ledger, scale_events=scale_events,
            obs_paths=self.obs_paths,
            recovered_tokens_replayed=recov["replayed"],
            recovered_tokens_resumed=recov["resumed"])

    def _decode_view(self, *, slots_free_default: int = 1,
                     quiet_for=None) -> fleet_policy.FleetView:
        """Snapshot the decode pool's admission gauges as the concrete
        `FleetState` the pure policies read.  `quiet_for` (outstanding
        map + live transfers) switches on the autoscale observation:
        missing gauges then default BUSY (occ/staged -> 1, slots_free ->
        0) exactly like the pre-refactor inline block, so a replica that
        has never ponged can be neither retired nor counted free."""
        reps = []
        for w in sorted(self._alive["decode"]):
            st = self._m[("decode", w)]["stats"]
            quiet = False
            if quiet_for is not None:
                outstanding, transfers = quiet_for
                quiet = (int(st.get("occ", 1)) == 0
                         and int(st.get("staged", 1)) == 0
                         and not outstanding.get(w)
                         and not any(tf["decode"] == w
                                     for tf in transfers.values()))
            reps.append(fleet_policy.ReplicaView(
                wid=w, occ=int(st.get("occ", 0)),
                staged=int(st.get("staged", 0)),
                slots_free=int(st.get("slots_free", slots_free_default)),
                quiet=quiet))
        return fleet_policy.FleetView(replicas=tuple(reps))

    def _pick_decode(self) -> Optional[int]:
        """Load-aware choice, delegated to the pure routing policy
        (fleet/policy.py) the simulator executes too — fewest live+staged
        sequences, preferring replicas that report a free slot (the
        admission gauges ride every pong/done/admitted message)."""
        route = getattr(fleet_policy,
                        fleet_policy.ROUTE_POLICY_FUNCS[self.router_policy])
        return route(self._decode_view(), None)

    def _autoscale_decide(self, *, depth: int, outstanding, transfers,
                          restarting, pressure_ticks: int, idle_ticks):
        """Observation here, decision in fleet/policy.autoscale — the
        same pure function the simulator sweeps lead times with."""
        view = self._decode_view(
            slots_free_default=0, quiet_for=(outstanding, transfers))
        view = view._replace(
            queue_depth=depth,
            wait_for_decode=depth + sum(
                1 for tf in transfers.values() if tf["decode"] is None),
            booting=sum(1 for (role, _w) in restarting if role == "decode"))
        params = fleet_policy.ScaleParams(
            scale_up_after=self.scale_up_after,
            scale_down_after=self.scale_down_after,
            max_decode=self.max_decode, min_decode=self.min_decode)
        return fleet_policy.autoscale(view, params, pressure_ticks,
                                      idle_ticks)

    def _forward(self, decode_wid: int, frame) -> None:
        try:
            self._send("decode", decode_wid, frame)
        except TransportError:
            pass  # dead receiver: reap re-ships the buffered transfer

    def merged(self, by_process: bool = False):
        """Per-member obs exports folded into one job view
        (obs --merge semantics, torn tails tolerated)."""
        from ..obs.aggregate import merge_files

        present = [p for p in self.obs_paths if os.path.exists(p)]
        if not present:
            raise FileNotFoundError(
                f"no fleet obs exports under {self.out_dir!r} yet")
        return merge_files(present, by_process=by_process)
