"""burstsim: seeded discrete-event fleet simulator over burstcost rates.

The process-backed `FleetCluster` proves correctness at ~10 workers; the
ROADMAP's policy questions (routing, preemption, tenant fairness,
autoscale lead time) only show up at 1000 replicas under diurnal
traffic.  This module replays million-request traces through a
heap-based discrete-event engine in seconds of wall-clock, executing the
SAME pure policy functions production runs (fleet/policy.py — the
delegation is spy-asserted), and emits a seeded-deterministic JSONL
report per policy.

Replica cost function — provenance, not guesswork: replicas advance by
three rates derived from burstcost's `--cost-json` table
(analysis/costmodel.py, schema burstcost-v2, itself cross-validated
against devstats pair counters and wire-byte counters):

  prefill tokens/s   the best fitting fwd row's ring pass: world*s
                     tokens through max(t_compute_s, t_comm_s);
  decode steps/s     ragged-paged attention's per-step HBM traffic
                     (`ragged_hbm` rows) against the generation's HBM
                     bandwidth — decode is bandwidth-bound;
  KV-ship bytes/s    the generation's ICI bandwidth (the transfer plane
                     rides the interconnect).

`SimRates` is also the injection seam: `calibrate_rates` rebuilds the
three rates from a REAL `--fleet` run's outcome timeline (and, when a
TPU window lands, obs counters can feed the same seam), which is how the
fidelity gate works — replay the real run's trace through the sim with
rates measured FROM that run and pin simulated goodput within
`SIM_FIDELITY_RTOL` of measured.  A sim-found policy becomes
`FleetCluster`'s default only after `promote_policy` sees it reproduce a
strict `serve.fleet_goodput` improvement in the real `--fleet` lane
(docs/fleet.md "Simulator").

Determinism contract: virtual time only (the wall clock is read solely
to report `sim.wallclock_per_sim_second`), no RNG anywhere in the
engine, heap ties broken by a monotone sequence number, and every
applied event folded into a SHA-256 event-log digest — two runs over the
same trace and seed produce bit-identical logs (pinned by the
1000-replica/1M-request acceptance test).

Event model (3 heap events per request on the happy path, so a million
requests stay under a minute):

  ARRIVAL       assign the earliest-free prefill worker (FCFS), schedule
                PREFILL_DONE at start + prompt_len / prefill_rate;
  PREFILL_DONE  route via the policy; admit (ship + decode scheduled as
                one completion event), shed, preempt, or join the
                pending queue;
  DECODE_DONE   retire the run, then drain the pending queue through the
                policy's dequeue order (tenant fairness lives here);
  BOOT / SCALE  autoscale lead-time experiments: scale ticks execute
                fleet/policy.autoscale with boot_s of spawn latency.

Decode service is priced at full occupancy (per-step time = slots /
decode_steps_per_s) — conservative and admission-order independent, so
preemption stays a single event cancellation (epoch bump).  Eviction
loses no decoded tokens (snapshot+journal semantics): the resume price
is re-shipping `kv_tokens` worth of pages, never a re-decode.
"""

import argparse
import hashlib
import heapq
import json
import math
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..obs import trace as tracing
from ..loadgen import trace as trace_mod
from ..loadgen.trace import Trace
from . import policy as fleet_policy
from .policy import (FleetView, PolicySpec, ReplicaView, ReqView, RunView,
                     ScaleParams)

C_EVENTS = obs.counter(
    "sim.events_processed", "discrete events applied by the fleet simulator")
G_WALL_RATIO = obs.gauge(
    "sim.wallclock_per_sim_second",
    "wall seconds burned per simulated second (last run)")
G_POLICY_GOODPUT = obs.gauge(
    "sim.policy_goodput",
    "simulated goodput tokens per virtual second, labeled {policy}")
C_PREEMPTIONS = obs.counter(
    "sim.preemptions", "evict-and-resume preemptions, labeled {class}")

SIM_FIDELITY_RTOL = 0.35  # fidelity gate: |sim - measured| / measured

# how many least-loaded candidates the sim state exposes per decision;
# the index is keyed by the exact least-loaded score, so the argmin is
# always candidate 0 and the FleetState contract ("never drop the
# argmin") holds for any K >= 1
_CANDIDATES = 4
_WARM_CAP = 32           # warm templates remembered per replica (FIFO)
_FAIR_SCAN = 32          # pending-queue prefix the dequeue policy scans


@dataclass(frozen=True)
class SimRates:
    """The three rates a simulated replica advances by, plus spawn
    latency.  The seam: build from the cost table
    (`rates_from_cost_table`), from a measured run (`calibrate_rates`),
    or inject obs-counter-calibrated values directly."""

    prefill_tokens_per_s: float
    decode_steps_per_s: float     # aggregate across a replica's slots
    ship_bytes_per_s: float
    kv_bytes_per_token: float
    boot_s: float = 30.0


def rates_from_cost_table(table: Optional[dict] = None, *,
                          generation: str = "v5e",
                          pool_dtype: str = "fp32",
                          boot_s: float = 30.0) -> SimRates:
    """Derive `SimRates` from a burstcost `--cost-json` table (computed
    in-process when `table` is None — same data `python -m
    burst_attn_tpu.analysis --cost-json` prints)."""
    if table is None:
        from ..analysis import costmodel
        table = costmodel.cost_table()
    if table.get("schema") != "burstcost-v2":
        raise ValueError(f"unsupported cost table schema "
                         f"{table.get('schema')!r}")
    hw = table["hw"][generation]
    shape = table["shape"]
    world = int(table["world"])
    rows = [r for r in table["rows"]
            if r["generation"] == generation and r["pass"] == "fwd"
            and r["wire"] is None and r["fits"]]
    if not rows:
        raise ValueError(f"no fitting fwd rows for generation "
                         f"{generation!r} in cost table")
    t_pass = min(max(r["t_compute_s"], r["t_comm_s"]) for r in rows)
    prefill_tokens_per_s = world * shape["s"] / t_pass
    hbm_rows = [r for r in table["ragged_hbm"]
                if r["pool_dtype"] == pool_dtype]
    if not hbm_rows:
        raise ValueError(f"no ragged_hbm rows for pool_dtype "
                         f"{pool_dtype!r} in cost table")
    step_bytes = hbm_rows[0]["hbm_bytes"]
    kv_len = hbm_rows[0]["kv_len"]
    return SimRates(
        prefill_tokens_per_s=prefill_tokens_per_s,
        decode_steps_per_s=hw["hbm_bw"] / step_bytes,
        ship_bytes_per_s=hw["ici_bw"],
        kv_bytes_per_token=step_bytes / kv_len,
        boot_s=boot_s)


@dataclass
class SimReport:
    """One policy's replay, seeded-deterministic (wall_s excepted)."""

    policy: str
    seed: int
    n_replicas: int
    slots: int
    n_requests: int
    n_done: int = 0
    n_shed: int = 0
    preemptions: Dict[str, int] = field(default_factory=dict)
    goodput_tokens_per_s: float = 0.0
    ttft_p50_s: float = 0.0
    ttft_p99_s: float = 0.0
    sim_duration_s: float = 0.0
    events: int = 0
    event_log_sha256: str = ""
    scale_ups: int = 0
    scale_downs: int = 0
    wall_s: float = 0.0

    def to_record(self) -> dict:
        d = dict(self.__dict__)
        d["record"] = "sim-policy-report"
        return d


class _SimState:
    """The simulator's `FleetState`: candidate index keyed by the exact
    least-loaded score, maintained incrementally (lazy heap with version
    stamps) so routing stays O(log n) at 1000 replicas."""

    __slots__ = ("occ", "slots", "alive", "version", "cand_heap",
                 "warm_sets", "warm_fifo", "warm_index",
                 "queue_depth", "wait_for_decode", "booting")

    def __init__(self, slots: int):
        self.slots = slots
        self.occ: List[int] = []
        self.alive: List[bool] = []
        self.version: List[int] = []
        self.cand_heap: List[Tuple] = []
        self.warm_sets: List[set] = []
        self.warm_fifo: List[List[int]] = []
        self.warm_index: Dict[int, Dict[int, None]] = {}
        self.queue_depth = 0
        self.wait_for_decode = 0
        self.booting = 0

    # -- executor-side maintenance ------------------------------------

    def add_replica(self) -> int:
        wid = len(self.occ)
        self.occ.append(0)
        self.alive.append(True)
        self.version.append(0)
        self.warm_sets.append(set())
        self.warm_fifo.append([])
        self.touch(wid)
        return wid

    def touch(self, wid: int) -> None:
        """Re-key `wid` in the candidate index after any gauge change."""
        self.version[wid] += 1
        if self.alive[wid]:
            occ = self.occ[wid]
            heapq.heappush(self.cand_heap,
                           (self.slots - occ <= 0, occ, wid,
                            self.version[wid]))

    def retire(self, wid: int) -> None:
        self.alive[wid] = False
        self.version[wid] += 1

    def note_warm(self, wid: int, template_seed: int) -> None:
        if template_seed < 0 or template_seed in self.warm_sets[wid]:
            return
        self.warm_sets[wid].add(template_seed)
        self.warm_fifo[wid].append(template_seed)
        self.warm_index.setdefault(template_seed, {})[wid] = None
        if len(self.warm_fifo[wid]) > _WARM_CAP:
            old = self.warm_fifo[wid].pop(0)
            self.warm_sets[wid].discard(old)
            idx = self.warm_index.get(old)
            if idx is not None:
                idx.pop(wid, None)

    def is_warm(self, wid: int, template_seed: int) -> bool:
        return template_seed in self.warm_sets[wid]

    def _view(self, wid: int) -> ReplicaView:
        occ = self.occ[wid]
        return ReplicaView(wid=wid, occ=occ, staged=0,
                           slots_free=self.slots - occ)

    # -- FleetState ---------------------------------------------------

    @property
    def replicas(self) -> Tuple[ReplicaView, ...]:
        """Top-K candidates by the least-loaded score (argmin first)."""
        heap, valid = self.cand_heap, []
        while heap and len(valid) < _CANDIDATES:
            entry = heapq.heappop(heap)
            _nofree, occ, wid, ver = entry
            if ver == self.version[wid] and self.alive[wid] \
                    and occ == self.occ[wid]:
                valid.append(entry)
        for entry in valid:
            heapq.heappush(heap, entry)
        return tuple(self._view(e[2]) for e in valid)

    def warm_candidates(self, template_seed: int
                        ) -> Tuple[ReplicaView, ...]:
        idx = self.warm_index.get(template_seed)
        if not idx:
            return ()
        out = []
        for wid in idx:
            if self.alive[wid]:
                out.append(self._view(wid))
                if len(out) >= 16:
                    break
        return tuple(out)

    def full_view(self) -> FleetView:
        """Complete wid-sorted snapshot for autoscale ticks (the same
        concrete view the real router hands the policy)."""
        reps = tuple(
            ReplicaView(wid=w, occ=self.occ[w], staged=0,
                        slots_free=self.slots - self.occ[w],
                        quiet=self.occ[w] == 0)
            for w in range(len(self.occ)) if self.alive[w])
        return FleetView(replicas=reps, queue_depth=self.queue_depth,
                         wait_for_decode=self.wait_for_decode,
                         booting=self.booting)


# event codes (digest lines carry the names)
_ARRIVAL, _PREFILL_DONE, _DECODE_DONE, _BOOT, _SCALE = range(5)
_NAMES = ("arrive", "prefill", "decode", "boot", "scale")


def simulate(trace: Trace, spec: PolicySpec, *, n_replicas: int,
             slots: int = 8, n_prefill: Optional[int] = None,
             rates: Optional[SimRates] = None, seed: int = 0,
             autoscale: Optional[ScaleParams] = None,
             scale_interval_s: float = 1.0,
             log_path: Optional[str] = None) -> SimReport:
    """Replay `trace` under policy `spec`.  Pure function of its inputs
    (the seed only labels the report — the engine itself draws nothing);
    `log_path` optionally writes the full event log (tests; the digest
    is always computed)."""
    route = getattr(fleet_policy, spec.route)
    next_waiting = getattr(fleet_policy, spec.next_waiting)
    if rates is None:
        rates = rates_from_cost_table()
    if n_prefill is None:
        n_prefill = max(1, n_replicas // 4)
    step_s = slots / rates.decode_steps_per_s
    ship_inv = (0.0 if math.isinf(rates.ship_bytes_per_s)
                else 1.0 / rates.ship_bytes_per_s)
    bpt = rates.kv_bytes_per_token

    state = _SimState(slots)
    for _ in range(n_replicas):
        state.add_replica()

    views: Dict[int, ReqView] = {}
    arrival_t: Dict[int, float] = {}
    epoch: Dict[int, int] = {}
    run_wid: Dict[int, int] = {}
    run_steps: Dict[int, int] = {}       # steps remaining at admission
    run_t0: Dict[int, float] = {}        # decode start (post-ship)
    run_kv: Dict[int, int] = {}          # resident kv tokens at admission
    runs_by_wid: Dict[int, Dict[int, None]] = {}
    pending: List[int] = []
    served_by_tenant: Dict[int, int] = {}
    ttfts: List[float] = []
    preempt_counts: Dict[str, int] = {}
    booting_wids: set = set()
    pressure_ticks, idle_ticks = 0, {}
    scale_ups = scale_downs = 0
    n_done = n_shed = 0
    tokens_done = 0
    last_done_t = 0.0

    # virtual-clock request tracing: when obs.trace is enabled the sim
    # emits the SAME record schema the live engines do, with clock
    # "virtual" and deterministic trace ids (no RNG, no wall reads — the
    # event-log digest is untouched)
    trc = tracing.enabled()

    def _tc(rid: int) -> tracing.TraceContext:
        return tracing.TraceContext(trace_id=f"sim{seed}-r{rid}",
                                    clock="virtual")

    hasher = hashlib.sha256()
    log_fh = open(log_path, "w", encoding="utf-8") if log_path else None

    def log(t: float, code: int, a: int, b: int) -> None:
        line = f"{t:.6f} {_NAMES[code]} {a} {b}\n"
        hasher.update(line.encode())
        if log_fh is not None:
            log_fh.write(line)

    heap: List[Tuple] = []
    seq = 0

    def push(t: float, code: int, a: int = 0, b: int = 0) -> None:
        nonlocal seq
        heapq.heappush(heap, (t, seq, code, a, b))
        seq += 1

    arrivals = trace.requests  # arrival-ordered by construction
    arr_i = 0
    if arrivals:
        push(arrivals[0].t_arrival, _ARRIVAL, 0)
    if autoscale is not None:
        push(scale_interval_s, _SCALE)
    pf_free = [(0.0, p) for p in range(n_prefill)]
    in_prefill = 0

    def runviews(wid: int) -> Tuple[RunView, ...]:
        out = []
        for rid in runs_by_wid.get(wid, ()):
            v = views[rid]
            out.append(RunView(rid=rid, priority=v.priority,
                               kv_tokens=run_kv[rid]))
        return tuple(out)

    def evict(rid: int, t: float) -> None:
        nonlocal pressure_ticks
        wid = run_wid.pop(rid)
        runs_by_wid[wid].pop(rid, None)
        epoch[rid] += 1  # cancels the in-flight DECODE_DONE
        state.occ[wid] -= 1
        state.touch(wid)
        done = max(0, int((t - run_t0[rid]) / step_s)) \
            if t > run_t0[rid] else 0
        done = min(done, run_steps[rid])
        run_steps[rid] -= done
        run_kv[rid] += done  # journal keeps every decoded token
        cls = str(views[rid].priority)
        preempt_counts[cls] = preempt_counts.get(cls, 0) + 1
        pending.insert(0, rid)  # resume ahead of fresh arrivals

    def admit(rid: int, t: float) -> bool:
        req = views[rid]
        wid = route(state, req)
        if wid is None or not state.alive[wid]:
            return False
        if state.occ[wid] >= slots:
            if spec.preempt and req.priority > 0:
                victim = fleet_policy.preempt_victim(runviews(wid),
                                                     req.priority)
                if victim is None:
                    return False
                evict(victim, t)
            else:
                return False
        resume = rid in run_kv
        if resume:
            ship_bytes = run_kv[rid] * bpt
            steps = run_steps[rid]
        else:
            warm = state.is_warm(wid, req.template_seed) \
                if req.template_seed >= 0 else False
            ship_tokens = req.prompt_len - req.overlap_len if warm \
                else req.prompt_len
            ship_bytes = ship_tokens * bpt
            steps = req.max_new_tokens
            run_kv[rid] = req.prompt_len
            run_steps[rid] = steps
        ship_dur = ship_bytes * ship_inv
        t0 = t + ship_dur
        run_t0[rid] = t0
        run_wid[rid] = wid
        runs_by_wid.setdefault(wid, {})[rid] = None
        state.occ[wid] += 1
        state.touch(wid)
        state.note_warm(wid, req.template_seed)
        epoch[rid] = epoch.get(rid, 0) + 1
        push(t0 + steps * step_s, _DECODE_DONE, rid, epoch[rid])
        if not resume:
            ttfts.append(t0 + step_s - arrival_t[rid])
            if trc:
                tc = _tc(rid)
                tracing.record_span(tc, "sim.ship", t, t0, wid=wid)
                tracing.marker(tc, "sim.first_token", t0 + step_s)
                tracing.note_ttft(tc, t0 + step_s - arrival_t[rid],
                                  metric="sim.ttft_s")
        return True

    def drain(t: float) -> None:
        while pending:
            scan = pending[:_FAIR_SCAN]
            idx = next_waiting([views[r] for r in scan], served_by_tenant)
            rid = scan[idx]
            if not admit(rid, t):
                break
            pending.remove(rid)
            ten = views[rid].tenant
            served_by_tenant[ten] = served_by_tenant.get(ten, 0) + 1

    wall0 = time.perf_counter()
    events = 0
    while heap:
        t, _s, code, a, b = heapq.heappop(heap)
        if code == _ARRIVAL:
            req = arrivals[a]
            arr_i = a + 1
            if arr_i < len(arrivals):
                push(arrivals[arr_i].t_arrival, _ARRIVAL, arr_i)
            arrival_t[req.rid] = req.t_arrival
            views[req.rid] = ReqView(
                rid=req.rid, prompt_len=req.prompt_len,
                max_new_tokens=req.max_new_tokens, tenant=req.tenant,
                priority=req.priority, template_seed=req.template_seed,
                overlap_len=req.overlap_len)
            free_at, pid = heapq.heappop(pf_free)
            start = free_at if free_at > t else t
            done = start + req.prompt_len / rates.prefill_tokens_per_s
            heapq.heappush(pf_free, (done, pid))
            in_prefill += 1
            push(done, _PREFILL_DONE, req.rid)
            if trc:
                tc = _tc(req.rid)
                tracing.record_span(tc, "sim.queued", req.t_arrival, start)
                tracing.record_span(tc, "sim.prefill", start, done, pid=pid)
            log(t, code, req.rid, pid)
        elif code == _PREFILL_DONE:
            in_prefill -= 1
            rid = a
            state.queue_depth = max(0, in_prefill - n_prefill)
            state.wait_for_decode = len(pending)
            if admit(rid, t):
                log(t, code, rid, run_wid[rid])
            else:
                verdict = fleet_policy.admit_or_shed(
                    state, views[rid], len(pending), spec.max_pending)
                if verdict == "shed":
                    n_shed += 1
                    log(t, code, rid, -2)
                else:
                    pending.append(rid)
                    log(t, code, rid, -1)
        elif code == _DECODE_DONE:
            rid = a
            if epoch.get(rid) != b:
                continue  # cancelled by a preemption
            wid = run_wid.pop(rid)
            runs_by_wid[wid].pop(rid, None)
            state.occ[wid] -= 1
            state.touch(wid)
            n_done += 1
            tokens_done += views[rid].max_new_tokens
            last_done_t = t
            if trc:
                tc = _tc(rid)
                tracing.record_span(tc, "sim.decode", run_t0[rid], t,
                                    wid=wid)
                tracing.record_span(tc, "sim.request", arrival_t[rid], t,
                                    root=True, rid=rid)
            log(t, code, rid, wid)
            state.wait_for_decode = len(pending)
            drain(t)
        elif code == _BOOT:
            wid = a
            booting_wids.discard(wid)
            state.booting = len(booting_wids)
            state.alive[wid] = True
            state.touch(wid)
            log(t, code, wid, 0)
            drain(t)
        elif code == _SCALE:
            view = state.full_view()
            decision, pressure_ticks, idle_ticks = fleet_policy.autoscale(
                view, autoscale, pressure_ticks, idle_ticks)
            if decision.up:
                wid = state.add_replica()
                state.alive[wid] = False  # booting: not yet routable
                state.version[wid] += 1
                booting_wids.add(wid)
                state.booting = len(booting_wids)
                scale_ups += 1
                push(t + rates.boot_s, _BOOT, wid)
            if decision.down is not None:
                state.retire(decision.down)
                scale_downs += 1
            log(t, code, int(decision.up),
                -1 if decision.down is None else decision.down)
            # keep ticking while work remains
            if arr_i < len(arrivals) or pending or run_wid:
                push(t + scale_interval_s, _SCALE)
        events += 1
    wall = time.perf_counter() - wall0
    if log_fh is not None:
        log_fh.close()

    duration = last_done_t if last_done_t > 0 else trace.duration_s
    ttfts.sort()

    def pct(p: float) -> float:
        if not ttfts:
            return 0.0
        return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))]

    report = SimReport(
        policy=spec.name, seed=seed, n_replicas=n_replicas, slots=slots,
        n_requests=len(trace.requests), n_done=n_done, n_shed=n_shed,
        preemptions=dict(sorted(preempt_counts.items())),
        goodput_tokens_per_s=round(tokens_done / duration, 6)
        if duration > 0 else 0.0,
        ttft_p50_s=round(pct(0.50), 6), ttft_p99_s=round(pct(0.99), 6),
        sim_duration_s=round(duration, 6), events=events,
        event_log_sha256=hasher.hexdigest(),
        scale_ups=scale_ups, scale_downs=scale_downs,
        wall_s=round(wall, 3))
    C_EVENTS.inc(events)
    if duration > 0:
        G_WALL_RATIO.set(wall / duration)
    G_POLICY_GOODPUT.set(report.goodput_tokens_per_s, policy=spec.name)
    for cls, n in report.preemptions.items():
        C_PREEMPTIONS.inc(n, **{"class": cls})
    return report


def sweep(trace: Trace, specs, **kw) -> List[SimReport]:
    """Replay the trace under every policy; deterministic order."""
    return [simulate(trace, spec, **kw) for spec in specs]


def write_report_jsonl(reports, path: str) -> str:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for rep in reports:
            f.write(json.dumps(rep.to_record(), sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return path


# --------------------------------------------------------------------------
# fidelity + promotion gates


def calibrate_rates(trace: Trace, outcomes: Dict[int, object], *,
                    n_prefill: int, slots: int,
                    boot_s: float = 30.0) -> SimRates:
    """Rebuild `SimRates` from a REAL fleet run's outcome timeline (the
    injection seam, fed from measurement instead of the cost table).

    prefill tokens/s: FCFS busy-span decomposition — replay the
    dispatch order over `n_prefill` earliest-free servers and divide
    prompt tokens by the busy time up to each admission (t_submit marks
    prefill+ship complete in the fleet).  decode steps/s: inverts the
    sim's own service model (per-step time = slots / rate) from the
    aggregate tokens / decode-span ratio, so replaying with these rates
    validates the ENGINE's queueing dynamics, not a rate guess."""
    done = [o for o in outcomes.values()
            if o.status == "done" and o.t_submit is not None
            and o.t_done is not None]
    if not done:
        raise ValueError("no completed outcomes to calibrate from")
    by_rid = {r.rid: r for r in trace.requests}
    done.sort(key=lambda o: (o.t_submit, o.rid))
    free = [0.0] * n_prefill
    busy = 0.0
    tokens_in = 0
    for o in done:
        start = max(o.t_arrival, min(free))
        span = max(o.t_submit - start, 1e-9)
        busy += span
        free[free.index(min(free))] = o.t_submit
        tokens_in += by_rid[o.rid].prompt_len
    decode_span = sum(max(o.t_done - o.t_submit, 1e-9) for o in done)
    tokens_out = sum(len(o.tokens) for o in done)
    step_s = decode_span / max(tokens_out, 1)
    return SimRates(
        prefill_tokens_per_s=tokens_in / busy,
        decode_steps_per_s=slots / step_s,
        ship_bytes_per_s=math.inf,  # folded into the prefill span
        kv_bytes_per_token=0.0,
        boot_s=boot_s)


def measured_goodput(outcomes: Dict[int, object]) -> float:
    """tokens / virtual makespan over completed outcomes — the virtual-
    domain analogue of the bench's `serve.fleet_goodput` (which divides
    by wall seconds; the two differ by the replay's constant `speed`
    factor, which cancels in the fidelity ratio)."""
    done = [o for o in outcomes.values()
            if o.status == "done" and o.t_done is not None]
    if not done:
        return 0.0
    span = max(o.t_done for o in done) \
        - min(o.t_arrival for o in done)
    return sum(len(o.tokens) for o in done) / max(span, 1e-9)


def fidelity_check(trace: Trace, outcomes: Dict[int, object], *,
                   n_replicas: int, slots: int, n_prefill: int,
                   rtol: float = SIM_FIDELITY_RTOL) -> dict:
    """The fidelity gate: replay a real `--fleet` run's trace through
    the sim with rates calibrated FROM that run and pin simulated
    goodput within `rtol` of measured."""
    rates = calibrate_rates(trace, outcomes, n_prefill=n_prefill,
                            slots=slots)
    rep = simulate(trace, fleet_policy.POLICIES["least_loaded"],
                   n_replicas=n_replicas, slots=slots,
                   n_prefill=n_prefill, rates=rates)
    measured = measured_goodput(outcomes)
    # same definition on the sim side: decode budget == emitted tokens
    sim_tokens = sum(r.max_new_tokens for r in trace.requests)
    sim_span = rep.sim_duration_s - min(
        r.t_arrival for r in trace.requests)
    simulated = sim_tokens / max(sim_span, 1e-9) if rep.n_done else 0.0
    ratio = simulated / measured if measured > 0 else math.inf
    return {"measured_goodput": measured, "simulated_goodput": simulated,
            "ratio": ratio, "rtol": rtol,
            "ok": bool(abs(ratio - 1.0) <= rtol),
            "rates": dict(rates.__dict__), "sim_report": rep.to_record()}


def promote_policy(default: str, sim_goodput: Dict[str, float],
                   fleet_goodput: Dict[str, float]) -> str:
    """The promotion gate: the sim's best policy replaces `default` ONLY
    if a real `--fleet` measurement shows a STRICT goodput improvement
    over the default.  Missing measurements never promote."""
    best = max(sorted(sim_goodput), key=lambda k: sim_goodput[k])
    if best == default:
        return default
    base = fleet_goodput.get(default)
    cand = fleet_goodput.get(best)
    if base is None or cand is None or not cand > base:
        return default
    return best


# --------------------------------------------------------------------------
# CLI: python -m burst_attn_tpu.fleet.sim


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m burst_attn_tpu.fleet.sim",
        description="burstsim: discrete-event fleet simulator "
                    "(policies from fleet/policy.py, rates from "
                    "burstcost)")
    ap.add_argument("--policy", default="all",
                    help="policy name or 'all' (default)")
    ap.add_argument("--trace-kind", default="diurnal",
                    choices=("diurnal", "heavy_tail"))
    ap.add_argument("--requests", type=int, default=20000)
    ap.add_argument("--replicas", type=int, default=100)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prefill", type=int, default=0,
                    help="prefill workers (default replicas/4)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--generation", default="v5e")
    ap.add_argument("--autoscale", action="store_true")
    ap.add_argument("--trace", action="store_true",
                    help="emit virtual-clock request trace records "
                         "(ride the --json export; render with "
                         "`python -m burst_attn_tpu.obs --trace`)")
    ap.add_argument("--report", metavar="PATH",
                    help="write per-policy JSONL report")
    ap.add_argument("--json", metavar="PATH",
                    help="export sim.* obs metrics as JSONL "
                         "(merges through `python -m burst_attn_tpu.obs "
                         "--merge`)")
    args = ap.parse_args(argv)

    if args.trace_kind == "diurnal":
        tr = trace_mod.synthesize_diurnal_trace(
            args.requests, seed=args.seed, vocab=97, period_s=600.0,
            mean_rate=max(20.0, args.requests / 200.0),
            priority_fraction=0.1)
    else:
        tr = trace_mod.synthesize_heavy_tail_trace(
            args.requests, seed=args.seed, vocab=97,
            mean_interarrival_s=min(0.05, 200.0 / args.requests),
            priority_tenants=2)
    if args.trace:
        tracing.enable()
    rates = rates_from_cost_table(generation=args.generation)
    names = sorted(fleet_policy.POLICIES) if args.policy == "all" \
        else [args.policy]
    specs = [fleet_policy.POLICIES[n] for n in names]
    scale = ScaleParams(3, 12, args.replicas, 1) if args.autoscale \
        else None
    reports = sweep(tr, specs, n_replicas=args.replicas, slots=args.slots,
                    n_prefill=args.prefill or None, rates=rates,
                    seed=args.seed, autoscale=scale)
    for rep in reports:
        print(f"{rep.policy:18s} goodput={rep.goodput_tokens_per_s:12.1f} "
              f"tok/s  ttft_p99={rep.ttft_p99_s:8.3f}s  "
              f"done={rep.n_done} shed={rep.n_shed} "
              f"preempt={sum(rep.preemptions.values())} "
              f"events={rep.events} wall={rep.wall_s:.2f}s")
    if args.report:
        print("report:", write_report_jsonl(reports, args.report))
    if args.json:
        print("obs:", obs.export_jsonl(args.json))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
