"""One wire protocol for the whole serving fleet.

Every router<->worker message — loadgen submits, heartbeat pings, KV
transfer frames — travels as one FRAME:

    MAGIC "BAF1" | length !I | crc32(payload) !I | payload

    payload = 1 codec byte + body
      codec 0x01  msgpack (bin-typed ndarray envelopes; the fast path)
      codec 0x02  JSON with base64 byte envelopes (no-deps fallback —
                  msgpack is never a hard requirement)

ndarrays ride as {"__nd__": 1, "dtype", "shape", "data"} envelopes and
rebuild exactly (tobytes/frombuffer — the KV plane's byte-identity
tests lean on this).  Tuples decode as lists; message handlers index
positionally, so both shapes dispatch the same.

Two carriers implement the same `Transport` surface (`send` / `recv` /
`flush` / `close`):

  QueueTransport   frame bytes on multiprocessing (or queue.Queue)
                   queues — the in-process cluster path.  What used to
                   be bare-pickle `q.put(tuple)` in loadgen now ships
                   CRC-checked frames, so the single-host cluster and
                   the cross-host fleet literally run one protocol.
  SocketTransport  frames over TCP.  `connect()` retries refused/timed
                   out connections on the PR 10 seeded RetryBackoff;
                   sends carry a timeout (a wedged peer must not wedge
                   the sender); the receive side buffers and reparses,
                   so partial reads are invisible to callers.

Torn-tail contract (mirrors checkpoint.read_journal): a peer that dies
mid-send leaves at most one PARTIAL final frame.  The live receive path
counts it (`torn` on the buffer) and reports clean EOF; the offline
`scan_frames` reader skips a torn/corrupt FINAL frame after >= 1 clean
frame and raises on corruption anywhere else — exactly read_journal's
"skip the torn tail, stay loud on interior corruption".

A CRC-failed frame with intact framing is DROPPED and counted (the
sender retries; `Dedup` makes redelivery idempotent by (rid, seq));
a broken magic means the stream lost sync and raises FrameError.
"""

import base64
import json
import queue as _queue
import socket
import time
import zlib
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from .. import obs
from ..protocols import transport as _proto_wire

try:  # the container bakes msgpack in; the JSON codec keeps this soft
    import msgpack as _msgpack
except ImportError:  # pragma: no cover - exercised via force_json paths
    _msgpack = None

# framing constants live with the pure parse machine — ONE definition
# for production, the offline scanner, and the model checker
MAGIC = _proto_wire.MAGIC
_HEADER = _proto_wire._HEADER  # magic, payload length, crc32(payload)
CODEC_MSGPACK = 1
CODEC_JSON = 2
MAX_FRAME = _proto_wire.MAX_FRAME  # a corrupt length must not OOM us

M_FRAMES_SENT = obs.counter(
    "fleet.frames_sent", "transport frames sent")
M_BYTES_SENT = obs.counter(
    "fleet.bytes_sent", "transport bytes sent (incl. headers)")
M_FRAMES_RECV = obs.counter(
    "fleet.frames_recv", "transport frames received CRC-clean")
M_FRAMES_CRC_REJECTED = obs.counter(
    "fleet.frames_crc_rejected", "frames dropped on CRC mismatch")
M_FRAMES_TORN = obs.counter(
    "fleet.frames_torn", "partial final frames from dead peers")
M_PEER_LOSS_SWALLOWED = obs.counter(
    "fleet.peer_loss_swallowed",
    "dead-peer errors absorbed on transport protocol paths (each one is "
    "also logged — silent-by-design must still be countable)")


def _log():
    from ..obs.logs import get_logger

    return get_logger("burst_attn_tpu.fleet.transport")
M_FRAMES_DEDUPED = obs.counter(
    "fleet.frames_deduped", "duplicate (rid, seq) frames dropped")
M_SEND_RETRIES = obs.counter(
    "fleet.send_retries", "retryable send failures retried")


class TransportError(Exception):
    """Base for transport failures; `retryable` says whether a resend
    (same frame, new attempt) can succeed."""

    retryable = False


class FrameError(TransportError):
    """CRC mismatch or framing corruption.  Retryable: the frame is
    dropped on the floor and the sender's retry path re-ships it."""

    retryable = True


class SendTimeout(TransportError):
    retryable = True


class TransportClosed(TransportError):
    pass


# -- codec ------------------------------------------------------------------


def _nd_envelope(a: np.ndarray) -> dict:
    return {"__nd__": 1, "dtype": str(a.dtype), "shape": list(a.shape),
            "data": np.ascontiguousarray(a).tobytes()}


def _from_envelope(d: dict):
    data = d["data"]
    if isinstance(data, str):  # JSON codec: base64 text
        data = base64.b64decode(data)
    a = np.frombuffer(data, dtype=np.dtype(d["dtype"]))
    return a.reshape([int(s) for s in d["shape"]]).copy()


def _msgpack_default(o):
    if isinstance(o, np.ndarray):
        return _nd_envelope(o)
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    raise TypeError(f"cannot serialize {type(o).__name__} on the fleet wire")


def _msgpack_hook(d: dict):
    if d.get("__nd__"):
        return _from_envelope(d)
    return d


def _jsonify(o):
    """JSON codec pre-pass: envelopes for ndarrays/bytes, plain types
    everywhere else.  Dict keys stringify (JSON law); int-keyed maps on
    the wire must be re-int'd by the consumer — every fleet consumer
    already does (`{int(k): ... for ...}`)."""
    if isinstance(o, np.ndarray):
        env = _nd_envelope(o)
        env["data"] = base64.b64encode(env["data"]).decode("ascii")
        return env
    if isinstance(o, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(o)).decode("ascii")}
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, dict):
        return {str(k): _jsonify(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_jsonify(v) for v in o]
    return o


def _json_hook(d: dict):
    if d.get("__nd__"):
        return _from_envelope(d)
    if "__b64__" in d and len(d) == 1:
        return base64.b64decode(d["__b64__"])
    return d


def encode_message(msg: Any, force_json: bool = False) -> bytes:
    """message -> codec byte + body."""
    if _msgpack is not None and not force_json:
        return bytes([CODEC_MSGPACK]) + _msgpack.packb(
            msg, default=_msgpack_default, use_bin_type=True)
    return bytes([CODEC_JSON]) + json.dumps(_jsonify(msg)).encode("utf-8")


def decode_message(payload: bytes) -> Any:
    if not payload:
        raise FrameError("empty payload")
    codec, body = payload[0], payload[1:]
    if codec == CODEC_MSGPACK:
        if _msgpack is None:  # pragma: no cover - gated dep
            raise FrameError("msgpack frame but msgpack is not installed")
        try:
            return _msgpack.unpackb(body, object_hook=_msgpack_hook,
                                    strict_map_key=False, raw=False)
        except Exception as e:  # msgpack raises a zoo of unpack errors
            raise FrameError(f"undecodable msgpack body: {e}") from e
    if codec == CODEC_JSON:
        try:
            return json.loads(body.decode("utf-8"), object_hook=_json_hook)
        except (UnicodeDecodeError, ValueError) as e:
            raise FrameError(f"undecodable json body: {e}") from e
    raise FrameError(f"unknown codec byte {codec}")


# -- framing ----------------------------------------------------------------


def pack_frame(payload: bytes) -> bytes:
    return _HEADER.pack(MAGIC, len(payload),
                        zlib.crc32(payload) & 0xFFFFFFFF) + payload


def unpack_frame(frame: bytes) -> bytes:
    """Exactly-one-frame validator (the queue carrier: one frame per
    queue item).  Raises FrameError on any mismatch."""
    if len(frame) < _HEADER.size:
        raise FrameError(f"short frame: {len(frame)} bytes")
    magic, length, crc = _HEADER.unpack_from(frame)
    if magic != MAGIC:
        raise FrameError(f"bad magic {magic!r}")
    if length > MAX_FRAME:
        raise FrameError(f"frame length {length} exceeds {MAX_FRAME}")
    payload = frame[_HEADER.size:]
    if len(payload) != length:
        raise FrameError(f"length {len(payload)} != header {length}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        M_FRAMES_CRC_REJECTED.inc()
        raise FrameError("crc mismatch")
    return payload


def scan_frames(data: bytes) -> Tuple[List[bytes], int]:
    """Offline stream reader, read_journal's contract on frames: parse
    payloads in order; a torn or CRC-corrupt FINAL frame after >= 1
    clean frame is skipped and counted; corruption anywhere else (or a
    stream that never yields a clean frame) raises FrameError.  Returns
    (payloads, n_torn)."""
    payloads: List[bytes] = []
    off = 0
    n = len(data)
    while off < n:
        rest = n - off
        if rest < _HEADER.size:
            if payloads:
                return payloads, 1  # torn final header
            raise FrameError(f"truncated header at offset {off}")
        magic, length, crc = _HEADER.unpack_from(data, off)
        if magic != MAGIC or length > MAX_FRAME:
            # a mangled header leaves no trustworthy frame extent: probe
            # for another MAGIC downstream — none means the corruption is
            # confined to the tail (torn final); one means an interior
            # frame was destroyed, which stays loud like read_journal's
            # "corrupt journal line"
            if payloads and data.find(MAGIC, off + 1) == -1:
                return payloads, 1
            raise FrameError(f"bad magic/length at offset {off}")
        end = off + _HEADER.size + length
        if end > n:
            if payloads:
                return payloads, 1  # torn final payload
            raise FrameError(f"truncated payload at offset {off}")
        payload = data[off + _HEADER.size:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            if end == n and payloads:
                return payloads, 1  # corrupt FINAL frame == torn tail
            raise FrameError(f"crc mismatch at offset {off}")
        payloads.append(payload)
        off = end
    return payloads, 0


class FrameBuffer:
    """Incremental frame parser for the live receive path (sockets feed
    it chunks; fuzz feeds it mutated streams).  Policy: a CRC-failed
    frame whose framing is intact is dropped and counted (`crc_rejected`
    — the peer's retry re-ships it); broken magic or an absurd length
    means lost sync and raises FrameError; `eof()` with a partial frame
    pending counts a torn tail, exactly like read_journal's final line.

    The parse itself is the PURE machine `protocols.transport.wire_step`
    — the same transition function burstcheck's wire model explores —
    this class only applies its outputs (frame queue, obs counters, the
    desync raise).
    """

    def __init__(self):
        self._wire = _proto_wire.wire_init()
        self.frames: deque = deque()

    @property
    def crc_rejected(self) -> int:
        return self._wire.crc_rejected

    @property
    def torn(self) -> int:
        return self._wire.torn

    def feed(self, chunk: bytes) -> None:
        self._wire, outs = _proto_wire.wire_step(self._wire,
                                                 ("feed", bytes(chunk)))
        for out in outs:
            if out[0] == "frame":
                self.frames.append(out[1])
                M_FRAMES_RECV.inc()
            elif out[0] == "crc_reject":
                M_FRAMES_CRC_REJECTED.inc()
            else:  # ("desync", msg): terminal — the stream lost sync
                raise FrameError(out[1])

    def eof(self) -> None:
        """Peer closed: a pending partial frame is a torn tail."""
        self._wire, outs = _proto_wire.wire_step(self._wire, ("eof",))
        if outs:
            M_FRAMES_TORN.inc()

    def pending(self) -> int:
        return len(self._wire.buf)


class Dedup:
    """At-least-once -> exactly-once: retried sends may deliver a frame
    twice; consumers key idempotency by (rid, seq) and drop repeats.
    Decisions come from `protocols.transport.dedup_step` — the machine
    the checker's redelivery model runs."""

    def __init__(self):
        self._state = _proto_wire.dedup_init()

    @property
    def _seen(self):
        return set(self._state.seen)

    def accept(self, rid, seq) -> bool:
        self._state, outs = _proto_wire.dedup_step(self._state,
                                                   ("frame", rid, seq))
        if outs[0][0] == "dup":
            M_FRAMES_DEDUPED.inc()
            return False
        return True

    def forget_rid(self, rid) -> None:
        """A new transfer attempt for `rid` restarts its seq space."""
        self._state, _ = _proto_wire.dedup_step(self._state,
                                                ("forget", rid))


# -- carriers ---------------------------------------------------------------


class QueueTransport:
    """Frames over queue.Queue / multiprocessing.Queue pairs.  `send_q`
    is OUR outbound direction (the peer's recv side).  recv() returns
    None on empty/torn-down queues — the poll idiom the cluster router
    already speaks."""

    def __init__(self, send_q, recv_q):
        self._send_q = send_q
        self._recv_q = recv_q
        self._flushed = False

    def send(self, msg: Any) -> None:
        frame = pack_frame(encode_message(msg))
        try:
            self._send_q.put(frame)
        except (OSError, ValueError) as e:
            raise TransportClosed(f"send queue torn down: {e}") from e
        M_FRAMES_SENT.inc()
        M_BYTES_SENT.inc(len(frame))

    def recv(self, timeout: float = 0.0) -> Optional[Any]:
        try:
            if timeout > 0:
                frame = self._recv_q.get(timeout=timeout)
            else:
                frame = self._recv_q.get_nowait()
        except _queue.Empty:
            return None
        except (OSError, EOFError, ValueError) as e:
            # queue torn down under us (dead peer): None is the contract,
            # but the absorbed error must stay observable — a recv loop
            # spinning on a dead queue shows up as this counter climbing
            M_PEER_LOSS_SWALLOWED.inc()
            from ..obs.logs import safe_warn
            safe_warn(_log(), "recv queue torn down (%s: %s); "
                      "returning None", type(e).__name__, e)
            return None
        return decode_message(unpack_frame(frame))

    def flush(self) -> None:
        """Drain the mp feeder thread so already-sent frames survive this
        process dying right after (the worker error path: the "error"
        frame must reach the router even though we are about to raise).
        After flush() the send side is closed."""
        if self._flushed:
            return
        self._flushed = True
        q = self._send_q
        if hasattr(q, "close") and hasattr(q, "join_thread"):
            q.close()
            q.join_thread()

    def close(self) -> None:
        self.flush()


class SocketTransport:
    """Frames over one TCP connection."""

    def __init__(self, sock: socket.socket, send_timeout_s: float = 30.0):
        self._sock = sock
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.send_timeout_s = send_timeout_s
        self._fb = FrameBuffer()
        self._closed = False

    @classmethod
    def connect(cls, host: str, port: int, *, timeout_s: float = 5.0,
                retries: int = 8, backoff=None, rid: int = 0,
                send_timeout_s: float = 30.0) -> "SocketTransport":
        """Dial with retry: refused/timed-out connects back off on the
        seeded RetryBackoff (loadgen.driver) and redial — a worker that
        boots before its router's listener is up must not die for it."""
        from ..loadgen.driver import RetryBackoff

        bo = backoff if backoff is not None else RetryBackoff()
        last = None
        for attempt in range(retries + 1):
            try:
                sock = socket.create_connection((host, port),
                                                timeout=timeout_s)
                return cls(sock, send_timeout_s=send_timeout_s)
            except (ConnectionRefusedError, socket.timeout, OSError) as e:
                last = e
                if attempt < retries:
                    M_SEND_RETRIES.inc()
                    time.sleep(bo.delay(rid, attempt + 1))
        raise TransportClosed(
            f"connect to {host}:{port} failed after {retries + 1} "
            f"attempts: {last}")

    def send(self, msg: Any) -> None:
        if self._closed:
            raise TransportClosed("transport already closed")
        frame = pack_frame(encode_message(msg))
        self._sock.settimeout(self.send_timeout_s)
        try:
            self._sock.sendall(frame)
        except socket.timeout as e:
            raise SendTimeout(
                f"send timed out after {self.send_timeout_s:g}s") from e
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            self._closed = True
            raise TransportClosed(f"peer gone: {e}") from e
        M_FRAMES_SENT.inc()
        M_BYTES_SENT.inc(len(frame))

    def recv(self, timeout: float = 0.0) -> Optional[Any]:
        deadline = time.monotonic() + max(timeout, 0.0)
        while True:
            if self._fb.frames:
                return decode_message(self._fb.frames.popleft())
            if self._closed:
                return None
            remaining = deadline - time.monotonic()
            self._sock.settimeout(max(remaining, 0.0) or 1e-4)
            try:
                chunk = self._sock.recv(1 << 16)
            except (socket.timeout, BlockingIOError):
                if time.monotonic() >= deadline:
                    return None
                continue
            except (ConnectionResetError, OSError) as e:
                # peer reset mid-read: converted to EOF so the torn-tail
                # accounting below runs, but logged + counted first
                M_PEER_LOSS_SWALLOWED.inc()
                from ..obs.logs import safe_warn
                safe_warn(_log(), "socket recv failed (%s: %s); "
                          "treating as EOF", type(e).__name__, e)
                chunk = b""
            if not chunk:
                self._closed = True
                self._fb.eof()  # partial tail from a dead peer: torn
                continue
            self._fb.feed(chunk)

    @property
    def torn(self) -> int:
        return self._fb.torn

    def flush(self) -> None:
        pass  # sendall is synchronous

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError as e:
            # already dead; close is best-effort by contract — but count
            # it, so "every teardown errors" is visible in obs
            M_PEER_LOSS_SWALLOWED.inc()
            from ..obs.logs import safe_warn
            safe_warn(_log(), "socket close failed (%s: %s); ignored",
                      type(e).__name__, e)


def listen(host: str = "127.0.0.1", port: int = 0):
    """(listening socket, bound port) for a fleet router."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(64)
    return sock, sock.getsockname()[1]


def accept(listener: socket.socket, timeout_s: float = 60.0,
           send_timeout_s: float = 30.0) -> SocketTransport:
    listener.settimeout(timeout_s)
    try:
        conn, _ = listener.accept()
    except socket.timeout as e:
        raise TransportClosed(
            f"no connection within {timeout_s:g}s") from e
    return SocketTransport(conn, send_timeout_s=send_timeout_s)


def send_with_retry(transport, msg: Any, *, backoff=None, retries: int = 5,
                    rid: int = 0,
                    reconnect: Optional[Callable[[], Any]] = None):
    """Send with the seeded backoff on every retryable failure.  When
    `reconnect` is given a TransportClosed also retries through a fresh
    transport (returned so the caller adopts it); otherwise only
    retryable errors (timeouts, CRC rejections surfaced by a NACK path)
    are retried."""
    from ..loadgen.driver import RetryBackoff

    bo = backoff if backoff is not None else RetryBackoff()
    cur = transport
    for attempt in range(retries + 1):
        try:
            cur.send(msg)
            return cur
        except TransportError as e:
            recoverable = e.retryable or (
                isinstance(e, TransportClosed) and reconnect is not None)
            if attempt >= retries or not recoverable:
                raise
            M_SEND_RETRIES.inc()
            time.sleep(bo.delay(rid, attempt + 1))
            if isinstance(e, TransportClosed) and reconnect is not None:
                cur = reconnect()
    return cur
