"""Disaggregated prefill/decode serving fleet (ROADMAP item 2).

Three layers, bottom up:

  transport.py  one wire protocol — length-prefixed, CRC-framed msgpack
                (JSON fallback) — with two carriers: multiprocessing
                queues (in-process clusters, loadgen/) and TCP sockets
                (cross-host fleets).  Torn-final-frame tolerant on the
                receive side exactly like checkpoint.read_journal.
  kvplane.py    the KV transfer plane: a handoff slot's pool pages
                serialized per-page in table order, staged on the
                receiver, and committed transactionally (all pages land
                CRC-clean or zero pool mutation).
  fleet.py      the role-split fleet: a ring-prefill worker pool and a
                paged-decode replica pool behind one router, with
                cross-boundary failover (heartbeats, journaled resume,
                snapshot restarts) and load-aware routing + autoscaling.
"""

from .transport import (  # noqa: F401
    Dedup, FrameBuffer, FrameError, QueueTransport, SendTimeout,
    SocketTransport, TransportClosed, TransportError, decode_message,
    encode_message, pack_frame, scan_frames, send_with_retry, unpack_frame,
)
from .kvplane import (  # noqa: F401
    KvReceiver, export_slot_pages, page_bytes, page_digest,
)
from .fleet import (  # noqa: F401
    FLEET_FAULT_KINDS, FleetCluster, FleetFault, FleetReport, fleet_oracle,
)
