"""Ragged paged serving subsystem: one kernel, one pool, one engine.

The fourth pillar next to ring/obs/analysis (ROADMAP open item 3).  The
pieces:

  * `ops/ragged_paged.py` — ONE Pallas launch attending a mixed
    chunked-prefill + decode token batch against the paged KV pool.
  * `serving.model.ragged_model_step` — the jitted transformer step that
    scatters each slot's new K/V into its pages and attends through the
    ragged kernel (or the dense-gather fallback when the capability
    probe declines).
  * `serving.engine.RaggedServeEngine` — continuous batching: per-step
    admission, chunked prefill interleaved with in-flight decode, page
    allocation/eviction from one pool, speculative decoding as a
    scheduler policy.
  * `serving.handoff` — the million-token path: ring-sharded prefill
    whose K/V lands DIRECTLY in pool pages (no re-layout copy), feeding
    sequence-parallel paged decode (models/dist_decode.py);
    `handoff_decode` is the resumable/journaled decode surface.
  * `serving.checkpoint` — crash consistency: atomic engine snapshots,
    the write-ahead token journal, and resume-not-replay recovery
    (`recover_engine`) for both engines and the bare handoff state.

docs/serving.md walks the batch layout, page-table format, scheduler
policy, the handoff diagram, and the recovery protocol.
"""

from .engine import RaggedServeEngine
from .model import ragged_model_step
from .handoff import check_handoff_preconditions, handoff_decode, \
    handoff_generate, ring_prefill_to_pages
from .checkpoint import (
    RecoveryInfo, TokenJournal, journal_tokens_by_ext, journal_view,
    load_paged_snapshot, load_snapshot, read_journal, recover_engine,
    restore_into, rewrite_journal, run_recovered, save_paged_snapshot,
    save_snapshot, trim_complete,
)

__all__ = [
    "RaggedServeEngine",
    "RecoveryInfo",
    "TokenJournal",
    "check_handoff_preconditions",
    "handoff_decode",
    "handoff_generate",
    "journal_tokens_by_ext",
    "journal_view",
    "load_paged_snapshot",
    "load_snapshot",
    "ragged_model_step",
    "read_journal",
    "recover_engine",
    "restore_into",
    "rewrite_journal",
    "ring_prefill_to_pages",
    "run_recovered",
    "save_paged_snapshot",
    "save_snapshot",
    "trim_complete",
]
