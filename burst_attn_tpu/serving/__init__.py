"""Ragged paged serving subsystem: one kernel, one pool, one engine.

The fourth pillar next to ring/obs/analysis (ROADMAP open item 3).  The
pieces:

  * `ops/ragged_paged.py` — ONE Pallas launch attending a mixed
    chunked-prefill + decode token batch against the paged KV pool.
  * `serving.model.ragged_model_step` — the jitted transformer step that
    scatters each slot's new K/V into its pages and attends through the
    ragged kernel (or the dense-gather fallback when the capability
    probe declines).
  * `serving.engine.RaggedServeEngine` — continuous batching: per-step
    admission, chunked prefill interleaved with in-flight decode, page
    allocation/eviction from one pool, speculative decoding as a
    scheduler policy.
  * `serving.handoff` — the million-token path: ring-sharded prefill
    whose K/V lands DIRECTLY in pool pages (no re-layout copy), feeding
    sequence-parallel paged decode (models/dist_decode.py).

docs/serving.md walks the batch layout, page-table format, scheduler
policy, and the handoff diagram.
"""

from .engine import RaggedServeEngine
from .model import ragged_model_step
from .handoff import ring_prefill_to_pages, handoff_generate

__all__ = [
    "RaggedServeEngine",
    "ragged_model_step",
    "ring_prefill_to_pages",
    "handoff_generate",
]
