"""Crash-consistent serving: engine snapshots + the write-ahead token
journal (ROADMAP item 5's single-host remainder).

A SIGKILL'd worker used to cost every in-flight request a full
regeneration from scratch — at million-token prompts that is an entire
ring prefill re-burned per crash.  This module makes recovery RESUME
instead of replay, with two durability layers that compose:

  SNAPSHOT  `save_snapshot(engine, path)` serializes the engine's whole
            serving state — page-pool contents (every layer's K/V pages
            + scales), page tables, per-sequence request metadata
            (prompt, budget, generated tokens, prefill cursor), the
            admission queue, the sampler RNG key, and the host pool's
            free-list/refcounts — into ONE atomic `.npz` (tmp + fsync +
            rename, so a crash mid-save leaves the previous snapshot
            intact).  `restore_into` rebuilds a fresh same-spec engine
            bit-for-bit: its subsequent `run()` is token-exact with the
            uninterrupted oracle because the RNG key, pool order, and
            device state are all restored exactly.  Works for both
            `RaggedServeEngine` and the legacy `ServeEngine`.

  JOURNAL   `TokenJournal` is a write-ahead fsynced JSONL of per-tick
            token records (the engines append under their step() sync
            barrier: tokens are DURABLE before the result leaves the
            process).  The reader is torn-tail tolerant with exactly
            `obs.aggregate.load_records_tolerant`'s semantics — a kill
            mid-append tears at most the final line, which is skipped
            and counted; corruption anywhere else stays loud.

Recovery (`recover_engine`) composes them: restore the last snapshot if
one exists, then roll the journal forward — sequences present in the
snapshot re-decode only the journal LAG (tokens journaled after the
snapshot), sequences known only to the journal resume via prompt-concat
prefill (the journaled prefix is teacher-forced as prompt, never
re-decoded; greedy continuation of a greedy prefix is identical).  The
split is accounted on two counters the acceptance gate reads:

  serve.recovered_tokens_replayed   tokens a recovery had to RE-DECODE
                                    (bounded by journal lag / snapshot
                                    cadence — strictly below the
                                    replay-from-scratch baseline)
  serve.recovered_tokens_resumed    tokens recovered WITHOUT re-decoding
                                    (snapshot state + journal prefixes)

Journal-prefix resume requires greedy decoding (temperature 0): the
prefix is only a valid continuation seed when the engine would have
deterministically produced it.  Snapshot restore has no such limit —
the RNG key is part of the snapshot, so sampled streams restore exactly.

Prefix caching (ISSUE 13) snapshots completely: the pool's refcounts
were always serialized wholesale (`_pool_meta`), and the meta now also
carries the PrefixCache hash-chain index (`PrefixCache.to_meta`, LRU
order preserved) plus the ragged engine's slot -> pinned-shared-pages
map, so a restored engine keeps every sharing relationship — restore
NEVER re-bumps refcounts (the serialized totals already include the
cache's references; double-bumping is exactly the leak the checkpoint
fuzz hunts).

Unsupported for snapshot: engines with a draft model attached
(speculative mirror state) or a tp mesh on the legacy engine —
`save_snapshot` raises rather than silently dropping state.
"""

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import obs

M_RECOVERED_REPLAYED = obs.counter(
    "serve.recovered_tokens_replayed",
    "previously generated tokens a recovery had to re-decode "
    "(journal lag past the last snapshot)")
M_RECOVERED_RESUMED = obs.counter(
    "serve.recovered_tokens_resumed",
    "previously generated tokens recovered without re-decoding "
    "(snapshot state + journaled prefixes)")
M_JOURNAL_RECORDS = obs.counter(
    "serve.journal_records", "write-ahead token journal records appended")
M_SNAPSHOT_SAVES = obs.counter(
    "serve.checkpoint_saves", "atomic engine snapshots written")
M_JOURNAL_REOPEN_CORRUPT = obs.counter(
    "serve.journal_reopen_corrupt",
    "append-mode journal reopens that found an unreadable file")

SNAPSHOT_VERSION = 1


def _log():
    from ..obs.logs import get_logger

    return get_logger("burst_attn_tpu.serving.checkpoint")


# -- write-ahead token journal ---------------------------------------------


class TokenJournal:
    """Append-only fsynced JSONL keyed by ENGINE rid.  Records:

      {"record": "submit", "rid": R, "ext": E, "prompt": [...],
       "max_new": M}                     ownership: engine rid R serves
                                         external (router) rid E
      {"record": "tokens", "rid": R, "toks": [...]}   tokens appended
      {"record": "done",   "rid": R}     request finished (write-ahead:
                                         journaled before the result is
                                         reported anywhere)
      {"record": "reset",  "rid": R}     drain() requeued the request —
                                         its token prefix is void

    Appends buffer in the file object; `sync()` (flush + fsync) is the
    durability barrier — the engines call it once per step(), AFTER the
    tick's appends and BEFORE returning results, so any token a caller
    has seen is on disk.

    Every append/sync/deliver runs the PURE machine
    `protocols.journal.step` in lockstep with the file — the same
    transition function burstcheck model-checks (proto-journal-durable).
    `delivered(rid, n)` is the engines' delivery barrier: it raises
    `DurabilityViolation` if a caller is about to see tokens the fsync
    has not covered, so a sync-ordering regression fails in every
    journaled test run, not just in the checker."""

    def __init__(self, path: str, *, truncate: bool = False):
        from ..protocols import journal as _jp

        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._proto = _jp.init()
        if not truncate and os.path.exists(path) and os.path.getsize(path):
            # append-mode reopen: seed the machine's durable view with the
            # existing file's fold so delivery checks stay exact
            try:
                view = journal_view(path)
                self._proto = self._proto._replace(
                    durable=tuple(sorted((r, len(t))
                                         for r, t in view.tokens.items())),
                    durable_done=tuple(sorted(view.done)))
            except ValueError as e:
                M_JOURNAL_REOPEN_CORRUPT.inc()
                _log().warning(
                    "journal %s unreadable on append-mode reopen (%s); "
                    "delivery tracking restarts empty", path, e)
        self._f = open(path, "w" if truncate else "a", encoding="utf-8")
        self._dirty = False

    def _proto_step(self, event) -> None:
        from ..protocols import journal as _jp

        self._proto, _ = _jp.step(self._proto, event)

    def _append(self, rec: dict) -> None:
        self._proto_step(("append", rec["record"], rec["rid"],
                          len(rec.get("toks", ()))))
        self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._dirty = True
        M_JOURNAL_RECORDS.inc()

    def submit(self, rid: int, ext: int, prompt, max_new: int) -> None:
        self._append({"record": "submit", "rid": int(rid), "ext": int(ext),
                      "prompt": [int(x) for x in prompt],
                      "max_new": int(max_new)})

    def tokens(self, rid: int, toks) -> None:
        toks = [int(t) for t in toks]
        if toks:
            self._append({"record": "tokens", "rid": int(rid), "toks": toks})

    def done(self, rid: int) -> None:
        self._append({"record": "done", "rid": int(rid)})

    def reset(self, rid: int) -> None:
        self._append({"record": "reset", "rid": int(rid)})

    def sync(self) -> None:
        if self._dirty and not self._f.closed:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._dirty = False
            self._proto_step(("sync",))

    def delivered(self, rid: int, n_total: int) -> None:
        """The delivery barrier: a caller is observing `rid` at
        `n_total` total journaled tokens.  Raises DurabilityViolation
        (protocols.journal) when those tokens are not yet durable —
        i.e. someone returned results before sync()."""
        self._proto_step(("deliver", int(rid), int(n_total)))

    def close(self) -> None:
        if not self._f.closed:
            self.sync()
            self._f.close()


def read_journal(path: str) -> Tuple[List[dict], int]:
    """(records, n_skipped) — torn-tail tolerant with
    `obs.aggregate.load_records_tolerant`'s exact semantics: a SIGKILL
    lands mid-append at most once, at the END of the file, so a bad
    FINAL line (with valid records before it) is skipped and counted;
    a bad line anywhere ELSE is real corruption and raises."""
    records: List[dict] = []
    n_skipped = 0
    with open(path, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    last = len(lines) - 1
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict) or "record" not in rec:
                raise ValueError("not a journal record")
        except ValueError:
            if i == last and records:
                n_skipped += 1
                continue
            raise ValueError(
                f"corrupt journal line {i + 1} in {path!r}: {line[:120]!r}")
        records.append(rec)
    return records, n_skipped


@dataclass
class JournalView:
    """The journal folded into per-request state (resets applied)."""

    submits: Dict[int, dict] = field(default_factory=dict)   # rid -> record
    tokens: Dict[int, List[int]] = field(default_factory=dict)
    done: set = field(default_factory=set)
    n_skipped: int = 0


def journal_view(path: Optional[str]) -> JournalView:
    """Fold a journal file; a missing path is an empty view (a worker
    killed before its first sync left nothing — recovery starts from
    the prompt)."""
    view = JournalView()
    if not path or not os.path.exists(path):
        return view
    records, view.n_skipped = read_journal(path)
    for rec in records:
        rid = int(rec["rid"])
        kind = rec["record"]
        if kind == "submit":
            view.submits[rid] = rec
            view.tokens.setdefault(rid, [])
        elif kind == "tokens":
            view.tokens.setdefault(rid, []).extend(
                int(t) for t in rec["toks"])
        elif kind == "done":
            view.done.add(rid)
        elif kind == "reset":
            view.tokens[rid] = []
    return view


def journal_tokens_by_ext(path: Optional[str]) -> Dict[int, List[int]]:
    """external rid -> journaled tokens, for every request the journal
    knows (the router's reroute map: a dead worker's journal tells the
    replacement route how far each sequence already got)."""
    view = journal_view(path)
    out: Dict[int, List[int]] = {}
    for rid, sub in view.submits.items():
        out[int(sub["ext"])] = list(view.tokens.get(rid, []))
    return out


def trim_complete(toks: List[int], max_new: int,
                  eos_id: Optional[int]) -> Optional[List[int]]:
    """If a journaled prefix already satisfies the request (budget hit or
    EOS emitted), return the trimmed final stream; else None.  Matches
    the engines' retirement rule (first EOS wins, then the budget)."""
    toks = [int(t) for t in toks]
    if eos_id is not None and eos_id in toks:
        return toks[: toks.index(eos_id) + 1]
    if len(toks) >= max_new:
        return toks[:max_new]
    return None


# -- atomic npz snapshot ----------------------------------------------------


def _atomic_savez(path: str, meta: dict,
                  arrays: Dict[str, np.ndarray]) -> None:
    """Write meta (JSON, as a uint8 entry) + arrays as ONE npz, atomically:
    tmp file, fsync, rename — a crash mid-save never clobbers the
    previous snapshot."""
    payload = dict(arrays)
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_snapshot(path: str) -> dict:
    """{"meta": dict, "arrays": {name: np.ndarray}} from one snapshot."""
    with np.load(path) as z:
        arrays = {k: np.asarray(z[k]) for k in z.files if k != "__meta__"}
        meta = json.loads(z["__meta__"].tobytes().decode("utf-8"))
    if meta.get("version") != SNAPSHOT_VERSION:
        raise ValueError(f"snapshot {path!r} has version "
                         f"{meta.get('version')!r}, this build reads "
                         f"{SNAPSHOT_VERSION}")
    return {"meta": meta, "arrays": arrays}


def _paged_arrays(state) -> Dict[str, np.ndarray]:
    """PagedState -> host arrays (np.asarray gathers sharded pools)."""
    arrays: Dict[str, np.ndarray] = {
        "page_table": np.asarray(state.page_table),
        "lengths": np.asarray(state.lengths),
    }
    quant = state.k_scales is not None
    for li in range(len(state.k_pages)):
        arrays[f"k_pages_{li}"] = np.asarray(state.k_pages[li])
        arrays[f"v_pages_{li}"] = np.asarray(state.v_pages[li])
        if quant:
            arrays[f"k_scales_{li}"] = np.asarray(state.k_scales[li])
            arrays[f"v_scales_{li}"] = np.asarray(state.v_scales[li])
    return arrays


def _pool_array(a: np.ndarray, page_dtype: Optional[str]):
    """One saved page bank back to a jnp array.  np.load hands ml_dtypes
    extension dtypes (bfloat16 pools, fp8 pools) back as raw void bytes
    (`|V1`/`|V2`) — the bytes are exact, only the dtype name is lost —
    so a void bank is re-viewed through the page dtype name recorded in
    the snapshot's pool meta."""
    import jax.numpy as jnp

    if a.dtype.kind == "V":
        if not page_dtype:
            raise ValueError(
                f"snapshot page bank has an opaque dtype ({a.dtype.str}) "
                f"and the snapshot records no page dtype to re-view it "
                f"through")
        a = a.view(np.dtype(page_dtype))
    return jnp.asarray(a)


def _paged_from_arrays(arrays: Dict[str, np.ndarray], n_layers: int,
                       pool_meta: Optional[dict] = None):
    from ..models.paged_decode import PagedState

    import jax.numpy as jnp

    pd = (pool_meta or {}).get("page_dtype")
    quant = "k_scales_0" in arrays
    return PagedState(
        tuple(_pool_array(arrays[f"k_pages_{li}"], pd)
              for li in range(n_layers)),
        tuple(_pool_array(arrays[f"v_pages_{li}"], pd)
              for li in range(n_layers)),
        jnp.asarray(arrays["page_table"]),
        jnp.asarray(arrays["lengths"]),
        tuple(jnp.asarray(arrays[f"k_scales_{li}"])
              for li in range(n_layers)) if quant else None,
        tuple(jnp.asarray(arrays[f"v_scales_{li}"])
              for li in range(n_layers)) if quant else None,
    )


def _pool_meta(pool, state=None) -> dict:
    meta = {"n_pages": int(pool.n_pages),
            "dtype": pool.dtype,
            "free": [int(p) for p in pool._free],
            "refs": [int(r) for r in pool._refs]}
    if state is not None:
        # the element dtype of the page banks, by NAME: np.load strips
        # ml_dtypes extension dtypes (bfloat16, fp8) to raw void bytes,
        # so restore re-views the banks through this
        meta["page_dtype"] = str(state.k_pages[0].dtype)
    return meta


def _pool_restore(pool, meta: dict) -> None:
    if int(meta["n_pages"]) != int(pool.n_pages):
        raise ValueError(f"snapshot pool has {meta['n_pages']} pages, "
                         f"engine pool has {pool.n_pages}")
    # dtype agreement: restoring a quantized snapshot into a pool of a
    # different storage dtype would reinterpret page bytes (old
    # snapshots carry no tag — treated as full-precision, i.e. None)
    if meta.get("dtype") != pool.dtype:
        raise ValueError(f"snapshot pool dtype {meta.get('dtype')!r} != "
                         f"engine pool dtype {pool.dtype!r}")
    pool._free = [int(p) for p in meta["free"]]
    pool._refs = [int(r) for r in meta["refs"]]


def _new_pool(meta: dict):
    from ..models.paged_decode import PagePool

    pool = PagePool(int(meta["n_pages"]), dtype=meta.get("dtype"))
    _pool_restore(pool, meta)
    return pool


# -- engine snapshot --------------------------------------------------------


def _engine_kind(engine) -> str:
    from ..models.serve import ServeEngine
    from .engine import RaggedServeEngine

    if isinstance(engine, RaggedServeEngine):
        return "ragged"
    if isinstance(engine, ServeEngine):
        return "legacy"
    raise TypeError(f"cannot snapshot a {type(engine).__name__}")


def _check_snapshotable(engine, kind: str) -> None:
    if engine.draft is not None:
        raise ValueError("snapshot does not support engines with a draft "
                         "model attached (speculative mirror state)")
    if kind == "legacy":
        if getattr(engine, "mesh", None) is not None:
            raise ValueError("snapshot does not support a tp-sharded "
                             "legacy engine")


def _req_to_dict(req, kind: str) -> dict:
    d = {"rid": int(req.rid), "prompt": [int(x) for x in req.prompt],
         "max_new": int(req.max_new_tokens),
         "tokens": [int(t) for t in req.tokens]}
    if kind == "ragged":
        d["n_prefilled"] = int(req.n_prefilled)
    return d


def _req_from_dict(d: dict, kind: str):
    if kind == "ragged":
        from .engine import _Request

        return _Request(int(d["rid"]), np.asarray(d["prompt"], np.int32),
                        int(d["max_new"]),
                        tokens=[int(t) for t in d["tokens"]],
                        t_submit=time.perf_counter(),
                        n_prefilled=int(d.get("n_prefilled", 0)))
    from ..models.serve import _Request

    return _Request(int(d["rid"]), np.asarray(d["prompt"], np.int32),
                    int(d["max_new"]),
                    tokens=[int(t) for t in d["tokens"]],
                    t_submit=time.perf_counter())


def _rng_meta(key) -> dict:
    import jax

    try:
        typed = jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key)
    except (AttributeError, TypeError):
        typed = False
    if typed:
        return {"typed": True, "impl": str(jax.random.key_impl(key)),
                "data": np.asarray(jax.random.key_data(key)).tolist()}
    return {"typed": False, "data": np.asarray(key).tolist()}


def _rng_restore(meta: dict):
    import jax
    import jax.numpy as jnp

    data = jnp.asarray(np.asarray(meta["data"], np.uint32))
    if meta.get("typed"):
        return jax.random.wrap_key_data(data)
    return data


def snapshot(engine, extra: Optional[dict] = None) -> Tuple[dict, dict]:
    """(meta, arrays) for one engine — everything restore_into needs.
    `extra` is caller payload carried verbatim (the loadgen worker stores
    its engine-rid -> router-rid map and resume prefixes here)."""
    kind = _engine_kind(engine)
    _check_snapshotable(engine, kind)
    # a pipelined engine must quiesce first: an in-flight deferred launch
    # holds sampled-but-unaccounted tokens on device that no snapshot
    # field can represent (legacy engines have no pipeline to flush)
    flush = getattr(engine, "flush_pipeline", None)
    if flush is not None:
        flush()
    meta = {
        "version": SNAPSHOT_VERSION,
        "kind": kind,
        "n_layers": len(engine.state.k_pages),
        "slots_n": len(engine.slots),
        "page": int(engine.page),
        "pool": _pool_meta(engine.pool, engine.state),
        "slots": [None if r is None else _req_to_dict(r, kind)
                  for r in engine.slots],
        "queue": [_req_to_dict(r, kind) for r in engine._queue],
        "next_tok": [int(t) for t in engine._next_tok],
        "next_id": int(engine._next_id),
        "finished": [[int(rid), [int(t) for t in toks]]
                     for rid, toks in sorted(engine._finished.items())],
        "rng": _rng_meta(engine._rng),
        "spec": [int(engine.spec_proposed), int(engine.spec_accepted),
                 int(engine.spec_rounds)],
        "extra": extra or {},
    }
    cache = getattr(engine, "cache", None)
    if cache is not None:
        # pool refcounts (including the cache's own references) already
        # ride in meta["pool"]; this is the index itself, LRU-ordered
        meta["prefix_cache"] = cache.to_meta()
        if kind == "ragged":
            meta["shared"] = [[int(s), [int(p) for p in pages]]
                              for s, pages in sorted(engine._shared.items())]
    return meta, _paged_arrays(engine.state)


def save_snapshot(engine, path: str, extra: Optional[dict] = None) -> None:
    """Serialize `engine` to `path` atomically (see module docstring)."""
    meta, arrays = snapshot(engine, extra)
    _atomic_savez(path, meta, arrays)
    M_SNAPSHOT_SAVES.inc()


def restore_into(engine, snap: dict) -> dict:
    """Apply a loaded snapshot to a FRESHLY BUILT engine with identical
    specs (same model/params, slots, pool size, page size).  Returns the
    snapshot's `extra` payload.  The restored engine's run() is
    token-exact with the uninterrupted original: device state, pool
    order, request metadata, and the sampler RNG key are all exact."""
    meta = snap["meta"]
    kind = _engine_kind(engine)
    _check_snapshotable(engine, kind)
    if meta["kind"] != kind:
        raise ValueError(f"snapshot is for a {meta['kind']!r} engine, "
                         f"restore target is {kind!r}")
    if meta["slots_n"] != len(engine.slots):
        raise ValueError(f"snapshot has {meta['slots_n']} slots, engine "
                         f"has {len(engine.slots)}")
    if meta["page"] != int(engine.page):
        raise ValueError(f"snapshot page size {meta['page']} != engine "
                         f"page size {engine.page}")
    if meta["n_layers"] != len(engine.state.k_pages):
        raise ValueError(f"snapshot has {meta['n_layers']} layers, engine "
                         f"model has {len(engine.state.k_pages)}")
    want = snap["arrays"]["k_pages_0"].shape
    have = tuple(engine.state.k_pages[0].shape)
    if tuple(want) != have:
        raise ValueError(f"snapshot pool geometry {tuple(want)} != engine "
                         f"pool geometry {have}")
    _pool_restore(engine.pool, meta["pool"])
    engine.state = _paged_from_arrays(snap["arrays"], meta["n_layers"],
                                      meta["pool"])
    engine.slots = [None if d is None else _req_from_dict(d, kind)
                    for d in meta["slots"]]
    engine._queue = [_req_from_dict(d, kind) for d in meta["queue"]]
    engine._next_tok = np.asarray(meta["next_tok"], np.int32)
    engine._next_id = int(meta["next_id"])
    engine._finished = {int(rid): [int(t) for t in toks]
                        for rid, toks in meta["finished"]}
    engine._rng = _rng_restore(meta["rng"])
    engine.spec_proposed, engine.spec_accepted, engine.spec_rounds = \
        meta["spec"]
    cache_meta = meta.get("prefix_cache")
    if cache_meta is not None:
        from ..models.paged_decode import PrefixCache

        if getattr(engine, "cache", None) is None:
            raise ValueError(
                "snapshot carries a prefix cache; build the restore "
                "target with prefix_cache=True")
        # from_meta does NOT re-bump refcounts — _pool_restore already
        # installed the totals that include the cache's references
        engine.cache = PrefixCache.from_meta(engine.pool, cache_meta)
    elif getattr(engine, "cache", None) is not None:
        # cache-less snapshot into a cache-enabled engine: start empty
        from ..models.paged_decode import PrefixCache

        engine.cache = PrefixCache(engine.pool)
    if kind == "ragged":
        engine._shared = {int(s): tuple(int(p) for p in pages)
                          for s, pages in meta.get("shared", [])}
    return meta.get("extra", {})


# -- paged-state-level snapshot (the handoff path has no engine) ------------


def save_paged_snapshot(path: str, state, pool,
                        extra: Optional[dict] = None) -> None:
    """Snapshot a bare PagedState + PagePool (the ring->pages handoff
    decode loop runs without an engine object).  Same atomic format."""
    meta = {"version": SNAPSHOT_VERSION, "kind": "paged",
            "n_layers": len(state.k_pages),
            "pool": _pool_meta(pool, state),
            "extra": extra or {}}
    _atomic_savez(path, meta, _paged_arrays(state))
    M_SNAPSHOT_SAVES.inc()


def load_paged_snapshot(path: str):
    """(PagedState, PagePool, extra) from a save_paged_snapshot file —
    fresh arrays/pool, nothing shared with the writer (restart semantics:
    a replacement process rebuilds the whole serving state from disk)."""
    snap = load_snapshot(path)
    meta = snap["meta"]
    if meta["kind"] != "paged":
        raise ValueError(f"{path!r} is a {meta['kind']!r} snapshot, not a "
                         "bare paged snapshot")
    state = _paged_from_arrays(snap["arrays"], meta["n_layers"],
                               meta["pool"])
    return state, _new_pool(meta["pool"]), meta.get("extra", {})


# -- recovery ---------------------------------------------------------------


@dataclass
class RecoveryInfo:
    """What recover_engine did, per EXTERNAL rid.  `replayed` tokens will
    be re-decoded by the engine (journal lag past the snapshot);
    `resumed` tokens were recovered without re-decoding; `done` requests
    were already complete per the journal and need no engine time at
    all.  `baseline_replay` is what a replay-from-scratch recovery would
    have re-decoded (every journaled token of every unfinished request)
    — the strict upper bound the acceptance test gates `replayed`
    against."""

    rid_map: Dict[int, int] = field(default_factory=dict)   # erid -> ext
    resume_prefix: Dict[int, List[int]] = field(default_factory=dict)
    replayed: Dict[int, int] = field(default_factory=dict)  # ext -> count
    resumed: Dict[int, int] = field(default_factory=dict)   # ext -> count
    done: Dict[int, List[int]] = field(default_factory=dict)
    baseline_replay: int = 0
    from_snapshot: bool = False
    n_skipped: int = 0

    @property
    def total_replayed(self) -> int:
        return sum(self.replayed.values())

    @property
    def total_resumed(self) -> int:
        return sum(self.resumed.values())


def _enqueue_raw(engine, prompt, max_new: int) -> int:
    """Queue a recovered request BYPASSING admission shedding: work that
    was already admitted before the crash must not be shed by its own
    recovery."""
    kind = _engine_kind(engine)
    rid = engine._next_id
    engine._next_id += 1
    engine._queue.append(_req_from_dict(
        {"rid": rid, "prompt": [int(x) for x in prompt],
         "max_new": int(max_new), "tokens": []}, kind))
    return rid


def recover_engine(engine, snapshot_path: Optional[str],
                   journal_path: Optional[str]) -> RecoveryInfo:
    """Restore a freshly built engine from the last snapshot (if any) and
    roll the journal forward (see module docstring).  The engine is left
    ready to step(); attach a fresh journal with `rewrite_journal` before
    doing so.  Journal-prefix resume teacher-forces via prompt concat,
    which requires greedy decoding — raises for sampled engines when the
    journal holds non-snapshot sequences."""
    info = RecoveryInfo()
    if snapshot_path and os.path.exists(snapshot_path):
        extra = restore_into(engine, load_snapshot(snapshot_path))
        info.from_snapshot = True
        info.rid_map = {int(k): int(v)
                        for k, v in (extra.get("rid_map") or {}).items()}
        info.resume_prefix = {
            int(k): [int(t) for t in v]
            for k, v in (extra.get("resume_prefix") or {}).items()}
    view = journal_view(journal_path)
    info.n_skipped = view.n_skipped

    def _journal_finished(rid, jt):
        """The full journaled stream iff the journal proves `rid` done
        (explicit done record, or complete by eos/budget against the
        ORIGINAL submit's budget)."""
        if rid in view.done:
            return jt
        sub = view.submits.get(rid)
        if sub is not None and jt:
            return trim_complete(jt, int(sub["max_new"]), engine.eos_id)
        return None

    owned = set()
    # Slot residents: a journal-complete request takes its journaled
    # stream and retires on the first step (no decode — _retire_finished
    # runs before any launch and frees the slot's pages); the rest
    # re-decode only the journal lag past the snapshot.
    for req in [r for r in engine.slots if r is not None]:
        owned.add(req.rid)
        ext = info.rid_map.get(req.rid, req.rid)
        pre = info.resume_prefix.get(req.rid, [])
        jt = list(view.tokens.get(req.rid, []))
        fin = _journal_finished(req.rid, jt)
        if fin is not None:
            req.tokens = [int(t) for t in fin[len(pre):]]
            info.replayed[ext] = 0
            info.resumed[ext] = len(fin)
            if fin:
                M_RECOVERED_RESUMED.inc(len(fin))
            continue
        have = len(pre) + len(req.tokens)
        lag = max(0, len(jt) - have)
        info.replayed[ext] = lag
        info.resumed[ext] = have
        if lag:
            M_RECOVERED_REPLAYED.inc(lag)
        if have:
            M_RECOVERED_RESUMED.inc(have)
    # Queued residents: journal-complete ones must LEAVE the queue
    # (admission would prefill and append one token past the finished
    # stream) — they surface straight through info.done.
    for req in list(engine._queue):
        owned.add(req.rid)
        ext = info.rid_map.get(req.rid, req.rid)
        pre = info.resume_prefix.get(req.rid, [])
        jt = list(view.tokens.get(req.rid, []))
        fin = _journal_finished(req.rid, jt)
        if fin is not None:
            engine._queue.remove(req)
            info.done[ext] = [int(t) for t in fin]
            info.replayed[ext] = 0
            info.resumed[ext] = len(fin)
            if fin:
                M_RECOVERED_RESUMED.inc(len(fin))
            continue
        have = len(pre) + len(req.tokens)
        lag = max(0, len(jt) - have)
        info.replayed[ext] = lag
        info.resumed[ext] = have
        if lag:
            M_RECOVERED_REPLAYED.inc(lag)
        if have:
            M_RECOVERED_RESUMED.inc(have)

    for rid, sub in sorted(view.submits.items()):
        if rid in owned:
            continue
        ext = int(sub["ext"])
        toks = list(view.tokens.get(rid, []))
        if rid in view.done:
            info.done[ext] = toks
            continue
        complete = trim_complete(toks, int(sub["max_new"]), engine.eos_id)
        if complete is not None:
            # journaled past the finish line but never marked done (the
            # kill landed between the append and the done record)
            info.done[ext] = complete
            info.resumed[ext] = len(complete)
            M_RECOVERED_RESUMED.inc(len(complete))
            continue
        if toks and engine.temperature != 0.0:
            raise ValueError(
                "journal-prefix resume requires greedy decoding "
                "(temperature 0); snapshot-only recovery supports "
                "sampled engines")
        new_rid = _enqueue_raw(engine, list(sub["prompt"]) + toks,
                               int(sub["max_new"]) - len(toks))
        info.rid_map[new_rid] = ext
        if toks:
            info.resume_prefix[new_rid] = toks
            M_RECOVERED_RESUMED.inc(len(toks))
        info.resumed[ext] = len(toks)
        info.replayed[ext] = 0

    info.baseline_replay = sum(
        len(view.tokens.get(rid, []))
        for rid in view.submits if rid not in view.done)
    return info


def rewrite_journal(engine, path: str, rid_map: Dict[int, int],
                    resume_prefix: Dict[int, List[int]]) -> TokenJournal:
    """Start a FRESH journal consistent with a just-recovered engine:
    one submit + one tokens record per in-flight/queued request, so a
    second failure recovers from this worker's journal alone (no
    duplicate records from the previous life)."""
    journal = TokenJournal(path, truncate=True)
    reqs = [r for r in engine.slots if r is not None] + list(engine._queue)
    for req in sorted(reqs, key=lambda r: r.rid):
        pre = resume_prefix.get(req.rid, [])
        # the engine-side prompt of a resumed request is orig_prompt +
        # prefix; the journal records the ORIGINAL request shape
        prompt = [int(x) for x in req.prompt]
        if pre:
            prompt = prompt[:len(prompt) - len(pre)]
        journal.submit(req.rid, rid_map.get(req.rid, req.rid), prompt,
                       req.max_new_tokens + len(pre))
        journal.tokens(req.rid, list(pre) + [int(t) for t in req.tokens])
    journal.sync()
    return journal


def run_recovered(engine, info: RecoveryInfo,
                  max_steps: int = 100_000) -> Dict[int, List[int]]:
    """Drive a recovered engine to completion and return the EXTERNAL
    view: ext rid -> full token stream (journal-resumed prefixes
    prepended, journal-complete requests included without engine time).
    This is the single-process recovery harness the checkpoint fuzz and
    the handoff fault tests assert token-exactness on."""
    out = dict(info.done)
    for erid, toks in engine.run(max_steps).items():
        ext = info.rid_map.get(erid, erid)
        out[ext] = info.resume_prefix.get(erid, []) + [int(t) for t in toks]
    return out
